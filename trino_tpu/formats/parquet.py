"""From-scratch Parquet reader producing columnar Batches.

Reference parity: lib/trino-parquet (8.9k loc — ParquetReader,
MetadataReader, the typed column readers under reader/; the writer
lives in trino-hive at the reference snapshot, so this is reader-only
like the reference library). Nothing is delegated to pyarrow — the
thrift-compact footer parser, RLE/bit-packed hybrid decoder, PLAIN /
dictionary decoders, and a pure-python Snappy decompressor live here,
with numpy doing the wide decodes (the TPU-first angle: every column
lands as a dense lane ready for device upload).

Supported surface (flat schemas):
- physical types BOOLEAN, INT32, INT64, FLOAT, DOUBLE, BYTE_ARRAY
- logical/converted types UTF8 -> VARCHAR, DATE -> DATE,
  TIMESTAMP_MILLIS/MICROS -> TIMESTAMP(3)
- encodings PLAIN, RLE (levels), PLAIN_DICTIONARY / RLE_DICTIONARY
- codecs UNCOMPRESSED, SNAPPY, GZIP, ZSTD (via stdlib/zlib; snappy is
  implemented below)
- optional columns via definition levels; no repeated (nested) groups
"""

from __future__ import annotations

import io
import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..columnar import Batch, Column, StringDictionary, pad_batch
from ..config import capacity_for
from ..types import (BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, REAL,
                     TimestampType, Type, VarcharType)

MAGIC = b"PAR1"


# --------------------------------------------------------------------------
# thrift compact protocol (the footer/page-header wire format)
# --------------------------------------------------------------------------

class _TReader:
    """Minimal thrift compact-protocol struct reader: structs become
    {field_id: value} dicts; only what parquet.thrift needs."""

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def _varint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self._byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def _zigzag(self) -> int:
        v = self._varint()
        return (v >> 1) ^ -(v & 1)

    def _bytes(self) -> bytes:
        n = self._varint()
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def _value(self, ttype: int):
        if ttype == 1:
            return True
        if ttype == 2:
            return False
        if ttype in (3, 4, 5, 6):
            return self._zigzag()
        if ttype == 7:
            v = struct.unpack("<d", self.buf[self.pos:self.pos + 8])[0]
            self.pos += 8
            return v
        if ttype == 8:
            return self._bytes()
        if ttype in (9, 10):
            return self._list()
        if ttype == 12:
            return self.struct()
        raise ValueError(f"thrift type {ttype} unsupported")

    def _list(self):
        head = self._byte()
        size = head >> 4
        etype = head & 0x0F
        if size == 15:
            size = self._varint()
        if etype == 1:          # bool list elements carry their value
            return [self._byte() == 1 for _ in range(size)]
        return [self._value(etype) for _ in range(size)]

    def struct(self) -> Dict[int, object]:
        out: Dict[int, object] = {}
        fid = 0
        while True:
            head = self._byte()
            if head == 0:
                return out
            delta = head >> 4
            ttype = head & 0x0F
            if delta == 0:
                fid = self._zigzag()
            else:
                fid += delta
            if ttype in (1, 2):
                out[fid] = ttype == 1
            else:
                out[fid] = self._value(ttype)


# --------------------------------------------------------------------------
# snappy (pure python; raw block format)
# --------------------------------------------------------------------------

def snappy_decompress(data: bytes) -> bytes:
    """Raw Snappy block decode: preamble varint = uncompressed length,
    then literal / copy tags."""
    pos = 0
    # uncompressed length varint
    n = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    while pos < len(data):
        tag = data[pos]
        pos += 1
        kind = tag & 0x03
        if kind == 0:                       # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                length = int.from_bytes(data[pos:pos + extra],
                                        "little") + 1
                pos += extra
            out += data[pos:pos + length]
            pos += length
            continue
        if kind == 1:                       # copy, 1-byte offset
            length = ((tag >> 2) & 0x07) + 4
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:                     # copy, 2-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:                               # copy, 4-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if offset == 0:
            raise ValueError("snappy: zero copy offset")
        start = len(out) - offset
        for i in range(length):             # may self-overlap
            out.append(out[start + i])
    if len(out) != n:
        raise ValueError("snappy: length mismatch")
    return bytes(out)


def _decompress(data: bytes, codec: int, uncompressed: int) -> bytes:
    if codec == 0:
        return data
    if codec == 1:
        return snappy_decompress(data)
    if codec == 2:
        return zlib.decompress(data, 31)    # gzip wrapper
    if codec == 6:
        try:
            import zstandard                 # pragma: no cover
            return zstandard.ZstdDecompressor().decompress(data)
        except ImportError:
            raise ValueError("zstd codec requires the zstandard module")
    raise ValueError(f"compression codec {codec} unsupported")


# --------------------------------------------------------------------------
# RLE / bit-packed hybrid
# --------------------------------------------------------------------------

def _read_rle_bitpacked(buf: bytes, bit_width: int,
                        count: int) -> np.ndarray:
    """The RLE/bit-packing hybrid used for levels and dictionary ids."""
    out = np.empty(count, dtype=np.int64)
    got = 0
    pos = 0
    byte_width = (bit_width + 7) // 8
    while got < count and pos < len(buf):
        # varint header
        header = 0
        shift = 0
        while True:
            b = buf[pos]
            pos += 1
            header |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        if header & 1:                       # bit-packed run
            groups = header >> 1
            nvals = groups * 8
            nbytes = groups * bit_width
            chunk = np.frombuffer(buf[pos:pos + nbytes], dtype=np.uint8)
            pos += nbytes
            bits = np.unpackbits(chunk, bitorder="little")
            vals = bits.reshape(-1, bit_width)
            weights = (1 << np.arange(bit_width)).astype(np.int64)
            decoded = vals @ weights
            take = min(nvals, count - got)
            out[got:got + take] = decoded[:take]
            got += take
        else:                                # rle run
            run = header >> 1
            raw = buf[pos:pos + byte_width]
            pos += byte_width
            v = int.from_bytes(raw, "little") if byte_width else 0
            take = min(run, count - got)
            out[got:got + take] = v
            got += take
    return out


# --------------------------------------------------------------------------
# metadata model
# --------------------------------------------------------------------------

@dataclass
class _ColumnInfo:
    name: str
    physical: int                # parquet Type enum
    converted: Optional[int]
    optional: bool
    logical: Optional[dict] = None


@dataclass
class _ChunkInfo:
    column: _ColumnInfo
    codec: int
    num_values: int
    data_offset: int
    dict_offset: Optional[int]


@dataclass
class ParquetMetadata:
    num_rows: int
    columns: List[_ColumnInfo]
    row_groups: List[List[_ChunkInfo]]   # per group, per column


_PHYS_BOOLEAN, _PHYS_INT32, _PHYS_INT64, _PHYS_INT96 = 0, 1, 2, 3
_PHYS_FLOAT, _PHYS_DOUBLE, _PHYS_BYTE_ARRAY, _PHYS_FIXED = 4, 5, 6, 7


def _sql_type(c: _ColumnInfo) -> Type:
    if c.physical == _PHYS_BOOLEAN:
        return BOOLEAN
    if c.physical == _PHYS_INT32:
        if c.converted == 6:                 # DATE
            return DATE
        return INTEGER
    if c.physical == _PHYS_INT64:
        if c.converted in (9, 10):           # TIMESTAMP_MILLIS/MICROS
            return TimestampType(3)
        if c.logical is not None and 8 in c.logical:
            return TimestampType(3)          # logicalType TIMESTAMP
        return BIGINT
    if c.physical == _PHYS_FLOAT:
        return REAL
    if c.physical == _PHYS_DOUBLE:
        return DOUBLE
    if c.physical == _PHYS_BYTE_ARRAY:
        return VarcharType(None)
    raise ValueError(f"parquet physical type {c.physical} unsupported "
                     f"for column {c.name}")


def read_metadata(path: str) -> ParquetMetadata:
    """Footer parse (reference: trino-parquet MetadataReader.java)."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != MAGIC or data[-4:] != MAGIC:
        raise ValueError(f"{path}: not a parquet file")
    flen = int.from_bytes(data[-8:-4], "little")
    footer = _TReader(data[len(data) - 8 - flen:len(data) - 8]).struct()
    schema = footer[2]
    cols: List[_ColumnInfo] = []
    # schema[0] is the root group; flat children follow
    for el in schema[1:]:
        if el.get(5):                        # num_children -> nested
            raise ValueError("nested parquet schemas are not supported")
        cols.append(_ColumnInfo(
            name=el[4].decode(),
            physical=el.get(1, 0),
            converted=el.get(6),
            optional=el.get(3, 0) == 1,
            logical=el.get(10)))
    groups: List[List[_ChunkInfo]] = []
    for rg in footer[4]:
        chunks: List[_ChunkInfo] = []
        for i, cc in enumerate(rg[1]):
            md = cc[3]
            chunks.append(_ChunkInfo(
                column=cols[i],
                codec=md.get(4, 0),
                num_values=md[5],
                data_offset=md[9],
                dict_offset=md.get(11)))
        groups.append(chunks)
    return ParquetMetadata(footer[3], cols, groups)


# --------------------------------------------------------------------------
# column chunk reader
# --------------------------------------------------------------------------

_NP_FOR_PHYS = {
    _PHYS_INT32: np.dtype("<i4"), _PHYS_INT64: np.dtype("<i8"),
    _PHYS_FLOAT: np.dtype("<f4"), _PHYS_DOUBLE: np.dtype("<f8"),
}


def _plain_decode(phys: int, raw: bytes, n: int):
    """PLAIN-encoded values -> (np array | list of bytes)."""
    if phys in _NP_FOR_PHYS:
        dt = _NP_FOR_PHYS[phys]
        return np.frombuffer(raw[:n * dt.itemsize], dtype=dt).copy()
    if phys == _PHYS_BOOLEAN:
        bits = np.unpackbits(np.frombuffer(raw, dtype=np.uint8),
                             bitorder="little")
        return bits[:n].astype(bool)
    if phys == _PHYS_BYTE_ARRAY:
        out = []
        pos = 0
        for _ in range(n):
            ln = int.from_bytes(raw[pos:pos + 4], "little")
            pos += 4
            out.append(raw[pos:pos + ln])
            pos += ln
        return out
    raise ValueError(f"PLAIN decode for physical {phys} unsupported")


def _read_chunk(data: bytes, chunk: _ChunkInfo) -> Tuple[list, list]:
    """Read every page of one column chunk; returns (values, valid)
    with values positionally dense (nulls hold placeholder)."""
    col = chunk.column
    dictionary = None
    pos = chunk.dict_offset if chunk.dict_offset is not None \
        else chunk.data_offset
    values: List = []
    valid: List[bool] = []
    remaining = chunk.num_values
    while remaining > 0:
        rd = _TReader(data, pos)
        header = rd.struct()
        page_type = header[1]
        comp_size = header[3]
        uncomp_size = header[2]
        body = data[rd.pos:rd.pos + comp_size]
        pos = rd.pos + comp_size
        if page_type == 2:                   # dictionary page
            raw = _decompress(body, chunk.codec, uncomp_size)
            dph = header[7]
            dictionary = _plain_decode(col.physical, raw, dph[1])
            continue
        if page_type == 0:                   # data page v1
            dp = header[5]
            nvals = dp[1]
            encoding = dp[2]
            raw = _decompress(body, chunk.codec, uncomp_size)
            off = 0
            if col.optional:
                dl_len = int.from_bytes(raw[off:off + 4], "little")
                off += 4
                levels = _read_rle_bitpacked(raw[off:off + dl_len], 1,
                                             nvals)
                off += dl_len
                present = levels == 1
            else:
                present = np.ones(nvals, bool)
        elif page_type == 3:                 # data page v2
            dp = header[8]
            nvals = dp[1]
            encoding = dp[4]
            dl_len = dp.get(5, 0)
            rl_len = dp.get(6, 0)
            lev = body[:rl_len + dl_len]
            payload = body[rl_len + dl_len:]
            if dp.get(7, True):
                payload = _decompress(
                    payload, chunk.codec,
                    uncomp_size - rl_len - dl_len)
            raw = payload
            off = 0
            if col.optional and dl_len:
                levels = _read_rle_bitpacked(
                    lev[rl_len:rl_len + dl_len], 1, nvals)
                present = levels == 1
            else:
                present = np.ones(nvals, bool)
        else:
            raise ValueError(f"page type {page_type} unsupported")
        ndef = int(present.sum())
        if encoding == 0:                    # PLAIN
            vals = _plain_decode(col.physical, raw[off:], ndef)
        elif encoding in (2, 8):             # PLAIN_/RLE_DICTIONARY
            bw = raw[off]
            ids = _read_rle_bitpacked(raw[off + 1:], bw, ndef)
            if dictionary is None:
                raise ValueError("dictionary page missing")
            if isinstance(dictionary, list):
                vals = [dictionary[i] for i in ids]
            else:
                vals = dictionary[ids]
        else:
            raise ValueError(f"encoding {encoding} unsupported")
        # scatter into row positions
        it = iter(vals) if isinstance(vals, list) else None
        vi = 0
        for p in present:
            if p:
                values.append(next(it) if it is not None
                              else vals[vi])
                vi += 1
            else:
                values.append(None)
            valid.append(bool(p))
        remaining -= nvals
    return values, valid


def read_parquet(path: str,
                 columns: Optional[Sequence[str]] = None,
                 row_group: Optional[int] = None) -> Batch:
    """Read a parquet file (or one row group) into a Batch."""
    meta = read_metadata(path)
    with open(path, "rb") as f:
        data = f.read()
    want = list(columns) if columns is not None \
        else [c.name for c in meta.columns]
    groups = meta.row_groups if row_group is None \
        else [meta.row_groups[row_group]]
    per_col: Dict[str, Tuple[list, list]] = \
        {name: ([], []) for name in want}
    for chunks in groups:
        for chunk in chunks:
            nm = chunk.column.name
            if nm not in per_col:
                continue
            vals, valid = _read_chunk(data, chunk)
            per_col[nm][0].extend(vals)
            per_col[nm][1].extend(valid)
    cols: Dict[str, Column] = {}
    n = 0
    for info in meta.columns:
        if info.name not in per_col:
            continue
        vals, valid = per_col[info.name]
        n = len(vals)
        t = _sql_type(info)
        cols[info.name] = _to_column(info, t, vals, valid)
    out = Batch(cols, n)
    return pad_batch(out, capacity_for(max(n, 1), minimum=8))


def _to_column(info: _ColumnInfo, t: Type, vals: list,
               valid: list) -> Column:
    va = np.asarray(valid, bool)
    if isinstance(t, VarcharType):
        strings = [v.decode("utf-8", "replace")
                   if isinstance(v, (bytes, bytearray)) else v
                   for v in vals]
        d, codes = StringDictionary.from_strings(strings)
        return Column(t, codes, None if va.all() else va, d)
    dt = t.np_dtype
    data = np.zeros(len(vals), dtype=dt)
    for i, v in enumerate(vals):
        if v is not None:
            data[i] = v
    if t.name == "timestamp(3)" and info.converted == 10:
        data //= 1000                        # micros -> millis
    return Column(t, data, None if va.all() else va)


def schema_of(path: str) -> Dict[str, Type]:
    meta = read_metadata(path)
    return {c.name: _sql_type(c) for c in meta.columns}


def num_row_groups(path: str) -> int:
    return len(read_metadata(path).row_groups)
