"""File-format libraries (reference: lib/trino-parquet, trino-orc,
trino-rcfile). Readers produce columnar Batches directly."""
