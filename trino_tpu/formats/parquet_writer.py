"""From-scratch Parquet writer (PLAIN encoding, uncompressed).

Reference parity: lib/trino-parquet's ParquetWriter +
plugin/trino-hive write support — the L12 "file-format libraries"
writer half (round-4 verdict: readers only). One row group, data page
v1, RLE/bit-packed definition levels for nullable columns; metadata in
thrift compact protocol (the mirror of parquet.py's _TReader).

Supported lanes: BIGINT/INTEGER (INT64/INT32), DOUBLE, BOOLEAN,
VARCHAR (BYTE_ARRAY/UTF8), DATE (INT32/DATE). Round-trips through both
this package's reader and pyarrow (tests/test_parquet_writer.py).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..columnar import Batch
from ..types import (BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, Type,
                     VarcharType, is_string)

_MAGIC = b"PAR1"

# parquet physical types
_T_BOOLEAN, _T_INT32, _T_INT64, _T_DOUBLE, _T_BYTE_ARRAY = 0, 1, 2, 5, 6
# converted types
_C_UTF8, _C_DATE = 0, 6


class _TWriter:
    """Thrift compact-protocol struct writer (the _TReader mirror)."""

    def __init__(self):
        self.out = bytearray()

    def _varint(self, v: int):
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.append(b | 0x80)
            else:
                self.out.append(b)
                return

    def _zigzag(self, v: int):
        self._varint((v << 1) ^ (v >> 63) if v < 0 else v << 1)

    def field(self, last_id: int, fid: int, ttype: int) -> int:
        delta = fid - last_id
        if 0 < delta <= 15:
            self.out.append((delta << 4) | ttype)
        else:
            self.out.append(ttype)
            self._zigzag(fid)
        return fid

    def i_field(self, last_id: int, fid: int, v: int,
                ttype: int = 6) -> int:
        last_id = self.field(last_id, fid, ttype)
        self._zigzag(v)
        return last_id

    def bytes_field(self, last_id: int, fid: int, v: bytes) -> int:
        last_id = self.field(last_id, fid, 8)
        self._varint(len(v))
        self.out += v
        return last_id

    def list_header(self, size: int, etype: int):
        if size < 15:
            self.out.append((size << 4) | etype)
        else:
            self.out.append(0xF0 | etype)
            self._varint(size)

    def stop(self):
        self.out.append(0)


def _phys_type(t: Type) -> Tuple[int, Optional[int]]:
    if t is BIGINT or t.name in ("bigint",):
        return _T_INT64, None
    if t is INTEGER or t.name in ("integer", "smallint", "tinyint"):
        return _T_INT32, None
    if t is DOUBLE or t.name in ("double", "real"):
        return _T_DOUBLE, None
    if t is BOOLEAN or t.name == "boolean":
        return _T_BOOLEAN, None
    if t is DATE or t.name == "date":
        return _T_INT32, _C_DATE
    if is_string(t):
        return _T_BYTE_ARRAY, _C_UTF8
    raise ValueError(f"parquet writer: unsupported type {t}")


def _plain_encode(phys: int, values: list) -> bytes:
    if phys == _T_INT64:
        return np.asarray(values, dtype="<i8").tobytes()
    if phys == _T_INT32:
        return np.asarray(values, dtype="<i4").tobytes()
    if phys == _T_DOUBLE:
        return np.asarray(values, dtype="<f8").tobytes()
    if phys == _T_BOOLEAN:
        return np.packbits(np.asarray(values, dtype=bool),
                           bitorder="little").tobytes()
    if phys == _T_BYTE_ARRAY:
        out = bytearray()
        for v in values:
            b = str(v).encode()
            out += struct.pack("<I", len(b))
            out += b
        return bytes(out)
    raise AssertionError(phys)


def _uleb(out: bytearray, v: int) -> None:
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _def_levels(valid: np.ndarray) -> bytes:
    """RLE/bit-packed hybrid encoding of 1-bit definition levels,
    4-byte length prefixed (DataPageHeader definition_level_encoding
    RLE)."""
    n = len(valid)
    body = bytearray()
    if valid.all():
        _uleb(body, n << 1)        # one RLE run of value 1
        body.append(1)
        return struct.pack("<I", len(body)) + bytes(body)
    groups = (n + 7) // 8          # bit-packed groups of 8 values
    _uleb(body, (groups << 1) | 1)
    bits = np.zeros(groups * 8, dtype=bool)
    bits[:n] = valid
    body += np.packbits(bits, bitorder="little").tobytes()
    return struct.pack("<I", len(body)) + bytes(body)


def _page_header(num_values: int, uncompressed: int) -> bytes:
    """PageHeader{type=DATA_PAGE, sizes, DataPageHeader{num_values,
    encoding=PLAIN(0), def/rep level encoding=RLE(3)}}."""
    w = _TWriter()
    last = w.i_field(0, 1, 0, 5)                 # type = DATA_PAGE
    last = w.i_field(last, 2, uncompressed, 5)   # uncompressed_size
    last = w.i_field(last, 3, uncompressed, 5)   # compressed_size
    last = w.field(last, 5, 12)                  # data_page_header
    l2 = w.i_field(0, 1, num_values, 5)
    l2 = w.i_field(l2, 2, 0, 5)                  # encoding PLAIN
    l2 = w.i_field(l2, 3, 3, 5)                  # def levels RLE
    l2 = w.i_field(l2, 4, 3, 5)                  # rep levels RLE
    w.stop()                                     # end DataPageHeader
    w.stop()                                     # end PageHeader
    return bytes(w.out)


def write_parquet(path: str, batch: Batch,
                  columns: Optional[List[str]] = None) -> None:
    """Write a Batch's live rows as a one-row-group parquet file."""
    names = columns or list(batch.columns)
    n = batch.num_rows_host()
    chunks = []          # (name, phys, conv, nullable, page_bytes)
    for name in names:
        col = batch.column(name)
        phys, conv = _phys_type(col.type)
        data = np.asarray(col.data)[:n]
        valid = (np.ones(n, dtype=bool) if col.valid is None
                 else np.asarray(col.valid)[:n].astype(bool))
        if is_string(col.type):
            if col.dictionary is not None:
                vals = col.dictionary.values
                dec = vals[np.clip(data.astype(np.int64), 0,
                                   len(vals) - 1)]
            else:
                dec = data
            present = [dec[i] for i in range(n) if valid[i]]
        else:
            present = data[valid].tolist()
        # schema declares every column OPTIONAL, so definition levels
        # are always present (an all-ones RLE run when nothing is null)
        body = _def_levels(valid)
        body += _plain_encode(phys, present)
        page = _page_header(n, len(body)) + body
        chunks.append((name, phys, conv, True, page, len(body)))

    out = bytearray(_MAGIC)
    offsets = []
    for name, phys, conv, _, page, _sz in chunks:
        offsets.append(len(out))
        out += page

    # ---- FileMetaData ------------------------------------------------
    w = _TWriter()
    last = w.i_field(0, 1, 1, 5)                 # version
    last = w.field(last, 2, 9)                   # schema list
    w.list_header(1 + len(chunks), 12)
    # root element
    se = _TWriter()
    l2 = se.bytes_field(0, 4, b"schema")
    l2 = se.i_field(l2, 5, len(chunks), 5)       # num_children
    se.stop()
    w.out += se.out
    for name, phys, conv, _, _, _sz in chunks:
        se = _TWriter()
        l2 = se.i_field(0, 1, phys, 5)           # physical type
        l2 = se.i_field(l2, 3, 1, 5)             # repetition OPTIONAL
        l2 = se.bytes_field(l2, 4, name.encode())
        if conv is not None:
            l2 = se.i_field(l2, 6, conv, 5)      # converted_type
        se.stop()
        w.out += se.out
    last = w.i_field(last, 3, n, 6)              # num_rows
    last = w.field(last, 4, 9)                   # row_groups list
    w.list_header(1, 12)
    rg = _TWriter()
    l2 = rg.field(0, 1, 9)                       # columns list
    rg.list_header(len(chunks), 12)
    total = 0
    for (name, phys, conv, _, page, body_sz), off in zip(chunks,
                                                         offsets):
        cc = _TWriter()
        l3 = cc.i_field(0, 2, off, 6)            # file_offset
        l3 = cc.field(l3, 3, 12)                 # meta_data
        md = _TWriter()
        l4 = md.i_field(0, 1, phys, 5)           # type
        l4 = md.field(l4, 2, 9)                  # encodings
        md.list_header(2, 5)
        md._zigzag(0)                            # PLAIN
        md._zigzag(3)                            # RLE
        l4 = md.field(l4, 3, 9)                  # path_in_schema
        md.list_header(1, 8)
        md._varint(len(name.encode()))
        md.out += name.encode()
        l4 = md.i_field(l4, 4, 0, 5)             # codec UNCOMPRESSED
        l4 = md.i_field(l4, 5, n, 6)             # num_values
        l4 = md.i_field(l4, 6, len(page), 6)     # total_uncompressed
        l4 = md.i_field(l4, 7, len(page), 6)     # total_compressed
        l4 = md.i_field(l4, 9, off, 6)           # data_page_offset
        md.stop()
        cc.out += md.out
        cc.stop()
        rg.out += cc.out
        total += len(page)
    l2 = rg.i_field(1, 2, total, 6)              # total_byte_size
    l2 = rg.i_field(l2, 3, n, 6)                 # num_rows
    rg.stop()
    w.out += rg.out
    w.stop()
    meta = bytes(w.out)
    out += meta
    out += struct.pack("<I", len(meta))
    out += _MAGIC
    with open(path, "wb") as f:
        f.write(out)
