"""Row decoders for raw byte messages — lib/trino-record-decoder.

Reference parity: the decoder SPI the kafka/redis-class connectors
feed (RowDecoder.decodeRow; json/csv/raw field decoders with per-field
mappings). Column-at-a-time here: each field extracts across ALL
messages into a lane, then the batch assembles once — the vectorized
inversion of the reference's per-row DecoderColumnHandle loop.

Decoders: ``json`` (mapping = dot path into the document), ``csv``
(mapping = field index), ``raw`` (whole message as varchar).
"""

from __future__ import annotations

import csv as _csv
import io
import json as _json
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ..columnar import Batch, batch_from_pylist
from ..types import Type, is_string


@dataclass(frozen=True)
class DecoderField:
    """One decoded column (DecoderColumnHandle): output name, SQL type,
    and the decoder-specific mapping (json path / csv index)."""
    name: str
    type: Type
    mapping: Optional[str] = None


def _coerce(v, t: Type):
    if v is None:
        return None
    try:
        if t.name in ("bigint", "integer", "smallint", "tinyint"):
            return int(v)
        if t.name in ("double", "real"):
            return float(v)
        if t.name == "boolean":
            if isinstance(v, str):
                return v.strip().lower() in ("true", "t", "1")
            return bool(v)
        if is_string(t):
            return v if isinstance(v, str) else _json.dumps(v)
    except (TypeError, ValueError):
        return None
    return v


def _json_path(doc, path: str):
    cur = doc
    for part in path.split("/" if "/" in path else "."):
        if isinstance(cur, dict):
            cur = cur.get(part)
        elif isinstance(cur, list):
            try:
                cur = cur[int(part)]
            except (ValueError, IndexError):
                return None
        else:
            return None
    return cur


class RowDecoder:
    """decode(messages) -> Batch (RowDecoder.decodeRow, batched)."""

    def __init__(self, fields: Sequence[DecoderField]):
        self.fields = list(fields)

    def decode(self, messages: Sequence[bytes]) -> Batch:
        raise NotImplementedError


class JsonRowDecoder(RowDecoder):
    """decoder/json/JsonRowDecoder.java: one JSON document per
    message; mappings are dot/slash paths. Undecodable messages decode
    to all-NULL rows (the reference's lenient mode)."""

    def decode(self, messages: Sequence[bytes]) -> Batch:
        docs = []
        for m in messages:
            try:
                docs.append(_json.loads(m))
            except (ValueError, UnicodeDecodeError):
                docs.append(None)
        data: Dict[str, list] = {}
        for f in self.fields:
            path = f.mapping or f.name
            data[f.name] = [
                None if d is None else _coerce(_json_path(d, path),
                                               f.type)
                for d in docs]
        return batch_from_pylist(data,
                                 {f.name: f.type for f in self.fields})


class CsvRowDecoder(RowDecoder):
    """decoder/csv/CsvRowDecoder.java: one CSV record per message;
    mapping is the zero-based field index (required — a silent
    default would decode column 0 into a misconfigured field)."""

    def __init__(self, fields):
        super().__init__(fields)
        for f in self.fields:
            if f.mapping is None or not str(f.mapping).isdigit():
                raise ValueError(
                    f"csv decoder field '{f.name}' needs a numeric "
                    f"mapping (got {f.mapping!r})")

    def decode(self, messages: Sequence[bytes]) -> Batch:
        rows = []
        for m in messages:
            try:
                parsed = next(_csv.reader(
                    io.StringIO(m.decode("utf-8", "replace"))), [])
            except Exception:       # noqa: BLE001
                parsed = []
            rows.append(parsed)
        data: Dict[str, list] = {}
        for f in self.fields:
            idx = int(f.mapping) if f.mapping is not None else 0
            data[f.name] = [
                _coerce(r[idx], f.type) if idx < len(r) else None
                for r in rows]
        return batch_from_pylist(data,
                                 {f.name: f.type for f in self.fields})


class RawRowDecoder(RowDecoder):
    """decoder/raw/RawRowDecoder.java collapsed to the varchar case:
    the whole message is the single field's value."""

    def decode(self, messages: Sequence[bytes]) -> Batch:
        f = self.fields[0]
        data = {f.name: [m.decode("utf-8", "replace")
                         for m in messages]}
        return batch_from_pylist(data, {f.name: f.type})


_DECODERS = {"json": JsonRowDecoder, "csv": CsvRowDecoder,
             "raw": RawRowDecoder}


def create_decoder(kind: str,
                   fields: Sequence[DecoderField]) -> RowDecoder:
    """DispatchingRowDecoderFactory.create analog."""
    cls = _DECODERS.get(kind)
    if cls is None:
        raise ValueError(f"unknown decoder '{kind}' "
                         f"(have: {sorted(_DECODERS)})")
    return cls(fields)
