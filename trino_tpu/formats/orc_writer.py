"""From-scratch ORC writer (RLEv1 DIRECT, uncompressed, one stripe).

Reference parity: lib/trino-orc's OrcWriter — the writer half of the
L12 file-format libraries (round-4 verdict: readers only). Streams:
PRESENT (bit MSB-first under byte RLE, only when nulls exist), DATA,
LENGTH; protobuf footers mirror this package's reader (orc.py) and
round-trip through pyarrow.orc (tests/test_orc_writer.py).

Supported lanes: BIGINT/INTEGER (LONG/INT), DOUBLE, BOOLEAN, VARCHAR
(STRING direct), DATE.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..columnar import Batch
from ..types import Type, is_string

from .orc import (K_BOOLEAN, K_DATE, K_DOUBLE, K_INT, K_LONG, K_STRING,
                  K_STRUCT, MAGIC, S_DATA, S_LENGTH, S_PRESENT)

_NONE_COMPRESSION = 0


# --------------------------------------------------------------------------
# protobuf writing (the pb_decode mirror)
# --------------------------------------------------------------------------

def _varint(out: bytearray, v: int):
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _uint(out: bytearray, tag: int, v: int):
    _varint(out, (tag << 3) | 0)
    _varint(out, v)


def _blob(out: bytearray, tag: int, b: bytes):
    _varint(out, (tag << 3) | 2)
    _varint(out, len(b))
    out += b


# --------------------------------------------------------------------------
# stream encoders (mirrors of orc.py's decoders)
# --------------------------------------------------------------------------

def _sleb(out: bytearray, v: int):
    _varint(out, (v << 1) ^ (v >> 63) if v < 0 else v << 1)


def rle_v1_encode(vals, signed: bool) -> bytes:
    """Integer RLEv1 as literal groups of <=128 (always decodable;
    run detection is an optimization the reader doesn't require)."""
    out = bytearray()
    vals = [int(v) for v in vals]
    for lo in range(0, len(vals), 128):
        group = vals[lo:lo + 128]
        out.append(256 - len(group))
        for v in group:
            if signed:
                _sleb(out, v)
            else:
                _varint(out, v)
    return bytes(out)


def byte_rle_encode(raw: bytes) -> bytes:
    """Byte-level RLE as literal groups of <=128."""
    out = bytearray()
    for lo in range(0, len(raw), 128):
        group = raw[lo:lo + 128]
        out.append(256 - len(group))
        out += group
    return bytes(out)


def _bool_stream(bits: np.ndarray) -> bytes:
    return byte_rle_encode(np.packbits(bits.astype(bool)).tobytes())


# --------------------------------------------------------------------------
# writer
# --------------------------------------------------------------------------

def _orc_kind(t: Type) -> int:
    name = t.name
    if name in ("bigint", "integer", "smallint", "tinyint"):
        return K_LONG if name == "bigint" else K_INT
    if name in ("double", "real"):
        return K_DOUBLE
    if name == "boolean":
        return K_BOOLEAN
    if name == "date":
        return K_DATE
    if is_string(t):
        return K_STRING
    raise ValueError(f"orc writer: unsupported type {t}")


def write_orc(path: str, batch: Batch,
              columns: Optional[List[str]] = None) -> None:
    """Write a Batch's live rows as a one-stripe ORC file."""
    names = columns or list(batch.columns)
    n = batch.num_rows_host()

    # column id 0 is the root struct; children are 1..len(names)
    col_streams: List[Tuple[int, int, bytes]] = []  # (kind, col, data)
    kinds: List[int] = []
    for ci, name in enumerate(names, start=1):
        col = batch.column(name)
        kind = _orc_kind(col.type)
        kinds.append(kind)
        data = np.asarray(col.data)[:n]
        valid = (np.ones(n, dtype=bool) if col.valid is None
                 else np.asarray(col.valid)[:n].astype(bool))
        has_nulls = not valid.all()
        if has_nulls:
            col_streams.append((S_PRESENT, ci, _bool_stream(valid)))
        if kind == K_BOOLEAN:
            body = _bool_stream(data[valid].astype(bool))
            col_streams.append((S_DATA, ci, body))
        elif kind in (K_LONG, K_INT, K_DATE):
            col_streams.append(
                (S_DATA, ci,
                 rle_v1_encode(data[valid].tolist(), signed=True)))
        elif kind == K_DOUBLE:
            col_streams.append(
                (S_DATA, ci,
                 np.ascontiguousarray(data[valid],
                                      dtype="<f8").tobytes()))
        else:   # K_STRING, direct encoding
            if col.dictionary is not None:
                vals = col.dictionary.values
                dec = vals[np.clip(data.astype(np.int64), 0,
                                   len(vals) - 1)]
            else:
                dec = data
            blobs = [str(dec[i]).encode() for i in range(n)
                     if valid[i]]
            col_streams.append((S_DATA, ci, b"".join(blobs)))
            col_streams.append(
                (S_LENGTH, ci,
                 rle_v1_encode([len(b) for b in blobs],
                               signed=False)))

    # ---- stripe ------------------------------------------------------
    stripe_offset = len(MAGIC)
    data_blob = bytearray()
    sfoot = bytearray()
    for kind, ci, body in col_streams:
        data_blob += body
        s = bytearray()
        _uint(s, 1, kind)
        _uint(s, 2, ci)
        _uint(s, 3, len(body))
        _blob(sfoot, 1, bytes(s))
    for _ in range(len(names) + 1):      # root + children: DIRECT
        e = bytearray()
        _uint(e, 1, 0)
        _blob(sfoot, 2, bytes(e))
    sfoot_b = bytes(sfoot)

    # ---- file footer -------------------------------------------------
    footer = bytearray()
    _uint(footer, 1, len(MAGIC))                      # headerLength
    _uint(footer, 2,
          len(MAGIC) + len(data_blob) + len(sfoot_b))  # contentLength
    si = bytearray()
    _uint(si, 1, stripe_offset)
    _uint(si, 2, 0)                                   # indexLength
    _uint(si, 3, len(data_blob))
    _uint(si, 4, len(sfoot_b))
    _uint(si, 5, n)
    _blob(footer, 3, bytes(si))
    root = bytearray()
    _uint(root, 1, K_STRUCT)
    for ci in range(1, len(names) + 1):
        _uint(root, 2, ci)
    for name in names:
        _blob(root, 3, name.encode())
    _blob(footer, 4, bytes(root))
    for kind in kinds:
        t = bytearray()
        _uint(t, 1, kind)
        _blob(footer, 4, bytes(t))
    _uint(footer, 6, n)                               # numberOfRows
    _uint(footer, 8, 0)                               # rowIndexStride
    footer_b = bytes(footer)

    ps = bytearray()
    _uint(ps, 1, len(footer_b))                       # footerLength
    _uint(ps, 2, _NONE_COMPRESSION)
    _uint(ps, 3, 0)                                   # block size
    _uint(ps, 4, 0)                                   # version 0.12
    _uint(ps, 4, 12)
    _uint(ps, 5, 0)                                   # metadataLength
    _blob(ps, 8000, b"ORC")                           # PostScript.magic
    ps_b = bytes(ps)
    assert len(ps_b) < 256

    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(bytes(data_blob))
        f.write(sfoot_b)
        f.write(footer_b)
        f.write(ps_b)
        f.write(bytes([len(ps_b)]))
