"""From-scratch ORC reader producing columnar Batches.

Reference parity: lib/trino-orc (29.3k loc — the largest lib module:
OrcReader.java:66,251, the typed stream readers under reader/, the
RLEv1/v2 + boolean decoders under stream/). Nothing delegates to
pyarrow — the protobuf tail parser, compression-chunk framing, byte/
boolean RLE, integer RLEv1 + all four RLEv2 sub-encodings, and the
typed column readers live here; numpy does the wide decodes so every
column lands as a dense lane ready for device upload (same TPU-first
angle as formats/parquet.py).

Supported surface (flat schemas — a root STRUCT of primitive fields):
- types BOOLEAN, BYTE, SHORT, INT, LONG, FLOAT, DOUBLE, STRING,
  VARCHAR, CHAR, DATE, TIMESTAMP, DECIMAL(p<=18), BINARY (as varchar)
- encodings DIRECT, DIRECT_V2, DICTIONARY_V2 (+ byte/boolean RLE)
- codecs NONE, ZLIB (raw deflate), SNAPPY, ZSTD, LZ4 (error)
- nulls via PRESENT bit streams; multiple stripes concatenated

The protobuf tail is decoded with a minimal wire-format reader (the
schema constants below mirror orc_proto.proto field numbers).
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..columnar import Batch, Column, StringDictionary, pad_batch
from ..config import capacity_for
from ..types import (BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, REAL,
                     SMALLINT, TINYINT, DecimalType, TimestampType, Type,
                     VarcharType, CharType, VARCHAR, is_string)

MAGIC = b"ORC"

# orc_proto.proto CompressionKind
_NONE, _ZLIB, _SNAPPY, _LZO, _LZ4, _ZSTD = range(6)

# orc_proto.proto Type.Kind
(K_BOOLEAN, K_BYTE, K_SHORT, K_INT, K_LONG, K_FLOAT, K_DOUBLE, K_STRING,
 K_BINARY, K_TIMESTAMP, K_LIST, K_MAP, K_STRUCT, K_UNION, K_DECIMAL,
 K_DATE, K_VARCHAR, K_CHAR) = range(18)

# Stream.Kind
(S_PRESENT, S_DATA, S_LENGTH, S_DICTIONARY_DATA, S_DICTIONARY_COUNT,
 S_SECONDARY, S_ROW_INDEX, S_BLOOM_FILTER) = range(8)

# ColumnEncoding.Kind
E_DIRECT, E_DICTIONARY, E_DIRECT_V2, E_DICTIONARY_V2 = range(4)

# ORC timestamp epoch: 2015-01-01 00:00:00 UTC, seconds
_TS_EPOCH = 1420070400


# --------------------------------------------------------------------------
# minimal protobuf wire-format reader
# --------------------------------------------------------------------------

def _varint(buf: bytes, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def pb_decode(buf: bytes) -> Dict[int, list]:
    """Wire-level decode: {field_number: [raw values]} — varints stay
    ints, length-delimited stay bytes (decoded further by the caller)."""
    out: Dict[int, list] = {}
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _varint(buf, pos)
        fno, wt = tag >> 3, tag & 7
        if wt == 0:
            v, pos = _varint(buf, pos)
        elif wt == 1:
            v = struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        elif wt == 2:
            ln, pos = _varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            v = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        else:
            raise ValueError(f"orc: unsupported protobuf wire type {wt}")
        out.setdefault(fno, []).append(v)
    return out


def _packed_uints(vals: list) -> List[int]:
    """A repeated uint field arrives either as N varints or as packed
    length-delimited bytes."""
    out: List[int] = []
    for v in vals:
        if isinstance(v, int):
            out.append(v)
        else:
            pos = 0
            while pos < len(v):
                u, pos = _varint(v, pos)
                out.append(u)
    return out


# --------------------------------------------------------------------------
# compression framing
# --------------------------------------------------------------------------

def _decompress_block(kind: int, data: bytes) -> bytes:
    if kind == _ZLIB:
        return zlib.decompress(data, -15)
    if kind == _SNAPPY:
        from .parquet import snappy_decompress
        return snappy_decompress(data)
    if kind == _ZSTD:
        try:
            from compression import zstd  # py3.14 stdlib
            return zstd.decompress(data)
        except ImportError:
            try:
                import zstandard
                return zstandard.ZstdDecompressor().decompress(data)
            except ImportError:
                raise ValueError(
                    "orc: zstd codec requires the zstandard module")
    raise ValueError(f"orc: unsupported compression kind {kind}")


def _read_stream(raw: bytes, kind: int) -> bytes:
    """Un-frame an ORC compressed stream: 3-byte chunk headers of
    (length << 1 | isOriginal), little-endian."""
    if kind == _NONE:
        return raw
    out = bytearray()
    pos = 0
    n = len(raw)
    while pos + 3 <= n:
        h = raw[pos] | (raw[pos + 1] << 8) | (raw[pos + 2] << 16)
        pos += 3
        ln = h >> 1
        chunk = raw[pos:pos + ln]
        pos += ln
        out += chunk if h & 1 else _decompress_block(kind, chunk)
    return bytes(out)


# --------------------------------------------------------------------------
# RLE decoders
# --------------------------------------------------------------------------

def _zigzag(u: np.ndarray) -> np.ndarray:
    u = u.astype(np.uint64)
    return ((u >> np.uint64(1)) ^ (-(u & np.uint64(1))).astype(
        np.uint64)).astype(np.int64)


def _byte_rle(buf: bytes, count: int) -> np.ndarray:
    """Byte-level RLE (used for PRESENT/boolean bit streams and BYTE)."""
    out = np.empty(count, np.uint8)
    got = 0
    pos = 0
    while got < count and pos < len(buf):
        ctrl = buf[pos]
        pos += 1
        if ctrl < 128:                  # run
            run = ctrl + 3
            out[got:got + run] = buf[pos]
            pos += 1
            got += run
        else:                           # literals
            lit = 256 - ctrl
            out[got:got + lit] = np.frombuffer(
                buf, np.uint8, lit, pos)
            pos += lit
            got += lit
    return out[:count]


def _bool_bits(buf: bytes, count: int) -> np.ndarray:
    by = _byte_rle(buf, (count + 7) // 8)
    bits = np.unpackbits(by)  # MSB first
    return bits[:count].astype(bool)


def _sleb128(buf: bytes, pos: int) -> Tuple[int, int]:
    """Signed varint (used by RLEv1 base and DECIMAL mantissas):
    unbounded zigzag."""
    u, pos = _varint(buf, pos)
    return (u >> 1) ^ -(u & 1), pos


_WIDTH_TABLE = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16,
                17, 18, 19, 20, 21, 22, 23, 24, 26, 28, 30, 32, 40, 48,
                56, 64]


def _unpack_bits(buf: bytes, pos: int, count: int, width: int
                 ) -> Tuple[np.ndarray, int]:
    """MSB-first bit unpacking of `count` values of `width` bits."""
    nbytes = (count * width + 7) // 8
    bits = np.unpackbits(np.frombuffer(buf, np.uint8, nbytes, pos))
    bits = bits[:count * width].reshape(count, width).astype(np.uint64)
    weights = (np.uint64(1) << np.arange(width - 1, -1, -1,
                                         dtype=np.uint64))
    return bits @ weights, pos + nbytes


def _rle_v2(buf: bytes, count: int, signed: bool) -> np.ndarray:
    """Integer RLEv2: SHORT_REPEAT / DIRECT / PATCHED_BASE / DELTA."""
    chunks: List[np.ndarray] = []
    got = 0
    pos = 0
    while got < count and pos < len(buf):
        first = buf[pos]
        enc = first >> 6
        if enc == 0:                    # SHORT_REPEAT
            nbytes = ((first >> 3) & 0x7) + 1
            run = (first & 0x7) + 3
            val = int.from_bytes(buf[pos + 1:pos + 1 + nbytes], "big")
            pos += 1 + nbytes
            if signed:
                val = (val >> 1) ^ -(val & 1)
            chunks.append(np.full(run, val, np.int64))
            got += run
        elif enc == 1:                  # DIRECT
            width = _WIDTH_TABLE[(first >> 1) & 0x1F]
            ln = ((first & 1) << 8 | buf[pos + 1]) + 1
            pos += 2
            vals, pos = _unpack_bits(buf, pos, ln, width)
            v = _zigzag(vals) if signed else vals.astype(np.int64)
            chunks.append(v)
            got += ln
        elif enc == 2:                  # PATCHED_BASE
            width = _WIDTH_TABLE[(first >> 1) & 0x1F]
            ln = ((first & 1) << 8 | buf[pos + 1]) + 1
            third, fourth = buf[pos + 2], buf[pos + 3]
            bw = (third >> 5) + 1       # base width, bytes
            pw = _WIDTH_TABLE[third & 0x1F]
            pgw = (fourth >> 5) + 1     # patch gap width, bits
            pll = fourth & 0x1F         # patch list length
            pos += 4
            base = int.from_bytes(buf[pos:pos + bw], "big")
            sign_mask = 1 << (bw * 8 - 1)
            if base & sign_mask:        # sign-magnitude
                base = -(base & (sign_mask - 1))
            pos += bw
            vals, pos = _unpack_bits(buf, pos, ln, width)
            if pll:
                patch, pos = _unpack_bits(buf, pos, pll, pgw + pw)
                idx = 0
                for p in patch:
                    gap = int(p) >> pw
                    pv = int(p) & ((1 << pw) - 1)
                    idx += gap
                    vals[idx] = vals[idx] | (np.uint64(pv) << np.uint64(
                        width))
            chunks.append(vals.astype(np.int64) + base)
            got += ln
        else:                           # DELTA
            wcode = (first >> 1) & 0x1F
            ln = ((first & 1) << 8 | buf[pos + 1]) + 1
            pos += 2
            if signed:
                base, pos = _sleb128(buf, pos)
            else:
                base, pos = _varint(buf, pos)
            delta0, pos = _sleb128(buf, pos)
            out = np.empty(ln, np.int64)
            out[0] = base
            if ln > 1:
                out[1] = base + delta0
            if ln > 2:
                if wcode == 0:          # fixed delta
                    deltas = np.full(ln - 2, delta0, np.int64)
                else:
                    width = _WIDTH_TABLE[wcode]
                    dv, pos = _unpack_bits(buf, pos, ln - 2, width)
                    deltas = dv.astype(np.int64)
                    if delta0 < 0:
                        deltas = -deltas
                out[2:] = out[1] + np.cumsum(deltas)
            chunks.append(out)
            got += ln
    vals = (np.concatenate(chunks) if chunks
            else np.zeros(0, np.int64))
    return vals[:count]


def _rle_v1(buf: bytes, count: int, signed: bool) -> np.ndarray:
    chunks: List[np.ndarray] = []
    got = 0
    pos = 0
    while got < count and pos < len(buf):
        ctrl = buf[pos]
        pos += 1
        if ctrl < 128:                  # run
            run = ctrl + 3
            delta = struct.unpack_from("b", buf, pos)[0]
            pos += 1
            if signed:
                base, pos = _sleb128(buf, pos)
            else:
                base, pos = _varint(buf, pos)
            chunks.append(base + delta * np.arange(run, dtype=np.int64))
            got += run
        else:
            lit = 256 - ctrl
            vals = np.empty(lit, np.int64)
            for i in range(lit):
                if signed:
                    vals[i], pos = _sleb128(buf, pos)
                else:
                    v, pos = _varint(buf, pos)
                    vals[i] = v
            chunks.append(vals)
            got += lit
    vals = (np.concatenate(chunks) if chunks
            else np.zeros(0, np.int64))
    return vals[:count]


def _read_ints(buf: bytes, count: int, signed: bool,
               encoding: int) -> np.ndarray:
    if encoding in (E_DIRECT_V2, E_DICTIONARY_V2):
        return _rle_v2(buf, count, signed)
    return _rle_v1(buf, count, signed)


# --------------------------------------------------------------------------
# file metadata
# --------------------------------------------------------------------------

@dataclass
class OrcType:
    kind: int
    subtypes: List[int] = field(default_factory=list)
    field_names: List[str] = field(default_factory=list)
    max_length: int = 0
    precision: int = 0
    scale: int = 0


@dataclass
class StripeInfo:
    offset: int
    index_length: int
    data_length: int
    footer_length: int
    num_rows: int


@dataclass
class OrcMeta:
    compression: int
    types: List[OrcType]
    stripes: List[StripeInfo]
    num_rows: int


def read_meta(path: str) -> OrcMeta:
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < 4 or not data.startswith(MAGIC):
        raise ValueError(f"{path}: not an ORC file")
    ps_len = data[-1]
    ps = pb_decode(data[-1 - ps_len:-1])
    footer_len = ps[1][0]
    compression = ps.get(2, [_NONE])[0]
    footer_raw = data[-1 - ps_len - footer_len:-1 - ps_len]
    footer = pb_decode(_read_stream(footer_raw, compression))
    types: List[OrcType] = []
    for raw in footer.get(4, []):
        t = pb_decode(raw)
        types.append(OrcType(
            kind=t.get(1, [K_STRUCT])[0],
            subtypes=_packed_uints(t.get(2, [])),
            field_names=[b.decode() for b in t.get(3, [])],
            max_length=t.get(4, [0])[0],
            precision=t.get(5, [0])[0],
            scale=t.get(6, [0])[0]))
    stripes = []
    for raw in footer.get(3, []):
        s = pb_decode(raw)
        stripes.append(StripeInfo(
            s.get(1, [0])[0], s.get(2, [0])[0], s.get(3, [0])[0],
            s.get(4, [0])[0], s.get(5, [0])[0]))
    return OrcMeta(compression, types, stripes,
                   footer.get(6, [0])[0])


def _sql_type(t: OrcType) -> Type:
    if t.kind == K_BOOLEAN:
        return BOOLEAN
    if t.kind == K_BYTE:
        return TINYINT
    if t.kind == K_SHORT:
        return SMALLINT
    if t.kind == K_INT:
        return INTEGER
    if t.kind == K_LONG:
        return BIGINT
    if t.kind == K_FLOAT:
        return REAL
    if t.kind == K_DOUBLE:
        return DOUBLE
    if t.kind in (K_STRING, K_BINARY):
        return VARCHAR
    if t.kind == K_VARCHAR:
        return VarcharType(t.max_length or None)
    if t.kind == K_CHAR:
        return CharType(t.max_length or 1)
    if t.kind == K_DATE:
        return DATE
    if t.kind == K_TIMESTAMP:
        return TimestampType(3)
    if t.kind == K_DECIMAL:
        p = t.precision or 38
        if p > 18:
            raise ValueError("orc: DECIMAL precision > 18 unsupported")
        return DecimalType(p, t.scale)
    raise ValueError(f"orc: unsupported type kind {t.kind}")


def schema_of(path: str) -> Dict[str, Type]:
    meta = read_meta(path)
    root = meta.types[0]
    if root.kind != K_STRUCT:
        raise ValueError("orc: root type must be a struct")
    return {name: _sql_type(meta.types[sub])
            for name, sub in zip(root.field_names, root.subtypes)}


# --------------------------------------------------------------------------
# stripe reading
# --------------------------------------------------------------------------

def _column_from_streams(t: OrcType, sql: Type, n: int, enc: int,
                         dict_size: int, streams: Dict[int, bytes]
                         ) -> Tuple[np.ndarray, Optional[np.ndarray],
                                    Optional[list], Optional[np.ndarray]]:
    """Returns (values, valid, dict_strings, data2) with `values` dense
    over n rows (nulls zero-filled)."""
    present = streams.get(S_PRESENT)
    valid = _bool_bits(present, n) if present is not None else None
    nnz = int(valid.sum()) if valid is not None else n

    def scatter(vals: np.ndarray, fill=0) -> np.ndarray:
        if valid is None:
            return vals
        out = np.full(n, fill, vals.dtype)
        out[valid] = vals[:nnz]
        return out

    data = streams.get(S_DATA, b"")
    if t.kind == K_BOOLEAN:
        return scatter(_bool_bits(data, nnz)), valid, None, None
    if t.kind == K_BYTE:
        return (scatter(_byte_rle(data, nnz).astype(np.int8)
                        .astype(np.int64)), valid, None, None)
    if t.kind in (K_SHORT, K_INT, K_LONG, K_DATE):
        return (scatter(_read_ints(data, nnz, True, enc)), valid,
                None, None)
    if t.kind == K_FLOAT:
        vals = np.frombuffer(data, "<f4", nnz).astype(np.float32)
        return scatter(vals), valid, None, None
    if t.kind == K_DOUBLE:
        vals = np.frombuffer(data, "<f8", nnz)
        return scatter(vals), valid, None, None
    if t.kind == K_TIMESTAMP:
        secs = _read_ints(data, nnz, True, enc) + _TS_EPOCH
        nraw = _read_ints(streams.get(S_SECONDARY, b""), nnz, False, enc)
        z = nraw & 7
        nanos = np.where(z == 0, nraw >> 3,
                         (nraw >> 3) * 10 ** (z + 1).astype(np.int64))
        # negative seconds with nonzero nanos count backwards
        secs = np.where((secs < 0) & (nanos != 0), secs - 1, secs)
        ms = secs * 1000 + nanos // 1_000_000
        return scatter(ms), valid, None, None
    if t.kind == K_DECIMAL:
        mant = np.empty(nnz, np.int64)
        pos = 0
        for i in range(nnz):
            mant[i], pos = _sleb128(data, pos)
        scales = _read_ints(streams.get(S_SECONDARY, b""), nnz, True,
                            enc)
        target = t.scale
        adj = target - scales
        mant = (mant * np.power(10, np.clip(adj, 0, None))
                // np.power(10, np.clip(-adj, 0, None)))
        return scatter(mant), valid, None, None
    if t.kind in (K_STRING, K_VARCHAR, K_CHAR, K_BINARY):
        if enc in (E_DICTIONARY, E_DICTIONARY_V2):
            codes = _read_ints(data, nnz, False, enc)
            lens = _read_ints(streams.get(S_LENGTH, b""), dict_size,
                              False, enc)
            blob = streams.get(S_DICTIONARY_DATA, b"")
            offs = np.concatenate([[0], np.cumsum(lens)])
            words = [blob[offs[i]:offs[i + 1]].decode("utf-8", "replace")
                     for i in range(dict_size)]
            strs = [words[int(c)] if dict_size else "" for c in codes]
        else:
            lens = _read_ints(streams.get(S_LENGTH, b""), nnz, False,
                              enc)
            offs = np.concatenate([[0], np.cumsum(lens)])
            strs = [data[offs[i]:offs[i + 1]].decode("utf-8", "replace")
                    for i in range(nnz)]
        full: List[Optional[str]]
        if valid is None:
            full = strs
        else:
            full = [None] * n
            j = 0
            for i in range(n):
                if valid[i]:
                    full[i] = strs[j]
                    j += 1
        return np.zeros(n, np.int32), valid, full, None
    raise ValueError(f"orc: unsupported column kind {t.kind}")


def read_orc(path: str, columns: Optional[Sequence[str]] = None,
             stripe_index: Optional[int] = None) -> Batch:
    """Read an ORC file (or one stripe of it) into a host Batch."""
    meta = read_meta(path)
    root = meta.types[0]
    names = root.field_names
    want = set(columns) if columns is not None else set(names)
    with open(path, "rb") as f:
        data = f.read()

    stripes = (meta.stripes if stripe_index is None
               else [meta.stripes[stripe_index]])
    per_col_vals: Dict[str, list] = {c: [] for c in names if c in want}
    per_col_valid: Dict[str, list] = {c: [] for c in names if c in want}
    per_col_strs: Dict[str, list] = {c: [] for c in names if c in want}
    any_null: Dict[str, bool] = {c: False for c in names if c in want}

    for st in stripes:
        sf_off = st.offset + st.index_length + st.data_length
        sfoot = pb_decode(_read_stream(
            data[sf_off:sf_off + st.footer_length], meta.compression))
        streams = []
        for raw in sfoot.get(1, []):
            s = pb_decode(raw)
            streams.append((s.get(1, [0])[0], s.get(2, [0])[0],
                            s.get(3, [0])[0]))
        encodings = []
        for raw in sfoot.get(2, []):
            e = pb_decode(raw)
            encodings.append((e.get(1, [0])[0], e.get(2, [0])[0]))
        # stream byte ranges: cumulative from stripe start, index
        # streams included
        pos = st.offset
        col_streams: Dict[int, Dict[int, bytes]] = {}
        for kind, col, length in streams:
            if kind not in (S_ROW_INDEX, S_BLOOM_FILTER):
                col_streams.setdefault(col, {})[kind] = _read_stream(
                    data[pos:pos + length], meta.compression)
            pos += length
        for fi, (name, ci) in enumerate(zip(names, root.subtypes)):
            if name not in want:
                continue
            t = meta.types[ci]
            sql = _sql_type(t)
            enc, dsz = (encodings[ci] if ci < len(encodings)
                        else (E_DIRECT_V2, 0))
            vals, valid, strs, d2 = _column_from_streams(
                t, sql, st.num_rows, enc, dsz,
                col_streams.get(ci, {}))
            per_col_vals[name].append(vals)
            per_col_valid[name].append(
                valid if valid is not None
                else np.ones(st.num_rows, bool))
            if valid is not None:
                any_null[name] = True
            if strs is not None:
                per_col_strs[name].extend(strs)

    total = sum(st.num_rows for st in stripes)
    cols: Dict[str, Column] = {}
    by_name = dict(zip(names, root.subtypes))
    ordered = (list(columns) if columns is not None else names)
    for name in ordered:
        ci = by_name[name]
        if name not in want:
            continue
        sql = _sql_type(meta.types[ci])
        if per_col_strs[name] or is_string(sql):
            # string columns need a dictionary even with zero rows
            # (Column.__post_init__ enforces it)
            dct, codes = StringDictionary.from_strings(
                per_col_strs[name])
            valid = (np.asarray([s is not None
                                 for s in per_col_strs[name]])
                     if any_null[name] else None)
            cols[name] = Column(sql, codes, valid, dct)
        else:
            vals = (np.concatenate(per_col_vals[name])
                    if per_col_vals[name] else np.zeros(0, np.int64))
            valid = (np.concatenate(per_col_valid[name])
                     if any_null[name] else None)
            cols[name] = Column(sql, vals, valid)
    b = Batch(cols, total)
    return pad_batch(b, capacity_for(max(total, 1)))


def num_stripes(path: str) -> int:
    return len(read_meta(path).stripes)
