"""Python client for the coordinator's REST protocol.

Reference parity: client/trino-client StatementClientV1.java:108,324-336
— POST /v1/statement, then advance() through nextUri until the payload
carries no nextUri; data rows accumulate across pages. stdlib-only
(urllib), synchronous.

nextUri polls retry transient transport failures (connection refused /
reset, HTTP 503) with bounded exponential backoff, like the reference
client's advance() loop: a coordinator failover — the old process dead,
its replacement binding the same port and resuming the query from the
spooled execution manifest — looks to the client like a brief outage in
the middle of an otherwise ordinary poll chain. The initial POST is NOT
retried: submission is not idempotent.
"""

from __future__ import annotations

import http.client
import json
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class ClientError(Exception):
    pass


@dataclass
class ClientResult:
    columns: List[dict] = field(default_factory=list)
    rows: List[list] = field(default_factory=list)
    query_id: str = ""
    state: str = ""
    update_type: Optional[str] = None
    update_count: Optional[int] = None

    @property
    def column_names(self) -> List[str]:
        return [c["name"] for c in self.columns]


class StatementClient:
    def __init__(self, base_uri: str, user: str = "user",
                 catalog: str = "tpch", schema: str = "tiny",
                 session_properties: Optional[Dict[str, str]] = None,
                 timeout: float = 600.0, poll_retries: int = 8,
                 poll_retry_delay: float = 0.05):
        self.base_uri = base_uri.rstrip("/")
        self.user = user
        self.catalog = catalog
        self.schema = schema
        self.session_properties = dict(session_properties or {})
        self.timeout = timeout
        # transient-failure budget for one nextUri poll: attempts and
        # the initial backoff (doubled per retry, capped at 1s). ~2.5s
        # of cumulative patience at the defaults — enough to ride out
        # a coordinator restart, short enough that a dead cluster
        # still fails fast
        self.poll_retries = max(0, int(poll_retries))
        self.poll_retry_delay = float(poll_retry_delay)
        # client-held prepared statements, replayed on every request
        # via X-Trino-Prepared-Statement (ProtocolHeaders.java — the
        # coordinator's sessions are per-request, so prepared state
        # lives client-side exactly like the reference protocol)
        self.prepared: Dict[str, str] = {}

    def _request(self, method: str, uri: str, body: Optional[bytes]
                 = None) -> dict:
        from urllib.parse import quote
        req = urllib.request.Request(uri, data=body, method=method)
        req.add_header("X-Trino-User", self.user)
        req.add_header("X-Trino-Catalog", self.catalog)
        req.add_header("X-Trino-Schema", self.schema)
        if self.session_properties:
            req.add_header("X-Trino-Session", ",".join(
                f"{k}={v}" for k, v in self.session_properties.items()))
        if self.prepared:
            req.add_header("X-Trino-Prepared-Statement", ",".join(
                f"{name}={quote(sql)}"
                for name, sql in self.prepared.items()))
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            payload = resp.read()
        return json.loads(payload) if payload else {}

    def execute(self, sql: str) -> ClientResult:
        out = ClientResult()
        payload = self._request("POST", f"{self.base_uri}/v1/statement",
                                sql.encode())
        while True:
            out.query_id = payload.get("id", out.query_id)
            out.state = payload.get("stats", {}).get("state", out.state)
            if "error" in payload:
                err = payload["error"]
                raise ClientError(
                    f"{err.get('errorName')}: {err.get('message')}")
            if "columns" in payload and not out.columns:
                out.columns = payload["columns"]
            out.rows.extend(payload.get("data", []))
            out.update_type = payload.get("updateType", out.update_type)
            out.update_count = payload.get("updateCount",
                                           out.update_count)
            nxt = payload.get("nextUri")
            if not nxt:
                self._track_prepared(sql, out)
                return out
            payload = self._poll(nxt)

    def _poll(self, uri: str) -> dict:
        """One nextUri advance with bounded retry. GET on an executing
        URI is idempotent (the token addresses the page), so retrying
        it can duplicate no rows — unlike the initial POST."""
        delay = self.poll_retry_delay
        for attempt in range(self.poll_retries + 1):
            try:
                return self._request("GET", uri)
            except urllib.error.HTTPError as e:
                # 503 = overloaded / restarting, worth the wait; any
                # other status is an answer, not an outage
                if e.code != 503 or attempt >= self.poll_retries:
                    raise
            except (urllib.error.URLError, ConnectionError,
                    http.client.HTTPException) as e:
                if attempt >= self.poll_retries:
                    raise ClientError(
                        f"giving up on {uri} after "
                        f"{self.poll_retries + 1} attempts: {e}") from e
            time.sleep(delay)
            delay = min(delay * 2, 1.0)
        raise ClientError(f"unreachable poll state for {uri}")

    def _track_prepared(self, sql: str, out: ClientResult) -> None:
        """Keep the client-side prepared-statement registry in sync
        with successful PREPARE/DEALLOCATE statements."""
        import re
        if out.update_type == "PREPARE":
            m = re.match(r"\s*prepare\s+(\w+)\s+from\s+(.*)\Z", sql,
                         re.IGNORECASE | re.DOTALL)
            if m:
                self.prepared[m.group(1)] = m.group(2).strip()
        elif out.update_type == "DEALLOCATE":
            m = re.match(r"\s*deallocate\s+(?:prepare\s+)?(\w+)", sql,
                         re.IGNORECASE)
            if m:
                self.prepared.pop(m.group(1), None)
