"""Function registry: name + argument types -> result type.

Reference parity: core/trino-main/.../metadata/FunctionRegistry.java:368
(~267 builtins) + SignatureBinder overload resolution, collapsed to a
type-directed table because the TPU engine dispatches execution on
(name, physical lane dtype) in the evaluator rather than on MethodHandles.
Implementations live in exec/scalars.py; this module is pure typing.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from .types import (BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, REAL, UNKNOWN,
                    VARCHAR, DecimalType, TimestampType, Type, VarcharType,
                    common_super_type, is_exact_numeric, is_integral,
                    is_numeric, is_string, GEOMETRY)

# --- aggregates -----------------------------------------------------------

AGGREGATE_NAMES = {
    "sum", "min", "max", "avg", "count", "count_if", "any_value",
    "arbitrary", "bool_and", "bool_or", "every", "stddev", "stddev_samp",
    "stddev_pop", "variance", "var_samp", "var_pop", "geometric_mean",
    "approx_distinct", "min_by", "max_by", "array_agg", "checksum",
    "corr", "covar_samp", "covar_pop", "regr_slope", "regr_intercept",
    "skewness", "kurtosis", "approx_percentile", "map_agg", "histogram",
    "approx_most_frequent", "approx_set", "merge",
    "bitwise_and_agg", "bitwise_or_agg", "map_union", "multimap_agg",
    "numeric_histogram", "tdigest_agg", "qdigest_agg",
}

WINDOW_ONLY_NAMES = {
    "row_number", "rank", "dense_rank", "percent_rank", "cume_dist",
    "ntile", "first_value", "last_value", "nth_value", "lag", "lead",
}


def aggregate_result_type(name: str, arg_types: Sequence[Type]) -> Type:
    """Result type of an aggregate (reference: operator/aggregation/*
    output types, SURVEY.md Appendix A.7)."""
    t = arg_types[0] if arg_types else None
    if name == "count" or name == "count_if" or name == "approx_distinct":
        return BIGINT
    if name == "sum":
        if is_integral(t):
            return BIGINT
        if isinstance(t, DecimalType):
            return DecimalType(38, t.scale)
        return t
    if name in ("min", "max", "any_value", "arbitrary",
                "approx_percentile"):
        return t
    if name in ("min_by", "max_by"):
        return t
    if name == "avg":
        if isinstance(t, DecimalType):
            return t
        if t is REAL:
            return REAL
        return DOUBLE
    if name in ("bool_and", "bool_or", "every"):
        return BOOLEAN
    if name in ("stddev", "stddev_samp", "stddev_pop", "variance",
                "var_samp", "var_pop", "geometric_mean", "corr",
                "covar_samp", "covar_pop", "regr_slope", "regr_intercept",
                "skewness", "kurtosis"):
        return DOUBLE
    if name == "checksum":
        return BIGINT
    if name in ("bitwise_and_agg", "bitwise_or_agg"):
        if not is_integral(t):
            raise FunctionResolutionError(
                f"{name}({t}) not supported: argument must be integral")
        return BIGINT
    if name == "map_union":
        from .types import MapType
        if not isinstance(t, MapType):
            raise FunctionResolutionError(
                f"map_union({t}) not supported: argument must be a map")
        return t
    if name == "multimap_agg":
        from .types import ArrayType, MapType
        return MapType(arg_types[0], ArrayType(arg_types[1]))
    if name == "numeric_histogram":
        from .types import MapType
        return MapType(DOUBLE, DOUBLE)
    if name == "approx_set":
        # declared bits match the runtime sketch (ops/hll.py
        # APPROX_SET_BUCKET_BITS); an explicit max-error argument
        # re-types the aggregate at plan time (planner/logical.py)
        from .types import HyperLogLogType
        from .ops.hll import APPROX_SET_BUCKET_BITS
        return HyperLogLogType(APPROX_SET_BUCKET_BITS)
    if name == "merge":
        # merge() combines sketch values — result type follows the
        # input (HLL, tdigest or qdigest, like the reference)
        from .types import HyperLogLogType, QDigestType, TDigestType
        if not isinstance(t, (HyperLogLogType, TDigestType,
                              QDigestType)):
            raise FunctionResolutionError(
                f"merge({t}) not supported: argument must be a "
                "HyperLogLog / tdigest / qdigest sketch")
        return t
    if name == "tdigest_agg":
        from .types import T_DIGEST
        if not is_numeric(t):
            raise FunctionResolutionError(
                f"tdigest_agg({t}) not supported")
        return T_DIGEST
    if name == "qdigest_agg":
        from .types import QDigestType
        if not is_numeric(t):
            raise FunctionResolutionError(
                f"qdigest_agg({t}) not supported")
        return QDigestType(t)
    if name == "array_agg":
        from .types import ArrayType
        return ArrayType(t)
    if name == "map_agg":
        from .types import MapType
        return MapType(arg_types[0], arg_types[1])
    if name == "histogram":
        from .types import MapType
        return MapType(t, BIGINT)
    if name == "approx_most_frequent":
        from .types import MapType
        return MapType(arg_types[1] if len(arg_types) > 1 else t,
                       BIGINT)
    raise KeyError(f"unknown aggregate: {name}")


# --- scalars --------------------------------------------------------------

class FunctionResolutionError(Exception):
    pass


def _numeric_unary(name, args):
    t = args[0]
    if not is_numeric(t):
        raise FunctionResolutionError(f"{name}({t}) not supported")
    return t


def _double_fn(name, args):
    for t in args:
        if not is_numeric(t):
            raise FunctionResolutionError(f"{name}({t}) not supported")
    return DOUBLE


def _common(name, args):
    out = args[0]
    for t in args[1:]:
        nxt = common_super_type(out, t)
        if nxt is None:
            raise FunctionResolutionError(
                f"{name}: incompatible types {out}, {t}")
        out = nxt
    return out


def _varchar_fn(name, args):
    return VARCHAR


def _bigint_fn(name, args):
    return BIGINT


def _varbinary_fn(name, args):
    from .types import VARBINARY
    return VARBINARY


def _double_fn_maps(name, args):
    from .types import MapType
    for t in args:
        if not isinstance(t, MapType):
            raise FunctionResolutionError(
                f"{name} requires map(varchar, double) arguments")
    return DOUBLE


def _zip_type(name, args):
    from .types import ArrayType, RowType
    for t in args:
        if not isinstance(t, ArrayType):
            raise FunctionResolutionError(f"{name} requires arrays")
    return ArrayType(RowType(
        [(f"field{i}", t.element) for i, t in enumerate(args)]))


def _map_from_entries_type(name, args):
    from .types import ArrayType, MapType, RowType
    if (not args or not isinstance(args[0], ArrayType)
            or not isinstance(args[0].element, RowType)
            or len(args[0].element.fields) != 2):
        raise FunctionResolutionError(
            f"{name} requires array(row(K, V))")
    f = args[0].element.fields
    return MapType(f[0][1], f[1][1])


def _multimap_from_entries_type(name, args):
    from .types import ArrayType, MapType
    m = _map_from_entries_type(name, args)
    return MapType(m.key, ArrayType(m.value))


def _split_to_multimap_type():
    from .types import ArrayType, MapType
    return MapType(VARCHAR, ArrayType(VARCHAR))


def _value_at_quantile_type(name, args):
    from .types import QDigestType, TDigestType
    if not args or not isinstance(args[0], (TDigestType, QDigestType)):
        raise FunctionResolutionError(
            f"{name} requires a tdigest/qdigest argument")
    if isinstance(args[0], QDigestType):
        return args[0].value_type
    return DOUBLE


def _double_fn_sketch(name, args):
    _value_at_quantile_type(name, args)
    return DOUBLE


_SCALARS: Dict[str, Callable[[str, Sequence[Type]], Type]] = {
    # math (operator/scalar/MathFunctions.java)
    "abs": _numeric_unary,
    "negate": _numeric_unary,
    "round": lambda n, a: a[0] if not is_string(a[0]) else _err(n, a),
    "floor": _numeric_unary,
    "ceil": _numeric_unary,
    "ceiling": _numeric_unary,
    "truncate": _numeric_unary,
    "sqrt": _double_fn, "cbrt": _double_fn, "exp": _double_fn,
    "ln": _double_fn, "log2": _double_fn, "log10": _double_fn,
    "power": _double_fn, "pow": _double_fn,
    "sin": _double_fn, "cos": _double_fn, "tan": _double_fn,
    "asin": _double_fn, "acos": _double_fn, "atan": _double_fn,
    "atan2": _double_fn, "sinh": _double_fn, "cosh": _double_fn,
    "tanh": _double_fn, "degrees": _double_fn, "radians": _double_fn,
    "sign": _numeric_unary,
    "mod": _common,
    "pi": lambda n, a: DOUBLE,
    "e": lambda n, a: DOUBLE,
    "random": lambda n, a: DOUBLE,
    "rand": lambda n, a: DOUBLE,
    "nan": lambda n, a: DOUBLE,
    "infinity": lambda n, a: DOUBLE,
    "is_nan": lambda n, a: BOOLEAN,
    "is_finite": lambda n, a: BOOLEAN,
    "is_infinite": lambda n, a: BOOLEAN,
    "greatest": _common, "least": _common,
    "width_bucket": _bigint_fn,
    # geospatial core (plugin/trino-geospatial GeoFunctions; TPU-first
    # point lanes — ops/geo.py)
    "st_point": lambda n, a: GEOMETRY,
    "st_geometryfromtext": lambda n, a: GEOMETRY,
    "st_astext": lambda n, a: VARCHAR,
    "st_x": lambda n, a: DOUBLE, "st_y": lambda n, a: DOUBLE,
    "st_distance": lambda n, a: DOUBLE,
    "st_contains": lambda n, a: BOOLEAN,
    "great_circle_distance": _double_fn,
    # conditional (SpecialForm in the reference)
    "coalesce": _common,
    "nullif": lambda n, a: a[0],
    "if": lambda n, a: _common(n, a[1:]),
    "try": lambda n, a: a[0],
    # strings (operator/scalar/StringFunctions.java)
    "length": _bigint_fn,
    "lower": _varchar_fn, "upper": _varchar_fn,
    "trim": _varchar_fn, "ltrim": _varchar_fn, "rtrim": _varchar_fn,
    "reverse": _varchar_fn,
    "substring": _varchar_fn, "substr": _varchar_fn,
    "replace": _varchar_fn,
    "concat": _varchar_fn,
    "concat_ws": _varchar_fn,
    "strpos": _bigint_fn,
    "position": _bigint_fn,
    "split_part": _varchar_fn,
    "lpad": _varchar_fn, "rpad": _varchar_fn,
    "chr": _varchar_fn,
    "codepoint": _bigint_fn,
    "starts_with": lambda n, a: BOOLEAN,
    "hamming_distance": _bigint_fn,
    "levenshtein_distance": _bigint_fn,
    "regexp_like": lambda n, a: BOOLEAN,
    "regexp_replace": _varchar_fn,
    "regexp_extract": _varchar_fn,
    "regexp_extract_all": lambda n, a: _mk_array(VARCHAR),
    "regexp_split": lambda n, a: _mk_array(VARCHAR),
    "split": lambda n, a: _mk_array(VARCHAR),
    "split_to_map": lambda n, a: _split_to_map_type(),
    "normalize": _varchar_fn,
    "to_base": _varchar_fn,
    "from_base": _bigint_fn,
    "format": _varchar_fn,
    # datetime (operator/scalar/DateTimeFunctions.java)
    "year": _bigint_fn, "quarter": _bigint_fn, "month": _bigint_fn,
    "week": _bigint_fn, "day": _bigint_fn, "day_of_month": _bigint_fn,
    "day_of_week": _bigint_fn, "dow": _bigint_fn,
    "day_of_year": _bigint_fn, "doy": _bigint_fn,
    "year_of_week": _bigint_fn, "yow": _bigint_fn,
    "hour": _bigint_fn, "minute": _bigint_fn, "second": _bigint_fn,
    "millisecond": _bigint_fn,
    "date_trunc": lambda n, a: a[1],
    "date_add": lambda n, a: a[2],
    "date_diff": _bigint_fn,
    "date": lambda n, a: DATE,
    "current_date": lambda n, a: DATE,
    "now": lambda n, a: TimestampType(3),
    "current_timestamp": lambda n, a: TimestampType(3),
    "localtimestamp": lambda n, a: TimestampType(3),
    "current_time": lambda n, a: _time_type(),
    "localtime": lambda n, a: _time_type(),
    "from_unixtime": lambda n, a: TimestampType(3),
    "to_unixtime": lambda n, a: DOUBLE,
    "date_format": _varchar_fn,
    "date_parse": lambda n, a: TimestampType(3),
    "at_timezone": lambda n, a: _tstz(a),
    "with_timezone": lambda n, a: _tstz(a),
    "to_iso8601": _varchar_fn,
    # misc
    "typeof": _varchar_fn,
    "to_hex": _varchar_fn,
    "from_hex": lambda n, a: VARCHAR,
    "xxhash64": _bigint_fn,
    # bitwise (operator/scalar/BitwiseFunctions.java)
    "bitwise_and": _bigint_fn, "bitwise_or": _bigint_fn,
    "bitwise_xor": _bigint_fn, "bitwise_not": _bigint_fn,
    "bitwise_left_shift": _bigint_fn,
    "bitwise_right_shift": _bigint_fn,
    "bit_count": _bigint_fn,
    # digests (VarbinaryFunctions; ours return hex varchar)
    "md5": _varchar_fn, "sha1": _varchar_fn, "sha256": _varchar_fn,
    "sha512": _varchar_fn, "crc32": _bigint_fn,
    # URL (operator/scalar/UrlFunctions.java)
    "url_extract_protocol": _varchar_fn,
    "url_extract_host": _varchar_fn,
    "url_extract_port": _bigint_fn,
    "url_extract_path": _varchar_fn,
    "url_extract_query": _varchar_fn,
    "url_extract_fragment": _varchar_fn,
    "url_extract_parameter": _varchar_fn,
    "url_encode": _varchar_fn, "url_decode": _varchar_fn,
    "translate": _varchar_fn,
    "log": _double_fn,
    # arrays (operator/scalar/ArrayFunctions + ArraySubscript)
    "cardinality": _bigint_fn,
    "element_at": lambda n, a: _array_elem(n, a),
    "contains": lambda n, a: BOOLEAN,
    "array_position": _bigint_fn,
    "array_min": lambda n, a: _array_of(n, a).element,
    "array_max": lambda n, a: _array_of(n, a).element,
    "array_distinct": lambda n, a: _array_of(n, a),
    "array_sort": lambda n, a: _array_of(n, a),
    "array_join": _varchar_fn,
    "slice": lambda n, a: _array_of(n, a),
    "repeat": lambda n, a: _mk_array(a[0]),
    "sequence": lambda n, a: _mk_array(a[0]),
    "flatten": lambda n, a: _array_of(n, a).element,
    "arrays_overlap": lambda n, a: BOOLEAN,
    "array_union": lambda n, a: _common(n, a),
    "array_intersect": lambda n, a: _common(n, a),
    "array_except": lambda n, a: _common(n, a),
    # maps (operator/scalar/MapFunctions.java etc.)
    "map": lambda n, a: _map_ctor(n, a),
    "map_keys": lambda n, a: _mk_array(_map_of(n, a).key),
    "map_values": lambda n, a: _mk_array(_map_of(n, a).value),
    "map_concat": _common,
    "map_entries": lambda n, a: _map_entries(n, a),
    # HyperLogLog (operator/scalar/HyperLogLogFunctions.java)
    "empty_approx_set": lambda n, a: _hll_type(),
    # JSON (operator/scalar/JsonFunctions.java)
    "json_extract_scalar": _varchar_fn,
    "json_extract": _varchar_fn,
    "json_array_length": _bigint_fn,
    "json_size": _bigint_fn,
    "json_format": _varchar_fn,
    "json_parse": _varchar_fn,
    # HMAC + binary (HmacFunctions.java / VarbinaryFunctions.java;
    # varbinary is carried as a dictionary column like varchar)
    "hmac_md5": _varbinary_fn, "hmac_sha1": _varbinary_fn,
    "hmac_sha256": _varbinary_fn, "hmac_sha512": _varbinary_fn,
    "to_utf8": _varbinary_fn,
    "from_utf8": _varchar_fn,
    "to_big_endian_64": _varbinary_fn,
    "from_big_endian_64": _bigint_fn,
    "to_big_endian_32": _varbinary_fn,
    "from_big_endian_32": lambda n, a: INTEGER,
    "to_ieee754_64": _varbinary_fn,
    "from_ieee754_64": lambda n, a: DOUBLE,
    "to_ieee754_32": _varbinary_fn,
    "from_ieee754_32": lambda n, a: REAL,
    # ANSI bar charts (ColorFunctions.java; color type folded to varchar)
    "bar": _varchar_fn,
    "color": _varchar_fn,
    "render": _varchar_fn,
    # datetime extras (DateTimeFunctions.java joda-pattern entry points)
    "parse_datetime": lambda n, a: _tstz([TimestampType(3)]),
    "format_datetime": _varchar_fn,
    "from_iso8601_date": lambda n, a: DATE,
    "from_iso8601_timestamp": lambda n, a: _tstz([TimestampType(3)]),
    "last_day_of_month": lambda n, a: DATE,
    "timezone_hour": _bigint_fn,
    "timezone_minute": _bigint_fn,
    # similarity (ArrayFunctions / MathFunctions)
    "cosine_similarity": _double_fn_maps,
    "word_stem": _varchar_fn,
    # array extras
    "array_remove": lambda n, a: _array_of(n, a),
    "zip": _zip_type,
    "ngrams": lambda n, a: _mk_array(_array_of(n, a)),
    "combinations": lambda n, a: _mk_array(_array_of(n, a)),
    "array_last": lambda n, a: _array_of(n, a).element,
    "array_first": lambda n, a: _array_of(n, a).element,
    "map_from_entries": _map_from_entries_type,
    "multimap_from_entries": _multimap_from_entries_type,
    "split_to_multimap": lambda n, a: _split_to_multimap_type(),
    # quantile sketch accessors (TDigestFunctions/QuantileDigestFunctions)
    "value_at_quantile": _value_at_quantile_type,
    "values_at_quantiles": lambda n, a: _mk_array(
        _value_at_quantile_type(n, a)),
    "quantile_at_value": _double_fn_sketch,
}


def _hll_type():
    # matches approx_set's default bucket count so empty_approx_set()
    # merges with approx_set(x) sketches (APPROX_SET_BUCKET_BITS)
    from .types import HyperLogLogType
    from .ops.hll import APPROX_SET_BUCKET_BITS
    return HyperLogLogType(APPROX_SET_BUCKET_BITS)


def _array_elem(name, args):
    from .types import ArrayType, MapType
    if args and isinstance(args[0], MapType):
        return args[0].value
    if not args or not isinstance(args[0], ArrayType):
        raise FunctionResolutionError(
            f"{name} requires an array argument")
    return args[0].element


def _array_of(name, args):
    from .types import ArrayType
    if not args or not isinstance(args[0], ArrayType):
        raise FunctionResolutionError(f"{name} requires an array")
    return args[0]


def _map_of(name, args):
    from .types import MapType
    if not args or not isinstance(args[0], MapType):
        raise FunctionResolutionError(f"{name} requires a map")
    return args[0]


def _mk_array(t):
    from .types import ArrayType
    return ArrayType(t)


def _time_type():
    from .types import TimeType
    return TimeType(3)


def _tstz(args):
    from .types import TimestampTZType
    p = getattr(args[0], "precision", 3) if args else 3
    return TimestampTZType(p)


def _split_to_map_type():
    from .types import MapType
    return MapType(VARCHAR, VARCHAR)


def _map_ctor(name, args):
    from .types import ArrayType, MapType
    if (len(args) != 2 or not isinstance(args[0], ArrayType)
            or not isinstance(args[1], ArrayType)):
        raise FunctionResolutionError(
            "map() takes two array arguments (keys, values)")
    return MapType(args[0].element, args[1].element)


def _map_entries(name, args):
    from .types import ArrayType, RowType
    m = _map_of(name, args)
    return ArrayType(RowType([("key", m.key), ("value", m.value)]))


def _err(name, args):
    raise FunctionResolutionError(
        f"{name}({', '.join(str(a) for a in args)}) not supported")


def is_aggregate(name: str) -> bool:
    return name in AGGREGATE_NAMES or name == "count"


def is_window(name: str) -> bool:
    return name in WINDOW_ONLY_NAMES


def scalar_result_type(name: str, arg_types: Sequence[Type]) -> Type:
    fn = _SCALARS.get(name)
    if fn is None:
        raise FunctionResolutionError(f"Function '{name}' not registered")
    return fn(name, list(arg_types))


def list_functions() -> List[str]:
    return sorted(set(_SCALARS) | AGGREGATE_NAMES | WINDOW_ONLY_NAMES
                  | {"count"})
