"""Plugin SPI + loader: dynamic connector/function registration.

Reference parity: core/trino-spi/.../Plugin.java:35-90 (a plugin
contributes connector factories, types, functions, access controls,
event listeners) + server/PluginManager.java (discovers plugin dirs and
registers every SPI surface). Python redesign: a plugin is an importable
module exposing ``get_connector_factories()`` (and optionally
``get_functions()`` / ``get_event_listeners()``); isolation comes from
the module system rather than per-plugin classloaders.
"""

from __future__ import annotations

import importlib
from typing import Callable, Dict, List, Optional


class ConnectorFactory:
    """spi/connector/ConnectorFactory: name + create(catalog, props)."""

    def __init__(self, name: str, create: Callable):
        self.name = name
        self._create = create

    def create(self, catalog_name: str, props: Dict[str, str]):
        return self._create(catalog_name, props)


_FACTORIES: Dict[str, ConnectorFactory] = {}


def register_factory(factory: ConnectorFactory) -> None:
    _FACTORIES[factory.name] = factory


def connector_factories() -> List[str]:
    _ensure_builtins()
    return sorted(_FACTORIES)


def load_plugin(module_path: str) -> List[str]:
    """Import a plugin module and register its factories
    (PluginManager.installPlugin). Returns the factory names added."""
    mod = importlib.import_module(module_path)
    added = []
    get = getattr(mod, "get_connector_factories", None)
    if get is None:
        raise ValueError(
            f"plugin module {module_path!r} has no "
            "get_connector_factories()")
    for f in get():
        if not isinstance(f, ConnectorFactory):
            name, create = f  # (name, callable) tuple form
            f = ConnectorFactory(name, create)
        register_factory(f)
        added.append(f.name)
    for reg in getattr(mod, "get_functions", lambda: [])():
        # (name, typing_fn, eval_fn): contribute a scalar builtin
        fname, typing_fn, eval_fn = reg
        from . import functions as _fns
        from .exec import expr as _expr
        _fns._SCALARS[fname] = typing_fn
        _expr._DISPATCH[fname] = eval_fn
        added.append(fname)
    return added


def create_connector(kind: str, catalog_name: str,
                     props: Optional[Dict[str, str]] = None):
    """connector.name -> Connector instance; ``kind`` may also be a
    'module.path:factory_name' reference, loaded on demand."""
    _ensure_builtins()
    props = props or {}
    if kind not in _FACTORIES and ":" in kind:
        module_path, _, fname = kind.partition(":")
        load_plugin(module_path)
        kind = fname
    f = _FACTORIES.get(kind)
    if f is None:
        raise KeyError(
            f"unknown connector.name '{kind}' (available: "
            f"{', '.join(sorted(_FACTORIES))})")
    return f.create(catalog_name, props)


_BUILTINS_DONE = False


def _ensure_builtins() -> None:
    global _BUILTINS_DONE
    if _BUILTINS_DONE:
        return
    _BUILTINS_DONE = True
    from .connectors.memory import BlackholeConnector, MemoryConnector
    from .connectors.system import SystemConnector
    from .connectors.tpcds import TpcdsConnector
    from .connectors.tpch import TpchConnector

    register_factory(ConnectorFactory(
        "tpch", lambda n, p: TpchConnector(
            rows_per_split=int(p["tpch.rows-per-split"]))
        if "tpch.rows-per-split" in p else TpchConnector()))
    register_factory(ConnectorFactory(
        "tpcds", lambda n, p: TpcdsConnector()))
    register_factory(ConnectorFactory(
        "memory", lambda n, p: MemoryConnector()))
    register_factory(ConnectorFactory(
        "blackhole", lambda n, p: BlackholeConnector()))
    register_factory(ConnectorFactory(
        "system", lambda n, p: SystemConnector()))

    def _stream(n, p):
        from .connectors.stream import StreamConnector
        return StreamConnector(p.get("stream.dir"))
    register_factory(ConnectorFactory("stream", _stream))

    def _localfile(n, p):
        from .connectors.localfile import LocalFileConnector
        return LocalFileConnector(p.get("localfile.root", "."))
    register_factory(ConnectorFactory("localfile", _localfile))

    def _jdbc(n, p):
        from .connectors.jdbc import SqliteConnector
        return SqliteConnector(p.get("connection-url", ":memory:"),
                               p.get("jdbc.schema", "public"))
    register_factory(ConnectorFactory("jdbc", _jdbc))
