"""Multi-stage MPP execution (the stage-DAG subsystem).

Reference parity: SqlQueryScheduler -> SqlStageExecution -> RemoteTask
with PartitionedOutputOperator hash repartition (SURVEY L5/L6). A plan
is cut at exchange points into a DAG of stages (fragmenter.py); each
stage runs as N worker tasks whose output is hash-partitioned across
the downstream stage's tasks (repartition.py) and committed to the
content-addressed FTE spool (fte/spool.py); downstream tasks PULL their
partition of every upstream task through the spool or the producing
worker's partition endpoint (exchange.py); the stage scheduler
(scheduler.py) drives the DAG topologically with per-stage task retries
and straggler speculation. The coordinator executes only the root
stage, streaming the final gather.
"""

from .fragmenter import Stage, StageDAG, StageFragmenter  # noqa: F401
from .repartition import partition_batch, partition_frames  # noqa: F401
from .exchange import ExchangePuller, exchange_task_key  # noqa: F401
