"""Hash repartition: the PartitionedOutput operator.

Reference parity: operator/output/PartitionedOutputOperator.java +
operator/PartitionFunction (hash bucket = raw xxhash of the key columns
mod partition count) and operator/InterpretedHashGenerator combining
key columns. Here the bucketing kernel is a jit-compiled jnp program
over uint64 lanes (ops/hashing.py's splitmix64 finalizer +
multiply-combine), and the row scatter into per-partition pages is a
host gather over the kernel's bucket lane — the same two-phase
"compute on device, pick rows on host" shape as ops/join.py.

Determinism contract (the whole point): the bucket of a row is a pure
function of its key VALUES — never of process-local state. Numeric
lanes cast bijectively to uint64; floats decompose through the
equality-preserving frexp lanes; DICTIONARY string columns hash the
string BYTES per dictionary entry (FNV-1a 64) and gather per-row — two
workers holding the same value under different dictionary codes must
agree on the bucket, or a distributed join silently drops matches.
NULL keys hash to 0 (Trino convention), so all-null-key rows colocate
on partition 0 and outer-join row preservation stays single-copy.

Layout contract: a stage task's spooled attempt holds EXACTLY
``nparts`` frames, frame index == partition index (page_00000.bin is
partition 0). The consumer task for partition p reads frame p of every
upstream task — content-addressed, no manifest needed.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Batch, Column
from ..obs.metrics import (EXCHANGE_PARTITION_BYTES, EXCHANGE_PARTITIONS,
                           JIT_CACHE_LOOKUPS)
from ..ops.hashing import lane_to_u64, mix64, partition_of

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1


def _fnv1a64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h


def dictionary_value_hashes(dictionary) -> np.ndarray:
    """Per-entry uint64 value hash of a StringDictionary — a pure
    function of the string bytes, NOT of the (process-local) code
    assignment. Gathered per row by the entry code, this is the string
    key's partition lane."""
    out = np.empty(len(dictionary.values), dtype=np.uint64)
    for i, s in enumerate(dictionary.values):
        out[i] = _fnv1a64(str(s).encode("utf-8"))
    return out


# cross-query cache of jitted bucket kernels (exec/progkey.py cache
# doctrine). Key lanes are ALWAYS uint64 and valids always bool, so
# (key count, capacity, partition count) is the whole jit signature —
# the one structural cache in the engine that needs no lane-spec walk.
_BUCKET_JIT_CACHE: Dict[tuple, object] = {}


def bucket_program_key(nkeys: int, capacity: int, nparts: int) -> tuple:
    return ("repartition", int(nkeys), int(capacity), int(nparts))


def make_bucket_program(nkeys: int, nparts: int):
    """Per-row partition bucket from pre-extracted uint64 key lanes:
    mix64 each lane (NULL rows -> 0), multiply-combine across key
    columns (CombineHashFunction's 31*h+x), mod the partition count.
    One fused device program per (key count, shape). Module-level
    builder so exec/aot.py rebuilds the EXACT closure this cache
    holds (the "repartition" AOT kind)."""

    def fn(lanes, valids) -> jax.Array:
        hashed = [jnp.where(v, mix64(l), jnp.uint64(0))
                  for l, v in zip(lanes, valids)]
        if nkeys == 1:
            h = hashed[0]
        else:
            acc = jnp.zeros_like(hashed[0]) \
                + jnp.uint64(0x9E3779B97F4A7C15)
            for h1 in hashed:
                acc = acc * jnp.uint64(31) + h1
            h = mix64(acc)
        return partition_of(h, nparts)

    return fn


def _key_lane(col: Column) -> jax.Array:
    """uint64 partition lane of one key column, value-faithful across
    processes (see module docstring)."""
    if col.dictionary is not None:
        entry = dictionary_value_hashes(col.dictionary)
        codes = np.asarray(col.data).astype(np.int64)
        codes = np.clip(codes, 0, len(entry) - 1)
        return jnp.asarray(entry[codes])
    return lane_to_u64(jnp.asarray(col.data))


def partition_buckets(batch: Batch, keys: Sequence[str],
                      nparts: int, session=None) -> np.ndarray:
    """Bucket index in [0, nparts) for each LIVE row of ``batch``."""
    from ..exec import executor as _ex
    from ..exec.hotshapes import record_program
    n = batch.num_rows_host()
    lanes, valids = [], []
    for k in keys:
        c = batch.column(k)
        lanes.append(_key_lane(c))
        valids.append(jnp.ones((c.capacity,), bool) if c.valid is None
                      else jnp.asarray(c.valid).astype(bool))
    cap = int(batch.capacity)
    key = bucket_program_key(len(keys), cap, nparts)
    jitted = _BUCKET_JIT_CACHE.get(key)
    hit = jitted is not None
    JIT_CACHE_LOOKUPS.inc(cache="repartition",
                          result="hit" if hit else "miss")
    if jitted is None:
        jitted = jax.jit(make_bucket_program(len(keys), nparts))
        _ex._cache_put(_BUCKET_JIT_CACHE, key, jitted)
    record_program(
        "repartition", key, None, None, session,
        payload_fn=lambda: {"kind": "repartition",
                            "nkeys": len(keys), "capacity": cap,
                            "nparts": int(nparts)})
    bk = jitted(tuple(lanes), tuple(valids))
    return np.asarray(bk)[:n]


def _host_col(c: Column) -> Column:
    """One device->host readback per lane, shared by every partition's
    row gather (np.asarray on an already-host array is free)."""
    data = np.asarray(c.data)
    valid = None if c.valid is None else np.asarray(c.valid)
    d2 = None if c.data2 is None else np.asarray(c.data2)
    children = None if c.children is None else tuple(
        _host_col(ch) for ch in c.children)
    return Column(c.type, data, valid, c.dictionary, d2, c.elements,
                  c.elements2, children)


def _take_rows_col(c: Column, idx: np.ndarray, n: int) -> Column:
    """Row gather of one column's live prefix. Offset lanes and the
    shared elements pools ride whole (ARRAY/MAP semantics, same as
    server/task_worker._slice_batch); ROW children are row-aligned and
    gather recursively."""
    data = np.asarray(c.data)[:n][idx]
    valid = None if c.valid is None else np.asarray(c.valid)[:n][idx]
    d2 = None if c.data2 is None else np.asarray(c.data2)[:n][idx]
    children = None
    if c.children is not None:
        children = tuple(_take_rows_col(ch, idx, n) for ch in c.children)
    return Column(c.type, data, valid, c.dictionary, d2, c.elements,
                  c.elements2, children)


def _take_rows(batch: Batch, idx: np.ndarray, n: int) -> Batch:
    return Batch({s: _take_rows_col(c, idx, n)
                  for s, c in batch.columns.items()}, len(idx))


def partition_batch(batch: Batch, keys: Sequence[str],
                    nparts: int, session=None) -> List[Batch]:
    """Split ``batch`` into exactly ``nparts`` batches by key hash.
    Partitions are complete and disjoint: every live row lands in
    exactly one output, at bucket(partition_buckets). Empty partitions
    are real (zero-row) batches so the frame layout stays dense."""
    n = batch.num_rows_host()
    if not keys:
        # keyless repartition: deterministic round-robin by row index
        # (the reference's round-robin PagePartitioner for
        # FIXED_ARBITRARY distributions)
        bk = np.arange(n, dtype=np.int64) % max(nparts, 1)
    else:
        bk = partition_buckets(batch, keys, nparts, session=session)
    host = Batch({s: _host_col(c) for s, c in batch.columns.items()},
                 n)
    return [_take_rows(host, np.flatnonzero(bk == p), n)
            for p in range(nparts)]


def partition_frames(batch: Batch, keys: Sequence[str], kind: str,
                     nparts: int, codec: Optional[int] = None,
                     session=None) -> List[bytes]:
    """Serialize a stage's output as partition frames: frame i IS
    partition i (one frame per partition — the deterministic layout the
    exchange contract requires; a consumer reads frame index
    == its own partition). kind="gather" (or nparts==1) emits the whole
    batch as the single partition; kind="replicate" does the same on
    the producing side — the REPLICATE semantics live in the consumer
    (stage/exchange.py), where EVERY task reads frame 0 instead of its
    own partition index, so the bytes are spooled once, not once per
    consumer task."""
    from ..serde import serialize_batch
    n = batch.num_rows_host()
    if kind in ("gather", "replicate") or nparts <= 1:
        host = Batch({s: _host_col(c)
                      for s, c in batch.columns.items()}, n)
        parts = [_take_rows(host, np.arange(n, dtype=np.int64), n)]
    else:
        parts = partition_batch(batch, keys, nparts, session=session)
    frames = [serialize_batch(p, codec=codec) for p in parts]
    EXCHANGE_PARTITIONS.inc(len(frames), direction="written")
    EXCHANGE_PARTITION_BYTES.inc(sum(len(f) for f in frames),
                                 direction="written")
    return frames
