"""Topological stage scheduler: SqlQueryScheduler for the stage DAG.

Reference parity: SqlQueryScheduler driving one SqlStageExecution per
fragment — each stage's tasks dispatch once every input stage's output
is committed, and the coordinator participates only as the root
stage's consumer. Fault tolerance rides the same per-attempt machinery
as the flat path (fte/retry.py budgets + backoff + worker rotation,
fte/speculate.py straggler duplicates): every attempt of a stage task
commits its partition frames to the WORKER's spool under the
attempt-independent exchange key, so the spool's first-commit-wins
marker arbitrates duplicate attempts per-stage for free, and a task
retried after its worker died re-pulls its upstream partitions off the
spool (stage/exchange.py).

Two scheduling modes (``stage_pipelining`` session property):

- **eager pipelining** (default): every stage's tasks dispatch
  IMMEDIATELY, in topological order but without barriers. A consumer
  task's exchange puller blocks per upstream partition until the
  producing task COMMITS it (stage/exchange.py eager mode) — the
  spool's first-commit-wins frames make these partial reads safe, so
  a consumer starts joining/aggregating the moment its first upstream
  task lands while sibling producers are still running. Source
  records publish up front with winner URIs filled in as tasks
  complete; a ``candidates`` list (every live worker) covers the
  cross-host pull before a winner is known.
- **per-stage barrier** (``stage_pipelining=false``): the pre-PR-13
  behavior — a stage dispatches only after every input stage fully
  committed. Kept as the conservative mode and the bench A/B baseline.

The pipelining overlap (share of exchange wall time where >= 2 stages
had tasks in flight) is recorded per query in
``trino_tpu_mpp_pipeline_overlap_ratio``.

A permanently failed task aborts the whole DAG run: the execution-wide
``abort`` event cancels sibling stages' in-flight waits (without
blaming their workers) so a pipelined consumer never spins out its
full timeout against a producer that can no longer commit.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from ..exec.executor import NodeStats
from ..fte.retry import (TASK_RETRIES, RetryController, RetryPolicy,
                         backoff_delay, pick_worker)
from ..fte.speculate import (SPECULATIVE_TASKS, SPECULATIVE_WINS,
                             StragglerDetector)
from ..fte.faultpoints import fault_point
from ..obs.metrics import (FAILOVER_PARTITIONS, MPP_OVERLAP_RATIO,
                           STAGES_SCHEDULED)
from ..plan.nodes import PlanNode, TableScanNode
from .exchange import exchange_task_key
from .fragmenter import Stage, StageDAG


class _Watch:
    """``is_set()`` ORs several events — aborts a status poll the
    moment a sibling attempt wins, the DAG run fails elsewhere, or the
    user cancels."""

    __slots__ = ("_events",)

    def __init__(self, *events):
        self._events = [e for e in events if e is not None]

    def is_set(self) -> bool:
        return any(e.is_set() for e in self._events)


def _plan_has_scan(plan: PlanNode) -> bool:
    """True when a stage body reads table splits (its fan-out follows
    the leaf policy even when it also consumes exchange inputs — the
    colocated scan+join shape)."""
    stack = [plan]
    while stack:
        n = stack.pop()
        if isinstance(n, TableScanNode):
            return True
        stack.extend(n.sources)
    return False


class _STask:
    """One (stage, partition) task's dispatch state across attempts."""

    __slots__ = ("sid", "part", "key", "done", "spec_done", "lock",
                 "failed", "errors", "winner", "_attempts",
                 "running_since", "running_worker", "speculated")

    def __init__(self, sid: int, part: int, key: str):
        self.sid = sid
        self.part = part
        self.key = key
        self.done = threading.Event()
        self.spec_done = threading.Event()
        self.lock = threading.Lock()
        self.failed = False
        self.errors: List[str] = []
        # (attempt, worker index, speculative) of the first completion
        self.winner: Optional[Tuple[int, int, bool]] = None
        self._attempts = 0
        self.running_since: Optional[float] = None
        self.running_worker: Optional[int] = None
        self.speculated = False

    def next_attempt(self) -> int:
        with self.lock:
            attempt = self._attempts
            self._attempts += 1
            return attempt


class _StageRun:
    """One launched stage's in-flight state (tasks + telemetry sinks),
    handed from ``_launch_stage`` to ``_await_stage``."""

    __slots__ = ("stage", "tasks", "worker_stats", "stop_ev")

    def __init__(self, stage: Stage, tasks: List[_STask]):
        self.stage = stage
        self.tasks = tasks
        self.worker_stats: List[List[NodeStats]] = []
        self.stop_ev = threading.Event()


class StageExecution:
    """Runs every worker stage of a DAG for one query; the caller
    (exec/remote.py RemoteScheduler) then executes the root plan on
    the coordinator against ``self.sources``."""

    def __init__(self, scheduler, dag: StageDAG,
                 payloads: Dict[int, dict],
                 qid: Optional[str] = None,
                 ntasks_override: Optional[Dict[int, int]] = None,
                 resume_spool=None):
        self.s = scheduler              # the owning RemoteScheduler
        self.dag = dag
        self.payloads = payloads
        self.qid = qid or uuid.uuid4().hex[:12]
        # failover resume (fte/recovery.py ExecutionManifestStore): the
        # exchange spool's first-commit-wins markers are the durable
        # progress log, so a resuming coordinator marks every already-
        # COMMITTED (stage, part) done WITHOUT dispatching it and
        # replays only the missing partitions. ``resume_spool`` is the
        # spool the workers committed exchange output to;
        # ``ntasks_override`` pins the fan-out recorded in the manifest
        # (the exchange keys embed it — a recomputed fan-out against a
        # different live-worker count would address different keys).
        self.resume_spool = resume_spool
        self._ntasks_override = ntasks_override
        self.resumed_parts = 0          # committed: served off spool
        self.replayed_parts = 0         # missing: re-dispatched
        session = scheduler.session
        self.policy = RetryPolicy.from_session(session)
        self.controller = RetryController(self.policy)
        self.straggler = StragglerDetector(
            multiplier=float(session.get("speculation_multiplier")),
            min_runtime_s=int(
                session.get("speculation_min_runtime_ms")) / 1000.0)
        self.speculation_on = bool(
            session.get("speculation_enabled")) \
            and len(scheduler.workers) > 1
        self.pipelined = bool(session.get("stage_pipelining"))
        # execution-wide abort: set when any stage fails permanently,
        # unblocking sibling stages' waits and eager exchange pulls
        self.abort = threading.Event()
        # sid -> {"tasks": [exchange keys], "uris": [winner uris],
        #         "kind": .., "candidates": [..], "eager": bool} —
        # published up front; task threads fill uris[part] at win time
        self.sources: Dict[int, dict] = {}
        self.ntasks: Dict[int, int] = {}
        self._assign_task_counts()
        # winning-attempt wall windows (sid, t0, t1) for the pipelining
        # overlap rollup; guarded by the scheduler's stats lock
        self._windows: List[Tuple[int, float, float]] = []
        self.overlap_ratio: float = 0.0
        # per-stage telemetry for the EXPLAIN ANALYZE rollup
        # (sid -> MERGED per-node stats across the stage's tasks)
        self.stage_stats: Dict[int, List[NodeStats]] = {}
        self.stage_reported: Dict[int, int] = {}
        self.resources: List[Tuple[int, int]] = []   # (peak, spill)
        # per-stage attribution sums (ISSUE 15): worker-reported
        # scheduler CPU seconds + device seconds, summed across the
        # stage's winning tasks (guarded by the scheduler stats lock)
        self.stage_cpu: Dict[int, float] = {}
        self.stage_device: Dict[int, float] = {}

    # -- task-count assignment ----------------------------------------
    def _assign_task_counts(self) -> None:
        """Fix every stage's task fan-out up front (a stage's OUTPUT
        partition count is its consumer's task count — the bucket-count
        decision the plan deliberately does not carry). Split-reading
        stages (a plain leaf, or a colocated scan+join stage that also
        consumes a replicate input) follow hash_partition_count like
        the flat path; exchange-only stages follow
        exchange_partition_count; a stage fed by a gather exchange runs
        exactly one task (it consumes the single gathered
        partition)."""
        session = self.s.session
        nworkers = len(self.s.workers)
        hpc = int(session.get("hash_partition_count"))
        epc = int(session.get("exchange_partition_count"))
        for st in self.dag.stages:
            if not st.inputs or _plan_has_scan(st.plan):
                n = min(nworkers, hpc) if hpc > 0 else nworkers
            else:
                n = epc if epc > 0 else nworkers
            if st.max_tasks is not None:
                n = min(n, st.max_tasks)
            if any(self.dag.stage(i).output_node.kind == "gather"
                   for i in st.inputs):
                n = 1
            self.ntasks[st.sid] = max(1, n)
        if self._ntasks_override:
            for sid, n in self._ntasks_override.items():
                self.ntasks[int(sid)] = max(1, int(n))

    def _nparts_out(self, stage: Stage) -> int:
        if stage.consumer is None:
            return 1                    # the coordinator's root gather
        return self.ntasks[stage.consumer]

    # -- source records -----------------------------------------------
    def _publish_sources(self) -> None:
        """Pre-publish every stage's exchange record. Task threads fill
        ``uris[part]`` as winners land; under the barrier every uri is
        set before any consumer dispatches, under pipelining the
        ``candidates`` sweep covers the not-yet-known winners."""
        candidates = [c.base_uri for c in self.s.workers]
        for st in self.dag.stages:
            n = self.ntasks[st.sid]
            self.sources[st.sid] = {  # tt-lint: ignore[race-attr-write] published by the driver thread BEFORE any task thread launches; task threads only assign uris slots
                "tasks": [exchange_task_key(self.qid, st.sid, p)
                          for p in range(n)],
                "uris": [None] * n,
                "kind": st.output_node.kind,
                "candidates": candidates,
                "eager": self.pipelined}

    def _snapshot_sources(self, stage: Stage) -> Dict[str, dict]:
        """Per-attempt copy of the input stages' records (the uris
        list mutates as winners land — a submit must ship a stable
        snapshot)."""
        out: Dict[str, dict] = {}
        for i in stage.inputs:
            src = self.sources[i]
            out[str(i)] = {"tasks": list(src["tasks"]),
                           "uris": list(src["uris"]),
                           "kind": src["kind"],
                           "candidates": list(src["candidates"]),
                           "eager": src["eager"]}
        return out

    # -- overlap rollup ------------------------------------------------
    def _compute_overlap(self) -> float:
        """Share of covered wall time where tasks of >= 2 DIFFERENT
        stages ran concurrently — 0 under the barrier, the pipelining
        win when > 0."""
        with self.s._stats_lock:
            windows = list(self._windows)
        if not windows:
            return 0.0
        events: List[Tuple[float, int, int]] = []
        for sid, t0, t1 in windows:
            if t1 > t0:
                events.append((t0, 1, sid))
                events.append((t1, -1, sid))
        if not events:
            return 0.0
        events.sort(key=lambda e: e[0])
        live: Dict[int, int] = {}
        covered = multi = 0.0
        prev = events[0][0]
        for t, delta, sid in events:
            nstages = sum(1 for v in live.values() if v > 0)
            if t > prev and nstages > 0:
                covered += t - prev
                if nstages > 1:
                    multi += t - prev
            live[sid] = live.get(sid, 0) + delta
            prev = t
        return (multi / covered) if covered > 0 else 0.0

    # -- the run -------------------------------------------------------
    def run(self) -> Dict[int, dict]:
        self._publish_sources()
        if self.pipelined:
            self._run_pipelined()
        else:
            for stage in self.dag.stages:
                # deadline propagation: no stage is dispatched past the
                # query's wall-clock budget (the per-attempt waits
                # below are bounded by the same shrinking remainder)
                self.s._check_deadline(f"stage {stage.sid} dispatch")
                self._await_stage(self._launch_stage(stage))
        self.overlap_ratio = self._compute_overlap()  # tt-lint: ignore[race-attr-write] driver-thread-only, written after every stage's tasks completed
        MPP_OVERLAP_RATIO.set(self.overlap_ratio)
        return self.sources

    def _run_pipelined(self) -> None:
        """Eager mode: launch every stage now (topological order, no
        barrier); consumers block inside their exchange pulls until
        upstream partitions commit. Awaiting still walks producers
        first, so per-stage telemetry lands in DAG order; a failure
        aborts the remaining stages' waits."""
        runs: List[_StageRun] = []
        self.s._check_deadline("stage-DAG dispatch")
        for stage in self.dag.stages:
            runs.append(self._launch_stage(stage))
        first_err: Optional[BaseException] = None
        for sr in runs:
            try:
                self._await_stage(sr)
            except BaseException as e:  # noqa: BLE001 — propagate the
                # FIRST failure after unblocking every sibling stage
                self.abort.set()
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err

    def _launch_stage(self, stage: Stage) -> _StageRun:
        s = self.s
        session = s.session
        sid = stage.sid
        ntasks = self.ntasks[sid]
        nout = self._nparts_out(stage)
        STAGES_SCHEDULED.inc()
        tasks = [_STask(sid, part,
                        exchange_task_key(self.qid, sid, part))
                 for part in range(ntasks)]
        sr = _StageRun(stage, tasks)
        trace = getattr(session, "trace", None)
        trace_parent = trace.current() if trace is not None else None
        timeout_s = float(session.get("remote_task_timeout"))

        def alive(wi: int) -> bool:
            det = s.failure_detector
            return det is None or det.is_alive(s.workers[wi].base_uri)

        def run_attempt(st: _STask, attempt: int, wi: int,
                        speculative: bool = False) -> Optional[str]:
            """One attempt of stage task ``st`` on worker ``wi``;
            None on success OR benign loss to a sibling attempt."""
            tid = f"{self.qid}.s{sid}.{st.part}.a{attempt}"
            client = s.workers[wi]
            t0 = time.perf_counter()
            if not speculative:
                with st.lock:
                    st.running_since = t0
                    st.running_worker = wi
            # live memory beats: while the task runs, every status
            # poll folds its current worker-side reservation into the
            # cluster pool (exec/remote.py _live_memory_hook ->
            # server/memory.py reserve_remote), so the low-memory
            # killer judges live worker bytes DURING execution
            beat = s._live_memory_hook(tid)
            on_status = None
            if beat is not None:
                def on_status(stt, _beat=beat):
                    _beat(stt.get("liveMemoryBytes") or 0)
            # distributed tracing: pre-mint this attempt's span id and
            # ship the W3C traceparent so the worker's spans are born
            # with the query's trace id and this id as their parent
            span_id = tp = None
            if trace is not None:
                span_id = trace.new_span_id()
                tp = trace.traceparent(span_id)
            try:
                client.submit_fragment(
                    tid, self.payloads[sid],
                    catalog=session.catalog, schema=session.schema,
                    part=st.part, nparts=ntasks,
                    properties=dict(session.properties),
                    collect_stats=s.collect_stats,
                    attempt=attempt, spool=True,
                    deadline_s=s._remaining_s(),
                    resource_group=getattr(session, "resource_group",
                                           None),
                    group_weight=getattr(session,
                                         "resource_group_weight",
                                         None),
                    stage={"sid": sid, "exchange_key": st.key,
                           "nparts_out": nout,
                           "sources": self._snapshot_sources(stage)},
                    traceparent=tp)
                watch = _Watch(getattr(session, "cancel", None),
                               st.done, self.abort)
                status = client.wait_done(
                    tid, cancel=watch,
                    timeout_s=s._attempt_budget_s(timeout_s),
                    on_status=on_status, traceparent=tp)
                if status.get("state") != "FINISHED":
                    raise RuntimeError(
                        f"task is {status.get('state')}: "
                        f"{status.get('error') or 'no error recorded'}")
            except Exception as e:      # noqa: BLE001
                if not speculative:
                    with st.lock:
                        st.running_since = None
                if st.done.is_set():
                    if not st.failed:
                        return None     # a sibling attempt already won
                    return (f"stage {sid} fragment task {tid}: aborted "
                            "(task already failed)")
                cancel = getattr(session, "cancel", None)
                if cancel is not None and cancel.is_set():
                    return f"stage {sid} fragment task {tid}: canceled"
                if self.abort.is_set():
                    # the DAG already failed elsewhere: this abort is
                    # not evidence against THIS worker — no detector
                    # demerit, no exclusion
                    return (f"stage {sid} fragment task {tid}: aborted "
                            "(query failed in another stage)")
                from ..exec.remote import BUSY_MARK, _busy_decline
                if _busy_decline(e):
                    # retryable BUSY shed (worker 503): rotate to
                    # another worker without a detector demerit or
                    # per-query exclusion — the worker is healthy
                    return (f"{BUSY_MARK} stage {sid} fragment task "
                            f"{tid} on worker {client.base_uri}: "
                            "busy (load shed)")
                if s.failure_detector is not None:
                    s.failure_detector.record_task_failure(
                        client.base_uri, f"{type(e).__name__}: {e}")
                with s._excl_lock:
                    s.excluded.add(wi)
                return (f"stage {sid} fragment task {tid} on worker "
                        f"{client.base_uri}: {type(e).__name__}: {e}")
            finally:
                if beat is not None:
                    beat.release()  # terminal attempt: stop charging
            t1 = time.perf_counter()
            if s.failure_detector is not None:
                s.failure_detector.record_task_success(client.base_uri)
            self.straggler.record(sid, t1 - t0)
            won = False
            with st.lock:
                if st.winner is None:
                    st.winner = (attempt, wi, speculative)
                    won = True
            if not won:
                return None     # duplicate output: the spool's
                #                 first-commit-wins already discarded it
            # publish the winner uri for consumers dispatched from now
            # on (pipelined consumers already in flight sweep the
            # candidates list instead)
            self.sources[sid]["uris"][st.part] = client.base_uri  # tt-lint: ignore[race-attr-write] slot-exclusive: one winner per part, list item assignment is atomic
            # the winner MUST set st.done (finally): a crash in the
            # best-effort telemetry would strand the untimed stage wait
            try:
                # stage tasks report their compiled-shape deltas in
                # the status the scheduler already polls — merged here
                # so the coordinator's hot-shape registry covers
                # worker-side joins/aggregations too (exec/hotshapes)
                from ..exec.hotshapes import HOT_SHAPES
                HOT_SHAPES.merge(status.get("hotShapes") or [])
                # the worker's observed per-operator selectivities /
                # rates ride the same status beat into the learned-
                # stats registry (exec/learnedstats.py) — origin-
                # deduped like the hot shapes above
                from ..exec.learnedstats import LEARNED_STATS
                LEARNED_STATS.merge(status.get("learnedStats") or [])
                cpu_s = float(status.get("cpuSeconds") or 0.0)
                dev_s = float(status.get("deviceSeconds") or 0.0)
                with s._stats_lock:
                    # morsel-streaming rollup: stage tasks report
                    # their chunk counts + h2d bytes like peak memory
                    s.stream_chunks += int(
                        status.get("streamChunks") or 0)
                    s.stream_h2d_bytes += int(
                        status.get("streamH2dBytes") or 0)
                    s.cpu_seconds += cpu_s
                    s.device_seconds += dev_s
                    s.ragged_batched += int(
                        status.get("raggedBatched") or 0)
                    self.stage_cpu[sid] = \
                        self.stage_cpu.get(sid, 0.0) + cpu_s
                    self.stage_device[sid] = \
                        self.stage_device.get(sid, 0.0) + dev_s
                    self._windows.append((sid, t0, t1))
                if speculative:
                    with s._stats_lock:
                        s.speculative_wins += 1
                    SPECULATIVE_WINS.inc()
                if s.collect_stats:
                    reported = [NodeStats.from_dict(d) for d in
                                status.get("nodeStats") or []]
                    if reported:
                        sr.worker_stats.append(reported)
                    with s._stats_lock:
                        self.resources.append((
                            int(status.get("peakMemoryBytes") or 0),
                            int(status.get("spillBytes") or 0)))
                    if trace is not None:
                        # the pre-minted id is what the worker's spans
                        # already name as parent: id-preserving merge
                        sp = trace.record(
                            f"stage_{sid}_execute", t0, t1,
                            parent=trace_parent, span_id=span_id,
                            worker=wi, task=tid,
                            attempt=attempt, speculative=speculative,
                            cpu_s=round(cpu_s, 6),
                            device_ms=round(dev_s * 1000, 3))
                        trace.graft(sp, status.get("spans") or [])
            except Exception:   # noqa: BLE001 — telemetry best-effort
                pass
            finally:
                st.done.set()
            return None

        def run_task(st: _STask) -> None:
            from ..exec.remote import BUSY_MARK, BUSY_RETRY_LIMIT
            failures = 0
            busy_declines = 0
            attempt = st.next_attempt()
            while True:
                if attempt > 0:
                    s._sync_workers()   # live membership: late joiners
                with s._excl_lock:
                    banned = frozenset(s.excluded)
                wi = pick_worker(len(s.workers), st.part, attempt,
                                 banned, alive)
                try:
                    err = run_attempt(st, attempt, wi)
                except Exception as e:  # noqa: BLE001 — an attempt-path
                    # bug must fail the task, not strand the stage wait
                    err = (f"stage {sid} attempt {attempt}: internal: "
                           f"{type(e).__name__}: {e}")
                if err is None:
                    return
                failures += 1
                st.errors.append(err)
                cancel = getattr(session, "cancel", None)
                canceled = (cancel is not None and cancel.is_set()) \
                    or self.abort.is_set()
                rem = s._remaining_s()
                if rem is not None and rem <= 0:
                    canceled = True     # deadline outranks the budget
                if err.startswith(BUSY_MARK) and not canceled:
                    # a BUSY decline never started the dispatch: back
                    # off and rotate without consuming the retry
                    # budget (bounded — a permanently wedged fleet
                    # still fails through the budget machinery)
                    busy_declines += 1
                    if busy_declines <= BUSY_RETRY_LIMIT:
                        delay = backoff_delay(
                            self.policy, failures,
                            f"{self.qid}.s{sid}.{st.part}")
                        if rem is not None:
                            delay = min(delay, max(rem, 0.0))
                        if st.done.wait(delay):
                            return
                        attempt = st.next_attempt()
                        continue
                if canceled or not self.controller.record_failure(
                        (sid, st.part)):
                    # out of attempts — but a healthy speculative
                    # duplicate still in flight decides the task's
                    # fate, not this exhausted primary
                    with st.lock:
                        spec_pending = (st.speculated
                                        and st.winner is None)
                    if spec_pending and not canceled:
                        st.spec_done.wait()
                    with st.lock:
                        if st.winner is None:
                            st.failed = True
                    st.done.set()
                    return
                with s._stats_lock:
                    s.task_retries += 1
                TASK_RETRIES.inc()
                if trace is not None:
                    trace.record(f"stage_{sid}_retry",
                                 time.perf_counter(),
                                 time.perf_counter(),
                                 parent=trace_parent, part=st.part,
                                 worker=wi, attempt=attempt,
                                 error=err[-160:])
                delay = backoff_delay(self.policy, failures,
                                      f"{self.qid}.s{sid}.{st.part}")
                if rem is not None:
                    delay = min(delay, max(rem, 0.0))
                if st.done.wait(delay):
                    return    # a speculative sibling won during backoff
                attempt = st.next_attempt()

        def run_speculative(st: _STask, attempt: int, wi: int) -> None:
            try:
                err = run_attempt(st, attempt, wi, speculative=True)
                if err is not None:
                    st.errors.append("[speculative] " + err)
            except Exception as e:      # noqa: BLE001
                st.errors.append("[speculative] internal: "
                                 f"{type(e).__name__}: {e}")
            finally:
                st.spec_done.set()

        def monitor() -> None:
            while not sr.stop_ev.wait(0.05):
                pending = [st for st in tasks if not st.done.is_set()]
                if not pending:
                    return
                for st in pending:
                    if st.speculated:
                        continue
                    with st.lock:
                        t0 = st.running_since
                        wi_cur = st.running_worker
                        settled = st.winner is not None
                    if settled or t0 is None:
                        continue
                    elapsed = time.perf_counter() - t0
                    if not self.straggler.is_straggler(sid, elapsed):
                        continue
                    rem = s._remaining_s()
                    if rem is not None and rem <= 0:
                        continue     # past the deadline: no new work
                    if not self.controller.grant_speculation(
                            (sid, st.part)):
                        continue
                    st.speculated = True
                    attempt = st.next_attempt()
                    s._sync_workers()
                    with s._excl_lock:
                        banned = frozenset(
                            s.excluded
                            | ({wi_cur} if wi_cur is not None
                               else set()))
                    wi = pick_worker(len(s.workers), st.part, attempt,
                                     banned, alive)
                    if wi == wi_cur:
                        st.spec_done.set()   # nowhere better to run
                        continue
                    with s._stats_lock:
                        s.speculative_launches += 1
                    SPECULATIVE_TASKS.inc()
                    if trace is not None:
                        trace.record(f"stage_{sid}_speculate", t0,
                                     time.perf_counter(),
                                     parent=trace_parent, part=st.part,
                                     attempt=attempt, worker=wi,
                                     straggler_worker=wi_cur)
                    threading.Thread(target=run_speculative,
                                     args=(st, attempt, wi),
                                     daemon=True).start()

        pending = tasks
        if self.resume_spool is not None:
            # failover resume: a COMMITTED exchange key means some
            # earlier attempt's output is durable on the spool —
            # consumers (and the root gather) read it from there, so
            # the task is done without dispatching anything. Only the
            # missing partitions are replayed.
            pending = []
            for st in tasks:
                committed = None
                try:
                    committed = self.resume_spool.committed_attempt(
                        st.key, 0, 0)
                except Exception:   # noqa: BLE001 — treat as missing
                    pass
                if committed is not None:
                    with st.lock:
                        st.winner = (committed, -1, False)
                    st.done.set()
                    self.resumed_parts += 1  # tt-lint: ignore[race-attr-write] driver-thread-only: counted before any task thread launches
                    FAILOVER_PARTITIONS.inc(outcome="resumed")
                else:
                    pending.append(st)
                    self.replayed_parts += 1  # tt-lint: ignore[race-attr-write] driver-thread-only: counted before any task thread launches
                    FAILOVER_PARTITIONS.inc(outcome="replayed")
        for st in pending:
            threading.Thread(target=run_task, args=(st,),
                             daemon=True).start()
        if self.speculation_on:
            threading.Thread(target=monitor, daemon=True).start()
        return sr

    def _await_stage(self, sr: _StageRun) -> None:
        s = self.s
        sid = sr.stage.sid
        try:
            for st in sr.tasks:
                st.done.wait()
        finally:
            sr.stop_ev.set()
        failed = [st for st in sr.tasks if st.failed]
        if failed:
            from ..exec.executor import QueryError
            raise QueryError(
                "remote task failed: " + "; ".join(
                    "; ".join(st.errors[-2:]) for st in failed[:3]))
        # deterministic chaos site: the stage's every partition is now
        # COMMITTED on the spool — the exact boundary where a crashed
        # coordinator leaves a resumable, partially-complete query
        fault_point("coordinator.post_stage_commit")
        if s.collect_stats:
            from ..exec.executor import merge_node_stats
            self.stage_stats[sid] = merge_node_stats(sr.worker_stats)  # tt-lint: ignore[race-attr-write] driver-thread-only, written after the stage's tasks completed
            self.stage_reported[sid] = len(sr.worker_stats)  # tt-lint: ignore[race-attr-write] driver-thread-only, written after the stage's tasks completed
