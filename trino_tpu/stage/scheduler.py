"""Topological stage scheduler: SqlQueryScheduler for the stage DAG.

Reference parity: SqlQueryScheduler driving one SqlStageExecution per
fragment — each stage's tasks dispatch once every input stage's output
is committed, and the coordinator participates only as the root
stage's consumer. Fault tolerance rides the same per-attempt machinery
as the flat path (fte/retry.py budgets + backoff + worker rotation,
fte/speculate.py straggler duplicates): every attempt of a stage task
commits its partition frames to the WORKER's spool under the
attempt-independent exchange key, so the spool's first-commit-wins
marker arbitrates duplicate attempts per-stage for free, and a task
retried after its worker died re-pulls its upstream partitions off the
spool (stage/exchange.py).

Scheduling is stage-by-stage with a barrier (the DAG arrives in
topological order from the fragmenter; eager cross-stage pipelining is
a follow-on — correctness first, the exchange layout already permits
it since consumers address committed frames only).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from ..exec.executor import NodeStats
from ..fte.retry import (TASK_RETRIES, RetryController, RetryPolicy,
                         backoff_delay, pick_worker)
from ..fte.speculate import (SPECULATIVE_TASKS, SPECULATIVE_WINS,
                             StragglerDetector)
from ..obs.metrics import STAGES_SCHEDULED
from .exchange import exchange_task_key
from .fragmenter import Stage, StageDAG


class _Watch:
    """``is_set()`` ORs several events — aborts a status poll the
    moment a sibling attempt wins or the user cancels."""

    __slots__ = ("_events",)

    def __init__(self, *events):
        self._events = [e for e in events if e is not None]

    def is_set(self) -> bool:
        return any(e.is_set() for e in self._events)


class _STask:
    """One (stage, partition) task's dispatch state across attempts."""

    __slots__ = ("sid", "part", "key", "done", "spec_done", "lock",
                 "failed", "errors", "winner", "_attempts",
                 "running_since", "running_worker", "speculated")

    def __init__(self, sid: int, part: int, key: str):
        self.sid = sid
        self.part = part
        self.key = key
        self.done = threading.Event()
        self.spec_done = threading.Event()
        self.lock = threading.Lock()
        self.failed = False
        self.errors: List[str] = []
        # (attempt, worker index, speculative) of the first completion
        self.winner: Optional[Tuple[int, int, bool]] = None
        self._attempts = 0
        self.running_since: Optional[float] = None
        self.running_worker: Optional[int] = None
        self.speculated = False

    def next_attempt(self) -> int:
        with self.lock:
            attempt = self._attempts
            self._attempts += 1
            return attempt


class StageExecution:
    """Runs every worker stage of a DAG for one query; the caller
    (exec/remote.py RemoteScheduler) then executes the root plan on
    the coordinator against ``self.sources``."""

    def __init__(self, scheduler, dag: StageDAG,
                 payloads: Dict[int, dict],
                 qid: Optional[str] = None):
        self.s = scheduler              # the owning RemoteScheduler
        self.dag = dag
        self.payloads = payloads
        self.qid = qid or uuid.uuid4().hex[:12]
        session = scheduler.session
        self.policy = RetryPolicy.from_session(session)
        self.controller = RetryController(self.policy)
        self.straggler = StragglerDetector(
            multiplier=float(session.get("speculation_multiplier")),
            min_runtime_s=int(
                session.get("speculation_min_runtime_ms")) / 1000.0)
        self.speculation_on = bool(
            session.get("speculation_enabled")) \
            and len(scheduler.workers) > 1
        # sid -> {"tasks": [exchange keys], "uris": [winner uris]}
        self.sources: Dict[int, dict] = {}
        self.ntasks: Dict[int, int] = {}
        self._assign_task_counts()
        # per-stage telemetry for the EXPLAIN ANALYZE rollup
        # (sid -> MERGED per-node stats across the stage's tasks)
        self.stage_stats: Dict[int, List[NodeStats]] = {}
        self.stage_reported: Dict[int, int] = {}
        self.resources: List[Tuple[int, int]] = []   # (peak, spill)

    # -- task-count assignment ----------------------------------------
    def _assign_task_counts(self) -> None:
        """Fix every stage's task fan-out up front (a stage's OUTPUT
        partition count is its consumer's task count — the bucket-count
        decision the plan deliberately does not carry). Leaf fan-out
        follows hash_partition_count like the flat path; intermediate
        stages follow exchange_partition_count; a stage fed by a
        gather exchange runs exactly one task (it consumes the single
        gathered partition)."""
        session = self.s.session
        nworkers = len(self.s.workers)
        hpc = int(session.get("hash_partition_count"))
        epc = int(session.get("exchange_partition_count"))
        for st in self.dag.stages:
            if not st.inputs:
                n = min(nworkers, hpc) if hpc > 0 else nworkers
            else:
                n = epc if epc > 0 else nworkers
            if st.max_tasks is not None:
                n = min(n, st.max_tasks)
            if any(self.dag.stage(i).output_node.kind == "gather"
                   for i in st.inputs):
                n = 1
            self.ntasks[st.sid] = max(1, n)

    def _nparts_out(self, stage: Stage) -> int:
        if stage.consumer is None:
            return 1                    # the coordinator's root gather
        return self.ntasks[stage.consumer]

    # -- the run -------------------------------------------------------
    def run(self) -> Dict[int, dict]:
        for stage in self.dag.stages:
            # deadline propagation: no stage is dispatched past the
            # query's wall-clock budget (the per-attempt waits below
            # are bounded by the same shrinking remainder)
            self.s._check_deadline(f"stage {stage.sid} dispatch")
            self._run_stage(stage)
        return self.sources

    def _run_stage(self, stage: Stage) -> None:
        s = self.s
        session = s.session
        sid = stage.sid
        ntasks = self.ntasks[sid]
        nout = self._nparts_out(stage)
        STAGES_SCHEDULED.inc()
        stage_sources = {str(i): self.sources[i] for i in stage.inputs}
        tasks = [_STask(sid, part,
                        exchange_task_key(self.qid, sid, part))
                 for part in range(ntasks)]
        trace = getattr(session, "trace", None)
        trace_parent = trace.current() if trace is not None else None
        worker_stats: List[List[NodeStats]] = []
        timeout_s = float(session.get("remote_task_timeout"))

        def alive(wi: int) -> bool:
            det = s.failure_detector
            return det is None or det.is_alive(s.workers[wi].base_uri)

        def run_attempt(st: _STask, attempt: int, wi: int,
                        speculative: bool = False) -> Optional[str]:
            """One attempt of stage task ``st`` on worker ``wi``;
            None on success OR benign loss to a sibling attempt."""
            tid = f"{self.qid}.s{sid}.{st.part}.a{attempt}"
            client = s.workers[wi]
            t0 = time.perf_counter()
            if not speculative:
                with st.lock:
                    st.running_since = t0
                    st.running_worker = wi
            try:
                client.submit_fragment(
                    tid, self.payloads[sid],
                    catalog=session.catalog, schema=session.schema,
                    part=st.part, nparts=ntasks,
                    properties=dict(session.properties),
                    collect_stats=s.collect_stats,
                    attempt=attempt, spool=True,
                    deadline_s=s._remaining_s(),
                    stage={"sid": sid, "exchange_key": st.key,
                           "nparts_out": nout,
                           "sources": stage_sources})
                watch = _Watch(getattr(session, "cancel", None),
                               st.done)
                status = client.wait_done(
                    tid, cancel=watch,
                    timeout_s=s._attempt_budget_s(timeout_s))
                if status.get("state") != "FINISHED":
                    raise RuntimeError(
                        f"task is {status.get('state')}: "
                        f"{status.get('error') or 'no error recorded'}")
            except Exception as e:      # noqa: BLE001
                if not speculative:
                    with st.lock:
                        st.running_since = None
                if st.done.is_set():
                    if not st.failed:
                        return None     # a sibling attempt already won
                    return (f"stage {sid} fragment task {tid}: aborted "
                            "(task already failed)")
                cancel = getattr(session, "cancel", None)
                if cancel is not None and cancel.is_set():
                    return f"stage {sid} fragment task {tid}: canceled"
                if s.failure_detector is not None:
                    s.failure_detector.record_task_failure(
                        client.base_uri, f"{type(e).__name__}: {e}")
                with s._excl_lock:
                    s.excluded.add(wi)
                return (f"stage {sid} fragment task {tid} on worker "
                        f"{client.base_uri}: {type(e).__name__}: {e}")
            t1 = time.perf_counter()
            if s.failure_detector is not None:
                s.failure_detector.record_task_success(client.base_uri)
            self.straggler.record(sid, t1 - t0)
            won = False
            with st.lock:
                if st.winner is None:
                    st.winner = (attempt, wi, speculative)
                    won = True
            if not won:
                return None     # duplicate output: the spool's
                #                 first-commit-wins already discarded it
            # the winner MUST set st.done (finally): a crash in the
            # best-effort telemetry would strand the untimed stage wait
            try:
                # stage tasks report their compiled-shape deltas in
                # the status the scheduler already polls — merged here
                # so the coordinator's hot-shape registry covers
                # worker-side joins/aggregations too (exec/hotshapes)
                from ..exec.hotshapes import HOT_SHAPES
                HOT_SHAPES.merge(status.get("hotShapes") or [])
                with s._stats_lock:
                    # morsel-streaming rollup: stage tasks report
                    # their chunk counts + h2d bytes like peak memory
                    s.stream_chunks += int(
                        status.get("streamChunks") or 0)
                    s.stream_h2d_bytes += int(
                        status.get("streamH2dBytes") or 0)
                if speculative:
                    with s._stats_lock:
                        s.speculative_wins += 1
                    SPECULATIVE_WINS.inc()
                if s.collect_stats:
                    reported = [NodeStats.from_dict(d) for d in
                                status.get("nodeStats") or []]
                    if reported:
                        worker_stats.append(reported)
                    with s._stats_lock:
                        self.resources.append((
                            int(status.get("peakMemoryBytes") or 0),
                            int(status.get("spillBytes") or 0)))
                    if trace is not None:
                        sp = trace.record(
                            f"stage_{sid}_execute", t0, t1,
                            parent=trace_parent, worker=wi, task=tid,
                            attempt=attempt, speculative=speculative)
                        trace.graft(sp, status.get("spans") or [])
            except Exception:   # noqa: BLE001 — telemetry best-effort
                pass
            finally:
                st.done.set()
            return None

        def run_task(st: _STask) -> None:
            failures = 0
            attempt = st.next_attempt()
            while True:
                if attempt > 0:
                    s._sync_workers()   # live membership: late joiners
                with s._excl_lock:
                    banned = frozenset(s.excluded)
                wi = pick_worker(len(s.workers), st.part, attempt,
                                 banned, alive)
                try:
                    err = run_attempt(st, attempt, wi)
                except Exception as e:  # noqa: BLE001 — an attempt-path
                    # bug must fail the task, not strand the stage wait
                    err = (f"stage {sid} attempt {attempt}: internal: "
                           f"{type(e).__name__}: {e}")
                if err is None:
                    return
                failures += 1
                st.errors.append(err)
                cancel = getattr(session, "cancel", None)
                canceled = cancel is not None and cancel.is_set()
                rem = s._remaining_s()
                if rem is not None and rem <= 0:
                    canceled = True     # deadline outranks the budget
                if canceled or not self.controller.record_failure(
                        (sid, st.part)):
                    # out of attempts — but a healthy speculative
                    # duplicate still in flight decides the task's
                    # fate, not this exhausted primary
                    with st.lock:
                        spec_pending = (st.speculated
                                        and st.winner is None)
                    if spec_pending and not canceled:
                        st.spec_done.wait()
                    with st.lock:
                        if st.winner is None:
                            st.failed = True
                    st.done.set()
                    return
                with s._stats_lock:
                    s.task_retries += 1
                TASK_RETRIES.inc()
                if trace is not None:
                    trace.record(f"stage_{sid}_retry",
                                 time.perf_counter(),
                                 time.perf_counter(),
                                 parent=trace_parent, part=st.part,
                                 worker=wi, attempt=attempt,
                                 error=err[-160:])
                delay = backoff_delay(self.policy, failures,
                                      f"{self.qid}.s{sid}.{st.part}")
                if rem is not None:
                    delay = min(delay, max(rem, 0.0))
                if st.done.wait(delay):
                    return    # a speculative sibling won during backoff
                attempt = st.next_attempt()

        def run_speculative(st: _STask, attempt: int, wi: int) -> None:
            try:
                err = run_attempt(st, attempt, wi, speculative=True)
                if err is not None:
                    st.errors.append("[speculative] " + err)
            except Exception as e:      # noqa: BLE001
                st.errors.append("[speculative] internal: "
                                 f"{type(e).__name__}: {e}")
            finally:
                st.spec_done.set()

        def monitor(stop_ev: threading.Event) -> None:
            while not stop_ev.wait(0.05):
                pending = [st for st in tasks if not st.done.is_set()]
                if not pending:
                    return
                for st in pending:
                    if st.speculated:
                        continue
                    with st.lock:
                        t0 = st.running_since
                        wi_cur = st.running_worker
                        settled = st.winner is not None
                    if settled or t0 is None:
                        continue
                    elapsed = time.perf_counter() - t0
                    if not self.straggler.is_straggler(sid, elapsed):
                        continue
                    rem = s._remaining_s()
                    if rem is not None and rem <= 0:
                        continue     # past the deadline: no new work
                    if not self.controller.grant_speculation(
                            (sid, st.part)):
                        continue
                    st.speculated = True
                    attempt = st.next_attempt()
                    s._sync_workers()
                    with s._excl_lock:
                        banned = frozenset(
                            s.excluded
                            | ({wi_cur} if wi_cur is not None
                               else set()))
                    wi = pick_worker(len(s.workers), st.part, attempt,
                                     banned, alive)
                    if wi == wi_cur:
                        st.spec_done.set()   # nowhere better to run
                        continue
                    with s._stats_lock:
                        s.speculative_launches += 1
                    SPECULATIVE_TASKS.inc()
                    if trace is not None:
                        trace.record(f"stage_{sid}_speculate", t0,
                                     time.perf_counter(),
                                     parent=trace_parent, part=st.part,
                                     attempt=attempt, worker=wi,
                                     straggler_worker=wi_cur)
                    threading.Thread(target=run_speculative,
                                     args=(st, attempt, wi),
                                     daemon=True).start()

        for st in tasks:
            threading.Thread(target=run_task, args=(st,),
                             daemon=True).start()
        stop_ev = threading.Event()
        if self.speculation_on:
            threading.Thread(target=monitor, args=(stop_ev,),
                             daemon=True).start()
        try:
            for st in tasks:
                st.done.wait()
        finally:
            stop_ev.set()
        failed = [st for st in tasks if st.failed]
        if failed:
            from ..exec.executor import QueryError
            raise QueryError(
                "remote task failed: " + "; ".join(
                    "; ".join(st.errors[-2:]) for st in failed[:3]))
        self.sources[sid] = {  # tt-lint: ignore[race-attr-write] DAG-level maps are driver-thread-only: written between stage barriers, task threads never touch them
            "tasks": [st.key for st in tasks],
            "uris": [s.workers[st.winner[1]].base_uri
                     if st.winner is not None else None
                     for st in tasks]}
        if s.collect_stats:
            from ..exec.executor import merge_node_stats
            self.stage_stats[sid] = merge_node_stats(worker_stats)  # tt-lint: ignore[race-attr-write] driver-thread-only, written after the stage barrier
            self.stage_reported[sid] = len(worker_stats)  # tt-lint: ignore[race-attr-write] driver-thread-only, written after the stage barrier
