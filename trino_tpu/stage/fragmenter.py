"""Stage-DAG fragmenter: cut an optimized plan at exchange points.

Reference parity: sql/planner/PlanFragmenter.java over an
AddExchanges-annotated plan — SURVEY L5: SqlQueryScheduler builds a
SqlStageExecution per fragment, fragments meet at
PartitionedOutput/RemoteSource pairs. Here the fragmenter performs
both jobs in one recursive walk: it decides WHERE the exchanges go
(partitioning requirements of each heavy operator) and cuts there.

Stage shapes produced (one "heavy" operator per worker stage plus its
row-local shell):

- **leaf**: a remotable scan chain (scan | filter | project | unnest,
  or a union of such chains), executed over (part, nparts) split
  shares — optionally with a PARTIAL aggregation fused above it;
- **join**: a JoinNode over two RemoteSources co-partitioned on the
  equi-clause keys (hash-partitioned join — both sides repartition by
  their key columns, equal values colocate);
- **aggregation**: FINAL (combinable kinds, avg split into sum+count)
  or SINGLE (holistic kinds and grouping sets — the rows themselves
  repartition by the group keys, including the grouping-set id, so
  every group is complete per task);
- **window**: partition_by-keyed repartition, window per task;
- **semi join**: the filtering source becomes a REPLICATE stage — every
  consumer task reads the WHOLE filtering relation, so SQL's NULL-IN
  semantics (a non-matching probe row's verdict depends on whether the
  filtering side contains a NULL *anywhere*) hold per task; this is
  the replicate-nulls-and-any partitioning collapsed to full
  replication. The probe side stays INLINE (colocated with its scan
  chain) — no probe-side exchange hop;
- **cross / replicated join**: joins without equi-criteria replicate
  the right side; equi-joins the optimizer marked REPLICATED
  (broadcast distribution) do the same, keeping the probe-side scan
  chain inline in the join stage — an exchange hop deleted outright;
- **values**: a single-task stage (inlining VALUES into a split-shared
  stage would duplicate its rows once per task).

Anything else raises ``_Fallback`` and ``fragment`` returns None — the
caller falls back to the flat leaf-fragment path (exec/remote.py).
"""

from __future__ import annotations

from dataclasses import dataclass, replace as dc_replace
from typing import Dict, List, Optional, Tuple

from ..plan.nodes import (Aggregate, AggregationNode,
                          EnforceSingleRowNode, FilterNode, GroupIdNode,
                          JoinNode, LimitNode, OffsetNode, OutputNode,
                          PartitionedOutputNode, PlanNode, ProjectNode,
                          RemoteSourceNode, SemiJoinNode, SortNode,
                          TableScanNode, TopNNode, UnionNode, UnnestNode,
                          ValuesNode, WindowNode)
from ..planner.logical import SemiJoinMultiNode
from ..rex import Call, InputRef
from ..types import BIGINT, DecimalType

# aggregate kinds a PARTIAL/FINAL split supports host-side, mapping to
# the FINAL combine kind (reference: AggregationNode PARTIAL->FINAL +
# InternalAggregationFunction combine; avg splits into sum+count).
# Shared with the flat fragmenter in exec/remote.py — one table, two
# consumers, zero drift.
COMBINABLE_AGGS = {"sum": "sum", "count": "sum", "count_star": "sum",
                   "min": "min", "max": "max", "any_value": "any_value",
                   "bool_and": "bool_and", "bool_or": "bool_or",
                   "every": "bool_and"}


def splittable_aggregates(node: AggregationNode) -> bool:
    """True when every aggregate of ``node`` combines through a
    PARTIAL/FINAL split (distinct never does; avg splits into
    sum+count)."""
    for a in node.aggregates.values():
        if a.distinct:
            return False
        if a.kind == "avg":
            continue
        if a.kind not in COMBINABLE_AGGS:
            return False
    return True


def split_aggregates(aggregates: Dict[str, Aggregate], src_schema
                     ) -> Tuple[Dict[str, Aggregate],
                                Dict[str, Aggregate],
                                Dict[str, Tuple[str, str]]]:
    """(partial, final, avg_posts) for a PARTIAL/FINAL aggregation
    split (PushPartialAggregationThroughExchange, host leg). ``posts``
    maps each avg output symbol to its (sum, count) partial symbols —
    the consumer reconstructs avg as sum/count AFTER the final
    combine."""
    from ..functions import aggregate_result_type
    partial: Dict[str, Aggregate] = {}
    final: Dict[str, Aggregate] = {}
    posts: Dict[str, Tuple[str, str]] = {}
    for sym, a in aggregates.items():
        if a.kind == "avg":
            ssym, csym = sym + "$rsum", sym + "$rcnt"
            sum_t = aggregate_result_type("sum",
                                          [src_schema[a.argument]])
            partial[ssym] = Aggregate("sum", a.argument, sum_t,
                                      mask=a.mask)
            partial[csym] = Aggregate("count", a.argument, BIGINT,
                                      mask=a.mask)
            final[ssym] = Aggregate("sum", ssym, sum_t)
            final[csym] = Aggregate("sum", csym, BIGINT)
            posts[sym] = (ssym, csym)
        else:
            partial[sym] = a
            final[sym] = Aggregate(COMBINABLE_AGGS[a.kind], sym, a.type)
    return partial, final, posts


def build_final_aggregation(pre: PlanNode, node: AggregationNode,
                            finals: Dict[str, Aggregate],
                            posts: Dict[str, Tuple[str, str]]
                            ) -> PlanNode:
    """FINAL combine over gathered/exchanged partials + the avg
    reconstruction projection (decimal division stays on the exact
    Int128 kernel via the planner's "decimal_/" op naming)."""
    out: PlanNode = AggregationNode(pre, node.group_keys, finals,
                                    step="SINGLE")
    if posts:
        assigns = {}
        schema = out.output_schema()
        for s in node.output_schema():
            if s in posts:
                ssym, csym = posts[s]
                a = node.aggregates[s]
                num = InputRef(ssym, schema[ssym])
                den = InputRef(csym, schema[csym])
                op = ("decimal_/" if isinstance(a.type, DecimalType)
                      else "/")
                assigns[s] = Call(op, (num, den), a.type)
            else:
                assigns[s] = InputRef(s, schema[s])
        out = ProjectNode(out, assigns)
    return out


class _Fallback(Exception):
    """This plan shape stays on the flat leaf-fragment path."""


@dataclass
class Stage:
    """One worker stage: N tasks each executing ``plan`` (rooted in a
    PartitionedOutputNode) over either a split share (leaf) or its own
    partition of every ``inputs`` stage's output."""
    sid: int
    plan: PlanNode
    inputs: Tuple[int, ...] = ()
    consumer: Optional[int] = None      # None == consumed by the root
    max_tasks: Optional[int] = None     # 1 for global-FINAL / VALUES

    @property
    def output_node(self) -> PartitionedOutputNode:
        return self.plan  # the fragmenter roots every stage plan here


@dataclass
class StageDAG:
    """Worker stages in topological order (producers first) + the
    coordinator's root plan whose leaves are RemoteSourceNodes."""
    stages: List[Stage]
    root_plan: PlanNode

    def stage(self, sid: int) -> Stage:
        return self.stages[sid]

    def lines(self) -> List[str]:
        """Text rendering for EXPLAIN (the textDistributedPlan analog):
        one block per stage, root last."""
        from ..plan.nodes import plan_tree_lines
        out: List[str] = []
        for st in self.stages:
            po = st.output_node
            head = (f"Stage {st.sid} [output: {po.kind}"
                    + (f" by {list(po.partition_keys)}"
                       if po.partition_keys else "")
                    + (f" <- stages {list(st.inputs)}" if st.inputs
                       else " <- table splits")
                    + (", single task" if st.max_tasks == 1 else "")
                    + "]")
            out.append(head)
            out.extend("   " + l for l in plan_tree_lines(po.source))
        out.append("Stage root [coordinator]")
        out.extend("   " + l for l in plan_tree_lines(self.root_plan))
        return out


class _Ctx:
    """Per-stage build context: upstream stages referenced by the body
    under construction, and a task-count cap the body imposes."""

    __slots__ = ("inputs", "max_tasks")

    def __init__(self):
        self.inputs: List[int] = []
        self.max_tasks: Optional[int] = None


# nodes the coordinator keeps for itself above the top-most exchange:
# inherently-gathered operations (global order, final limit, client
# output) — everything heavy below them runs on workers
_SHELL = (OutputNode, SortNode, TopNNode, LimitNode, OffsetNode,
          EnforceSingleRowNode)


class StageFragmenter:
    """Cuts an optimized plan into a StageDAG, or declines (None)."""

    def __init__(self, catalogs, session=None):
        self.catalogs = catalogs
        self.session = session
        self.stages: List[Stage] = []

    # -- entry ---------------------------------------------------------
    def fragment(self, plan: PlanNode) -> Optional[StageDAG]:
        self.stages = []  # tt-lint: ignore[race-attr-write] a fragmenter instance is created and consumed by ONE thread per fragment() call
        try:
            shell: List[PlanNode] = []
            node = plan
            while True:
                if isinstance(node, _SHELL):
                    shell.append(node)
                    node = node.source
                    continue
                # a row-local wrapper directly above another shell node
                # (Project between Output and Sort) rides with the
                # coordinator; one directly above the core is pushed
                # into the core's stage by _build_body instead
                if isinstance(node, (ProjectNode, FilterNode)) \
                        and isinstance(node.source, _SHELL):
                    shell.append(node)
                    node = node.source
                    continue
                break
            sid = self._stage(node, "gather", ())
            if len(self.stages) < 2:
                # a lone leaf stage: the flat path already handles it,
                # streaming pages instead of spooling an exchange
                return None
            out: PlanNode = RemoteSourceNode(
                (sid,), self.stages[sid].plan.output_schema(), "gather")
            for n in reversed(shell):
                out = dc_replace(n, source=out)
            return StageDAG(self.stages, out)
        except (_Fallback, KeyError):
            return None

    # -- stage construction -------------------------------------------
    def _stage(self, node: PlanNode, out_kind: str,
               out_keys: Tuple[str, ...], post=None) -> int:
        ctx = _Ctx()
        body = self._build_body(node, ctx)
        if post is not None:
            body = post(body)
        schema = body.output_schema()
        missing = [k for k in out_keys if k not in schema]
        if missing:
            raise _Fallback(f"partition keys {missing} not produced")
        sid = len(self.stages)
        stage = Stage(sid, PartitionedOutputNode(body, tuple(out_keys),
                                                 out_kind),
                      tuple(ctx.inputs), None, ctx.max_tasks)
        for i in ctx.inputs:
            self.stages[i].consumer = sid  # tt-lint: ignore[race-attr-write] fragmenter state is single-threaded (one thread per fragment() call)
        self.stages.append(stage)  # tt-lint: ignore[race-attr-mutate] fragmenter state is single-threaded (one thread per fragment() call)
        return sid

    # -- distribution predicates --------------------------------------
    def _remotable_scan(self, scan: TableScanNode) -> bool:
        """Only pure-generator scans may execute on a remote worker
        (coordinator-state-backed catalogs — system.runtime, memory
        tables — must read THIS process; reference:
        SystemPartitioningHandle.COORDINATOR_ONLY)."""
        try:
            conn = self.catalogs.connector(scan.handle.catalog)
        except Exception:       # noqa: BLE001
            return False
        return bool(getattr(conn, "remote_scan_ok",
                            getattr(conn, "scan_cache_ok", False)))

    def _scan_subtree(self, node: PlanNode) -> bool:
        """Source-distributed subtree: executable per split share with
        the shares unioning to the full output (scan chains and unions
        of scan chains; every row-local node in between is fine —
        GroupIdNode replicates rows split-locally, so the shares still
        union to the full grouping-set expansion)."""
        if isinstance(node, TableScanNode):
            return self._remotable_scan(node)
        if isinstance(node, (FilterNode, ProjectNode, UnnestNode,
                             GroupIdNode)):
            return self._scan_subtree(node.source)
        if isinstance(node, UnionNode):
            return all(self._scan_subtree(c) for c in node.children)
        return False

    @staticmethod
    def _values_subtree(node: PlanNode) -> bool:
        while isinstance(node, (FilterNode, ProjectNode)):
            node = node.source
        return isinstance(node, ValuesNode)

    # -- body builder --------------------------------------------------
    def _build_body(self, node: PlanNode, ctx: _Ctx) -> PlanNode:
        """Rewrite ``node`` to execute inside ONE stage's tasks:
        source-distributed subtrees stay inline (split shares), heavy
        operators get RemoteSource inputs backed by freshly cut
        upstream stages with the partitioning the operator needs."""
        if self._scan_subtree(node):
            return node
        if self._values_subtree(node):
            # VALUES emits its rows once per executing task — legal
            # only in a single-task stage
            ctx.max_tasks = 1
            return node
        if isinstance(node, (FilterNode, ProjectNode, UnnestNode,
                             GroupIdNode)):
            return dc_replace(node,
                              source=self._build_body(node.source, ctx))
        if isinstance(node, JoinNode):
            return self._join_body(node, ctx)
        if isinstance(node, (SemiJoinNode, SemiJoinMultiNode)):
            return self._semi_join_body(node, ctx)
        if isinstance(node, AggregationNode):
            return self._aggregation_body(node, ctx)
        if isinstance(node, WindowNode) and node.partition_by:
            sid = self._stage(node.source, "hash",
                              tuple(node.partition_by))
            ctx.inputs.append(sid)
            return dc_replace(node, source=RemoteSourceNode(
                (sid,), node.source.output_schema()))
        raise _Fallback(type(node).__name__)

    def _replicate_input(self, node: PlanNode,
                         ctx: _Ctx) -> RemoteSourceNode:
        """Cut ``node`` into a REPLICATE stage: every task of the
        consuming stage reads its whole output (the reference's
        REPLICATE exchange / BroadcastOutputBuffer)."""
        sid = self._stage(node, "replicate", ())
        ctx.inputs.append(sid)
        return RemoteSourceNode((sid,), node.output_schema(),
                                "replicate")

    def _semi_join_body(self, node, ctx: _Ctx) -> PlanNode:
        """Semi join: the filtering source replicates WHOLE to every
        task, so each task sees any filtering-side NULL anywhere and
        NULL-IN semantics hold per task (the replicate-nulls-and-any
        partitioning, collapsed to full replication). The probe side
        stays inline — colocated with its scan chain, no probe
        exchange hop."""
        filt = self._replicate_input(node.filtering_source, ctx)
        src = self._build_body(node.source, ctx)
        return dc_replace(node, source=src, filtering_source=filt)

    def _join_body(self, node: JoinNode, ctx: _Ctx) -> PlanNode:
        if not node.criteria:
            # cross / filter-only join: replicate the build (right)
            # side, keep the probe inline. Sound only when each task
            # owns its probe rows exclusively — inner/cross always,
            # LEFT because unmatched-probe preservation is probe-local;
            # right/full would preserve the REPLICATED side once per
            # task (duplicates), so they stay on the fallback path.
            if node.join_type not in ("inner", "cross", "left"):
                raise _Fallback(
                    f"{node.join_type} join without equi-criteria")
            right = self._replicate_input(node.right, ctx)
            left = self._build_body(node.left, ctx)
            return dc_replace(node, left=left, right=right)
        if (str(node.distribution or "").lower() == "replicated"
                and node.join_type in ("inner", "left")):
            # REPLICATED (broadcast) distribution, chosen by the
            # optimizer's size heuristic: the build side replicates to
            # every task and the probe-side scan chain stays INLINE in
            # this stage — the probe-side exchange hop is deleted
            # outright (reference: AddExchanges' REPLICATED branch
            # keeps the probe source-distributed)
            right = self._replicate_input(node.right, ctx)
            left = self._build_body(node.left, ctx)
            return dc_replace(node, left=left, right=right)
        lkeys = tuple(c.left for c in node.criteria)
        rkeys = tuple(c.right for c in node.criteria)
        # co-partitioned hash join: both sides repartition on their
        # clause keys with the same bucket function and the same
        # downstream task count, so equal key values meet in the same
        # task (NULL keys hash to 0 on both sides: never match, and
        # outer-row preservation happens exactly once, on partition 0)
        lsid = self._stage(node.left, "hash", lkeys)
        rsid = self._stage(node.right, "hash", rkeys)
        ctx.inputs.extend((lsid, rsid))
        return dc_replace(
            node,
            left=RemoteSourceNode((lsid,),
                                  node.left.output_schema()),
            right=RemoteSourceNode((rsid,),
                                   node.right.output_schema()))

    def _aggregation_body(self, node: AggregationNode,
                          ctx: _Ctx) -> PlanNode:
        if node.step != "SINGLE":
            raise _Fallback("non-SINGLE aggregation")
        # grouping sets distribute like holistic kinds: the group keys
        # include the grouping-set id (planner/logical.py appends it),
        # and GroupIdNode's expansion runs split-locally below, so a
        # hash repartition on the full key tuple colocates every
        # (key values, set id) group — NULLed key lanes of subtotal
        # copies hash identically on every worker (NULL -> 0)
        combinable = (splittable_aggregates(node)
                      and node.group_id_symbol is None)
        gk = tuple(node.group_keys)
        if gk and combinable:
            # PARTIAL fused into the producer stage (above its join /
            # scan), hash exchange on the group keys, FINAL here
            partials, finals, posts = split_aggregates(
                node.aggregates, node.source.output_schema())
            psid = self._stage(
                node.source, "hash", gk,
                post=lambda p, k=gk, ag=partials: AggregationNode(
                    p, k, ag, step="SINGLE"))
            ctx.inputs.append(psid)
            pre = RemoteSourceNode(
                (psid,), self.stages[psid].plan.output_schema())
            return build_final_aggregation(pre, node, finals, posts)
        if gk:
            # holistic kinds (distinct, approx_*, min_by...): the ROWS
            # repartition by group key, every group is complete in one
            # task, the aggregation runs unsplit
            psid = self._stage(node.source, "hash", gk)
            ctx.inputs.append(psid)
            return dc_replace(node, source=RemoteSourceNode(
                (psid,), node.source.output_schema()))
        if not combinable:
            raise _Fallback("global holistic aggregation")
        # global combinable: per-task PARTIALs gather into ONE final
        # task (still a worker — the coordinator only streams the root)
        partials, finals, posts = split_aggregates(
            node.aggregates, node.source.output_schema())
        psid = self._stage(
            node.source, "gather", (),
            post=lambda p, ag=partials: AggregationNode(
                p, (), ag, step="SINGLE"))
        ctx.inputs.append(psid)
        ctx.max_tasks = 1
        pre = RemoteSourceNode(
            (psid,), self.stages[psid].plan.output_schema())
        return build_final_aggregation(pre, node, finals, posts)
