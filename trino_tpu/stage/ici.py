"""ICI-native stage execution: the stage DAG on one TPU slice.

Reference parity: PartitionedOutputOperator's hash repartition — but
lowered to ``jax.lax.all_to_all`` over the inter-chip interconnect
(SURVEY §7.4, "Query Processing on Tensor Computation Runtimes":
collective-based exchange is where tensor-runtime engines beat
host-mediated shuffles). When every task of a stage edge lands on ONE
TPU slice there is no reason to round-trip the exchange through
spool+HTTP frames: a stage's N tasks are the N mesh shards of one SPMD
program, and the PartitionedOutputNode at each stage boundary becomes
a device collective:

- ``hash``  -> ``repartition_by_hash`` (parallel/spmd.py — the
  all_to_all kernel), sized by real per-destination counts;
- ``gather``/``replicate`` -> host materialization of the sharded
  value (the consumers' replicated-operand shape; still in-process,
  no serde, no wire).

This UNIFIES the formerly orphaned ``exec/distributed.py`` mesh
machinery with the stage scheduler: the fragmenter cuts the same
StageDAG the HTTP scheduler runs, and this module executes it with
``DistributedExecutor`` node kernels between collective boundaries —
only cross-host edges ever touch the spool. Exchange volume is split
into ``trino_tpu_exchange_ici_bytes_total`` (device collectives,
here) vs ``trino_tpu_exchange_partition_bytes_total`` (spool/HTTP
frames, stage/repartition.py) so the bench can report where the
shuffle actually moved.

Per-fragment observability (the PR 4 follow-on exec/distributed.py
never got): every stage records a ``stage_<sid>_ici_execute`` span
with row/byte figures, and a straggler detector tracks per-stage wall
against the DAG's running median — an SPMD stage has no sibling
attempt to speculate onto (the slice executes in lockstep), so a
straggling stage is surfaced as a ``stage_<sid>_ici_straggler`` span
plus per-shard row-count skew detail, the actionable signal (data
skew) behind virtually every slow collective stage.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..columnar import Batch
from ..config import capacity_for
from ..fte.speculate import StragglerDetector
from ..obs.metrics import EXCHANGE_ICI_BYTES, EXCHANGE_ICI_EDGES
from ..parallel.mesh import ShardedBatch, unshard_batch
from .fragmenter import Stage, StageDAG


def _value_nbytes(val) -> int:
    """Lane-shape byte volume of a batch/sharded batch (no device
    sync: jax arrays know nbytes from shape * dtype)."""
    total = 0
    for c in val.columns.values():
        for lane in (c.data, c.valid, c.data2):
            if lane is not None:
                total += int(getattr(lane, "nbytes", 0))
    return total


def _repartitionable(sb: ShardedBatch, keys) -> bool:
    """The all_to_all kernel moves data/valid/data2 lanes; array
    columns (shared elements pools) and dual-lane keys stay on the
    consumer-side exchange fallback."""
    if sb.n_shards <= 1:
        return False
    if any(k not in sb.columns for k in keys):
        return False
    if any(c.elements is not None for c in sb.columns.values()):
        return False
    if any(sb.columns[k].data2 is not None for k in keys):
        return False
    return True


class IciStageExecution:
    """Executes a StageDAG on the device mesh of a
    ``DistributedExecutor``: stage bodies run through the executor's
    sharded node kernels, stage boundaries lower to device
    collectives. The executor's ``_ici_values`` map is the in-slice
    exchange: RemoteSourceNode leaves resolve to the producer stage's
    value instead of pulling spool frames."""

    def __init__(self, dexec, dag: StageDAG):
        self.dexec = dexec
        self.dag = dag
        self.values: Dict[int, object] = {}
        session = dexec.session
        self.straggler = StragglerDetector(
            multiplier=float(session.get("speculation_multiplier")),
            min_runtime_s=int(
                session.get("speculation_min_runtime_ms")) / 1000.0)

    # -- boundary lowering --------------------------------------------
    def _lower_boundary(self, stage: Stage, val):
        """Lower the stage's PartitionedOutputNode to a device
        collective. Best-effort placement: the sharded node kernels
        downstream re-exchange as their operator needs (join
        broadcast/repartition, aggregation all_to_all), so an edge the
        kernel cannot move stays put — correctness never depends on
        the boundary, only locality does."""
        po = stage.output_node
        kind = po.kind
        if kind == "hash" and isinstance(val, ShardedBatch):
            keys = list(po.partition_keys)
            if _repartitionable(val, keys):
                from ..parallel.spmd import (repartition_by_hash,
                                             repartition_dest_counts)
                counts = repartition_dest_counts(val, keys)
                cap = capacity_for(max(int(jnp.max(counts)), 1))
                out = repartition_by_hash(val, keys, out_cap=cap)
                EXCHANGE_ICI_EDGES.inc(kind="hash")
                EXCHANGE_ICI_BYTES.inc(_value_nbytes(out), kind="hash")
                return out
            return val
        if kind in ("gather", "replicate"):
            if isinstance(val, ShardedBatch):
                out = unshard_batch(val)
                EXCHANGE_ICI_EDGES.inc(kind=kind)
                EXCHANGE_ICI_BYTES.inc(_value_nbytes(out), kind=kind)
                return out
            return val
        return val

    def _skew(self, val) -> Optional[str]:
        """Per-shard row-count imbalance of a sharded stage output —
        the data-skew face of a straggling collective stage."""
        if not isinstance(val, ShardedBatch):
            return None
        counts = np.asarray(val.num_rows)
        if counts.size < 2 or counts.max() == 0:
            return None
        med = float(np.median(counts))
        if med > 0 and counts.max() > 2.0 * med:
            return (f"max shard {int(counts.max())} rows vs median "
                    f"{int(med)}")
        return None

    # -- the run -------------------------------------------------------
    def run(self) -> Batch:
        dexec = self.dexec
        trace = getattr(dexec.session, "trace", None)
        prev = getattr(dexec, "_ici_values", None)
        dexec._ici_values = self.values
        try:
            for st in self.dag.stages:
                t0 = time.perf_counter()
                val = dexec.execute(st.plan.source)
                val = self._lower_boundary(st, val)
                self.values[st.sid] = val  # tt-lint: ignore[race-attr-write] ICI stage runs are driver-thread-only (one SPMD program at a time, no task threads)
                t1 = time.perf_counter()
                wall = t1 - t0
                straggling = self.straggler.is_straggler("ici", wall)
                self.straggler.record("ici", wall)
                if trace is not None:
                    rows = (val.total_rows_host()
                            if isinstance(val, ShardedBatch)
                            else val.num_rows_host())
                    trace.record(f"stage_{st.sid}_ici_execute", t0, t1,
                                 kind=st.output_node.kind,
                                 rows=int(rows),
                                 bytes=_value_nbytes(val))
                    if straggling:
                        # no sibling shard to speculate onto inside a
                        # lockstep SPMD program: surface the straggler
                        # with its skew diagnosis instead
                        trace.record(f"stage_{st.sid}_ici_straggler",
                                     t0, t1,
                                     skew=self._skew(val) or "none")
            return dexec.execute(self.dag.root_plan)
        finally:
            dexec._ici_values = prev
