"""Worker-to-worker partitioned exchange: the consuming half.

Reference parity: operator/ExchangeOperator + ExchangeClient pulling a
RemoteSourceNode's pages from upstream tasks' output buffers. Here the
shuffle medium is the content-addressed FTE spool (fte/spool.py): a
stage task's attempt commits exactly one frame per output partition
under the attempt-independent exchange key ``<qid>.s<sid>.p<part>``,
so a consumer addresses partition ``p`` of upstream task ``t`` as
frame ``p`` of that key's COMMITTED attempt — no manifest, and task
retries/speculation dedupe through the spool's first-commit-wins
marker exactly like any other attempt.

Exchange kinds (the producing stage's PartitionedOutputNode.kind,
recorded per source by the stage scheduler):

- ``hash``: a consumer task reads frame index == its OWN partition of
  every upstream task (co-partitioned exchange);
- ``gather``: a single consumer task reads the single frame 0;
- ``replicate``: EVERY consumer task reads frame 0 of every upstream
  task — the REPLICATE exchange (broadcast build sides, semi-join
  filtering sources: each task sees the WHOLE relation, which is what
  makes NULL-IN semantics and cross joins partition-safe).

Pull order per upstream task:
  1. the local spool (``read_frame``) — on a shared spool base
     (same-host worker fleet, or the object-store backend) this is the
     whole exchange: a consumer never touches the network, and a DEAD
     producer's committed output is still readable (what makes
     mid-DAG task retry recovery work);
  2. HTTP ``GET /v1/partition/{key}/{index}`` on the worker the
     scheduler observed winning the task (server/task_worker.py) —
     the cross-host leg when spools are not shared. Under eager
     pipelining the winner is not known at consumer-dispatch time, so
     the scheduler also ships a ``candidates`` list (every live
     worker) and the puller sweeps it.

Eager pipelining (``eager`` in the source record): a partition that
resolves nowhere is NOT an instant failure — the producer stage may
simply still be running, so the puller BLOCKS, re-polling spool+HTTP
until the frame commits, bounded by ``timeout_s``/``cancel``. The
spool's first-commit-wins markers make these partial reads safe: only
committed attempts are ever visible. In barrier mode (no ``eager``
flag) an unresolvable partition raises immediately — the consuming
ATTEMPT fails and the stage scheduler's retry machinery takes over.
"""

from __future__ import annotations

import threading
import time
import urllib.request
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..obs.metrics import (EXCHANGE_PARTITION_BYTES,
                           EXCHANGE_PARTITIONS, REPLICATE_CACHE)


def exchange_task_key(query_id: str, sid: int, part: int) -> str:
    """Attempt-independent spool address of one stage task's output
    (every attempt of the task commits under this key; the COMMITTED
    marker arbitrates)."""
    return f"{query_id}.s{sid}.p{part}"


# --------------------------------------------------------------------------
# per-worker fetch-once cache for replicate exchange edges: EVERY
# consumer task of a replicated (broadcast) stage output reads the
# SAME frame 0 of every upstream task, so without a cache a worker
# running N consumer tasks pulls (and a remote producer serves) the
# identical frame N times — on the HTTP path that is N network round
# trips per edge (ROADMAP item 4 leftover). Keyed by the attempt-
# independent exchange key + frame index: first-commit-wins makes the
# bytes under a key immutable once committed, so a cached frame can
# never go stale. LRU by bytes (CONFIG.replicate_cache_bytes); also
# shed under memory pressure (exec/executor.py evict_cache_pressure).
# --------------------------------------------------------------------------

_REPL_LOCK = threading.Lock()
_REPL_CACHE: "OrderedDict[Tuple[str, int], bytes]" = OrderedDict()
_REPL_BYTES = [0]           # box: mutated under _REPL_LOCK only


def _replicate_cache_get(key: str, index: int) -> Optional[bytes]:
    with _REPL_LOCK:
        frame = _REPL_CACHE.get((key, index))
        if frame is not None:
            _REPL_CACHE.move_to_end((key, index))
    REPLICATE_CACHE.inc(result="hit" if frame is not None else "miss")
    return frame


def _replicate_cache_put(key: str, index: int, frame: bytes) -> None:
    from ..config import CONFIG
    limit = int(CONFIG.replicate_cache_bytes or 0)
    if limit <= 0 or len(frame) > limit:
        return                  # disabled, or the frame alone busts it
    with _REPL_LOCK:
        if (key, index) in _REPL_CACHE:
            return              # a concurrent consumer won the fill
        while _REPL_BYTES[0] + len(frame) > limit and _REPL_CACHE:
            _, old = _REPL_CACHE.popitem(last=False)
            _REPL_BYTES[0] -= len(old)
        _REPL_CACHE[(key, index)] = frame
        _REPL_BYTES[0] += len(frame)


def evict_replicate_cache(need_bytes: Optional[int] = None) -> int:
    """Shed fetch-once cache bytes oldest-first (memory-pressure
    governance hook; ``None`` clears everything). Returns bytes
    freed."""
    freed = 0
    with _REPL_LOCK:
        while _REPL_CACHE and (need_bytes is None
                               or freed < int(need_bytes)):
            _, old = _REPL_CACHE.popitem(last=False)
            _REPL_BYTES[0] -= len(old)
            freed += len(old)
    if freed:
        from ..obs.metrics import CACHE_PRESSURE_EVICTS
        CACHE_PRESSURE_EVICTS.inc(cache="replicate")
    return freed


def replicate_cache_bytes() -> int:
    with _REPL_LOCK:
        return _REPL_BYTES[0]


class ExchangePuller:
    """Reads this task's partition of every upstream stage task.

    ``sources`` maps stage id (as str or int — JSON stringifies dict
    keys on the wire) to ``{"tasks": [exchange keys...],
    "uris": [winning worker base uris...], "kind": "hash|gather|
    replicate", "candidates": [worker base uris...], "eager": bool}``
    as recorded by the stage scheduler. ``spool`` is the caller's
    local spool (the worker's own, or a worker-shaped spool on the
    coordinator) and may be None.
    """

    def __init__(self, sources: Dict, part: int, spool=None,
                 timeout_s: float = 600.0, cancel=None):
        self.sources = {str(k): v for k, v in (sources or {}).items()}
        self.part = int(part)
        self.spool = spool
        self.timeout_s = float(timeout_s)
        self.cancel = cancel

    # -- one partition frame ------------------------------------------
    def _try_once(self, key: str, index: int, uris: List[str],
                  errors: List[str], req_timeout: float
                  ) -> Optional[bytes]:
        if self.spool is not None:
            try:
                frame = self.spool.read_frame(key, 0, 0, index)
            except Exception as e:      # noqa: BLE001 — fall to HTTP
                frame = None
                errors.append(f"spool: {type(e).__name__}: {e}")
            if frame is not None:
                return frame
        from ..serde import frame_valid
        for uri in uris:
            if not uri:
                continue
            try:
                with urllib.request.urlopen(
                        f"{uri.rstrip('/')}/v1/partition/{key}/{index}",
                        timeout=req_timeout) as r:
                    frame = r.read()
                # the candidate sweep may hit a wedged/foreign endpoint
                # that 200s arbitrary bytes: only a structurally valid
                # frame (magic + checksum) is an answer
                if frame_valid(frame):
                    return frame
                errors.append(f"{uri}: invalid frame body")
            except Exception as e:      # noqa: BLE001
                errors.append(f"{uri}: {type(e).__name__}: {e}")
        return None

    def pull_frame(self, key: str, uri: Optional[str],
                   index: Optional[int] = None,
                   candidates: Optional[List[str]] = None,
                   eager: bool = False) -> bytes:
        """One partition frame of one upstream task. ``index`` defaults
        to this consumer's own partition (the hash-exchange contract);
        gather/replicate pulls pass 0. ``eager`` blocks until the frame
        commits (pipelined mode) instead of failing the attempt."""
        idx = self.part if index is None else int(index)
        uris = [uri] + [c for c in (candidates or ()) if c != uri]
        deadline = time.monotonic() + self.timeout_s
        # start near-spin: sub-second stages commit in milliseconds,
        # and a 20ms first sleep would hand the whole pipelining win
        # back as added per-edge latency; back off geometrically for
        # genuinely long producers
        delay = 0.002
        # eager sweeps probe with a SHORT per-request timeout: the loop
        # re-polls anyway, and a half-dead candidate (zombie listening
        # socket of a killed worker) must cost seconds per pass, not
        # the whole attempt budget
        req_timeout = (2.0 if eager
                       else max(1.0, min(self.timeout_s, 60.0)))
        while True:
            if self.cancel is not None and self.cancel.is_set():
                raise RuntimeError(f"exchange pull of {key} canceled")
            errors: List[str] = []
            frame = self._try_once(key, idx, uris, errors, req_timeout)
            if frame is not None:
                return frame
            if not eager or time.monotonic() > deadline:
                raise RuntimeError(
                    f"exchange partition {idx} of {key} unavailable"
                    + (f" ({'; '.join(errors[-3:])})" if errors else ""))
            # the producer task may still be running: wait for its
            # commit (the whole point of eager pipelining — consumers
            # start before producers finish)
            time.sleep(delay)
            delay = min(delay * 1.6, 0.1)

    # -- the Executor hook (exec/executor.py _exec_RemoteSourceNode) --
    def read_fragment(self, fid: int) -> List:
        """Deserialized batches: this task's slice of upstream stage
        ``fid`` — its own partition of every task (hash), or the whole
        output (gather/replicate)."""
        from ..serde import deserialize_batch
        src = self.sources.get(str(fid))
        if src is None:
            raise RuntimeError(
                f"no exchange source recorded for stage {fid}")
        tasks = list(src.get("tasks") or ())
        uris = list(src.get("uris") or ())
        uris += [None] * (len(tasks) - len(uris))
        kind = str(src.get("kind") or "hash")
        candidates = list(src.get("candidates") or ())
        eager = bool(src.get("eager"))
        index = 0 if kind in ("gather", "replicate") else None
        out, nbytes = [], 0
        for key, uri in zip(tasks, uris):
            frame = None
            if kind == "replicate":
                # fetch-once: sibling consumer tasks on this worker
                # already pulled the identical broadcast frame
                frame = _replicate_cache_get(key, 0)
            if frame is None:
                frame = self.pull_frame(key, uri, index=index,
                                        candidates=candidates,
                                        eager=eager)
                if kind == "replicate":
                    _replicate_cache_put(key, 0, frame)
            nbytes += len(frame)
            out.append(deserialize_batch(frame))
        EXCHANGE_PARTITIONS.inc(len(out), direction="read")
        EXCHANGE_PARTITION_BYTES.inc(nbytes, direction="read")
        return out
