"""Worker-to-worker partitioned exchange: the consuming half.

Reference parity: operator/ExchangeOperator + ExchangeClient pulling a
RemoteSourceNode's pages from upstream tasks' output buffers. Here the
shuffle medium is the content-addressed FTE spool (fte/spool.py): a
stage task's attempt commits exactly one frame per output partition
under the attempt-independent exchange key ``<qid>.s<sid>.p<part>``,
so a consumer addresses partition ``p`` of upstream task ``t`` as
frame ``p`` of that key's COMMITTED attempt — no manifest, and task
retries/speculation dedupe through the spool's first-commit-wins
marker exactly like any other attempt.

Pull order per upstream task:
  1. the local spool (``read_frame``) — on a shared spool base
     (same-host worker fleet, or the object-store backend) this is the
     whole exchange: a consumer never touches the network, and a DEAD
     producer's committed output is still readable (what makes
     mid-DAG task retry recovery work);
  2. HTTP ``GET /v1/partition/{key}/{index}`` on the worker the
     scheduler observed winning the task (server/task_worker.py) —
     the cross-host leg when spools are not shared.

A partition that resolves nowhere raises — the consuming ATTEMPT
fails and the stage scheduler's retry machinery takes over.
"""

from __future__ import annotations

import urllib.request
from typing import Dict, List, Optional

from ..obs.metrics import EXCHANGE_PARTITION_BYTES, EXCHANGE_PARTITIONS


def exchange_task_key(query_id: str, sid: int, part: int) -> str:
    """Attempt-independent spool address of one stage task's output
    (every attempt of the task commits under this key; the COMMITTED
    marker arbitrates)."""
    return f"{query_id}.s{sid}.p{part}"


class ExchangePuller:
    """Reads this task's partition of every upstream stage task.

    ``sources`` maps stage id (as str or int — JSON stringifies dict
    keys on the wire) to ``{"tasks": [exchange keys...],
    "uris": [winning worker base uris...]}`` as recorded by the stage
    scheduler. ``spool`` is the caller's local spool (the worker's own,
    or a worker-shaped spool on the coordinator) and may be None.
    """

    def __init__(self, sources: Dict, part: int, spool=None,
                 timeout_s: float = 600.0, cancel=None):
        self.sources = {str(k): v for k, v in (sources or {}).items()}
        self.part = int(part)
        self.spool = spool
        self.timeout_s = float(timeout_s)
        self.cancel = cancel

    # -- one partition frame ------------------------------------------
    def pull_frame(self, key: str, uri: Optional[str]) -> bytes:
        if self.cancel is not None and self.cancel.is_set():
            raise RuntimeError(f"exchange pull of {key} canceled")
        errors: List[str] = []
        if self.spool is not None:
            try:
                frame = self.spool.read_frame(key, 0, 0, self.part)
            except Exception as e:      # noqa: BLE001 — fall to HTTP
                frame, errors = None, [f"spool: {type(e).__name__}: {e}"]
            if frame is not None:
                return frame
        if uri:
            try:
                with urllib.request.urlopen(
                        f"{uri.rstrip('/')}/v1/partition/{key}/"
                        f"{self.part}",
                        timeout=max(1.0, min(self.timeout_s, 60.0))) as r:
                    return r.read()
            except Exception as e:      # noqa: BLE001
                errors.append(f"{uri}: {type(e).__name__}: {e}")
        raise RuntimeError(
            f"exchange partition {self.part} of {key} unavailable"
            + (f" ({'; '.join(errors)})" if errors else ""))

    # -- the Executor hook (exec/executor.py _exec_RemoteSourceNode) --
    def read_fragment(self, fid: int) -> List:
        """Deserialized batches: this task's partition of every task of
        upstream stage ``fid``."""
        from ..serde import deserialize_batch
        src = self.sources.get(str(fid))
        if src is None:
            raise RuntimeError(
                f"no exchange source recorded for stage {fid}")
        tasks = list(src.get("tasks") or ())
        uris = list(src.get("uris") or ())
        uris += [None] * (len(tasks) - len(uris))
        out, nbytes = [], 0
        for key, uri in zip(tasks, uris):
            frame = self.pull_frame(key, uri)
            nbytes += len(frame)
            out.append(deserialize_batch(frame))
        EXCHANGE_PARTITIONS.inc(len(out), direction="read")
        EXCHANGE_PARTITION_BYTES.inc(nbytes, direction="read")
        return out
