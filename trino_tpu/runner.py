"""LocalQueryRunner: in-process parse -> plan -> optimize -> execute.

Reference parity: core/trino-main/.../testing/LocalQueryRunner.java:220
(994 loc) — full query execution in one process, no RPC, pluggable
catalogs — plus the DDL/utility statement dispatch that the reference
routes through execution/*Task.java (SetSessionTask, CreateTableTask,
ShowQueriesRewrite for SHOW statements).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .catalog import (CatalogManager, ColumnMetadata, TableHandle,
                      TableMetadata)
from .columnar import Batch, batch_from_pylist
from .connectors.memory import BlackholeConnector, MemoryConnector
from .connectors.tpcds import TpcdsConnector
from .connectors.tpch import TpchConnector
from .exec import Executor, QueryError
from .functions import list_functions
from .obs.trace import null_span
from .plan.nodes import OutputNode, plan_tree_lines
from .planner import LogicalPlanner, PlanningError
from .planner.optimizer import optimize
from .session import SESSION_PROPERTIES, Session
from .sql import ast as A
from .sql.parser import parse_statement
from .sql.tokenizer import ParseError
from .types import Type, VARCHAR, BIGINT, parse_type


@dataclass
class QueryResult:
    """Client-facing result (reference: client QueryResults payload,
    Appendix B.1) plus the telemetry captured while producing it
    (per-node stats, the span tree, the plan rendering — the inputs of
    /v1/query/{id} and EXPLAIN ANALYZE)."""
    columns: List[str]
    types: List[Type]
    rows: List[list]
    query_id: str = ""
    wall_s: float = 0.0
    update_type: Optional[str] = None
    update_count: Optional[int] = None
    stats: Optional[list] = None            # List[NodeStats]
    plan_lines: Optional[List[str]] = None  # captured at execution time
    trace: Optional[object] = None          # obs.trace.QueryTrace
    peak_memory_bytes: int = 0
    spill_bytes: int = 0
    # canonical plan key (exec/learnedstats.py plan_key_for): the
    # identity the query-history store and the learned-stats registry
    # share — renamed/reordered plans of one structural program match
    plan_key: str = ""

    def __iter__(self):
        return iter(self.rows)

    def __len__(self):
        return len(self.rows)


class LocalQueryRunner:
    """In-process runner; with ``distributed=True`` executes over the
    device mesh (the DistributedQueryRunner analog — N mesh devices play
    the N workers, SURVEY.md §4 tier 2)."""

    def __init__(self, session: Optional[Session] = None,
                 with_tpch: bool = True, distributed: bool = False,
                 n_devices: Optional[int] = None,
                 catalogs: Optional[CatalogManager] = None,
                 mesh=None, collect_node_stats: bool = False):
        # per-node wall/row stats on every query (OperatorStats is
        # always-on in the reference; here opt-in because the stats
        # fence adds a device sync per plan node)
        self.collect_node_stats = collect_node_stats
        if catalogs is not None:
            self.catalogs = catalogs
        else:
            self.catalogs = CatalogManager()
            if with_tpch:
                self.catalogs.register("tpch", TpchConnector())
                self.catalogs.register("tpcds", TpcdsConnector())
            self.catalogs.register("memory", MemoryConnector())
            self.catalogs.register("blackhole", BlackholeConnector())
            from .connectors.system import SystemConnector
            self.catalogs.register("system", SystemConnector())
            # disk-backed (CONFIG.stream_dir), so unlike memory this
            # default genuinely shares state with worker processes
            from .connectors.stream import StreamConnector
            self.catalogs.register("stream", StreamConnector())
        self.session = session or Session(catalog="tpch", schema="tiny")
        self.mesh = mesh
        # engine transaction state (reference:
        # transaction/InMemoryTransactionManager — per-catalog
        # copy-on-begin, restore-on-rollback)
        self._txn_snapshot = None
        if distributed and self.mesh is None:
            from .parallel.mesh import get_mesh
            self.mesh = get_mesh(n_devices)

    def _make_executor(self, collect_stats: bool = False) -> Executor:
        if self.mesh is not None:
            from .exec.distributed import DistributedExecutor
            return DistributedExecutor(self.catalogs, self.session,
                                       self.mesh, collect_stats)
        return Executor(self.catalogs, self.session, collect_stats)

    # ------------------------------------------------------------------
    def execute(self, sql: str) -> QueryResult:
        from .obs.metrics import (QUERY_PEAK_MEMORY_BYTES,
                                  QUERY_WALL_SECONDS)
        from .obs.trace import QueryTrace
        t0 = time.perf_counter()
        # tracing rides with stats collection: it is cheap but not
        # free (a span per jitted dispatch), so the no-telemetry path
        # must stay trace-less for _jit_call's early return to matter
        trace = QueryTrace() if self.collect_node_stats else None
        sp = trace.span if trace is not None else null_span
        prev_trace = self.session.trace
        self.session.trace = trace
        # deadline derivation for standalone runs: the coordinator's
        # tracker stamps session.deadline before dispatch; a runner
        # used directly derives it here so query_max_run_time is
        # enforced (executor checks between plan nodes) without a
        # tracker above it
        owned_deadline = False
        if self.session.deadline is None:
            limit = int(self.session.get("query_max_run_time") or 0)
            if limit > 0:
                self.session.deadline = time.monotonic() + limit
                owned_deadline = True
        try:
            try:
                with sp("parse"):
                    stmt = parse_statement(sql)
            except ParseError as e:
                raise QueryError(f"SYNTAX_ERROR: {e}") from e
            # a coordinator-stamped id (QueryTracker.submit) wins so
            # split events and spans correlate with /v1/query entries;
            # it is consumed here — a reused standalone session mints a
            # fresh runner-local id per query
            qid = self.session.query_id or self.session.next_query_id()
            self.session.query_id = qid
            if trace is not None:
                trace.query_id = qid
            try:
                result = self._dispatch(stmt, sql)
            except PlanningError as e:
                raise QueryError(str(e)) from e
            except KeyError as e:
                raise QueryError(str(e).strip('"')) from e
        finally:
            self.session.trace = prev_trace
            self.session.query_id = ""
            if owned_deadline:
                self.session.deadline = None
            # observed for failed/canceled queries too — the slowest
            # queries are exactly the ones that time out, and a latency
            # histogram that drops them reads optimistic at p99
            QUERY_WALL_SECONDS.observe(time.perf_counter() - t0)
            # OTLP export (obs/otlp.py): best-effort, sink-configured
            # — in the finally so failed queries' traces export too
            if trace is not None and trace.roots:
                from .obs.otlp import maybe_export
                maybe_export(trace, session=self.session)
        result.query_id = qid
        result.wall_s = time.perf_counter() - t0
        result.trace = trace
        QUERY_PEAK_MEMORY_BYTES.set(result.peak_memory_bytes)
        return result

    # ------------------------------------------------------------------
    def execute_batch(self, sql: str):
        """Run a query, returning the raw result Batch (the task-worker
        data plane serializes it into page frames — server/
        task_worker.py; the reference's TaskOutputOperator hands Pages
        to the output buffer rather than JSON rows)."""
        stmt = parse_statement(sql)
        if not isinstance(stmt, A.QueryStatement):
            raise QueryError("execute_batch supports queries only")
        planner = LogicalPlanner(self.catalogs, self.session)
        plan = optimize(planner.plan(stmt), self.catalogs, self.session)
        batch = self._make_executor(False).execute(plan)
        # wire format carries DISPLAY column names, not plan symbols;
        # repeated names are disambiguated positionally (the frame is
        # keyed by name, unlike the reference's positional wire pages)
        cols = {}
        for i, (name, sym) in enumerate(zip(plan.names, plan.symbols)):
            key = name if name not in cols else f"{name}${i}"
            cols[key] = batch.column(sym)
        return Batch(cols, batch.num_rows)

    # ------------------------------------------------------------------
    def plan_sql(self, sql: str, optimized: bool = True) -> OutputNode:
        stmt = parse_statement(sql)
        if isinstance(stmt, A.Explain):
            stmt = stmt.statement
        if not isinstance(stmt, A.QueryStatement):
            raise QueryError("only queries can be planned")
        planner = LogicalPlanner(self.catalogs, self.session)
        plan = planner.plan(stmt)
        return optimize(plan, self.catalogs, self.session) \
            if optimized else plan

    # ------------------------------------------------------------------
    def _dispatch(self, stmt: A.Statement, sql: str = "") -> QueryResult:
        if isinstance(stmt, A.QueryStatement):
            return self._run_query(stmt,
                                   collect_stats=self.collect_node_stats)
        if isinstance(stmt, A.CreateView):
            return self._create_view(stmt, sql)
        if isinstance(stmt, A.DropView):
            cat, schema, name = self._qualify(stmt.name)
            if not self.catalogs.drop_view(cat, schema, name) \
                    and not stmt.if_exists:
                raise QueryError(
                    f"View '{cat}.{schema}.{name}' does not exist")
            return _msg_result("DROP VIEW")
        if isinstance(stmt, A.ShowCreate):
            return self._show_create(stmt)
        if isinstance(stmt, A.ShowStats):
            return self._show_stats(stmt)
        if isinstance(stmt, A.Describe):
            return self._dispatch(A.ShowColumns(stmt.table))
        if isinstance(stmt, A.Prepare):
            self.session.prepared[stmt.name] = stmt.statement
            return _msg_result("PREPARE")
        if isinstance(stmt, A.Deallocate):
            if stmt.name not in self.session.prepared:
                raise QueryError(
                    f"Prepared statement not found: {stmt.name}")
            del self.session.prepared[stmt.name]
            return _msg_result("DEALLOCATE")
        if isinstance(stmt, A.ExecuteStmt):
            return self._execute_prepared(stmt)
        if isinstance(stmt, A.DescribeInput):
            prep = self._prepared(stmt.name)
            n = A.count_parameters(prep)
            return QueryResult(["Position", "Type"], [BIGINT, VARCHAR],
                               [[i, "unknown"] for i in range(n)])
        if isinstance(stmt, A.DescribeOutput):
            prep = self._prepared(stmt.name)
            if not isinstance(prep, A.QueryStatement):
                return QueryResult(["Column Name", "Type"],
                                   [VARCHAR, VARCHAR], [])
            # bind dummy NULLs for parameters so the query plans
            n = A.count_parameters(prep)
            bound, _ = A.replace_parameters(
                prep, [A.Literal(None)] * n)
            planner = LogicalPlanner(self.catalogs, self.session)
            plan = planner.plan(bound)
            schema = plan.output_schema()
            return QueryResult(
                ["Column Name", "Type"], [VARCHAR, VARCHAR],
                [[name, str(schema[s])]
                 for name, s in zip(plan.names, plan.symbols)])
        if isinstance(stmt, A.CallStatement):
            parts = tuple(p.lower() for p in stmt.name)
            if len(parts) != 3:
                raise QueryError(
                    "CALL requires catalog.schema.procedure")
            cat, schema, proc = parts
            planner = LogicalPlanner(self.catalogs, self.session)
            args = [planner._const_expr(a).value for a in stmt.args]
            conn = self.catalogs.connector(cat)
            try:
                conn.call_procedure(schema, proc, args)
            except (KeyError, ValueError) as e:
                raise QueryError(str(e).strip('"')) from e
            return _msg_result("CALL")
        if isinstance(stmt, A.StartTransaction):
            if self._txn_snapshot is not None:
                raise QueryError("Nested transactions not supported")
            self._txn_snapshot = {
                name: self.catalogs.connector(name).snapshot_state()
                for name in self.catalogs.list_catalogs()}
            return _msg_result("START TRANSACTION")
        if isinstance(stmt, A.Commit):
            if self._txn_snapshot is None:
                raise QueryError("No transaction in progress")
            self._txn_snapshot = None
            return _msg_result("COMMIT")
        if isinstance(stmt, A.Rollback):
            if self._txn_snapshot is None:
                raise QueryError("No transaction in progress")
            for name, snap in self._txn_snapshot.items():
                if snap is not None:
                    self.catalogs.connector(name).restore_state(snap)
            self._txn_snapshot = None
            return _msg_result("ROLLBACK")
        if isinstance(stmt, A.Explain):
            return self._explain(stmt)
        if isinstance(stmt, A.UseStatement):
            if stmt.catalog:
                self.catalogs.connector(stmt.catalog)  # validate
                self.session.catalog = stmt.catalog
            self.session.schema = stmt.schema
            return _msg_result("USE")
        if isinstance(stmt, A.SetSession):
            planner = LogicalPlanner(self.catalogs, self.session)
            v = planner._const_expr(stmt.value).value
            self.session.set(stmt.name.split(".")[-1], v)
            return _msg_result("SET SESSION")
        if isinstance(stmt, A.ResetSession):
            self.session.reset(stmt.name.split(".")[-1])
            return _msg_result("RESET SESSION")
        if isinstance(stmt, A.ShowCatalogs):
            rows = [[c] for c in self.catalogs.list_catalogs()]
            return QueryResult(["Catalog"], [VARCHAR], rows)
        if isinstance(stmt, A.ShowSchemas):
            cat = stmt.catalog or self.session.catalog
            conn = self.catalogs.connector(cat)
            return QueryResult(["Schema"], [VARCHAR],
                               [[s] for s in conn.list_schemas()])
        if isinstance(stmt, A.ShowTables):
            cat = self.session.catalog
            schema = self.session.schema
            if stmt.schema:
                parts = stmt.schema
                if len(parts) == 2:
                    cat, schema = parts
                else:
                    schema = parts[0]
            conn = self.catalogs.connector(cat)
            tables = sorted(set(conn.list_tables(schema))
                            | set(self.catalogs.list_views(cat,
                                                           schema)))
            if stmt.like:
                import re
                from .exec.expr import like_to_regex
                rx = re.compile(like_to_regex(stmt.like))
                tables = [t for t in tables if rx.fullmatch(t)]
            return QueryResult(["Table"], [VARCHAR], [[t] for t in tables])
        if isinstance(stmt, A.ShowColumns):
            cat, schema, table = self._qualify(stmt.table)
            conn = self.catalogs.connector(cat)
            meta = conn.get_table_metadata(schema, table)
            if meta is None:
                raise QueryError(
                    f"Table '{cat}.{schema}.{table}' does not exist")
            rows = [[c.name, c.type.name, "", ""] for c in meta.columns]
            return QueryResult(["Column", "Type", "Extra", "Comment"],
                               [VARCHAR] * 4, rows)
        if isinstance(stmt, A.ShowSession):
            rows = [[k, str(self.session.get(k)).lower(), str(d).lower()]
                    for k, (_, d) in sorted(SESSION_PROPERTIES.items())]
            return QueryResult(["Name", "Value", "Default"],
                               [VARCHAR] * 3, rows)
        if isinstance(stmt, A.ShowFunctions):
            return QueryResult(["Function"], [VARCHAR],
                               [[f] for f in list_functions()])
        if isinstance(stmt, (A.Grant, A.Revoke, A.Deny)):
            return self._grant_revoke(stmt)
        if isinstance(stmt, A.ShowGrants):
            return self._show_grants(stmt)
        if isinstance(stmt, A.CreateTable):
            return self._create_table(stmt)
        if isinstance(stmt, A.DropTable):
            cat, schema, table = self._qualify(stmt.name)
            self._check_access("drop_table", cat, schema, table)
            conn = self.catalogs.connector(cat)
            if conn.get_table_metadata(schema, table) is None:
                if stmt.if_exists:
                    return _msg_result("DROP TABLE")
                raise QueryError(
                    f"Table '{cat}.{schema}.{table}' does not exist")
            conn.drop_table(schema, table)
            return _msg_result("DROP TABLE")
        if isinstance(stmt, A.Insert):
            return self._insert(stmt)
        if isinstance(stmt, A.Delete):
            return self._delete(stmt)
        if isinstance(stmt, A.Update):
            return self._update(stmt)
        if isinstance(stmt, A.Merge):
            return self._merge_stmt(stmt)
        raise QueryError(
            f"statement {type(stmt).__name__} not supported")

    # ------------------------------------------------------------------
    def _run_query(self, stmt: A.QueryStatement,
                   collect_stats: bool = False):
        trace = self.session.trace
        sp = (trace.span if trace is not None else null_span)
        with sp("plan"):
            planner = LogicalPlanner(self.catalogs, self.session)
            plan = planner.plan(stmt)
        with sp("optimize"):
            plan = optimize(plan, self.catalogs, self.session)
        ex = self._make_executor(collect_stats)
        with sp("execute"):
            batch = ex.execute(plan)
        schema = batch.schema()
        types = [schema[s] for s in plan.symbols]
        rows = batch.to_pylist()
        result = QueryResult(list(plan.names), types, rows)
        # the rendering /v1/query/{id} serves — captured HERE so the
        # detail endpoint never re-plans the query (and never silently
        # diverges from what actually ran)
        result.plan_lines = plan_tree_lines(plan)
        result.peak_memory_bytes = getattr(ex, "peak_reserved_bytes", 0)
        result.spill_bytes = getattr(ex, "spilled_bytes", 0)
        result.ragged_batched = getattr(ex, "ragged_batched", 0)
        if collect_stats:
            result.stats = ex.stats
            # learned operator statistics (exec/learnedstats.py): this
            # LOCAL execution's observed rows-in/rows-out feed the
            # selectivity/throughput EMAs under the plan's canonical
            # key — dispatched fragments report theirs via worker
            # task-status deltas instead, so nothing double-counts
            from .exec.learnedstats import (plan_key_for,
                                            record_node_stats)
            result.plan_key = plan_key_for(plan)
            try:
                record_node_stats(result.plan_key, ex.stats,
                                  self.session)
            except Exception:   # noqa: BLE001 — telemetry best-effort
                pass
        return result

    def _explain(self, stmt: A.Explain) -> QueryResult:
        from .exec.executor import render_analyze_lines
        inner = stmt.statement
        if not isinstance(inner, A.QueryStatement):
            raise QueryError("EXPLAIN supports queries only")
        if stmt.analyze:
            # EXPLAIN ANALYZE always traces, even on a runner whose
            # normal queries don't collect telemetry. The rendered plan
            # is the one _run_query captured — the plan that actually
            # ran, with no second plan+optimize pass
            trace = self.session.trace
            owned = trace is None
            if owned:
                from .obs.trace import QueryTrace
                trace = QueryTrace(self.session.query_id)
                self.session.trace = trace
            try:
                res = self._run_query(inner, collect_stats=True)
            finally:
                if owned:
                    self.session.trace = None
            lines = render_analyze_lines(res.plan_lines, res.stats,
                                         trace)
            return QueryResult(["Query Plan"], [VARCHAR],
                               [[l] for l in lines])
        planner = LogicalPlanner(self.catalogs, self.session)
        plan = optimize(planner.plan(inner), self.catalogs,
                        self.session)
        return QueryResult(["Query Plan"], [VARCHAR],
                           [[l] for l in plan_tree_lines(plan)])

    def _create_view(self, stmt: A.CreateView, sql: str) -> QueryResult:
        from .catalog import ViewDefinition
        cat, schema, name = self._qualify(stmt.name)
        self.catalogs.connector(cat)  # validate catalog
        # validate the definition by planning it now (reference:
        # CreateViewTask analyzes the view query)
        planner = LogicalPlanner(self.catalogs, self.session)
        planner.plan(A.QueryStatement(stmt.query))
        try:
            self.catalogs.create_view(
                cat, schema, name, ViewDefinition(stmt.query, sql),
                replace=stmt.replace)
        except KeyError as e:
            raise QueryError(str(e).strip('"')) from e
        return _msg_result("CREATE VIEW")

    def _resolve_table(self, parts) -> Tuple[str, str, str]:
        cat, schema, name = self._qualify(parts)
        conn = self.catalogs.connector(cat)
        if conn.get_table_metadata(schema, name) is None \
                and self.catalogs.get_view(cat, schema, name) is None:
            raise QueryError(
                f"Table '{cat}.{schema}.{name}' does not exist")
        return cat, schema, name

    def _grant_revoke(self, stmt) -> QueryResult:
        """GRANT / REVOKE / DENY on an engine-level grant store
        (reference: execution/{GrantTask,RevokeTask,DenyTask}.java; the
        reference routes to connector metadata, ours is engine-scoped
        so every connector supports grants)."""
        cat, schema, name = self._resolve_table(stmt.table)
        store = self.catalogs.grants
        if isinstance(stmt, A.Grant):
            for p in stmt.privileges:
                key = (stmt.grantee, p, cat, schema, name)
                store[key] = stmt.grant_option or store.get(key, False)
            return _msg_result("GRANT")
        if isinstance(stmt, A.Deny):
            for p in stmt.privileges:
                self.catalogs.denies.add(
                    (stmt.grantee, p, cat, schema, name))
            return _msg_result("DENY")
        for p in stmt.privileges:
            key = (stmt.grantee, p, cat, schema, name)
            if stmt.grant_option_for:
                if key in store:
                    store[key] = False
            else:
                store.pop(key, None)
                self.catalogs.denies.discard(key)
        return _msg_result("REVOKE")

    def _show_grants(self, stmt: "A.ShowGrants") -> QueryResult:
        """SHOW GRANTS [ON t] — information_schema.table_privileges
        shape (reference: ShowQueriesRewrite + TablePrivilegeInfo)."""
        from .types import BOOLEAN as _B
        flt = None
        if stmt.table is not None:
            flt = self._resolve_table(stmt.table)
        rows = []
        for (grantee, p, cat, schema, name), opt in sorted(
                self.catalogs.grants.items()):
            if flt is not None and (cat, schema, name) != flt:
                continue
            rows.append([self.session.user or "admin", "USER", grantee,
                         "USER", cat, schema, name, p.upper(), opt,
                         None])
        return QueryResult(
            ["Grantor", "Grantor Type", "Grantee", "Grantee Type",
             "Catalog", "Schema", "Table", "Privilege", "Grantable",
             "With Hierarchy"],
            [VARCHAR] * 8 + [_B, _B], rows)

    def _show_stats(self, stmt: "A.ShowStats") -> QueryResult:
        """SHOW STATS FOR table (reference: sql/rewrite/
        ShowStatsRewrite.java) — one row per column from the
        connector's ColumnStatistics plus the row-count summary row."""
        from .types import DOUBLE
        cat, schema, name = self._qualify(stmt.table)
        conn = self.catalogs.connector(cat)
        meta = conn.get_table_metadata(schema, name)
        if meta is None:
            raise QueryError(
                f"Table '{cat}.{schema}.{name}' does not exist")
        handle = TableHandle(cat, schema, name)
        rows_est = conn.table_row_count(handle)
        out = []
        for c in meta.columns:
            cs = conn.column_statistics(handle, c.name)
            if cs is None:
                out.append([c.name, None, None, None, None, None,
                            None])
                continue
            fmt = (lambda v: None if v is None else str(v))
            out.append([c.name, None, float(cs.ndv),
                        float(cs.null_fraction), None,
                        fmt(cs.min_value), fmt(cs.max_value)])
        out.append([None, None, None, None,
                    None if rows_est is None else float(rows_est),
                    None, None])
        return QueryResult(
            ["column_name", "data_size", "distinct_values_count",
             "nulls_fraction", "row_count", "low_value", "high_value"],
            [VARCHAR, DOUBLE, DOUBLE, DOUBLE, DOUBLE, VARCHAR,
             VARCHAR], out)

    def _show_create(self, stmt: A.ShowCreate) -> QueryResult:
        cat, schema, name = self._qualify(stmt.name)
        if stmt.kind == "view":
            view = self.catalogs.get_view(cat, schema, name)
            if view is None:
                raise QueryError(
                    f"View '{cat}.{schema}.{name}' does not exist")
            return QueryResult(["Create View"], [VARCHAR],
                               [[view.sql or f"CREATE VIEW "
                                 f"{cat}.{schema}.{name} AS ..."]])
        conn = self.catalogs.connector(cat)
        meta = conn.get_table_metadata(schema, name)
        if meta is None:
            raise QueryError(
                f"Table '{cat}.{schema}.{name}' does not exist")
        cols = ",\n   ".join(f"{c.name} {c.type}" for c in meta.columns)
        return QueryResult(
            ["Create Table"], [VARCHAR],
            [[f"CREATE TABLE {cat}.{schema}.{name} (\n   {cols}\n)"]])

    def _prepared(self, name: str):
        """Prepared statement by name; header-carried entries are SQL
        text (X-Trino-Prepared-Statement) and parse lazily."""
        prep = self.session.prepared.get(name)
        if prep is None:
            raise QueryError(f"Prepared statement not found: {name}")
        if isinstance(prep, str):
            prep = parse_statement(prep)
        return prep

    def _execute_prepared(self, stmt: A.ExecuteStmt) -> QueryResult:
        prep = self._prepared(stmt.name)
        planner = LogicalPlanner(self.catalogs, self.session)
        values = []
        for p in stmt.params:
            c = planner._const_expr(p)
            lit = A.Literal(c.value)
            values.append(lit)
        try:
            bound, used = A.replace_parameters(prep, values)
        except ValueError as e:
            raise QueryError(str(e)) from e
        if used < len(values):
            raise QueryError(
                f"statement takes {used} parameters but "
                f"{len(values)} were given")
        return self._dispatch(bound)

    def _create_table(self, stmt: A.CreateTable) -> QueryResult:
        cat, schema, table = self._qualify(stmt.name)
        self._check_access("create_table", cat, schema, table)
        conn = self.catalogs.connector(cat)
        if conn.get_table_metadata(schema, table) is not None:
            if stmt.if_not_exists:
                return _msg_result("CREATE TABLE")
            raise QueryError(
                f"Table '{cat}.{schema}.{table}' already exists")
        if stmt.query is not None:
            res = self._run_query(A.QueryStatement(stmt.query))
            cols = tuple(ColumnMetadata(n, t)
                         for n, t in zip(res.columns, res.types))
            conn.create_table(TableMetadata(schema, table, cols))
            data = {c.name: [row[i] for row in res.rows]
                    for i, c in enumerate(cols)}
            batch = batch_from_pylist(
                data, {c.name: c.type for c in cols})
            n = conn.insert(schema, table, batch)
            return _msg_result("CREATE TABLE AS", n)
        cols = tuple(ColumnMetadata(c.name.lower(), parse_type(c.type_name))
                     for c in stmt.columns)
        conn.create_table(TableMetadata(schema, table, cols))
        return _msg_result("CREATE TABLE")

    def _insert(self, stmt: A.Insert) -> QueryResult:
        cat, schema, table = self._qualify(stmt.table)
        self._check_access("insert", cat, schema, table)
        conn = self.catalogs.connector(cat)
        meta = conn.get_table_metadata(schema, table)
        if meta is None:
            raise QueryError(
                f"Table '{cat}.{schema}.{table}' does not exist")
        res = self._run_query(A.QueryStatement(stmt.query))
        target_cols = (list(stmt.columns) if stmt.columns
                       else [c.name for c in meta.columns
                             if not c.hidden])
        if len(res.columns) != len(target_cols):
            raise QueryError(
                f"INSERT has {len(res.columns)} columns but table "
                f"expects {len(target_cols)}")
        data = {}
        for tgt, i in zip(target_cols, range(len(target_cols))):
            data[tgt] = [row[i] for row in res.rows]
        schema_map = {c: meta.column_type(c) for c in target_cols}
        batch = batch_from_pylist(data, schema_map)
        n = conn.insert(schema, table, batch)
        return _msg_result("INSERT", n)

    def _delete(self, stmt: A.Delete) -> QueryResult:
        """DELETE as survivor rewrite (reference: plan/TableDeleteNode +
        connector delete; the memory connector swaps contents)."""
        cat, schema, table = self._qualify(stmt.table)
        self._check_access("delete", cat, schema, table)
        conn = self.catalogs.connector(cat)
        meta = conn.get_table_metadata(schema, table)
        if meta is None:
            raise QueryError(
                f"Table '{cat}.{schema}.{table}' does not exist")
        if not hasattr(conn, "replace"):
            raise QueryError(f"{conn.name}: DELETE not supported")
        total = conn.table_row_count(
            TableHandle(cat, schema, table)) or 0
        if stmt.where is None:
            from .columnar import empty_batch
            conn.replace(schema, table, empty_batch(
                {c.name: c.type for c in meta.columns}))
            return _msg_result("DELETE", int(total))
        # survivors: rows where the predicate is not TRUE (3VL)
        survivors = self._run_query(A.QueryStatement(A.Query(
            A.QuerySpecification(
                tuple(A.SelectItem(A.Identifier((c.name,)), c.name)
                      for c in meta.columns),
                from_=A.Table((cat, schema, table)),
                where=A.UnaryOp(
                    "not", A.FunctionCall(
                        "coalesce", (stmt.where,
                                     A.Literal(False))))))))
        data = {c.name: [row[i] for row in survivors.rows]
                for i, c in enumerate(meta.columns)}
        batch = batch_from_pylist(
            data, {c.name: c.type for c in meta.columns})
        conn.replace(schema, table, batch)
        return _msg_result("DELETE", int(total) - len(survivors.rows))

    def _writable_meta(self, cat: str, schema: str, table: str,
                       what: str):
        conn = self.catalogs.connector(cat)
        meta = conn.get_table_metadata(schema, table)
        if meta is None:
            raise QueryError(
                f"Table '{cat}.{schema}.{table}' does not exist")
        if not hasattr(conn, "replace"):
            raise QueryError(f"{conn.name}: {what} not supported")
        return conn, meta

    def _update(self, stmt: "A.Update") -> QueryResult:
        """UPDATE as whole-table rewrite: every column becomes
        CASE WHEN pred THEN cast(assignment) ELSE old END (reference:
        UpdateOperator + connector row change; the memory connector
        swaps contents like DELETE above)."""
        cat, schema, table = self._qualify(stmt.table)
        self._check_access("update", cat, schema, table)
        conn, meta = self._writable_meta(cat, schema, table, "UPDATE")
        names = {c.name for c in meta.columns}
        assigns = {}
        for col, e in stmt.assignments:
            if col.lower() not in names:
                raise QueryError(f"Column '{col}' does not exist")
            assigns[col.lower()] = e
        cond = (A.FunctionCall("coalesce",
                               (stmt.where, A.Literal(False)))
                if stmt.where is not None else A.Literal(True))
        items = []
        for c in meta.columns:
            if c.name in assigns:
                items.append(A.SelectItem(
                    A.Case(((cond, A.Cast(assigns[c.name],
                                          str(c.type))),),
                           A.Identifier((c.name,))), c.name))
            else:
                items.append(A.SelectItem(A.Identifier((c.name,)),
                                          c.name))
        items.append(A.SelectItem(cond, "__updated"))
        res = self._run_query(A.QueryStatement(A.Query(
            A.QuerySpecification(
                tuple(items), from_=A.Table((cat, schema, table))))))
        data = {c.name: [row[i] for row in res.rows]
                for i, c in enumerate(meta.columns)}
        batch = batch_from_pylist(
            data, {c.name: c.type for c in meta.columns})
        conn.replace(schema, table, batch)
        n = sum(1 for row in res.rows if row[-1])
        return _msg_result("UPDATE", n)

    def _merge_stmt(self, stmt: "A.Merge") -> QueryResult:
        """MERGE INTO target USING source ON cond WHEN ... — executed
        as engine queries (reference: the MERGE row-change plan):
        matched target rows flow through nested-CASE transforms (first
        satisfied clause wins; DELETE arms drop the row), unmatched
        source rows satisfying a NOT MATCHED arm are appended. A
        target row matching multiple source rows is not detected (the
        reference raises); the first join expansion wins."""
        cat, schema, table = self._qualify(stmt.target)
        self._check_access("update", cat, schema, table)
        conn, meta = self._writable_meta(cat, schema, table, "MERGE")
        talias = (stmt.target_alias or table).lower()
        trel: A.Relation = A.Table((cat, schema, table))
        if stmt.target_alias:
            trel = A.AliasedRelation(trel, talias, ())

        # source wrapped with a match indicator column
        ind = "__merge_m"
        src = stmt.source
        src_alias = None
        if isinstance(src, A.AliasedRelation):
            src_alias = src.alias.lower()
        elif isinstance(src, A.Table):
            src_alias = src.parts[-1].lower()
        else:
            raise QueryError("MERGE source subquery requires an alias")
        wrapped = A.AliasedRelation(
            A.SubqueryRelation(A.Query(A.QuerySpecification(
                (A.SelectItem(A.Star(), None),
                 A.SelectItem(A.Literal(1), ind)),
                from_=src))), src_alias, ())

        matched_flag = A.IsNull(A.Identifier((src_alias, ind)),
                                negated=True)

        def arm_cond(cl: "A.MergeClause") -> A.Expression:
            c: A.Expression = matched_flag if cl.matched else \
                A.IsNull(A.Identifier((src_alias, ind)))
            if cl.condition is not None:
                c = A.BinaryOp("and", c, A.FunctionCall(
                    "coalesce", (cl.condition, A.Literal(False))))
            return c

        matched_clauses = [c for c in stmt.clauses if c.matched]
        insert_clauses = [c for c in stmt.clauses if not c.matched]
        for cl in insert_clauses:
            if cl.action != "insert":
                raise QueryError(
                    "WHEN NOT MATCHED supports only INSERT")
        for cl in matched_clauses:
            if cl.action not in ("update", "delete"):
                raise QueryError(
                    "WHEN MATCHED supports only UPDATE or DELETE")

        # pass 1: target rows (kept/transformed)
        items = []
        for c in meta.columns:
            whens = []
            for cl in matched_clauses:
                if cl.action != "update":
                    continue
                assigns = {k.lower(): v for k, v in cl.assignments}
                if c.name in assigns:
                    whens.append((arm_cond(cl),
                                  A.Cast(assigns[c.name],
                                         str(c.type))))
                else:
                    whens.append((arm_cond(cl),
                                  A.Identifier((talias, c.name))))
            items.append(A.SelectItem(
                A.Case(tuple(whens), A.Identifier((talias, c.name)))
                if whens else A.Identifier((talias, c.name)), c.name))
        keep_whens = tuple(
            (arm_cond(cl), A.Literal(cl.action != "delete"))
            for cl in matched_clauses)
        fired_whens = tuple((arm_cond(cl), A.Literal(True))
                            for cl in matched_clauses)
        items.append(A.SelectItem(
            A.Case(keep_whens, A.Literal(True)), "__keep"))
        items.append(A.SelectItem(
            A.Case(fired_whens, A.Literal(False)), "__fired"))
        res = self._run_query(A.QueryStatement(A.Query(
            A.QuerySpecification(
                tuple(items),
                from_=A.Join("left", trel, wrapped, on=stmt.on)))))
        kept = [row[:-2] for row in res.rows if row[-2]]
        n_changed = sum(1 for row in res.rows if row[-1])

        # pass 2: NOT MATCHED inserts
        for cl in insert_clauses:
            cols = tuple(c.lower() for c in cl.insert_columns) or \
                tuple(c.name for c in meta.columns)
            if len(cols) != len(cl.insert_values):
                raise QueryError("MERGE INSERT arity mismatch")
            by_col = dict(zip(cols, cl.insert_values))
            ins_items = tuple(
                A.SelectItem(A.Cast(by_col[c.name], str(c.type))
                             if c.name in by_col else A.Literal(None),
                             c.name)
                for c in meta.columns)
            where: A.Expression = A.Exists(A.Query(
                A.QuerySpecification(
                    (A.SelectItem(A.Literal(1), None),),
                    from_=trel, where=stmt.on)), negated=True)
            if cl.condition is not None:
                where = A.BinaryOp("and", where, A.FunctionCall(
                    "coalesce", (cl.condition, A.Literal(False))))
            ires = self._run_query(A.QueryStatement(A.Query(
                A.QuerySpecification(ins_items, from_=src,
                                     where=where))))
            kept.extend(ires.rows)
            n_changed += len(ires.rows)

        data = {c.name: [row[i] for row in kept]
                for i, c in enumerate(meta.columns)}
        batch = batch_from_pylist(
            data, {c.name: c.type for c in meta.columns})
        conn.replace(schema, table, batch)
        return _msg_result("MERGE", n_changed)

    def _check_access(self, privilege: str, cat: str, schema: str,
                      table: str) -> None:
        ac = self.catalogs.access_control
        if ac is None:
            return
        from .security import AccessDeniedError
        try:
            getattr(ac, f"check_can_{privilege}")(
                self.session.user, cat, schema, table)
        except AccessDeniedError as e:
            raise QueryError(str(e)) from e

    def _qualify(self, parts: Tuple[str, ...]):
        parts = tuple(p.lower() for p in parts)
        if len(parts) == 3:
            return parts
        if len(parts) == 2:
            return (self.session.catalog,) + parts
        return (self.session.catalog, self.session.schema or "default",
                parts[0])


def _msg_result(update_type: str,
                count: Optional[int] = None) -> QueryResult:
    return QueryResult([], [], [], update_type=update_type,
                       update_count=count)
