"""Logical plan nodes.

Reference parity: core/trino-main/.../sql/planner/plan/ (39 concrete
PlanNode types, SURVEY.md Appendix A.1). Implemented here as frozen
dataclasses whose ``output_schema`` maps symbol -> Type. Symbols are
engine-unique strings; Batch columns at execution time are keyed by them.

Node coverage this file provides vs Appendix A.1:
TableScan, Filter, Project, Aggregation (SINGLE/PARTIAL/FINAL), Join,
SemiJoin, Sort, TopN, Limit, Offset, DistinctLimit(= Aggregation+Limit at
plan time), Values, Output, Union, Intersect, Except, EnforceSingleRow,
AssignUniqueId, MarkDistinct, Window, Exchange, RemoteSource, GroupId,
Unnest, Sample, ExplainAnalyze, TableWriter/TableFinish/Delete (DML),
Apply/CorrelatedJoin exist only transiently inside the planner
(decorrelation happens at plan time, reference: iterative/rule/
TransformCorrelated*). IndexJoin/IndexSource are intentionally dropped
(connector indexes are not part of the TPU engine's SPI).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..catalog import TableHandle
from ..rex import RowExpr
from ..types import BIGINT, BOOLEAN, Type


class PlanNode:
    __slots__ = ()

    @property
    def sources(self) -> Tuple["PlanNode", ...]:
        return ()

    def output_schema(self) -> Dict[str, Type]:
        raise NotImplementedError

    @property
    def output_symbols(self) -> List[str]:
        return list(self.output_schema())


@dataclass(frozen=True)
class TableScanNode(PlanNode):
    """sql/planner/plan/TableScanNode.java. ``assignments`` maps output
    symbol -> connector column name."""
    handle: TableHandle
    assignments: Dict[str, str]
    schema: Dict[str, Type]

    def output_schema(self):
        return dict(self.schema)


@dataclass(frozen=True)
class FilterNode(PlanNode):
    source: PlanNode
    predicate: RowExpr

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        return self.source.output_schema()


@dataclass(frozen=True)
class ProjectNode(PlanNode):
    source: PlanNode
    assignments: Dict[str, RowExpr]   # symbol -> expression

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        return {s: e.type for s, e in self.assignments.items()}

    @property
    def is_identity(self) -> bool:
        from ..rex import InputRef
        return all(isinstance(e, InputRef) and e.name == s
                   for s, e in self.assignments.items())


@dataclass(frozen=True)
class Aggregate:
    """One aggregate function instance (plan/AggregationNode.Aggregation).
    ``argument`` is an input symbol (pre-projected); None for count(*).
    ``mask`` is a boolean input symbol from FILTER (WHERE ...) or a
    MarkDistinct marker."""
    kind: str                      # sum|count|count_star|min|max|avg|any_value|...
    argument: Optional[str]
    type: Type
    distinct: bool = False
    mask: Optional[str] = None
    argument2: Optional[str] = None  # 2nd arg (min_by/corr/covar/regr)
    param: Optional[float] = None    # constant arg (approx_percentile q)


@dataclass(frozen=True)
class AggregationNode(PlanNode):
    """plan/AggregationNode.java. step: SINGLE | PARTIAL | FINAL."""
    source: PlanNode
    group_keys: Tuple[str, ...]
    aggregates: Dict[str, Aggregate]     # output symbol -> aggregate
    step: str = "SINGLE"
    group_id_symbol: Optional[str] = None   # set when fed by GroupIdNode

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        src = self.source.output_schema()
        out = {k: src[k] for k in self.group_keys}
        for s, a in self.aggregates.items():
            out[s] = a.type
        return out


@dataclass(frozen=True)
class GroupIdNode(PlanNode):
    """plan/GroupIdNode.java — replicates rows per grouping set with a
    grouping-set id column; keys absent from a set become NULL."""
    source: PlanNode
    grouping_sets: Tuple[Tuple[str, ...], ...]
    all_keys: Tuple[str, ...]
    id_symbol: str

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        out = dict(self.source.output_schema())
        out[self.id_symbol] = BIGINT
        return out


@dataclass(frozen=True)
class JoinClause:
    left: str
    right: str


@dataclass(frozen=True)
class JoinNode(PlanNode):
    """plan/JoinNode.java. join_type: inner|left|right|full|cross.
    ``criteria`` are equi-clauses; ``filter`` is the residual non-equi
    condition evaluated over combined columns."""
    left: PlanNode
    right: PlanNode
    join_type: str
    criteria: Tuple[JoinClause, ...] = ()
    filter: Optional[RowExpr] = None
    distribution: Optional[str] = None   # PARTITIONED | REPLICATED (set by optimizer)

    @property
    def sources(self):
        return (self.left, self.right)

    def output_schema(self):
        out = dict(self.left.output_schema())
        out.update(self.right.output_schema())
        return out


@dataclass(frozen=True)
class SemiJoinNode(PlanNode):
    """plan/SemiJoinNode.java — adds a boolean 'match' column."""
    source: PlanNode
    filtering_source: PlanNode
    source_key: str
    filtering_key: str
    output: str

    @property
    def sources(self):
        return (self.source, self.filtering_source)

    def output_schema(self):
        out = dict(self.source.output_schema())
        out[self.output] = BOOLEAN
        return out


@dataclass(frozen=True)
class SortKey:
    symbol: str
    ascending: bool = True
    nulls_first: bool = False


@dataclass(frozen=True)
class SortNode(PlanNode):
    source: PlanNode
    keys: Tuple[SortKey, ...]

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        return self.source.output_schema()


@dataclass(frozen=True)
class TopNNode(PlanNode):
    source: PlanNode
    count: int
    keys: Tuple[SortKey, ...]
    step: str = "SINGLE"    # SINGLE | PARTIAL | FINAL

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        return self.source.output_schema()


@dataclass(frozen=True)
class LimitNode(PlanNode):
    source: PlanNode
    count: int
    partial: bool = False

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        return self.source.output_schema()


@dataclass(frozen=True)
class OffsetNode(PlanNode):
    source: PlanNode
    count: int

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        return self.source.output_schema()


@dataclass(frozen=True)
class ValuesNode(PlanNode):
    """plan/ValuesNode.java — rows of constant expressions."""
    schema: Dict[str, Type]
    rows: Tuple[Tuple[object, ...], ...]   # python values, column order

    def output_schema(self):
        return dict(self.schema)


@dataclass(frozen=True)
class UnionNode(PlanNode):
    """plan/UnionNode.java; symbol_maps[i] maps output symbol -> source i
    symbol."""
    children: Tuple[PlanNode, ...]
    schema: Dict[str, Type]
    symbol_maps: Tuple[Dict[str, str], ...]

    @property
    def sources(self):
        return self.children

    def output_schema(self):
        return dict(self.schema)


@dataclass(frozen=True)
class SetOpNode(PlanNode):
    """IntersectNode / ExceptNode (distinct or all)."""
    op: str                   # intersect | except
    distinct: bool
    left: PlanNode
    right: PlanNode
    schema: Dict[str, Type]
    left_map: Dict[str, str]
    right_map: Dict[str, str]

    @property
    def sources(self):
        return (self.left, self.right)

    def output_schema(self):
        return dict(self.schema)


@dataclass(frozen=True)
class EnforceSingleRowNode(PlanNode):
    """plan/EnforceSingleRowNode.java — scalar subquery cardinality."""
    source: PlanNode

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        return self.source.output_schema()


@dataclass(frozen=True)
class AssignUniqueIdNode(PlanNode):
    source: PlanNode
    symbol: str

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        out = dict(self.source.output_schema())
        out[self.symbol] = BIGINT
        return out


@dataclass(frozen=True)
class MarkDistinctNode(PlanNode):
    """plan/MarkDistinctNode.java — true on first occurrence of key."""
    source: PlanNode
    marker: str
    keys: Tuple[str, ...]

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        out = dict(self.source.output_schema())
        out[self.marker] = BOOLEAN
        return out


@dataclass(frozen=True)
class WindowFunction:
    """One windowed function (plan/WindowNode.Function)."""
    kind: str                 # row_number|rank|dense_rank|sum|avg|...
    argument: Optional[str]
    type: Type
    frame_unit: str = "range"
    frame_start: str = "unbounded_preceding"
    frame_end: str = "current"
    offset: Optional[str] = None     # lag/lead offset symbol
    default: Optional[str] = None    # lag/lead default symbol
    # constant offsets for '<n> PRECEDING/FOLLOWING' frame bounds
    # (operator/window/FrameInfo.java)
    frame_start_value: Optional[int] = None
    frame_end_value: Optional[int] = None


@dataclass(frozen=True)
class WindowNode(PlanNode):
    source: PlanNode
    partition_by: Tuple[str, ...]
    order_by: Tuple[SortKey, ...]
    functions: Dict[str, WindowFunction]

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        out = dict(self.source.output_schema())
        for s, f in self.functions.items():
            out[s] = f.type
        return out


@dataclass(frozen=True)
class UnnestNode(PlanNode):
    source: PlanNode
    replicate: Tuple[str, ...]
    unnest: Dict[str, str]          # output symbol -> array-typed input
    ordinality: Optional[str] = None

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        from ..types import ArrayType
        src = self.source.output_schema()
        out = {s: src[s] for s in self.replicate}
        for o, i in self.unnest.items():
            t = src[i]
            out[o] = t.element if isinstance(t, ArrayType) else t
        if self.ordinality:
            out[self.ordinality] = BIGINT
        return out


@dataclass(frozen=True)
class SampleNode(PlanNode):
    source: PlanNode
    method: str         # bernoulli | system
    ratio: float

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        return self.source.output_schema()


@dataclass(frozen=True)
class OutputNode(PlanNode):
    """plan/OutputNode.java — final column names for the client."""
    source: PlanNode
    names: Tuple[str, ...]
    symbols: Tuple[str, ...]

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        src = self.source.output_schema()
        return {s: src[s] for s in self.symbols}


# --- distribution (M3) ----------------------------------------------------

@dataclass(frozen=True)
class ExchangeNode(PlanNode):
    """plan/ExchangeNode.java:47-57 — Type GATHER/REPARTITION/REPLICATE ×
    Scope LOCAL/REMOTE. Partitioning keys empty == round-robin/single."""
    source: PlanNode
    kind: str                       # gather | repartition | replicate
    scope: str = "remote"
    partition_keys: Tuple[str, ...] = ()

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        return self.source.output_schema()


@dataclass(frozen=True)
class RemoteSourceNode(PlanNode):
    """plan/RemoteSourceNode.java — reads a fragment's exchange output.

    In the stage-DAG path (trino_tpu/stage/) ``fragment_ids`` name the
    upstream STAGES whose partitioned output this node consumes: a task
    executing this node pulls its own partition index from every task
    of each named stage (exec/executor.py ``_exec_RemoteSourceNode``
    through the stage exchange puller)."""
    fragment_ids: Tuple[int, ...]
    schema: Dict[str, Type]
    kind: str = "repartition"

    def output_schema(self):
        return dict(self.schema)


@dataclass(frozen=True)
class PartitionedOutputNode(PlanNode):
    """The producing half of a stage boundary (reference:
    sql/planner/plan/ExchangeNode partitioning scheme +
    operator/output/PartitionedOutputOperator.java). A stage whose plan
    is rooted here hash-partitions its output rows across the consumer
    stage's tasks by ``partition_keys`` (kind="hash"); kind="gather"
    emits a single partition for a single consumer (the root stage or a
    1-task FINAL aggregation); kind="replicate" emits a single
    partition that EVERY consumer task reads whole (the REPLICATE
    exchange: broadcast join build sides, semi-join filtering
    sources). The partition COUNT is not part of the
    plan — the stage scheduler fixes it at dispatch time (the consumer
    stage's task count), exactly like the reference's bucket-count
    decision living in scheduling, not in the fragment."""
    source: PlanNode
    partition_keys: Tuple[str, ...] = ()
    kind: str = "hash"              # hash | gather | replicate

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        return self.source.output_schema()


# --- DML ------------------------------------------------------------------

@dataclass(frozen=True)
class TableWriterNode(PlanNode):
    """plan/TableWriterNode.java — writes source rows to a target table."""
    source: PlanNode
    target: TableHandle
    column_names: Tuple[str, ...]
    symbols: Tuple[str, ...]
    rows_symbol: str = "rows"

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        return {self.rows_symbol: BIGINT}


@dataclass(frozen=True)
class TableDeleteNode(PlanNode):
    """plan/TableDeleteNode.java — whole-table / filtered delete."""
    target: TableHandle
    predicate: Optional[RowExpr]
    rows_symbol: str = "rows"

    def output_schema(self):
        return {self.rows_symbol: BIGINT}


@dataclass(frozen=True)
class ExplainAnalyzeNode(PlanNode):
    source: PlanNode
    symbol: str

    @property
    def sources(self):
        return (self.source,)

    def output_schema(self):
        from ..types import VARCHAR
        return {self.symbol: VARCHAR}


def plan_tree_lines(node: PlanNode, indent: int = 0) -> List[str]:
    """Text rendering (reference: sql/planner/planprinter/PlanPrinter)."""
    pad = "   " * indent
    name = type(node).__name__.replace("Node", "")
    detail = ""
    if isinstance(node, TableScanNode):
        extras = ""
        if getattr(node.handle, "constraint", None) is not None:
            extras += f" constraint=({node.handle.constraint})"
        if getattr(node.handle, "limit", None) is not None:
            extras += f" limit={node.handle.limit}"
        detail = (f"[{node.handle.catalog}.{node.handle.schema}."
                  f"{node.handle.table}{extras}]")
    elif isinstance(node, FilterNode):
        detail = f"[{node.predicate}]"
    elif isinstance(node, ProjectNode):
        detail = "[" + ", ".join(
            f"{s} := {e}" for s, e in node.assignments.items()) + "]"
    elif isinstance(node, AggregationNode):
        aggs = ", ".join(f"{s} := {a.kind}({a.argument or '*'})"
                         for s, a in node.aggregates.items())
        detail = f"[{node.step} by({', '.join(node.group_keys)}) {aggs}]"
    elif isinstance(node, JoinNode):
        crit = " AND ".join(f"{c.left} = {c.right}" for c in node.criteria)
        detail = f"[{node.join_type} {crit}]"
    elif isinstance(node, (TopNNode,)):
        detail = f"[{node.count} by {[k.symbol for k in node.keys]}]"
    elif isinstance(node, LimitNode):
        detail = f"[{node.count}]"
    elif isinstance(node, ExchangeNode):
        detail = f"[{node.kind}/{node.scope} by {list(node.partition_keys)}]"
    elif isinstance(node, PartitionedOutputNode):
        detail = f"[{node.kind} by {list(node.partition_keys)}]"
    elif isinstance(node, RemoteSourceNode):
        detail = f"[stages {list(node.fragment_ids)}]"
    elif isinstance(node, OutputNode):
        detail = f"[{', '.join(node.names)}]"
    lines = [f"{pad}- {name}{detail}"]
    for s in node.sources:
        lines.extend(plan_tree_lines(s, indent + 1))
    return lines
