from .nodes import *  # noqa: F401,F403
