"""Plan-fragment wire format: plan/rex/predicate dataclasses <-> JSON.

Reference parity: the coordinator ships PlanFragments to workers as
JSON (server/remotetask/HttpRemoteTask.java:103 posting a
TaskUpdateRequest whose fragment is Jackson-serialized
sql/planner/PlanFragment.java). Here the engine's plan IR is frozen
dataclasses, so one generic tagged walker covers every node/expression/
domain class — no per-class codecs to drift out of sync.

Encoding:
  dataclass        -> {"$c": "ClassName", "f": {field: enc, ...}}
  Type             -> {"$t": "<type name>"}   (parse_type round-trip)
  dict             -> {"$m": {key: enc}}      (plan dicts are str-keyed)
  tuple            -> {"$u": [enc, ...]}
  Decimal          -> {"$dec": "..."}
  int/float/str/bool/None/list -> native JSON
"""

from __future__ import annotations

import dataclasses
from decimal import Decimal
from typing import Any, Dict

from ..types import Type, parse_type


def _registry() -> Dict[str, type]:
    from .. import catalog, predicate, rex
    from ..planner import logical
    from . import nodes
    # planner.logical contributes the plan nodes born inside the
    # planner (SemiJoinMultiNode) — without it a plan carrying one
    # encodes fine but cannot decode, which the sanity checker's serde
    # round-trip validator (analysis/sanity.py) treats as a broken
    # fragment
    reg: Dict[str, type] = {}
    for mod in (nodes, rex, predicate, catalog, logical):
        for name in dir(mod):
            cls = getattr(mod, name)
            if isinstance(cls, type) and dataclasses.is_dataclass(cls):
                reg[name] = cls
    return reg


_REG: Dict[str, type] = {}


def to_jsonable(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        return obj
    if isinstance(obj, Type):
        return {"$t": str(obj.name)}
    if isinstance(obj, Decimal):
        return {"$dec": str(obj)}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {"$c": type(obj).__name__,
                "f": {f.name: to_jsonable(getattr(obj, f.name))
                      for f in dataclasses.fields(obj)}}
    if isinstance(obj, dict):
        return {"$m": {str(k): to_jsonable(v) for k, v in obj.items()}}
    if isinstance(obj, tuple):
        return {"$u": [to_jsonable(v) for v in obj]}
    if isinstance(obj, list):
        return [to_jsonable(v) for v in obj]
    # numpy scalars from the planner's constant folding
    try:
        import numpy as np
        if isinstance(obj, np.integer):
            return int(obj)
        if isinstance(obj, np.floating):
            return float(obj)
        if isinstance(obj, np.bool_):
            return bool(obj)
    except ImportError:      # pragma: no cover
        pass
    raise TypeError(
        f"plan serde: unsupported value {type(obj).__name__}: {obj!r}")


def from_jsonable(obj: Any) -> Any:
    global _REG
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, list):
        return [from_jsonable(v) for v in obj]
    if "$t" in obj:
        return parse_type(obj["$t"])
    if "$dec" in obj:
        return Decimal(obj["$dec"])
    if "$m" in obj:
        return {k: from_jsonable(v) for k, v in obj["$m"].items()}
    if "$u" in obj:
        return tuple(from_jsonable(v) for v in obj["$u"])
    if "$c" in obj:
        if not _REG:
            _REG = _registry()
        cls = _REG.get(obj["$c"])
        if cls is None:
            raise TypeError(f"plan serde: unknown class {obj['$c']}")
        return cls(**{k: from_jsonable(v)
                      for k, v in obj["f"].items()})
    raise TypeError(f"plan serde: unrecognized object {obj!r}")
