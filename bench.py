"""Benchmark: TPC-H q1 engine throughput, rows/sec/chip, vs 1 CPU worker.

Two legs per backend (the reference's HandTpchQuery1.java micro vs the
full-engine operator path):
  engine — SQL TPC-H q1 @ sf1 through the FULL path
           (parse -> plan -> optimize -> execute), BASELINE.json configs[1]
  micro  — the hand-fused jitted q1 stage program over raw sf1 lanes

Harness contract (round-4 postmortem: rc=124, nothing printed — the old
harness ran up to 6 subprocesses x 1200s each):
  * HARD overall wall-clock budget: env BENCH_BUDGET, default 540s.
    Every subprocess timeout derives from the remaining budget; a
    SIGALRM net guarantees the JSON line prints even if bookkeeping is
    wrong.
  * ONE device subprocess runs BOTH device legs (backend init through
    the axon tunnel is the dominant fixed cost — pay it once), then ONE
    CPU subprocess runs both baseline legs. Probes print each leg's
    result as its own JSON line the moment the leg finishes, so a
    timeout mid-probe still yields the completed legs (TimeoutExpired
    carries the captured stdout).
  * sf1 q1 lanes are generated once and cached as npz under
    ~/.cache/trino_tpu/ (generate: ~7s, load: ~0.3s on this 1-core host).
  * CPU micro baseline runs on a 10% row sample (rows/sec normalizes);
    CPU engine runs sf1 (measured ~3s/iteration — affordable).

  * The device side runs as SUB-PROBES — device_init (backend contact
    only), device_first_compile (pays the q1 compile, populating the
    persistent XLA cache), device_steady (engine/micro/telemetry over
    the warm cache), device_q18 (streamed q18 at scale) — each its own
    subprocess under its OWN cap, each checkpointed to
    ~/.cache/trino_tpu/bench_subprobes.json the moment it lands. A
    rerun of a timed-out round resumes past completed sub-probes; one
    sub-probe's blowout zeroes ONLY its own keys (round-5 verdict: a
    single 360s device hang zeroed every device number).
  * Every probe subprocess shares one TRINO_TPU_XLA_CACHE_DIR, so the
    first_compile sub-probe's XLA artifacts carry into device_steady
    (a different process) and into later ROUNDS: warm numbers measure
    the cache, not a lucky process lifetime.
  * BENCH_FORCE_SUBPROBE_TIMEOUT=<name[,name]> caps the named
    sub-probes at ~1s — the resumability/blowout drill.

Whatever happens, exactly ONE final JSON line is printed:
{"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

BUDGET = float(os.environ.get("BENCH_BUDGET", "540"))
_T0 = time.monotonic()
CACHE_DIR = os.path.expanduser(os.environ.get(
    "TRINO_TPU_BENCH_CACHE", "~/.cache/trino_tpu"))
# ONE persistent-XLA-cache dir for every probe subprocess of every
# round (config.py honors the exact path, no machine-tag suffix):
# cross-process AND cross-round compile reuse
XLA_CACHE_DIR = os.environ.get("TRINO_TPU_XLA_CACHE_DIR") \
    or os.path.join(CACHE_DIR, "xla_cache")


def _remaining() -> float:
    return BUDGET - (time.monotonic() - _T0)


# --------------------------------------------------------------------------
# sub-probe checkpoint: a timed-out/crashed round resumes where it died
# --------------------------------------------------------------------------

_CKPT_PATH = os.path.join(CACHE_DIR, "bench_subprobes.json")
_CKPT_TTL = float(os.environ.get("BENCH_CHECKPOINT_TTL", "7200"))
_ROUND_ID = os.environ.get("BENCH_ROUND_ID", "")


def _ckpt_load() -> dict:
    """Completed sub-probes of THIS round (same BENCH_ROUND_ID, within
    TTL) — anything else is a different round's history, ignored."""
    try:
        with open(_CKPT_PATH) as f:
            d = json.load(f)
        if d.get("round") != _ROUND_ID:
            return {}
        if time.time() - float(d.get("ts", 0.0)) > _CKPT_TTL:
            return {}
        return dict(d.get("subprobes", {}))
    except Exception:
        return {}


def _ckpt_save(subprobes: dict) -> None:
    try:
        os.makedirs(CACHE_DIR, exist_ok=True)
        tmp = _CKPT_PATH + f".{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"round": _ROUND_ID, "ts": time.time(),
                       "subprobes": subprobes}, f)
        os.replace(tmp, _CKPT_PATH)
    except Exception:
        pass


# --------------------------------------------------------------------------
# data: sf1 q1 lanes, generated once, npz-cached across probes/rounds
# --------------------------------------------------------------------------

def _gen_q1_columns(sf: float):
    """q1's 7 lineitem lanes straight from the generator's vectorized
    field functions (no host string materialization)."""
    from trino_tpu.connectors.tpch import (_LineFields, _line_counts,
                                           CURRENTDATE, table_rows)
    orders = table_rows("orders", sf)
    order_idx = np.arange(1, orders + 1, dtype=np.int64)
    counts = _line_counts(order_idx)
    order_rep = np.repeat(order_idx, counts)
    line_no = np.concatenate([np.arange(1, c + 1) for c in counts])
    lf = _LineFields(order_rep, line_no.astype(np.int64), sf)
    returned = lf.receiptdate <= CURRENTDATE
    from trino_tpu.connectors.tpch import _u64, _SEED
    ra = (_u64(_SEED["lineitem"] + 20, lf.rid) % np.uint64(2)).astype(
        np.int64)
    rflag = np.where(returned, ra, 2).astype(np.int32)
    lstatus = (lf.shipdate > CURRENTDATE).astype(np.int32)
    return (lf.quantity, lf.extendedprice, lf.discount, lf.tax,
            lf.shipdate.astype(np.int32), rflag, lstatus)


def _q1_columns_cached(sf: float):
    tag = str(sf).replace(".", "_")
    path = os.path.join(CACHE_DIR, f"bench_q1_sf{tag}.npz")
    if os.path.exists(path):
        try:
            d = np.load(path)
            return [d[f"c{i}"] for i in range(7)]
        except Exception:
            pass
    cols = _gen_q1_columns(sf)
    try:
        os.makedirs(CACHE_DIR, exist_ok=True)
        # np.savez appends .npz when missing — name the temp file with
        # the suffix or os.replace never finds it
        tmp = path + f".{os.getpid()}.tmp.npz"
        np.savez(tmp, **{f"c{i}": c for i, c in enumerate(cols)})
        os.replace(tmp, path)
    except Exception:
        pass
    return cols


# --------------------------------------------------------------------------
# probe legs (run inside the probe subprocess)
# --------------------------------------------------------------------------

def _cold_warm(run_once, iters: int):
    """(cold wall, best warm wall) of ``run_once``: the first call pays
    trace + XLA compile (or proves the persistent cache absorbed
    them), the best of ``iters`` repeats is steady state. Splitting
    the two is the whole point of the compile-amortization work —
    every leg reports both."""
    t0 = time.perf_counter()
    run_once()
    cold = time.perf_counter() - t0
    best = float("inf")
    for _ in range(max(iters, 1)):
        t0 = time.perf_counter()
        run_once()
        best = min(best, time.perf_counter() - t0)
    return cold, best


def _cw_keys(cold: float, warm: float) -> dict:
    """The per-leg compile/warm scoreboard keys: compile_s is the
    cold-minus-warm wall (trace + XLA compile + cache population),
    warm_speedup the cold/warm ratio (ROADMAP item 1's success
    metric: how much the second run gains)."""
    return {"cold_s": round(cold, 4), "warm_s": round(warm, 4),
            "compile_s": round(max(cold - warm, 0.0), 4),
            "warm_speedup": round(cold / warm, 2) if warm > 0 else 0.0}


def _leg_micro(sf: float, iters: int) -> dict:
    """rows/sec of the jitted q1 stage program on this backend."""
    import jax
    import jax.numpy as jnp
    import trino_tpu  # noqa: F401  (x64)
    from __graft_entry__ import _q1_step

    cols = _q1_columns_cached(sf)
    rows = len(cols[0])
    cap = 1
    while cap < rows:
        cap <<= 1
    padded = [np.pad(c, (0, cap - rows)) for c in cols]
    dev = [jax.device_put(jnp.asarray(c)) for c in padded]
    n = jnp.asarray(rows, jnp.int64)

    def fetch(out, ng):
        # the timed unit ends with results ON HOST: under the axon
        # tunnel block_until_ready can return before execution completes
        # (measured round 1), so a real host readback is the only honest
        # fence
        return {k: np.asarray(v) for k, v in out.items()}, int(ng)

    step = jax.jit(_q1_step)
    cold, best = _cold_warm(lambda: fetch(*step(*dev, n)), iters)
    return dict({"rows_per_sec": rows / best}, **_cw_keys(cold, best))


def _leg_engine(schema: str, iters: int) -> dict:
    """rows/sec of SQL TPC-H q1 through the FULL engine path."""
    import trino_tpu  # noqa: F401
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    from trino_tpu.runner import LocalQueryRunner
    from trino_tpu.session import Session

    r = LocalQueryRunner(session=Session(catalog="tpch", schema=schema))
    rows = int(r.execute("SELECT count(*) FROM lineitem").rows[0][0])

    def once():
        res = r.execute(TPCH_QUERIES[1])
        assert len(res.rows) >= 4

    cold, best = _cold_warm(once, iters)
    return dict({"rows_per_sec": rows / best}, **_cw_keys(cold, best))


def _leg_warm(schema: str) -> dict:
    """The explicit cold-vs-warm leg: the SAME query through two FRESH
    LocalQueryRunners (fresh planner, fresh Executor per run). The
    second runner's first execution rides the canonical-key structural
    caches (exec/progkey.py) — its "cold" wall is what a repeated
    query costs after the compile tax is paid once, and warm_speedup
    = runner1-cold / runner2-first is the amortization factor the
    whole subsystem exists to maximize.

    Runs FIRST in the probe (before the engine leg, which executes the
    same query): the cold wall must genuinely pay the q1 compile, not
    ride programs an earlier leg cached. Data generation is hoisted
    out of the timed walls through a query whose programs DON'T
    overlap q1's (count(*) — different canonical keys), and
    fragment-jit is forced on for the leg's runners so the CPU probe
    measures the same amortization machinery the device path uses."""
    import trino_tpu  # noqa: F401
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    from trino_tpu.runner import LocalQueryRunner
    from trino_tpu.session import Session

    def once():
        r = LocalQueryRunner(
            session=Session(catalog="tpch", schema=schema))
        res = r.execute(TPCH_QUERIES[1])
        assert len(res.rows) >= 4

    prev = os.environ.get("TRINO_TPU_FRAGMENT_JIT")
    os.environ["TRINO_TPU_FRAGMENT_JIT"] = "1"
    try:
        # generate the tables without compiling any q1 program
        LocalQueryRunner(
            session=Session(catalog="tpch", schema=schema)).execute(
                "SELECT count(*) FROM lineitem")
        cold, warm = _cold_warm(once, 1)
    finally:
        if prev is None:
            os.environ.pop("TRINO_TPU_FRAGMENT_JIT", None)
        else:
            os.environ["TRINO_TPU_FRAGMENT_JIT"] = prev
    return dict({"fresh_runner": True}, **_cw_keys(cold, warm))


def _leg_q18(schema: str) -> dict:
    """rows/sec of TPC-H q18 (BASELINE configs[3] shape: large
    build-side join + IN-subquery semi-join) through the full engine,
    under a per-node memory budget deliberately SMALLER than the q18
    probe working set — the beyond-HBM morsel-streaming path
    (exec/streamjoin.py) engages every round: probe chunks stream
    through double-buffered host->device transfers instead of the
    query dying on the materialization estimate. The budget covers
    the orders build state plus 64MB of chunk room — far below the
    lineitem probe estimate, so the build materializes and the probe
    streams; BENCH_Q18_BUDGET_BYTES overrides."""
    import trino_tpu  # noqa: F401
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    from trino_tpu.config import capacity_for
    from trino_tpu.connectors.tpch import SCHEMAS, table_rows
    from trino_tpu.obs.metrics import METRICS
    from trino_tpu.runner import LocalQueryRunner
    from trino_tpu.session import Session

    n_orders = table_rows("orders", SCHEMAS[schema])
    rows = n_orders * 4                 # ~lineitem rows
    # budget = the orders build state (4 lanes + sorted hash-table
    # lanes at its capacity bucket) + 64MB of chunk room — well below
    # the ~16B/row lineitem probe estimate, so the probe streams
    budget = int(os.environ.get("BENCH_Q18_BUDGET_BYTES",
                                capacity_for(n_orders) * 48
                                + (64 << 20)))
    session = Session(catalog="tpch", schema=schema)
    session.set("query_max_memory_per_node", budget)
    r = LocalQueryRunner(session=session)

    # hoist the bulk of data generation out of the timed walls (scale
    # probes run in a fresh subprocess — untimed, cold_s would report
    # sf10 table generation as compile tax). Column generation is
    # lazy, so a residual sliver can still land in cold_s; datagen_s
    # makes the split auditable in the artifact.
    t0 = time.perf_counter()
    for t in ("lineitem", "orders", "customer"):
        r.execute(f"SELECT count(*) FROM {t}")
    datagen_s = time.perf_counter() - t0

    def once():
        res = r.execute(TPCH_QUERIES[18])
        # tiny legitimately has zero orders over the HAVING>300 bar
        assert len(res.rows) > 0 or schema == "tiny"

    chunks = METRICS.counter("trino_tpu_stream_chunks_total")
    h2d = METRICS.counter("trino_tpu_stream_bytes_h2d_total")
    over = METRICS.counter(
        "trino_tpu_stream_transfers_overlapped_total")

    def stream_totals():
        return (sum(v for _, v in chunks.samples()),
                h2d.value(), over.value())

    c0, b0, o0 = stream_totals()
    cold, warm = _cold_warm(once, 1)
    c1, b1, o1 = stream_totals()
    nruns = 2                       # cold + 1 timed repeat
    dc = max(c1 - c0, 0.0)
    return dict({"rows_per_sec": rows / warm,
                 "datagen_s": round(datagen_s, 2),
                 "budget_bytes": budget,
                 "stream_chunks": round(dc / nruns, 1),
                 "stream_h2d_bytes": round((b1 - b0) / nruns, 1),
                 "stream_overlap_ratio":
                     round((o1 - o0) / dc, 4) if dc else 0.0},
                **_cw_keys(cold, warm))


def _leg_telemetry(schema: str, iters: int) -> dict:
    """Fractional overhead of telemetry on the DEFAULT (multistage
    MPP) distributed path: TPC-H q1 through two in-process workers
    with collect_node_stats OFF vs ON — ON meaning the full PR 15
    stack (distributed tracing with traceparent propagation and
    id-preserving span merge, device/CPU attribution, OTLP file
    export) PLUS the PR 19 ride-alongs: learned operator statistics
    (worker ``learnedStats`` deltas merged at the scheduler,
    exec/learnedstats.py) and a query-history record append per run
    (obs/history.py). The always-on OperatorStats question — this
    ratio is what decides whether telemetry can default on; target
    < 0.05 (tests/test_observability.py). ``overhead`` is a fraction
    (0.03 = 3% slower); the compile/warm split rides along from the
    telemetry-off run. Each bench round also appends its own summary
    record to the DEFAULT history store, so the perf trajectory
    itself is queryable via system.runtime.queries."""
    import tempfile

    import trino_tpu  # noqa: F401
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    from trino_tpu.config import CONFIG
    from trino_tpu.exec.learnedstats import LEARNED_STATS
    from trino_tpu.exec.remote import DistributedHostQueryRunner
    from trino_tpu.obs.history import QueryHistoryStore, sql_digest
    from trino_tpu.server.task_worker import TaskWorkerServer
    from trino_tpu.session import Session

    workers = [TaskWorkerServer().start() for _ in range(2)]
    uris = [w.base_uri for w in workers]
    sink = os.path.join(tempfile.mkdtemp(prefix="bench_otlp_"),
                        "traces.jsonl")
    hist = QueryHistoryStore(os.path.join(
        CONFIG.spool_dir, "history", "queries.jsonl"))
    old_file = CONFIG.otlp_file
    lstats0 = len(LEARNED_STATS)
    plan_key = ""
    try:
        def cold_best(collect: bool):
            # OTLP export + history append ride ONLY the telemetry-on
            # side: the overhead number prices tracing + attribution +
            # export + history + learned stats together, against a
            # genuinely dark baseline
            CONFIG.otlp_file = sink if collect else ""
            r = DistributedHostQueryRunner(
                uris, session=Session(catalog="tpch", schema=schema),
                collect_node_stats=collect)

            def once():
                res = r.execute(TPCH_QUERIES[1])
                if collect:
                    nonlocal plan_key
                    plan_key = getattr(res, "plan_key", "") or plan_key
                    hist.record({
                        "query_id": "bench_telemetry_"
                                    + time.strftime("%Y%m%d_%H%M%S"),
                        "state": "FINISHED", "user": "bench",
                        "source": "bench", "sql": TPCH_QUERIES[1][:512],
                        "sql_digest": sql_digest(TPCH_QUERIES[1]),
                        "plan_key": plan_key,
                        "wall_s": 0.0, "rows": len(res.rows),
                        "cpu_s": getattr(res, "cpu_seconds", 0.0),
                        "created": time.time()})

            return _cold_warm(once, iters)

        off_cold, off = cold_best(False)
        _, on = cold_best(True)
        try:
            with open(sink) as f:
                exports = sum(1 for _ in f)
        except OSError:
            exports = 0
    finally:
        CONFIG.otlp_file = old_file
        for w in workers:
            w.stop()
    # the leg's own verdict record: one summary per bench round, the
    # overhead trajectory queryable as source='bench' history rows
    hist.record({
        "query_id": "bench_round_" + time.strftime("%Y%m%d_%H%M%S"),
        "state": "FINISHED", "user": "bench", "source": "bench",
        "sql": "-- bench telemetry leg summary",
        "sql_digest": sql_digest("-- bench telemetry leg summary"),
        "plan_key": plan_key, "wall_s": on, "created": time.time(),
        "bench_overhead": max(on / off - 1.0, 0.0)})
    return dict({"overhead": max(on / off - 1.0, 0.0),
                 "otlp_exports": exports,
                 "learned_entries": len(LEARNED_STATS) - lstats0,
                 "history_records": len(hist)},
                **_cw_keys(off_cold, off))


def _fault_failover_subleg() -> dict:
    """Coordinator-failover resume mini-leg: a 3-stage distributed
    query whose coordinator dies at the ``coordinator.post_stage_commit``
    fault site (fte/faultpoints.py) right after the first stage's
    partitions commit; a replacement coordinator binds the SAME port,
    reloads the spooled execution manifest, re-reads the committed
    partitions off the spool and re-dispatches only the rest. Reports
    the wall seconds from coordinator death to the client seeing
    FINISHED (through the ordinary nextUri chain — the client's
    bounded poll retry rides out the outage) plus the resumed/replayed
    partition split."""
    import threading
    import time as _time

    from trino_tpu.client import StatementClient
    from trino_tpu.fte import faultpoints
    from trino_tpu.obs.metrics import FAILOVER_PARTITIONS
    from trino_tpu.server.coordinator import Coordinator
    from trino_tpu.server.task_worker import TaskWorkerServer

    sql = ("SELECT n_name, count(*) FROM nation "
           "JOIN region ON n_regionkey = r_regionkey "
           "GROUP BY n_name ORDER BY n_name")
    workers = [TaskWorkerServer().start() for _ in range(2)]
    uris = [w.base_uri for w in workers]
    co1 = Coordinator(worker_uris=uris).start()
    died = {}
    replacement = {}

    def kill(site):
        # in-process stand-in for SIGKILL at the fault site: the HTTP
        # server goes away and SystemExit (not an Exception — q.run
        # cannot catch it) freezes the query thread mid-flight
        died["t"] = _time.perf_counter()
        co1.tracker.manifests = None
        co1.tracker.results = None
        co1._httpd.shutdown()
        co1._httpd.server_close()
        died["closed"] = True
        raise SystemExit

    def boot_replacement():
        while "closed" not in died:
            _time.sleep(0.005)
        for _ in range(100):    # the dying server's port may linger
            try:
                replacement["co"] = Coordinator(
                    port=co1.port, worker_uris=uris).start()
                return
            except OSError:
                _time.sleep(0.02)

    r0 = FAILOVER_PARTITIONS.value(outcome="resumed")
    p0 = FAILOVER_PARTITIONS.value(outcome="replayed")
    faultpoints.reset()
    faultpoints.install("coordinator.post_stage_commit", callback=kill)
    try:
        threading.Thread(target=boot_replacement, daemon=True).start()
        client = StatementClient(
            co1.base_uri, session_properties={
                "retry_policy": "TASK",
                "retry_initial_delay_ms": "10",
                "remote_task_timeout": "30"})
        res = client.execute(sql)
        wall = _time.perf_counter() - died["t"]
        if res.state != "FINISHED" or "t" not in died:
            return {}
        return {
            "coordinator_failover_resume_s": wall,
            "failover_parts_resumed":
                FAILOVER_PARTITIONS.value(outcome="resumed") - r0,
            "failover_parts_replayed":
                FAILOVER_PARTITIONS.value(outcome="replayed") - p0,
        }
    finally:
        faultpoints.reset()
        co = replacement.get("co")
        if co is not None:
            co.stop()
        for w in workers:
            w.stop()


def _leg_fault(iters: int) -> dict:
    """Fault-tolerant execution recovery overhead: the SAME distributed
    query through two in-process workers, 0 vs 1 injected worker
    failure (a stub that 500s every results pull), retry_policy=TASK.
    The fractional slowdown is the price of a mid-query worker death;
    the dict also carries the scrape-side artifacts (task-retry counter
    + per-query peak-memory gauge) so the leg proves /metrics exposes
    them, and the coordinator-failover mini-leg's resume timing +
    partition split ride along."""
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    import trino_tpu  # noqa: F401
    from trino_tpu.exec.remote import DistributedHostQueryRunner
    from trino_tpu.obs.metrics import METRICS
    from trino_tpu.server.task_worker import TaskWorkerServer
    from trino_tpu.session import Session

    sql = ("SELECT l_returnflag, l_linestatus, sum(l_quantity), "
           "count(*) FROM lineitem GROUP BY l_returnflag, "
           "l_linestatus ORDER BY l_returnflag, l_linestatus")

    class _DeadHandler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            body = b'{"taskId": "x", "state": "RUNNING"}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            self.send_error(500, "injected worker failure")

        def do_DELETE(self):
            self.send_response(204)
            self.send_header("Content-Length", "0")
            self.end_headers()

    # 3 workers in BOTH runs so nparts (and the per-worker split
    # share) is identical — the fault run swaps one good worker for
    # the dead stub, isolating recovery cost from fan-out changes
    workers = [TaskWorkerServer().start() for _ in range(3)]
    dead = ThreadingHTTPServer(("127.0.0.1", 0), _DeadHandler)
    threading.Thread(target=dead.serve_forever, daemon=True).start()
    dead_uri = f"http://127.0.0.1:{dead.server_address[1]}"

    def make_session():
        s = Session(catalog="tpch", schema="tiny")
        s.set("retry_policy", "TASK")
        s.set("retry_initial_delay_ms", 10)
        return s

    def best_of(uris):
        # collect_node_stats so workers report peakMemoryBytes and the
        # per-query gauge this leg advertises carries a real value
        r = DistributedHostQueryRunner(uris, session=make_session(),
                                       collect_node_stats=True)
        return _cold_warm(lambda: r.execute(sql), iters)

    try:
        good = [w.base_uri for w in workers]
        cold_ok, t_ok = best_of(good)
        _, t_fault = best_of([dead_uri] + good[:2])
    finally:
        dead.shutdown()
        for w in workers:
            w.stop()
    try:
        failover = _fault_failover_subleg()
    except Exception:           # noqa: BLE001 — the mini-leg is a
        failover = {}           # ride-along, never the leg's verdict
    return dict({
        "overhead": max(t_fault / t_ok - 1.0, 0.0),
        "task_retries_total":
            METRICS.counter("trino_tpu_task_retries_total").value(),
        "query_peak_memory_bytes":
            METRICS.gauge("trino_tpu_query_peak_memory_bytes").value(),
    }, **failover, **_cw_keys(cold_ok, t_ok))


def _mpp_ici_subleg(sql: str, nrows: int) -> dict:
    """ICI-native exchange mini-leg: the SAME stage DAG, executed on a
    4-virtual-device mesh with the hash repartition lowered to
    jax.lax.all_to_all (stage/ici.py) instead of spool+HTTP frames.
    Runs in a grandchild process because the virtual-device XLA flag
    must be set before jax imports (and must not perturb the other
    legs' single-device baseline)."""
    code = (
        "import json, os, time\n"
        "from trino_tpu.runner import LocalQueryRunner\n"
        "from trino_tpu.obs.metrics import METRICS\n"
        "sql = os.environ['BENCH_MPP_SQL']\n"
        "r = LocalQueryRunner(distributed=True, n_devices=4)\n"
        "r.execute(sql)\n"
        "b = METRICS.counter('trino_tpu_exchange_ici_bytes_total')\n"
        "b0 = sum(v for _, v in b.samples())\n"
        "t0 = time.perf_counter(); r.execute(sql)\n"
        "wall = time.perf_counter() - t0\n"
        "moved = sum(v for _, v in b.samples()) - b0\n"
        "print(json.dumps({'wall_s': wall, 'ici_bytes': moved}))\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=4"
                        ).strip()
    # ONE timed iteration after the warm-up: the mesh path re-traces
    # its shard_map programs per query (known spmd cost), so extra
    # iterations buy accuracy at ~1 re-compile each — the CPU probe's
    # budget is better spent on the worker legs
    env["BENCH_MPP_SQL"] = sql
    budget = min(max(_remaining() * 0.5, 30.0), 150.0)
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=budget, env=env)
        d = json.loads((p.stdout or "").strip().splitlines()[-1])
        return {"ici_rows_per_sec": nrows / max(d["wall_s"], 1e-9),
                "exchange_ici_bytes": float(d["ici_bytes"])}
    except Exception as e:      # noqa: BLE001 — the split stays a
        # reported 0, never a lost worker-leg result
        return {"exchange_ici_bytes": 0.0,
                "ici_error": f"{type(e).__name__}: {e}"[:160]}


def _leg_mpp(iters: int) -> dict:
    """Multi-stage MPP leg: a distributed hash-join + final-aggregation
    query through the stage-DAG scheduler (trino_tpu/stage/) — joins
    and the final aggregation run ON the workers over the partitioned
    worker-to-worker exchange — at 1 vs 3 in-process workers, with the
    per-stage-barrier vs eager-pipelining A/B (stage_pipelining) and
    the ICI-vs-spool exchange byte split. Reports rows/s (lineitem
    rows / best wall), the pipelining overlap ratio, and the exchange
    bytes each medium moved, so worker-side execution is a tracked
    metric next to cpu_engine_rows_per_sec."""
    from trino_tpu.exec.remote import DistributedHostQueryRunner
    from trino_tpu.obs.metrics import METRICS
    from trino_tpu.runner import LocalQueryRunner
    from trino_tpu.server.task_worker import TaskWorkerServer
    from trino_tpu.session import Session

    sql = ("SELECT o_orderpriority, count(*), sum(l_extendedprice) "
           "FROM lineitem JOIN orders ON l_orderkey = o_orderkey "
           "GROUP BY o_orderpriority ORDER BY o_orderpriority")
    nrows = int(LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny")).execute(
            "SELECT count(*) FROM lineitem").rows[0][0])

    def make_session(pipelining: bool = True):
        s = Session(catalog="tpch", schema="tiny")
        s.set("multistage_execution", True)
        s.set("stage_pipelining", pipelining)
        return s

    def ex_bytes_written():
        # producer side only: "read" re-counts the same frames on the
        # consumer side, and summing both would double-report the
        # shuffle volume
        return METRICS.counter(
            "trino_tpu_exchange_partition_bytes_total").value(
                direction="written")

    nruns = max(iters, 1) + 1       # warm-up + timed iterations

    def best_of(uris, pipelining: bool = True):
        r = DistributedHostQueryRunner(
            uris, session=make_session(pipelining))
        return _cold_warm(lambda: r.execute(sql), iters)

    workers = [TaskWorkerServer().start() for _ in range(3)]
    try:
        uris = [w.base_uri for w in workers]
        _, t_one = best_of(uris[:1])
        # the A/B: identical DAG, identical fleet — only the barrier
        # differs (stage_pipelining=false is the pre-PR-13 behavior)
        _, t_barrier = best_of(uris, pipelining=False)
        b0 = ex_bytes_written()
        cold_all, t_all = best_of(uris, pipelining=True)
        # identical runs: the per-query shuffle volume is the written
        # delta divided by how many times the query executed
        moved = (ex_bytes_written() - b0) / nruns
        overlap = METRICS.gauge(
            "trino_tpu_mpp_pipeline_overlap_ratio").value()
    finally:
        for w in workers:
            w.stop()
    return dict({
        "rows_per_sec": nrows / t_all,
        "rows_per_sec_1_worker": nrows / t_one,
        "rows_per_sec_barrier": nrows / t_barrier,
        "speedup_vs_1_worker": t_one / t_all,
        "pipelined_speedup_vs_barrier": t_barrier / t_all,
        "pipeline_overlap_ratio": overlap,
        "exchange_bytes": moved,
        "exchange_spool_bytes": moved,
    }, **_mpp_ici_subleg(sql, nrows), **_cw_keys(cold_all, t_all))


def _leg_load(duration_s: float, clients: int) -> dict:
    """Closed-loop concurrency leg (ROADMAP item 2's tracked metric):
    K concurrent protocol clients hammer one coordinator for a fixed
    duration against a concurrency-capped resource group, so queries
    queue, drain fair, and occasionally bounce off the full queue.
    Reports QPS, p50/p95/p99 query wall (from the PR 4 histogram,
    delta-snapshotted around the run), average queued time, and the
    governance counters (rejections, memory kills) — overload behavior
    as a number, like rows/s."""
    import threading

    import trino_tpu  # noqa: F401
    from trino_tpu.client import ClientError, StatementClient
    from trino_tpu.obs.metrics import (MEMORY_KILLS, QUEUE_REJECTIONS,
                                       QUERY_QUEUED_SECONDS,
                                       QUERY_WALL_SECONDS)
    from trino_tpu.server.coordinator import Coordinator
    from trino_tpu.server.resourcegroups import (ResourceGroup,
                                                 ResourceGroupManager)

    mgr = ResourceGroupManager()
    grp = mgr.root.add(ResourceGroup(
        "bench", hard_concurrency=2,
        # smaller than the client count minus the running slots, so
        # the burst occasionally trips QUERY_QUEUE_FULL — the
        # rejection path is part of what this leg measures
        max_queued=max(2, clients // 3)))
    mgr.add_selector(grp)
    co = Coordinator(resource_groups=mgr,
                     memory_pool_bytes=4 << 30).start()
    sql = "SELECT count(*) FROM tpch.tiny.region"
    # warm the engine — and split the warm-up into the leg's own
    # compile/warm scoreboard keys while at it
    warm_client = StatementClient(co.base_uri)
    cold_s, warm_s = _cold_warm(lambda: warm_client.execute(sql), 1)
    wall0, n0, _ = QUERY_WALL_SECONDS.snapshot()
    q0, qn0, qs0 = QUERY_QUEUED_SECONDS.snapshot()
    rej0 = QUEUE_REJECTIONS.value()
    kills0 = MEMORY_KILLS.value()
    completed = [0] * clients
    rejected = [0] * clients
    stop_at = time.monotonic() + duration_s

    def run(i: int):
        c = StatementClient(co.base_uri)
        while time.monotonic() < stop_at:
            try:
                c.execute(sql)
                completed[i] += 1
            except ClientError as e:
                if "QUERY_QUEUE_FULL" in str(e):
                    rejected[i] += 1
                    time.sleep(0.02)    # back off like a real client
                else:
                    raise

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0
    wall1, n1, _ = QUERY_WALL_SECONDS.snapshot()
    _, qn1, qs1 = QUERY_QUEUED_SECONDS.snapshot()
    co.stop()
    deltas = [b - a for a, b in zip(wall0, wall1)]
    n = n1 - n0
    pct = lambda q: QUERY_WALL_SECONDS.quantile_from_deltas(  # noqa: E731
        QUERY_WALL_SECONDS.buckets, deltas, n, q)
    qcount = qn1 - qn0
    return dict(_cw_keys(cold_s, warm_s), **{
        "qps": sum(completed) / max(elapsed, 1e-9),
        "clients": clients,
        "duration_s": round(elapsed, 2),
        "completed": sum(completed),
        "p50_ms": round(pct(0.50) * 1000, 2),
        "p95_ms": round(pct(0.95) * 1000, 2),
        "p99_ms": round(pct(0.99) * 1000, 2),
        "queued_ms_avg": round(
            (qs1 - qs0) / qcount * 1000, 2) if qcount else 0.0,
        "queued_dequeues": qcount,
        "rejections": (QUEUE_REJECTIONS.value() - rej0),
        "memory_kills": (MEMORY_KILLS.value() - kills0),
    })


def _leg_load_mixed(duration_s: float, clients: int) -> dict:
    """Mixed-size load leg (ISSUE 14 acceptance): K >> runner-threads
    concurrent clients — half small point queries, half large joins —
    against ONE worker whose shared split scheduler (exec/taskexec.py)
    time-slices every query's tasks through 2 runner slots. Reports
    the small queries' p95 vs their ISOLATED latency (the acceptance
    bound: within 3x at K >> runners — without the fair scheduler a
    large query owns the worker and small-query latency balloons) and
    a starvation/fairness metric (min/max completed across the small
    clients; 1.0 = perfectly fair, 0 = a client starved)."""
    import threading

    import trino_tpu  # noqa: F401
    from trino_tpu.client import ClientError, StatementClient
    from trino_tpu.obs.metrics import METRICS
    from trino_tpu.server.coordinator import Coordinator
    from trino_tpu.server.task_worker import TaskWorkerServer

    RUNNERS = 2
    worker = TaskWorkerServer(task_runners=RUNNERS).start()
    # no admission cap: this leg measures WORKER-side fairness, so
    # every client's query must actually reach the worker at once
    co = Coordinator(worker_uris=[worker.base_uri],
                     memory_pool_bytes=4 << 30).start()
    small_sql = "SELECT count(*) FROM tpch.tiny.region"
    # the large shape is scan-heavy (chunkable end to end): forced
    # chunking below turns every chunk into a scheduler yield point
    large_sql = ("SELECT l_returnflag, count(*), "
                 "sum(l_extendedprice * (1 - l_discount)), "
                 "avg(l_quantity) FROM tpch.tiny.lineitem "
                 "WHERE l_shipdate <= DATE '1998-09-02' "
                 "GROUP BY l_returnflag ORDER BY l_returnflag")
    warm_client = StatementClient(co.base_uri)
    cold_s, warm_s = _cold_warm(
        lambda: (warm_client.execute(small_sql),
                 warm_client.execute(large_sql)), 1)
    # isolated small-query latency (warm, no contention): the
    # denominator of the acceptance ratio
    iso = []
    for _ in range(5):
        t0 = time.monotonic()
        warm_client.execute(small_sql)
        iso.append(time.monotonic() - t0)
    iso_p50 = sorted(iso)[len(iso) // 2]
    n_small = max(clients // 2, 1)
    lats: list = [[] for _ in range(clients)]
    completed = [0] * clients
    yields0 = METRICS.counter(
        "trino_tpu_task_scheduler_yields_total").value()
    stop_at = time.monotonic() + duration_s

    errors = [0] * clients

    def run(i: int):
        # large clients force chunked execution (stream_chunk_rows):
        # every chunk is a scheduler yield point, so a large query
        # cannot own a runner slot for a whole operator — the quanta
        # the small queries' latency bound depends on
        props = ({} if i < n_small
                 else {"stream_chunk_rows": "4096"})
        props["retry_policy"] = "TASK"
        c = StatementClient(co.base_uri, session_properties=props)
        sql = small_sql if i < n_small else large_sql
        while time.monotonic() < stop_at:
            t0 = time.monotonic()
            try:
                c.execute(sql)
            except ClientError:
                # transient under churn (connection resets on the
                # threaded HTTP stack): counted, never a dead client
                errors[i] += 1
                continue
            lats[i].append(time.monotonic() - t0)
            completed[i] += 1

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0
    co.stop()
    worker.stop()
    small_lats = sorted(x for i in range(n_small) for x in lats[i])
    large_lats = sorted(x for i in range(n_small, clients)
                        for x in lats[i])

    def pct(sorted_xs, q):
        if not sorted_xs:
            return 0.0
        return sorted_xs[min(int(q * len(sorted_xs)),
                             len(sorted_xs) - 1)]

    small_counts = completed[:n_small]
    fairness = (min(small_counts) / max(small_counts)
                if max(small_counts) else 0.0)
    p95 = pct(small_lats, 0.95)
    return dict(_cw_keys(cold_s, warm_s), **{
        "mixed_qps": sum(completed) / max(elapsed, 1e-9),
        "clients": clients,
        "runner_threads": RUNNERS,
        "duration_s": round(elapsed, 2),
        "small_completed": sum(small_counts),
        "large_completed": sum(completed[n_small:]),
        "small_p50_ms": round(pct(small_lats, 0.50) * 1000, 2),
        "small_p95_ms": round(p95 * 1000, 2),
        "large_p95_ms": round(pct(large_lats, 0.95) * 1000, 2),
        "isolated_small_p50_ms": round(iso_p50 * 1000, 2),
        # the acceptance ratio: <= 3.0 means small queries held their
        # latency next to the large ones at K >> runner threads
        "small_p95_vs_isolated": round(p95 / max(iso_p50, 1e-9), 2),
        "fairness_min_over_max": round(fairness, 3),
        "client_errors": sum(errors),
        "scheduler_yields": METRICS.counter(
            "trino_tpu_task_scheduler_yields_total").value() - yields0,
    })


def _leg_storm(duration_s: float, clients: int) -> dict:
    """Point-query-storm leg (ISSUE 18): K concurrent protocol clients
    replay Zipf-distributed point lookups against ONE coordinator —
    the dashboard-storm shape the ragged batch executor
    (exec/taskexec.py RaggedBatcher + executor._try_ragged_chain) and
    the coordinator result cache (exec/resultcache.py) exist to serve.
    Phase A runs with both OFF (every query dispatches and executes
    alone); phase B turns on ragged_batching + result_cache_enabled —
    same clients, same Zipf stream, same duration. Reports each
    phase's client-observed p99, phase B's queries-per-compile
    (completed / structural jit-cache misses — > 1 means co-batched
    or cached queries shared a compiled program), and the
    result-cache hit ratio the Zipf head drove."""
    import threading

    import trino_tpu  # noqa: F401
    from trino_tpu.client import ClientError, StatementClient
    # the real metric objects, not name lookups: resultcache/taskexec
    # register these families with labels on first import — a bare
    # METRICS.counter(name) here would register an unlabeled twin
    from trino_tpu.exec.resultcache import RESULT_CACHE_LOOKUPS as rc
    from trino_tpu.exec.taskexec import (RAGGED_BATCHES as rb,
                                         RAGGED_QUERIES as rq)
    from trino_tpu.obs.metrics import JIT_CACHE_LOOKUPS as jit
    from trino_tpu.server.coordinator import Coordinator

    KEYS = 256          # distinct point lookups under the Zipf tail

    def sql_for(k: int) -> str:
        return ("SELECT c_name FROM tpch.tiny.customer "
                f"WHERE c_custkey = {k}")

    def jit_misses() -> float:
        # every cache family (chain/stream/masked/ragged) counts: a
        # compile is a compile wherever it lands
        return sum(v for k, v in jit.samples() if k and k[-1] == "miss")

    # both phases ride the canonical-key structural path — only the
    # batching/cache session properties differ between A and B
    prev = os.environ.get("TRINO_TPU_FRAGMENT_JIT")
    os.environ["TRINO_TPU_FRAGMENT_JIT"] = "1"
    co = Coordinator(memory_pool_bytes=4 << 30).start()
    try:
        # warm-up: generate tiny tables + pay the parse/plan caches,
        # split into the leg's compile/warm scoreboard keys
        warm_client = StatementClient(co.base_uri)
        cold_s, warm_s = _cold_warm(
            lambda: warm_client.execute(sql_for(KEYS + 1)), 1)

        def phase(props):
            lats: list = []
            lock = threading.Lock()
            errors = [0]
            stop_at = time.monotonic() + duration_s

            def run(i: int):
                c = StatementClient(co.base_uri,
                                    session_properties=props)
                rng = np.random.default_rng(1000 + i)
                mine = []
                while time.monotonic() < stop_at:
                    k = min(int(rng.zipf(1.3)), KEYS)
                    t0 = time.monotonic()
                    try:
                        c.execute(sql_for(k))
                    except (ClientError, OSError):
                        # transient under churn (admission bounce or a
                        # connection reset on the threaded HTTP
                        # stack): counted, never a dead client
                        errors[0] += 1
                        continue
                    mine.append(time.monotonic() - t0)
                with lock:
                    lats.extend(mine)

            threads = [threading.Thread(target=run, args=(i,))
                       for i in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return sorted(lats), errors[0]

        def pct(sorted_xs, q):
            if not sorted_xs:
                return 0.0
            return sorted_xs[min(int(q * len(sorted_xs)),
                                 len(sorted_xs) - 1)]

        a_lats, a_errs = phase({})
        m0, h0, l0 = (jit_misses(), rc.value(result="hit"),
                      sum(v for _, v in rc.samples()))
        q0, b0 = rq.value(), rb.value()
        b_lats, b_errs = phase({"ragged_batching": "true",
                                "result_cache_enabled": "true"})
        dm = jit_misses() - m0
        dl = sum(v for _, v in rc.samples()) - l0
        hits = rc.value(result="hit") - h0
    finally:
        co.stop()
        if prev is None:
            os.environ.pop("TRINO_TPU_FRAGMENT_JIT", None)
        else:
            os.environ["TRINO_TPU_FRAGMENT_JIT"] = prev
    return dict(_cw_keys(cold_s, warm_s), **{
        "clients": clients,
        "duration_s": round(duration_s, 2),
        "storm_completed": len(a_lats),
        "storm_batched_completed": len(b_lats),
        "storm_p99_ms": round(pct(a_lats, 0.99) * 1000, 2),
        "storm_batched_p99_ms": round(pct(b_lats, 0.99) * 1000, 2),
        "storm_p50_ms": round(pct(a_lats, 0.50) * 1000, 2),
        "storm_batched_p50_ms": round(pct(b_lats, 0.50) * 1000, 2),
        # phase B completions per structural compile: > 1 means the
        # storm amortized compiles across queries (ragged batches
        # sharing one program + result-cache hits compiling nothing)
        "storm_queries_per_compile": round(
            len(b_lats) / max(dm, 1.0), 2),
        "result_cache_hit_ratio": round(hits / dl, 4) if dl else 0.0,
        "ragged_queries": rq.value() - q0,
        "ragged_batches": rb.value() - b0,
        "client_errors": a_errs + b_errs,
    })


def _leg_streaming(duration_s: float) -> dict:
    """Streaming ingest throughput leg (ISSUE 20): a producer streams
    newline-delimited JSON batches into POST /v1/ingest/{topic} for a
    fixed duration while a continuous ``insert`` job drains the topic
    into a sink table on a poll cadence. Headline is
    ``ingest_rows_per_sec`` (producer-observed append throughput
    through the HTTP route, segment-file durability included);
    ride-alongs are the drain side — rows the continuous job moved
    per second, cycles it took, and the end-to-end lag from last
    ingest to fully-drained sink."""
    import json as _json
    import tempfile
    import urllib.request

    import trino_tpu  # noqa: F401
    from trino_tpu.client import StatementClient
    from trino_tpu.config import CONFIG as _CFG
    from trino_tpu.server.coordinator import Coordinator

    _CFG.stream_dir = tempfile.mkdtemp(prefix="bench_stream_")
    BATCH = 200                       # rows per producer POST

    def _post(uri, body=b"", method="POST"):
        req = urllib.request.Request(uri, data=body or None,
                                     method=method)
        with urllib.request.urlopen(req, timeout=30) as resp:
            return _json.load(resp)

    def _batch(base: int) -> bytes:
        return b"\n".join(
            _json.dumps({"k": (base + i) % 16, "v": float(base + i),
                         "ts": float(base + i)}).encode()
            for i in range(BATCH))

    co = Coordinator().start()
    try:
        c = StatementClient(co.base_uri)
        c.execute("CREATE TABLE stream.default.bench_events "
                  "(k BIGINT, v DOUBLE, ts DOUBLE)")
        c.execute("CREATE TABLE memory.default.bench_sink "
                  "(k BIGINT, o BIGINT, v DOUBLE)")
        # warm-up round = one ingest POST + the scan the continuous
        # cycles will re-dispatch, split into the leg's compile/warm
        # scoreboard keys
        warm = [0]

        def round_once():
            _post(co.base_uri + "/v1/ingest/bench_events",
                  _batch(warm[0]))
            warm[0] += BATCH
            c.execute("SELECT count(*) "
                      "FROM stream.default.bench_events")

        cold_s, warm_s = _cold_warm(round_once, 2)
        job = _post(co.base_uri + "/v1/continuous", _json.dumps({
            "kind": "insert", "topic": "bench_events",
            "poll_interval_ms": 100,
            "sql": "INSERT INTO memory.default.bench_sink "
                   "SELECT k, _offset, v "
                   "FROM stream.default.bench_events"}).encode())
        # the ingest storm: closed-loop single producer for the
        # duration — every POST durably appends before returning
        produced = warm[0]
        t0 = time.monotonic()
        while time.monotonic() < t0 + duration_s:
            _post(co.base_uri + "/v1/ingest/bench_events",
                  _batch(produced))
            produced += BATCH
        ingest_s = time.monotonic() - t0
        # drain: wait for the continuous job to catch up, then read
        # its scoreboard
        drain_t0 = time.monotonic()
        deadline = drain_t0 + max(duration_s * 10, 30.0)
        sink = 0
        while time.monotonic() < deadline:
            sink = c.execute("SELECT count(*) FROM "
                             "memory.default.bench_sink").rows[0][0]
            if sink >= produced:
                break
            time.sleep(0.1)
        drain_lag_s = time.monotonic() - drain_t0
        info = _post(co.base_uri + "/v1/continuous/" + job["job_id"],
                     method="GET")
        return dict(_cw_keys(cold_s, warm_s), **{
            "ingest_rows_per_sec": (produced - warm[0]) / ingest_s,
            "ingested_rows": produced,
            "drained_rows": sink,
            "drain_rows_per_sec": (
                info["rows_total"] / max(ingest_s + drain_lag_s,
                                         1e-9)),
            "drain_lag_s": round(drain_lag_s, 3),
            "continuous_cycles": info["cycles"],
            "zero_dup_zero_loss": bool(sink == produced),
        })
    finally:
        co.stop()


def _run_probe_body(kind: str):
    """Inside the subprocess: run both legs, print one JSON line per leg
    the moment it completes so a timeout loses only the unfinished leg."""
    if kind == "init":
        # fail-fast device-init probe: backend contact ONLY, no data,
        # no compile — the ≤60s answer to "is there a device at all",
        # kept separate so an init hang can never eat compute budget
        # (round-5 verdict: device init alone ate 360s of 540s)
        import jax
        devs = jax.devices()
        platform = devs[0].platform
        # a silent jax fallback to CPU is NOT a device: passing it
        # through would let the compute leg record CPU throughput as
        # the device engine number (the exact scoreboard corruption
        # the driver-unverified README annotation exists to prevent)
        print(json.dumps({"leg": "init", "ok": platform != "cpu",
                          "platform": platform,
                          "device_count": len(devs)}), flush=True)
        return
    if kind == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    if kind == "scale":
        sf = os.environ.get("BENCH_Q18_SCHEMA", "sf10")
        legs = [("q18", lambda: _leg_q18(sf))]
    elif kind == "first_compile":
        # the device compile sub-probe: ONLY the warm leg — its cold
        # wall pays the real q1 compile (fresh runners, nothing cached
        # beforehand) and populates the shared persistent XLA cache the
        # steady sub-probe (a separate process) then rides
        legs = [("warm", lambda: _leg_warm("sf1"))]
    elif kind == "steady":
        # steady-state sub-probe: engine/micro/telemetry with the XLA
        # compile already on disk — pays re-trace, never the compile
        legs = [("engine", lambda: _leg_engine("sf1", 2)),
                ("micro", lambda: _leg_micro(1.0, 3)),
                ("telemetry", lambda: _leg_telemetry("sf1", 2))]
    else:
        legs = [("warm", lambda: _leg_warm("sf1")),
                ("engine", lambda: _leg_engine("sf1", 2)),
                ("micro", lambda: _leg_micro(0.1, 2)),
                ("telemetry", lambda: _leg_telemetry("sf1", 2)),
                ("fault", lambda: _leg_fault(2)),
                ("mpp", lambda: _leg_mpp(2)),
                ("load", lambda: _leg_load(6.0, 6)),
                ("load_mixed", lambda: _leg_load_mixed(6.0, 8)),
                ("storm", lambda: _leg_storm(6.0, 64)),
                ("streaming", lambda: _leg_streaming(6.0))]
    for name, fn in legs:
        try:
            # every leg returns a dict carrying (at least) compile_s +
            # warm_speedup next to its headline number — the
            # compile-tax split is a first-class column of every row
            print(json.dumps(dict({"leg": name}, **fn())), flush=True)
        except Exception as e:  # report, keep going to the next leg
            print(json.dumps(
                {"leg": name,
                 "error": f"{type(e).__name__}: {e}"[:300]}), flush=True)


def _probe(kind: str, timeout: float, force_cpu: bool = False,
           extra_env: dict = None):
    """Run a probe subprocess; returns ({leg: rps}, {leg: err}).
    ``force_cpu`` pins a non-cpu probe kind to the CPU backend (the
    scale leg's fallback when no device landed an engine number)."""
    env = dict(os.environ)
    if kind == "cpu" or force_cpu:
        env["PYTHONPATH"] = ""       # skip the TPU-forcing sitecustomize
        env["JAX_PLATFORMS"] = "cpu"
    # every probe compiles against ONE persistent cache dir: compiles
    # carry across sub-probe processes and across bench rounds
    env["TRINO_TPU_XLA_CACHE_DIR"] = XLA_CACHE_DIR
    env["BENCH_PROBE_KIND"] = kind
    if extra_env:
        env.update(extra_env)
    out_text = ""
    err_note = None
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--probe"],
            capture_output=True, text=True, timeout=max(timeout, 10),
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
        out_text = p.stdout or ""
        if p.returncode != 0:
            # a hard crash (PJRT abort/segfault) after some legs printed
            # must still be surfaced — round 3 lost its engine leg to a
            # silent 0.0 exactly here
            tail = (p.stderr or "").strip().splitlines()[-4:]
            err_note = (f"rc={p.returncode}: "
                        + " | ".join(t.strip() for t in tail))[-300:]
    except subprocess.TimeoutExpired as e:
        s = e.stdout   # alias of e.output
        out_text = s.decode() if isinstance(s, bytes) else (s or "")
        err_note = f"probe timed out after {int(timeout)}s"
    vals, errs = {}, {}
    for line in out_text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        leg = d.get("leg", "?")
        # compile-tax scoreboard keys ride every leg (acceptance:
        # compile_s + warm_speedup in every leg's JSON) — hoovered
        # into prefixed vals so the final report can surface any of
        # them without per-leg plumbing
        for k in ("compile_s", "warm_speedup", "cold_s", "warm_s"):
            if k in d:
                vals[f"{leg}_{k}"] = d[k]
        if leg == "warm" and "warm_speedup" in d:
            vals["warm"] = d["warm_speedup"]
        if d.get("leg") == "init":
            if d.get("ok"):
                vals["init"] = d
            else:
                # keep the diagnostic: "not ok" here means the probe
                # RAN and found no device (e.g. silent jax CPU
                # fallback) — the scoreboard must say that, not the
                # generic "leg did not complete" hang message
                errs["init"] = ("no accelerator: platform="
                                f"{d.get('platform')} x"
                                f"{d.get('device_count')}")
        elif leg == "load_mixed" and "mixed_qps" in d:
            # mixed-size load ride-alongs: worker-side fairness
            vals["load_mixed"] = d["mixed_qps"]
            for k in ("small_p50_ms", "small_p95_ms", "large_p95_ms",
                      "isolated_small_p50_ms", "small_p95_vs_isolated",
                      "fairness_min_over_max", "small_completed",
                      "large_completed", "scheduler_yields"):
                if k in d:
                    vals[f"load_mixed_{k}"] = d[k]
        elif leg == "storm" and "storm_p99_ms" in d:
            # point-query-storm ride-alongs: the ragged-batch +
            # result-cache scoreboard (ISSUE 18 acceptance keys)
            vals["storm"] = d["storm_p99_ms"]
            for k in ("storm_p99_ms", "storm_batched_p99_ms",
                      "storm_p50_ms", "storm_batched_p50_ms",
                      "storm_queries_per_compile",
                      "result_cache_hit_ratio", "storm_completed",
                      "storm_batched_completed", "ragged_queries",
                      "ragged_batches"):
                if k in d:
                    vals[f"storm_{k}" if not k.startswith("storm")
                         else k] = d[k]
        elif leg == "streaming" and "ingest_rows_per_sec" in d:
            # streaming ingest leg (ISSUE 20): the producer-side
            # append throughput is the headline; the continuous
            # job's drain side rides along
            vals["streaming"] = d["ingest_rows_per_sec"]
            for k in ("ingest_rows_per_sec", "drain_rows_per_sec",
                      "drain_lag_s", "continuous_cycles",
                      "ingested_rows", "drained_rows",
                      "zero_dup_zero_loss"):
                if k in d:
                    vals[f"streaming_{k}"] = d[k]
        elif "qps" in d:
            # load leg ride-alongs: the concurrency scoreboard
            vals["load"] = d["qps"]
            for k in ("p50_ms", "p95_ms", "p99_ms", "queued_ms_avg",
                      "rejections", "memory_kills", "completed"):
                if k in d:
                    vals[f"load_{k}"] = d[k]
        elif "rows_per_sec" in d:
            vals[d.get("leg", "?")] = d["rows_per_sec"]
            # streamed-execution ride-alongs (the q18 scale leg):
            # chunk count, overlap ratio, transfer volume, budget
            for k in ("stream_chunks", "stream_overlap_ratio",
                      "stream_h2d_bytes", "budget_bytes",
                      "datagen_s"):
                if k in d:
                    vals[f"{leg}_{k}"] = d[k]
            # mpp leg ride-alongs: worker-side execution artifacts,
            # the barrier-vs-pipelined A/B, and the ICI/spool split
            if "speedup_vs_1_worker" in d:
                vals["mpp_speedup"] = d["speedup_vs_1_worker"]
            if "exchange_bytes" in d:
                vals["mpp_exchange_bytes"] = d["exchange_bytes"]
            if "rows_per_sec_1_worker" in d:
                vals["mpp_1_worker"] = d["rows_per_sec_1_worker"]
            for k in ("rows_per_sec_barrier",
                      "pipelined_speedup_vs_barrier",
                      "pipeline_overlap_ratio",
                      "exchange_spool_bytes", "exchange_ici_bytes",
                      "ici_rows_per_sec"):
                if k in d:
                    vals[f"mpp_{k}"] = d[k]
        elif "overhead" in d:
            vals[d.get("leg", "?")] = d["overhead"]
            # fault leg ride-alongs: scrape-side FTE artifacts
            if "task_retries_total" in d:
                vals["task_retries"] = d["task_retries_total"]
            if "query_peak_memory_bytes" in d:
                vals["peak_memory_bytes"] = d["query_peak_memory_bytes"]
            # fault leg ride-alongs: coordinator-failover resume
            for k in ("coordinator_failover_resume_s",
                      "failover_parts_resumed",
                      "failover_parts_replayed"):
                if k in d:
                    vals[k] = d[k]
            # telemetry leg ride-along: OTLP documents the file sink
            # actually accepted during the telemetry-on runs
            if "otlp_exports" in d:
                vals["telemetry_otlp_exports"] = d["otlp_exports"]
        elif "error" in d:
            errs[d.get("leg", "?")] = d["error"]
    if err_note:
        errs.setdefault("probe", err_note)
    expected = ("init",) if kind == "init" else \
        ("q18",) if kind == "scale" else \
        ("warm",) if kind == "first_compile" else \
        ("engine", "micro", "telemetry") if kind == "steady" else \
        ("engine", "warm", "micro", "telemetry",
         "fault", "mpp", "load", "load_mixed", "storm")
    for leg in expected:              # a 0.0 must never be unexplained
        if leg not in vals and leg not in errs:
            errs[leg] = "leg did not complete"
    return vals, errs


def main():
    if "--probe" in sys.argv:
        _run_probe_body(os.environ.get("BENCH_PROBE_KIND", "device"))
        return

    # Last-ditch net: whatever goes wrong below, print the JSON line.
    state = {"printed": False, "report": None}

    def _emit(report):
        if not state["printed"]:
            state["printed"] = True
            print(json.dumps(report), flush=True)

    def _alarm(signum, frame):
        _emit(state["report"] or {
            "metric": "tpch_q1_sf1_engine_rows_per_sec", "value": 0.0,
            "unit": "rows/s", "vs_baseline": 0.0,
            "error": "bench harness overran its own budget"})
        os._exit(0)

    signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(int(BUDGET) + 20)

    # --- sub-probe machinery: every probe is its own subprocess under
    # its OWN cap, checkpointed the moment it lands cleanly. One
    # sub-probe blowing its cap zeroes only its own keys (the r04/r05
    # failure mode — one device hang zeroing every device number — is
    # structurally impossible), and a rerun of the round resumes past
    # whatever already landed.
    forced_blowouts = {s.strip() for s in os.environ.get(
        "BENCH_FORCE_SUBPROBE_TIMEOUT", "").split(",") if s.strip()}
    ckpt = _ckpt_load()
    subtimes = {}

    def _subprobe(name: str, kind: str, cap: float,
                  force_cpu: bool = False, extra_env: dict = None):
        """One checkpointed, individually-capped sub-probe. Completed
        sub-probes replay from the checkpoint (status "resumed") —
        only the unfinished remainder of a blown round re-runs."""
        done = ckpt.get(name)
        if done is not None:
            subtimes[name] = {
                "status": "resumed", "cap_s": round(cap, 1),
                "elapsed_s": done.get("elapsed_s", 0.0)}
            return dict(done.get("vals", {})), dict(done.get("errs", {}))
        # the blowout drill: the named sub-probe gets a ~1s cap, times
        # out, and the artifact must still carry every OTHER number
        cap_eff = 1.0 if name in forced_blowouts else cap
        t0 = time.monotonic()
        vals, errs = _probe(kind, cap_eff, force_cpu=force_cpu,
                            extra_env=extra_env)
        elapsed = time.monotonic() - t0
        blowout = any("timed out" in str(v) for v in errs.values())
        subtimes[name] = {
            "status": ("ok" if vals and not errs else
                       "timeout" if blowout else
                       "partial" if vals else "error"),
            "cap_s": round(cap_eff, 1), "elapsed_s": round(elapsed, 1)}
        # partial results checkpoint too: a probe that timed out after
        # landing some legs keeps them on resume — re-burning its full
        # cap to reproduce the same partial is the one thing a blown
        # round cannot afford
        if vals:
            ckpt[name] = {"vals": vals, "errs": errs,
                          "elapsed_s": round(elapsed, 1)}
            _ckpt_save(ckpt)
        return vals, errs

    # --- CPU baseline probe FIRST (round-5 verdict #1: the device
    # probe ate 360s of the 540s budget and the scoreboard lost its
    # only real number) — the engine leg leads inside the probe, so
    # cpu_engine_rows_per_sec lands every round no matter what the
    # device backend does afterwards. Checkpointed like the device
    # sub-probes: a resumed round keeps its baseline for free.
    cpu_vals, cpu_errs = {}, {}
    cpu_budget = min(_remaining() - 90, 210)
    # a checkpointed baseline replays even when this run's budget
    # would not admit a fresh probe — resumed numbers are free
    if cpu_budget > 30 or "cpu_baseline" in ckpt:
        cpu_vals, cpu_errs = _subprobe("cpu_baseline", "cpu",
                                       cpu_budget)
    else:
        cpu_errs["probe"] = "skipped: insufficient budget"

    # --- device side: the init -> first_compile -> steady ladder
    INIT_CAP = float(os.environ.get(
        "BENCH_DEV_INIT_CAP", min(60.0, 0.1 * BUDGET)))
    COMPILE_CAP = float(os.environ.get(
        "BENCH_DEV_COMPILE_CAP", 0.2 * BUDGET))
    STEADY_CAP = float(os.environ.get(
        "BENCH_DEV_STEADY_CAP", 0.2 * BUDGET))
    Q18_CAP = float(os.environ.get(
        "BENCH_DEV_Q18_CAP", 0.3 * BUDGET))

    dev_vals = {}
    sub_errs = {}           # {sub-probe name: cause} — satellite shape
    if _remaining() > 45:
        init_vals, init_errs = _subprobe(
            "device_init", "init", min(INIT_CAP, _remaining() - 20))
        if "init" not in init_vals:
            # no device within the fail-fast window: skip the compute
            # sub-probes entirely instead of feeding them caps to hang in
            sub_errs["device_init"] = json.dumps(init_errs)[:200]
        else:
            if _remaining() > 60:
                cv, ce = _subprobe(
                    "device_first_compile", "first_compile",
                    min(COMPILE_CAP, _remaining() - 45))
                dev_vals.update(cv)
                if ce:
                    sub_errs["device_first_compile"] = \
                        json.dumps(ce)[:200]
            else:
                sub_errs["device_first_compile"] = \
                    "skipped: insufficient budget"
            if _remaining() > 60:
                sv, se = _subprobe(
                    "device_steady", "steady",
                    min(STEADY_CAP, _remaining() - 30))
                dev_vals.update(sv)
                if se:
                    sub_errs["device_steady"] = json.dumps(se)[:200]
            else:
                sub_errs["device_steady"] = \
                    "skipped: insufficient budget"
    else:
        sub_errs["device_init"] = "skipped: insufficient budget"

    # --- scale leg: q18 under a beyond-HBM budget ---------------------
    # (BASELINE configs[3] direction). A device round runs STREAMED
    # q18 at sf100 as its own capped+checkpointed sub-probe; CPU
    # fallback keeps the scaled-down schema with the same scaled-down
    # memory budget — the morsel-streaming path (exec/streamjoin.py)
    # is exercised every round either way. Failure here never harms
    # the primary metric.
    scale_vals, scale_errs = {}, {}
    on_device = bool(dev_vals.get("engine"))
    q18_schema = os.environ.get(
        "BENCH_Q18_SCHEMA",
        os.environ.get("BENCH_Q18_SCHEMA_DEVICE", "sf100")
        if on_device else "sf10")
    if (on_device or cpu_vals.get("engine")) and _remaining() > 120:
        scale_vals, scale_errs = _subprobe(
            "device_q18" if on_device else "cpu_q18", "scale",
            min(Q18_CAP if on_device else 420, _remaining() - 30),
            force_cpu=not on_device,
            extra_env={"BENCH_Q18_SCHEMA": q18_schema})
        if on_device and scale_errs:
            sub_errs["device_q18"] = json.dumps(scale_errs)[:200]
    else:
        scale_errs["q18"] = ("skipped: no engine leg landed"
                             if not (on_device
                                     or cpu_vals.get("engine"))
                             else "skipped: insufficient budget")

    # stamp cause + elapsed/cap onto every failed sub-probe (the
    # "failed device leg must say WHICH phase died and how long it
    # lived" satellite)
    for name, cause in list(sub_errs.items()):
        st = subtimes.get(name)
        if st:
            sub_errs[name] = (f"{cause} (elapsed {st['elapsed_s']}s"
                              f"/cap {st['cap_s']}s)")

    tpu_eng = dev_vals.get("engine")
    tpu_micro = dev_vals.get("micro")
    cpu_eng = cpu_vals.get("engine")
    cpu_micro = cpu_vals.get("micro")
    value = tpu_eng or 0.0
    vs = (value / cpu_eng) if (value and cpu_eng) else 0.0
    report = {
        "metric": "tpch_q1_sf1_engine_rows_per_sec",
        "value": round(value, 1),
        "unit": "rows/s",
        "vs_baseline": round(vs, 2),
        "baseline": "SQL q1 sf1 through the same engine on 1 host CPU "
                    f"worker ({round(cpu_eng, 1) if cpu_eng else 'n/a'} "
                    "rows/s); north star >=5x (BASELINE.json)",
        # first-class every round (round-5 verdict #1): the CPU engine
        # number is the one metric five rounds have actually produced —
        # it must never again live only inside the baseline string
        "cpu_engine_rows_per_sec": round(cpu_eng or 0.0, 1),
        "micro_rows_per_sec": round(tpu_micro or 0.0, 1),
        # cpu micro ran on a 10% sample: rows/sec normalizes per-row, so
        # the ratio divides the rates directly
        "micro_vs_cpu": (round(tpu_micro / cpu_micro, 2)
                         if tpu_micro and cpu_micro else 0.0),
        # compile-amortization scoreboard (ROADMAP item 1): compile
        # wall split out of the engine leg, and the explicit
        # cold-vs-warm leg's speedup (same q1 through two fresh
        # runners — what the second run gains once the compile tax is
        # paid). Device preferred, CPU fallback: these keys are
        # PARTIAL-SAFE — the CPU probe runs first, so a dying device
        # leg can no longer produce an all-zero artifact.
        # sourced from the WARM leg, not the engine leg: warm runs
        # first and genuinely pays the q1 compile; the engine leg's
        # cold run then rides the process-wide caches the warm leg
        # populated, so its compile_s is structurally ~0
        "compile_s": round(
            dev_vals.get("warm_compile_s",
                         cpu_vals.get("warm_compile_s", 0.0))
            or 0.0, 4),
        "warm_speedup": round(
            dev_vals.get("warm_warm_speedup",
                         cpu_vals.get("warm_warm_speedup", 0.0))
            or 0.0, 2),
        "cold_s": round(
            dev_vals.get("warm_cold_s",
                         cpu_vals.get("warm_cold_s", 0.0)) or 0.0, 4),
        "warm_s": round(
            dev_vals.get("warm_warm_s",
                         cpu_vals.get("warm_warm_s", 0.0)) or 0.0, 4),
        # per-sub-probe scoreboard (round-5 postmortem: WHICH device
        # phase died, how long it lived, under what cap — first-class
        # keys, never only inside the errors blob)
        "device_init_s": round(
            subtimes.get("device_init", {}).get("elapsed_s", 0.0), 1),
        "device_first_compile_s": round(
            subtimes.get("device_first_compile", {})
            .get("elapsed_s", 0.0), 1),
        "device_steady_s": round(
            subtimes.get("device_steady", {}).get("elapsed_s", 0.0), 1),
        "device_subprobes": json.dumps(subtimes)[:500],
        # observability-regression tripwire: q1 on the DEFAULT
        # distributed MPP path with the full telemetry stack
        # (tracing + device/CPU attribution + OTLP export) on vs off;
        # device preferred, CPU fallback — target < 0.05
        # (tests/test_observability.py)
        "telemetry_overhead": round(
            dev_vals.get("telemetry",
                         cpu_vals.get("telemetry", 0.0)) or 0.0, 4),
        "telemetry_otlp_exports": int(
            dev_vals.get("telemetry_otlp_exports",
                         cpu_vals.get("telemetry_otlp_exports", 0))
            or 0),
        # fault-tolerant execution (trino_tpu/fte/): fractional
        # slowdown of the same distributed query with one injected
        # worker failure under retry_policy=TASK, plus the scrape-side
        # artifacts the leg drove (task retries, peak-memory gauge)
        "fault_recovery_overhead": round(
            cpu_vals.get("fault", 0.0) or 0.0, 4),
        "fault_task_retries": round(
            cpu_vals.get("task_retries", 0.0) or 0.0, 1),
        "query_peak_memory_bytes": round(
            cpu_vals.get("peak_memory_bytes", 0.0) or 0.0, 1),
        # mid-flight coordinator failover (fte/faultpoints.py +
        # recovery.py ExecutionManifestStore): seconds from coordinator
        # death — injected at coordinator.post_stage_commit after the
        # first stage commits — to the SAME query FINISHED on a
        # replacement coordinator, and how many stage partitions were
        # read off the spool (resumed) vs re-dispatched (replayed)
        "coordinator_failover_resume_s": round(
            cpu_vals.get("coordinator_failover_resume_s", 0.0)
            or 0.0, 4),
        "failover_partitions_resumed": int(
            cpu_vals.get("failover_parts_resumed", 0.0) or 0),
        "failover_partitions_replayed": int(
            cpu_vals.get("failover_parts_replayed", 0.0) or 0),
        # multi-stage MPP (trino_tpu/stage/): a distributed hash-join +
        # final-aggregation query with joins/aggs executing ON workers
        # (default-on engine since PR 13); rows/s at 3 workers with
        # eager pipelining, the 1-worker and per-stage-barrier ratios,
        # the pipelining overlap ratio, and the exchange byte split —
        # spool/HTTP frames vs ICI device collectives (stage/ici.py)
        "mpp_rows_per_sec": round(cpu_vals.get("mpp", 0.0) or 0.0, 1),
        "mpp_speedup_vs_1_worker": round(
            cpu_vals.get("mpp_speedup", 0.0) or 0.0, 2),
        "mpp_rows_per_sec_barrier": round(
            cpu_vals.get("mpp_rows_per_sec_barrier", 0.0) or 0.0, 1),
        "mpp_pipelined_speedup_vs_barrier": round(
            cpu_vals.get("mpp_pipelined_speedup_vs_barrier", 0.0)
            or 0.0, 3),
        "mpp_pipeline_overlap_ratio": round(
            cpu_vals.get("mpp_pipeline_overlap_ratio", 0.0) or 0.0, 4),
        "mpp_exchange_bytes": round(
            cpu_vals.get("mpp_exchange_bytes", 0.0) or 0.0, 1),
        "exchange_spool_bytes_total": round(
            cpu_vals.get("mpp_exchange_spool_bytes", 0.0) or 0.0, 1),
        "exchange_ici_bytes_total": round(
            cpu_vals.get("mpp_exchange_ici_bytes", 0.0) or 0.0, 1),
        "mpp_ici_rows_per_sec": round(
            cpu_vals.get("mpp_ici_rows_per_sec", 0.0) or 0.0, 1),
        # overload governance (server/resourcegroups.py + memory.py):
        # closed-loop load — K concurrent clients for a fixed duration
        # against a hard_concurrency=2 group. QPS + latency percentiles
        # from the query-wall histogram, average admission queue wait,
        # and the governance counters the run drove (ROADMAP item 2's
        # concurrency metric, tracked like rows/s)
        "load_qps": round(cpu_vals.get("load", 0.0) or 0.0, 2),
        "load_p50_ms": round(cpu_vals.get("load_p50_ms", 0.0) or 0.0, 2),
        "load_p95_ms": round(cpu_vals.get("load_p95_ms", 0.0) or 0.0, 2),
        "load_p99_ms": round(cpu_vals.get("load_p99_ms", 0.0) or 0.0, 2),
        "load_queued_ms_avg": round(
            cpu_vals.get("load_queued_ms_avg", 0.0) or 0.0, 2),
        "load_rejections": round(
            cpu_vals.get("load_rejections", 0.0) or 0.0, 1),
        "load_memory_kills": round(
            cpu_vals.get("load_memory_kills", 0.0) or 0.0, 1),
        # worker-side multi-query runtime (exec/taskexec.py, ISSUE 14):
        # mixed-size closed loop — K=8 clients (half small point
        # queries, half large joins) over ONE worker with 2 runner
        # slots. The acceptance bound is small_p95_vs_isolated <= 3.0
        # (small queries hold their latency at K >> runner threads);
        # fairness is min/max completed across the small clients
        "load_mixed_qps": round(
            cpu_vals.get("load_mixed", 0.0) or 0.0, 2),
        "load_mixed_small_p95_ms": round(
            cpu_vals.get("load_mixed_small_p95_ms", 0.0) or 0.0, 2),
        "load_mixed_small_p95_vs_isolated": round(
            cpu_vals.get("load_mixed_small_p95_vs_isolated", 0.0)
            or 0.0, 2),
        "load_mixed_isolated_small_p50_ms": round(
            cpu_vals.get("load_mixed_isolated_small_p50_ms", 0.0)
            or 0.0, 2),
        "load_mixed_large_p95_ms": round(
            cpu_vals.get("load_mixed_large_p95_ms", 0.0) or 0.0, 2),
        "load_mixed_fairness_min_over_max": round(
            cpu_vals.get("load_mixed_fairness_min_over_max", 0.0)
            or 0.0, 3),
        "load_mixed_scheduler_yields": round(
            cpu_vals.get("load_mixed_scheduler_yields", 0.0) or 0.0, 1),
        # point-query-storm serving (ISSUE 18: exec/taskexec.py
        # RaggedBatcher + exec/resultcache.py): K=64 Zipf clients,
        # phase A per-query dispatch vs phase B ragged batching +
        # coordinator result cache. Acceptance: batched p99 below
        # unbatched p99, queries-per-compile > 1, and a non-zero
        # result-cache hit ratio off the Zipf head
        "storm_p99_ms": round(
            cpu_vals.get("storm_p99_ms", 0.0) or 0.0, 2),
        "storm_batched_p99_ms": round(
            cpu_vals.get("storm_batched_p99_ms", 0.0) or 0.0, 2),
        "storm_queries_per_compile": round(
            cpu_vals.get("storm_queries_per_compile", 0.0) or 0.0, 2),
        "result_cache_hit_ratio": round(
            cpu_vals.get("storm_result_cache_hit_ratio", 0.0)
            or 0.0, 4),
        "storm_ragged_batches": round(
            cpu_vals.get("storm_ragged_batches", 0.0) or 0.0, 1),
        "budget_s": BUDGET,
        "elapsed_s": round(time.monotonic() - _T0, 1),
        # BASELINE configs[3] direction: q18 at scale, now through the
        # chunk-streamed probe join (exec/streamjoin.py): the leg runs
        # under a memory budget smaller than the probe working set and
        # reports the chunk count, the double-buffer overlap ratio,
        # and the h2d volume next to rows/s.
        f"q18_{q18_schema}_rows_per_sec":
            round(scale_vals.get("q18", 0.0), 1),
        "q18_stream_chunks": round(
            scale_vals.get("q18_stream_chunks", 0.0) or 0.0, 1),
        "q18_stream_overlap_ratio": round(
            scale_vals.get("q18_stream_overlap_ratio", 0.0) or 0.0, 4),
        "q18_stream_h2d_bytes": round(
            scale_vals.get("q18_stream_h2d_bytes", 0.0) or 0.0, 1),
        "q18_budget_bytes": round(
            scale_vals.get("q18_budget_bytes", 0.0) or 0.0, 1),
        "q18_datagen_s": round(
            scale_vals.get("q18_datagen_s", 0.0) or 0.0, 2),
        "q18_sf100": (
            round(scale_vals.get("q18", 0.0), 1)
            if q18_schema == "sf100" and scale_vals.get("q18")
            else "sf100 (~600M-row lineitem, ~34GB of q18 lanes) runs "
                 "as the device_q18 sub-probe on device rounds (the "
                 "chunk-streamed probe join bounds the footprint to "
                 "hash table + 2 chunk buffers); CPU-fallback rounds "
                 f"ran BENCH_Q18_SCHEMA={q18_schema} under a scaled-"
                 "down budget instead"),
    }
    # per-sub-probe causes keep their own keys (device_init /
    # device_first_compile / device_steady / device_q18 — each cause
    # stamped with elapsed/cap); cpu+scale keep the old prefixes
    errs = {**sub_errs,
            **{f"cpu_{k}": v for k, v in cpu_errs.items()},
            # device_q18 causes already live in sub_errs under their
            # own key — don't double-report them with a scale_ prefix
            **({} if on_device else
               {f"scale_{k}": v for k, v in scale_errs.items()})}
    if errs:
        report["errors"] = json.dumps(errs)[:800]
    state["report"] = report
    _emit(report)


if __name__ == "__main__":
    main()
