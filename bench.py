"""Benchmark: TPC-H q1 stage-pipeline throughput, rows/sec/chip.

Measures the flagship pipeline (scan-filter-project-8-way-aggregate over
sf1 lineitem, ~6M rows — BASELINE.json configs[1]) as one jitted device
program on the default backend (the real TPU chip under the driver), and
compares against the same engine on one host CPU worker (the
"vs 1 CPU worker" denominator of the BASELINE.json north star, measured
live in a subprocess rather than assumed).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

ROWS_SCALE = float(os.environ.get("BENCH_SF", "1"))
N_ITERS = int(os.environ.get("BENCH_ITERS", "5"))


def _gen_q1_columns(sf: float):
    """sf lineitem columns needed by q1, straight from the generator's
    vectorized field functions (no host string materialization)."""
    from trino_tpu.connectors.tpch import (_LineFields, _line_counts,
                                           CURRENTDATE, table_rows)
    orders = table_rows("orders", sf)
    order_idx = np.arange(1, orders + 1, dtype=np.int64)
    counts = _line_counts(order_idx)
    order_rep = np.repeat(order_idx, counts)
    line_no = np.concatenate([np.arange(1, c + 1) for c in counts])
    lf = _LineFields(order_rep, line_no.astype(np.int64), sf)
    returned = lf.receiptdate <= CURRENTDATE
    from trino_tpu.connectors.tpch import _u64, _SEED
    ra = (_u64(_SEED["lineitem"] + 20, lf.rid) % np.uint64(2)).astype(
        np.int64)
    rflag = np.where(returned, ra, 2).astype(np.int32)
    lstatus = (lf.shipdate > CURRENTDATE).astype(np.int32)
    return (lf.quantity, lf.extendedprice, lf.discount, lf.tax,
            lf.shipdate.astype(np.int32), rflag, lstatus)


def _bench_once() -> float:
    """Returns rows/sec of the jitted q1 pipeline on this backend."""
    import jax
    import jax.numpy as jnp
    import trino_tpu  # noqa: F401  (x64)
    from __graft_entry__ import _q1_step

    cols = _gen_q1_columns(ROWS_SCALE)
    rows = len(cols[0])
    cap = 1
    while cap < rows:
        cap <<= 1
    padded = [np.pad(c, (0, cap - rows)) for c in cols]
    dev = [jax.device_put(jnp.asarray(c)) for c in padded]
    n = jnp.asarray(rows, jnp.int64)

    step = jax.jit(_q1_step)
    out, ng = step(*dev, n)  # compile + warm
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(N_ITERS):
        t0 = time.perf_counter()
        out, ng = step(*dev, n)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return rows / best


def main():
    if "--cpu-probe" in sys.argv:
        import jax
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps({"cpu_rows_per_sec": _bench_once()}))
        return

    try:
        tpu_rps = _bench_once()
    except Exception as e:  # never leave the driver without a JSON line
        print(json.dumps({"metric": "tpch_q1_sf1_rows_per_sec_per_chip",
                          "value": 0.0, "unit": "rows/s",
                          "vs_baseline": 0.0, "error": str(e)[:200]}))
        return

    cpu_rps = None
    try:
        env = dict(os.environ)
        env["PYTHONPATH"] = ""          # skip the TPU-forcing sitecustomize
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_ITERS"] = "2"
        probe = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cpu-probe"],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        for line in probe.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                cpu_rps = json.loads(line).get("cpu_rows_per_sec")
    except Exception:
        pass

    vs = (tpu_rps / cpu_rps) if cpu_rps else 0.0
    print(json.dumps({
        "metric": "tpch_q1_sf1_rows_per_sec_per_chip",
        "value": round(tpu_rps, 1),
        "unit": "rows/s",
        "vs_baseline": round(vs, 2),
        "baseline": "same engine, 1 host CPU worker "
                    f"({round(cpu_rps, 1) if cpu_rps else 'n/a'} rows/s); "
                    "north star is >=5x (BASELINE.json)",
    }))


if __name__ == "__main__":
    main()
