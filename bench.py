"""Benchmark: TPC-H q1 stage-pipeline throughput, rows/sec/chip.

Measures the flagship pipeline (scan-filter-project-8-way-aggregate over
sf1 lineitem, ~6M rows — BASELINE.json configs[1]) as one jitted device
program on the default backend (the real TPU chip under the driver), and
compares against the same engine on one host CPU worker (the
"vs 1 CPU worker" denominator of the BASELINE.json north star, measured
live in a subprocess rather than assumed).

Robustness (round-1 postmortem: a transient axon PJRT init failure was
caught and silently reported as 0.0 rows/s): each measurement now runs in
its own subprocess — a failed backend init cannot poison this process —
and the TPU probe is retried with backoff before giving up. Whatever
happens, exactly ONE JSON line is printed:
{"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

ROWS_SCALE = float(os.environ.get("BENCH_SF", "1"))
N_ITERS = int(os.environ.get("BENCH_ITERS", "5"))
TPU_ATTEMPTS = int(os.environ.get("BENCH_TPU_ATTEMPTS", "3"))
PROBE_TIMEOUT = int(os.environ.get("BENCH_PROBE_TIMEOUT", "1200"))


def _gen_q1_columns(sf: float):
    """sf lineitem columns needed by q1, straight from the generator's
    vectorized field functions (no host string materialization)."""
    from trino_tpu.connectors.tpch import (_LineFields, _line_counts,
                                           CURRENTDATE, table_rows)
    orders = table_rows("orders", sf)
    order_idx = np.arange(1, orders + 1, dtype=np.int64)
    counts = _line_counts(order_idx)
    order_rep = np.repeat(order_idx, counts)
    line_no = np.concatenate([np.arange(1, c + 1) for c in counts])
    lf = _LineFields(order_rep, line_no.astype(np.int64), sf)
    returned = lf.receiptdate <= CURRENTDATE
    from trino_tpu.connectors.tpch import _u64, _SEED
    ra = (_u64(_SEED["lineitem"] + 20, lf.rid) % np.uint64(2)).astype(
        np.int64)
    rflag = np.where(returned, ra, 2).astype(np.int32)
    lstatus = (lf.shipdate > CURRENTDATE).astype(np.int32)
    return (lf.quantity, lf.extendedprice, lf.discount, lf.tax,
            lf.shipdate.astype(np.int32), rflag, lstatus)


def _bench_once() -> float:
    """Returns rows/sec of the jitted q1 pipeline on this backend."""
    import jax
    import jax.numpy as jnp
    import trino_tpu  # noqa: F401  (x64)
    from __graft_entry__ import _q1_step

    cols = _gen_q1_columns(ROWS_SCALE)
    rows = len(cols[0])
    cap = 1
    while cap < rows:
        cap <<= 1
    padded = [np.pad(c, (0, cap - rows)) for c in cols]
    dev = [jax.device_put(jnp.asarray(c)) for c in padded]
    n = jnp.asarray(rows, jnp.int64)

    def fetch(out, ng):
        # the timed unit ends with results ON HOST: under the axon
        # tunnel block_until_ready returns before execution completes
        # (measured: 0.27ms "latency" for a 9s computation), so a real
        # host readback is the only honest fence
        return {k: np.asarray(v) for k, v in out.items()}, int(ng)

    step = jax.jit(_q1_step)
    fetch(*step(*dev, n))  # compile + warm
    best = float("inf")
    for _ in range(N_ITERS):
        t0 = time.perf_counter()
        fetch(*step(*dev, n))
        best = min(best, time.perf_counter() - t0)
    return rows / best


def _bench_engine_once() -> float:
    """rows/sec of SQL TPC-H q1 @ sf1 through the FULL engine path
    (parse -> plan -> optimize -> execute) — the honest engine-level
    number BASELINE.json asks for, alongside the hand-fused micro
    (the reference's HandTpchQuery1.java vs the operator path)."""
    import trino_tpu  # noqa: F401
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    from trino_tpu.runner import LocalQueryRunner
    from trino_tpu.session import Session

    sf = {1.0: "sf1", 0.01: "tiny"}.get(ROWS_SCALE, "sf1")
    r = LocalQueryRunner(session=Session(catalog="tpch", schema=sf))
    rows = int(r.execute(
        "SELECT count(*) FROM lineitem").rows[0][0])
    r.execute(TPCH_QUERIES[1])      # compile + warm every fragment
    best = float("inf")
    for _ in range(max(N_ITERS // 2, 1)):
        t0 = time.perf_counter()
        res = r.execute(TPCH_QUERIES[1])
        assert len(res.rows) >= 4
        best = min(best, time.perf_counter() - t0)
    return rows / best


def _probe_subprocess(extra_env, iters=None, mode="micro"):
    """Run --probe in a fresh interpreter; returns (rows_per_sec, err)."""
    env = dict(os.environ)
    env.update(extra_env)
    env["BENCH_MODE"] = mode
    if iters is not None:
        env["BENCH_ITERS"] = str(iters)
    try:
        probe = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--probe"],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return None, f"probe timed out after {PROBE_TIMEOUT}s"
    for line in probe.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                payload = json.loads(line)
            except json.JSONDecodeError:
                continue
            if payload.get("rows_per_sec"):
                return payload["rows_per_sec"], None
            if payload.get("error"):
                return None, payload["error"]
    tail = (probe.stderr or probe.stdout or "").strip().splitlines()[-6:]
    return None, " | ".join(t.strip() for t in tail)[-500:]


def main():
    if "--probe" in sys.argv:
        # Honor an explicit platform request (the CPU-worker baseline
        # leg); otherwise run on the environment's default backend —
        # the real chip under the driver.
        want = os.environ.get("BENCH_PLATFORM")
        if want:
            import jax
            jax.config.update("jax_platforms", want)
        try:
            if os.environ.get("BENCH_MODE") == "engine":
                rps = _bench_engine_once()
            else:
                rps = _bench_once()
            print(json.dumps({"rows_per_sec": rps}))
        except Exception as e:
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:400]}))
            raise
        return

    cpu_env = {"PYTHONPATH": "",   # skip the TPU-forcing sitecustomize
               "JAX_PLATFORMS": "cpu",
               "BENCH_PLATFORM": "cpu"}

    # --- device legs: fresh subprocess per attempt, with retry --------
    tpu_eng, eng_err = None, None
    for attempt in range(TPU_ATTEMPTS):
        tpu_eng, eng_err = _probe_subprocess({}, mode="engine")
        if tpu_eng:
            break
        if attempt < TPU_ATTEMPTS - 1:
            time.sleep(min(30, 5 * (attempt + 1)))
    tpu_micro, micro_err = _probe_subprocess({}, mode="micro")

    if not tpu_eng and not tpu_micro:
        # device unreachable: report the failure, but still record the
        # CPU legs so the round has diagnostic numbers
        cpu_eng, _ = _probe_subprocess(cpu_env, iters=2, mode="engine")
        cpu_micro, _ = _probe_subprocess(cpu_env, iters=2, mode="micro")
        print(json.dumps({"metric": "tpch_q1_sf1_engine_rows_per_sec",
                          "value": 0.0, "unit": "rows/s",
                          "vs_baseline": 0.0,
                          "error": (eng_err or micro_err
                                    or "unknown")[:400],
                          "attempts": TPU_ATTEMPTS,
                          "cpu_engine_rows_per_sec":
                              round(cpu_eng or 0.0, 1),
                          "cpu_micro_rows_per_sec":
                              round(cpu_micro or 0.0, 1)}))
        return

    # --- CPU-worker baseline legs (north-star denominator) ------------
    cpu_eng, cpu_eng_err = _probe_subprocess(cpu_env, iters=2,
                                             mode="engine")
    cpu_micro, _ = _probe_subprocess(cpu_env, iters=2, mode="micro")

    value = tpu_eng or 0.0
    vs = (value / cpu_eng) if (value and cpu_eng) else 0.0
    report = {
        "metric": "tpch_q1_sf1_engine_rows_per_sec",
        "value": round(value, 1),
        "unit": "rows/s",
        "vs_baseline": round(vs, 2),
        "baseline": "SQL q1 sf1 through the same engine on 1 host CPU "
                    f"worker ({round(cpu_eng, 1) if cpu_eng else 'n/a'} "
                    "rows/s); north star >=5x (BASELINE.json)",
        "micro_rows_per_sec": round(tpu_micro or 0.0, 1),
        "micro_vs_cpu": (round(tpu_micro / cpu_micro, 2)
                         if tpu_micro and cpu_micro else 0.0),
    }
    errs = [e for e in (eng_err, cpu_eng_err) if e]
    if errs:
        report["error"] = " | ".join(errs)[:400]
    print(json.dumps(report))


if __name__ == "__main__":
    main()
