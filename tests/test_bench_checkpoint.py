"""bench.py sub-probe checkpoint: a timed-out/crashed round resumes
where it died — completed (even PARTIAL) sub-probes replay from
~/.cache/trino_tpu/bench_subprobes.json instead of re-burning their
time cap, scoped to one BENCH_ROUND_ID and a TTL so a stale file can
never masquerade as this round's progress."""

import glob
import json
import os

import pytest

import bench


@pytest.fixture(autouse=True)
def _sandboxed_ckpt(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "CACHE_DIR", str(tmp_path))
    monkeypatch.setattr(bench, "_CKPT_PATH",
                        str(tmp_path / "bench_subprobes.json"))
    monkeypatch.setattr(bench, "_ROUND_ID", "round-a")
    monkeypatch.setattr(bench, "_CKPT_TTL", 7200.0)


def test_ckpt_roundtrip_same_round():
    sub = {"cpu_baseline": {"vals": {"engine": 123.0},
                            "errs": {}, "elapsed_s": 9.5}}
    bench._ckpt_save(sub)
    assert bench._ckpt_load() == sub


def test_ckpt_round_mismatch_ignored(monkeypatch):
    bench._ckpt_save({"cpu_baseline": {"vals": {"x": 1}}})
    monkeypatch.setattr(bench, "_ROUND_ID", "round-b")
    assert bench._ckpt_load() == {}


def test_ckpt_ttl_expiry_ignored(monkeypatch):
    bench._ckpt_save({"cpu_baseline": {"vals": {"x": 1}}})
    monkeypatch.setattr(bench, "_CKPT_TTL", 0.0)
    assert bench._ckpt_load() == {}


def test_ckpt_corrupt_file_is_empty_not_fatal():
    with open(bench._CKPT_PATH, "w") as f:
        f.write("{not json")
    assert bench._ckpt_load() == {}
    # and a save over the corrupt file heals it
    bench._ckpt_save({"device_init": {"vals": {"init": 1.0}}})
    assert "device_init" in bench._ckpt_load()


def test_ckpt_save_is_atomic_no_tmp_litter():
    bench._ckpt_save({"a": {"vals": {}}})
    bench._ckpt_save({"a": {"vals": {}}, "b": {"vals": {}}})
    assert glob.glob(bench._CKPT_PATH + ".*.tmp") == []
    with open(bench._CKPT_PATH) as f:
        d = json.load(f)
    assert d["round"] == "round-a" and set(d["subprobes"]) == {"a", "b"}
