"""Coordinator -> remote worker dispatch for REAL queries.

Reference parity: the DistributedQueryRunner tier —
server/remotetask/HttpRemoteTask.java:103 (fragment POST),
execution/SqlTaskManager.java:370-403 (worker execution),
operator/ExchangeClient.java:149 (page pulls). A coordinator process
plans the query, ships serialized leaf fragments (plan/serde.py) to two
worker PROCESSES with (part, nparts) split shares, pulls pages, and
combines locally; results must equal LocalQueryRunner exactly.
"""

import multiprocessing as mp

import pytest

from trino_tpu.exec.remote import DistributedHostQueryRunner, RemoteScheduler
from trino_tpu.plan.serde import from_jsonable, to_jsonable
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.server.task_worker import spawn_worker_env, worker_main
from trino_tpu.session import Session


@pytest.fixture(scope="module")
def workers():
    ctx = mp.get_context("spawn")
    procs = []
    uris = []
    with spawn_worker_env():
        for _ in range(2):
            parent, child = ctx.Pipe()
            p = ctx.Process(target=worker_main, args=(child, "cpu"),
                            daemon=True)
            p.start()
            if not parent.poll(180):
                raise RuntimeError("worker child did not start")
            uris.append(f"http://127.0.0.1:{parent.recv()}")
            procs.append(p)
    yield uris
    for p in procs:
        p.terminate()


def _check(workers, sql, approx_cols=()):
    dist = DistributedHostQueryRunner(
        workers, session=Session(catalog="tpch", schema="tiny"))
    local = LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny"))
    got = dist.execute(sql)
    exp = local.execute(sql)
    assert got.columns == exp.columns
    assert len(got.rows) == len(exp.rows)
    for g, e in zip(got.rows, exp.rows):
        for i, (gv, ev) in enumerate(zip(g, e)):
            if i in approx_cols:
                assert gv == pytest.approx(ev, rel=1e-9)
            else:
                assert gv == ev


def test_plan_serde_roundtrips_tpch_plans():
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    from trino_tpu.planner.logical import LogicalPlanner
    from trino_tpu.planner.optimizer import optimize
    r = LocalQueryRunner(session=Session(catalog="tpch", schema="tiny"))
    from trino_tpu.sql.parser import parse_statement
    for qn in (1, 3, 6, 18):
        stmt = parse_statement(TPCH_QUERIES[qn])
        plan = optimize(LogicalPlanner(r.catalogs, r.session).plan(stmt),
                        r.catalogs, r.session)
        assert from_jsonable(to_jsonable(plan)) == plan


def test_fragmenter_cuts_scan_chains():
    """Plan shape check without processes: q3 produces one fragment per
    base table; q1 pushes a partial aggregation into its fragment."""
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    from trino_tpu.plan.nodes import AggregationNode
    from trino_tpu.planner.logical import LogicalPlanner
    from trino_tpu.planner.optimizer import optimize
    from trino_tpu.sql.parser import parse_statement
    r = LocalQueryRunner(session=Session(catalog="tpch", schema="tiny"))
    sched = RemoteScheduler.__new__(RemoteScheduler)  # no workers needed
    sched.catalogs, sched.session = r.catalogs, r.session

    stmt = parse_statement(TPCH_QUERIES[3])
    plan = optimize(LogicalPlanner(r.catalogs, r.session).plan(stmt),
                    r.catalogs, r.session)
    frags = []
    sched._cut(plan, frags)
    assert len(frags) == 3      # customer, orders, lineitem chains

    stmt = parse_statement(TPCH_QUERIES[1])
    plan = optimize(LogicalPlanner(r.catalogs, r.session).plan(stmt),
                    r.catalogs, r.session)
    frags = []
    sched._cut(plan, frags)
    assert len(frags) == 1
    assert isinstance(frags[0].plan, AggregationNode)  # partial pushed


def test_remote_q6(workers):
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    _check(workers, TPCH_QUERIES[6], approx_cols=(0,))


def test_remote_q1(workers):
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    _check(workers, TPCH_QUERIES[1], approx_cols=(2, 3, 4, 5, 6, 7, 8))


@pytest.mark.slow      # ~12s; test_remote_q1 + decimal/strings keep
# the HTTP dispatch path tier-1
def test_remote_q3(workers):
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    _check(workers, TPCH_QUERIES[3], approx_cols=(1,))


def test_remote_decimal_and_strings(workers):
    _check(workers,
           "SELECT n_name, count(*) FROM nation "
           "JOIN region ON n_regionkey = r_regionkey "
           "WHERE r_name = 'ASIA' GROUP BY n_name ORDER BY n_name")


def test_remote_topn_pushdown(workers):
    _check(workers,
           "SELECT o_orderkey, o_totalprice FROM orders "
           "ORDER BY o_totalprice DESC LIMIT 10", approx_cols=(1,))


def test_remote_cancel_aborts_task(workers):
    """A set cancel event makes the page pull abort the remote task and
    raise instead of blocking until completion."""
    import threading
    from trino_tpu.server.task_worker import RemoteTaskClient
    c = RemoteTaskClient(workers[0])
    c.submit("cancel-me", "SELECT count(*) FROM lineitem l1, nation",
             catalog="tpch", schema="tiny")
    ev = threading.Event()
    ev.set()
    with pytest.raises(RuntimeError, match="canceled"):
        c.pages("cancel-me", cancel=ev)


def test_remote_decimal_aggregates_exact(workers):
    """Decimal sum/avg through remote partial/final must be bit-exact
    vs local (no approx): the avg reconstruction divides the Int128 sum
    with the decimal kernel, not float."""
    dist = DistributedHostQueryRunner(
        workers, session=Session(catalog="tpcds", schema="tiny"))
    local = LocalQueryRunner(
        session=Session(catalog="tpcds", schema="tiny"))
    sql = ("SELECT ss_store_sk, sum(ss_ext_sales_price), "
           "avg(ss_sales_price), min(ss_net_paid), max(ss_net_paid) "
           "FROM store_sales GROUP BY ss_store_sk ORDER BY ss_store_sk")
    got = dist.execute(sql)
    exp = local.execute(sql)
    assert got.rows == exp.rows     # exact, including NULL groups


def test_http_coordinator_dispatches_to_workers(workers):
    """The FULL reference shape: client -> coordinator HTTP -> worker
    HTTP -> pages back -> client rows (server/coordinator.py routing
    through exec/remote.py when a worker fleet is registered)."""
    from trino_tpu.client import StatementClient
    from trino_tpu.server.coordinator import Coordinator
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    coord = Coordinator(worker_uris=list(workers)).start()
    try:
        c = StatementClient(coord.base_uri, catalog="tpch",
                            schema="tiny")
        got = c.execute(TPCH_QUERIES[3])
        exp = LocalQueryRunner(
            session=Session(catalog="tpch", schema="tiny")).execute(
                TPCH_QUERIES[3])
        assert [r[0] for r in got.rows] == [r[0] for r in exp.rows]
        nodes = c.execute("SELECT count(*) FROM system.runtime.nodes")
        assert nodes.rows[0][0] == 3      # coordinator + 2 workers
    finally:
        coord.stop()
