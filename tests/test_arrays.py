"""ARRAY type + UNNEST + array_agg tests.

Reference parity: spi/block/ArrayBlock.java (offsets + flat elements),
operator/unnest/UnnestOperator.java, operator/scalar/ArraySubscript /
ArrayFunctions, operator/aggregation/ArrayAggregationFunction.
"""

import pytest

from trino_tpu.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def q(runner, sql):
    return runner.execute(sql).rows


def test_array_constructor(runner):
    assert q(runner, "SELECT ARRAY[1, 2, 3]") == [[[1, 2, 3]]]
    assert q(runner, "SELECT ARRAY['a', 'b']") == [[['a', 'b']]]
    assert q(runner, "SELECT ARRAY[1, NULL, 3]") == [[[1, None, 3]]]
    assert q(runner, "SELECT ARRAY[1.5, 2]") == [[[1.5, 2.0]]]


def test_array_subscript_element_at(runner):
    got = q(runner, "SELECT ARRAY[10, 20, 30][2], "
                    "element_at(ARRAY[10, 20], -1), "
                    "element_at(ARRAY[10, 20], 7), "
                    "cardinality(ARRAY[1, 2, 3, 4])")
    assert got == [[20, 20, None, 4]]


def test_array_per_row(runner):
    got = q(runner, "SELECT ARRAY[n_nationkey, n_regionkey] "
                    "FROM tpch.tiny.nation WHERE n_nationkey < 3 "
                    "ORDER BY n_nationkey")
    assert got == [[[0, 0]], [[1, 1]], [[2, 1]]]


def test_unnest_values(runner):
    assert q(runner, "SELECT x FROM UNNEST(ARRAY[1, 2, 3]) t(x)") == \
        [[1], [2], [3]]


def test_unnest_with_ordinality(runner):
    got = q(runner, "SELECT x, o FROM UNNEST(ARRAY['a', 'b', 'c']) "
                    "WITH ORDINALITY t(x, o)")
    assert got == [['a', 1], ['b', 2], ['c', 3]]


def test_unnest_lateral(runner):
    got = q(runner, "SELECT n_name, e FROM tpch.tiny.nation "
                    "CROSS JOIN UNNEST(ARRAY[n_nationkey, n_regionkey]) "
                    "t(e) WHERE n_nationkey < 2 ORDER BY n_name, e")
    assert got == [['ALGERIA', 0], ['ALGERIA', 0],
                   ['ARGENTINA', 1], ['ARGENTINA', 1]]


def test_unnest_multi_array_zip(runner):
    # shorter arrays null-pad (UnnestOperator zip semantics)
    got = q(runner, "SELECT a, b FROM "
                    "UNNEST(ARRAY[1, 2, 3], ARRAY['x']) t(a, b)")
    assert got == [[1, 'x'], [2, None], [3, None]]


def test_array_agg_global_and_grouped(runner):
    assert q(runner, "SELECT array_agg(x) FROM (VALUES 3, 1, 2) t(x)") \
        == [[[3, 1, 2]]]
    got = q(runner, "SELECT n_regionkey, array_agg(n_name) "
                    "FROM tpch.tiny.nation WHERE n_regionkey < 2 "
                    "GROUP BY n_regionkey ORDER BY 1")
    assert got[0][1][0] == 'ALGERIA'
    assert len(got[0][1]) == 5 and len(got[1][1]) == 5


def test_array_agg_filter_and_nulls(runner):
    got = q(runner, "SELECT array_agg(x) FILTER (WHERE x > 1) "
                    "FROM (VALUES 1, 2, NULL, 3) t(x)")
    assert got == [[[2, 3]]]
    # NULL values are collected when not filtered out
    got = q(runner, "SELECT array_agg(x) FROM (VALUES 1, NULL) t(x)")
    assert got == [[[1, None]]]


def test_array_agg_unnest_roundtrip(runner):
    got = q(runner, """
        SELECT rk, sum(v) FROM (
          SELECT a.rk rk, e v FROM
            (SELECT n_regionkey rk, array_agg(n_nationkey) arr
             FROM tpch.tiny.nation GROUP BY n_regionkey) a
          CROSS JOIN UNNEST(a.arr) u(e)
        ) GROUP BY rk ORDER BY rk""")
    want = q(runner, "SELECT n_regionkey, sum(n_nationkey) "
                     "FROM tpch.tiny.nation GROUP BY n_regionkey "
                     "ORDER BY 1")
    assert got == want


def test_unnest_empty_and_null_arrays(runner):
    got = q(runner, "SELECT e FROM (VALUES 2) t(x) "
                    "CROSS JOIN UNNEST(ARRAY[x]) u(e) WHERE x < 0")
    assert got == []
