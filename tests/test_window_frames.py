"""Explicit window frames: ROWS / RANGE / GROUPS BETWEEN bounds.

Reference parity: operator/window/FrameInfo.java + WindowPartition
frame machinery + AggregateWindowFunction; oracle values computed by
hand per the SQL standard.
"""

import pytest

from trino_tpu.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def q(runner, sql):
    return runner.execute(sql).rows


BASE = ("FROM (VALUES (1, 10), (2, 20), (3, 30), (4, 40), (5, 50)) "
        "AS t(k, v)")


def test_rows_preceding_following(runner):
    got = q(runner,
            "SELECT k, sum(v) OVER (ORDER BY k ROWS BETWEEN 1 "
            f"PRECEDING AND 1 FOLLOWING) {BASE} ORDER BY k")
    assert got == [[1, 30], [2, 60], [3, 90], [4, 120], [5, 90]]


def test_rows_current_and_following(runner):
    got = q(runner,
            "SELECT k, sum(v) OVER (ORDER BY k ROWS BETWEEN CURRENT "
            f"ROW AND UNBOUNDED FOLLOWING) {BASE} ORDER BY k")
    assert got == [[1, 150], [2, 140], [3, 120], [4, 90], [5, 50]]


def test_rows_moving_avg_min_max(runner):
    got = q(runner,
            "SELECT k, avg(v) OVER (ORDER BY k ROWS BETWEEN 2 "
            "PRECEDING AND CURRENT ROW), "
            "min(v) OVER (ORDER BY k ROWS BETWEEN 1 PRECEDING AND "
            "1 FOLLOWING), "
            "max(v) OVER (ORDER BY k ROWS BETWEEN 1 PRECEDING AND "
            f"1 FOLLOWING) {BASE} ORDER BY k")
    assert got == [
        [1, 10.0, 10, 20], [2, 15.0, 10, 30], [3, 20.0, 20, 40],
        [4, 30.0, 30, 50], [5, 40.0, 40, 50]]


def test_rows_count_empty_frame(runner):
    got = q(runner,
            "SELECT k, count(v) OVER (ORDER BY k ROWS BETWEEN 3 "
            "FOLLOWING AND 4 FOLLOWING), "
            "sum(v) OVER (ORDER BY k ROWS BETWEEN 3 FOLLOWING AND "
            f"4 FOLLOWING) {BASE} ORDER BY k")
    assert got == [[1, 2, 90], [2, 1, 50], [3, 0, None], [4, 0, None],
                   [5, 0, None]]


def test_range_value_offsets(runner):
    got = q(runner,
            "SELECT k, sum(v) OVER (ORDER BY k RANGE BETWEEN 2 "
            f"PRECEDING AND CURRENT ROW) {BASE} ORDER BY k")
    assert got == [[1, 10], [2, 30], [3, 60], [4, 90], [5, 120]]


def test_range_peers_included(runner):
    # ties: RANGE CURRENT ROW spans the whole peer group
    got = q(runner,
            "SELECT v, sum(v) OVER (ORDER BY g RANGE BETWEEN "
            "UNBOUNDED PRECEDING AND CURRENT ROW) FROM (VALUES "
            "(1, 10), (1, 20), (2, 30)) AS t(g, v) ORDER BY v")
    assert got == [[10, 30], [20, 30], [30, 60]]


def test_groups_frames(runner):
    got = q(runner,
            "SELECT g, v, sum(v) OVER (ORDER BY g GROUPS BETWEEN 1 "
            "PRECEDING AND CURRENT ROW) FROM (VALUES "
            "(1, 10), (1, 20), (2, 30), (3, 40)) AS t(g, v) "
            "ORDER BY g, v")
    assert got == [[1, 10, 30], [1, 20, 30], [2, 30, 60],
                   [3, 40, 70]]


def test_first_last_nth_with_frames(runner):
    got = q(runner,
            "SELECT k, first_value(v) OVER (ORDER BY k ROWS BETWEEN "
            "1 PRECEDING AND 1 FOLLOWING), "
            "last_value(v) OVER (ORDER BY k ROWS BETWEEN 1 PRECEDING "
            "AND 1 FOLLOWING), "
            "nth_value(v, 2) OVER (ORDER BY k ROWS BETWEEN 1 "
            f"PRECEDING AND 1 FOLLOWING) {BASE} ORDER BY k")
    assert got == [[1, 10, 20, 20], [2, 10, 30, 20], [3, 20, 40, 30],
                   [4, 30, 50, 40], [5, 40, 50, 50]]


def test_frames_with_partitions(runner):
    got = q(runner,
            "SELECT p, k, sum(v) OVER (PARTITION BY p ORDER BY k "
            "ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) FROM (VALUES "
            "('a', 1, 10), ('a', 2, 20), ('b', 1, 5), ('b', 2, 7)) "
            "AS t(p, k, v) ORDER BY p, k")
    assert got == [['a', 1, 10], ['a', 2, 30], ['b', 1, 5],
                   ['b', 2, 12]]


def test_frames_with_nulls(runner):
    got = q(runner,
            "SELECT k, sum(v) OVER (ORDER BY k ROWS BETWEEN 1 "
            "PRECEDING AND CURRENT ROW) FROM (VALUES (1, 10), "
            "(2, CAST(NULL AS bigint)), (3, 30)) AS t(k, v) "
            "ORDER BY k")
    assert got == [[1, 10], [2, 10], [3, 30]]
