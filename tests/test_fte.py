"""Fault-tolerant execution: spooled exchange, task retries, straggler
speculation, fault injection.

Reference parity: Trino's retry-policy=TASK mode — spooling exchange
(trino-exchange-filesystem), task-attempt bookkeeping
(EventDrivenFaultTolerantQueryScheduler), and speculative execution —
exercised here with real HTTP worker servers plus fault-injection stubs
that kill / 500 / hang the results pull mid-query.
"""

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from trino_tpu.exec import QueryError
from trino_tpu.exec.remote import DistributedHostQueryRunner
from trino_tpu.fte.retry import (RetryController, RetryPolicy,
                                 backoff_delay, pick_worker)
from trino_tpu.fte.speculate import StragglerDetector
from trino_tpu.fte.spool import LocalDirSpool
from trino_tpu.obs.metrics import METRICS
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.server.failure import HeartbeatFailureDetector
from trino_tpu.server.task_worker import (RemoteTaskClient,
                                          TaskWorkerServer)
from trino_tpu.session import Session

SQL = ("SELECT n_name, count(*) FROM nation "
       "JOIN region ON n_regionkey = r_regionkey "
       "WHERE r_name = 'ASIA' GROUP BY n_name ORDER BY n_name")


def _counter(name: str) -> float:
    return METRICS.counter(name).value()


# --------------------------------------------------------------------------
# spool: commit / read / first-commit-wins / TTL
# --------------------------------------------------------------------------

def test_spool_commit_and_read(tmp_path):
    spool = LocalDirSpool(str(tmp_path))
    frames = [b"page-zero", b"page-one"]
    assert spool.commit("q1", 0, 0, 0, frames) == 0
    assert spool.committed_attempt("q1", 0, 0) == 0
    assert spool.read("q1", 0, 0) == frames
    assert spool.read("q1", 0, 1) is None        # nothing committed
    spool.release("q1")
    assert spool.read("q1", 0, 0) is None


def test_spool_duplicate_attempt_discarded(tmp_path):
    """Idempotent writes: the second attempt's output is dropped, not
    double-counted — the winner's frames survive verbatim."""
    spool = LocalDirSpool(str(tmp_path))
    before = _counter("trino_tpu_spool_duplicate_attempts_total")
    assert spool.commit("q1", 2, 1, 0, [b"winner"]) == 0
    # a late duplicate (retry or speculative loser) reports the winner
    assert spool.commit("q1", 2, 1, 1, [b"loser"]) == 0
    assert spool.read("q1", 2, 1) == [b"winner"]
    assert _counter(
        "trino_tpu_spool_duplicate_attempts_total") == before + 1


def test_spool_corrupt_marker_usurped(tmp_path):
    """A crashed commit can no longer leave an empty marker (the claim
    hard-links a fully written file), but a legacy/corrupt one must be
    usurped by the next attempt — never poisoning the part, and never
    costing the new attempt its own frames."""
    import os
    spool = LocalDirSpool(str(tmp_path))
    tdir = spool._task_dir("q", 0, 0)
    os.makedirs(tdir)
    open(os.path.join(tdir, "COMMITTED"), "w").close()  # empty marker
    assert spool.committed_attempt("q", 0, 0) is None
    assert spool.commit("q", 0, 0, 1, [b"x"]) == 1
    assert spool.read("q", 0, 0) == [b"x"]


def test_spool_release_tombstone(tmp_path):
    """A late loser attempt completing after the query released its
    spool must not resurrect the query dir (disk leak until TTL)."""
    spool = LocalDirSpool(str(tmp_path))
    spool.commit("q", 0, 0, 0, [b"x"])
    spool.release("q")
    spool.commit("q", 0, 0, 1, [b"y"])
    assert spool.read("q", 0, 0) is None
    assert not (tmp_path / "q").exists()


def test_spool_ttl_cleanup(tmp_path):
    import os
    spool = LocalDirSpool(str(tmp_path), ttl_s=3600)
    spool.commit("old_query", 0, 0, 0, [b"x"])
    spool.commit("new_query", 0, 0, 0, [b"y"])
    stale = time.time() - 7200
    os.utime(tmp_path / "old_query", (stale, stale))
    assert spool.cleanup() == 1
    assert spool.read("old_query", 0, 0) is None
    assert spool.read("new_query", 0, 0) == [b"y"]


# --------------------------------------------------------------------------
# retry policy engine
# --------------------------------------------------------------------------

def test_retry_policy_from_session():
    s = Session()
    assert not RetryPolicy.from_session(s).enabled
    s.set("retry_policy", "TASK")
    s.set("task_retry_attempts", 3)
    s.set("retry_initial_delay_ms", 10)
    p = RetryPolicy.from_session(s)
    assert p.enabled and p.task_retry_attempts == 3
    assert p.backoff_initial_s == pytest.approx(0.01)


def test_retry_controller_budgets():
    p = RetryPolicy(policy="TASK", task_retry_attempts=3,
                    query_retry_attempts=3)
    c = RetryController(p)
    # task budget: 3 total attempts = 2 retries
    assert c.record_failure((0, 0))
    assert c.record_failure((0, 0))
    assert not c.record_failure((0, 0))
    # query budget: 3 extra attempts already spent (2 retries + 1 spec)
    assert c.grant_speculation((0, 1))
    assert not c.record_failure((0, 1))
    assert c.retries_granted == 3

    none = RetryController(RetryPolicy())
    assert not none.record_failure((0, 0))   # NONE: no retries, ever
    # speculation is orthogonal to the retry policy (budget-bounded)
    assert none.grant_speculation((0, 0))


def test_backoff_deterministic_and_bounded():
    p = RetryPolicy(policy="TASK", backoff_initial_s=0.1,
                    backoff_max_s=1.0)
    d1 = backoff_delay(p, 1, "q.0.0")
    assert d1 == backoff_delay(p, 1, "q.0.0")      # deterministic
    assert 0.05 <= d1 < 0.1                        # jitter in [0.5, 1)
    assert d1 != backoff_delay(p, 1, "q.0.1")      # de-correlated
    assert backoff_delay(p, 9, "q.0.0") < 1.0      # capped


def test_pick_worker_rotation_and_exclusions():
    # attempt 0 lands on the home worker
    assert pick_worker(3, home=1, attempt=0) == 1
    # a retry moves off the home worker deterministically
    assert pick_worker(3, home=1, attempt=1) == 2
    # excluded workers are skipped...
    assert pick_worker(3, 1, 1, excluded=frozenset({2})) == 0
    # ...the detector's dead nodes too...
    assert pick_worker(3, 1, 1, excluded=frozenset({2}),
                       is_alive=lambda wi: wi != 0) == 1
    # ...and with everything excluded the scheduler still gets a slot
    assert pick_worker(2, 0, 1, excluded=frozenset({0, 1})) == 1


def test_straggler_detector():
    d = StragglerDetector(multiplier=2.0, min_samples=2,
                          min_runtime_s=0.1)
    assert not d.is_straggler(0, 60.0)     # no samples yet
    d.record(0, 0.2)
    assert not d.is_straggler(0, 60.0)     # below min_samples
    d.record(0, 0.3)
    assert d.median(0) == pytest.approx(0.3)
    assert not d.is_straggler(0, 0.05)     # under the absolute floor
    assert not d.is_straggler(0, 0.5)      # under 2x median
    assert d.is_straggler(0, 0.7)
    assert not d.is_straggler(1, 0.7)      # other fragments unaffected


def test_failure_detector_verdict_expires_when_stale():
    """A feedback-only detector (no probe loop) must not exclude a
    node forever on transient task failures: after four quiet decay
    windows the stale verdict expires and the node earns a fresh
    chance."""
    det = HeartbeatFailureDetector(warmup_probes=1)
    det.record_task_failure("http://w1", "boom")
    assert "http://w1" in det.failed()
    st = det._stats["http://w1"]
    st.last_update = time.time() - 4.1 * st.decay_seconds
    assert det.is_alive("http://w1")


# --------------------------------------------------------------------------
# fault injection: kill / 500 / hang a worker mid-query
# --------------------------------------------------------------------------

class _QuietServer(ThreadingHTTPServer):
    def handle_error(self, request, client_address):   # injected faults
        pass                                           # are not noise


class _FaultyWorker:
    """A fake worker that accepts task POSTs, then sabotages the
    results pull: mode 'kill' drops the connection and stops serving
    (a worker process dying mid-query), '500' answers every pull with
    an injected error, 'hang' answers 202 forever (a wedged task)."""

    def __init__(self, mode: str):
        faulty = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                body = b'{"taskId": "x", "state": "RUNNING"}'
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if faulty.mode == "hang":
                    self.send_response(202)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                if faulty.mode == "500":
                    self.send_error(500, "injected worker failure")
                    return
                # kill: die mid-request, then refuse all connections.
                # SHUT_RDWR forces an immediate EOF/RST on the client
                # side — without it a half-closed socket can leave the
                # puller blocked until its per-request timeout
                import socket as _socket
                threading.Thread(target=faulty.httpd.shutdown,
                                 daemon=True).start()
                try:
                    self.connection.shutdown(_socket.SHUT_RDWR)
                except OSError:
                    pass
                raise ConnectionResetError("killed mid-query")

            def do_DELETE(self):
                self.send_response(204)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self.mode = mode
        self.httpd = _QuietServer(("127.0.0.1", 0), Handler)
        self.base_uri = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        try:
            self.httpd.shutdown()
            self.httpd.server_close()
        except OSError:
            pass


@pytest.fixture(scope="module")
def workers():
    """Two REAL in-process worker servers (full HTTP + serde path)."""
    w1, w2 = TaskWorkerServer().start(), TaskWorkerServer().start()
    yield [w1.base_uri, w2.base_uri]
    w1.stop()
    w2.stop()


@pytest.fixture(scope="module")
def expected():
    return LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny")).execute(SQL)


def _task_session(**props) -> Session:
    s = Session(catalog="tpch", schema="tiny")
    s.set("retry_policy", "TASK")
    s.set("retry_initial_delay_ms", 10)
    # bound every attempt so a worst-case half-open socket on an
    # injected fault resolves in seconds, not the 600s default
    s.set("remote_task_timeout", 30)
    for k, v in props.items():
        s.set(k, v)
    return s


def test_worker_killed_mid_query_retries_and_completes(workers,
                                                       expected):
    """The acceptance scenario: one worker dies mid-execution; under
    retry_policy=TASK the query completes with the SAME result as the
    no-failure run, the retry shows in task_retries_total, and the
    query trace carries a retry span."""
    killed = _FaultyWorker("kill")
    detector = HeartbeatFailureDetector(warmup_probes=1)
    before = _counter("trino_tpu_task_retries_total")
    try:
        runner = DistributedHostQueryRunner(
            [killed.base_uri] + workers,
            session=_task_session(),
            collect_node_stats=True, failure_detector=detector)
        res = runner.execute(SQL)
    finally:
        killed.stop()
    no_failure = DistributedHostQueryRunner(
        workers, session=_task_session()).execute(SQL)
    assert res.rows == no_failure.rows == expected.rows
    assert _counter("trino_tpu_task_retries_total") > before
    # the failure fed the heartbeat detector (scheduler feedback path)
    assert killed.base_uri in detector.failed()
    # ...and the retry is visible in the span tree
    names = []

    def walk(spans):
        for sp in spans:
            names.append(sp["name"])
            walk(sp.get("children", []))

    walk(res.trace.to_dicts())
    assert any(n.endswith("_retry") for n in names), names
    assert any(n.endswith("_execute") for n in names), names


def test_retry_policy_none_fails_fast_with_worker_and_fragment(workers):
    flaky = _FaultyWorker("500")
    try:
        runner = DistributedHostQueryRunner(
            [flaky.base_uri] + workers,
            session=Session(catalog="tpch", schema="tiny"))
        with pytest.raises(QueryError) as e:
            runner.execute(SQL)
        msg = str(e.value)
        assert flaky.base_uri in msg        # WHICH worker died...
        assert "fragment" in msg            # ...running WHAT
    finally:
        flaky.stop()


def test_wedged_worker_times_out_and_retries(workers, expected):
    """A hung results pull turns into a retriable failure via
    remote_task_timeout instead of wedging the query."""
    hung = _FaultyWorker("hang")
    try:
        runner = DistributedHostQueryRunner(
            [hung.base_uri] + workers,
            session=_task_session(remote_task_timeout=1))
        res = runner.execute(SQL)
    finally:
        hung.stop()
    assert res.rows == expected.rows


def test_speculation_rescues_straggler(workers, expected):
    """First-completion-wins: the task stuck on the hung worker is
    speculatively re-dispatched once its elapsed time passes the
    fragment median multiple; the duplicate's result lands, the
    straggler's eventual output would be discarded."""
    hung = _FaultyWorker("hang")
    wins_before = _counter("trino_tpu_speculative_wins_total")
    try:
        # flat-path pin: this exercises the leaf-fragment scheduler's
        # speculation machinery (the explicit fallback since PR 13 —
        # the stage-path twin lives in test_stage_mpp). Under the
        # stage scheduler a 202-forever status poll is a malformed
        # status, failing the attempt into a plain retry instead of a
        # page-pull wedge.
        runner = DistributedHostQueryRunner(
            [hung.base_uri] + workers,
            session=_task_session(multistage_execution=False,
                                  speculation_enabled=True,
                                  speculation_multiplier=1.5,
                                  speculation_min_runtime_ms=100))
        res = runner.execute(SQL)
    finally:
        hung.stop()
    assert res.rows == expected.rows
    assert _counter("trino_tpu_speculative_wins_total") > wins_before


def test_retry_budget_exhaustion_fails_query(workers):
    """Every worker poisoned: TASK retries burn the budget and the
    query fails with the attempt history, not an infinite loop."""
    f1, f2 = _FaultyWorker("500"), _FaultyWorker("500")
    try:
        runner = DistributedHostQueryRunner(
            [f1.base_uri, f2.base_uri],
            session=_task_session(task_retry_attempts=2))
        with pytest.raises(QueryError, match="remote task failed"):
            runner.execute(SQL)
    finally:
        f1.stop()
        f2.stop()


# --------------------------------------------------------------------------
# worker-side spool endpoint + attempt ids
# --------------------------------------------------------------------------

def test_worker_spool_endpoint_survives_task_eviction():
    srv = TaskWorkerServer().start()
    try:
        client = RemoteTaskClient(srv.base_uri)
        client._post("spooled-task", {
            "sql": "SELECT 1 AS x", "catalog": "tpch",
            "schema": "tiny", "spool": True, "attempt": 1})
        first = client.pages("spooled-task")
        assert srv.get_task("spooled-task").attempt == 1
        assert client.status("spooled-task")["attempt"] == 1
        client.abort("spooled-task")          # evict from memory
        assert srv.get_task("spooled-task") is None
        # pages_raw falls back to /v1/spool on the 404 transparently
        again = client.pages("spooled-task")
        assert [b.to_pylist() for b in again] \
            == [b.to_pylist() for b in first]
    finally:
        srv.stop()


# --------------------------------------------------------------------------
# pluggable spool backends: one idempotency/TTL contract, two backends
# --------------------------------------------------------------------------

@pytest.fixture(params=["local", "memory"])
def any_spool(request, tmp_path):
    """Each backend under the IDENTICAL suite: the local directory tree
    and the object-store code path must be interchangeable behind the
    SpoolManager contract. ``.age(query_id, seconds)`` backdates a
    query's spooled state for the TTL tests."""
    if request.param == "local":
        import os
        spool = LocalDirSpool(str(tmp_path), ttl_s=3600)

        def age(query_id, seconds):
            stale = time.time() - seconds
            os.utime(tmp_path / query_id, (stale, stale))
    else:
        from trino_tpu.fte.objectstore import (InMemoryObjectStore,
                                               ObjectStoreSpool)
        store = InMemoryObjectStore()
        spool = ObjectStoreSpool(store, ttl_s=3600, max_attempts=3,
                                 backoff_initial_s=0.001)

        def age(query_id, seconds):
            with store._lock:
                for k, (data, mt) in list(store._objects.items()):
                    if k.startswith(f"{query_id}/"):
                        store._objects[k] = (data, mt - seconds)
    spool.age = age
    return spool


def test_spool_backend_commit_read_release(any_spool):
    frames = [b"page-zero", b"page-one"]
    assert any_spool.commit("q1", 0, 0, 0, frames) == 0
    assert any_spool.committed_attempt("q1", 0, 0) == 0
    assert any_spool.read("q1", 0, 0) == frames
    assert any_spool.read("q1", 0, 1) is None    # nothing committed
    any_spool.release("q1")
    assert any_spool.read("q1", 0, 0) is None


def test_spool_backend_first_commit_wins(any_spool):
    before = _counter("trino_tpu_spool_duplicate_attempts_total")
    assert any_spool.commit("q1", 2, 1, 0, [b"winner"]) == 0
    assert any_spool.commit("q1", 2, 1, 1, [b"loser"]) == 0
    assert any_spool.read("q1", 2, 1) == [b"winner"]
    assert _counter(
        "trino_tpu_spool_duplicate_attempts_total") == before + 1


def test_spool_backend_release_tombstone(any_spool):
    """A late loser completing after the query released its spool must
    not resurrect the query's state (leak until TTL)."""
    any_spool.commit("q", 0, 0, 0, [b"x"])
    any_spool.release("q")
    any_spool.commit("q", 0, 0, 1, [b"y"])
    assert any_spool.read("q", 0, 0) is None


def test_spool_backend_ttl(any_spool):
    any_spool.commit("old_query", 0, 0, 0, [b"x"])
    any_spool.commit("new_query", 0, 0, 0, [b"y"])
    any_spool.age("old_query", 7200)
    assert any_spool.cleanup() == 1
    assert any_spool.read("old_query", 0, 0) is None
    assert any_spool.read("new_query", 0, 0) == [b"y"]


def test_spool_backend_frame_at_a_time(any_spool):
    """The /v1/spool serving surface: per-frame reads agree with the
    whole-attempt read, and uncommitted parts answer None."""
    frames = [b"f0", b"f1", b"f2"]
    any_spool.commit("q", 1, 0, 0, frames)
    assert any_spool.frame_count("q", 1, 0) == 3
    assert [any_spool.read_frame("q", 1, 0, i)
            for i in range(3)] == frames
    assert any_spool.frame_count("q", 9, 0) is None


def test_make_spool_backend_selection():
    from trino_tpu.fte.objectstore import ObjectStoreSpool
    from trino_tpu.fte.spool import default_spool, make_spool
    assert isinstance(make_spool("local"), LocalDirSpool)
    assert isinstance(make_spool("memory"), ObjectStoreSpool)
    with pytest.raises(ValueError, match="unknown spool backend"):
        make_spool("s3://not-wired")
    # the process-wide default is a singleton PER backend name
    assert default_spool("memory") is default_spool("memory")
    assert default_spool("local") is not default_spool("memory")


# --------------------------------------------------------------------------
# object-store backend: injected transient faults vs the retry budget
# --------------------------------------------------------------------------

def _mem_spool(max_attempts=4):
    from trino_tpu.fte.objectstore import (InMemoryObjectStore,
                                           ObjectStoreSpool)
    store = InMemoryObjectStore()
    return store, ObjectStoreSpool(store, max_attempts=max_attempts,
                                   backoff_initial_s=0.001)


def test_objectstore_survives_transient_put_get_failures():
    """The acceptance fault: 503-SlowDown-shaped failures on put and
    get resolve inside the bounded retry budget — the commit lands,
    the read returns the committed frames, and the retry counter
    records the recoveries."""
    store, spool = _mem_spool(max_attempts=4)
    retried = _counter("trino_tpu_objectstore_retries_total")
    store.inject_failures(3, ops=["put"])
    assert spool.commit("q", 0, 0, 0, [b"a", b"b"]) == 0
    store.inject_failures(2, ops=["get"])
    assert spool.read("q", 0, 0) == [b"a", b"b"]
    assert _counter("trino_tpu_objectstore_retries_total") >= retried + 5


def test_objectstore_retry_budget_exhausted_raises():
    """A dead bucket fails the attempt (for the task-retry engine to
    handle) instead of hanging the query in an infinite retry loop."""
    from trino_tpu.fte.objectstore import TransientObjectStoreError
    store, spool = _mem_spool(max_attempts=2)
    store.inject_failures(50)
    with pytest.raises(TransientObjectStoreError):
        spool.commit("q", 0, 0, 0, [b"x"])
    # the store heals -> the next attempt goes through untouched
    store.inject_failures(0)
    assert spool.commit("q", 0, 0, 1, [b"x"]) == 1


@pytest.mark.slow      # ~31s: the kill acceptance re-run with the
# object-store spool backend; the primary kill path stays tier-1
def test_worker_killed_with_objectstore_spool_backend(expected):
    """The PR 5 acceptance kill with the object-store-shaped spool
    active, UN-PINNED onto the default stage path (PR 14): the
    workers themselves spool stage output through the bucket
    emulation (each worker's own in-memory store — consumers fall to
    the HTTP partition leg, the cross-host shape), one worker dies
    mid-query, and the query completes exactly with the bucket
    request counter moving."""
    def ops_total():
        return sum(v for _, v in METRICS.counter(
            "trino_tpu_objectstore_requests_total").samples())

    killed = _FaultyWorker("kill")
    w1 = TaskWorkerServer(spool_backend="memory").start()
    w2 = TaskWorkerServer(spool_backend="memory").start()
    ops_before = ops_total()
    retries_before = _counter("trino_tpu_task_retries_total")
    try:
        runner = DistributedHostQueryRunner(
            [killed.base_uri, w1.base_uri, w2.base_uri],
            session=_task_session())
        res = runner.execute(SQL)
    finally:
        killed.stop()
        w1.stop()
        w2.stop()
    assert res.rows == expected.rows
    assert ops_total() > ops_before
    assert _counter("trino_tpu_task_retries_total") > retries_before


def test_fte_metrics_exposed(workers, expected):
    """The new families render in the Prometheus exposition with the
    names the ISSUE commits to."""
    from trino_tpu.obs.metrics import parse_exposition
    res = DistributedHostQueryRunner(
        workers, session=_task_session()).execute(SQL)
    assert res.rows == expected.rows
    families = parse_exposition(METRICS.render())
    for name in ("trino_tpu_task_retries_total",
                 "trino_tpu_spool_bytes_written_total",
                 "trino_tpu_spool_bytes_read_total",
                 "trino_tpu_speculative_wins_total",
                 "trino_tpu_query_peak_memory_bytes"):
        assert name in families, name
    # a TASK-policy query spools its fragment output through disk
    assert families["trino_tpu_spool_bytes_written_total"][()] > 0
    assert families["trino_tpu_spool_bytes_read_total"][()] > 0
