"""q18 at scale vs the independent numpy oracle (BASELINE configs[3]
direction; the sqlite oracle tier stops at tiny).

Gated like tests/test_scale.py: sf1 engine + oracle passes cost minutes
on the 1-core CI box."""

import datetime
import os

import pytest

from trino_tpu.benchmarks.q18_oracle import q18_oracle

pytestmark = pytest.mark.skipif(
    os.environ.get("TRINO_TPU_SCALE_TESTS") != "1",
    reason="scale tests are opt-in (TRINO_TPU_SCALE_TESTS=1)")
from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session

EPOCH = datetime.date(1970, 1, 1)


def test_q18_sf1_matches_numpy_oracle():
    r = LocalQueryRunner(session=Session(catalog="tpch", schema="sf1"))
    got = r.execute(TPCH_QUERIES[18]).rows
    exp = q18_oracle(1.0)
    assert len(got) == len(exp) > 0
    for g, e in zip(got, exp):
        assert [g[0], g[1], g[2], (g[3] - EPOCH).days, g[4], g[5]] == e
