"""TupleDomain predicate model + connector pushdown.

Reference parity: spi/predicate/ (TupleDomain/Domain/Range),
sql/planner/DomainTranslator.java,
sql/planner/iterative/rule/PushPredicateIntoTableScan.java /
PushLimitIntoTableScan.java.
"""

import pytest

from trino_tpu.predicate import (Domain, Range, TupleDomain,
                                 extract_tuple_domain)
from trino_tpu.rex import Call, Const, InputRef
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.types import BIGINT, BOOLEAN, VARCHAR


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


# --- domain algebra -------------------------------------------------------

def test_domain_intersect_union():
    d1 = Domain.range(BIGINT, 0, True, 10, True)
    d2 = Domain.range(BIGINT, 5, True, 20, True)
    inter = d1.intersect(d2)
    assert inter.ranges == (Range(5, True, 10, True),)
    uni = d1.union(d2)
    assert uni.ranges == (Range(0, True, 20, True),)
    assert Domain.single(BIGINT, 3).intersect(
        Domain.single(BIGINT, 4)).is_none()


def test_domain_in_values_and_mask():
    import numpy as np
    d = Domain.in_values(BIGINT, [3, 1, 3, 7])
    assert [r.low for r in d.ranges] == [1, 3, 7]
    mask = d.mask_for(np.asarray([0, 1, 2, 3, 7, 8]))
    assert list(mask) == [False, True, False, True, True, False]


def test_tuple_domain_intersect():
    td1 = TupleDomain.of({"a": Domain.single(BIGINT, 1)})
    td2 = TupleDomain.of({"a": Domain.single(BIGINT, 2)})
    assert td1.intersect(td2).is_none
    td3 = TupleDomain.of({"b": Domain.not_null(BIGINT)})
    merged = td1.intersect(td3)
    assert set(merged.as_dict()) == {"a", "b"}


def test_extract_tuple_domain():
    types = {"x": BIGINT, "y": VARCHAR}
    x = InputRef("x", BIGINT)
    pred = Call("and", (
        Call(">=", (x, Const(5, BIGINT)), BOOLEAN),
        Call("<", (x, Const(10, BIGINT)), BOOLEAN)), BOOLEAN)
    td, residual = extract_tuple_domain(pred, types)
    assert not residual
    dom = td.domain("x")
    assert dom.ranges == (Range(5, True, 10, False),)
    # untranslatable residual stays
    pred2 = Call("and", (
        Call("=", (x, Const(1, BIGINT)), BOOLEAN),
        Call("like", (InputRef("y", VARCHAR), Const("a%", VARCHAR)),
             BOOLEAN)), BOOLEAN)
    td2, res2 = extract_tuple_domain(pred2, types)
    assert td2.domain("x") is not None and len(res2) == 1


# --- engine integration ---------------------------------------------------

def test_pushdown_correctness_vs_no_pushdown(runner):
    queries = [
        "SELECT count(*) FROM tpch.tiny.lineitem WHERE l_quantity < 10",
        "SELECT count(*) FROM tpch.tiny.orders WHERE "
        "o_orderdate >= DATE '1995-01-01' AND "
        "o_orderdate < DATE '1996-01-01'",
        "SELECT count(*) FROM tpch.tiny.nation WHERE "
        "n_name IN ('CANADA', 'BRAZIL')",
        "SELECT count(*) FROM tpch.tiny.customer WHERE "
        "c_mktsegment = 'BUILDING' AND c_custkey > 100",
    ]
    with_pd = [runner.execute(q).rows for q in queries]
    runner.execute("SET SESSION pushdown_into_scan = false")
    try:
        without = [runner.execute(q).rows for q in queries]
    finally:
        runner.execute("SET SESSION pushdown_into_scan = true")
    assert with_pd == without


def test_pushdown_shows_in_plan(runner):
    plan = runner.execute(
        "EXPLAIN SELECT n_name FROM tpch.tiny.nation "
        "WHERE n_nationkey = 3").rows
    txt = "\n".join(r[0] for r in plan)
    assert "constraint=" in txt
    assert "Filter" not in txt      # fully enforced -> filter gone


def test_residual_filter_stays(runner):
    plan = runner.execute(
        "EXPLAIN SELECT n_name FROM tpch.tiny.nation "
        "WHERE n_nationkey = 3 AND n_comment LIKE '%a%'").rows
    txt = "\n".join(r[0] for r in plan)
    assert "constraint=" in txt and "Filter" in txt


def test_limit_pushdown(runner):
    plan = runner.execute(
        "EXPLAIN SELECT n_name FROM tpch.tiny.nation LIMIT 3").rows
    txt = "\n".join(r[0] for r in plan)
    assert "limit=3" in txt
    got = runner.execute(
        "SELECT n_name FROM tpch.tiny.nation LIMIT 3").rows
    assert len(got) == 3


def test_memory_connector_pushdown(runner):
    runner.execute("CREATE TABLE memory.default.pd AS "
                   "SELECT * FROM tpch.tiny.region")
    got = runner.execute("SELECT r_name FROM memory.default.pd "
                         "WHERE r_regionkey = 2").rows
    assert got == [['ASIA']]
    got = runner.execute("SELECT r_name FROM memory.default.pd "
                         "WHERE r_name < 'ASIA' ORDER BY r_name").rows
    assert got == [['AFRICA'], ['AMERICA']]
    runner.execute("DROP TABLE memory.default.pd")


def test_contradiction_prunes_to_zero(runner):
    got = runner.execute("SELECT count(*) FROM tpch.tiny.nation "
                         "WHERE n_nationkey = 1 AND "
                         "n_nationkey = 2").rows
    assert got == [[0]]


def test_pushdown_with_nulls(runner):
    runner.execute("CREATE TABLE memory.default.pn (x bigint)")
    runner.execute("INSERT INTO memory.default.pn VALUES (1), (NULL), "
                   "(3)")
    assert runner.execute("SELECT count(*) FROM memory.default.pn "
                          "WHERE x > 0").rows == [[2]]
    assert runner.execute("SELECT count(*) FROM memory.default.pn "
                          "WHERE x IS NULL").rows == [[1]]
    assert runner.execute("SELECT count(*) FROM memory.default.pn "
                          "WHERE x IS NOT NULL").rows == [[2]]
    runner.execute("DROP TABLE memory.default.pn")
