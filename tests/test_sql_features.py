"""Feature tests: grouping sets, quantified comparisons, correlated IN,
information_schema, DELETE, CBO plan shape.

Reference parity anchors: GroupIdNode (plan/GroupIdNode.java),
QuantifiedComparison rewrites, TransformCorrelatedInPredicateToJoin,
connector/informationschema/, TableDeleteNode, and
DetermineJoinDistributionType / build-side selection (cost/)."""

import pytest

from trino_tpu.exec import QueryError
from trino_tpu.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def test_rollup(runner):
    res = runner.execute("""
        SELECT l_returnflag, l_linestatus, count(*) AS n FROM lineitem
        GROUP BY ROLLUP (l_returnflag, l_linestatus)
        ORDER BY l_returnflag, l_linestatus""")
    total = runner.execute("SELECT count(*) FROM lineitem").rows[0][0]
    grand = [r for r in res.rows if r[0] is None and r[1] is None]
    assert grand == [[None, None, total]]
    flags = [r for r in res.rows if r[0] is not None and r[1] is None]
    assert sum(r[2] for r in flags) == total


def test_cube_set_count(runner):
    res = runner.execute("""
        SELECT l_returnflag, l_linestatus, count(*) FROM lineitem
        GROUP BY CUBE (l_returnflag, l_linestatus)""")
    # 3 flags x 2 statuses is sparse (A/R only pair with F): the cube has
    # detail(4) + by-flag(3) + by-status(2) + grand(1)
    assert len(res.rows) == 10


def test_grouping_sets_explicit(runner):
    res = runner.execute("""
        SELECT l_returnflag, l_linestatus, count(*) FROM lineitem
        GROUP BY GROUPING SETS ((l_returnflag), (l_linestatus))
        ORDER BY 1, 2""")
    assert len(res.rows) == 5  # 3 flags + 2 statuses
    assert all((r[0] is None) != (r[1] is None) for r in res.rows)


def test_quantified_all_any(runner):
    q = runner.execute
    assert q("SELECT 5 > ALL (SELECT x FROM (VALUES (1),(3)) t(x))"
             ).rows == [[True]]
    assert q("SELECT 2 > ALL (SELECT x FROM (VALUES (1),(3)) t(x))"
             ).rows == [[False]]
    assert q("SELECT 5 > ALL (SELECT x FROM (VALUES (1),(NULL)) t(x))"
             ).rows == [[None]]
    assert q("SELECT 1 > ALL (SELECT x FROM (VALUES (2)) t(x) "
             "WHERE x > 99)").rows == [[True]]
    assert q("SELECT 0 > ANY (SELECT x FROM (VALUES (1),(NULL)) t(x))"
             ).rows == [[None]]
    assert q("SELECT 2 >= ANY (SELECT x FROM (VALUES (1),(NULL)) t(x))"
             ).rows == [[True]]
    assert q("SELECT 9 = ANY (SELECT x FROM (VALUES (9)) t(x))"
             ).rows == [[True]]
    assert q("SELECT 9 <> ALL (SELECT x FROM (VALUES (1),(2)) t(x))"
             ).rows == [[True]]


def test_correlated_in(runner):
    res = runner.execute("""
        SELECT count(*) FROM orders o WHERE o.o_orderkey IN
          (SELECT l.l_orderkey FROM lineitem l
           WHERE l.l_orderkey = o.o_orderkey AND l.l_quantity = 50)""")
    ref = runner.execute(
        "SELECT count(DISTINCT l_orderkey) FROM lineitem "
        "WHERE l_quantity = 50")
    assert res.rows[0][0] == ref.rows[0][0]


def test_information_schema(runner):
    res = runner.execute(
        "SELECT table_name FROM information_schema.tables "
        "WHERE table_schema = 'tiny' ORDER BY 1")
    assert ["lineitem"] in res.rows and ["region"] in res.rows
    res = runner.execute(
        "SELECT column_name, data_type FROM information_schema.columns "
        "WHERE table_schema = 'tiny' AND table_name = 'nation' "
        "ORDER BY ordinal_position")
    assert res.rows[0] == ["n_nationkey", "bigint"]
    res = runner.execute(
        "SELECT schema_name FROM information_schema.schemata")
    assert ["sf100"] in res.rows


def test_delete(runner):
    runner.execute("CREATE TABLE memory.default.del_t AS "
                   "SELECT * FROM (VALUES (1),(2),(3),(NULL)) t(x)")
    d = runner.execute("DELETE FROM memory.default.del_t WHERE x >= 2")
    assert d.update_count == 2
    # NULL predicate rows survive (3VL: not TRUE)
    res = runner.execute(
        "SELECT x FROM memory.default.del_t ORDER BY x")
    assert res.rows == [[1], [None]]
    d = runner.execute("DELETE FROM memory.default.del_t")
    assert d.update_count == 2
    assert runner.execute(
        "SELECT count(*) FROM memory.default.del_t").rows == [[0]]
    runner.execute("DROP TABLE memory.default.del_t")


def test_join_build_side_selection(runner):
    # CBO must put the big table (lineitem) on the probe (left) side
    from trino_tpu.plan.nodes import JoinNode, TableScanNode

    plan = runner.plan_sql("""
        SELECT count(*) FROM nation, lineitem
        WHERE n_nationkey = l_suppkey""")

    def find_join(n):
        if isinstance(n, JoinNode):
            return n
        for s in n.sources:
            j = find_join(s)
            if j is not None:
                return j
        return None

    join = find_join(plan)
    assert join is not None

    def scans(n):
        if isinstance(n, TableScanNode):
            yield n.handle.table
        for s in n.sources:
            yield from scans(s)

    assert "lineitem" in set(scans(join.left))
    assert "nation" in set(scans(join.right))
    assert join.distribution == "replicated"


def test_rollup_aggregate_over_key(runner):
    # aggregate argument == grouping key: subtotal rows must aggregate
    # the real values, not the nulled key lane
    res = runner.execute("""
        SELECT x, sum(x) AS s, count(x) AS c
        FROM (VALUES (1),(2),(3)) t(x) GROUP BY ROLLUP (x)
        ORDER BY x""")
    grand = [r for r in res.rows if r[0] is None][0]
    assert grand[1] == 6 and grand[2] == 3


def test_delete_with_date_column(runner):
    runner.execute("CREATE TABLE memory.default.del_d AS "
                   "SELECT o_orderkey, o_orderdate FROM orders LIMIT 10")
    d = runner.execute(
        "DELETE FROM memory.default.del_d WHERE o_orderkey > 0")
    assert d.update_count == 10
    runner.execute("DROP TABLE memory.default.del_d")


def test_correlated_not_in_rejected(runner):
    with pytest.raises(QueryError, match="NOT IN"):
        runner.execute("""
            SELECT count(*) FROM orders o WHERE o.o_orderkey NOT IN
              (SELECT l_orderkey FROM lineitem l
               WHERE l.l_orderkey = o.o_orderkey)""")


def test_streaming_aggregation_matches_single_batch():
    # multi-split scans aggregate split-by-split (grouped-execution
    # analog); results match the single-batch path to float tolerance
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    from trino_tpu.connectors.tpch import TpchConnector

    streamed = LocalQueryRunner()
    streamed.catalogs.register("tpch", TpchConnector(rows_per_split=1 << 14))
    single = LocalQueryRunner()
    a = streamed.execute(TPCH_QUERIES[1]).rows
    b = single.execute(TPCH_QUERIES[1]).rows
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        for x, y in zip(ra, rb):
            if isinstance(x, float):
                assert x == pytest.approx(y, rel=1e-9)
            else:
                assert x == y
    # distinct aggregation streams through the dedupe rewrite
    a = streamed.execute(
        "SELECT count(DISTINCT l_suppkey) FROM lineitem").rows
    b = single.execute(
        "SELECT count(DISTINCT l_suppkey) FROM lineitem").rows
    assert a == b
