"""Control-plane tests: coordinator REST protocol + client + CLI.

Reference parity: the protocol behaviors of QueuedStatementResource /
ExecutingStatementResource / StatementClientV1 (SURVEY.md §3.1) —
submission, nextUri paging, error payloads, session properties via
X-Trino-Session, /v1/info and /v1/query.
"""

import json
import urllib.request

import pytest

from trino_tpu.client import ClientError, StatementClient
from trino_tpu.server import Coordinator


@pytest.fixture(scope="module")
def coordinator():
    co = Coordinator().start()
    yield co
    co.stop()


@pytest.fixture(scope="module")
def client(coordinator):
    return StatementClient(coordinator.base_uri)


def test_info(coordinator):
    with urllib.request.urlopen(
            f"{coordinator.base_uri}/v1/info") as r:
        info = json.loads(r.read())
    assert info["coordinator"] is True


def test_simple_query(client):
    res = client.execute("SELECT 1 + 2 AS x, 'hi' AS s")
    assert res.column_names == ["x", "s"]
    assert res.rows == [[3, "hi"]]
    assert res.state == "FINISHED"


def test_query_over_tpch(client):
    res = client.execute(
        "SELECT l_returnflag, count(*) FROM lineitem "
        "GROUP BY l_returnflag ORDER BY 1")
    assert [r[0] for r in res.rows] == ["A", "N", "R"]


def test_paging(client, coordinator):
    # > PAGE_ROWS rows forces multiple nextUri fetches
    res = client.execute(
        "SELECT l_orderkey FROM lineitem LIMIT 6000")
    assert len(res.rows) == 6000


def test_error_payload(client):
    with pytest.raises(ClientError, match="cannot be resolved"):
        client.execute("SELECT nosuch FROM lineitem")


def test_session_properties(coordinator):
    c = StatementClient(coordinator.base_uri,
                        session_properties={"task_concurrency": "4"})
    res = c.execute("SHOW SESSION")
    row = [r for r in res.rows if r[0] == "task_concurrency"][0]
    assert row[1] == "4"


def test_date_json_encoding(client):
    res = client.execute("SELECT date '2001-08-22' AS d")
    assert res.rows == [["2001-08-22"]]


def test_query_list(coordinator, client):
    client.execute("SELECT 42")
    with urllib.request.urlopen(
            f"{coordinator.base_uri}/v1/query") as r:
        infos = json.loads(r.read())
    assert any(i["state"] == "FINISHED" for i in infos)


def test_update_statement(client):
    res = client.execute(
        "CREATE TABLE memory.default.srv_t AS SELECT 1 AS a")
    assert res.update_type
    res = client.execute("SELECT a FROM memory.default.srv_t")
    assert res.rows == [[1]]


def test_cli_execute(capsys):
    from trino_tpu.cli import main
    rc = main(["--local", "-e", "SELECT 1 AS one, 'x' AS s"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "one" in out and "1" in out and "(1 row" in out


def test_cancel_interrupts_execution():
    """Cancellation must stop the executor between plan nodes, not just
    flip the client-visible state (VERDICT r2 weak #8)."""
    import threading

    from trino_tpu.exec import QueryError
    from trino_tpu.runner import LocalQueryRunner
    from trino_tpu.session import Session

    ev = threading.Event()
    ev.set()
    r = LocalQueryRunner(session=Session(catalog="tpch", schema="tiny",
                                         cancel=ev))
    with pytest.raises(QueryError, match="canceled"):
        r.execute("SELECT count(*) FROM lineitem")


def test_unknown_session_property_rejected():
    from trino_tpu.session import Session

    s = Session()
    with pytest.raises(KeyError, match="does not exist"):
        s.set("no_such_property", "1")
    with pytest.raises(KeyError, match="does not exist"):
        s.get("tpu_enabled")   # deleted inert flag stays deleted


def test_query_detail_endpoint_and_ui_pages():
    """Web UI v1: /v1/query/{id} carries state, per-node stats, and the
    optimized plan tree; both UI pages serve (webapp QueryList/
    QueryDetail analog)."""
    import json as _json
    import urllib.request
    from trino_tpu.client import StatementClient
    from trino_tpu.server.coordinator import Coordinator
    coord = Coordinator().start()
    try:
        c = StatementClient(coord.base_uri, catalog="tpch",
                            schema="tiny")
        res = c.execute("SELECT o_orderpriority, count(*) FROM orders "
                        "GROUP BY o_orderpriority")
        qid = res.query_id
        with urllib.request.urlopen(
                f"{coord.base_uri}/v1/query/{qid}") as r:
            d = _json.loads(r.read())
        assert d["state"] == "FINISHED"
        assert d["rows"] == 5
        assert any("Aggregation" in line for line in d["plan"])
        assert any("TableScan" in line for line in d["plan"])
        stats = d.get("nodeStats") or []
        assert stats and any(s["outputRows"] >= 5 for s in stats)
        for page in ("/ui", f"/ui/query.html?{qid}"):
            with urllib.request.urlopen(coord.base_uri + page) as r:
                body = r.read().decode()
            assert "<html" in body
    finally:
        coord.stop()
