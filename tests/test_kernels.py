"""Unit tests for the M0 kernel substrate (SURVEY.md §7 build order).

Modeled on the reference's operator unit tests
(core/trino-main/src/test/java/io/trino/operator/TestHashAggregationOperator
etc.), but asserting against plain-python recomputation.
"""

import numpy as np
import pytest

from trino_tpu.columnar import Batch, batch_from_pylist, concat_batches
from trino_tpu.ops.compact import filter_batch, limit_batch, offset_batch
from trino_tpu.ops.groupby import (AggInput, global_aggregate,
                                   group_aggregate)
from trino_tpu.ops.join import (cross_counts, expand_join, match_counts,
                                semi_join_mask)
from trino_tpu.ops.sort import SortKey, sort_batch, topn_batch
from trino_tpu.types import BIGINT, DOUBLE, INTEGER, VARCHAR, DecimalType

import jax.numpy as jnp


def make_batch():
    return batch_from_pylist(
        {
            "k": [1, 2, 1, 3, 2, 1, None, 3],
            "v": [10.0, 20.0, 30.0, None, 50.0, 60.0, 70.0, 80.0],
            "s": ["a", "b", "a", "c", None, "b", "a", "c"],
        },
        {"k": BIGINT, "v": DOUBLE, "s": VARCHAR},
    )


def test_pylist_roundtrip():
    b = make_batch()
    rows = b.to_pylist()
    assert rows[0] == [1, 10.0, "a"]
    assert rows[6] == [None, 70.0, "a"]
    assert len(rows) == 8


def test_filter_compacts():
    b = make_batch()
    k = jnp.asarray(b.column("k").data)
    kv = b.column("k").valid_mask()
    out = filter_batch(b, (k == 1) & kv)
    rows = out.to_pylist()
    assert rows == [[1, 10.0, "a"], [1, 30.0, "a"], [1, 60.0, "b"]]


def test_limit_offset():
    b = make_batch()
    assert len(limit_batch(b, 3).to_pylist()) == 3
    rows = offset_batch(b, 6).to_pylist()
    assert len(rows) == 2
    assert rows[0][1] == 70.0


def test_group_aggregate_sum_count_min_max():
    b = make_batch()
    out = group_aggregate(
        b, ["k"],
        [AggInput("sum", "v", output="sv"),
         AggInput("count", "v", output="cv"),
         AggInput("count_star", output="cs"),
         AggInput("min", "v", output="mn"),
         AggInput("max", "v", output="mx")])
    rows = {r[0]: r[1:] for r in out.to_pylist()}
    assert len(rows) == 4  # 1, 2, 3, NULL
    assert rows[1] == [100.0, 3, 3, 10.0, 60.0]
    assert rows[2] == [70.0, 2, 2, 20.0, 50.0]
    assert rows[3] == [80.0, 1, 2, 80.0, 80.0]  # one NULL v in group 3
    assert rows[None] == [70.0, 1, 1, 70.0, 70.0]


def test_group_by_string_key():
    b = make_batch()
    out = group_aggregate(b, ["s"], [AggInput("count_star", output="c")])
    rows = {r[0]: r[1] for r in out.to_pylist()}
    assert rows == {"a": 3, "b": 2, "c": 2, None: 1}


def test_group_by_multi_key():
    b = make_batch()
    out = group_aggregate(b, ["k", "s"],
                          [AggInput("count_star", output="c")])
    rows = {(r[0], r[1]): r[2] for r in out.to_pylist()}
    assert rows[(1, "a")] == 2
    assert rows[(1, "b")] == 1
    assert rows[(None, "a")] == 1


def test_global_aggregate():
    b = make_batch()
    out = global_aggregate(
        b, [AggInput("sum", "v", output="s"),
            AggInput("count", "k", output="c"),
            AggInput("count_star", output="cs"),
            AggInput("min", "v", output="mn")])
    assert out.to_pylist() == [[320.0, 7, 8, 10.0]]


def test_global_aggregate_empty():
    b = batch_from_pylist({"v": []}, {"v": DOUBLE})
    out = global_aggregate(b, [AggInput("sum", "v", output="s"),
                               AggInput("count", "v", output="c")])
    assert out.to_pylist() == [[None, 0]]


def test_sort_and_nulls():
    b = make_batch()
    out = sort_batch(b, [SortKey("v", ascending=False)])
    vals = [r[1] for r in out.to_pylist()]
    assert vals == [None, 80.0, 70.0, 60.0, 50.0, 30.0, 20.0, 10.0]
    out2 = sort_batch(b, [SortKey("v", ascending=True)])
    vals2 = [r[1] for r in out2.to_pylist()]
    assert vals2 == [10.0, 20.0, 30.0, 50.0, 60.0, 70.0, 80.0, None]


def test_sort_string_and_multikey():
    b = make_batch()
    out = sort_batch(b, [SortKey("s"), SortKey("v", ascending=False)])
    rows = out.to_pylist()
    assert [r[2] for r in rows[:3]] == ["a", "a", "a"]
    assert [r[1] for r in rows[:3]] == [70.0, 30.0, 10.0]
    assert rows[-1][2] is None  # nulls last


def test_topn():
    b = make_batch()
    out = topn_batch(b, [SortKey("v", ascending=False,
                                 nulls_first=False)], 2)
    assert [r[1] for r in out.to_pylist()] == [80.0, 70.0]


def _join(probe, build, pk, bk, join_type="inner", prefix="b_"):
    start, count, order = match_counts(probe, build, pk, bk)
    total = int(jnp.maximum(count, 1).sum()) if join_type == "left" \
        else int(count.sum())
    cap = max(8, 1 << max(0, (total - 1).bit_length()))
    return expand_join(probe, build, start, count, order, cap,
                       join_type, prefix)


def test_inner_join():
    probe = batch_from_pylist({"k": [1, 2, 3, None, 5]},
                              {"k": BIGINT})
    build = batch_from_pylist({"k": [1, 1, 2, None], "w": [7, 8, 9, 10]},
                              {"k": BIGINT, "w": BIGINT})
    out = _join(probe, build, ["k"], ["k"])
    rows = sorted(map(tuple, out.to_pylist()))
    assert rows == [(1, 1, 7), (1, 1, 8), (2, 2, 9)]


def test_left_join():
    probe = batch_from_pylist({"k": [1, 3, None]}, {"k": BIGINT})
    build = batch_from_pylist({"k": [1, 2], "w": [7, 9]},
                              {"k": BIGINT, "w": BIGINT})
    out = _join(probe, build, ["k"], ["k"], "left")
    rows = sorted(map(tuple, out.to_pylist()),
                  key=lambda r: (r[0] is None, r))
    assert rows == [(1, 1, 7), (3, None, None), (None, None, None)]


def test_multikey_join():
    probe = batch_from_pylist({"a": [1, 1, 2], "b": [10, 11, 10]},
                              {"a": BIGINT, "b": BIGINT})
    build = batch_from_pylist({"a": [1, 2], "b": [10, 10],
                               "w": [100, 200]},
                              {"a": BIGINT, "b": BIGINT, "w": BIGINT})
    out = _join(probe, build, ["a", "b"], ["a", "b"])
    rows = sorted(map(tuple, out.to_pylist()))
    assert rows == [(1, 10, 1, 10, 100), (2, 10, 2, 10, 200)]


def test_semi_join_mask():
    probe = batch_from_pylist({"k": [1, 2, None]}, {"k": BIGINT})
    build = batch_from_pylist({"k": [1, None]}, {"k": BIGINT})
    matched, key_null, has_null, nonempty = semi_join_mask(
        probe, build, ["k"], ["k"])
    assert list(np.asarray(matched)[:3]) == [True, False, False]
    assert list(np.asarray(key_null)[:3]) == [False, False, True]
    assert bool(has_null) and bool(nonempty)


def test_cross_join():
    probe = batch_from_pylist({"a": [1, 2]}, {"a": BIGINT})
    build = batch_from_pylist({"b": [10, 20, 30]}, {"b": BIGINT})
    start, count, order = cross_counts(probe, build)
    out = expand_join(probe, build, start, count, order, 8, "inner", "")
    rows = sorted(map(tuple, out.to_pylist()))
    assert len(rows) == 6
    assert rows[0] == (1, 10)


def test_concat_batches_merges_dictionaries():
    b1 = batch_from_pylist({"s": ["x", "y"]}, {"s": VARCHAR})
    b2 = batch_from_pylist({"s": ["y", "z"]}, {"s": VARCHAR})
    out = concat_batches([b1, b2])
    assert [r[0] for r in out.to_pylist()] == ["x", "y", "y", "z"]


def test_decimal_column():
    b = batch_from_pylist({"d": [1.25, 2.50, None]},
                          {"d": DecimalType(10, 2)})
    import decimal
    assert b.to_pylist() == [[decimal.Decimal("1.25")], [decimal.Decimal("2.5")], [None]]


def test_decimal_half_up_rounding():
    # 1.115 * 100 == 111.4999... in binary floats; must store 112
    b = batch_from_pylist({"d": [1.115]}, {"d": DecimalType(10, 2)})
    import decimal
    assert b.to_pylist() == [[decimal.Decimal("1.12")]]


def test_string_join_across_dictionaries():
    probe = batch_from_pylist({"s": ["a", "b"]}, {"s": VARCHAR})
    build = batch_from_pylist({"s": ["b", "c"], "w": [1, 2]},
                              {"s": VARCHAR, "w": BIGINT})
    out = _join(probe, build, ["s"], ["s"], prefix="b_")
    assert out.to_pylist() == [["b", "b", 1]]


def test_string_min_max_uses_collation():
    b = batch_from_pylist({"g": [1, 1], "s": ["b", "a"]},
                          {"g": BIGINT, "s": VARCHAR})
    out = group_aggregate(b, ["g"], [AggInput("min", "s", output="mn"),
                                     AggInput("max", "s", output="mx")])
    assert out.to_pylist() == [[1, "a", "b"]]
    gout = global_aggregate(b, [AggInput("min", "s", output="mn")])
    assert gout.to_pylist() == [["a"]]


def test_long_decimal_int128_roundtrip():
    import decimal
    from trino_tpu.columnar import concat_batches
    big = 12345678901234567890123456789
    b1 = batch_from_pylist({"d": [big, -big]}, {"d": DecimalType(38, 0)})
    assert b1.to_pylist() == [[big], [-big]]
    b2 = batch_from_pylist({"d": [5]}, {"d": DecimalType(38, 0)})
    assert concat_batches([b1, b2]).to_pylist() == [[big], [-big], [5]]
    d = batch_from_pylist({"d": ["12345678901234567.89"]},
                          {"d": DecimalType(38, 2)})
    assert d.to_pylist()[0][0] == decimal.Decimal("12345678901234567.89")


def test_grouped_any_value_skips_nulls():
    from trino_tpu.ops.groupby import AggInput, group_aggregate
    b = batch_from_pylist({"k": [1, 1, 2], "x": [None, 7.0, None]},
                          {"k": BIGINT, "x": DOUBLE})
    out = group_aggregate(b, ["k"],
                          [AggInput("any_value", "x", output="a")])
    assert out.to_pylist() == [[1, 7.0], [2, None]]
