"""Fragment-JIT tests: pipeline chains compiled as one XLA program must
match eager execution (reference analog: compiled PageProcessor vs
interpreted path, sql/gen/PageFunctionCompiler.java:101 vs
ExpressionInterpreter). Floating-point aggregates compare with a 1e-9
relative tolerance: XLA may reassociate reductions when fusing, so the
compiled sum order legitimately differs from the eager one (SURVEY.md
§7 hard part 6)."""

import math

import pytest

from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
from trino_tpu.exec import Executor
from trino_tpu.planner import LogicalPlanner
from trino_tpu.planner.optimizer import optimize
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.sql.parser import parse_statement


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def _both(runner, sql):
    stmt = parse_statement(sql)
    plan = optimize(
        LogicalPlanner(runner.catalogs, runner.session).plan(stmt))
    eager = Executor(runner.catalogs, runner.session,
                     fragment_jit=False).execute(plan).to_pylist()
    jitted = Executor(runner.catalogs, runner.session,
                      fragment_jit=True).execute(plan).to_pylist()
    return eager, jitted


def assert_rows_close(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert len(ra) == len(rb)
        for x, y in zip(ra, rb):
            if isinstance(x, float) and isinstance(y, float):
                assert math.isclose(x, y, rel_tol=1e-9, abs_tol=1e-9), \
                    (x, y)
            else:
                assert x == y, (x, y)


@pytest.mark.parametrize("q", [1, 6, 12])
def test_tpch_jit_matches_eager(runner, q):
    eager, jitted = _both(runner, TPCH_QUERIES[q])
    assert_rows_close(eager, jitted)


def test_jit_with_strings_and_nulls(runner):
    eager, jitted = _both(runner, """
        SELECT l_shipmode, count(*) AS n,
               sum(CASE WHEN l_quantity > 25 THEN 1 ELSE 0 END) AS big
        FROM lineitem WHERE l_returnflag <> 'N'
        GROUP BY l_shipmode ORDER BY l_shipmode
    """)
    assert eager == jitted


def test_jit_host_fallback(runner):
    # cast to varchar materializes rows on host -> the chain must fall
    # back to eager execution and still produce correct results
    eager, jitted = _both(runner, """
        SELECT cast(l_linenumber AS varchar) AS s, count(*)
        FROM lineitem GROUP BY 1 ORDER BY 1
    """)
    assert eager == jitted


def test_whole_table_hbm_path_matches_streaming(monkeypatch):
    """The device-backend whole-table fast path (exec/executor.py
    read_table_cached: splits concatenated once into an HBM-resident
    batch, aggregation fused into ONE program incl. final combine +
    post-processing) must agree with the default split-streaming path.
    Forced on here via TRINO_TPU_WHOLE_TABLE=1 (it is auto-off on the
    CPU test backend)."""
    monkeypatch.setenv("TRINO_TPU_WHOLE_TABLE", "1")
    r = LocalQueryRunner()
    for q in (1, 6):
        stmt = parse_statement(TPCH_QUERIES[q])
        plan = optimize(
            LogicalPlanner(r.catalogs, r.session).plan(stmt))
        whole = Executor(r.catalogs, r.session,
                         fragment_jit=True).execute(plan).to_pylist()
        monkeypatch.setenv("TRINO_TPU_WHOLE_TABLE", "0")
        stream = Executor(r.catalogs, r.session,
                          fragment_jit=True).execute(plan).to_pylist()
        monkeypatch.setenv("TRINO_TPU_WHOLE_TABLE", "1")
        assert_rows_close(stream, whole)


def test_structural_jit_cache_reuses_program(monkeypatch):
    """Two separately planned executions of the same SQL must share one
    cached streaming-aggregation program (plan-fingerprint keyed —
    the ExpressionCompiler generated-class cache analog)."""
    from trino_tpu.exec import executor as ex
    monkeypatch.setenv("TRINO_TPU_WHOLE_TABLE", "1")
    r = LocalQueryRunner()
    sql = ("SELECT l_returnflag, sum(l_quantity), avg(l_discount) "
           "FROM lineitem WHERE l_shipdate <= DATE '1998-09-02' "
           "GROUP BY l_returnflag ORDER BY l_returnflag")
    outs = []
    for _ in range(2):
        stmt = parse_statement(sql)
        plan = optimize(
            LogicalPlanner(r.catalogs, r.session).plan(stmt))
        outs.append(Executor(r.catalogs, r.session,
                             fragment_jit=True).execute(plan).to_pylist())
    assert_rows_close(outs[0], outs[1])
    # both executions landed on the same fingerprint entries
    assert any(isinstance(k, tuple) and k and k[-1] == "full"
               for k in ex._STREAM_JIT_CACHE)
