"""DB-API 2.0 driver (the trino-jdbc analog).

Reference parity: client/trino-jdbc — statement execution with
parameter binding over the REST client protocol.
"""

import pytest

from trino_tpu.dbapi import ProgrammingError, connect
from trino_tpu.server.coordinator import Coordinator


@pytest.fixture(scope="module")
def coordinator():
    co = Coordinator().start()
    yield co
    co.stop()


def test_basic_query(coordinator):
    with connect(coordinator.base_uri) as conn:
        cur = conn.cursor()
        cur.execute("SELECT r_regionkey, r_name FROM "
                    "tpch.tiny.region ORDER BY r_regionkey")
        assert cur.description[0][0] == "r_regionkey"
        assert cur.rowcount == 5
        first = cur.fetchone()
        assert first == [0, "AFRICA"]
        rest = cur.fetchall()
        assert len(rest) == 4


def test_qmark_parameters(coordinator):
    conn = connect(coordinator.base_uri)
    cur = conn.cursor()
    cur.execute("SELECT n_name FROM tpch.tiny.nation WHERE "
                "n_nationkey = ?", (3,))
    assert cur.fetchall() == [["CANADA"]]
    cur.execute("SELECT count(*) FROM tpch.tiny.nation WHERE "
                "n_name < ? AND n_regionkey = ?", ("CANADA", 1))
    assert cur.fetchone() == [2]


def test_fetchmany_iteration(coordinator):
    cur = connect(coordinator.base_uri).cursor()
    cur.execute("SELECT n_nationkey FROM tpch.tiny.nation "
                "ORDER BY n_nationkey")
    assert cur.fetchmany(3) == [[0], [1], [2]]
    assert len(list(cur)) == 22


def test_ddl_and_rowcount(coordinator):
    conn = connect(coordinator.base_uri, catalog="memory",
                   schema="default")
    cur = conn.cursor()
    cur.execute("CREATE TABLE memory.default.dbapi_t (x bigint)")
    cur.execute("INSERT INTO memory.default.dbapi_t VALUES (1), (2)")
    assert cur.rowcount == 2
    cur.execute("SELECT count(*) FROM memory.default.dbapi_t")
    assert cur.fetchone() == [2]
    cur.execute("DROP TABLE memory.default.dbapi_t")


def test_parameters_through_proxy(coordinator):
    from trino_tpu.server.proxy import Proxy
    px = Proxy(coordinator.base_uri).start()
    try:
        cur = connect(px.base_uri).cursor()
        cur.execute("SELECT n_name FROM tpch.tiny.nation WHERE "
                    "n_nationkey = ?", (3,))
        assert cur.fetchall() == [["CANADA"]]
    finally:
        px.stop()


def test_render_param_edge_values():
    import decimal
    from trino_tpu.dbapi import _render_param
    assert _render_param(float("inf")) == "infinity()"
    assert _render_param(float("-inf")) == "-infinity()"
    assert _render_param(float("nan")) == "nan()"
    assert _render_param(decimal.Decimal("1.25")) == "1.25"
    assert _render_param(None) == "NULL"
    assert _render_param("o'brien") == "'o''brien'"


def test_error_raises(coordinator):
    cur = connect(coordinator.base_uri).cursor()
    with pytest.raises(ProgrammingError):
        cur.execute("SELECT * FROM tpch.tiny.not_a_table")
