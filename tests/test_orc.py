"""ORC reader (from scratch) + local-file connector binding.

Reference parity: lib/trino-orc (reader surface). Test files are
generated with pyarrow.orc — an INDEPENDENT writer — so the reader is
validated against real third-party output, not a round-trip of itself.
"""

import datetime
import os

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.orc as po  # noqa: E402

from trino_tpu.connectors.localfile import LocalFileConnector  # noqa
from trino_tpu.formats.orc import (num_stripes, read_meta, read_orc,
                                   schema_of)  # noqa: E402
from trino_tpu.runner import LocalQueryRunner  # noqa: E402


N = 4000


@pytest.fixture(scope="module")
def table():
    rng = np.random.default_rng(11)
    # `skewed` forces RLEv2 PATCHED_BASE (a few huge outliers over a
    # narrow base range); `runs` forces SHORT_REPEAT / DELTA
    skewed = rng.integers(0, 100, N)
    skewed[::500] = 10**15
    return pa.table({
        "id": pa.array(np.arange(N, dtype=np.int64)),
        "qty": pa.array(rng.integers(0, 50, N).astype(np.int32)),
        "price": pa.array(np.round(rng.uniform(1.0, 100.0, N), 4)),
        "flag": pa.array((np.arange(N) % 3 == 0)),
        "name": pa.array([f"orc_{i % 23}" for i in range(N)]),
        "maybe": pa.array([None if i % 7 == 0 else i * 11
                           for i in range(N)], type=pa.int64()),
        "day": pa.array([datetime.date(2001, 6, 1)
                         + datetime.timedelta(days=int(i % 900))
                         for i in range(N)]),
        "ts": pa.array([datetime.datetime(2022, 5, 6, 7, 8, 9, 250000)
                        + datetime.timedelta(seconds=int(i))
                        for i in range(N)], type=pa.timestamp("ms")),
        "skewed": pa.array(skewed, pa.int64()),
        "runs": pa.array(np.repeat(np.arange(N // 100), 100)),
    })


@pytest.fixture(scope="module", params=["UNCOMPRESSED", "ZLIB",
                                        "SNAPPY", "ZSTD"])
def orc_file(request, table, tmp_path_factory):
    d = tmp_path_factory.mktemp("orc")
    path = str(d / f"data_{request.param}.orc")
    po.write_table(table, path, compression=request.param)
    return path


def test_schema(orc_file):
    s = schema_of(orc_file)
    assert str(s["id"]) == "bigint"
    assert str(s["qty"]) == "integer"
    assert str(s["price"]) == "double"
    assert str(s["flag"]) == "boolean"
    assert str(s["day"]) == "date"
    assert str(s["ts"]) == "timestamp(3)"


def test_full_read_matches_pyarrow(orc_file, table):
    b = read_orc(orc_file)
    rows = b.to_pylist()
    assert len(rows) == N
    want = table.to_pylist()
    names = list(b.names)
    for i in (0, 1, 17, N // 2, N - 1):
        got = dict(zip(names, rows[i]))
        for k in ("id", "qty", "flag", "name", "maybe", "day", "ts",
                  "skewed", "runs"):
            assert got[k] == want[i][k], (i, k, got[k], want[i][k])
        assert abs(got["price"] - want[i]["price"]) < 1e-9


def test_patched_base_and_runs_whole_column(orc_file, table):
    b = read_orc(orc_file, columns=["skewed", "runs", "maybe"])
    sk = [r[0] for r in b.to_pylist()]
    assert sk == table.column("skewed").to_pylist()
    rn = [r[1] for r in b.to_pylist()]
    assert rn == table.column("runs").to_pylist()
    mb = [r[2] for r in b.to_pylist()]
    assert mb == table.column("maybe").to_pylist()


def test_multi_stripe(table, tmp_path_factory):
    d = tmp_path_factory.mktemp("orcs")
    path = str(d / "striped.orc")
    big = pa.concat_tables([table] * 8)  # exceed one stripe's rows
    po.write_table(big, path, compression="SNAPPY",
                   stripe_size=16 * 1024)
    meta = read_meta(path)
    assert len(meta.stripes) > 1
    assert num_stripes(path) == len(meta.stripes)
    b = read_orc(path)
    assert [r[0] for r in b.to_pylist()] == list(range(N)) * 8
    # single-stripe read == that stripe's slice
    b0 = read_orc(path, stripe_index=0)
    assert b0.num_rows_host() == meta.stripes[0].num_rows


def test_sql_over_orc(table, tmp_path_factory):
    d = tmp_path_factory.mktemp("orcsql")
    po.write_table(table, str(d / "events.orc"), compression="ZLIB",
                   stripe_size=32 * 1024)
    runner = LocalQueryRunner()
    runner.catalogs.register("files",
                             LocalFileConnector(str(d)))
    rows = runner.execute(
        "SELECT count(*), sum(qty), min(day), max(name) "
        "FROM files.default.events").rows
    want_qty = sum(table.column("qty").to_pylist())
    assert rows == [[N, want_qty, datetime.date(2001, 6, 1),
                     "orc_9"]]
    top = runner.execute(
        "SELECT name, count(*) c FROM files.default.events "
        "WHERE maybe IS NOT NULL GROUP BY name "
        "ORDER BY c DESC, name LIMIT 3").rows
    assert len(top) == 3
