"""Load-bearing session properties, end-to-end from the
X-Trino-Session header to executor behavior.

Reference: SystemSessionProperties.java:53-123 — the knobs clients and
tests key off. Each test observes the BEHAVIOR change, not just the
stored value.
"""

import time

import pytest

from trino_tpu.client import StatementClient
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.server.coordinator import Coordinator
from trino_tpu.session import SESSION_PROPERTIES, Session


def test_property_registry_breadth():
    for name in ("join_distribution_type", "join_reordering_strategy",
                 "task_concurrency", "spill_enabled",
                 "enable_dynamic_filtering", "distributed_sort",
                 "query_max_memory_per_node", "hash_partition_count",
                 "exchange_compression", "query_max_run_time",
                 "use_table_statistics", "pushdown_into_scan",
                 "multistage_execution", "exchange_partition_count",
                 "prewarm_enabled", "hot_shape_top_k",
                 "stream_chunk_rows", "result_cache_enabled",
                 "ragged_batching", "ragged_batch_max_rows",
                 "query_history_enabled", "learned_stats_enabled",
                 "slow_query_log_ms"):
        assert name in SESSION_PROPERTIES, name


def test_point_lookup_serving_properties_defaults_and_types():
    """ISSUE 18 knobs: both serving paths ship OFF by default (opt-in
    per session — dashboards turn them on), and the batch row cap
    defaults to the TRINO_TPU_RAGGED_BATCH_ROWS config value."""
    from trino_tpu.config import CONFIG
    s = Session()
    assert s.get("result_cache_enabled") is False
    assert s.get("ragged_batching") is False
    assert int(s.get("ragged_batch_max_rows")) == CONFIG.ragged_batch_rows
    s.set("result_cache_enabled", "true")
    assert s.get("result_cache_enabled") is True
    s.set("ragged_batching", "true")
    assert s.get("ragged_batching") is True
    s.set("ragged_batch_max_rows", "4096")
    assert s.get("ragged_batch_max_rows") == 4096


def test_observability_properties_defaults_and_types():
    """ISSUE 19 knobs: history and learned stats default ON (the
    always-on OperatorStats stance — the overhead tests hold them
    under budget), the slow-query log defaults OFF (0 = disarmed,
    any positive value is a millisecond threshold)."""
    s = Session()
    assert s.get("query_history_enabled") is True
    assert s.get("learned_stats_enabled") is True
    assert int(s.get("slow_query_log_ms")) == 0
    s.set("query_history_enabled", "false")
    assert s.get("query_history_enabled") is False
    s.set("learned_stats_enabled", "false")
    assert s.get("learned_stats_enabled") is False
    s.set("slow_query_log_ms", "250")
    assert s.get("slow_query_log_ms") == 250


def test_stream_chunk_rows_defaults_and_types():
    s = Session()
    assert int(s.get("stream_chunk_rows")) == 0   # auto-engage
    s.set("stream_chunk_rows", "4096")
    assert s.get("stream_chunk_rows") == 4096
    s.set("stream_chunk_rows", -1)                # disabled
    assert s.get("stream_chunk_rows") == -1


def test_prewarm_properties_defaults_and_types():
    s = Session()
    assert isinstance(s.get("prewarm_enabled"), bool)
    assert int(s.get("hot_shape_top_k")) > 0
    s.set("prewarm_enabled", "false")
    assert s.get("prewarm_enabled") is False
    s.set("hot_shape_top_k", "3")
    assert s.get("hot_shape_top_k") == 3


def test_multistage_execution_gates_the_stage_fragmenter():
    """The stage-DAG path IS the engine (default ON since PR 13); the
    session property is the explicit fallback knob to the flat
    scatter-gather path (end-to-end behavior in test_stage_mpp.py)."""
    from trino_tpu.exec.remote import RemoteScheduler
    sched = RemoteScheduler.__new__(RemoteScheduler)
    sched.session = Session()
    assert sched._multistage_enabled()
    sched.session.set("multistage_execution", False)
    assert not sched._multistage_enabled()
    assert int(sched.session.get("exchange_partition_count")) == 0
    # the pipelining + ICI knobs ship default-on next to it
    assert sched.session.get("stage_pipelining") is True
    assert sched.session.get("ici_exchange") is True


def test_unknown_property_rejected():
    s = Session()
    with pytest.raises(KeyError):
        s.set("no_such_property", "1")


def test_query_max_run_time_fails_with_time_limit_error():
    """Deterministic on any backend speed: the scan blocks in the
    connector, the 1s deadline fires, and the client sees the query
    FAIL with EXCEEDED_TIME_LIMIT (the reference's QUERY_MAX_RUN_TIME
    semantics — a deadline breach is an engine failure with its own
    error identity, not a user cancel) long before the scan would
    finish."""
    from trino_tpu.catalog import CatalogManager
    from trino_tpu.connectors.tpch import TpchConnector

    class SlowTpch(TpchConnector):
        def read_split(self, split, columns):
            time.sleep(8)
            return super().read_split(split, columns)

    cats = CatalogManager()
    cats.register("tpch", SlowTpch())
    coord = Coordinator(catalogs=cats).start()
    try:
        c = StatementClient(
            coord.base_uri, catalog="tpch", schema="tiny",
            session_properties={"query_max_run_time": "1"})
        t0 = time.time()
        with pytest.raises(Exception, match="EXCEEDED_TIME_LIMIT"):
            c.execute("SELECT count(*) FROM nation")
        assert time.time() - t0 < 7   # stopped, not completed
    finally:
        coord.stop()


def test_exchange_compression_off_serves_store_frames():
    import struct
    from trino_tpu.serde import CODEC_LZ4, CODEC_STORE
    from trino_tpu.server.task_worker import (RemoteTaskClient,
                                              TaskWorkerServer)
    import urllib.request
    from trino_tpu.serde import native_available
    srv = TaskWorkerServer().start()
    try:
        c = RemoteTaskClient(srv.base_uri)
        sql = "SELECT o_comment FROM orders LIMIT 2000"
        # without the native library the default codec is already STORE
        default_codec = CODEC_LZ4 if native_available() else CODEC_STORE
        for tid, props, want in (
                ("t-lz4", {}, default_codec),
                ("t-raw", {"exchange_compression": "false"},
                 CODEC_STORE)):
            c.submit(tid, sql, properties=props)
            # raw frame: codec byte sits right after the 4-byte magic
            with urllib.request.urlopen(
                    f"{srv.base_uri}/v1/task/{tid}/results/0") as r:
                while r.status == 202:
                    r.close()
                    r = urllib.request.urlopen(
                        f"{srv.base_uri}/v1/task/{tid}/results/0")
                body = r.read()
            (codec,) = struct.unpack_from("<B", body, 4)
            assert codec == want, (tid, codec)
    finally:
        srv.stop()


def test_use_table_statistics_changes_plans():
    from trino_tpu.planner.logical import LogicalPlanner
    from trino_tpu.planner.optimizer import optimize
    from trino_tpu.sql.parser import parse_statement
    r = LocalQueryRunner(session=Session(catalog="tpch", schema="tiny"))
    sql = ("SELECT count(*) FROM lineitem, orders, customer "
           "WHERE l_orderkey = o_orderkey AND o_custkey = c_custkey")
    stmt = parse_statement(sql)

    def plan_for(use_stats):
        s = Session(catalog="tpch", schema="tiny")
        s.set("use_table_statistics", use_stats)
        return optimize(LogicalPlanner(r.catalogs, s).plan(stmt),
                        r.catalogs, s)

    from trino_tpu.plan.nodes import JoinNode

    def joins(p):
        out = []
        stack = [p]
        while stack:
            n = stack.pop()
            if isinstance(n, JoinNode):
                out.append(n)
            stack.extend(n.sources)
        return out

    with_stats = joins(plan_for(True))
    without = joins(plan_for(False))
    assert any(j.distribution is not None for j in with_stats)
    assert all(j.distribution is None for j in without)
    # and the result is identical either way
    r.session.set("use_table_statistics", False)
    no_stats_rows = r.execute(sql).rows
    r.session.reset("use_table_statistics")
    assert no_stats_rows == r.execute(sql).rows


def test_join_distribution_type_forced_partitioned():
    from trino_tpu.planner.logical import LogicalPlanner
    from trino_tpu.planner.optimizer import optimize
    from trino_tpu.plan.nodes import JoinNode
    from trino_tpu.sql.parser import parse_statement
    r = LocalQueryRunner(session=Session(catalog="tpch", schema="tiny"))
    sql = ("SELECT count(*) FROM lineitem JOIN orders "
           "ON l_orderkey = o_orderkey")
    s = Session(catalog="tpch", schema="tiny")
    s.set("join_distribution_type", "PARTITIONED")
    plan = optimize(LogicalPlanner(r.catalogs, s).plan(
        parse_statement(sql)), r.catalogs, s)
    stack, dists = [plan], []
    while stack:
        n = stack.pop()
        if isinstance(n, JoinNode):
            dists.append(n.distribution)
        stack.extend(n.sources)
    assert dists and all(d == "partitioned" for d in dists)
