"""Bitwise / digest / URL / misc scalar functions.

Reference parity: operator/scalar/BitwiseFunctions.java,
VarbinaryFunctions (digests — ours return hex varchar),
UrlFunctions.java, StringFunctions.translate, MathFunctions.log.
"""

import pytest

from trino_tpu.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def q(runner, sql):
    return runner.execute(sql).rows


def test_bitwise(runner):
    assert q(runner, "SELECT bitwise_and(12, 10), bitwise_or(12, 10), "
                     "bitwise_xor(12, 10), bitwise_not(0), "
                     "bitwise_left_shift(1, 4), "
                     "bitwise_right_shift(16, 2)") == \
        [[8, 14, 6, -1, 16, 4]]


def test_bit_count(runner):
    assert q(runner, "SELECT bit_count(7, 64), bit_count(255, 8), "
                     "bit_count(-1, 64)") == [[3, 8, 64]]


def test_digests(runner):
    got = q(runner, "SELECT md5('abc'), sha256('abc'), crc32('abc')")
    assert got[0][0] == "900150983cd24fb0d6963f7d28e17f72"
    assert got[0][1].startswith("ba7816bf8f01cfea")
    assert got[0][2] == 891568578


def test_xxhash64_known_vectors(runner):
    # cross-checked against the reference xxHash64 test vectors
    from trino_tpu.exec.expr import _xxh64_py
    assert _xxh64_py(b"") == 0xEF46DB3751D8E999
    assert _xxh64_py(b"a") == 0xD24EC4F1A98C6E5B
    got = q(runner, "SELECT xxhash64('hello')")
    assert isinstance(got[0][0], int)


def test_url_functions(runner):
    url = "'https://user@example.com:8080/path/x?a=1&b=two#frag'"
    got = q(runner, f"SELECT url_extract_protocol({url}), "
                    f"url_extract_host({url}), "
                    f"url_extract_port({url}), "
                    f"url_extract_path({url}), "
                    f"url_extract_query({url}), "
                    f"url_extract_fragment({url}), "
                    f"url_extract_parameter({url}, 'b')")
    assert got == [['https', 'example.com', 8080, '/path/x',
                    'a=1&b=two', 'frag', 'two']]


def test_url_encode_decode(runner):
    assert q(runner, "SELECT url_encode('a b&c'), "
                     "url_decode('a+b%26c')") == [['a+b%26c', 'a b&c']]


def test_translate_hex_log(runner):
    assert q(runner, "SELECT translate('hello', 'el', 'ip'), "
                     "to_hex(255), log(2, 8)") == \
        [['hippo', 'FF', 3.0]]


def test_over_table_rows(runner):
    got = q(runner, "SELECT count(DISTINCT md5(n_name)) FROM "
                    "tpch.tiny.nation")
    assert got == [[25]]
