"""Bitwise / digest / URL / misc scalar functions.

Reference parity: operator/scalar/BitwiseFunctions.java,
VarbinaryFunctions (digests — ours return hex varchar),
UrlFunctions.java, StringFunctions.translate, MathFunctions.log.
"""

import pytest

from trino_tpu.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def q(runner, sql):
    return runner.execute(sql).rows


def test_bitwise(runner):
    assert q(runner, "SELECT bitwise_and(12, 10), bitwise_or(12, 10), "
                     "bitwise_xor(12, 10), bitwise_not(0), "
                     "bitwise_left_shift(1, 4), "
                     "bitwise_right_shift(16, 2)") == \
        [[8, 14, 6, -1, 16, 4]]


def test_bit_count(runner):
    assert q(runner, "SELECT bit_count(7, 64), bit_count(255, 8), "
                     "bit_count(-1, 64)") == [[3, 8, 64]]


def test_digests(runner):
    got = q(runner, "SELECT md5('abc'), sha256('abc'), crc32('abc')")
    assert got[0][0] == "900150983cd24fb0d6963f7d28e17f72"
    assert got[0][1].startswith("ba7816bf8f01cfea")
    assert got[0][2] == 891568578


def test_xxhash64_known_vectors(runner):
    # cross-checked against the reference xxHash64 test vectors
    from trino_tpu.exec.expr import _xxh64_py
    assert _xxh64_py(b"") == 0xEF46DB3751D8E999
    assert _xxh64_py(b"a") == 0xD24EC4F1A98C6E5B
    got = q(runner, "SELECT xxhash64('hello')")
    assert isinstance(got[0][0], int)


def test_url_functions(runner):
    url = "'https://user@example.com:8080/path/x?a=1&b=two#frag'"
    got = q(runner, f"SELECT url_extract_protocol({url}), "
                    f"url_extract_host({url}), "
                    f"url_extract_port({url}), "
                    f"url_extract_path({url}), "
                    f"url_extract_query({url}), "
                    f"url_extract_fragment({url}), "
                    f"url_extract_parameter({url}, 'b')")
    assert got == [['https', 'example.com', 8080, '/path/x',
                    'a=1&b=two', 'frag', 'two']]


def test_url_encode_decode(runner):
    assert q(runner, "SELECT url_encode('a b&c'), "
                     "url_decode('a+b%26c')") == [['a+b%26c', 'a b&c']]


def test_translate_hex_log(runner):
    assert q(runner, "SELECT translate('hello', 'el', 'ip'), "
                     "to_hex(255), log(2, 8)") == \
        [['hippo', 'FF', 3.0]]


def test_over_table_rows(runner):
    got = q(runner, "SELECT count(DISTINCT md5(n_name)) FROM "
                    "tpch.tiny.nation")
    assert got == [[25]]


def test_regexp_family(runner):
    assert q(runner,
             "SELECT regexp_extract('1a 2b 14m', '(\\d+)([a-z]+)', 2), "
             "regexp_replace('1a 2b 14m', '(\\d+)([a-z]+)', '$2'), "
             "regexp_extract_all('1a 2b 14m', '\\d+')") == \
        [["a", "a b m", ["1", "2", "14"]]]
    assert q(runner, "SELECT regexp_split('one,two,,three', ',')") == \
        [[["one", "two", "", "three"]]]


def test_split_functions(runner):
    assert q(runner, "SELECT split('a.b.c', '.'), "
                     "split('a.b.c', '.', 2), "
                     "split_part('a.b.c', '.', 2)") == \
        [[["a", "b", "c"], ["a", "b.c"], "b"]]
    assert q(runner, "SELECT split_to_map('a=1,b=2', ',', '=')") == \
        [[{"a": "1", "b": "2"}]]


def test_array_join(runner):
    assert q(runner, "SELECT array_join(ARRAY['x','y','z'], '-'), "
                     "array_join(ARRAY[1, 2, 3], ','), "
                     "array_join(ARRAY['a', NULL, 'c'], ',', 'N')") == \
        [["x-y-z", "1,2,3", "a,N,c"]]


def test_string_distance_and_misc(runner):
    assert q(runner,
             "SELECT levenshtein_distance('kitten', 'sitting'), "
             "hamming_distance('karolin', 'kathrin'), "
             "codepoint('A'), chr(66), "
             "normalize('Å'), "
             "concat_ws('-', 'a', NULL, 'b')") == \
        [[3, 3, 65, "B", "Å", "a-b"]]


def test_math_constants(runner):
    import math
    got = q(runner, "SELECT pi(), e(), atan2(1, 1), "
                    "width_bucket(5.3, 0.2, 10.6, 5), "
                    "is_nan(nan()), infinity() > 1e308")[0]
    assert abs(got[0] - math.pi) < 1e-12
    assert abs(got[1] - math.e) < 1e-12
    assert abs(got[2] - math.pi / 4) < 1e-12
    assert got[3:] == [3, True, True]


def test_bases_and_format(runner):
    assert q(runner, "SELECT to_base(255, 16), from_base('ff', 16), "
                     "format('%s=%d [%.2f]', 'x', 42, 1.5), "
                     "format('%,d', 1234567)") == \
        [["ff", 255, "x=42 [1.50]", "1,234,567"]]


def test_typeof_and_time(runner):
    assert q(runner, "SELECT typeof(1), typeof('x'), typeof(1.5e0)") == \
        [["integer", "varchar(1)", "double"]]
    got = q(runner, "SELECT current_date, year(current_date), "
                    "now() > TIMESTAMP '2020-01-01 00:00:00'")[0]
    assert got[1] >= 2026 and got[2] is True


def test_year_of_week(runner):
    # 2005-01-01 was a Saturday of ISO week 53 of 2004
    assert q(runner, "SELECT year_of_week(DATE '2005-01-01'), "
                     "year_of_week(DATE '2008-12-31')") == [[2004, 2009]]


def test_random(runner):
    got = q(runner, "SELECT random(), random(10) FROM lineitem LIMIT 5")
    assert all(0.0 <= r[0] < 1.0 and 0 <= r[1] < 10 for r in got)
