"""Bitwise / digest / URL / misc scalar functions.

Reference parity: operator/scalar/BitwiseFunctions.java,
VarbinaryFunctions (digests — ours return hex varchar),
UrlFunctions.java, StringFunctions.translate, MathFunctions.log.
"""

import pytest

from trino_tpu.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def q(runner, sql):
    return runner.execute(sql).rows


def test_bitwise(runner):
    assert q(runner, "SELECT bitwise_and(12, 10), bitwise_or(12, 10), "
                     "bitwise_xor(12, 10), bitwise_not(0), "
                     "bitwise_left_shift(1, 4), "
                     "bitwise_right_shift(16, 2)") == \
        [[8, 14, 6, -1, 16, 4]]


def test_bit_count(runner):
    assert q(runner, "SELECT bit_count(7, 64), bit_count(255, 8), "
                     "bit_count(-1, 64)") == [[3, 8, 64]]


def test_digests(runner):
    got = q(runner, "SELECT md5('abc'), sha256('abc'), crc32('abc')")
    assert got[0][0] == "900150983cd24fb0d6963f7d28e17f72"
    assert got[0][1].startswith("ba7816bf8f01cfea")
    assert got[0][2] == 891568578


def test_xxhash64_known_vectors(runner):
    # cross-checked against the reference xxHash64 test vectors
    from trino_tpu.exec.expr import _xxh64_py
    assert _xxh64_py(b"") == 0xEF46DB3751D8E999
    assert _xxh64_py(b"a") == 0xD24EC4F1A98C6E5B
    got = q(runner, "SELECT xxhash64('hello')")
    assert isinstance(got[0][0], int)


def test_url_functions(runner):
    url = "'https://user@example.com:8080/path/x?a=1&b=two#frag'"
    got = q(runner, f"SELECT url_extract_protocol({url}), "
                    f"url_extract_host({url}), "
                    f"url_extract_port({url}), "
                    f"url_extract_path({url}), "
                    f"url_extract_query({url}), "
                    f"url_extract_fragment({url}), "
                    f"url_extract_parameter({url}, 'b')")
    assert got == [['https', 'example.com', 8080, '/path/x',
                    'a=1&b=two', 'frag', 'two']]


def test_url_encode_decode(runner):
    assert q(runner, "SELECT url_encode('a b&c'), "
                     "url_decode('a+b%26c')") == [['a+b%26c', 'a b&c']]


def test_translate_hex_log(runner):
    assert q(runner, "SELECT translate('hello', 'el', 'ip'), "
                     "to_hex(255), log(2, 8)") == \
        [['hippo', 'FF', 3.0]]


def test_over_table_rows(runner):
    got = q(runner, "SELECT count(DISTINCT md5(n_name)) FROM "
                    "tpch.tiny.nation")
    assert got == [[25]]


def test_regexp_family(runner):
    assert q(runner,
             "SELECT regexp_extract('1a 2b 14m', '(\\d+)([a-z]+)', 2), "
             "regexp_replace('1a 2b 14m', '(\\d+)([a-z]+)', '$2'), "
             "regexp_extract_all('1a 2b 14m', '\\d+')") == \
        [["a", "a b m", ["1", "2", "14"]]]
    assert q(runner, "SELECT regexp_split('one,two,,three', ',')") == \
        [[["one", "two", "", "three"]]]


def test_split_functions(runner):
    assert q(runner, "SELECT split('a.b.c', '.'), "
                     "split('a.b.c', '.', 2), "
                     "split_part('a.b.c', '.', 2)") == \
        [[["a", "b", "c"], ["a", "b.c"], "b"]]
    assert q(runner, "SELECT split_to_map('a=1,b=2', ',', '=')") == \
        [[{"a": "1", "b": "2"}]]


def test_array_join(runner):
    assert q(runner, "SELECT array_join(ARRAY['x','y','z'], '-'), "
                     "array_join(ARRAY[1, 2, 3], ','), "
                     "array_join(ARRAY['a', NULL, 'c'], ',', 'N')") == \
        [["x-y-z", "1,2,3", "a,N,c"]]


def test_string_distance_and_misc(runner):
    assert q(runner,
             "SELECT levenshtein_distance('kitten', 'sitting'), "
             "hamming_distance('karolin', 'kathrin'), "
             "codepoint('A'), chr(66), "
             "normalize('Å'), "
             "concat_ws('-', 'a', NULL, 'b')") == \
        [[3, 3, 65, "B", "Å", "a-b"]]


def test_math_constants(runner):
    import math
    got = q(runner, "SELECT pi(), e(), atan2(1, 1), "
                    "width_bucket(5.3, 0.2, 10.6, 5), "
                    "is_nan(nan()), infinity() > 1e308")[0]
    assert abs(got[0] - math.pi) < 1e-12
    assert abs(got[1] - math.e) < 1e-12
    assert abs(got[2] - math.pi / 4) < 1e-12
    assert got[3:] == [3, True, True]


def test_bases_and_format(runner):
    assert q(runner, "SELECT to_base(255, 16), from_base('ff', 16), "
                     "format('%s=%d [%.2f]', 'x', 42, 1.5), "
                     "format('%,d', 1234567)") == \
        [["ff", 255, "x=42 [1.50]", "1,234,567"]]


def test_typeof_and_time(runner):
    assert q(runner, "SELECT typeof(1), typeof('x'), typeof(1.5e0)") == \
        [["integer", "varchar(1)", "double"]]
    got = q(runner, "SELECT current_date, year(current_date), "
                    "now() > TIMESTAMP '2020-01-01 00:00:00'")[0]
    assert got[1] >= 2026 and got[2] is True


def test_year_of_week(runner):
    # 2005-01-01 was a Saturday of ISO week 53 of 2004
    assert q(runner, "SELECT year_of_week(DATE '2005-01-01'), "
                     "year_of_week(DATE '2008-12-31')") == [[2004, 2009]]


def test_random(runner):
    got = q(runner, "SELECT random(), random(10) FROM lineitem LIMIT 5")
    assert all(0.0 <= r[0] < 1.0 and 0 <= r[1] < 10 for r in got)


# -- round-4 scalar breadth -------------------------------------------------

def test_hmac(runner):
    import hashlib
    import hmac as hm
    exp = hm.new(b"key", b"hello", hashlib.sha256).hexdigest()
    assert q(runner, "SELECT hmac_sha256('hello', 'key')") == [[exp]]
    exp = hm.new(b"k", b"v", hashlib.md5).hexdigest()
    assert q(runner, "SELECT hmac_md5('v', 'k')") == [[exp]]


def test_utf8_roundtrip(runner):
    assert q(runner, "SELECT from_utf8(to_utf8('héllo'))") == [["héllo"]]


def test_big_endian_roundtrip(runner):
    assert q(runner, "SELECT from_big_endian_64(to_big_endian_64(x)) "
                     "FROM (VALUES 0, 1, -1, 1234567890123) t(x)") == \
        [[0], [1], [-1], [1234567890123]]


def test_ieee754_roundtrip(runner):
    assert q(runner, "SELECT from_ieee754_64(to_ieee754_64(x)) "
                     "FROM (VALUES 0.5e0, -2.25e0) t(x)") == \
        [[0.5], [-2.25]]


def test_bar(runner):
    (b,), = q(runner, "SELECT bar(0.5e0, 10)")
    assert len(b) == 10 and b.startswith("█████ ")


def test_parse_format_datetime(runner):
    got = q(runner, "SELECT format_datetime(TIMESTAMP "
                    "'2001-08-22 03:04:05.321', 'yyyy-MM-dd HH:mm:ss')")
    assert got == [["2001-08-22 03:04:05"]]
    got = q(runner, "SELECT year(parse_datetime('2020/06/10', "
                    "'yyyy/MM/dd'))")
    assert got == [[2020]]


def test_from_iso8601(runner):
    import datetime
    got = q(runner, "SELECT from_iso8601_date('2020-05-11'), "
                    "hour(from_iso8601_timestamp("
                    "'2020-05-11T11:15:05+02:00'))")
    assert got == [[datetime.date(2020, 5, 11), 11]]


def test_last_day_of_month(runner):
    import datetime
    assert q(runner, "SELECT last_day_of_month(DATE '2024-02-11'), "
                     "last_day_of_month(DATE '2023-02-01')") == \
        [[datetime.date(2024, 2, 29), datetime.date(2023, 2, 28)]]


def test_timezone_parts(runner):
    got = q(runner, "SELECT timezone_hour(from_iso8601_timestamp("
                    "'2020-05-11T11:15:05+05:30')), "
                    "timezone_minute(from_iso8601_timestamp("
                    "'2020-05-11T11:15:05+05:30'))")
    assert got == [[5, 30]]


def test_word_stem(runner):
    assert q(runner, "SELECT word_stem('running'), word_stem('cats'), "
                     "word_stem('nationalization')") == \
        [["run", "cat", "nationalize"]]


def test_json_parse_format(runner):
    assert q(runner, "SELECT json_format(json_parse("
                     "' {\"a\" : 1, \"b\": [1, 2]} '))") == \
        [['{"a":1,"b":[1,2]}']]


def test_cosine_similarity(runner):
    got = q(runner, "SELECT cosine_similarity("
                    "map(ARRAY['a', 'b'], ARRAY[1.0e0, 2.0e0]), "
                    "map(ARRAY['a', 'b'], ARRAY[1.0e0, 2.0e0]))")
    assert abs(got[0][0] - 1.0) < 1e-12
    got = q(runner, "SELECT cosine_similarity("
                    "map(ARRAY['a'], ARRAY[1.0e0]), "
                    "map(ARRAY['b'], ARRAY[1.0e0]))")
    assert got == [[0.0]]


def test_array_remove_zip(runner):
    assert q(runner, "SELECT array_remove(ARRAY[1, 2, 1, 3], 1)") == \
        [[[2, 3]]]
    assert q(runner, "SELECT zip(ARRAY[1, 2], ARRAY['a', 'b', 'c'])") \
        == [[[[1, "a"], [2, "b"], [None, "c"]]]]


def test_ngrams_combinations(runner):
    assert q(runner, "SELECT ngrams(ARRAY['a', 'b', 'c', 'd'], 2)") == \
        [[[["a", "b"], ["b", "c"], ["c", "d"]]]]
    assert q(runner, "SELECT combinations(ARRAY[1, 2, 3], 2)") == \
        [[[[1, 2], [1, 3], [2, 3]]]]


def test_array_first_last(runner):
    assert q(runner, "SELECT array_first(ARRAY[5, 6, 7]), "
                     "array_last(ARRAY[5, 6, 7])") == [[5, 7]]


def test_map_from_entries(runner):
    got = q(runner, "SELECT map_from_entries(ARRAY["
                    "ROW('a', 1), ROW('b', 2)])")
    assert got == [[{"a": 1, "b": 2}]]
    got = q(runner, "SELECT multimap_from_entries(ARRAY["
                    "ROW('a', 1), ROW('a', 2), ROW('b', 3)])")
    assert got == [[{"a": [1, 2], "b": [3]}]]


def test_split_to_multimap(runner):
    got = q(runner, "SELECT split_to_multimap("
                    "'a=1,b=2,a=3', ',', '=')")
    assert got == [[{"a": ["1", "3"], "b": ["2"]}]]


def test_hmac_over_varbinary_bytes(runner):
    import hashlib
    import hmac as hm
    import struct
    exp = hm.new(b"k", struct.pack(">q", 200), hashlib.sha256).hexdigest()
    assert q(runner, "SELECT hmac_sha256(to_big_endian_64(200), 'k')") \
        == [[exp]]


def test_format_datetime_millis_no_collision(runner):
    got = q(runner, "SELECT format_datetime(TIMESTAMP "
                    "'2024-01-01 00:10:00.001', 'HHmmSSS')")
    assert got == [["0010001"]]
