"""UPDATE / MERGE / SHOW STATS.

Reference parity: UpdateOperator + MERGE row-change plans and
sql/rewrite/ShowStatsRewrite.java, executed against the memory
connector's swap-contents write path.
"""

import pytest

from trino_tpu.runner import LocalQueryRunner


@pytest.fixture()
def runner():
    r = LocalQueryRunner()
    r.execute("CREATE TABLE memory.default.acct AS "
              "SELECT * FROM (VALUES (1, 'alice', 100.0), "
              "(2, 'bob', 250.0), (3, 'carol', 0.0), "
              "(4, 'dan', 75.0)) t(id, name, balance)")
    return r


def rows(r, sql):
    return r.execute(sql).rows


def test_update_where(runner):
    res = runner.execute(
        "UPDATE memory.default.acct SET balance = balance + 10 "
        "WHERE balance < 100")
    assert res.update_count == 2
    got = rows(runner, "SELECT id, balance FROM memory.default.acct "
                       "ORDER BY id")
    assert got == [[1, 100.0], [2, 250.0], [3, 10.0], [4, 85.0]]


def test_update_all_and_multiple_columns(runner):
    res = runner.execute(
        "UPDATE memory.default.acct SET balance = 0, name = 'x'")
    assert res.update_count == 4
    got = rows(runner, "SELECT DISTINCT name, balance "
                       "FROM memory.default.acct")
    assert got == [["x", 0.0]]


def test_update_unknown_column(runner):
    with pytest.raises(Exception, match="does not exist"):
        runner.execute("UPDATE memory.default.acct SET nope = 1")


def test_merge_update_insert_delete(runner):
    runner.execute(
        "CREATE TABLE memory.default.delta AS "
        "SELECT * FROM (VALUES (2, 40.0), (3, -1.0), (9, 500.0)) "
        "t(id, amount)")
    res = runner.execute(
        "MERGE INTO memory.default.acct a "
        "USING memory.default.delta d ON a.id = d.id "
        "WHEN MATCHED AND d.amount < 0 THEN DELETE "
        "WHEN MATCHED THEN UPDATE SET balance = balance + d.amount "
        "WHEN NOT MATCHED THEN INSERT (id, name, balance) "
        "VALUES (d.id, 'new', d.amount)")
    assert res.update_count == 3
    got = rows(runner, "SELECT id, name, balance "
                       "FROM memory.default.acct ORDER BY id")
    assert got == [[1, "alice", 100.0], [2, "bob", 290.0],
                   [4, "dan", 75.0], [9, "new", 500.0]]


def test_merge_not_matched_condition(runner):
    runner.execute(
        "CREATE TABLE memory.default.adds AS "
        "SELECT * FROM (VALUES (7, 5.0), (8, -3.0)) t(id, amount)")
    res = runner.execute(
        "MERGE INTO memory.default.acct a "
        "USING memory.default.adds d ON a.id = d.id "
        "WHEN NOT MATCHED AND d.amount > 0 THEN "
        "INSERT (id, name, balance) VALUES (d.id, 'pos', d.amount)")
    assert res.update_count == 1
    got = rows(runner, "SELECT id FROM memory.default.acct "
                       "WHERE id >= 7 ORDER BY id")
    assert got == [[7]]


def test_show_stats(runner):
    got = rows(runner, "SHOW STATS FOR tpch.tiny.lineitem")
    by_col = {r[0]: r for r in got}
    assert None in by_col                      # summary row
    assert by_col[None][4] > 50000             # row_count estimate
    qty = by_col["l_quantity"]
    assert qty[2] == 50.0                      # NDV
    assert float(qty[5]) == 1.0 and float(qty[6]) == 50.0
    # memory connector: no stats -> NULL cells, but all columns listed
    got2 = rows(runner, "SHOW STATS FOR memory.default.acct")
    assert {r[0] for r in got2} == {"id", "name", "balance", None}
