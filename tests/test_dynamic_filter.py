"""Dynamic filtering tests (reference: server/DynamicFilterService.java
+ operator/DynamicFilterSourceOperator.java): build-side key domains
prune probe rows before the exchange in distributed inner joins."""

import pytest

from trino_tpu.exec.distributed import DistributedExecutor
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session

SQL = ("SELECT count(*), sum(l_extendedprice) FROM tpch.tiny.lineitem "
       "JOIN (SELECT o_orderkey FROM tpch.tiny.orders "
       "      WHERE o_totalprice > 400000) t "
       "ON l_orderkey = o_orderkey")


@pytest.fixture(scope="module")
def runners():
    return (LocalQueryRunner(),
            LocalQueryRunner(distributed=True, n_devices=8))


def test_dynamic_filter_correct_and_effective(runners):
    local, dist = runners
    assert dist.execute(SQL).rows == local.execute(SQL).rows
    ex = DistributedExecutor(dist.catalogs,
                             Session(catalog="tpch", schema="tiny"),
                             collect_stats=True)
    ex.execute(dist.plan_sql(SQL))
    before, after = ex.dynamic_filter_rows
    # exchange input drops by >99% on this shape (~22 hot orders)
    assert after < before * 0.01


def test_dynamic_filter_flag_disables(runners):
    _, dist = runners
    s = Session(catalog="tpch", schema="tiny")
    s.set("enable_dynamic_filtering", "false")
    ex = DistributedExecutor(dist.catalogs, s)
    ex.execute(dist.plan_sql(SQL))
    assert not hasattr(ex, "dynamic_filter_rows")


def test_dynamic_filter_left_join_untouched(runners):
    local, dist = runners
    sql = ("SELECT count(*), count(t.o_orderkey) FROM "
           "tpch.tiny.lineitem LEFT JOIN "
           "(SELECT o_orderkey FROM tpch.tiny.orders "
           " WHERE o_totalprice > 400000) t "
           "ON l_orderkey = t.o_orderkey")
    assert dist.execute(sql).rows == local.execute(sql).rows


def test_dynamic_filter_empty_build(runners):
    local, dist = runners
    sql = ("SELECT count(*) FROM tpch.tiny.lineitem JOIN "
           "(SELECT o_orderkey FROM tpch.tiny.orders "
           " WHERE o_totalprice > 99999999) t "
           "ON l_orderkey = t.o_orderkey")
    assert dist.execute(sql).rows == local.execute(sql).rows == [[0]]
