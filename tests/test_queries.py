"""End-to-end SQL tests on the tpch tiny catalog.

Reference parity: testing/trino-testing AbstractTestQueries +
H2QueryRunner cross-checking (SURVEY.md §4 tier 2) — here the oracle is
independent numpy computation over the same generated columns.
"""

import math

import numpy as np
import pytest

from trino_tpu.catalog import TableHandle
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.exec import QueryError
from trino_tpu.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


@pytest.fixture(scope="module")
def lineitem_np():
    """Raw tiny lineitem columns via the connector, as numpy."""
    c = TpchConnector()
    h = TableHandle("tpch", "tiny", "lineitem")
    cols = ["l_orderkey", "l_quantity", "l_extendedprice", "l_discount",
            "l_tax", "l_shipdate", "l_returnflag", "l_linestatus"]
    batches = [c.read_split(s, cols) for s in c.get_splits(h)]
    out = {}
    for name in cols:
        parts = []
        for b in batches:
            n = b.num_rows_host()
            col = b.column(name)
            if col.dictionary is not None:
                vals = col.dictionary.values
                parts.append(np.asarray(
                    [vals[i] for i in np.asarray(col.data)[:n]],
                    dtype=object))
            else:
                parts.append(np.asarray(col.data)[:n])
        out[name] = np.concatenate(parts)
    return out


def test_scan_sum(runner, lineitem_np):
    res = runner.execute(
        "SELECT sum(l_extendedprice) FROM tpch.tiny.lineitem")
    expected = float(np.sum(lineitem_np["l_extendedprice"]))
    assert res.columns == ["sum"]
    assert res.rows[0][0] == pytest.approx(expected, rel=1e-12)


def test_count_star(runner, lineitem_np):
    res = runner.execute("SELECT count(*) FROM lineitem")
    assert res.rows[0][0] == len(lineitem_np["l_orderkey"])


def test_filter_where(runner, lineitem_np):
    res = runner.execute(
        "SELECT count(*) FROM lineitem WHERE l_quantity > 45")
    expected = int(np.sum(lineitem_np["l_quantity"] > 45))
    assert res.rows[0][0] == expected


def test_group_by(runner, lineitem_np):
    res = runner.execute(
        "SELECT l_returnflag, count(*) AS c, sum(l_quantity) AS q "
        "FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag")
    flags = lineitem_np["l_returnflag"]
    qty = lineitem_np["l_quantity"]
    expected = sorted(
        (f, int(np.sum(flags == f)), float(qty[flags == f].sum()))
        for f in set(flags))
    assert len(res.rows) == len(expected)
    for row, (f, c, q) in zip(res.rows, expected):
        assert row[0] == f
        assert row[1] == c
        assert row[2] == pytest.approx(q, rel=1e-12)


def test_avg_null_semantics(runner):
    res = runner.execute(
        "SELECT avg(x), count(x), count(*), sum(x), min(x), max(x) "
        "FROM (VALUES (1), (2), (NULL), (5)) AS t(x)")
    row = res.rows[0]
    assert row == [pytest.approx(8 / 3), 3, 4, 8, 1, 5]


def test_all_null_aggregates(runner):
    res = runner.execute(
        "SELECT sum(x), min(x), count(x) FROM "
        "(VALUES (CAST(NULL AS integer)), (NULL)) AS t(x)")
    assert res.rows[0] == [None, None, 0]


def test_expressions(runner):
    res = runner.execute(
        "SELECT 1 + 2 * 3, 10 / 3, 10 % 3, -abs(-5), "
        "CASE WHEN 1 > 2 THEN 'a' ELSE 'b' END, "
        "coalesce(NULL, NULL, 7), nullif(3, 3)")
    assert res.rows[0] == [7, 3, 1, -5, "b", 7, None]


def test_three_valued_logic(runner):
    res = runner.execute(
        "SELECT (NULL AND false), (NULL AND true), (NULL OR true), "
        "(NULL OR false), (NOT NULL) "
        "FROM (VALUES (1)) AS t(x)")
    assert res.rows[0] == [False, None, True, None, None]


def test_inner_join(runner):
    res = runner.execute("""
        SELECT o.o_orderkey, count(*) AS n
        FROM orders o JOIN lineitem l ON o.o_orderkey = l.l_orderkey
        GROUP BY o.o_orderkey ORDER BY n DESC, o.o_orderkey LIMIT 5
    """)
    assert len(res.rows) == 5
    assert res.rows[0][1] == 7  # max lineitems per order


def test_join_row_counts(runner, lineitem_np):
    res = runner.execute(
        "SELECT count(*) FROM orders o, lineitem l "
        "WHERE o.o_orderkey = l.l_orderkey")
    # every lineitem has exactly one order
    assert res.rows[0][0] == len(lineitem_np["l_orderkey"])


def test_left_join_nulls(runner):
    res = runner.execute("""
        SELECT t.x, u.y FROM (VALUES (1), (2), (3)) AS t(x)
        LEFT JOIN (VALUES (1, 'a'), (3, 'c')) AS u(y2, y)
        ON t.x = u.y2 ORDER BY t.x
    """)
    assert res.rows == [[1, "a"], [2, None], [3, "c"]]


def test_semi_join_in(runner):
    res = runner.execute("""
        SELECT count(*) FROM orders
        WHERE o_orderkey IN (SELECT l_orderkey FROM lineitem
                             WHERE l_quantity = 50)
    """)
    ref = runner.execute("""
        SELECT count(DISTINCT l_orderkey) FROM lineitem
        WHERE l_quantity = 50
    """)
    assert res.rows[0][0] == ref.rows[0][0]


def test_in_null_semantics(runner):
    res = runner.execute("""
        SELECT x, x IN (SELECT y FROM (VALUES (1), (NULL)) AS s(y))
        FROM (VALUES (1), (2)) AS t(x) ORDER BY x
    """)
    assert res.rows == [[1, True], [2, None]]


def test_exists_correlated(runner):
    res = runner.execute("""
        SELECT count(*) FROM orders o
        WHERE EXISTS (SELECT 1 FROM lineitem l
                      WHERE l.l_orderkey = o.o_orderkey
                        AND l.l_quantity = 50)
    """)
    ref = runner.execute("""
        SELECT count(DISTINCT l_orderkey) FROM lineitem
        WHERE l_quantity = 50
    """)
    assert res.rows[0][0] == ref.rows[0][0]


def test_scalar_subquery_uncorrelated(runner, lineitem_np):
    res = runner.execute("""
        SELECT count(*) FROM lineitem
        WHERE l_quantity > (SELECT avg(l_quantity) FROM lineitem)
    """)
    avg = lineitem_np["l_quantity"].mean()
    assert res.rows[0][0] == int(np.sum(lineitem_np["l_quantity"] > avg))


def test_scalar_subquery_correlated(runner):
    # q17-style: per-part average
    res = runner.execute("""
        SELECT count(*) FROM lineitem l1
        WHERE l1.l_quantity < (
            SELECT avg(l2.l_quantity) FROM lineitem l2
            WHERE l2.l_orderkey = l1.l_orderkey)
    """)
    ref = runner.execute("""
        SELECT count(*) FROM lineitem l1 JOIN (
            SELECT l_orderkey, avg(l_quantity) AS a FROM lineitem
            GROUP BY l_orderkey) t ON l1.l_orderkey = t.l_orderkey
        WHERE l1.l_quantity < t.a
    """)
    assert res.rows[0][0] == ref.rows[0][0]


def test_order_by_limit_offset(runner):
    res = runner.execute(
        "SELECT x FROM (VALUES (3), (1), (2), (5), (4)) AS t(x) "
        "ORDER BY x DESC LIMIT 2 OFFSET 1")
    assert res.rows == [[4], [3]]


def test_distinct(runner):
    res = runner.execute(
        "SELECT DISTINCT l_linestatus FROM lineitem ORDER BY 1")
    assert res.rows == [["F"], ["O"]]


def test_union(runner):
    res = runner.execute(
        "SELECT x FROM (VALUES (1), (2)) AS t(x) UNION "
        "SELECT y FROM (VALUES (2), (3)) AS u(y) ORDER BY 1")
    assert res.rows == [[1], [2], [3]]
    res = runner.execute(
        "SELECT x FROM (VALUES (1), (2)) AS t(x) UNION ALL "
        "SELECT y FROM (VALUES (2)) AS u(y) ORDER BY 1")
    assert res.rows == [[1], [2], [2]]


def test_intersect_except(runner):
    res = runner.execute(
        "SELECT x FROM (VALUES (1), (2), (3)) AS t(x) INTERSECT "
        "SELECT y FROM (VALUES (2), (3), (4)) AS u(y) ORDER BY 1")
    assert res.rows == [[2], [3]]
    res = runner.execute(
        "SELECT x FROM (VALUES (1), (2), (3)) AS t(x) EXCEPT "
        "SELECT y FROM (VALUES (2)) AS u(y) ORDER BY 1")
    assert res.rows == [[1], [3]]


def test_like(runner):
    res = runner.execute(
        "SELECT count(*) FROM part WHERE p_type LIKE '%BRASS'")
    assert res.rows[0][0] > 0
    res2 = runner.execute(
        "SELECT count(*) FROM part WHERE p_type LIKE 'STANDARD%BRASS'")
    assert 0 < res2.rows[0][0] < res.rows[0][0]


def test_date_arithmetic(runner):
    res = runner.execute("""
        SELECT date '1998-12-01' - interval '90' day,
               date '1998-01-31' + interval '1' month,
               year(date '1995-06-17'), month(date '1995-06-17'),
               day(date '1995-06-17'), quarter(date '1995-06-17')
    """)
    import datetime
    row = res.rows[0]
    assert row[0] == datetime.date(1998, 9, 2)
    assert row[1] == datetime.date(1998, 2, 28)
    assert row[2:] == [1995, 6, 17, 2]


def test_between(runner, lineitem_np):
    res = runner.execute(
        "SELECT count(*) FROM lineitem "
        "WHERE l_discount BETWEEN 0.05 AND 0.07")
    d = lineitem_np["l_discount"]
    assert res.rows[0][0] == int(np.sum((d >= 0.05) & (d <= 0.07)))


def test_having(runner):
    res = runner.execute("""
        SELECT l_orderkey, count(*) AS c FROM lineitem
        GROUP BY l_orderkey HAVING count(*) >= 7
        ORDER BY l_orderkey LIMIT 3
    """)
    for row in res.rows:
        assert row[1] == 7


def test_cte(runner):
    res = runner.execute("""
        WITH big AS (SELECT * FROM lineitem WHERE l_quantity = 50)
        SELECT count(*) FROM big
    """)
    ref = runner.execute(
        "SELECT count(*) FROM lineitem WHERE l_quantity = 50")
    assert res.rows[0][0] == ref.rows[0][0]


def test_cross_check_error_messages(runner):
    with pytest.raises(QueryError, match="cannot be resolved"):
        runner.execute("SELECT nosuchcol FROM lineitem")
    with pytest.raises(QueryError, match="GROUP BY"):
        runner.execute(
            "SELECT l_orderkey, l_quantity FROM lineitem "
            "GROUP BY l_orderkey")
    with pytest.raises(QueryError):
        runner.execute("SELECT * FROM nosuchtable")


def test_show_statements(runner):
    assert ["lineitem"] in runner.execute("SHOW TABLES").rows
    cats = runner.execute("SHOW CATALOGS").rows
    assert ["tpch"] in cats and ["memory"] in cats
    cols = runner.execute("SHOW COLUMNS FROM lineitem").rows
    assert any(c[0] == "l_orderkey" and c[1] == "bigint" for c in cols)


def test_memory_connector_dml(runner):
    runner.execute("CREATE TABLE memory.default.t1 (a bigint, b varchar)")
    r = runner.execute(
        "INSERT INTO memory.default.t1 VALUES (1, 'x'), (2, 'y')")
    assert r.update_count == 2
    res = runner.execute(
        "SELECT a, b FROM memory.default.t1 ORDER BY a")
    assert res.rows == [[1, "x"], [2, "y"]]
    runner.execute("CREATE TABLE memory.default.t2 AS "
                   "SELECT a * 10 AS a10 FROM memory.default.t1")
    res = runner.execute("SELECT sum(a10) FROM memory.default.t2")
    assert res.rows[0][0] == 30
    runner.execute("DROP TABLE memory.default.t1")
    with pytest.raises(QueryError):
        runner.execute("SELECT * FROM memory.default.t1")


def test_window_row_number(runner):
    res = runner.execute("""
        SELECT x, row_number() OVER (PARTITION BY g ORDER BY x) AS rn
        FROM (VALUES ('a', 1), ('a', 3), ('a', 2), ('b', 5)) AS t(g, x)
        ORDER BY g, x
    """)
    assert res.rows == [["a" if False else 1, 1], [2, 2], [3, 3], [5, 1]] \
        or [r[1] for r in res.rows] == [1, 2, 3, 1]


def test_explain(runner):
    res = runner.execute(
        "EXPLAIN SELECT count(*) FROM lineitem WHERE l_quantity > 10")
    text = "\n".join(r[0] for r in res.rows)
    assert "TableScan" in text and "Aggregation" in text
