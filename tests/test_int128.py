"""Property tests for ops/int128.py against Python big-int arithmetic.

Covers the full DECIMAL(38) magnitude range (2^63 .. 10^38) that the
round-4 verdict flagged: single-lane int64 silently covered TPC-DS only
because values stayed under 2^63.
"""

import random

import numpy as np
import jax.numpy as jnp
import pytest

from trino_tpu.ops import int128 as i128

M128 = 1 << 128


def _to_signed128(q: int) -> int:
    q &= M128 - 1
    return q - M128 if q >= (1 << 127) else q


def _mk(vals):
    los, his = zip(*(i128.split_const(v) for v in vals))
    return (jnp.asarray(np.array(los, np.int64)),
            jnp.asarray(np.array(his, np.int64)))


def _back(lo, hi):
    lo = np.asarray(lo)
    hi = np.asarray(hi)
    return [i128.combine_host(int(l), int(h)) for l, h in zip(lo, hi)]


def _rand_vals(rng, n, lim=10 ** 38):
    out = []
    for _ in range(n):
        mag = rng.choice([10 ** 3, 2 ** 62, 2 ** 64, 10 ** 20, 10 ** 37,
                          lim - 1])
        out.append(rng.randint(-mag, mag))
    out += [0, 1, -1, 2 ** 63 - 1, -(2 ** 63), 2 ** 64, -(2 ** 64),
            10 ** 38 - 1, -(10 ** 38 - 1)]
    return out


@pytest.fixture(scope="module")
def rng():
    return random.Random(12345)


def test_split_combine_roundtrip(rng):
    vals = _rand_vals(rng, 50)
    lo, hi = _mk(vals)
    assert _back(lo, hi) == vals


def test_add_sub_neg(rng):
    a = _rand_vals(rng, 40)
    b = _rand_vals(rng, 40)[:len(a)]
    alo, ahi = _mk(a)
    blo, bhi = _mk(b)
    got = _back(*i128.add128(alo, ahi, blo, bhi))
    assert got == [_to_signed128(x + y) for x, y in zip(a, b)]
    got = _back(*i128.sub128(alo, ahi, blo, bhi))
    assert got == [_to_signed128(x - y) for x, y in zip(a, b)]
    got = _back(*i128.neg128(alo, ahi))
    assert got == [_to_signed128(-x) for x in a]
    got = _back(*i128.abs128(alo, ahi))
    assert got == [_to_signed128(abs(x)) for x in a]


def test_mul(rng):
    a = _rand_vals(rng, 40, lim=10 ** 19)
    b = _rand_vals(rng, 40, lim=10 ** 19)[:len(a)]
    alo, ahi = _mk(a)
    blo, bhi = _mk(b)
    got = _back(*i128.mul128(alo, ahi, blo, bhi))
    assert got == [_to_signed128(x * y) for x, y in zip(a, b)]


def test_mul_const(rng):
    a = _rand_vals(rng, 30, lim=10 ** 30)
    alo, ahi = _mk(a)
    for c in (1, 7, 10 ** 3, 10 ** 18, 10 ** 19):
        got = _back(*i128.mul_const(alo, ahi, c))
        assert got == [_to_signed128(x * c) for x in a]


def test_cmp(rng):
    a = _rand_vals(rng, 40)
    b = _rand_vals(rng, 40)[:len(a)]
    alo, ahi = _mk(a)
    blo, bhi = _mk(b)
    assert list(np.asarray(i128.lt128(alo, ahi, blo, bhi))) == \
        [x < y for x, y in zip(a, b)]
    assert list(np.asarray(i128.eq128(alo, ahi, blo, bhi))) == \
        [x == y for x, y in zip(a, b)]


def test_divmod_trunc(rng):
    a = _rand_vals(rng, 25)
    b = [v if v != 0 else 3 for v in _rand_vals(rng, 25)[:len(a)]]
    alo, ahi = _mk(a)
    blo, bhi = _mk(b)
    qlo, qhi, rlo, rhi = i128.divmod128_trunc(alo, ahi, blo, bhi)
    qs = _back(qlo, qhi)
    rs = _back(rlo, rhi)
    for x, y, q, r in zip(a, b, qs, rs):
        eq = abs(x) // abs(y)
        er = abs(x) % abs(y)
        eq = -eq if (x < 0) != (y < 0) else eq
        er = -er if x < 0 else er
        assert q == eq, (x, y, q, eq)
        assert r == er, (x, y, r, er)


def test_div_round_half_up(rng):
    a = _rand_vals(rng, 25)
    alo, ahi = _mk(a)
    for d in (2, 10, 10 ** 3, 10 ** 18, 10 ** 21):
        got = _back(*i128.div128_round_half_up(alo, ahi, d))
        for x, g in zip(a, got):
            # HALF_UP away from zero, in exact integer arithmetic
            # (Decimal's default 28-digit context would round the oracle)
            exp = (abs(x) + d // 2) // d
            exp = -exp if x < 0 else exp
            assert g == exp, (x, d, g, exp)


def test_rescale_roundtrip():
    vals = [123456789012345678901234567, -9 * 10 ** 30, 5, -5, 0]
    lo, hi = _mk(vals)
    up = i128.rescale(lo, hi, 6)
    assert _back(*up) == [v * 10 ** 6 for v in vals]
    down = i128.rescale(*up, -6)
    assert _back(*down) == vals


def test_sum_lanes(rng):
    vals = _rand_vals(rng, 200, lim=10 ** 36)
    lo, hi = _mk(vals)
    s0, s1, s2 = i128.sum_lanes(lo, hi)
    tot = i128.combine_sums(jnp.sum(s0)[None], jnp.sum(s1)[None],
                            jnp.sum(s2)[None])
    assert _back(*tot)[0] == _to_signed128(sum(vals))


def test_to_from_double():
    vals = [0, 5, -5, 2 ** 70, -(2 ** 70)]
    lo, hi = _mk(vals)
    d = np.asarray(i128.to_double(lo, hi))
    assert list(d) == [float(v) for v in vals]
    lo2, hi2 = i128.from_double(jnp.asarray(d))
    assert _back(lo2, hi2) == vals


def test_div_round_half_up_scaled_single_rounding():
    # the exact double-rounding boundary (round-5 advisor nit):
    # 0.29 averaged over 2 rows into a result scale one BELOW the sum
    # scale. Divide-then-rescale rounds twice (29/2 -> 15, 15/10 -> 2);
    # the fused divisor rounds once: HALF_UP(29/20) = 1.
    lo, hi = _mk([29])
    cnt = jnp.asarray(np.array([2], np.int64))
    qlo, qhi = i128.div128_round_half_up_scaled(lo, hi, cnt, 1)
    assert _back(qlo, qhi) == [1]
    # negative sums mirror away from zero
    lo, hi = _mk([-29])
    qlo, qhi = i128.div128_round_half_up_scaled(lo, hi, cnt, 1)
    assert _back(qlo, qhi) == [-1]
    # exact halves still round away from zero: 30/(2*10) = 1.5 -> 2
    lo, hi = _mk([30, -30])
    cnt = jnp.asarray(np.array([2, 2], np.int64))
    qlo, qhi = i128.div128_round_half_up_scaled(lo, hi, cnt, 1)
    assert _back(qlo, qhi) == [2, -2]


def test_div_round_half_up_scaled_matches_bigint(rng):
    def half_up(v, d):
        q, r = divmod(abs(v), d)
        q += 2 * r >= d
        return -q if v < 0 else q

    vals = _rand_vals(rng, 64, lim=10 ** 30)
    counts = [rng.randint(1, 10 ** 6) for _ in vals]
    for k in (0, 1, 3):
        lo, hi = _mk(vals)
        cnt = jnp.asarray(np.array(counts, np.int64))
        qlo, qhi = i128.div128_round_half_up_scaled(lo, hi, cnt, k)
        want = [half_up(v, c * 10 ** k)
                for v, c in zip(vals, counts)]
        assert _back(qlo, qhi) == want


def test_avg_post_decimal_downscale_single_rounding():
    # executor-level repro: the avg finisher with result scale below
    # the sum scale must produce the single-rounded quotient
    from trino_tpu.columnar import Batch, Column
    from trino_tpu.exec.executor import _avg_post
    from trino_tpu.types import BIGINT, DecimalType
    sum_t = DecimalType(38, 2)          # long decimal: (lo, hi) lanes
    res_t = DecimalType(18, 1)
    lo, hi = _mk([29, 30, -29])
    batch = Batch({
        "s": Column(sum_t, lo, None, data2=hi),
        "c": Column(BIGINT, jnp.asarray(np.array([2, 2, 2], np.int64)),
                    None)}, 3)
    out = _avg_post("s", "c", res_t)(batch)
    assert [int(v) for v in np.asarray(out.data)] == [1, 2, -1]
