"""Pallas grouped-aggregation kernel (ops/pallas_groupby.py).

Runs in interpreter mode on the CPU suite (TRINO_TPU_PALLAS=interpret);
on a real TPU the same kernel compiles via Mosaic. Validates the
exact-sum digit decomposition and the engine integration end-to-end
against the XLA masked-reduction path.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from trino_tpu.ops.pallas_groupby import G_PAD, grouped_sums


def test_grouped_sums_exact():
    rng = np.random.default_rng(1)
    cap, n = 8192, 7000
    gid = rng.integers(0, 11, cap).astype(np.int32)
    gid[n:] = G_PAD
    money = np.round(rng.uniform(900, 105000, cap), 2)
    small = rng.integers(0, 50, cap).astype(np.float64)
    signed = rng.normal(scale=1e9, size=cap)
    live = np.arange(cap) < n
    lanes = [np.where(live, x, 0.0) for x in (money, small, signed)]
    lanes.append(live.astype(np.float64))
    out = grouped_sums(jnp.asarray(gid),
                       [jnp.asarray(x) for x in lanes], 11,
                       interpret=True)
    for g in range(11):
        m = (gid[:n] == g)
        assert abs(float(out[0][g]) - money[:n][m].sum()) \
            <= 1e-8 * abs(money[:n][m].sum())
        assert float(out[1][g]) == small[:n][m].sum()
        assert abs(float(out[2][g]) - signed[:n][m].sum()) \
            <= 1e-8 * abs(signed[:n][m].sum())
        assert float(out[3][g]) == m.sum()


def test_grouped_sums_empty_and_zero_groups():
    cap = 512
    gid = np.full(cap, G_PAD, np.int32)   # everything dead
    out = grouped_sums(jnp.asarray(gid),
                       [jnp.zeros(cap)], 4, interpret=True)
    assert np.allclose(np.asarray(out[0]), 0.0)


def test_sql_q1_shape_matches_xla_path(monkeypatch):
    """The q1 aggregation (filter + multi-key GROUP BY + sums/avg/
    count) through the engine with the pallas path forced on must
    match the XLA masked-reduction path exactly enough for SQL."""
    from trino_tpu.runner import LocalQueryRunner
    sql = ("SELECT l_returnflag, l_linestatus, sum(l_quantity), "
           "sum(l_extendedprice), "
           "sum(l_extendedprice * (1 - l_discount)), "
           "avg(l_quantity), count(*) "
           "FROM lineitem WHERE l_shipdate <= DATE '1998-09-02' "
           "GROUP BY l_returnflag, l_linestatus "
           "ORDER BY l_returnflag, l_linestatus")
    monkeypatch.setenv("TRINO_TPU_PALLAS", "0")
    want = LocalQueryRunner().execute(sql).rows
    monkeypatch.setenv("TRINO_TPU_PALLAS", "interpret")
    got = LocalQueryRunner().execute(sql).rows
    assert len(got) == len(want) > 0
    for g, w in zip(got, want):
        assert g[:2] == w[:2]
        for a, b in zip(g[2:], w[2:]):
            assert a == pytest.approx(b, rel=1e-9)


def test_sql_filtered_count_matches(monkeypatch):
    from trino_tpu.runner import LocalQueryRunner
    sql = ("SELECT l_linestatus, "
           "count(*) FILTER (WHERE l_quantity > 25), "
           "sum(l_extendedprice) FILTER (WHERE l_discount > 0.05), "
           "min(l_shipdate), max(l_quantity) "
           "FROM lineitem GROUP BY l_linestatus ORDER BY 1")
    monkeypatch.setenv("TRINO_TPU_PALLAS", "0")
    want = LocalQueryRunner().execute(sql).rows
    monkeypatch.setenv("TRINO_TPU_PALLAS", "interpret")
    got = LocalQueryRunner().execute(sql).rows
    assert len(got) == len(want) > 0
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[1] == w[1]
        assert g[2] == pytest.approx(w[2], rel=1e-9)
        assert g[3] == w[3] and g[4] == w[4]
