"""MAP / ROW types + higher-order (lambda) functions.

Reference parity: spi/block/MapBlock.java / RowBlock.java,
operator/scalar/MapFunctions.java, ArrayTransformFunction.java,
ArrayFilterFunction, ReduceFunction, ZipWithFunction,
MapFilterFunction / MapTransformKeysFunction / MapTransformValuesFunction
(SURVEY.md Appendix A.10).
"""

import pytest

from trino_tpu.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def q(runner, sql):
    return runner.execute(sql).rows


# --- MAP ------------------------------------------------------------------

def test_map_constructor_and_subscript(runner):
    assert q(runner, "SELECT map(ARRAY[1, 2], ARRAY['a', 'b'])[2]") == \
        [['b']]
    assert q(runner,
             "SELECT element_at(map(ARRAY['x','y'], ARRAY[10,20]), 'y')"
             ) == [[20]]
    assert q(runner,
             "SELECT element_at(map(ARRAY[1], ARRAY[5]), 9)") == [[None]]


def test_map_materialization(runner):
    assert q(runner, "SELECT map(ARRAY[1, 2], ARRAY[10, 20])") == \
        [[{1: 10, 2: 20}]]


def test_map_keys_values_cardinality(runner):
    got = q(runner, "SELECT map_keys(m), map_values(m), cardinality(m) "
                    "FROM (SELECT map(ARRAY[3, 1], ARRAY['c', 'a']) "
                    "AS m) t")
    assert got == [[[3, 1], ['c', 'a'], 2]]


def test_map_concat(runner):
    got = q(runner, "SELECT map_concat(map(ARRAY[1, 2], ARRAY[10, 20]),"
                    " map(ARRAY[2, 3], ARRAY[99, 30]))")
    assert got == [[{1: 10, 2: 99, 3: 30}]]


def test_map_entries(runner):
    got = q(runner, "SELECT map_entries(map(ARRAY[1], ARRAY['a']))")
    assert got == [[[[1, 'a']]]]


def test_map_per_row(runner):
    got = q(runner, "SELECT map(ARRAY[n_nationkey], "
                    "ARRAY[n_regionkey])[n_nationkey] "
                    "FROM tpch.tiny.nation WHERE n_nationkey < 3 "
                    "ORDER BY n_nationkey")
    assert got == [[0], [1], [1]]


# --- ROW ------------------------------------------------------------------

def test_row_constructor_subscript(runner):
    assert q(runner, "SELECT ROW(1, 'x')[1], ROW(1, 'x')[2]") == \
        [[1, 'x']]


def test_row_materialization(runner):
    assert q(runner, "SELECT ROW(1, 2.5)") == [[[1, 2.5]]]


def test_row_cast_and_dereference(runner):
    got = q(runner, "SELECT CAST(ROW(1, 'a') AS "
                    "ROW(x BIGINT, y VARCHAR)).x")
    assert got == [[1]]


# --- lambdas --------------------------------------------------------------

def test_transform(runner):
    assert q(runner, "SELECT transform(ARRAY[1, 2, 3], x -> x * 10)") \
        == [[[10, 20, 30]]]


def test_transform_captures_outer_column(runner):
    got = q(runner, "SELECT transform(ARRAY[1, 2], "
                    "x -> x + n_nationkey) FROM tpch.tiny.nation "
                    "WHERE n_nationkey < 2 ORDER BY n_nationkey")
    assert got == [[[1, 2]], [[2, 3]]]


def test_filter(runner):
    assert q(runner,
             "SELECT filter(ARRAY[5, -1, 3, -7], x -> x > 0)") == \
        [[[5, 3]]]


def test_matches(runner):
    got = q(runner, "SELECT any_match(ARRAY[1, 2], x -> x > 1), "
                    "all_match(ARRAY[1, 2], x -> x > 0), "
                    "none_match(ARRAY[1, 2], x -> x > 5)")
    assert got == [[True, True, True]]


def test_reduce(runner):
    assert q(runner, "SELECT reduce(ARRAY[1, 2, 3, 4], 0, "
                     "(s, x) -> s + x, s -> s)") == [[10]]
    assert q(runner, "SELECT reduce(ARRAY[2, 3], 1, "
                     "(s, x) -> s * x, s -> s * 100)") == [[600]]


def test_zip_with(runner):
    assert q(runner, "SELECT zip_with(ARRAY[1, 2], ARRAY[10, 20], "
                     "(x, y) -> x + y)") == [[[11, 22]]]


def test_map_filter_transform(runner):
    assert q(runner, "SELECT map_filter(map(ARRAY[1, 2, 3], "
                     "ARRAY[10, 20, 30]), (k, v) -> k % 2 = 1)") == \
        [[{1: 10, 3: 30}]]
    assert q(runner, "SELECT transform_values(map(ARRAY[1], ARRAY[5]), "
                     "(k, v) -> v * k)") == [[{1: 5}]]
    assert q(runner, "SELECT transform_keys(map(ARRAY[1], ARRAY[5]), "
                     "(k, v) -> k + 100)") == [[{101: 5}]]


# --- array scalar breadth -------------------------------------------------

def test_contains_position(runner):
    got = q(runner, "SELECT contains(ARRAY[1, 2, 3], 2), "
                    "contains(ARRAY[1, 3], 2), "
                    "array_position(ARRAY[7, 8, 9], 9)")
    assert got == [[True, False, 3]]


def test_array_min_max_distinct_sort(runner):
    got = q(runner, "SELECT array_min(ARRAY[3, 1, 2]), "
                    "array_max(ARRAY[3, 1, 2]), "
                    "array_distinct(ARRAY[1, 2, 1, 3, 2]), "
                    "array_sort(ARRAY[3, 1, 2])")
    assert got == [[1, 3, [1, 2, 3], [1, 2, 3]]]


def test_slice_sequence_repeat_flatten(runner):
    got = q(runner, "SELECT slice(ARRAY[1, 2, 3, 4], 2, 2), "
                    "sequence(1, 4), repeat(7, 3), "
                    "flatten(ARRAY[ARRAY[1, 2], ARRAY[3]])")
    assert got == [[[2, 3], [1, 2, 3, 4], [7, 7, 7], [1, 2, 3]]]


def test_array_setops(runner):
    got = q(runner, "SELECT array_union(ARRAY[1, 2], ARRAY[2, 3]), "
                    "array_intersect(ARRAY[1, 2, 3], ARRAY[2, 3, 4]), "
                    "array_except(ARRAY[1, 2, 3], ARRAY[2]), "
                    "arrays_overlap(ARRAY[1, 2], ARRAY[2, 9])")
    assert got == [[[1, 2, 3], [2, 3], [1, 3], True]]


def test_map_agg(runner):
    got = q(runner, "SELECT map_agg(n_nationkey, n_name) "
                    "FROM tpch.tiny.nation WHERE n_nationkey < 3")
    assert got == [[{0: 'ALGERIA', 1: 'ARGENTINA', 2: 'BRAZIL'}]]


def test_map_agg_grouped(runner):
    got = q(runner, "SELECT n_regionkey, map_agg(n_nationkey, n_name) "
                    "FROM tpch.tiny.nation WHERE n_nationkey < 5 "
                    "GROUP BY n_regionkey ORDER BY n_regionkey")
    assert got == [[0, {0: 'ALGERIA'}],
                   [1, {1: 'ARGENTINA', 2: 'BRAZIL', 3: 'CANADA'}],
                   [4, {4: 'EGYPT'}]]


def test_histogram(runner):
    assert q(runner, "SELECT histogram(n_regionkey) "
                     "FROM tpch.tiny.nation") == \
        [[{0: 5, 1: 5, 2: 5, 3: 5, 4: 5}]]
    got = q(runner, "SELECT n_regionkey, histogram(n_regionkey % 2) "
                    "FROM tpch.tiny.nation GROUP BY n_regionkey "
                    "ORDER BY n_regionkey")
    assert got == [[0, {0: 5}], [1, {1: 5}], [2, {0: 5}], [3, {1: 5}],
                   [4, {0: 5}]]


def test_approx_most_frequent(runner):
    got = q(runner, "SELECT approx_most_frequent(2, n_regionkey) "
                    "FROM tpch.tiny.nation")
    assert len(got[0][0]) == 2
    assert all(v == 5 for v in got[0][0].values())
    got = q(runner, "SELECT n_regionkey, "
                    "approx_most_frequent(1, n_nationkey % 2) "
                    "FROM tpch.tiny.nation GROUP BY n_regionkey "
                    "ORDER BY n_regionkey")
    assert got == [[0, {0: 3}], [1, {1: 3}], [2, {0: 3}],
                   [3, {1: 3}], [4, {0: 3}]]


def test_lambda_in_where(runner):
    got = q(runner, "SELECT n_name FROM tpch.tiny.nation "
                    "WHERE any_match(ARRAY[n_nationkey], x -> x = 3)")
    assert got == [['CANADA']]
