"""Ragged multi-query batching (exec/taskexec.py RaggedBatcher +
exec/executor.py _try_ragged_chain): concurrent point lookups that
co-batch into ONE compiled program must come back row-for-row
identical to isolated runs — mixed types included (varchar
dictionaries, Int128 decimals) — and a batch-mate's failure must
degrade the whole group to solo execution, failing no innocent query.
"""

import threading

import pytest

import trino_tpu.exec.taskexec as te
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session

# the projection multiplies DECIMAL(12,2) lanes — precision > 18, so
# the batch carries Int128 (data2) decimal lanes through concat,
# the ragged program, and the demux gather; s_name rides a dictionary
SQLS = [
    ("SELECT s_name, s_acctbal, s_acctbal * s_acctbal AS sq "
     f"FROM supplier WHERE s_suppkey = {k}")
    # 9999 matches nothing: the pushed-down scan yields ZERO rows, so
    # the n<=0 gate runs it solo — it rides along to prove the
    # empty-result shape stays exact next to a forming batch
    for k in (3, 17, 42, 58, 9999)
]
N_BATCHABLE = 4     # the non-empty point lookups above


@pytest.fixture
def ragged_env(monkeypatch):
    """A formation window wide enough for plain test threads to meet,
    and the canonical-chain structural path forced on (the ragged
    executor only engages on canonicalized chain dispatches)."""
    monkeypatch.setenv("TRINO_TPU_FRAGMENT_JIT", "1")
    monkeypatch.setattr(te, "_RAGGED", te.RaggedBatcher(0.5, 1 << 20))


def _session(ragged: bool) -> Session:
    s = Session(catalog="tpch", schema="tiny")
    if ragged:
        s.set("ragged_batching", True)
    return s


def _solo_rows():
    return [LocalQueryRunner(session=_session(False)).execute(sql).rows
            for sql in SQLS]


def _concurrent_rows(ragged: bool = True):
    """Each query on its own thread through its own runner — the
    process-global batcher is where they meet."""
    rows = [None] * len(SQLS)
    errs = [None] * len(SQLS)
    batched = [0] * len(SQLS)
    barrier = threading.Barrier(len(SQLS))

    def run(i):
        r = LocalQueryRunner(session=_session(ragged))
        barrier.wait()
        try:
            res = r.execute(SQLS[i])
            rows[i] = res.rows
            batched[i] = getattr(res, "ragged_batched", 0)
        except Exception as e:  # noqa: BLE001 — surfaced in asserts
            errs[i] = e

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(len(SQLS))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return rows, errs, batched


def test_cobatched_rows_identical_to_isolated(ragged_env):
    expected = _solo_rows()
    assert any(expected), "solo baseline returned nothing"
    q0 = te.RAGGED_QUERIES.value()
    b0 = te.RAGGED_BATCHES.value()
    rows, errs, batched = _concurrent_rows()
    assert errs == [None] * len(SQLS)
    # every non-empty member was genuinely served by a ragged batch
    # (the 0.5s window is orders of magnitude wider than post-barrier
    # skew) — row-for-row identity of a batch that never formed
    # proves nothing
    assert te.RAGGED_QUERIES.value() - q0 == N_BATCHABLE
    assert te.RAGGED_BATCHES.value() - b0 >= 1
    assert batched == [1] * N_BATCHABLE + [0]
    for got, want, sql in zip(rows, expected, SQLS):
        assert got == want, sql


def test_batchmate_failure_leaves_innocents_exact(ragged_env,
                                                  monkeypatch):
    """run_group blowing up mid-batch fails NO query: the group
    publishes no results and every member re-executes solo on its own
    thread — innocents exact, the fallback counted as an error."""
    from trino_tpu.exec.executor import Executor
    expected = _solo_rows()

    def boom(self, key, canon, items):
        raise RuntimeError("injected ragged group failure")

    monkeypatch.setattr(Executor, "_run_ragged_group", boom)
    e0 = te.RAGGED_FALLBACKS.value(reason="error")
    b0 = te.RAGGED_BATCHES.value()
    rows, errs, _ = _concurrent_rows()
    assert errs == [None] * len(SQLS)
    assert rows == expected
    assert te.RAGGED_BATCHES.value() == b0          # nothing "served"
    assert te.RAGGED_FALLBACKS.value(reason="error") - e0 >= 1


def test_batcher_isolates_offender_to_its_own_thread():
    """Contract-level isolation: an offender poisoning run_group makes
    EVERY submit return (False, None) — each caller then runs solo,
    where only the offender's own retry raises."""
    batcher = te.RaggedBatcher(window_s=0.3, max_rows=1 << 16)
    outs = [None] * 3
    barrier = threading.Barrier(3)

    def run_group(items):
        if "poison" in items:
            raise ValueError("offender")
        return list(items)

    def submit(i, item):
        barrier.wait()
        outs[i] = batcher.submit(("sig",), 4, item, run_group)

    threads = [threading.Thread(target=submit, args=(i, item))
               for i, item in enumerate(["a", "poison", "b"])]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert outs == [(False, None)] * 3
    # the solo re-run: innocents succeed, the offender re-raises
    assert run_group(["a"]) == ["a"]
    with pytest.raises(ValueError):
        run_group(["poison"])


def test_oversized_fragment_falls_back_capacity():
    batcher = te.RaggedBatcher(window_s=0.0, max_rows=64)
    c0 = te.RAGGED_FALLBACKS.value(reason="capacity")
    ok, out = batcher.submit(("sig",), 65, "x", lambda items: items)
    assert (ok, out) == (False, None)
    assert te.RAGGED_FALLBACKS.value(reason="capacity") - c0 == 1
