"""HyperLogLog sketch family: approx_set / merge / cardinality /
empty_approx_set / casts (reference: operator/aggregation/
ApproximateSetAggregation.java, MergeHyperLogLogAggregation.java,
operator/scalar/HyperLogLogFunctions.java; sketch design in
trino_tpu/ops/hll.py)."""

import numpy as np
import pytest

from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner(session=Session(catalog="tpch",
                                            schema="tiny"))


def test_approx_set_small_exact(runner):
    rows = runner.execute(
        "SELECT cardinality(approx_set(x)) "
        "FROM (VALUES 1,2,3,4,5,2,3,NULL) t(x)").rows
    assert rows == [[5]]


def test_approx_set_grouped(runner):
    rows = runner.execute(
        "SELECT k, cardinality(approx_set(v)) FROM (VALUES "
        "('a',1),('a',2),('b',3),('b',3),('a',2)) t(k,v) "
        "GROUP BY k ORDER BY k").rows
    assert rows == [["a", 2], ["b", 1]]


def test_approx_set_null_only_group_is_null(runner):
    rows = runner.execute(
        "SELECT k, approx_set(v) IS NULL FROM (VALUES "
        "('a', 1), ('b', CAST(NULL AS integer))) t(k,v) "
        "GROUP BY k ORDER BY k").rows
    assert rows == [["a", False], ["b", True]]


def test_approx_set_accuracy_sf_column(runner):
    [[approx]] = runner.execute(
        "SELECT cardinality(approx_set(l_orderkey)) FROM lineitem").rows
    [[exact]] = runner.execute(
        "SELECT count(DISTINCT l_orderkey) FROM lineitem").rows
    # m=2048 -> stderr ~2.3%; allow 4 sigma
    assert abs(approx - exact) / exact < 0.10


def test_approx_set_error_parameter(runner):
    [[approx]] = runner.execute(
        "SELECT cardinality(approx_set(l_orderkey, 0.01)) "
        "FROM lineitem").rows
    [[exact]] = runner.execute(
        "SELECT count(DISTINCT l_orderkey) FROM lineitem").rows
    assert abs(approx - exact) / exact < 0.045

    with pytest.raises(Exception):
        runner.execute("SELECT approx_set(l_orderkey, 0.5) "
                       "FROM lineitem")


def test_merge_matches_global(runner):
    # merging per-group sketches must give the global sketch exactly
    # (register max is associative)
    [[merged]] = runner.execute(
        "SELECT cardinality(merge(s)) FROM (SELECT l_returnflag k, "
        "approx_set(l_partkey) s FROM lineitem GROUP BY "
        "l_returnflag)").rows
    [[direct]] = runner.execute(
        "SELECT cardinality(approx_set(l_partkey)) FROM lineitem").rows
    assert merged == direct


def test_merge_grouped(runner):
    rows = runner.execute(
        "SELECT g, cardinality(merge(s)) FROM (SELECT k, k = 'c' g, "
        "approx_set(v) s FROM (VALUES ('a',1),('a',2),('b',2),('b',3),"
        "('c',9)) t(k,v) GROUP BY k) GROUP BY g ORDER BY g").rows
    assert rows == [[False, 3], [True, 1]]


def test_empty_approx_set(runner):
    assert runner.execute(
        "SELECT cardinality(empty_approx_set())").rows == [[0]]


def test_cast_roundtrip(runner):
    rows = runner.execute(
        "SELECT cardinality(CAST(CAST(approx_set(x) AS varbinary) "
        "AS hyperloglog)) FROM (VALUES 1,2,3,4) t(x)").rows
    assert rows == [[4]]


def test_try_cast_malformed_sketch_is_null(runner):
    rows = runner.execute(
        "SELECT TRY_CAST('garbage' AS hyperloglog) IS NULL").rows
    assert rows == [[True]]
    with pytest.raises(Exception):
        runner.execute("SELECT CAST('garbage' AS hyperloglog)")


def test_merge_rejects_non_sketch(runner):
    with pytest.raises(Exception):
        runner.execute("SELECT merge(x) FROM (VALUES 1,2) t(x)")


def test_approx_distinct_strings(runner):
    [[approx]] = runner.execute(
        "SELECT approx_distinct(l_shipmode) FROM lineitem").rows
    assert approx == 7


@pytest.mark.slow
def test_hll_distributed_matches_local(runner):
    sql = ("SELECT l_returnflag, cardinality(approx_set(l_partkey)) "
           "FROM lineitem GROUP BY l_returnflag ORDER BY l_returnflag")
    dist = LocalQueryRunner(distributed=True, n_devices=8,
                            session=Session(catalog="tpch",
                                            schema="tiny"))
    assert dist.execute(sql).rows == runner.execute(sql).rows
