"""record-decoder + plugin/test toolkit (lib/trino-record-decoder,
lib/trino-plugin-toolkit + testing QueryAssertions analogs)."""

import pytest

from trino_tpu.formats.record_decoder import (DecoderField,
                                              create_decoder)
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.testing import (TestingConnector, assert_query,
                               assert_query_fails)
from trino_tpu.types import BIGINT, BOOLEAN, DOUBLE, VARCHAR


def test_json_decoder_paths_and_nulls():
    dec = create_decoder("json", [
        DecoderField("id", BIGINT, "user.id"),
        DecoderField("name", VARCHAR, "user.name"),
        DecoderField("score", DOUBLE, "score"),
        DecoderField("ok", BOOLEAN, "flags.ok"),
    ])
    msgs = [
        b'{"user": {"id": 1, "name": "a"}, "score": 1.5,'
        b' "flags": {"ok": true}}',
        b'{"user": {"id": 2}, "score": "2.5"}',
        b'not json at all',
    ]
    assert dec.decode(msgs).to_pylist() == [
        [1, "a", 1.5, True],
        [2, None, 2.5, None],
        [None, None, None, None],
    ]


def test_csv_decoder_indices():
    dec = create_decoder("csv", [
        DecoderField("a", BIGINT, "0"),
        DecoderField("b", VARCHAR, "2"),
    ])
    assert dec.decode([b"1,x,alpha", b'2,y,"q,uoted"', b"3"]) \
        .to_pylist() == [[1, "alpha"], [2, "q,uoted"], [3, None]]


def test_raw_decoder_and_unknown_kind():
    dec = create_decoder("raw", [DecoderField("msg", VARCHAR)])
    assert dec.decode([b"hello", b"world"]).to_pylist() == \
        [["hello"], ["world"]]
    with pytest.raises(ValueError, match="unknown decoder"):
        create_decoder("avro", [])


def test_testing_connector_and_assertions():
    conn = TestingConnector()
    conn.add_table("people", {"id": BIGINT, "city": VARCHAR},
                   [{"id": 1, "city": "oslo"},
                    {"id": 2, "city": "lima"},
                    {"id": 3, "city": None}])
    r = LocalQueryRunner()
    r.catalogs.register("t", conn)
    assert_query(r, "SELECT city, count(*) FROM t.default.people "
                    "GROUP BY city",
                 [["oslo", 1], ["lima", 1], [None, 1]])
    assert_query(r, "SELECT id FROM t.default.people ORDER BY id DESC",
                 [[3], [2], [1]], ordered=True)
    assert_query_fails(r, "SELECT nope FROM t.default.people",
                       "cannot be resolved")
    with pytest.raises(AssertionError):
        assert_query(r, "SELECT 1", [[2]])
