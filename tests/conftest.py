"""Test configuration: run on a virtual 8-device CPU mesh.

Mirrors the reference's test strategy tier 2 (SURVEY.md §4):
LocalQueryRunner-style in-process tests, multi-"node" via
xla_force_host_platform_device_count instead of real chips.

Note: a TPU-attached shell may force-select the tunnel backend by calling
jax.config.update("jax_platforms", ...) at interpreter start, so setting
the JAX_PLATFORMS env var alone is NOT enough — we call config.update
ourselves before the first backend initialization.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# Force CPU for unit tests even when launched from a TPU-attached shell;
# set TRINO_TPU_TEST_PLATFORM to override (e.g. to run the suite on chip).
jax.config.update("jax_platforms",
                  os.environ.get("TRINO_TPU_TEST_PLATFORM", "cpu"))

import trino_tpu  # noqa: E402,F401  (enables x64)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: benchmark-grade tests excluded from the tier-1 run")
