"""SPMD collective tests on the virtual 8-device CPU mesh.

Reference parity: the DistributedQueryRunner tier (SURVEY.md §4) — N
"workers" in one process; here N = 8 virtual XLA CPU devices and the
exchange layer is all_to_all/all_gather instead of HTTP page transfer.
"""

import collections

import numpy as np
import pytest

from trino_tpu.columnar import batch_from_pylist
from trino_tpu.ops.groupby import AggInput
from trino_tpu.parallel import (distributed_group_aggregate, get_mesh,
                                repartition_by_hash, shard_batch,
                                unshard_batch)
from trino_tpu.parallel.spmd import broadcast_sharded
from trino_tpu.types import BIGINT, DOUBLE, VARCHAR


@pytest.fixture(scope="module")
def mesh():
    m = get_mesh()
    assert m.devices.size == 8, "conftest must provide 8 virtual devices"
    return m


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(42)
    n = 3000
    k = rng.integers(0, 23, n)
    v = rng.normal(size=n)
    b = batch_from_pylist(
        {"k": [int(x) for x in k], "v": [float(x) for x in v]},
        {"k": BIGINT, "v": DOUBLE})
    return b, k, v


def test_shard_roundtrip(mesh, batch):
    b, k, v = batch
    sb = shard_batch(b, mesh)
    assert sb.total_rows_host() == len(k)
    back = unshard_batch(sb)
    assert back.num_rows_host() == len(k)
    got = sorted(back.to_pylist())
    want = sorted([int(a), float(x)] for a, x in zip(k, v))
    assert [r[0] for r in got] == [r[0] for r in want]


def test_repartition_collocates_keys(mesh, batch):
    b, k, v = batch
    sb = shard_batch(b, mesh)
    rp = repartition_by_hash(sb, ["k"])
    assert rp.total_rows_host() == len(k)
    # every key must live on exactly one shard
    counts = np.asarray(rp.num_rows)
    per = rp.per_shard_cap
    kk = np.asarray(rp.columns["k"].data)
    key_shards = collections.defaultdict(set)
    for d in range(8):
        for j in range(counts[d]):
            key_shards[int(kk[d * per + j])].add(d)
    assert all(len(s) == 1 for s in key_shards.values())


def test_distributed_groupby_matches_local(mesh, batch):
    b, k, v = batch
    sb = shard_batch(b, mesh)
    out = distributed_group_aggregate(
        sb, ["k"], [AggInput("sum", "v", output="s"),
                    AggInput("count_star", None, output="c"),
                    AggInput("max", "v", output="mx")])
    res = unshard_batch(out)
    n = res.num_rows_host()
    ref_s = collections.defaultdict(float)
    ref_c = collections.Counter()
    ref_m = collections.defaultdict(lambda: -1e18)
    for a, x in zip(k, v):
        ref_s[int(a)] += x
        ref_c[int(a)] += 1
        ref_m[int(a)] = max(ref_m[int(a)], x)
    assert n == len(ref_s)
    kk = np.asarray(res.column("k").data)[:n]
    ss = np.asarray(res.column("s").data)[:n]
    cc = np.asarray(res.column("c").data)[:n]
    mm = np.asarray(res.column("mx").data)[:n]
    for a, s, c, m in zip(kk, ss, cc, mm):
        assert ref_c[int(a)] == int(c)
        assert abs(ref_s[int(a)] - s) < 1e-9
        assert abs(ref_m[int(a)] - m) < 1e-12


@pytest.mark.slow      # ~13s; sibling test_distributed_groupby_matches_local
# keeps the distributed-groupby path tier-1
def test_distributed_groupby_strings(mesh):
    vals = ["apple", "pear", "apple", "fig", "pear", "apple"] * 50
    b = batch_from_pylist({"s": vals, "x": list(range(len(vals)))},
                          {"s": VARCHAR, "x": BIGINT})
    sb = shard_batch(b, get_mesh())
    out = distributed_group_aggregate(
        sb, ["s"], [AggInput("count_star", None, output="c")])
    res = unshard_batch(out)
    got = {r[0]: r[1] for r in
           [dict(zip(res.names, row)).values() and
            [row[res.names.index("s")], row[res.names.index("c")]]
            for row in res.to_pylist()]}
    want = collections.Counter(vals)
    assert got == dict(want)


def test_broadcast(mesh, batch):
    b, k, v = batch
    sb = shard_batch(b, mesh)
    bc = broadcast_sharded(sb)
    counts = np.asarray(bc.num_rows)
    assert (counts == len(k)).all()


@pytest.mark.slow
def test_graft_entry():
    import __graft_entry__ as ge
    import jax
    fn, args = ge.entry()
    out, n = jax.jit(fn)(*args)
    assert int(n) >= 1
    ge.dryrun_multichip(8)


@pytest.mark.slow      # ~34s: 8-device sampled range exchange at >4096
# rows; the stage-scheduler sort path keeps tier-1 coverage elsewhere
def test_range_repartition_distributed_sort(mesh):
    """Sampled range exchange + per-shard sort == global ORDER BY
    (exec/distributed.py _dexec_SortNode building blocks).
    Ungated in PR 13: the in-slice path rides the stage scheduler now,
    so the collective building blocks are tier-1 load-bearing."""
    from trino_tpu.ops.sort import SortKey, sort_batch
    from trino_tpu.parallel.spmd import (range_dest_counts,
                                         repartition_by_range,
                                         sample_range_splitters,
                                         shard_apply)
    from trino_tpu.config import capacity_for
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    n = 20000
    a = rng.integers(0, 50, n)
    d = rng.normal(size=n)
    b = batch_from_pylist(
        {"a": [int(x) for x in a], "d": [float(x) for x in d]},
        {"a": BIGINT, "d": DOUBLE})
    keys = [SortKey("a", True, None), SortKey("d", False, None)]
    want = sort_batch(b, keys).to_pylist()

    sb = shard_batch(b, mesh)
    splitters = sample_range_splitters(sb, keys)
    counts = range_dest_counts(sb, keys, splitters)
    assert int(jnp.sum(counts)) == n
    cap = capacity_for(max(int(jnp.max(counts)), 1))
    rp = repartition_by_range(sb, keys, splitters, out_cap=cap)
    assert rp.total_rows_host() == n
    out = shard_apply(rp, lambda x: sort_batch(x, keys), cap)
    got = unshard_batch(out).to_pylist()
    assert got == want


@pytest.mark.slow
def test_distributed_sort_sql_matches_local():
    """End-to-end ORDER BY through the distributed executor (large
    enough to take the range-exchange path, verified ordered)."""
    from trino_tpu.runner import LocalQueryRunner
    q = ("SELECT l_orderkey, l_linenumber, l_extendedprice FROM lineitem "
         "WHERE l_quantity < 30 ORDER BY l_extendedprice DESC, l_orderkey, "
         "l_linenumber")
    local = LocalQueryRunner().execute(q).rows
    dist = LocalQueryRunner(distributed=True, n_devices=8).execute(q).rows
    assert len(local) > 4096  # must exercise the range exchange
    assert dist == local


@pytest.mark.slow      # ~47s: 8-device windowed aggregation equality;
# window correctness stays tier-1 via test_window_frames/test_warmpath_aot
def test_distributed_window_matches_local():
    """q47-style windowed aggregation: hash repartition by partition
    keys + per-shard window == local (round-4 verdict weak #6).
    Ungated in PR 13: this plan now fragments into the stage DAG and
    executes through the ICI stage path (stage/ici.py), so it proves
    the unified in-slice engine end to end in tier 1."""
    q = ("SELECT o_custkey, o_orderkey, "
         "rank() OVER (PARTITION BY o_custkey ORDER BY o_totalprice DESC) "
         "AS r, sum(o_totalprice) OVER (PARTITION BY o_custkey) AS s "
         "FROM orders "
         "ORDER BY o_custkey, r, o_orderkey")
    from trino_tpu.runner import LocalQueryRunner
    loc = LocalQueryRunner().execute(q).rows
    dist = LocalQueryRunner(distributed=True, n_devices=8).execute(q).rows
    # all 15000 tiny orders: above MIN_SHARD_ROWS, so this exercises
    # the real repartition + per-shard window path, not the fallback
    assert len(dist) == len(loc) > 4096
    for d, l in zip(dist, loc):
        assert d[:3] == l[:3]
        assert d[3] == pytest.approx(l[3], rel=1e-9)


@pytest.mark.parametrize("setop", [
    "INTERSECT", "INTERSECT ALL", "EXCEPT", "EXCEPT ALL"])
def test_distributed_setops_match_local(setop):
    # right side drops multiples of 5 so EXCEPT keeps a real remainder
    # (o_custkey is never divisible by 3 by spec — filtering the right
    # on %3 would make EXCEPT legitimately empty)
    q = (f"SELECT o_custkey FROM orders {setop} "
         "SELECT c_custkey FROM customer WHERE c_custkey % 5 != 0 "
         "ORDER BY 1 LIMIT 50")
    from trino_tpu.runner import LocalQueryRunner
    loc = LocalQueryRunner().execute(q).rows
    dist = LocalQueryRunner(distributed=True, n_devices=8).execute(q).rows
    assert dist == loc and len(loc) > 0


@pytest.mark.slow
def test_distributed_setop_strings_match_local():
    """Both sides are sharded scans of DIFFERENT dictionary columns
    (shipmode vs orderpriority), driving _align_setop_dicts + the
    per-shard string set-op — not the coordinator fallback."""
    q = ("SELECT l_shipmode FROM lineitem EXCEPT "
         "SELECT o_orderpriority FROM orders ORDER BY 1")
    from trino_tpu.runner import LocalQueryRunner
    loc = LocalQueryRunner().execute(q).rows
    dist = LocalQueryRunner(distributed=True, n_devices=8).execute(q).rows
    assert dist == loc and len(loc) == 7   # all 7 ship modes survive

    q2 = ("SELECT l_shipmode FROM lineitem INTERSECT "
          "SELECT l_shipmode FROM lineitem WHERE l_orderkey % 2 = 0 "
          "ORDER BY 1")
    loc2 = LocalQueryRunner().execute(q2).rows
    dist2 = LocalQueryRunner(distributed=True,
                             n_devices=8).execute(q2).rows
    assert dist2 == loc2 and len(loc2) == 7
