"""Static-analysis layer tests: PlanSanityChecker + the AST lint.

Three contracts (ISSUE 7 acceptance):
- every tier-1 query plan (TPC-H + TPC-DS corpus) passes the full
  validator battery clean, after optimization AND as a raw logical
  plan;
- each seeded invariant break is caught by the RIGHT validator, with
  the responsible optimizer pass named;
- the lint reports zero unsuppressed findings over the real tree (this
  IS the CI wiring: tier-1 runs this module) and flags every seeded
  violation in its fixtures.
"""

import textwrap

import pytest

from trino_tpu.analysis.lint import Finding, lint_paths, lint_source, main
from trino_tpu.analysis.sanity import (PlanSanityChecker,
                                       PlanValidationError,
                                       validate_plan)
from trino_tpu.catalog import TableHandle
from trino_tpu.obs.metrics import PLAN_VALIDATION_FAILURES
from trino_tpu.plan.nodes import (FilterNode, JoinClause, JoinNode,
                                  ProjectNode, TableScanNode, UnionNode,
                                  ValuesNode)
from trino_tpu.rex import BOOLEAN, Call, Const, InputRef
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.types import BIGINT, VARCHAR


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def _scan(sym="n0", col="nationkey", typ=BIGINT, table="nation"):
    return TableScanNode(TableHandle("tpch", "tiny", table),
                         {sym: col}, {sym: typ})


# --------------------------------------------------------------------------
# sanity checker: the clean corpus
# --------------------------------------------------------------------------

def test_tier1_tpch_plans_validate_clean(runner):
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    ck = PlanSanityChecker()
    for name, sql in sorted(TPCH_QUERIES.items()):
        plan = runner.plan_sql(sql)
        ck.validate(plan, f"q{name}")
        ck.validate_fragment(plan, f"q{name}")
        # the per-pass debug battery also sees raw logical plans
        ck.validate(runner.plan_sql(sql, optimized=False),
                    f"q{name}-logical")


def test_tier1_tpcds_plans_validate_clean():
    from trino_tpu.benchmarks.tpcds_queries import TPCDS_QUERIES
    r = LocalQueryRunner()
    r.session.catalog, r.session.schema = "tpcds", "tiny"
    ck = PlanSanityChecker()
    for name, sql in sorted(TPCDS_QUERIES.items()):
        plan = r.plan_sql(sql)
        ck.validate(plan, f"q{name}")
        ck.validate_fragment(plan, f"q{name}")


def test_plan_validation_session_property_end_to_end(runner):
    # per-pass validation on: real queries still execute and return
    # the same rows (the battery must be invisible when plans are good)
    runner.session.set("plan_validation", True)
    try:
        res = runner.execute(
            "SELECT r.r_name, count(*) FROM tpch.tiny.nation n "
            "JOIN tpch.tiny.region r ON n.n_regionkey = r.r_regionkey "
            "GROUP BY r.r_name ORDER BY r.r_name")
        assert len(res.rows) == 5
    finally:
        runner.session.reset("plan_validation")


# --------------------------------------------------------------------------
# sanity checker: seeded invariant breaks, each blamed on its validator
# --------------------------------------------------------------------------

def test_dangling_inputref_caught_by_dependencies_checker():
    bad = FilterNode(_scan(), Call(
        "=", (InputRef("no_such_symbol", BIGINT), Const(1, BIGINT)),
        BOOLEAN))
    with pytest.raises(PlanValidationError) as ei:
        validate_plan(bad, "push_filters")
    assert ei.value.validator == "ValidateDependenciesChecker"
    assert ei.value.pass_name == "push_filters"
    assert "no_such_symbol" in str(ei.value)
    assert "push_filters" in str(ei.value)


def test_duplicate_node_object_caught():
    scan = _scan()
    bad = UnionNode((scan, scan), {"n0": BIGINT},
                    ({"n0": "n0"}, {"n0": "n0"}))
    with pytest.raises(PlanValidationError) as ei:
        validate_plan(bad, "cleanup_projects")
    assert ei.value.validator == "NoDuplicatePlanNodeIds"


def test_type_mismatched_join_clause_caught():
    left = _scan("n0", "nationkey", BIGINT, "nation")
    right = _scan("r0", "name", VARCHAR, "region")
    bad = JoinNode(left, right, "inner", (JoinClause("n0", "r0"),))
    with pytest.raises(PlanValidationError) as ei:
        validate_plan(bad, "reorder_joins")
    assert ei.value.validator == "JoinCriteriaChecker"
    assert "bigint" in str(ei.value) and "varchar" in str(ei.value)


def test_join_clause_wrong_side_caught():
    left = _scan("n0", "nationkey", BIGINT, "nation")
    right = _scan("r0", "regionkey", BIGINT, "region")
    bad = JoinNode(left, right, "inner", (JoinClause("r0", "r0"),))
    with pytest.raises(PlanValidationError) as ei:
        validate_plan(bad)
    assert ei.value.validator == "JoinCriteriaChecker"
    assert "left source" in str(ei.value)


def test_inputref_type_drift_caught_by_type_validator():
    bad = ProjectNode(_scan(), {"p0": InputRef("n0", VARCHAR)})
    with pytest.raises(PlanValidationError) as ei:
        validate_plan(bad, "prune_columns")
    assert ei.value.validator == "TypeValidator"


def test_serde_unstable_fragment_caught():
    # an int-keyed dict survives encode->decode only as a str-keyed
    # dict: the fragment a retry would decode is not the fragment the
    # first attempt ran
    bad = ValuesNode({"v0": BIGINT}, (({1: "a"},),))
    validate_plan(bad)          # plan battery alone is fine with it
    with pytest.raises(PlanValidationError) as ei:
        validate_plan(bad, "fragmenter", fragment=True)
    assert ei.value.validator == "SerdeRoundTripChecker"


def test_unserializable_fragment_caught():
    bad = ValuesNode({"v0": BIGINT}, (({"a", "b"},),))   # a set
    with pytest.raises(PlanValidationError) as ei:
        validate_plan(bad, fragment=True)
    assert ei.value.validator == "SerdeRoundTripChecker"
    assert "not serializable" in str(ei.value)


def test_validation_failures_counted():
    before = PLAN_VALIDATION_FAILURES.value(
        validator="ValidateDependenciesChecker")
    bad = FilterNode(_scan(), Call(
        "=", (InputRef("ghost", BIGINT), Const(1, BIGINT)), BOOLEAN))
    with pytest.raises(PlanValidationError):
        validate_plan(bad)
    after = PLAN_VALIDATION_FAILURES.value(
        validator="ValidateDependenciesChecker")
    assert after == before + 1


def test_broken_optimizer_pass_is_blamed(monkeypatch):
    # the debug battery pins a violation on the pass that made it:
    # corrupt prune_columns and the error must say so
    import trino_tpu.planner.optimizer as O
    from dataclasses import replace as dc_replace
    real = O.prune_columns

    def broken(plan):
        out = real(plan)
        dangling = FilterNode(out.source, Call(
            "=", (InputRef("__broken_by_prune", BIGINT),
                  Const(1, BIGINT)), BOOLEAN))
        return dc_replace(out, source=dangling)

    monkeypatch.setattr(O, "prune_columns", broken)
    r = LocalQueryRunner()
    r.session.set("plan_validation", True)
    with pytest.raises(PlanValidationError) as ei:
        r.execute("SELECT n_nationkey FROM tpch.tiny.nation")
    assert ei.value.pass_name == "prune_columns"
    assert ei.value.validator == "ValidateDependenciesChecker"
    # without the debug property the same corruption sails through the
    # optimizer and is caught by nothing until execution
    r2 = LocalQueryRunner()
    with pytest.raises(Exception) as ei2:
        r2.execute("SELECT n_nationkey FROM tpch.tiny.nation")
    assert not isinstance(ei2.value, PlanValidationError)


def test_remote_dispatch_always_validates():
    # no plan_validation property needed: a corrupt plan must die at
    # the scheduler's door, before any worker sees a byte
    from trino_tpu.exec.remote import RemoteScheduler
    from trino_tpu.session import Session
    r = LocalQueryRunner()
    sched = RemoteScheduler(["http://127.0.0.1:1"], r.catalogs,
                            Session(catalog="tpch", schema="tiny"))
    bad = FilterNode(_scan(), Call(
        "=", (InputRef("phantom", BIGINT), Const(1, BIGINT)), BOOLEAN))
    with pytest.raises(PlanValidationError) as ei:
        sched.execute_plan(bad)
    assert ei.value.pass_name == "pre-dispatch"


# --------------------------------------------------------------------------
# lint: the real tree is clean (the CI gate)
# --------------------------------------------------------------------------

def test_lint_real_tree_zero_unsuppressed_findings():
    from trino_tpu.analysis.lint import default_root
    findings = [f for f in lint_paths([default_root()])
                if not f.suppressed]
    assert findings == [], "\n".join(f.render() for f in findings)


_CROSS_CALLER = textwrap.dedent('''
    import threading

    class Dispatcher:
        def start(self, spool):
            self.spool = spool
            threading.Thread(target=self._run).start()

        def _run(self):
            self.spool.commit("q1")
''')

_CROSS_CALLER_LOCKED = textwrap.dedent('''
    import threading

    class Dispatcher:
        def start(self, spool):
            self.spool = spool
            threading.Thread(target=self._run).start()

        def _run(self):
            with self._lock:
                self.spool.commit("q1")
''')

_CROSS_CALLEE = textwrap.dedent('''
    class Spool:
        def commit(self, query):
            self.last = query        # unlocked shared write

    class _Private:
        def commit(self, query):
            self.hidden = query      # module-internal receiver
''')


def _write_cross(tmp_path, caller_src):
    caller = tmp_path / "sched.py"
    callee = tmp_path / "spoolmod.py"
    caller.write_text(caller_src)
    callee.write_text(_CROSS_CALLEE)
    return str(caller), str(callee)


def test_lint_cross_module_follows_thread_to_callee_edges(tmp_path):
    """The PR 7 follow-on: a scheduler thread calling spool.commit()
    is followed INTO the spool module; the unlocked write there is
    flagged in the spool's file — and a private (_-prefixed) class is
    exempt from cross-module name matching (its instances never cross
    the module boundary)."""
    caller, callee = _write_cross(tmp_path, _CROSS_CALLER)
    findings = lint_paths([caller, callee], cross_callees=("",))
    hits = [f for f in findings if f.rule == "race-attr-write"]
    assert any(f.path == callee and "self.last" in f.message
               for f in hits), findings
    assert not any("self.hidden" in f.message for f in hits), hits


def test_lint_cross_module_propagates_caller_lock_context(tmp_path):
    caller, callee = _write_cross(tmp_path, _CROSS_CALLER_LOCKED)
    findings = lint_paths([caller, callee], cross_callees=("",))
    assert not [f for f in findings
                if f.rule.startswith("race")], findings


def test_lint_cross_module_disabled_stays_module_local(tmp_path):
    caller, callee = _write_cross(tmp_path, _CROSS_CALLER)
    findings = lint_paths([caller, callee], cross_callees=None)
    assert not [f for f in findings
                if f.rule.startswith("race")], findings


def test_lint_cross_module_allowlist_scopes_callees(tmp_path):
    """Only modules matching the callee patterns are matchable
    receivers — the noise-control contract."""
    caller, callee = _write_cross(tmp_path, _CROSS_CALLER)
    findings = lint_paths([caller, callee],
                          cross_callees=("does-not-match-anything/",))
    assert not [f for f in findings
                if f.rule.startswith("race")], findings


def test_lint_suppressions_all_carry_reasons():
    # a suppression without a justification is itself a finding, so
    # the zero-unsuppressed gate above already enforces this; assert
    # the mechanism directly too
    from trino_tpu.analysis.lint import default_root
    findings = lint_paths([default_root()])
    assert not [f for f in findings
                if f.rule == "suppression-without-reason"]
    assert any(f.suppressed for f in findings), \
        "expected the tree's documented suppressions to register"


# --------------------------------------------------------------------------
# lint: seeded race fixtures
# --------------------------------------------------------------------------

_RACE_FIXTURE = textwrap.dedent('''
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self.items = []

        def start(self):
            threading.Thread(target=self._run, daemon=True).start()

        def _run(self):
            self.count += 1
            self.items.append(1)
            with self._lock:
                self.count += 1
            self._helper()
            with self._lock:
                self._locked_helper()

        def _helper(self):
            self.count = 5

        def _locked_helper(self):
            self.count = 9
''')


def test_lint_flags_unguarded_thread_writes():
    findings = lint_source(_RACE_FIXTURE, "fixture.py")
    rules = {(f.line, f.rule) for f in findings}
    # the two unguarded writes in the thread target
    assert (14, "race-attr-write") in rules
    assert (15, "race-attr-mutate") in rules
    # the transitively reachable helper
    assert any(r == "race-attr-write" and ln == 23
               for ln, r in rules)
    # guarded writes and lock-context callees are NOT findings
    assert not any(ln in (17, 26) for ln, _ in rules), findings


def test_lint_lock_context_propagates_through_calls():
    src = textwrap.dedent('''
        import threading

        class Stats:
            def record(self):
                self.weight = 1

        class Detector:
            def __init__(self):
                self._lock = threading.Lock()

            def start(self):
                threading.Thread(target=self._loop).start()

            def _loop(self):
                with self._lock:
                    Stats().record()
    ''')
    findings = [f for f in lint_source(src, "d.py")
                if f.rule.startswith("race")]
    assert findings == [], findings


def test_lint_timer_target_and_obj_method_resolution():
    src = textwrap.dedent('''
        import threading

        class Query:
            def cancel(self):
                self.state = "CANCELED"

        def arm(q):
            threading.Timer(5.0, q.cancel).start()
    ''')
    findings = lint_source(src, "t.py")
    assert any(f.rule == "race-attr-write" and f.line == 6
               for f in findings)


def test_lint_positional_thread_target_resolved():
    # Thread's FIRST positional parameter is 'group' — the callable is
    # at index 1 in both Thread(group, target) and Timer(interval, fn)
    src = textwrap.dedent('''
        import threading

        class W:
            def go(self):
                threading.Thread(None, self.body).start()

            def body(self):
                self.x = 1
    ''')
    findings = lint_source(src, "p.py")
    assert any(f.rule == "race-attr-write" and f.line == 9
               for f in findings), findings


def test_lint_handler_self_writes_exempt():
    src = textwrap.dedent('''
        from http.server import BaseHTTPRequestHandler

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                self.principal = None     # per-request instance: fine
    ''')
    findings = [f for f in lint_source(src, "h.py")
                if f.rule.startswith("race")]
    assert findings == [], findings


# --------------------------------------------------------------------------
# lint: seeded jit-purity fixtures
# --------------------------------------------------------------------------

_JIT_FIXTURE = textwrap.dedent('''
    import time
    import jax
    import numpy as np

    acc = []

    def make():
        def run(b):
            t0 = time.perf_counter()
            acc.append(b)
            x = np.random.rand(3)
            k = jax.random.PRNGKey(0)
            local = []
            local.append(x)
            return b
        return jax.jit(run)

    @jax.jit
    def decorated(x):
        print(x)
        return x
''')


def test_lint_flags_jit_impurities():
    findings = lint_source(_JIT_FIXTURE, "jit.py")
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f.line)
    assert sorted(by_rule.get("jit-impure", [])) == [10, 12, 21]
    assert by_rule.get("jit-closure-mutate") == [11]
    # jax.random is pure; appends to LOCAL lists are trace-time
    # plumbing, not closure mutation
    assert not any(f.line in (13, 15) for f in findings)
    assert all(f.severity == "error" for f in findings
               if f.rule == "jit-impure")
    assert all(f.severity == "warning" for f in findings
               if f.rule == "jit-closure-mutate")


def test_lint_flags_aot_unsafe_branches():
    """The aot-unsafe rule (PR 11): data-dependent Python control flow
    inside traced functions — ``.item()`` host syncs and
    int()/float()/bool() concretizations in branch conditions — can
    never be lowered by the AOT path (exec/aot.py has no data to
    branch on)."""
    src = textwrap.dedent('''
        import jax

        @jax.jit
        def f(x, y):
            if x.item():
                return y
            while int(y) > 0:
                y = y - 1
            if bool(x) and float(y) > 0.5:
                return x
            if int(3) > 2:
                return y          # constant arg: no data dependence
            n = x.shape[0]        # static metadata: fine
            return x + y

        def not_traced(x):
            if x.item():          # outside any traced function
                return 1
            return int(x)
    ''')
    findings = [f for f in lint_source(src, "a.py")
                if f.rule == "aot-unsafe"]
    lines = sorted(f.line for f in findings)
    assert lines == [6, 8, 10, 10]
    assert all(f.severity == "error" for f in findings)


def test_lint_aot_unsafe_suppressible():
    src = textwrap.dedent('''
        import jax

        @jax.jit
        def f(x):
            if x.item():  # tt-lint: ignore[aot-unsafe] shape-gated constant under static_argnums
                return x
            return x
    ''')
    findings = lint_source(src, "a.py")
    unsuppressed = [f for f in findings
                    if f.rule == "aot-unsafe" and not f.suppressed]
    assert not unsuppressed
    assert any(f.rule == "aot-unsafe" and f.suppressed
               for f in findings)


def test_lint_shard_map_and_partial_decorator():
    src = textwrap.dedent('''
        import time
        from functools import partial
        import jax
        from jax import shard_map

        def build(mesh):
            def f(x):
                time.sleep(1)
                return x
            return shard_map(f, mesh=mesh, in_specs=None,
                             out_specs=None)

        @partial(jax.jit, static_argnames=("k",))
        def kernel(x, k):
            import random
            return x + random.random()
    ''')
    findings = [f for f in lint_source(src, "s.py")
                if f.rule == "jit-impure"]
    assert {f.line for f in findings} == {9, 17}


# --------------------------------------------------------------------------
# lint: suppressions + CLI severity gate
# --------------------------------------------------------------------------

def test_lint_suppression_and_reason_requirement():
    src = textwrap.dedent('''
        import threading

        class W:
            def go(self):
                threading.Thread(target=self.body).start()

            def body(self):
                self.x = 1  # tt-lint: ignore[race-attr-write] single writer before publication
                self.y = 2  # tt-lint: ignore[race-attr-write]
                self.z = 3
    ''')
    findings = lint_source(src, "w.py")
    xs = [f for f in findings if f.line == 9]
    assert xs and all(f.suppressed for f in xs)
    ys = [f for f in findings if f.line == 10]
    assert any(f.suppressed for f in ys)
    assert any(f.rule == "suppression-without-reason" and
               not f.suppressed for f in ys)
    zs = [f for f in findings if f.line == 11]
    assert zs and not any(f.suppressed for f in zs)


def test_lint_cli_fail_on_flag(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent('''
        import threading

        class W:
            def go(self):
                threading.Thread(target=self.body).start()

            def body(self):
                self.x = 1
    '''))
    assert main([str(bad)]) == 1                        # error present
    assert main([str(bad), "--fail-on", "none"]) == 0
    warn_only = tmp_path / "warn.py"
    warn_only.write_text(textwrap.dedent('''
        import jax

        acc = []

        def f(x):
            acc.append(x)
            return x

        g = jax.jit(f)
    '''))
    assert main([str(warn_only)]) == 0                  # warnings pass
    assert main([str(warn_only), "--fail-on", "warning"]) == 1
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert main([str(clean), "--fail-on", "warning"]) == 0
    capsys.readouterr()


# --------------------------------------------------------------------------
# lint: metrics hygiene (PR 15)
# --------------------------------------------------------------------------

def test_metrics_hygiene_flags_missing_help_and_bad_names():
    src = textwrap.dedent('''
        from trino_tpu.obs.metrics import METRICS

        A = METRICS.counter("trino_tpu_good_total", "documented")
        B = METRICS.counter("trino_tpu_nohelp_total")
        C = METRICS.counter("bad_prefix_total", "has help")
        D = METRICS.counter("trino_tpu_not_a_counter", "has help")
        E = METRICS.histogram("trino_tpu_latency", "has help")
        F = METRICS.gauge("trino_tpu_thing", "has help")
        G = METRICS.gauge("trino_tpu_pool_bytes", "has help")
        H = METRICS.counter("trino_tpu_emptyhelp_total", "")
        _HELP = "documented elsewhere"
        I = METRICS.counter("trino_tpu_varhelp_total", _HELP)
    ''')
    findings = [f for f in lint_source(src, "m.py")
                if f.rule.startswith("metric")]
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f.message)
    missing = by_rule.get("metric-missing-help", [])
    assert any("trino_tpu_nohelp_total" in m for m in missing)
    assert any("trino_tpu_emptyhelp_total" in m for m in missing)
    # non-literal help (a name) is out of the rule's reach, not flagged
    assert not any("trino_tpu_varhelp_total" in m for m in missing)
    naming = " ".join(by_rule.get("metric-naming", []))
    assert "bad_prefix_total" in naming          # prefix rule
    assert "trino_tpu_not_a_counter" in naming   # counter _total rule
    assert "trino_tpu_latency" in naming         # histogram unit rule
    assert "'trino_tpu_thing'" in naming         # gauge unit rule
    # the clean families stay clean
    assert "trino_tpu_good_total" not in naming
    assert "trino_tpu_pool_bytes" not in naming


def test_metrics_hygiene_ignores_local_registries():
    # only the process singleton (METRICS/_METRICS) is in scope:
    # test-local registries register short undocumented names freely
    src = textwrap.dedent('''
        from trino_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        c = reg.counter("t2_total", "")
    ''')
    findings = [f for f in lint_source(src, "r.py")
                if f.rule.startswith("metric")]
    assert findings == [], findings


def test_metrics_hygiene_duplicate_registration_across_files(tmp_path):
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text(textwrap.dedent('''
        from trino_tpu.obs.metrics import METRICS
        X = METRICS.counter("trino_tpu_dup_total", "first home")
    '''))
    b.write_text(textwrap.dedent('''
        from trino_tpu.obs.metrics import METRICS
        Y = METRICS.counter("trino_tpu_dup_total", "second home")
    '''))
    findings = [f for f in lint_paths([str(a), str(b)])
                if f.rule == "metric-duplicate-registration"]
    assert len(findings) == 1
    # the finding lands at the LATER site and names the first
    assert findings[0].path == str(b)
    assert "a.py" in findings[0].message


def test_metrics_hygiene_duplicate_within_one_file():
    src = textwrap.dedent('''
        from trino_tpu.obs.metrics import METRICS
        X = METRICS.counter("trino_tpu_twice_total", "one")
        Y = METRICS.counter("trino_tpu_twice_total", "two")
    ''')
    findings = [f for f in lint_source(src, "dup.py")
                if f.rule == "metric-duplicate-registration"]
    assert len(findings) == 1 and findings[0].line == 4
