"""base-jdbc connector family over sqlite3: remote metadata, column
-at-a-time reads, TupleDomain -> remote WHERE pushdown, limit
pushdown. Cross-checked against the remote database directly (the
remote IS the oracle)."""

import pytest

from trino_tpu.connectors.jdbc import SqliteConnector
from trino_tpu.runner import LocalQueryRunner


@pytest.fixture
def runner():
    conn = SqliteConnector()
    conn.execute_remote(
        "CREATE TABLE emp (id INTEGER, name TEXT, dept TEXT, "
        "salary DOUBLE)")
    for row in [(1, "ann", "eng", 120.0), (2, "bo", "eng", 95.5),
                (3, "cy", "ops", 80.0), (4, None, "ops", None),
                (5, "di", None, 110.25)]:
        conn.execute_remote("INSERT INTO emp VALUES (?,?,?,?)", row)
    r = LocalQueryRunner()
    r.catalogs.register("pg", conn)
    return r, conn


def test_metadata_and_full_scan(runner):
    r, _ = runner
    assert r.execute("SHOW TABLES FROM pg.public").rows == [["emp"]]
    rows = r.execute("SELECT id, name, salary FROM pg.public.emp "
                     "ORDER BY id").rows
    assert rows[0] == [1, "ann", 120.0]
    assert rows[3] == [4, None, None]


def test_filter_pushdown_reaches_remote(runner):
    r, conn = runner
    # plan check: the domain lands in the handle (pushed remote)
    plan = r.plan_sql("SELECT id FROM pg.public.emp WHERE id >= 3")
    from trino_tpu.plan.nodes import TableScanNode

    def scans(n):
        out = [n] if isinstance(n, TableScanNode) else []
        for s in n.sources:
            out.extend(scans(s))
        return out
    sc = scans(plan)
    assert sc and sc[0].handle.constraint is not None

    got = r.execute("SELECT id FROM pg.public.emp WHERE id >= 3 "
                    "ORDER BY id").rows
    exp = conn.execute_remote(
        "SELECT id FROM emp WHERE id >= 3 ORDER BY id")
    assert [tuple(x) for x in got] == exp


def test_aggregation_joins_against_engine_tables(runner):
    r, _ = runner
    rows = r.execute(
        "SELECT dept, count(*), sum(salary) FROM pg.public.emp "
        "WHERE dept IS NOT NULL GROUP BY dept ORDER BY dept").rows
    assert rows == [["eng", 2, 215.5], ["ops", 2, 80.0]]
    # join remote against a generator table
    rows = r.execute(
        "SELECT e.name, n.n_name FROM pg.public.emp e "
        "JOIN tpch.tiny.nation n ON e.id = n.n_nationkey "
        "WHERE e.id <= 2 ORDER BY e.id").rows
    assert rows == [["ann", "ARGENTINA"], ["bo", "BRAZIL"]]


def test_limit_pushdown(runner):
    r, _ = runner
    rows = r.execute("SELECT id FROM pg.public.emp LIMIT 2").rows
    assert len(rows) == 2


def test_domain_to_sql_shapes():
    from trino_tpu.connectors.jdbc import domain_to_sql
    from trino_tpu.predicate import Domain, Range
    from trino_tpu.types import BIGINT
    d = Domain(BIGINT, (Range(1, True, 1, True),
                        Range(5, False, 9, True)))
    sql, params = domain_to_sql("x", d)
    assert '"x" = ?' in sql and params == [1, 5, 9]
    assert "IS NOT NULL" in sql
    d2 = Domain(BIGINT, (), True)       # only null
    sql2, p2 = domain_to_sql("x", d2)
    assert "IS NULL" in sql2 and p2 == []
