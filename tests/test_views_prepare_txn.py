"""Views, prepared statements, DESCRIBE, and transactions.

Reference parity: execution/CreateViewTask / DropViewTask /
PrepareTask / DeallocateTask, sql/rewrite/DescribeInputRewrite /
DescribeOutputRewrite, transaction/InMemoryTransactionManager.
"""

import pytest

from trino_tpu.exec import QueryError
from trino_tpu.runner import LocalQueryRunner


@pytest.fixture()
def runner():
    return LocalQueryRunner()


def test_create_select_drop_view(runner):
    runner.execute("CREATE VIEW memory.default.v AS "
                   "SELECT n_name, n_regionkey FROM tpch.tiny.nation "
                   "WHERE n_nationkey < 5")
    got = runner.execute(
        "SELECT n_name FROM memory.default.v ORDER BY n_name").rows
    assert got == [['ALGERIA'], ['ARGENTINA'], ['BRAZIL'], ['CANADA'],
                   ['EGYPT']]
    # views join with tables
    got = runner.execute(
        "SELECT count(*) FROM memory.default.v v "
        "JOIN tpch.tiny.region r ON v.n_regionkey = r.r_regionkey").rows
    assert got == [[5]]
    sql = runner.execute(
        "SHOW CREATE VIEW memory.default.v").rows[0][0]
    assert sql.startswith("CREATE VIEW")
    runner.execute("DROP VIEW memory.default.v")
    with pytest.raises(QueryError):
        runner.execute("SELECT * FROM memory.default.v")


def test_create_or_replace_view(runner):
    runner.execute("CREATE VIEW memory.default.v2 AS SELECT 1 AS x")
    with pytest.raises(QueryError):
        runner.execute(
            "CREATE VIEW memory.default.v2 AS SELECT 2 AS x")
    runner.execute(
        "CREATE OR REPLACE VIEW memory.default.v2 AS SELECT 2 AS x")
    assert runner.execute(
        "SELECT x FROM memory.default.v2").rows == [[2]]


def test_drop_view_if_exists(runner):
    runner.execute("DROP VIEW IF EXISTS memory.default.nope")
    with pytest.raises(QueryError):
        runner.execute("DROP VIEW memory.default.nope")


def test_prepare_execute_deallocate(runner):
    runner.execute("PREPARE q FROM SELECT n_name FROM "
                   "tpch.tiny.nation WHERE n_nationkey = ?")
    assert runner.execute("EXECUTE q USING 3").rows == [['CANADA']]
    assert runner.execute("EXECUTE q USING 0").rows == [['ALGERIA']]
    out = runner.execute("DESCRIBE OUTPUT q").rows
    assert out == [['n_name', 'varchar(25)']]
    inp = runner.execute("DESCRIBE INPUT q").rows
    assert inp == [[0, 'unknown']]
    runner.execute("DEALLOCATE PREPARE q")
    with pytest.raises(QueryError):
        runner.execute("EXECUTE q USING 1")


def test_execute_param_arity(runner):
    runner.execute("PREPARE p2 FROM SELECT ? + ?")
    assert runner.execute("EXECUTE p2 USING 1, 2").rows == [[3]]
    with pytest.raises(QueryError):
        runner.execute("EXECUTE p2 USING 1")


def test_describe_table(runner):
    rows = runner.execute("DESCRIBE tpch.tiny.region").rows
    assert [r[0] for r in rows] == ["r_regionkey", "r_name",
                                    "r_comment"]


def test_show_create_table(runner):
    sql = runner.execute(
        "SHOW CREATE TABLE tpch.tiny.region").rows[0][0]
    assert "r_regionkey" in sql and sql.startswith("CREATE TABLE")


def test_transaction_rollback_commit(runner):
    runner.execute("CREATE TABLE memory.default.tx (x bigint)")
    runner.execute("INSERT INTO memory.default.tx VALUES (1)")
    runner.execute("START TRANSACTION")
    runner.execute("INSERT INTO memory.default.tx VALUES (2)")
    runner.execute("DELETE FROM memory.default.tx WHERE x = 1")
    assert runner.execute(
        "SELECT x FROM memory.default.tx").rows == [[2]]
    runner.execute("ROLLBACK")
    assert runner.execute(
        "SELECT x FROM memory.default.tx").rows == [[1]]
    runner.execute("START TRANSACTION")
    runner.execute("INSERT INTO memory.default.tx VALUES (9)")
    runner.execute("COMMIT")
    assert sorted(runner.execute(
        "SELECT x FROM memory.default.tx").rows) == [[1], [9]]


def test_transaction_ddl_rollback(runner):
    runner.execute("START TRANSACTION")
    runner.execute("CREATE TABLE memory.default.ephemeral (x bigint)")
    runner.execute("ROLLBACK")
    with pytest.raises(QueryError):
        runner.execute("SELECT * FROM memory.default.ephemeral")


def test_transaction_errors(runner):
    with pytest.raises(QueryError):
        runner.execute("COMMIT")
    with pytest.raises(QueryError):
        runner.execute("ROLLBACK")
    runner.execute("START TRANSACTION")
    with pytest.raises(QueryError):
        runner.execute("START TRANSACTION")
    runner.execute("COMMIT")
