"""TIMESTAMP WITH TIME ZONE.

Reference parity: spi/type/TimestampWithTimeZoneType.java (instant-
based equality/ordering; zone kept for display/field extraction) +
operator/scalar/AtTimeZone.java / DateTimeFunctions.with_timezone.
"""

import datetime

import pytest

from trino_tpu.runner import LocalQueryRunner
from trino_tpu.types import parse_type


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def q(runner, sql):
    return runner.execute(sql).rows


def test_parse_type_roundtrip():
    t = parse_type("timestamp(3) with time zone")
    assert str(t) == "timestamp(3) with time zone"
    assert str(parse_type("timestamp with time zone")) == \
        "timestamp(3) with time zone"
    assert str(parse_type("timestamp(6) without time zone")) == \
        "timestamp(6)"


def test_literal_and_display(runner):
    got = q(runner,
            "SELECT TIMESTAMP '2020-06-01 10:30:00 +05:30'")[0][0]
    assert got == datetime.datetime(
        2020, 6, 1, 10, 30,
        tzinfo=datetime.timezone(datetime.timedelta(hours=5,
                                                    minutes=30)))
    # same instant in UTC
    assert got.astimezone(datetime.timezone.utc).hour == 5


def test_instant_equality_across_zones(runner):
    got = q(runner,
            "SELECT TIMESTAMP '2020-01-01 12:00:00 +02:00' = "
            "TIMESTAMP '2020-01-01 10:00:00 UTC', "
            "TIMESTAMP '2020-01-01 12:00:00 +02:00' < "
            "TIMESTAMP '2020-01-01 11:00:00 UTC'")
    assert got == [[True, True]]


def test_field_extraction_uses_zone(runner):
    got = q(runner,
            "SELECT hour(TIMESTAMP '2020-06-01 23:30:00 -07:00'), "
            "day(TIMESTAMP '2020-06-01 23:30:00 -07:00'), "
            "hour(CAST(TIMESTAMP '2020-06-01 23:30:00 -07:00' "
            "AS timestamp))")
    # local fields: hour 23, day 1; cast to plain timestamp keeps
    # the local wall-clock reading
    assert got == [[23, 1, 23]]


def test_at_time_zone(runner):
    got = q(runner,
            "SELECT TIMESTAMP '2020-01-01 00:00:00 UTC' "
            "AT TIME ZONE '+05:30'")[0][0]
    assert got.utcoffset() == datetime.timedelta(hours=5, minutes=30)
    assert got.astimezone(datetime.timezone.utc) == \
        datetime.datetime(2020, 1, 1,
                          tzinfo=datetime.timezone.utc)


def test_with_timezone_and_iso8601(runner):
    got = q(runner,
            "SELECT with_timezone(TIMESTAMP '2020-01-01 12:00:00', "
            "'+02:00'), "
            "to_iso8601(TIMESTAMP '2020-01-01 12:00:00 +02:00')")
    wt, iso = got[0]
    assert wt.astimezone(datetime.timezone.utc).hour == 10
    assert iso == "2020-01-01T12:00:00.000+02:00"


def test_cast_and_order(runner):
    got = q(runner,
            "SELECT CAST('2020-03-04 05:06:07' "
            "AS timestamp with time zone), "
            "CAST(TIMESTAMP '2020-03-04 23:30:00 -03:00' AS date)")
    assert got[0][0].astimezone(datetime.timezone.utc) == \
        datetime.datetime(2020, 3, 4, 5, 6, 7,
                          tzinfo=datetime.timezone.utc)
    assert got[0][1] == datetime.date(2020, 3, 4)
    ordered = q(runner, "SELECT t FROM (VALUES "
                "TIMESTAMP '2020-01-01 12:00:00 +05:00', "
                "TIMESTAMP '2020-01-01 12:00:00 +00:00', "
                "TIMESTAMP '2020-01-01 12:00:00 -03:00') v(t) "
                "ORDER BY t")
    instants = [r[0].astimezone(datetime.timezone.utc)
                for r in ordered]
    assert instants == sorted(instants)


def test_group_by_instant(runner):
    got = q(runner, "SELECT t, count(*) FROM (VALUES "
            "TIMESTAMP '2020-01-01 12:00:00 +02:00', "
            "TIMESTAMP '2020-01-01 10:00:00 UTC', "
            "TIMESTAMP '2020-01-01 11:00:00 UTC') v(t) "
            "GROUP BY t ORDER BY 2 DESC")
    assert [r[1] for r in got] == [2, 1]


def test_named_zone(runner):
    got = q(runner,
            "SELECT TIMESTAMP '2020-06-01 00:00:00 UTC' "
            "AT TIME ZONE 'America/New_York'")[0][0]
    assert got.utcoffset() == datetime.timedelta(hours=-4)  # EDT
