"""Parquet/ORC writers: round-trip through our readers, pyarrow, and
the SQL surface (CREATE TABLE / INSERT / SELECT on localfile).

Reference parity: lib/trino-parquet ParquetWriter + lib/trino-orc
OrcWriter + the connector page-sink SPI (round-4 verdict: L12 readers
only, no page-sink)."""

import datetime

import pytest

from trino_tpu.columnar import batch_from_pylist
from trino_tpu.formats.orc import read_orc
from trino_tpu.formats.orc_writer import write_orc
from trino_tpu.formats.parquet import read_parquet
from trino_tpu.formats.parquet_writer import write_parquet
from trino_tpu.types import BIGINT, BOOLEAN, DATE, DOUBLE, VARCHAR


def _sample():
    return batch_from_pylist(
        {"k": [1, 2, None, 4], "s": ["alpha", None, "beta", "g"],
         "v": [1.5, -2.25, None, 0.0], "f": [True, None, False, True],
         "d": [0, 10957, None, 20000]},
        {"k": BIGINT, "s": VARCHAR, "v": DOUBLE, "f": BOOLEAN,
         "d": DATE})


EXPECT = [
    [1, "alpha", 1.5, True, datetime.date(1970, 1, 1)],
    [2, None, -2.25, None, datetime.date(2000, 1, 1)],
    [None, "beta", None, False, None],
    [4, "g", 0.0, True, datetime.date(2024, 10, 4)],
]


@pytest.mark.parametrize("fmt", ["parquet", "orc"])
def test_writer_roundtrips_own_reader(tmp_path, fmt):
    path = str(tmp_path / f"t.{fmt}")
    if fmt == "parquet":
        write_parquet(path, _sample())
        back = read_parquet(path)
    else:
        write_orc(path, _sample())
        back = read_orc(path)
    assert back.to_pylist() == EXPECT


@pytest.mark.parametrize("fmt", ["parquet", "orc"])
def test_writer_roundtrips_pyarrow(tmp_path, fmt):
    path = str(tmp_path / f"t.{fmt}")
    if fmt == "parquet":
        pa = pytest.importorskip("pyarrow.parquet")
        write_parquet(path, _sample())
        t = pa.read_table(path)
    else:
        po = pytest.importorskip("pyarrow.orc")
        write_orc(path, _sample())
        t = po.ORCFile(path).read()
    d = t.to_pydict()
    assert d["k"] == [1, 2, None, 4]
    assert d["s"] == ["alpha", None, "beta", "g"]
    assert d["v"] == [1.5, -2.25, None, 0.0]
    assert d["f"] == [True, None, False, True]
    assert d["d"][1] == datetime.date(2000, 1, 1)


@pytest.mark.parametrize("fmt", ["parquet", "orc"])
def test_sql_create_insert_select_localfile(tmp_path, fmt):
    from trino_tpu.connectors.localfile import LocalFileConnector
    from trino_tpu.runner import LocalQueryRunner
    conn = LocalFileConnector(str(tmp_path))
    conn.write_format = fmt
    r = LocalQueryRunner()
    r.catalogs.register("files", conn)
    r.execute("CREATE TABLE files.default.sales "
              "(id BIGINT, region VARCHAR, amount DOUBLE)")
    r.execute("INSERT INTO files.default.sales VALUES "
              "(1, 'east', 10.5), (2, 'west', NULL), (3, NULL, 7.25)")
    r.execute("INSERT INTO files.default.sales VALUES (4, 'east', 1.0)")
    rows = r.execute("SELECT region, count(*), sum(amount) "
                     "FROM files.default.sales GROUP BY region "
                     "ORDER BY region").rows
    assert rows == [["east", 2, 11.5], ["west", 1, None],
                    [None, 1, 7.25]]
    # the file on disk is genuinely the declared format
    files = list(tmp_path.iterdir())
    assert len(files) == 1 and files[0].suffix == f".{fmt}"
    r.execute("DROP TABLE files.default.sales")
    assert not list(tmp_path.iterdir())
