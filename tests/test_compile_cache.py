"""Compile-amortization subsystem: canonical program keys
(exec/progkey.py), the hot-shape registry (exec/hotshapes.py), the AOT
compile path (exec/aot.py), and the coordinator/worker pre-warm
handshake — the kill-the-compile-tax acceptance battery.

Runs under JAX_PLATFORMS=cpu: fragment_jit is forced on where the jit
caches are the subject (TRINO_TPU_FRAGMENT_JIT / explicit arg), and
programs compile in milliseconds on the CPU backend while exercising
the identical cache/lower machinery the device path uses."""

import json
import time
import urllib.request

import pytest

from trino_tpu.exec import aot
from trino_tpu.exec import executor as exmod
from trino_tpu.exec.executor import Executor
from trino_tpu.exec.hotshapes import (HOT_SHAPES, HotShapeRegistry,
                                      record_program)
from trino_tpu.exec.progkey import canonicalize_nodes
from trino_tpu.obs.metrics import METRICS, parse_exposition
from trino_tpu.plan.nodes import FilterNode, ProjectNode
from trino_tpu.planner import LogicalPlanner
from trino_tpu.planner.optimizer import optimize
from trino_tpu.rex import Call, Const, InputRef
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session
from trino_tpu.sql.parser import parse_statement
from trino_tpu.types import BIGINT, BOOLEAN

_JIT_LOOKUPS = METRICS.counter("trino_tpu_jit_cache_total")


def _plan(runner, sql):
    stmt = parse_statement(sql)
    return optimize(
        LogicalPlanner(runner.catalogs, runner.session).plan(stmt))


def _filter_chain(sym: str, const: int):
    pred = Call("<", (InputRef(sym, BIGINT), Const(const, BIGINT)),
                BOOLEAN)
    return [FilterNode(None, pred)]


# --------------------------------------------------------------------------
# canonical program keys
# --------------------------------------------------------------------------

def test_canonical_key_ignores_symbol_names():
    a = canonicalize_nodes(_filter_chain("l_quantity$3", 10))
    b = canonicalize_nodes(_filter_chain("totally_other$9", 10))
    assert a is not None and b is not None
    assert a.key == b.key
    # ...and the plan-side mappings differ, each onto the same
    # canonical name
    assert a.mapping["l_quantity$3"] == b.mapping["totally_other$9"]


def test_canonical_key_distinguishes_constants():
    a = canonicalize_nodes(_filter_chain("x", 10))
    b = canonicalize_nodes(_filter_chain("x", 20))
    assert a.key != b.key


def test_canonical_key_rejects_volatile():
    pred = Call("<", (Call("random", (), BIGINT), Const(1, BIGINT)),
                BOOLEAN)
    assert canonicalize_nodes([FilterNode(None, pred)]) is None


def test_canonical_project_renames_inputs_and_outputs():
    n1 = ProjectNode(None, {"out$1": Call(
        "+", (InputRef("in$1", BIGINT), Const(1, BIGINT)), BIGINT)})
    n2 = ProjectNode(None, {"zz$7": Call(
        "+", (InputRef("aa$2", BIGINT), Const(1, BIGINT)), BIGINT)})
    c1, c2 = canonicalize_nodes([n1]), canonicalize_nodes([n2])
    assert c1.key == c2.key
    (sym, expr), = c1.nodes[0].assignments.items()
    assert sym.startswith("c") and expr.args[0].name.startswith("c")


def test_binding_normalizes_batch_column_order():
    """The Batch treedef (column-name tuple, columnar.py) is part of
    jax's trace-cache key: the binding must emit canonical columns in
    one deterministic order no matter how the source dict was
    ordered."""
    from trino_tpu.columnar import batch_from_pylist
    canon = canonicalize_nodes(_filter_chain("a", 5))
    b1 = batch_from_pylist({"a": [1, 2], "b": [3, 4]},
                           {"a": BIGINT, "b": BIGINT})
    b2 = batch_from_pylist({"b": [3, 4], "a": [1, 2]},
                           {"b": BIGINT, "a": BIGINT})
    r1 = canon.binding(b1).rename_in(b1)
    r2 = canon.binding(b2).rename_in(b2)
    assert list(r1.columns) == list(r2.columns)
    # round trip restores the plan's own names
    back = canon.binding(b1).rename_out(r1)
    assert set(back.columns) == {"a", "b"}


def test_renamed_plans_share_one_program_and_stay_correct():
    """Two alias spellings of the same query land on ONE cached chain
    program (1 miss + 1 hit) and both return correct rows — the
    binding renames the shared program's canonical output back to each
    plan's own symbols."""
    r = LocalQueryRunner()
    sqls = [
        "SELECT l_quantity + 41 AS a, l_discount * 2 AS b "
        "FROM lineitem WHERE l_quantity < 7 ORDER BY a LIMIT 5",
        "SELECT l_quantity + 41 AS zz, l_discount * 2 AS yy "
        "FROM lineitem WHERE l_quantity < 7 ORDER BY zz LIMIT 5"]
    h0 = _JIT_LOOKUPS.value(cache="chain", result="hit")
    outs = []
    for sql in sqls:
        plan = _plan(r, sql)
        eager = Executor(r.catalogs, r.session,
                         fragment_jit=False).execute(plan).to_pylist()
        jitted = Executor(r.catalogs, r.session,
                          fragment_jit=True).execute(plan).to_pylist()
        assert eager == jitted
        outs.append(jitted)
    assert outs[0] == outs[1]
    assert _JIT_LOOKUPS.value(cache="chain", result="hit") >= h0 + 1


# --------------------------------------------------------------------------
# warm-start proof (acceptance): second identical query through a
# FRESH Executor records zero jit_trace spans and renders "cache hit"
# --------------------------------------------------------------------------

def _span_names(trace):
    names = []

    def walk(sp):
        names.append(sp.name)
        for c in sp.children:
            walk(c)

    for root in trace.roots:
        walk(root)
    return names


def test_second_run_through_fresh_executor_is_warm(monkeypatch):
    from trino_tpu.obs.trace import QueryTrace
    monkeypatch.setenv("TRINO_TPU_WHOLE_TABLE", "1")
    r = LocalQueryRunner()
    # unique constant -> a key no other test has populated
    sql = ("SELECT l_returnflag, sum(l_quantity), avg(l_discount) "
           "FROM lineitem WHERE l_quantity < 43 "
           "GROUP BY l_returnflag ORDER BY l_returnflag")
    outs, traces, stats = [], [], []
    for _ in range(2):
        plan = _plan(r, sql)     # fresh plan = fresh symbols
        session = Session(catalog="tpch", schema="tiny")
        session.trace = QueryTrace("warmtest")
        ex = Executor(r.catalogs, session, collect_stats=True,
                      fragment_jit=True)
        with session.trace.span("execute"):
            outs.append(ex.execute(plan).to_pylist())
        traces.append(session.trace)
        stats.append(ex.stats)
    assert outs[0] == outs[1]
    # run 1 compiled at least one program; run 2 compiled NOTHING
    assert "jit_trace" in _span_names(traces[0])
    assert "jit_trace" not in _span_names(traces[1])
    assert "device_execute" in _span_names(traces[1])
    # ...and the EXPLAIN ANALYZE rendering says so
    rendered = "\n".join(exmod.stats_lines(stats[1]))
    assert "cache hit" in rendered
    assert all(s.cache_hit is not False for s in stats[1])


# --------------------------------------------------------------------------
# hot-shape registry
# --------------------------------------------------------------------------

def test_registry_ranking_and_lru_bound():
    reg = HotShapeRegistry(capacity=3)
    for key, hits in (("a", 1), ("b", 5), ("c", 2)):
        for _ in range(hits):
            assert reg.record("chain", key, lambda: {"k": key})
    assert [e["key"] for e in reg.top(2)] == ["b", "c"]
    # recency breaks hit ties
    reg.record("chain", "a", lambda: {"k": "a"})     # a: 2 hits, newest
    assert [e["key"] for e in reg.top(3)] == ["b", "a", "c"]
    # capacity bound: coldest entry (fewest hits, oldest among ties)
    # evicted — never the hottest, never the just-admitted newcomer
    reg.record("chain", "d", lambda: {"k": "d"})
    assert len(reg) == 3
    keys = {e["key"] for e in reg.top(10)}
    assert "c" not in keys and {"b", "a", "d"} <= keys


def test_registry_unsupported_payload_not_tracked():
    reg = HotShapeRegistry(capacity=4)
    assert reg.record("chain", "nope", lambda: None) is None
    assert len(reg) == 0


def test_registry_merge_dedupes_and_counts():
    reg = HotShapeRegistry(capacity=4)
    reg.record("chain", "k1", lambda: {"x": 1})
    n = reg.merge([
        {"kind": "chain", "key": "k1", "hits": 3, "payload": {"x": 1}},
        {"kind": "stream", "key": "k2", "hits": 1, "payload": {"y": 2}},
        {"bogus": True},                      # skipped, no raise
    ])
    assert n == 2
    top = {e["key"]: e["hits"] for e in reg.top(10)}
    assert top["k1"] == 4 and top["k2"] == 1


def test_registry_export_delta_ships_growth_only():
    """Task statuses ship hit-count DELTAS: re-exporting an entry
    across N statuses must contribute exactly the new sightings, never
    re-count cumulative totals (which would skew the top-K ranking
    toward shapes touched by many short tasks)."""
    reg = HotShapeRegistry(capacity=4)
    reg.record("chain", "k1", lambda: {"x": 1})
    base = reg.hit_counts()
    reg.record("chain", "k1", lambda: {"x": 1})      # +1 hit
    reg.record("stream", "k2", lambda: {"y": 2})     # new: 1 hit
    delta = reg.export_delta(base)
    assert {e["key"]: e["hits"] for e in delta} == {"k1": 1, "k2": 1}
    coord = HotShapeRegistry(capacity=4)
    coord.merge(delta)
    # a second status with NO new sightings contributes nothing
    coord.merge(reg.export_delta(reg.hit_counts()))
    assert {e["key"]: e["hits"]
            for e in coord.top(10)} == {"k1": 1, "k2": 1}


def test_prewarm_enabled_gates_recording():
    r = LocalQueryRunner()
    plan = _plan(r, "SELECT l_quantity + 977 AS v FROM lineitem "
                    "WHERE l_quantity < 977 LIMIT 3")
    session = Session(catalog="tpch", schema="tiny")
    session.set("prewarm_enabled", False)
    n0 = len(HOT_SHAPES)
    Executor(r.catalogs, session, fragment_jit=True).execute(plan)
    assert len(HOT_SHAPES) == n0     # gated off: nothing recorded
    session.set("prewarm_enabled", True)
    Executor(r.catalogs, session, fragment_jit=True).execute(plan)
    assert len(HOT_SHAPES) > n0


# --------------------------------------------------------------------------
# AOT compile path
# --------------------------------------------------------------------------

def test_aot_compile_from_registry_payload(monkeypatch):
    """Record a real run's shapes, wipe the in-process caches (a fresh
    worker process), AOT-compile from the exported payloads alone — no
    data — and prove the next run hits the pre-warmed slots."""
    monkeypatch.setenv("TRINO_TPU_WHOLE_TABLE", "1")
    r = LocalQueryRunner()
    sql = ("SELECT l_returnflag, sum(l_quantity), avg(l_discount) "
           "FROM lineitem WHERE l_quantity < 29 "
           "GROUP BY l_returnflag ORDER BY l_returnflag")
    plan = _plan(r, sql)
    ref = Executor(r.catalogs, r.session,
                   fragment_jit=True).execute(plan).to_pylist()
    entries = [e for e in HOT_SHAPES.top(50)]
    assert entries
    # round-trip through JSON: the endpoint serves exactly this form
    entries = json.loads(json.dumps(entries))
    exmod._STREAM_JIT_CACHE.clear()
    exmod._CHAIN_JIT_CACHE.clear()
    summary = aot.compile_entries(entries)
    assert summary["compiled"] >= 1 and summary["errors"] == 0
    h0 = _JIT_LOOKUPS.value(cache="stream", result="hit") \
        + _JIT_LOOKUPS.value(cache="chain", result="hit")
    out = Executor(r.catalogs, r.session,
                   fragment_jit=True).execute(_plan(r, sql)).to_pylist()
    assert out == ref
    h1 = _JIT_LOOKUPS.value(cache="stream", result="hit") \
        + _JIT_LOOKUPS.value(cache="chain", result="hit")
    assert h1 > h0


def test_aot_second_compile_is_cached():
    entries = HOT_SHAPES.top(1)
    if not entries:
        pytest.skip("no recorded shapes in this process")
    aot.compile_entries(entries)            # ensure resident
    summary = aot.compile_entries(entries)
    assert summary["cached"] == len(entries)


# --------------------------------------------------------------------------
# coordinator endpoint + worker pre-warm handshake
# --------------------------------------------------------------------------

def test_hotshapes_endpoint_serves_ranked_payloads():
    from trino_tpu.server.coordinator import Coordinator
    r = LocalQueryRunner()
    plan = _plan(r, "SELECT l_quantity * 3 AS t FROM lineitem "
                    "WHERE l_quantity < 31 LIMIT 4")
    Executor(r.catalogs, r.session, fragment_jit=True).execute(plan)
    co = Coordinator().start()
    try:
        with urllib.request.urlopen(
                co.base_uri + "/v1/hotshapes?k=100") as resp:
            d = json.loads(resp.read())
        assert d["tracked"] == len(HOT_SHAPES)
        assert d["shapes"] and all(
            "payload" in e and "kind" in e for e in d["shapes"])
        # k bounds the list
        with urllib.request.urlopen(
                co.base_uri + "/v1/hotshapes?k=1") as resp:
            assert len(json.loads(resp.read())["shapes"]) == 1
    finally:
        co.stop()


def _wait(pred, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_prewarm_readiness_flag_rides_announce():
    from trino_tpu.server.coordinator import Coordinator
    from trino_tpu.server.task_worker import TaskWorkerServer
    co = Coordinator().start()
    cold = TaskWorkerServer().start()
    warm = TaskWorkerServer().start()
    try:
        cold.announce(co.base_uri, prewarm=False)
        warm.announce(co.base_uri, prewarm=True)
        assert _wait(lambda: co.worker_prewarmed.get(
            warm.base_uri) is True)
        assert co.worker_prewarmed.get(cold.base_uri) is False
        # warm-first scheduling preference, stable within classes
        assert co.live_workers()[0] == warm.base_uri
    finally:
        cold.stop()
        warm.stop()
        co.stop()


def test_prewarmed_worker_serves_first_fragment_as_cache_hit(
        monkeypatch):
    """The acceptance e2e: a distributed query records its fragment
    shapes into the coordinator registry (worker task status ->
    merge); the in-process jit caches are wiped (a fresh worker
    process); a NEW worker joins with prewarm=True, compiles the hot
    list before taking traffic, and the same query's first fragment on
    it is an in-process cache hit — asserted through /metrics like an
    operator would."""
    from trino_tpu.client import StatementClient
    from trino_tpu.server.coordinator import Coordinator
    from trino_tpu.server.task_worker import TaskWorkerServer
    monkeypatch.setenv("TRINO_TPU_FRAGMENT_JIT", "1")
    sql = ("SELECT l_returnflag, sum(l_quantity) AS q FROM lineitem "
           "WHERE l_quantity < 37 GROUP BY l_returnflag "
           "ORDER BY l_returnflag")
    co = Coordinator().start()
    w1 = TaskWorkerServer().start()
    try:
        w1.announce(co.base_uri, prewarm=False)
        assert _wait(lambda: co.live_workers())
        c = StatementClient(co.base_uri, catalog="tpch", schema="tiny")
        ref = c.execute(sql).rows
        assert ref
        # the worker-side fragment shapes reached the coordinator's
        # registry via the task status hotShapes delta
        assert any(e["kind"] in ("stream", "chain")
                   for e in HOT_SHAPES.top(50))
        # fresh-worker simulation: in-process caches wiped; ONLY the
        # pre-warm pull can repopulate them
        exmod._STREAM_JIT_CACHE.clear()
        exmod._CHAIN_JIT_CACHE.clear()
        w2 = TaskWorkerServer().start()
        try:
            w2.announce(co.base_uri, prewarm=True)
            assert _wait(w2._is_prewarmed)
            assert (w2._prewarm_summary or {}).get("compiled", 0) >= 1
            def scrape():
                with urllib.request.urlopen(
                        w2.base_uri + "/metrics") as resp:
                    return parse_exposition(resp.read().decode())
            def hits(m):
                fam = m.get("trino_tpu_jit_cache_total", {})
                return sum(v for k, v in fam.items()
                           if "result=hit" in k)
            h0 = hits(scrape())
            rows = c.execute(sql).rows
            assert rows == ref
            m = scrape()
            assert hits(m) > h0
            aot_fam = m.get("trino_tpu_aot_compiles_total", {})
            assert sum(v for k, v in aot_fam.items()
                       if "result=compiled" in k) >= 1
        finally:
            w2.stop()
    finally:
        w1.stop()
        co.stop()


# --------------------------------------------------------------------------
# jit-cache eviction satellite
# --------------------------------------------------------------------------

def test_cache_put_honors_configured_capacity_and_counts_evictions(
        monkeypatch):
    from trino_tpu.config import CONFIG
    monkeypatch.setattr(CONFIG, "jit_cache_entries", 2)
    evict = METRICS.counter("trino_tpu_jit_cache_evictions_total")
    e0 = evict.value()
    scratch = {}
    for i in range(4):
        exmod._cache_put(scratch, ("k", i), object())
    assert len(scratch) == 2
    assert evict.value() == e0 + 2
    assert ("k", 3) in scratch and ("k", 2) in scratch
