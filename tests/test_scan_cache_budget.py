"""HBM residency budget: LRU eviction + bounded fallback.

Reference parity: memory/MemoryPool.java reserve/evict discipline +
execution/MemoryRevokingScheduler.java:50 (free revocable memory under
pressure). Here the revocable pool is the whole-table HBM scan cache
(exec/executor.py read_table_cached): entries evict LRU under a byte
budget, an over-budget table falls back to split streaming, and query
results never change with the budget.
"""

import pytest

from trino_tpu.config import CONFIG
from trino_tpu.exec import executor as ex
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session


@pytest.fixture
def tiny_budget(monkeypatch):
    """1 MiB scan-cache budget: no tpch table at tiny scale fits whole
    except nation/region."""
    monkeypatch.setattr(CONFIG, "scan_cache_bytes", 1 << 20)
    with ex._SCAN_CACHE_LOCK:
        ex._SCAN_CACHES.clear()
    yield
    with ex._SCAN_CACHE_LOCK:
        ex._SCAN_CACHES.clear()


def _cache_bytes():
    with ex._SCAN_CACHE_LOCK:
        return sum(s["bytes"] for s in ex._SCAN_CACHES.values())


def _run(sql):
    r = LocalQueryRunner(session=Session(catalog="tpch", schema="tiny"))
    return r.execute(sql).rows


@pytest.mark.parametrize("whole_table", ["0", "1"])
def test_results_identical_under_tiny_budget(tiny_budget, monkeypatch,
                                             whole_table):
    # "1" forces the whole-table HBM residency path (default-on for
    # device backends only) so the budget admission check is exercised
    # on the CPU test backend too
    monkeypatch.setenv("TRINO_TPU_WHOLE_TABLE", whole_table)
    q1 = ("SELECT l_returnflag, l_linestatus, sum(l_quantity), count(*) "
          "FROM lineitem WHERE l_shipdate <= DATE '1998-09-02' "
          "GROUP BY l_returnflag, l_linestatus ORDER BY 1, 2")
    got = _run(q1)
    assert _cache_bytes() <= CONFIG.scan_cache_bytes
    # independent run at the default budget
    with ex._SCAN_CACHE_LOCK:
        ex._SCAN_CACHES.clear()
    CONFIG.scan_cache_bytes = 4 << 30
    exp = _run(q1)
    assert got == exp


def test_join_streams_when_over_budget(tiny_budget):
    rows = _run("SELECT n_name, count(*) FROM orders "
                "JOIN customer ON o_custkey = c_custkey "
                "JOIN nation ON c_nationkey = n_nationkey "
                "GROUP BY n_name ORDER BY 2 DESC, 1 LIMIT 5")
    assert len(rows) == 5
    assert _cache_bytes() <= CONFIG.scan_cache_bytes


def test_lru_eviction_under_budget(monkeypatch):
    """Two tables that each fit but not together: the LRU keeps the
    budget invariant while both scans succeed."""
    r = LocalQueryRunner(session=Session(catalog="tpch", schema="tiny"))
    # ~supplier (100 rows) and customer (1500 rows) at tiny scale:
    # budget sized for one of them
    monkeypatch.setattr(CONFIG, "scan_cache_bytes", 300_000)
    with ex._SCAN_CACHE_LOCK:
        ex._SCAN_CACHES.clear()
    a = r.execute("SELECT count(*) FROM customer").rows[0][0]
    mid = _cache_bytes()
    b = r.execute("SELECT count(*) FROM supplier").rows[0][0]
    assert (a, b) == (1500, 100)
    assert _cache_bytes() <= 300_000
    with ex._SCAN_CACHE_LOCK:
        ex._SCAN_CACHES.clear()


def test_scan_reserves_against_memory_guard(monkeypatch):
    """A table whose materialization exceeds query_max_memory_per_node
    fails with the actionable memory error, not an HBM OOM."""
    r = LocalQueryRunner(session=Session(catalog="tpch", schema="tiny"))
    r.session.properties["query_max_memory_per_node"] = 1 << 10
    monkeypatch.setattr(CONFIG, "scan_cache_bytes", 0)  # force fallback
    with pytest.raises(Exception, match="memory limit"):
        # ORDER BY defeats the streaming-aggregation path: the scan
        # itself must materialize
        r.execute("SELECT * FROM orders ORDER BY o_orderkey LIMIT 5")


def test_split_share_scan_reserves_against_memory_guard():
    """The WORKER split-share scan path (scan_partition set, as the
    remote task runner does) hits the same reserve-before-allocate
    discipline: an oversized fragment fails with the actionable memory
    error instead of a raw HBM OOM mid-concat."""
    from trino_tpu.exec import QueryError
    from trino_tpu.exec.executor import Executor
    r = LocalQueryRunner(session=Session(catalog="tpch", schema="tiny"))
    r.session.properties["query_max_memory_per_node"] = 1 << 10
    plan = r.plan_sql("SELECT * FROM orders ORDER BY o_orderkey")
    worker_ex = Executor(r.catalogs, r.session)
    worker_ex.scan_partition = (0, 2)
    with pytest.raises(QueryError, match="memory limit"):
        worker_ex.execute(plan)
