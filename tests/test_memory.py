"""Memory accounting + spill-to-host tests.

Reference parity: lib/trino-memory-context (reservation tree),
ExceededMemoryLimitException, and the spill machinery
(execution/MemoryRevokingScheduler.java:50, HashBuilderOperator
spill states) — collapsed to the engine's two real mechanisms:
the capacity-planning memory guard and host-RAM chunk accumulation
for oversized join outputs.
"""

import numpy as np
import pytest

from trino_tpu.config import CONFIG
from trino_tpu.exec import QueryError
from trino_tpu.runner import LocalQueryRunner


@pytest.fixture()
def runner():
    return LocalQueryRunner()


def test_memory_guard_rejects_giant_cross_join(runner):
    runner.execute("SET SESSION query_max_memory_per_node = 1000000")
    runner.execute("SET SESSION spill_enabled = false")
    with pytest.raises(QueryError, match="memory limit"):
        runner.execute(
            "SELECT count(*) FROM tpch.tiny.lineitem a, "
            "tpch.tiny.lineitem b WHERE a.l_quantity > b.l_quantity")


def test_chunked_join_matches_unchunked(runner):
    """Force the spill path by shrinking the per-batch budget; results
    must match the in-memory join bit for bit."""
    sql = ("SELECT o_orderpriority, count(*) c, sum(l_quantity) s "
           "FROM tpch.tiny.orders JOIN tpch.tiny.lineitem "
           "ON l_orderkey = o_orderkey "
           "GROUP BY o_orderpriority ORDER BY 1")
    want = runner.execute(sql).rows
    old = CONFIG.max_batch_rows
    CONFIG.max_batch_rows = 4096   # lineitem join output ~60k rows
    try:
        got = runner.execute(sql).rows
    finally:
        CONFIG.max_batch_rows = old
    assert got == want


def test_chunked_left_join_matches(runner):
    sql = ("SELECT count(*), count(o_orderkey) "
           "FROM tpch.tiny.customer LEFT JOIN tpch.tiny.orders "
           "ON o_custkey = c_custkey")
    want = runner.execute(sql).rows
    old = CONFIG.max_batch_rows
    CONFIG.max_batch_rows = 4096
    try:
        got = runner.execute(sql).rows
    finally:
        CONFIG.max_batch_rows = old
    assert got == want


def test_spill_disabled_oversized_join_raises(runner):
    runner.execute("SET SESSION spill_enabled = false")
    runner.execute("SET SESSION query_max_memory_per_node = 100000")
    old = CONFIG.max_batch_rows
    CONFIG.max_batch_rows = 4096
    try:
        with pytest.raises(QueryError, match="memory limit"):
            runner.execute(
                "SELECT count(l_quantity) FROM tpch.tiny.orders "
                "JOIN tpch.tiny.lineitem ON l_orderkey = o_orderkey")
    finally:
        CONFIG.max_batch_rows = old


def test_chunked_residual_join_matches(runner):
    sql = ("SELECT count(*) FROM tpch.tiny.orders o "
           "JOIN tpch.tiny.lineitem l ON l_orderkey = o_orderkey "
           "AND l_extendedprice > o_totalprice * 0.5")
    want = runner.execute(sql).rows
    old = CONFIG.max_batch_rows
    CONFIG.max_batch_rows = 4096
    try:
        got = runner.execute(sql).rows
    finally:
        CONFIG.max_batch_rows = old
    assert got == want
