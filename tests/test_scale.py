"""Scale-ladder runs: BASELINE.json configs[2] (q3 @ sf10) and
configs[3] (q18 @ sf100).

Gated behind TRINO_TPU_SCALE_TESTS=1 — on the 1-core CI box these
take minutes (sf10) to tens of minutes (sf100); the point is
completing WITHOUT out-of-memory, exercising the memory guard +
split-streaming + chunked-join machinery (reference:
HashBuilderOperator spill state machine,
execution/MemoryRevokingScheduler).
"""

import os

import pytest

from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
from trino_tpu.runner import LocalQueryRunner

pytestmark = pytest.mark.skipif(
    os.environ.get("TRINO_TPU_SCALE_TESTS") != "1",
    reason="scale tests are opt-in (TRINO_TPU_SCALE_TESTS=1)")


def test_q3_sf10():
    runner = LocalQueryRunner()
    runner.execute("USE tpch.sf10")
    res = runner.execute(TPCH_QUERIES[3])
    assert len(res.rows) == 10
    # top row is the largest revenue; q3@sf10 revenue ~ 4e5..6e5
    assert res.rows[0][1] > 1e5


def test_q18_sf100():
    runner = LocalQueryRunner()
    runner.execute("USE tpch.sf100")
    res = runner.execute(TPCH_QUERIES[18])
    assert len(res.rows) <= 100
    for row in res.rows:
        assert row[-1] > 300     # sum(l_quantity) > 300 per the query
