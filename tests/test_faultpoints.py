"""Fault-point registry unit tests (trino_tpu/fte/faultpoints.py).

The registry is the deterministic half of the chaos harness: a named
site either does nothing (unarmed — the production state) or performs
exactly the scheduled action at exactly the scheduled hit. Everything
the failover tests rely on — skip counts, fire-once, env parsing,
programmatic installs beating the env — is pinned here in isolation.
"""

import time

import pytest

from trino_tpu.fte import faultpoints
from trino_tpu.fte.faultpoints import (FaultInjected, armed_sites,
                                       fault_point, install,
                                       parse_schedule)


@pytest.fixture(autouse=True)
def _clean_registry(monkeypatch):
    monkeypatch.delenv(faultpoints.ENV_VAR, raising=False)
    faultpoints.reset()
    yield
    faultpoints.reset()


def test_unarmed_site_is_a_noop():
    fault_point("coordinator.pre_dispatch")     # must not raise
    fault_point("never.heard.of.it")


def test_raise_action_fires_once_then_goes_inert():
    install("site.a", "raise")
    with pytest.raises(FaultInjected) as err:
        fault_point("site.a")
    assert err.value.site == "site.a"
    fault_point("site.a")                       # spent: inert now


def test_skip_defers_firing_to_the_nth_hit():
    install("site.b", "raise", skip=2)
    fault_point("site.b")
    fault_point("site.b")
    with pytest.raises(FaultInjected):
        fault_point("site.b")
    fault_point("site.b")


def test_count_allows_repeat_firing():
    install("site.c", "raise", count=2)
    for _ in range(2):
        with pytest.raises(FaultInjected):
            fault_point("site.c")
    fault_point("site.c")


def test_delay_action_sleeps_then_continues():
    install("site.d", "delay", seconds=0.05)
    t0 = time.perf_counter()
    fault_point("site.d")
    assert time.perf_counter() - t0 >= 0.05


def test_callback_runs_and_may_request_raise():
    seen = []
    install("site.e", callback=lambda site: seen.append(site))
    fault_point("site.e")
    assert seen == ["site.e"]

    install("site.f", callback=lambda site: "raise")
    with pytest.raises(FaultInjected):
        fault_point("site.f")


def test_parse_schedule_grammar():
    sched = parse_schedule(
        "coordinator.post_stage_commit=crash@1, "
        "worker.pre_status_beat=delay:0.5, spool.pre_marker=raise")
    assert sched["coordinator.post_stage_commit"].action == "crash"
    assert sched["coordinator.post_stage_commit"].skip == 1
    assert sched["worker.pre_status_beat"].action == "delay"
    assert sched["worker.pre_status_beat"].seconds == 0.5
    assert sched["spool.pre_marker"].action == "raise"


@pytest.mark.parametrize("bad", [
    "no-equals-sign",
    "site=frobnicate",           # unknown action
    "site=call",                 # call is install()-only
    "=raise",                    # missing site
    "site=delay:not-a-number",
    "site=raise@nope",
])
def test_parse_schedule_rejects_malformed_specs(bad):
    with pytest.raises(ValueError):
        parse_schedule(bad)


def test_env_schedule_arms_lazily_and_reset_rearms(monkeypatch):
    monkeypatch.setenv(faultpoints.ENV_VAR, "site.env=raise")
    faultpoints.reset()              # forget: env re-read on next use
    with pytest.raises(FaultInjected):
        fault_point("site.env")
    fault_point("site.env")          # spent
    faultpoints.reset()              # re-arms from env again
    with pytest.raises(FaultInjected):
        fault_point("site.env")


def test_install_beats_env_schedule(monkeypatch):
    monkeypatch.setenv(faultpoints.ENV_VAR, "site.g=raise")
    faultpoints.reset()
    install("site.g", "delay", seconds=0.0)
    fault_point("site.g")            # delay(0), NOT the env's raise
    assert armed_sites()["site.g"] == "delay"


def test_armed_sites_lists_env_and_installs(monkeypatch):
    monkeypatch.setenv(faultpoints.ENV_VAR, "site.h=crash")
    faultpoints.reset()
    install("site.i", "raise")
    sites = armed_sites()
    assert sites["site.h"] == "crash" and sites["site.i"] == "raise"


def test_startup_banner_parses_and_announces(monkeypatch, capsys):
    from trino_tpu.server.main import _announce_fault_points
    monkeypatch.setenv(faultpoints.ENV_VAR, "worker.pre_status_beat=delay:0.1")
    faultpoints.reset()
    _announce_fault_points()
    assert "worker.pre_status_beat=delay" in capsys.readouterr().err
    monkeypatch.setenv(faultpoints.ENV_VAR, "oops")
    with pytest.raises(ValueError):
        _announce_fault_points()
