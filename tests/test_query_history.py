"""Query history & learned operator statistics (PR 19): the engine
observes itself with SQL.

Covers the tentpole end to end — the bounded/TTL'd/JSONL-durable
query-history store (obs/history.py), the learned-stats registry with
hot-shape-style origin-deduped delta transport (exec/learnedstats.py),
the /v1/history, /v1/stats and bare /v1/trace endpoints, and the
system.runtime.{queries,operator_stats,metrics} tables scanned through
the default MPP path — plus the failure-path records satellite: an
OOM kill, a deadline breach and a queue-full rejection each land
exactly one classified record with non-zero timing."""

import json
import threading
import time
import urllib.request

import pytest

from trino_tpu.client import ClientError, StatementClient
from trino_tpu.exec.learnedstats import (LEARNED_STATS,
                                         LearnedStatsRegistry,
                                         record_node_stats)
from trino_tpu.obs.history import (QueryHistoryStore, TraceRing,
                                   record_from_query, sql_digest)
from trino_tpu.runner import LocalQueryRunner, QueryResult
from trino_tpu.server.coordinator import Coordinator, QueryTracker
from trino_tpu.session import Session


def _wait_until(pred, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _get_json(uri):
    with urllib.request.urlopen(uri, timeout=10) as resp:
        return json.load(resp)


# --- learned-stats registry (exec/learnedstats.py) -------------------------

def test_learned_stats_ema_selectivity_and_rate():
    reg = LearnedStatsRegistry(capacity=8, alpha=0.5)
    reg.observe("k1", "Filter", 0, rows_in=100, rows_out=50, wall_s=0.5)
    ent = reg.lookup("k1", "Filter", 0)
    assert ent["selectivity"] == pytest.approx(0.5)
    assert ent["rows_per_s"] == pytest.approx(100.0)
    # EMA folds the second observation at alpha=0.5
    reg.observe("k1", "Filter", 0, rows_in=100, rows_out=100, wall_s=0.5)
    ent = reg.lookup("k1", "Filter", 0)
    assert ent["selectivity"] == pytest.approx(0.75)
    assert ent["n"] == 2 and ent["rows_in"] == 200
    # unknown rows (-1) must not poison the EMAs
    reg.observe("k1", "Filter", 0, rows_in=-1, rows_out=-1, wall_s=0.1)
    assert reg.lookup("k1", "Filter", 0)["selectivity"] \
        == pytest.approx(0.75)
    # occurrence index separates repeated operator names
    reg.observe("k1", "Filter", 1, rows_in=10, rows_out=1, wall_s=0.1)
    assert reg.lookup("k1", "Filter", 1)["selectivity"] \
        == pytest.approx(0.1)


def test_learned_stats_lru_capacity():
    reg = LearnedStatsRegistry(capacity=2, alpha=0.5)
    for i in range(4):
        reg.observe(f"k{i}", "Scan", 0, 10, 10, 0.1)
    assert len(reg) == 2
    assert reg.lookup("k0", "Scan", 0) is None
    assert reg.lookup("k3", "Scan", 0) is not None


def test_learned_stats_merge_dedups_self_origin():
    """The hot-shape transport contract: a registry never re-absorbs
    its own exported observations (shared-process worker), merges
    foreign ones, and a relay re-export preserves the ORIGINAL origin
    so the true source still dedups."""
    a = LearnedStatsRegistry(capacity=8)
    b = LearnedStatsRegistry(capacity=8)
    before = a.seq()
    a.observe("k", "Join", 0, 100, 20, 0.2)
    delta = a.export_delta(before)
    assert len(delta) == 1 and delta[0]["origin"] == a.origin
    # self-merge: dropped entirely
    assert a.merge(delta) == 0 and a.lookup("k", "Join", 0)["n"] == 1
    # foreign merge: absorbed with b's own smoothing
    b_before = b.seq()
    assert b.merge(delta) == 1
    assert b.lookup("k", "Join", 0)["selectivity"] == pytest.approx(0.2)
    # relay: b re-exports what it merged; a still recognizes itself
    relayed = b.export_delta(b_before)
    assert relayed[0]["origin"] == a.origin
    assert a.merge(relayed) == 0
    assert a.lookup("k", "Join", 0)["n"] == 1


def test_learned_stats_save_load_roundtrip(tmp_path):
    reg = LearnedStatsRegistry(capacity=8)
    reg.observe("k", "Scan", 0, 1000, 500, 1.0)
    path = str(tmp_path / "learned_stats.json")
    assert reg.save(path)
    fresh = LearnedStatsRegistry(capacity=8)
    assert fresh.load(path) == 1
    ent = fresh.lookup("k", "Scan", 0)
    assert ent["selectivity"] == pytest.approx(0.5)
    assert ent["rows_per_s"] == pytest.approx(500.0)
    # live entries win over persisted ones on load
    fresh.observe("k2", "Scan", 0, 10, 1, 0.1)
    assert fresh.load(path) == 0      # k already live, nothing new
    assert len(fresh) == 2


def test_record_node_stats_respects_session_gate():
    reg_len = len(LEARNED_STATS)
    s = Session()
    s.set("learned_stats_enabled", False)
    r = QueryResult(["c"], [], [[1]])
    stats = [{"name": "Output", "input_rows": 5, "output_rows": 5,
              "wall_s": 0.01}]
    assert record_node_stats("gatedkey", stats, s) == 0
    assert len(LEARNED_STATS) == reg_len
    s.set("learned_stats_enabled", True)
    assert record_node_stats("gatedkey", stats, s) == 1
    assert LEARNED_STATS.lookup("gatedkey", "Output", 0) is not None


def test_plan_key_stable_and_distinct():
    """Every executed plan gets a non-empty deterministic key; the
    same SQL re-keys identically, different programs differ."""
    r = LocalQueryRunner(collect_node_stats=True)
    k1 = r.execute("SELECT count(*) FROM tpch.tiny.nation").plan_key
    k2 = r.execute("SELECT count(*) FROM tpch.tiny.nation").plan_key
    k3 = r.execute("SELECT count(*) FROM tpch.tiny.region").plan_key
    assert k1 and k1 == k2
    assert k3 and k3 != k1


# --- history store (obs/history.py) ----------------------------------------

def _fake_record(i, state="FINISHED", created=None):
    return {"query_id": f"q{i}", "state": state, "sql": f"SELECT {i}",
            "sql_digest": sql_digest(f"SELECT {i}"), "wall_s": 0.1,
            "created": created if created is not None else time.time()}


def test_history_store_bounded_and_jsonl_durable(tmp_path):
    path = str(tmp_path / "queries.jsonl")
    store = QueryHistoryStore(path, capacity=4, ttl_s=3600)
    for i in range(10):
        store.record(_fake_record(i))
    assert len(store) == 4
    recs = store.records()
    assert [r["query_id"] for r in recs] == ["q9", "q8", "q7", "q6"]
    assert store.get("q9") is not None and store.get("q0") is None
    # state filter + limit
    store.record(_fake_record(99, state="FAILED"))
    assert [r["query_id"] for r in store.records(state="FAILED")] \
        == ["q99"]
    assert len(store.records(limit=2)) == 2
    # a NEW store over the same file reloads the survivors
    again = QueryHistoryStore(path, capacity=4, ttl_s=3600)
    assert {r["query_id"] for r in again.records()} \
        == {"q99", "q9", "q8", "q7"}


def test_history_store_ttl_prunes(tmp_path):
    store = QueryHistoryStore(str(tmp_path / "q.jsonl"), capacity=8,
                              ttl_s=60)
    old = _fake_record(0, created=time.time() - 3600)
    old["recorded_at"] = time.time() - 3600
    store._records.append(old)        # pre-aged entry
    store.record(_fake_record(1))
    assert [r["query_id"] for r in store.records()] == ["q1"]
    # reload path drops expired lines too
    store2 = QueryHistoryStore(str(tmp_path / "q2.jsonl"), capacity=8,
                               ttl_s=60)
    store2.record(dict(_fake_record(2),
                       recorded_at=time.time() - 3600))
    assert QueryHistoryStore(store2.path, capacity=8,
                             ttl_s=60).records() == []


def test_slow_query_log_side_channel(tmp_path):
    store = QueryHistoryStore(str(tmp_path / "queries.jsonl"))
    rec = store.record(_fake_record(1))
    store.slow_log(rec, 50)
    lines = (tmp_path / "slow_queries.jsonl").read_text().splitlines()
    entry = json.loads(lines[-1])
    assert entry["query_id"] == "q1"
    assert entry["slow_query_threshold_ms"] == 50


def test_trace_ring_bounded_and_traceless_noop():
    ring = TraceRing(capacity=2)
    ring.append("q0", "FINISHED", None)         # traceless: no entry
    assert len(ring) == 0

    class _Span:
        def __init__(self, name):
            self.name, self.wall_s, self.children = name, 0.5, []

    class _Trace:
        def __init__(self, tid):
            self.trace_id = tid
            self.roots = [_Span("query")]

    for i in range(3):
        ring.append(f"q{i}", "FINISHED", _Trace(f"t{i}"))
    out = ring.list()
    assert [e["traceId"] for e in out] == ["t2", "t1"]
    assert out[0]["rootSpans"][0]["name"] == "query"


# --- terminal records through the coordinator ------------------------------

def test_coordinator_records_history_and_serves_endpoints(tmp_path):
    co = Coordinator(history_dir=str(tmp_path)).start()
    try:
        c = StatementClient(co.base_uri,
                            session_properties={"slow_query_log_ms": "1"})
        res = c.execute("SELECT count(*) FROM tpch.tiny.nation")
        _wait_until(lambda: co.history.get(res.query_id) is not None,
                    what="history record")
        out = _get_json(f"{co.base_uri}/v1/history")
        rec = next(r for r in out["records"]
                   if r["query_id"] == res.query_id)
        assert rec["state"] == "FINISHED"
        assert rec["plan_key"] and rec["sql_digest"]
        assert rec["wall_s"] > 0 and rec["cpu_s"] > 0
        assert rec["rows"] == 1
        assert rec["operators"], "per-operator rows-in/out missing"
        # ?state= and ?limit= filters
        assert _get_json(f"{co.base_uri}/v1/history?state=FAILED"
                         )["records"] == []
        assert len(_get_json(f"{co.base_uri}/v1/history?limit=1"
                             )["records"]) == 1
        # learned stats observed the execution
        stats = _get_json(f"{co.base_uri}/v1/stats")
        mine = [e for e in stats["entries"]
                if e["key"] == rec["plan_key"]]
        assert mine and any(e["selectivity"] is not None for e in mine)
        # bare /v1/trace (404'd before this PR) lists the trace
        traces = _get_json(f"{co.base_uri}/v1/trace")["traces"]
        assert any(t["queryId"] == res.query_id for t in traces)
        # slow-query log armed at 1ms caught it
        slow = (tmp_path / "slow_queries.jsonl").read_text()
        assert res.query_id in slow
    finally:
        co.stop()


def test_history_disabled_by_session_property(tmp_path):
    co = Coordinator(history_dir=str(tmp_path)).start()
    try:
        c = StatementClient(co.base_uri, session_properties={
            "query_history_enabled": "false"})
        res = c.execute("SELECT 1")
        time.sleep(0.3)
        assert co.history.get(res.query_id) is None
    finally:
        co.stop()


# --- failure-path records (satellite b) ------------------------------------

def test_oom_kill_lands_classified_record(tmp_path):
    """A CLUSTER_OUT_OF_MEMORY victim leaves one FAILED record with
    the kill's error identity and non-zero queued/wall timing."""
    from trino_tpu.server.memory import (ClusterMemoryManager,
                                         ClusterMemoryPool)
    store = QueryHistoryStore(str(tmp_path / "queries.jsonl"))
    gates = {"big": threading.Event(), "small": threading.Event()}

    class _Gated:
        def __init__(self, session):
            self.session = session

        def execute(self, sql):
            if self.session.memory is not None:
                self.session.memory.reserve(
                    700 if sql == "big" else 400)
            gate = gates[sql]
            while not gate.is_set():
                if self.session.cancel is not None \
                        and self.session.cancel.is_set():
                    from trino_tpu.exec.executor import QueryError
                    raise QueryError("Query was canceled")
                gate.wait(0.01)
            return QueryResult(["x"], [], [[1]])

    tracker = QueryTracker(
        _Gated, memory=ClusterMemoryManager(ClusterMemoryPool(1000)),
        history_sink=lambda q: store.record(record_from_query(q)))
    qbig = tracker.submit("big", Session())
    _wait_until(lambda: qbig.state == "RUNNING", what="big running")
    time.sleep(0.05)
    qsmall = tracker.submit("small", Session())   # 700+400 > 1000
    gates["small"].set()
    _wait_until(lambda: store.get(qbig.query_id) is not None,
                what="OOM record")
    rec = store.get(qbig.query_id)
    assert rec["state"] == "FAILED"
    assert rec["error_name"] == "CLUSTER_OUT_OF_MEMORY"
    assert rec["error_type"] == "INSUFFICIENT_RESOURCES"
    assert rec["wall_s"] > 0
    _wait_until(lambda: store.get(qsmall.query_id) is not None,
                what="survivor record")
    assert store.get(qsmall.query_id)["state"] == "FINISHED"
    assert len(store) == 2


def test_deadline_breach_lands_classified_record(tmp_path):
    """EXCEEDED_TIME_LIMIT (query_max_run_time) through the real
    coordinator: the record carries the deadline error identity and a
    wall time at least the granted budget."""
    from trino_tpu.catalog import CatalogManager
    from trino_tpu.connectors.tpch import TpchConnector

    class SlowTpch(TpchConnector):
        def read_split(self, split, columns):
            time.sleep(5)
            return super().read_split(split, columns)

    cats = CatalogManager()
    cats.register("tpch", SlowTpch())
    co = Coordinator(catalogs=cats, history_dir=str(tmp_path)).start()
    try:
        c = StatementClient(co.base_uri, session_properties={
            "query_max_run_time": "1"})
        with pytest.raises(ClientError, match="EXCEEDED_TIME_LIMIT"):
            c.execute("SELECT count(*) FROM nation")
        _wait_until(lambda: any(
            r["error_name"] == "EXCEEDED_TIME_LIMIT"
            for r in co.history.records()), what="deadline record")
        rec = next(r for r in co.history.records()
                   if r["error_name"] == "EXCEEDED_TIME_LIMIT")
        assert rec["state"] == "FAILED"
        assert rec["error_type"] == "INSUFFICIENT_RESOURCES"
        assert rec["wall_s"] >= 0.9
    finally:
        co.stop()


def test_queue_full_rejection_lands_classified_record(tmp_path):
    """A QUEUE_FULL admission rejection is history too — the
    rejection path never reaches run_and_release, so it exercises the
    second recording site."""
    from trino_tpu.server.resourcegroups import (ResourceGroup,
                                                 ResourceGroupManager)
    mgr = ResourceGroupManager()
    g = mgr.root.add(ResourceGroup("tiny", hard_concurrency=1,
                                   max_queued=0))
    mgr.add_selector(g)
    co = Coordinator(resource_groups=mgr,
                     history_dir=str(tmp_path)).start()
    try:
        slow_sql = ("SELECT count(*) FROM tpch.tiny.lineitem a, "
                    "tpch.tiny.lineitem b "
                    "WHERE a.l_suppkey = b.l_suppkey")
        errors = []

        def occupy():
            try:
                StatementClient(co.base_uri).execute(slow_sql)
            except Exception as e:      # noqa: BLE001
                errors.append(e)

        t = threading.Thread(target=occupy, daemon=True)
        t.start()
        _wait_until(lambda: any(q.state == "RUNNING"
                                for q in co.tracker.all()),
                    what="occupant running")
        with pytest.raises(ClientError, match="QUERY_QUEUE_FULL"):
            StatementClient(co.base_uri).execute("SELECT 1")
        _wait_until(lambda: any(
            r["error_name"] == "QUERY_QUEUE_FULL"
            for r in co.history.records()), what="rejection record")
        rec = next(r for r in co.history.records()
                   if r["error_name"] == "QUERY_QUEUE_FULL")
        assert rec["state"] == "FAILED"
        assert rec["error_type"] == "INSUFFICIENT_RESOURCES"
        assert rec["wall_s"] > 0          # rejected, but time passed
        t.join(60)
        assert not errors
    finally:
        co.stop()


# --- restart survival + MPP acceptance -------------------------------------

def test_history_and_learned_stats_survive_restart(tmp_path):
    LEARNED_STATS.clear()
    co1 = Coordinator(history_dir=str(tmp_path)).start()
    try:
        res = StatementClient(co1.base_uri).execute(
            "SELECT count(*) FROM tpch.tiny.nation")
        _wait_until(lambda: co1.history.get(res.query_id) is not None,
                    what="history record")
        assert len(LEARNED_STATS) > 0
    finally:
        co1.stop()                 # final learned-stats checkpoint
    LEARNED_STATS.clear()          # simulate a fresh process
    co2 = Coordinator(history_dir=str(tmp_path)).start()
    try:
        out = _get_json(f"{co2.base_uri}/v1/history")
        rec = next(r for r in out["records"]
                   if r["query_id"] == res.query_id)
        assert rec["state"] == "FINISHED" and rec["plan_key"]
        stats = _get_json(f"{co2.base_uri}/v1/stats")
        assert stats["tracked"] > 0
        assert any(e["key"] == rec["plan_key"]
                   for e in stats["entries"])
    finally:
        co2.stop()


def test_mpp_query_lands_in_system_runtime_tables(tmp_path):
    """The acceptance e2e: a TPCH query through the DEFAULT MPP path
    (real worker HTTP servers), then SELECT its own record back from
    system.runtime.queries — matching id, canonical plan key, non-zero
    cpu attribution — and its operators' learned selectivities from
    system.runtime.operator_stats; finally a coordinator restart
    serves both through /v1/history and /v1/stats."""
    from trino_tpu.server.task_worker import TaskWorkerServer
    LEARNED_STATS.clear()
    workers = [TaskWorkerServer().start() for _ in range(2)]
    co = Coordinator(worker_uris=[w.base_uri for w in workers],
                     history_dir=str(tmp_path)).start()
    try:
        c = StatementClient(co.base_uri)
        res = c.execute(
            "SELECT n_name, count(*) c FROM nation "
            "JOIN region ON n_regionkey = r_regionkey "
            "GROUP BY n_name ORDER BY n_name")
        assert len(res.rows) == 25
        _wait_until(lambda: co.history.get(res.query_id) is not None,
                    what="history record")
        rows = c.execute(
            "SELECT query_id, state, plan_key, cpu_s, rows "
            "FROM system.runtime.queries "
            f"WHERE query_id = '{res.query_id}'").rows
        assert len(rows) == 1
        qid, state, plan_key, cpu_s, nrows = rows[0]
        assert qid == res.query_id and state == "FINISHED"
        assert plan_key, "canonical plan key missing from record"
        assert cpu_s > 0, "no cpu attribution through the MPP path"
        assert nrows == 25
        # worker-observed operator selectivities (shipped as
        # learnedStats status deltas, merged at the scheduler)
        ops = c.execute(
            "SELECT plan_key, operator, selectivity, rows_per_s "
            "FROM system.runtime.operator_stats "
            "WHERE selectivity IS NOT NULL").rows
        assert ops, "no learned operator stats after an MPP query"
        assert all(sel >= 0 for _, _, sel, _ in ops)
        # failed queries are selectable BY error classification
        with pytest.raises(ClientError):
            c.execute("SELECT no_such_column FROM nation")
        _wait_until(lambda: any(
            r.get("error_name") for r in co.history.records()),
            what="failed record")
        failed = c.execute(
            "SELECT query_id, error_code FROM system.runtime.queries "
            "WHERE error_code IS NOT NULL ORDER BY wall_s DESC").rows
        assert failed and all(code for _, code in failed)
        # the metrics ring/rollup table scans (cluster-wide: the
        # coordinator's registry + scraped workers)
        m = c.execute(
            "SELECT count(*) FROM system.runtime.metrics "
            "WHERE sample = 'current'").rows
        assert m[0][0] > 0
    finally:
        co.stop()
        for w in workers:
            w.stop()
    # restart: both surfaces survive the coordinator process
    LEARNED_STATS.clear()
    co2 = Coordinator(history_dir=str(tmp_path)).start()
    try:
        recs = _get_json(f"{co2.base_uri}/v1/history")["records"]
        assert any(r["query_id"] == res.query_id for r in recs)
        assert _get_json(f"{co2.base_uri}/v1/stats")["tracked"] > 0
    finally:
        co2.stop()
