"""Multi-host data plane tests: task workers + serde page exchange.

Reference parity: the DistributedQueryRunner tier with REAL process +
HTTP boundaries (SURVEY.md §4: coordinator + N TestingTrinoServer in
one JVM over ephemeral ports) — here two worker PROCESSES execute
partial fragments and the parent pulls their result pages through the
token-acknowledged exchange (TaskResource results protocol), with every
page passing through serde.py framing (LZ4 + xxh64).
"""

import multiprocessing as mp

import numpy as np
import pytest

from trino_tpu import serde
from trino_tpu.columnar import Batch, batch_from_pylist
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.serde import deserialize_batch, serialize_batch
from trino_tpu.server.task_worker import (RemoteTaskClient,
                                          TaskWorkerServer, paginate,
                                          worker_main)
from trino_tpu.types import BIGINT, DOUBLE, VARCHAR


# --------------------------------------------------------------------------
# serde framing: the test that fails if serde breaks
# --------------------------------------------------------------------------

def _sample_batch():
    return batch_from_pylist(
        {"k": [1, 2, None, 4] * 64,
         "s": ["alpha", None, "beta", "gamma"] * 64,
         "v": [1.5, -2.25, 3.75, None] * 64},
        {"k": BIGINT, "s": VARCHAR, "v": DOUBLE})


@pytest.mark.parametrize("codec",
                         [serde.CODEC_STORE, serde.CODEC_LZ4])
def test_serde_roundtrip(codec):
    if codec == serde.CODEC_LZ4 and not serde.native_available():
        pytest.skip("native lz4 unavailable (g++ missing?)")
    b = _sample_batch()
    frame = serialize_batch(b, codec=codec)
    back = deserialize_batch(frame)
    assert back.to_pylist() == b.to_pylist()
    assert back.schema()["s"].name.startswith("varchar")


def test_serde_native_lz4_builds():
    # the native library is part of the data plane, not optional décor:
    # its absence must be a loud failure on a machine with a toolchain
    assert serde.native_available(), \
        "native/pageserde.cpp failed to build or load"


def test_serde_detects_corruption():
    frame = bytearray(serialize_batch(_sample_batch()))
    frame[len(frame) // 2] ^= 0x40
    with pytest.raises(Exception, match="checksum|corrupt"):
        deserialize_batch(bytes(frame))


def test_paginate_splits_and_preserves_rows():
    b = _sample_batch()
    pages = paginate(b, page_rows=100)
    assert len(pages) == 3            # 256 rows / 100
    rows = []
    for p in pages:
        rows.extend(deserialize_batch(p).to_pylist())
    assert rows == b.to_pylist()


# --------------------------------------------------------------------------
# in-process worker server (protocol mechanics)
# --------------------------------------------------------------------------

def test_task_worker_protocol():
    srv = TaskWorkerServer().start()
    try:
        c = RemoteTaskClient(srv.base_uri)
        c.submit("t1", "SELECT n_regionkey, count(*) AS c "
                       "FROM tpch.tiny.nation GROUP BY n_regionkey")
        pages = c.pages("t1")
        rows = sorted(r for p in pages for r in p.to_pylist())
        assert rows == [[r, 5] for r in range(5)]
        # pulls are idempotent per token (ack/retry semantics)
        again = c.pages("t1")
        assert sorted(r for p in again for r in p.to_pylist()) == rows
        c.abort("t1")
    finally:
        srv.stop()


def test_task_worker_error_propagates():
    srv = TaskWorkerServer().start()
    try:
        c = RemoteTaskClient(srv.base_uri)
        c.submit("bad", "SELECT nosuch FROM tpch.tiny.nation")
        with pytest.raises(Exception, match="500|cannot be resolved"):
            c.pages("bad")
    finally:
        srv.stop()


# --------------------------------------------------------------------------
# two worker PROCESSES: the real DCN leg
# --------------------------------------------------------------------------

def test_two_process_partial_final_aggregation():
    """Partial aggregation on two worker processes, page exchange over
    HTTP through serde, final aggregation in the parent — the
    PushPartialAggregationThroughExchange shape across a genuine
    process boundary."""
    ctx = mp.get_context("spawn")
    workers = []
    try:
        from trino_tpu.server.task_worker import spawn_worker_env
        with spawn_worker_env():
            # scrubbed env: spawn children must not run the
            # TPU-forcing sitecustomize (hangs when the tunnel is down)
            for _ in range(2):
                parent, child = ctx.Pipe()
                p = ctx.Process(target=worker_main,
                                args=(child, "cpu"), daemon=True)
                p.start()
                if not parent.poll(120):
                    raise RuntimeError("worker child did not start")
                port = parent.recv()
                workers.append((p, f"http://127.0.0.1:{port}"))

        partial_sql = ("SELECT o_orderpriority AS pri, "
                       "count(*) AS c, sum(o_totalprice) AS s "
                       "FROM tpch.tiny.orders WHERE o_orderkey % 2 = {k} "
                       "GROUP BY o_orderpriority")
        batches = []
        for k, (_, uri) in enumerate(workers):
            c = RemoteTaskClient(uri)
            c.submit(f"part{k}", partial_sql.format(k=k))
        for k, (_, uri) in enumerate(workers):
            c = RemoteTaskClient(uri)
            batches.extend(c.pages(f"part{k}"))

        # final combine in the parent engine
        from trino_tpu.exec.executor import device_concat
        from trino_tpu.ops.groupby import AggInput, group_aggregate
        merged = device_concat(batches)
        fin = group_aggregate(
            merged, ["pri"],
            [AggInput("sum", "c", output="c"),
             AggInput("sum", "s", output="s")])
        n = fin.num_rows_host()
        got = sorted(fin.to_pylist()[:n])

        direct = LocalQueryRunner().execute(
            "SELECT o_orderpriority, count(*), sum(o_totalprice) "
            "FROM tpch.tiny.orders GROUP BY o_orderpriority "
            "ORDER BY 1").rows
        assert [[g[0], g[1]] for g in got] == \
            [[d[0], d[1]] for d in direct]
        for g, d in zip(got, direct):
            assert g[2] == pytest.approx(d[2], rel=1e-9)
    finally:
        for p, _ in workers:
            p.terminate()
