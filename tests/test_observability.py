"""Telemetry subsystem tests (trino_tpu/obs/).

Covers the three layers end-to-end:
- metrics registry: Prometheus exposition parsed BACK and asserted on
  (counter monotonicity across queries, jit cache hit/miss, query-state
  counters) — reference analog: the JMX stats the web UI scrapes;
- query tracing: span-tree shape for a single-node and a distributed
  query (parse -> plan -> optimize -> execute with jit_trace /
  device_execute and per-fragment children);
- rich operator stats + the distributed rollup: worker-reported rows
  summing to coordinator totals, per-fragment EXPLAIN ANALYZE numbers.
"""

import json
import urllib.request

import pytest

from trino_tpu.obs.metrics import (METRICS, MetricsRegistry,
                                   parse_exposition)
from trino_tpu.obs.trace import QueryTrace
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session


# ---------------------------------------------------------------------------
# metrics registry unit tests
# ---------------------------------------------------------------------------

def test_registry_counter_labels_and_render():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "help text", ("op", "ok"))
    c.inc(op="scan", ok="true")
    c.inc(2, op="scan", ok="false")
    assert c.value(op="scan", ok="true") == 1
    assert c.value(op="scan", ok="false") == 2
    text = reg.render()
    assert "# TYPE t_total counter" in text
    parsed = parse_exposition(text)
    assert parsed["t_total"][("op=scan", "ok=false")] == 2.0


def test_registry_counter_rejects_label_drift_and_negatives():
    reg = MetricsRegistry()
    c = reg.counter("t2_total", "", ("a",))
    with pytest.raises(ValueError):
        c.inc(b="x")
    with pytest.raises(ValueError):
        c.inc(-1, a="x")
    # get-or-create is idempotent, kind mismatch is not
    assert reg.counter("t2_total", "", ("a",)) is c
    with pytest.raises(ValueError):
        reg.gauge("t2_total")


def test_registry_histogram_buckets():
    reg = MetricsRegistry()
    h = reg.histogram("t_seconds", "", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    parsed = parse_exposition(reg.render())
    assert parsed["t_seconds_bucket"][("le=0.1",)] == 1.0
    assert parsed["t_seconds_bucket"][("le=1",)] == 2.0
    assert parsed["t_seconds_bucket"][("le=+Inf",)] == 3.0
    assert parsed["t_seconds_count"][()] == 3.0
    assert parsed["t_seconds_sum"][()] == pytest.approx(5.55)


def test_registry_collector_refreshes_gauge_at_render():
    reg = MetricsRegistry()
    g = reg.gauge("t_depth", "")
    state = {"n": 0}
    reg.register_collector(lambda: g.set(state["n"]))
    state["n"] = 7
    assert parse_exposition(reg.render())["t_depth"][()] == 7.0


def test_trace_span_nesting_and_lines():
    tr = QueryTrace("q1")
    with tr.span("plan"):
        pass
    with tr.span("execute"):
        with tr.span("jit_trace", cache="chain"):
            pass
    assert [s.name for s in tr.roots] == ["plan", "execute"]
    assert tr.roots[1].children[0].name == "jit_trace"
    d = tr.to_dicts()
    assert d[1]["children"][0]["attrs"] == {"cache": "chain"}
    assert any("jit_trace" in l for l in tr.lines())


# ---------------------------------------------------------------------------
# single-node: spans, node stats, explain analyze
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny"),
        collect_node_stats=True)


def test_span_tree_single_node(runner):
    res = runner.execute(
        "SELECT l_returnflag, count(*) FROM lineitem "
        "GROUP BY l_returnflag")
    names = [s.name for s in res.trace.roots]
    assert names == ["parse", "plan", "optimize", "execute"]
    assert all(s.wall_s >= 0 for s in res.trace.roots)
    assert res.trace.query_id == res.query_id


def test_node_stats_rows_and_bytes(runner):
    res = runner.execute(
        "SELECT count(*) AS n FROM lineitem WHERE l_quantity > 30")
    scan = [s for s in res.stats if s.name == "TableScan"]
    agg = [s for s in res.stats if s.name == "Aggregation"]
    assert scan and agg
    # the scan fed the aggregation: its output IS the agg's input
    # (pushdown may shrink the scan below the table row count)
    assert agg[0].input_rows == scan[0].output_rows > 0
    assert agg[0].output_rows == 1
    assert all(s.output_bytes >= 0 for s in res.stats)
    assert scan[0].output_bytes > 0


def test_explain_analyze_reports_flow(runner):
    res = runner.execute(
        "EXPLAIN ANALYZE SELECT count(*) FROM orders")
    text = "\n".join(r[0] for r in res.rows)
    assert "TableScan" in text
    assert " in " in text and " out " in text and " rows" in text
    assert "Trace:" in text
    assert "execute" in text


def test_jit_cache_counters_and_compile_attribution(runner):
    from trino_tpu.exec.executor import Executor, _M_JIT
    plan = runner.plan_sql(
        "SELECT l_orderkey + 7 AS k FROM lineitem "
        "WHERE l_quantity > 30")
    before_hit = _M_JIT.value(cache="chain", result="hit")
    before_miss = _M_JIT.value(cache="chain", result="miss")
    sess = Session(catalog="tpch", schema="tiny")
    for _ in range(2):
        ex = Executor(runner.catalogs, sess, collect_stats=True,
                      fragment_jit=True)
        ex.execute(plan)
    # first executor misses (trace+compile), second hits the
    # cross-query structural cache
    assert _M_JIT.value(cache="chain", result="miss") == before_miss + 1
    assert _M_JIT.value(cache="chain", result="hit") == before_hit + 1
    assert any(s.cache_hit is True for s in ex.stats)


def test_peak_memory_reported(runner):
    res = runner.execute("SELECT count(*) FROM orders")
    assert res.peak_memory_bytes > 0
    assert res.spill_bytes == 0


# ---------------------------------------------------------------------------
# coordinator: /metrics exposition + query detail
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def coordinator():
    from trino_tpu.server import Coordinator
    co = Coordinator().start()
    yield co
    co.stop()


def _scrape(co):
    with urllib.request.urlopen(f"{co.base_uri}/metrics") as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        return parse_exposition(r.read().decode())


def _run(co, sql):
    from trino_tpu.client import StatementClient
    return StatementClient(co.base_uri, catalog="tpch",
                           schema="tiny").execute(sql)


def test_metrics_endpoint_counters_monotonic(coordinator):
    _run(coordinator, "SELECT 1")
    m1 = _scrape(coordinator)
    finished1 = m1["trino_tpu_query_states_total"][("state=FINISHED",)]
    assert finished1 >= 1
    assert m1["trino_tpu_query_states_total"][("state=QUEUED",)] >= \
        finished1
    _run(coordinator, "SELECT count(*) FROM orders")
    m2 = _scrape(coordinator)
    finished2 = m2["trino_tpu_query_states_total"][("state=FINISHED",)]
    assert finished2 == finished1 + 1
    # gauges from the render-time collector
    assert m2["trino_tpu_queries"][("state=FINISHED",)] >= 2
    assert ("trino_tpu_queue_depth" in m2)
    # the runner-level wall histogram grew with the queries
    assert m2["trino_tpu_query_wall_seconds_count"][()] > \
        m1["trino_tpu_query_wall_seconds_count"][()] - 1


def test_metrics_endpoint_includes_jit_and_scan_counters(coordinator):
    # drive the structural jit cache (fragment_jit is off on CPU by
    # default, so tick it explicitly through a jitted chain)
    from trino_tpu.exec.executor import Executor
    r = LocalQueryRunner(session=Session(catalog="tpch",
                                         schema="tiny"))
    plan = r.plan_sql("SELECT l_orderkey * 2 AS k FROM lineitem "
                      "WHERE l_quantity > 40")
    Executor(r.catalogs, r.session, fragment_jit=True).execute(plan)
    _run(coordinator, "SELECT count(*) FROM lineitem")
    m = _scrape(coordinator)
    jit = m["trino_tpu_jit_cache_total"]
    assert sum(jit.values()) >= 1
    assert any("result=hit" in k or "result=miss" in k
               for key in jit for k in key)
    scan = m["trino_tpu_scan_cache_total"]
    assert sum(scan.values()) >= 1


def test_query_detail_serves_cached_plan_and_spans(coordinator):
    res = _run(coordinator,
               "SELECT o_orderpriority, count(*) FROM orders "
               "GROUP BY o_orderpriority")
    q = coordinator.tracker.get(res.query_id)
    # the plan was captured at execution time, not re-derived per GET
    assert q.result.plan_lines
    with urllib.request.urlopen(
            f"{coordinator.base_uri}/v1/query/{res.query_id}") as r:
        d = json.loads(r.read())
    assert d["plan"] == q.result.plan_lines
    assert "planError" not in d
    spans = d.get("spans") or []
    assert [s["name"] for s in spans] == \
        ["parse", "plan", "optimize", "execute"]
    stats = d.get("nodeStats") or []
    assert stats and all("inputRows" in s and "compileMillis" in s
                         for s in stats)
    assert d["peakMemoryBytes"] > 0


def test_enriched_query_completed_event(coordinator):
    from trino_tpu.server.events import EventListener
    done = []

    class L(EventListener):
        def query_completed(self, event):
            done.append(event)

    coordinator.tracker.events.add_listener(L())
    _run(coordinator, "SELECT count(*) FROM orders")
    ev = done[-1]
    assert ev.state == "FINISHED"
    assert ev.peak_memory_bytes > 0
    assert ev.cumulative_operator_stats is not None
    assert ev.cumulative_operator_stats["output_rows"] >= 1
    assert ev.operator_summaries and \
        ev.operator_summaries[0].get("name")


def test_split_completed_event_fires_with_wall_time():
    from trino_tpu.server.events import (EventListener,
                                         EventListenerManager)
    got = []

    class L(EventListener):
        def split_completed(self, event):
            got.append(event)

    mgr = EventListenerManager()
    mgr.add_listener(L())
    sess = Session(catalog="tpch", schema="tiny", events=mgr)
    r = LocalQueryRunner(session=sess)
    r.execute("SELECT count(*) FROM orders")
    assert got, "no SplitCompletedEvent emitted"
    ev = got[0]
    assert ev.query_id.startswith("query_")
    assert "tpch.tiny.orders" in ev.split_id
    assert ev.wall_s >= 0


# ---------------------------------------------------------------------------
# distributed: rollup + per-fragment explain analyze + worker /metrics
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def worker_uris():
    from trino_tpu.server.task_worker import TaskWorkerServer
    workers = [TaskWorkerServer().start() for _ in range(2)]
    yield [w.base_uri for w in workers]
    for w in workers:
        w.stop()


def _flat_session() -> Session:
    """Flat-path pin for the leaf-fragment observability trio below:
    these assert the scatter-gather path's `fragment N xM workers`
    stats tags and `fragment_N_execute` spans — the explicit fallback
    since PR 13 (multistage default-on). The stage-DAG flavor of the
    same guarantees (per-STAGE tags, stage_N_execute spans, the stage
    section in EXPLAIN ANALYZE) is covered in test_stage_mpp.py."""
    return Session(catalog="tpch", schema="tiny",
                   properties={"multistage_execution": False})


def test_distributed_stats_rollup_sums_to_totals(worker_uris):
    from trino_tpu.exec.remote import DistributedHostQueryRunner
    d = DistributedHostQueryRunner(
        worker_uris, session=_flat_session(),
        collect_node_stats=True)
    res = d.execute("SELECT count(*) AS n FROM lineitem")
    total = res.rows[0][0]
    frag = [s for s in res.stats if "fragment" in s.detail]
    assert frag, "no fragment-stage stats in the rollup"
    # worker-reported input rows across the stage == the table rows the
    # coordinator counted
    agg_in = [s.input_rows for s in frag if s.name == "Aggregation"]
    assert agg_in and agg_in[0] == total
    # the coordinator combine consumed exactly the worker partials
    combine = [s for s in res.stats
               if s.name == "Aggregation" and "fragment" not in s.detail]
    frag_out = [s.output_rows for s in frag
                if s.name == "Aggregation"][0]
    assert combine and combine[0].input_rows == frag_out


def test_distributed_span_tree_has_fragment_children(worker_uris):
    from trino_tpu.exec.remote import DistributedHostQueryRunner
    d = DistributedHostQueryRunner(
        worker_uris, session=_flat_session(),
        collect_node_stats=True)
    res = d.execute("SELECT sum(l_quantity) FROM lineitem")
    roots = [s.name for s in res.trace.roots]
    assert roots == ["plan", "optimize", "execute"]
    execute = res.trace.roots[-1]
    kids = [c.name for c in execute.children]
    assert "schedule" in kids
    frags = [c for c in execute.children
             if c.name.startswith("fragment_")]
    assert len(frags) == 2          # one per worker
    # the worker's own task_execute subtree was grafted under it
    assert any(g.name == "task_execute"
               for f in frags for g in f.children)


def test_distributed_explain_analyze_per_fragment(worker_uris):
    from trino_tpu.exec.remote import DistributedHostQueryRunner
    d = DistributedHostQueryRunner(
        worker_uris, session=_flat_session(),
        collect_node_stats=True)
    res = d.execute(
        "EXPLAIN ANALYZE SELECT l_returnflag, count(*) FROM lineitem "
        "GROUP BY l_returnflag")
    text = "\n".join(r[0] for r in res.rows)
    assert "fragment 0 x2 workers" in text
    assert " in " in text and " rows" in text
    assert "Trace:" in text and "fragment_0_execute" in text


def test_worker_metrics_endpoint_and_task_stats(worker_uris):
    from trino_tpu.server.task_worker import RemoteTaskClient
    from trino_tpu.plan.serde import to_jsonable
    r = LocalQueryRunner(session=Session(catalog="tpch",
                                         schema="tiny"))
    plan = r.plan_sql("SELECT o_orderkey FROM orders "
                      "WHERE o_orderkey < 100")
    client = RemoteTaskClient(worker_uris[0])
    client.submit_fragment("obs-task-1", to_jsonable(plan),
                           catalog="tpch", schema="tiny", part=0,
                           nparts=1, collect_stats=True)
    pages = client.pages("obs-task-1")
    assert pages
    status = client.status("obs-task-1")
    assert status["state"] == "FINISHED"
    stats = status["nodeStats"]
    assert stats and any(s["name"] == "TableScan" for s in stats)
    assert status["spans"] and \
        status["spans"][0]["name"] == "task_execute"
    with urllib.request.urlopen(f"{worker_uris[0]}/metrics") as resp:
        m = parse_exposition(resp.read().decode())
    tasks = m["trino_tpu_worker_tasks_total"]
    assert tasks.get(("state=FINISHED",), 0) >= 1
    assert sum(m["trino_tpu_exchange_pages_total"].values()) >= 1


# ---------------------------------------------------------------------------
# overhead budget (bench.py telemetry_overhead tripwire)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_telemetry_overhead_under_5_percent():
    """Stats collection must stay cheap enough to leave always-on at
    the coordinator (the reference keeps OperatorStats always-on);
    bench.py emits the same measurement as telemetry_overhead.
    Iterations INTERLEAVE the two modes so machine-load drift hits
    both sides equally; best-of-N per side."""
    import time as _time
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    sql = TPCH_QUERIES[1]
    runners = {
        collect: LocalQueryRunner(
            session=Session(catalog="tpch", schema="sf1"),
            collect_node_stats=collect)
        for collect in (False, True)}
    for r in runners.values():
        r.execute(sql)                    # warm: generate + compile
    best = {False: float("inf"), True: float("inf")}
    for _ in range(5):
        for collect, r in runners.items():
            t0 = _time.perf_counter()
            r.execute(sql)
            best[collect] = min(best[collect],
                                _time.perf_counter() - t0)
    overhead = best[True] / best[False] - 1.0
    assert overhead < 0.05, \
        f"telemetry overhead {overhead:.1%} exceeds 5%"


@pytest.mark.slow
def test_telemetry_overhead_under_5_percent_distributed_mpp(tmp_path):
    """The PR 15 re-run of the overhead bound on the DEFAULT
    (multistage MPP) distributed path with the FULL telemetry stack
    on: distributed tracing (traceparent propagation + id-preserving
    span merge), device/CPU attribution, and OTLP file export —
    mirrors bench.py's rebuilt telemetry leg. Interleaved best-of-N
    as above."""
    import time as _time
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    from trino_tpu.config import CONFIG
    from trino_tpu.exec.remote import DistributedHostQueryRunner
    from trino_tpu.server.task_worker import TaskWorkerServer
    sql = TPCH_QUERIES[1]
    workers = [TaskWorkerServer().start() for _ in range(2)]
    uris = [w.base_uri for w in workers]
    sink = str(tmp_path / "otlp.jsonl")
    old_file = CONFIG.otlp_file
    try:
        runners = {
            collect: DistributedHostQueryRunner(
                uris, session=Session(catalog="tpch", schema="sf1"),
                collect_node_stats=collect)
            for collect in (False, True)}
        for r in runners.values():
            r.execute(sql)                # warm: generate + compile
        best = {False: float("inf"), True: float("inf")}
        for _ in range(5):
            for collect, r in runners.items():
                CONFIG.otlp_file = sink if collect else ""
                t0 = _time.perf_counter()
                r.execute(sql)
                best[collect] = min(best[collect],
                                    _time.perf_counter() - t0)
        overhead = best[True] / best[False] - 1.0
        assert overhead < 0.05, \
            f"MPP telemetry overhead {overhead:.1%} exceeds 5%"
        # export really ran on the telemetry-on side
        assert sum(1 for _ in open(sink)) >= 5
    finally:
        CONFIG.otlp_file = old_file
        for w in workers:
            w.stop()
