"""Verifier, proxy, and server bootstrap/config services.

Reference parity: service/trino-verifier (PrestoVerifier),
service/trino-proxy (ProxyResource), core/trino-server-main bootstrap
+ airlift etc/config.properties + etc/catalog/*.properties loading.
"""

import json
import urllib.error
import urllib.request

import pytest

from trino_tpu.client import StatementClient
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.server.coordinator import Coordinator
from trino_tpu.server.main import build_catalogs, load_properties
from trino_tpu.server.proxy import Proxy
from trino_tpu.verifier import Verifier, report, rows_match


# --- verifier -------------------------------------------------------------

def test_rows_match_tolerance_and_order():
    assert rows_match([[1, 2.0]], [[1, 2.0 + 1e-12]]) is None
    assert rows_match([[1], [2]], [[2], [1]]) is None          # unordered
    assert rows_match([[1], [2]], [[2], [1]], ordered=True)
    assert "row count" in rows_match([[1]], [[1], [2]])
    assert rows_match([[None]], [[None]]) is None
    assert rows_match([[None]], [[1]])


@pytest.mark.slow
def test_verifier_local_vs_distributed():
    control = LocalQueryRunner()
    test = LocalQueryRunner(distributed=True)
    v = Verifier(control, test, rel_tol=1e-9)
    results = v.run_suite([
        "SELECT count(*) FROM tpch.tiny.nation",
        "SELECT n_regionkey, count(*) FROM tpch.tiny.nation "
        "GROUP BY n_regionkey ORDER BY n_regionkey",
        "SELECT sum(l_extendedprice * l_discount) FROM "
        "tpch.tiny.lineitem WHERE l_quantity < 10",
    ])
    assert all(r.status == "MATCH" for r in results), \
        report(results)


def test_verifier_detects_mismatch():
    class Fake:
        def __init__(self, rows):
            self._rows = rows

        def execute(self, sql):
            class R:
                rows = self._rows
            return R()
    v = Verifier(Fake([[1]]), Fake([[2]]))
    r = v.verify("SELECT 1")
    assert r.status == "MISMATCH" and "1" in r.detail


def test_verifier_error_classification():
    good = LocalQueryRunner()

    class Broken:
        def execute(self, sql):
            raise RuntimeError("down")
    assert Verifier(good, Broken()).verify(
        "SELECT 1").status == "TEST_ERROR"
    assert Verifier(Broken(), good).verify(
        "SELECT 1").status == "CONTROL_ERROR"


# --- proxy ----------------------------------------------------------------

def test_proxy_forwards_and_rewrites():
    co = Coordinator().start()
    px = Proxy(co.base_uri).start()
    try:
        client = StatementClient(px.base_uri)
        res = client.execute("SELECT count(*) FROM tpch.tiny.region")
        assert res.rows == [[5]]
        # nextUri rewriting: poll through the proxy only
        req = urllib.request.Request(
            px.base_uri + "/v1/statement", data=b"SELECT 1",
            method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        next_uri = out.get("nextUri", "")
        assert co.base_uri not in next_uri
    finally:
        px.stop()
        co.stop()


def test_proxy_shared_secret():
    co = Coordinator().start()
    px = Proxy(co.base_uri, shared_secret="s3cret").start()
    try:
        req = urllib.request.Request(px.base_uri + "/v1/info")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 403
        req = urllib.request.Request(
            px.base_uri + "/v1/info",
            headers={"X-Proxy-Secret": "s3cret"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
    finally:
        px.stop()
        co.stop()


# --- config / bootstrap ---------------------------------------------------

def test_load_properties(tmp_path):
    p = tmp_path / "config.properties"
    p.write_text("# comment\nhttp-server.http.port=8099\n"
                 "coordinator=true\n")
    props = load_properties(str(p))
    assert props == {"http-server.http.port": "8099",
                     "coordinator": "true"}


def test_build_catalogs_from_etc(tmp_path):
    cat = tmp_path / "catalog"
    cat.mkdir()
    (cat / "analytics.properties").write_text("connector.name=tpch\n")
    (cat / "scratch.properties").write_text("connector.name=memory\n")
    mgr = build_catalogs(str(tmp_path))
    assert set(mgr.list_catalogs()) == {"analytics", "scratch"}
    runner = LocalQueryRunner(catalogs=mgr)
    assert runner.execute("SELECT count(*) FROM "
                          "analytics.tiny.region").rows == [[5]]


# -- GRANT / REVOKE / DENY / SHOW GRANTS (round 4) --------------------------

def test_grant_revoke_show_grants():
    from trino_tpu.runner import LocalQueryRunner
    r = LocalQueryRunner()
    r.execute("CREATE TABLE memory.default.gr_t AS SELECT 1 AS x")
    r.execute("GRANT SELECT, INSERT ON memory.default.gr_t TO alice")
    rows = r.execute("SHOW GRANTS ON memory.default.gr_t").rows
    assert sorted(x[7] for x in rows) == ["INSERT", "SELECT"]
    assert all(x[2] == "alice" for x in rows)
    r.execute("REVOKE INSERT ON memory.default.gr_t FROM alice")
    rows = r.execute("SHOW GRANTS ON memory.default.gr_t").rows
    assert [x[7] for x in rows] == ["SELECT"]
    r.execute("GRANT ALL PRIVILEGES ON TABLE memory.default.gr_t "
              "TO USER bob WITH GRANT OPTION")
    rows = r.execute("SHOW GRANTS").rows
    bob = [x for x in rows if x[2] == "bob"]
    assert len(bob) == 4 and all(x[8] is True for x in bob)


def test_grant_enforcement():
    import pytest
    from trino_tpu.catalog import CatalogManager
    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.runner import LocalQueryRunner, QueryError
    from trino_tpu.security import GrantBasedAccessControl
    from trino_tpu.session import Session

    cats = CatalogManager()
    cats.register("memory", MemoryConnector())
    admin = LocalQueryRunner(
        session=Session(catalog="memory", schema="default", user="admin"),
        catalogs=cats)
    admin.execute("CREATE TABLE memory.default.sec_t AS SELECT 1 AS x")
    cats.access_control = GrantBasedAccessControl(cats)
    alice = LocalQueryRunner(
        session=Session(catalog="memory", schema="default", user="alice"),
        catalogs=cats)
    with pytest.raises((QueryError, Exception), match="Access Denied"):
        alice.execute("SELECT * FROM memory.default.sec_t")
    admin.execute("GRANT SELECT ON memory.default.sec_t TO alice")
    assert alice.execute(
        "SELECT * FROM memory.default.sec_t").rows == [[1]]
    admin.execute("DENY SELECT ON memory.default.sec_t TO alice")
    with pytest.raises((QueryError, Exception), match="Access Denied"):
        alice.execute("SELECT * FROM memory.default.sec_t")


def test_jwt_bearer_authentication():
    """JWT HS256 end to end: valid token runs a query as the token's
    principal; expired/forged tokens get 401; impersonation mismatch
    gets 403 (server/security/jwt/JwtAuthenticator.java analog)."""
    import json as _json
    import time as _time
    import urllib.error
    import urllib.request
    from trino_tpu.security import JwtAuthenticator
    from trino_tpu.server.coordinator import Coordinator

    auth = JwtAuthenticator(b"secret-key", required_issuer="tt")
    coord = Coordinator(authenticator=auth).start()

    def post(token, extra=None):
        req = urllib.request.Request(
            coord.base_uri + "/v1/statement",
            data=b"SELECT 1",
            headers={"Authorization": f"Bearer {token}",
                     **(extra or {})}, method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, _json.loads(r.read())

    try:
        good = auth.sign({"sub": "alice", "iss": "tt",
                          "exp": _time.time() + 60})
        status, payload = post(good)
        assert status == 200 and "error" not in payload

        expired = auth.sign({"sub": "alice", "iss": "tt",
                             "exp": _time.time() - 5})
        try:
            post(expired)
            assert False, "expired token accepted"
        except urllib.error.HTTPError as e:
            assert e.code == 401

        forged = good[:-4] + "AAAA"
        try:
            post(forged)
            assert False, "forged token accepted"
        except urllib.error.HTTPError as e:
            assert e.code == 401

        wrong_iss = auth.sign({"sub": "alice", "iss": "other",
                               "exp": _time.time() + 60})
        try:
            post(wrong_iss)
            assert False, "wrong issuer accepted"
        except urllib.error.HTTPError as e:
            assert e.code == 401

        try:
            post(good, {"X-Trino-User": "mallory"})
            assert False, "impersonation allowed"
        except urllib.error.HTTPError as e:
            assert e.code == 403
    finally:
        coord.stop()


def test_jwt_missing_exp_rejected_by_default():
    """A token with no exp claim can never age out, so the default is
    to reject it; require_exp=False opts back into the legacy
    accept-forever behavior (for internal mint-on-boot tokens)."""
    import time as _time
    from trino_tpu.security import JwtAuthenticator

    strict = JwtAuthenticator(b"secret-key")
    eternal = strict.sign({"sub": "alice"})
    assert strict.authenticate_token(eternal) is None
    # a bounded token still authenticates under the strict default
    bounded = strict.sign({"sub": "alice", "exp": _time.time() + 60})
    assert strict.authenticate_token(bounded) == "alice"

    lax = JwtAuthenticator(b"secret-key", require_exp=False)
    assert lax.authenticate_token(eternal) == "alice"
    # opting out of require_exp must not weaken expiry enforcement
    expired = lax.sign({"sub": "alice", "exp": _time.time() - 5})
    assert lax.authenticate_token(expired) is None
