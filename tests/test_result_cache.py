"""Coordinator result cache (exec/resultcache.py): repeated identical
point queries short-circuit BEFORE dispatch — zero new worker tasks,
asserted via /metrics — a connector data-version bump forces a miss,
and the memory-pressure ladder sheds cached results ahead of compiled
programs.
"""

import urllib.request

from trino_tpu.client import StatementClient
from trino_tpu.exec.resultcache import (RESULT_CACHE, ResultCache,
                                        RESULT_CACHE_EVICTIONS,
                                        RESULT_CACHE_LOOKUPS)
from trino_tpu.server.coordinator import Coordinator
from trino_tpu.server.task_worker import TaskWorkerServer

PROPS = {"result_cache_enabled": "true"}


def _scrape(base_uri: str, name: str, **labels) -> float:
    """Sum a counter family out of a live /metrics exposition."""
    with urllib.request.urlopen(f"{base_uri}/metrics") as r:
        text = r.read().decode()
    want = [f'{k}="{v}"' for k, v in labels.items()]
    total = 0.0
    for line in text.splitlines():
        if line.startswith(name) and all(w in line for w in want):
            total += float(line.rsplit(None, 1)[-1])
    return total


def test_repeat_query_hits_with_zero_dispatched_tasks():
    """The ISSUE 18 acceptance shape: the second identical dashboard
    query is served from the coordinator cache — the worker's
    dispatched-task counter does not move."""
    worker = TaskWorkerServer().start()
    co = Coordinator(worker_uris=[worker.base_uri]).start()
    try:
        c = StatementClient(co.base_uri, session_properties=PROPS)
        sql = "SELECT n_name FROM tpch.tiny.nation WHERE n_nationkey = 7"
        first = c.execute(sql).rows
        tasks_before = _scrape(worker.base_uri,
                               "trino_tpu_worker_tasks_total")
        hits_before = _scrape(co.base_uri,
                              "trino_tpu_result_cache_lookups_total",
                              result="hit")
        second = c.execute(sql).rows
        assert second == first == [["GERMANY"]]
        assert _scrape(worker.base_uri,
                       "trino_tpu_worker_tasks_total") == tasks_before
        assert _scrape(co.base_uri,
                       "trino_tpu_result_cache_lookups_total",
                       result="hit") == hits_before + 1
    finally:
        co.stop()
        worker.stop()


def test_connector_version_bump_invalidates():
    """An INSERT bumps the memory connector's data version: the cached
    entry is dropped on the next lookup (reason=invalidated) and the
    query re-executes against fresh data."""
    co = Coordinator().start()
    try:
        c = StatementClient(co.base_uri, session_properties=PROPS)
        c.execute("CREATE TABLE memory.default.rc_inv (x bigint)")
        c.execute("INSERT INTO memory.default.rc_inv VALUES (1), (2)")
        sql = "SELECT x FROM memory.default.rc_inv WHERE x = 1"
        assert c.execute(sql).rows == [[1]]     # miss + store
        h0 = RESULT_CACHE_LOOKUPS.value(result="hit")
        assert c.execute(sql).rows == [[1]]     # hit
        assert RESULT_CACHE_LOOKUPS.value(result="hit") == h0 + 1
        i0 = RESULT_CACHE_EVICTIONS.value(reason="invalidated")
        c.execute("INSERT INTO memory.default.rc_inv VALUES (1)")
        assert c.execute(sql).rows == [[1], [1]]    # fresh, not stale
        assert RESULT_CACHE_EVICTIONS.value(
            reason="invalidated") == i0 + 1
    finally:
        co.stop()


def test_cache_off_by_default_no_lookups():
    co = Coordinator().start()
    try:
        c = StatementClient(co.base_uri)    # no session property
        sql = "SELECT r_name FROM tpch.tiny.region WHERE r_regionkey = 1"
        s0 = sum(v for _, v in RESULT_CACHE_LOOKUPS.samples())
        assert c.execute(sql).rows == c.execute(sql).rows
        assert sum(v for _, v in RESULT_CACHE_LOOKUPS.samples()) == s0
    finally:
        co.stop()


def test_pressure_ladder_sheds_result_cache_before_jit(monkeypatch):
    """evict_cache_pressure drops cached result rows (cheap to
    rebuild: saved latency) BEFORE halving the structural jit caches
    (expensive to rebuild: saved compile storms), and counts the shed
    under {cache="result"}."""
    from trino_tpu.exec import executor as ex
    from trino_tpu.obs.metrics import CACHE_PRESSURE_EVICTS

    # drain the scan/replicate tiers other tests populated — they
    # rank ahead of the result cache and would absorb a tiny deficit
    ex.evict_cache_pressure(1 << 40)
    RESULT_CACHE.put(("test-pressure",), ["x"], ["bigint"],
                     [[i] for i in range(64)], (("memory", 1),))
    assert len(RESULT_CACHE) >= 1
    nbytes = RESULT_CACHE.bytes()
    assert ex.cache_memory_bytes() >= nbytes    # governance sees it
    monkeypatch.setitem(ex._CHAIN_JIT_CACHE, ("sentinel-a",), object())
    monkeypatch.setitem(ex._CHAIN_JIT_CACHE, ("sentinel-b",), object())
    jit_before = len(ex._CHAIN_JIT_CACHE)
    r0 = CACHE_PRESSURE_EVICTS.value(cache="result")
    entries_before = len(RESULT_CACHE)
    freed = ex.evict_cache_pressure(1)      # tiny deficit: result-cache
    assert freed >= 1                       # rung alone must cover it
    assert len(RESULT_CACHE) < entries_before
    assert CACHE_PRESSURE_EVICTS.value(cache="result") > r0
    assert len(ex._CHAIN_JIT_CACHE) == jit_before   # jit tier untouched


def test_lru_and_capacity_bounds():
    rc = ResultCache(capacity_bytes=4096)
    v = (("memory", 1),)
    # an entry over capacity//4 is refused outright
    assert not rc.put(("big",), ["x"], ["varchar"],
                      [["y" * 8192]], v)
    for i in range(64):
        rc.put((f"k{i}",), ["x"], ["bigint"], [[i] * 8], v)
    assert rc.bytes() <= 4096
    assert rc.get(("k0",), v) is None       # LRU-evicted
    newest = rc.get(("k63",), v)
    assert newest is not None and newest[2] == [[63] * 8]
