"""Join hash-collision re-verification (VERDICT weak #9).

The equality lane of ops/join.py is exact only for a single integer-like
key; multi-column and float keys are hash-combined. The executor appends
real key-equality conjuncts for those (executor.join_verify_filter —
reference: JoinProbe verifies positions by actual equality, never by
hash). These tests inject collisions by weakening the hash combiner to
2 bits and assert results stay correct.
"""

import jax.numpy as jnp
import pytest

from trino_tpu.runner import LocalQueryRunner


@pytest.fixture()
def weak_hash(monkeypatch):
    """Collapse combined hashes to 4 distinct values — multi-key joins
    then see constant collisions unless re-verification kicks in."""
    from trino_tpu.ops import hashing, join as join_ops

    def weak(hashes):
        acc = jnp.zeros_like(hashes[0])
        for h in hashes:
            acc = acc + h
        return acc % jnp.uint64(4)

    # join key lanes use ops.join.combine_hashes (captured at import)
    monkeypatch.setattr(join_ops, "combine_hashes", weak)
    return weak


def _runner():
    return LocalQueryRunner()


def test_multikey_inner_join_collisions(weak_hash):
    r = _runner()
    res = r.execute(
        "SELECT a.x, a.y, b.v FROM "
        "(VALUES (1, 10, 'l1'), (2, 20, 'l2'), (3, 30, 'l3')) a(x, y, s) "
        "JOIN (VALUES (1, 10, 'r1'), (2, 99, 'r2'), (3, 30, 'r3')) "
        "b(x2, y2, v) ON a.x = b.x2 AND a.y = b.y2 ORDER BY a.x")
    assert res.rows == [[1, 10, "r1"], [3, 30, "r3"]]


def test_multikey_left_join_collisions(weak_hash):
    r = _runner()
    res = r.execute(
        "SELECT a.x, b.v FROM "
        "(VALUES (1, 10), (2, 20)) a(x, y) "
        "LEFT JOIN (VALUES (1, 10, 'r1'), (2, 99, 'r2')) b(x2, y2, v) "
        "ON a.x = b.x2 AND a.y = b.y2 ORDER BY a.x")
    assert res.rows == [[1, "r1"], [2, None]]


def test_multikey_full_join_collisions(weak_hash):
    r = _runner()
    res = r.execute(
        "SELECT a.x, b.x2 FROM "
        "(VALUES (1, 10), (2, 20)) a(x, y) "
        "FULL JOIN (VALUES (1, 10), (2, 99)) b(x2, y2) "
        "ON a.x = b.x2 AND a.y = b.y2 ORDER BY a.x, b.x2")
    key = lambda row: tuple((v is None, v or 0) for v in row)
    assert sorted(res.rows, key=key) == [[1, 1], [2, None], [None, 2]]


def test_multikey_semi_join_collisions(weak_hash):
    r = _runner()
    res = r.execute(
        "SELECT x FROM (VALUES (1, 10), (2, 20), (3, 30)) t(x, y) "
        "WHERE EXISTS (SELECT 1 FROM (VALUES (1, 10), (3, 99)) u(a, b) "
        "WHERE u.a = t.x AND u.b = t.y) ORDER BY x")
    assert res.rows == [[1]]


def test_float_single_key_join(weak_hash):
    r = _runner()
    res = r.execute(
        "SELECT a.x, b.v FROM (VALUES (1.5), (2.5)) a(x) "
        "JOIN (VALUES (CAST(1.5 AS double), 'm'), "
        "(CAST(9.5 AS double), 'n')) b(x2, v) "
        "ON a.x = CAST(b.x2 AS decimal(2,1)) ORDER BY a.x")
    assert len(res.rows) == 1 and res.rows[0][1] == "m"


@pytest.mark.slow
def test_distributed_partitioned_multikey(weak_hash):
    dist = LocalQueryRunner(distributed=True, n_devices=8)
    dist.execute("SET SESSION join_distribution_type = 'PARTITIONED'")
    loc = _runner()
    q = ("SELECT count(*) FROM lineitem l JOIN lineitem r "
         "ON l.l_orderkey = r.l_orderkey "
         "AND l.l_linenumber = r.l_linenumber "
         "WHERE l.l_quantity > 49")
    assert dist.execute(q).rows == loc.execute(q).rows
