"""Device-side TPC-H generation must be bit-identical to the host leg.

Reference parity: plugin/trino-tpch/.../TpchRecordSet.java:43-51 (the
split-addressable generator contract: any split, any scale, same rows).
"""

import numpy as np
import pytest

from trino_tpu.catalog import Split, TableHandle
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session


def _rows(batch, cols):
    n = batch.num_rows_host()
    out = []
    for c in cols:
        col = batch.column(c)
        data = np.asarray(col.data)[:n]
        if col.dictionary is not None:
            data = col.dictionary.values[
                np.clip(data.astype(np.int64), 0,
                        len(col.dictionary.values) - 1)]
        out.append(data)
    return out


@pytest.mark.parametrize("table,cols", [
    ("lineitem", ["l_orderkey", "l_partkey", "l_suppkey",
                  "l_linenumber", "l_quantity", "l_extendedprice",
                  "l_discount", "l_tax", "l_shipdate", "l_commitdate",
                  "l_receiptdate", "l_returnflag", "l_linestatus",
                  "l_shipinstruct", "l_shipmode"]),
    ("orders", ["o_orderkey", "o_custkey", "o_orderstatus",
                "o_totalprice", "o_orderdate", "o_orderpriority",
                "o_shippriority"]),
])
@pytest.mark.parametrize("part", [0, 1])
def test_device_generation_matches_host(monkeypatch, table, cols, part):
    conn = TpchConnector(rows_per_split=1 << 14)
    h = TableHandle("tpch", "tiny", table)
    split = Split(h, part, 2)
    monkeypatch.setenv("TRINO_TPU_DEVICE_GEN", "0")
    host = conn.read_split(split, cols)
    monkeypatch.setenv("TRINO_TPU_DEVICE_GEN", "1")
    dev = conn.read_split(split, cols)
    assert dev.num_rows_host() == host.num_rows_host()
    for name, hv, dv in zip(cols, _rows(host, cols), _rows(dev, cols)):
        assert np.array_equal(hv, dv), name


def _run(sql, devgen, monkeypatch):
    monkeypatch.setenv("TRINO_TPU_DEVICE_GEN", devgen)
    r = LocalQueryRunner(session=Session(catalog="tpch", schema="tiny"))
    return r.execute(sql).rows


@pytest.mark.parametrize("sql", [
    # q6 shape: date + numeric range pushdown into the device filter
    "SELECT sum(l_extendedprice * l_discount) FROM lineitem "
    "WHERE l_shipdate >= DATE '1994-01-01' "
    "AND l_shipdate < DATE '1995-01-01' "
    "AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24",
    # dictionary-coded pushdown
    "SELECT count(*) FROM lineitem WHERE l_shipmode IN ('MAIL', 'SHIP')",
    # q18 core: correlated-IN via HAVING over the whole table
    "SELECT o_orderkey, o_totalprice FROM orders WHERE o_orderkey IN "
    "(SELECT l_orderkey FROM lineitem GROUP BY l_orderkey "
    " HAVING sum(l_quantity) > 200) ORDER BY o_totalprice DESC LIMIT 5",
])
def test_engine_results_identical_with_device_generation(monkeypatch,
                                                         sql):
    assert _run(sql, "1", monkeypatch) == _run(sql, "0", monkeypatch)
