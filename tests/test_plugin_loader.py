"""Plugin SPI + dynamic loader.

Reference parity: core/trino-spi/.../Plugin.java:35-90 +
server/PluginManager.java (plugin discovery and registration of
connector factories / functions).
"""

import sys
import textwrap

import pytest

from trino_tpu import plugin
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session


@pytest.fixture()
def plugin_module(tmp_path, monkeypatch):
    p = tmp_path / "my_test_plugin.py"
    p.write_text(textwrap.dedent("""
        from trino_tpu.catalog import (ColumnMetadata, Connector, Split,
                                       TableHandle, TableMetadata)
        from trino_tpu.columnar import Batch, Column
        from trino_tpu.types import BIGINT
        import numpy as np


        class TinyConnector(Connector):
            name = "tiny"

            def __init__(self, start):
                self.start = start

            def list_schemas(self):
                return ["default"]

            def list_tables(self, schema):
                return ["nums"]

            def get_table_metadata(self, schema, table):
                if (schema, table) != ("default", "nums"):
                    return None
                return TableMetadata(
                    "default", "nums",
                    [ColumnMetadata("n", BIGINT)])

            def read_split(self, split, columns):
                data = np.arange(self.start, self.start + 4,
                                 dtype=np.int64)
                return Batch({"n": Column(BIGINT, data)}, 4)


        def get_connector_factories():
            return [("tiny", lambda name, props: TinyConnector(
                int(props.get("tiny.start", "0"))))]
    """))
    monkeypatch.syspath_prepend(str(tmp_path))
    yield "my_test_plugin"
    sys.modules.pop("my_test_plugin", None)


def test_load_plugin_and_query(plugin_module):
    added = plugin.load_plugin(plugin_module)
    assert "tiny" in added
    conn = plugin.create_connector("tiny", "t1", {"tiny.start": "10"})
    from trino_tpu.catalog import CatalogManager
    cats = CatalogManager()
    cats.register("t1", conn)
    r = LocalQueryRunner(
        session=Session(catalog="t1", schema="default"), catalogs=cats)
    assert r.execute("SELECT sum(n) FROM nums").rows == [[10+11+12+13]]


def test_create_connector_module_ref(plugin_module):
    conn = plugin.create_connector(
        f"{plugin_module}:tiny", "t2", {})
    assert conn.read_split(None, ["n"]).num_rows == 4


def test_unknown_connector_errors():
    with pytest.raises(KeyError, match="unknown connector.name"):
        plugin.create_connector("no-such-thing", "x", {})


def test_builtin_factories_present():
    names = plugin.connector_factories()
    for k in ("tpch", "tpcds", "memory", "blackhole", "system",
              "localfile"):
        assert k in names
