"""Streaming ingestion + continuous queries (PR 20).

Covers the tentpole's three layers — the append-only partitioned
message log (streaming/log.py), the spool-backed consumer offset
store (streaming/offsets.py), and the continuous-query scheduler
(streaming/continuous.py) — plus the stream connector's SQL surface
(connectors/stream.py window refs, ``_partition``/``_offset`` ledger
columns) and the coordinator/worker HTTP routes.

The slow acceptance e2e streams messages through ``/v1/ingest`` while
a continuous job watches counts grow, kills a worker mid-ingest, and
proves zero-dup/zero-loss from the offset ledger; the chaos tests arm
the two ingest-path fault points (``stream.pre_append``,
``stream.pre_offset_commit``)."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from trino_tpu.config import CONFIG
from trino_tpu.connectors.stream import (StreamConnector,
                                         parse_table_ref, window_ref)
from trino_tpu.fte.faultpoints import FaultInjected, install, reset
from trino_tpu.fte.spool import make_spool
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session
from trino_tpu.streaming.continuous import ContinuousQueryManager
from trino_tpu.streaming.log import MessageLog, get_log, ingest_http
from trino_tpu.streaming.offsets import OFFSETS_FRAGMENT, OffsetStore


def _wait_until(pred, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture
def stream_dir(tmp_path, monkeypatch):
    d = str(tmp_path / "stream")
    monkeypatch.setattr(CONFIG, "stream_dir", d)
    return d


def _post(uri, body=b"", method="POST"):
    req = urllib.request.Request(uri, data=body or None,
                                 method=method)
    with urllib.request.urlopen(req, timeout=15) as resp:
        return json.load(resp)


# --- message log (streaming/log.py) ----------------------------------------

def test_log_append_read_roundtrip(stream_dir):
    log = MessageLog(stream_dir)
    log.create_topic("t", partitions=2)
    assert log.append("t", [b"a", b"b"], partition=0) == {0: (0, 2)}
    assert log.append("t", [b"c"], partition=0) == {0: (2, 3)}
    assert log.append("t", [b"z"], partition=1) == {1: (0, 1)}
    assert log.read("t", 0, 0, 3) == [b"a", b"b", b"c"]
    assert log.read("t", 0, 1, 2) == [b"b"]
    assert log.read("t", 0, 2, 99) == [b"c"]   # end clamps to live end
    assert log.read("t", 1, 0, 1) == [b"z"]
    assert log.end_offsets("t") == {0: 3, 1: 1}
    assert log.data_version() > 0


def test_log_key_and_round_robin_routing(stream_dir):
    log = MessageLog(stream_dir)
    log.create_topic("t", partitions=4)
    # same key -> same partition, deterministically
    (p1,) = log.append("t", [b"x"], key="user-1")
    (p2,) = log.append("t", [b"y"], key="user-1")
    assert p1 == p2
    # round-robin spreads keyless batches across partitions
    hit = set()
    for _ in range(8):
        (p,) = log.append("t", [b"m"])
        hit.add(p)
    assert len(hit) == 4
    with pytest.raises(ValueError, match="out of range"):
        log.append("t", [b"m"], partition=9)


def test_log_torn_tail_refused(stream_dir):
    """A producer killed mid-write leaves a partial frame; the offset
    index must stop at the last complete frame, never serve garbage."""
    log = MessageLog(stream_dir)
    log.create_topic("t", partitions=1)
    log.append("t", [b"complete-1", b"complete-2"], partition=0)
    seg = os.path.join(stream_dir, "t", "p0.log")
    with open(seg, "ab") as f:
        f.write(b"\x00\x00\x00\x63only-partial")   # claims 99 bytes
    fresh = MessageLog(stream_dir)
    assert fresh.end_offsets("t") == {0: 2}
    assert fresh.read("t", 0, 0, 10) == [b"complete-1", b"complete-2"]


def test_log_cross_instance_visibility(stream_dir):
    """Two MessageLog instances over one dir (the coordinator and a
    worker next door) observe each other's appends with no protocol —
    the filesystem is the replication."""
    a, b = MessageLog(stream_dir), MessageLog(stream_dir)
    a.create_topic("t", partitions=1)
    a.append("t", [b"from-a"], partition=0)
    assert b.read("t", 0, 0, 1) == [b"from-a"]
    b.append("t", [b"from-b"], partition=0)
    assert a.read("t", 0, 0, 2) == [b"from-a", b"from-b"]
    # the process singleton hands every caller the same index
    assert get_log(stream_dir) is get_log(stream_dir)


def test_log_topic_validation_and_idempotent_create(stream_dir):
    log = MessageLog(stream_dir)
    for bad in ("", "a/b", "a\\b", "a$b", ".hidden"):
        with pytest.raises(ValueError):
            log.create_topic(bad)
    cfg = log.create_topic("t", fields=[("k", "bigint", None)],
                          partitions=3)
    # the first creation seals the config; a racing re-create adopts it
    again = log.create_topic("t", fields=[("other", "double", None)],
                             partitions=9)
    assert again == cfg and again["partitions"] == 3
    assert log.topics() == ["t"]
    log.drop_topic("t")
    assert log.topics() == []


def test_ingest_http_helper_routes_and_counts(stream_dir):
    log = MessageLog(stream_dir)
    log.create_topic("t", partitions=2)
    out = ingest_http(log, "t", b"one\ntwo\n\nthree", {"partition": ["1"]})
    assert out["count"] == 3 and out["ranges"] == {"1": [0, 3]}
    assert out["endOffsets"] == {"0": 0, "1": 3}
    assert ingest_http(log, "t", b"", {})["count"] == 0


# --- offset store (streaming/offsets.py) -----------------------------------

def test_offsets_commit_load_and_cold_replay(tmp_path, stream_dir):
    spool = make_spool("local", local_base_dir=str(tmp_path / "spool"))
    store = OffsetStore(spool)
    assert store.load("job1") == (0, {})
    assert store.commit("job1", 1, {"t": {0: 5, 1: 2}})
    assert store.commit("job1", 2, {"t": {0: 9, 1: 2}})
    assert store.load("job1") == (2, {"t": {0: 9, 1: 2}})
    # a cold store on the same spool (coordinator failover) replays
    # the ledger by probing epochs upward
    cold = OffsetStore(spool)
    assert cold.load("job1") == (2, {"t": {0: 9, 1: 2}})
    # consumers are isolated
    assert cold.load("job2") == (0, {})
    store.release("job1")
    assert OffsetStore(spool).load("job1") == (0, {})


def test_offsets_first_commit_wins(tmp_path, stream_dir):
    """Two racers on one epoch: only one frame seals; the loser is
    told so and reads the winner's watermark back."""
    spool = make_spool("local", local_base_dir=str(tmp_path / "spool"))
    # a foreign process (distinct attempt id) already sealed epoch 1
    frame = json.dumps({"epoch": 1, "offsets": {"t": {0: 7}}}).encode()
    spool.commit("stream.job1", OFFSETS_FRAGMENT, 1,
                 os.getpid() + 1, [frame])
    store = OffsetStore(spool)
    assert store.commit("job1", 1, {"t": {0: 999}}) is False
    assert store.load("job1") == (1, {"t": {0: 7}})


def test_offsets_consumer_name_validation(tmp_path):
    store = OffsetStore(make_spool("local",
                                   local_base_dir=str(tmp_path)))
    with pytest.raises(ValueError):
        store.commit("", 1, {})
    with pytest.raises(ValueError):
        store.load("a/b")


# --- window refs -----------------------------------------------------------

def test_window_ref_roundtrip():
    w = {0: (10, 20), 1: (0, 15)}
    ref = window_ref("events", w, "job1")
    assert ref == "events$win.0:10:20,1:0:15#job1"
    assert parse_table_ref(ref) == ("events", w)
    assert parse_table_ref("events") == ("events", None)
    assert parse_table_ref(window_ref("e", {})) == ("e", {})


# --- stream connector via SQL (connectors/stream.py) -----------------------

@pytest.fixture
def runner(stream_dir):
    r = LocalQueryRunner(with_tpch=False)
    r.execute("CREATE TABLE stream.default.events "
              "(k BIGINT, v DOUBLE, ts DOUBLE)")
    return r


def test_stream_scan_window_and_ledger_columns(runner, stream_dir):
    log = get_log(stream_dir)
    for i in range(6):
        log.append("events",
                   [json.dumps({"k": i % 2, "v": float(i),
                                "ts": i / 10.0}).encode()],
                   partition=i % 2)
    assert runner.execute(
        "SELECT count(*) FROM stream.default.events").rows == [[6]]
    # exact offset window through the full SQL path (quoted ident)
    ref = window_ref("events", {0: (1, 3), 1: (0, 1)})
    rows = runner.execute(
        f'SELECT count(*) FROM stream.default."{ref}"').rows
    assert rows == [[3]]
    # the SQL-visible ingest ledger
    led = runner.execute(
        "SELECT _partition, count(*) c, max(_offset) mx "
        "FROM stream.default.events GROUP BY _partition "
        "ORDER BY _partition").rows
    assert led == [[0, 3, 2], [1, 3, 2]]
    # malformed producer payloads decode as NULL-lane rows, not errors
    log.append("events", [b"not json at all"], partition=0)
    rows = runner.execute(
        "SELECT count(*) FROM stream.default.events "
        "WHERE k IS NULL").rows
    assert rows == [[1]]


def test_stream_sql_insert_and_schemaless_topic(runner, stream_dir):
    runner.execute("INSERT INTO stream.default.events "
                   "VALUES (1, 1.5, 0.1), (2, 2.5, 0.2)")
    assert runner.execute(
        "SELECT sum(v) FROM stream.default.events").rows == [[4.0]]
    # an implicitly created (schemaless) topic exposes _message
    get_log(stream_dir).append("bare", [b"hello", b"world"])
    rows = runner.execute(
        "SELECT _message FROM stream.default.bare "
        "ORDER BY _offset, _partition").rows
    assert sorted(r[0] for r in rows) == ["hello", "world"]
    with pytest.raises(Exception, match="reserved"):
        runner.execute(
            "CREATE TABLE stream.default.bad (_offset BIGINT)")


# --- continuous query manager (streaming/continuous.py) --------------------

def _mk_manager(runner, tmp_path, jobs_path=None):
    """Manager over a LocalQueryRunner. The runner is NOT thread-safe
    (shared Session), so cycles and test asserts serialize on a lock —
    the coordinator path gives every cycle its own Session instead."""
    lock = threading.Lock()

    def run_sql(sql):
        with lock:
            return runner.execute(sql)

    spool = make_spool("local", local_base_dir=str(tmp_path / "spool"))
    mgr = ContinuousQueryManager(
        run_sql, runner.catalogs, OffsetStore(spool),
        jobs_path=jobs_path, log=get_log())
    return mgr, run_sql


def test_continuous_insert_exactly_once(runner, tmp_path, stream_dir):
    runner.execute("CREATE TABLE memory.default.sink "
                   "(p BIGINT, o BIGINT, v DOUBLE)")
    mgr, run_sql = _mk_manager(runner, tmp_path)
    log = get_log(stream_dir)
    try:
        job = mgr.create({
            "kind": "insert", "topic": "events",
            "poll_interval_ms": 100,
            "sql": "INSERT INTO memory.default.sink "
                   "SELECT _partition, _offset, v "
                   "FROM stream.default.events"})
        total = 0
        for burst in range(3):
            for i in range(10):
                log.append("events",
                           [json.dumps({"k": i, "v": float(i),
                                        "ts": i * 1.0}).encode()])
            total += 10
            want = total
            _wait_until(lambda: run_sql(
                "SELECT count(*) FROM memory.default.sink"
            ).rows[0][0] >= want, what=f"burst {burst} drained")
        # exactly once: every (partition, offset) pair exactly one row
        n, dn = run_sql(
            "SELECT count(*), count(DISTINCT p * 1000000 + o) "
            "FROM memory.default.sink").rows[0]
        assert n == 30 and dn == 30
        info = mgr.get(job["job_id"])
        assert info["rows_total"] == 30 and info["last_epoch"] >= 3
        assert info["state"] == "RUNNING"
        assert mgr.cancel(job["job_id"])
        _wait_until(lambda: not mgr._threads[job["job_id"]].is_alive(),
                    what="job thread exit")
        assert mgr.get(job["job_id"])["state"] == "CANCELED"
        assert mgr.cancel("cq_nope") is False
    finally:
        mgr.stop()


def test_continuous_view_refresh(runner, tmp_path, stream_dir):
    mgr, run_sql = _mk_manager(runner, tmp_path)
    log = get_log(stream_dir)
    try:
        mgr.create({
            "kind": "view", "target": "memory.default.mv",
            "poll_interval_ms": 100,
            "sql": "SELECT k, count(*) c FROM stream.default.events "
                   "GROUP BY k"})
        log.append("events", [json.dumps({"k": 1, "v": 0.0,
                                          "ts": 0.0}).encode()] * 4)
        _wait_until(lambda: run_sql(
            "SELECT count(*) FROM memory.default.mv").rows[0][0] > 0,
            what="mv materialized")
        assert run_sql("SELECT c FROM memory.default.mv "
                       "WHERE k = 1").rows == [[4]]
        # the next refresh REPLACES the target with the new rollup
        log.append("events", [json.dumps({"k": 2, "v": 0.0,
                                          "ts": 0.0}).encode()])
        _wait_until(lambda: run_sql(
            "SELECT count(*) FROM memory.default.mv").rows[0][0] == 2,
            what="mv re-rollup")
    finally:
        mgr.stop()


def test_continuous_window_watermark(runner, tmp_path, stream_dir):
    """Watermarked windowed aggregation: the incremental copy lands in
    staging exactly once, the watermark trails max(ts) by lateness,
    and the view SQL's {watermark} predicate gates finalization."""
    mgr, run_sql = _mk_manager(runner, tmp_path)
    log = get_log(stream_dir)
    try:
        job = mgr.create({
            "kind": "window", "topic": "events",
            "target": "memory.default.winmv", "ts_column": "ts",
            "lateness_ms": 1000, "poll_interval_ms": 100,
            "sql": "SELECT k, count(*) c, sum(v) s "
                   "FROM stream.default.events "
                   "WHERE ts <= {watermark} GROUP BY k"})
        for i in range(10):
            log.append("events",
                       [json.dumps({"k": i % 2, "v": float(i),
                                    "ts": float(i * 500)}).encode()])
        # max ts = 4500, lateness 1000 -> watermark 3500 (earlier
        # cycles may surface lower watermarks while the copy catches
        # up — wait for the final one)
        _wait_until(lambda: (mgr.get(job["job_id"]) or {}).get(
            "watermark") == 3500.0, what="watermark advance")
        # staging carries the exactly-once copy with ledger columns
        n, dn = run_sql(
            "SELECT count(*), count(DISTINCT _partition * 1000000 "
            "+ _offset) FROM memory.default.winmv__cq_staging"
        ).rows[0]
        assert n == 10 and dn == 10
        # the view only aggregates rows at or below the watermark
        # (ts <= 3500 -> i in 0..7 -> 4 per key)
        rows = run_sql("SELECT k, c FROM memory.default.winmv "
                       "ORDER BY k").rows
        assert rows == [[0, 4], [1, 4]]
    finally:
        mgr.stop()


def test_continuous_restart_jobs_ledger(runner, tmp_path, stream_dir):
    """Coordinator failover for jobs: stop() leaves RUNNING state in
    the JSONL ledger; a replacement manager replays it and the job's
    consumer resumes from its committed epoch — no re-ingest."""
    runner.execute("CREATE TABLE memory.default.sink "
                   "(p BIGINT, o BIGINT, v DOUBLE)")
    jobs = str(tmp_path / "continuous.jsonl")
    mgr, run_sql = _mk_manager(runner, tmp_path, jobs_path=jobs)
    log = get_log(stream_dir)
    spec = {"kind": "insert", "topic": "events",
            "poll_interval_ms": 100,
            "sql": "INSERT INTO memory.default.sink "
                   "SELECT _partition, _offset, v "
                   "FROM stream.default.events"}
    job = mgr.create(spec)
    log.append("events", [json.dumps({"k": 1, "v": 1.0,
                                      "ts": 0.0}).encode()] * 5)
    _wait_until(lambda: run_sql(
        "SELECT count(*) FROM memory.default.sink").rows[0][0] == 5,
        what="first manager drain")
    mgr.stop()                     # failover: NOT a cancel
    # rows ingested while no coordinator was alive
    log.append("events", [json.dumps({"k": 2, "v": 2.0,
                                      "ts": 0.0}).encode()] * 3)
    mgr2, run_sql2 = _mk_manager(runner, tmp_path, jobs_path=jobs)
    try:
        assert mgr2.restart_jobs() == 1
        assert mgr2.restart_jobs() == 0     # idempotent
        assert mgr2.get(job["job_id"])["state"] == "RUNNING"
        _wait_until(lambda: run_sql2(
            "SELECT count(*) FROM memory.default.sink"
        ).rows[0][0] == 8, what="resumed drain")
        n, dn = run_sql2(
            "SELECT count(*), count(DISTINCT p * 1000000 + o) "
            "FROM memory.default.sink").rows[0]
        assert n == 8 and dn == 8, "failover duplicated or lost rows"
    finally:
        mgr2.stop()
    # a CANCELED job must NOT restart
    mgr2.cancel(job["job_id"])
    mgr3, _ = _mk_manager(runner, tmp_path, jobs_path=jobs)
    assert mgr3.restart_jobs() == 0
    mgr3.stop()


def test_continuous_create_validation(runner, tmp_path):
    mgr, _ = _mk_manager(runner, tmp_path)
    try:
        for bad in (
                {"kind": "nope", "sql": "SELECT 1"},
                {"kind": "insert", "sql": ""},
                {"kind": "insert", "sql": "SELECT 1"},   # no topic
                {"kind": "view", "sql": "SELECT 1",
                 "target": "not_fqn"},
                {"kind": "window", "sql": "SELECT 1", "topic": "t",
                 "target": "a.b.c"},                     # no ts_column
        ):
            with pytest.raises(ValueError):
                mgr.create(bad)
    finally:
        mgr.stop()


# --- fault points (satellite b) --------------------------------------------

def test_fault_point_pre_append_no_partial_write(stream_dir):
    """A producer dying at stream.pre_append leaves the log untouched:
    the retry is a clean re-ingest, not a half-written frame."""
    log = MessageLog(stream_dir)
    log.create_topic("t", partitions=1)
    log.append("t", [b"before"], partition=0)
    reset()
    install("stream.pre_append", "raise")
    try:
        with pytest.raises(FaultInjected):
            log.append("t", [b"doomed-1", b"doomed-2"], partition=0)
        assert log.end_offsets("t") == {0: 1}
        # the producer's retry lands cleanly after the fault clears
        assert log.append("t", [b"retry"], partition=0) == {0: (1, 2)}
    finally:
        reset()


def test_fault_point_pre_offset_commit(tmp_path, stream_dir):
    """A consumer dying at stream.pre_offset_commit loses the epoch
    but not the ledger: load() still serves the last sealed epoch, so
    the next cycle re-covers exactly the uncommitted window."""
    spool = make_spool("local", local_base_dir=str(tmp_path / "spool"))
    store = OffsetStore(spool)
    assert store.commit("job1", 1, {"t": {0: 5}})
    reset()
    install("stream.pre_offset_commit", "raise")
    try:
        with pytest.raises(FaultInjected):
            store.commit("job1", 2, {"t": {0: 9}})
        assert store.load("job1") == (1, {"t": {0: 5}})
        assert store.commit("job1", 2, {"t": {0: 9}})
        assert store.load("job1") == (2, {"t": {0: 9}})
    finally:
        reset()


@pytest.mark.slow
def test_chaos_offset_commit_crash_mid_job(runner, tmp_path,
                                           stream_dir):
    """The documented at-least-once boundary, demonstrated: a cycle
    dies between INSERT success and its offset commit; the next cycle
    re-covers the window (duplicates land), and the _partition/_offset
    ledger is exactly what dedupes them downstream."""
    runner.execute("CREATE TABLE memory.default.sink "
                   "(p BIGINT, o BIGINT, v DOUBLE)")
    mgr, run_sql = _mk_manager(runner, tmp_path)
    log = get_log(stream_dir)
    reset()
    install("stream.pre_offset_commit", "raise")
    try:
        mgr.create({
            "kind": "insert", "topic": "events",
            "poll_interval_ms": 100,
            "sql": "INSERT INTO memory.default.sink "
                   "SELECT _partition, _offset, v "
                   "FROM stream.default.events"})
        log.append("events", [json.dumps({"k": 1, "v": 1.0,
                                          "ts": 0.0}).encode()] * 4)
        # the faulted cycle inserts, fails to commit, and the NEXT
        # cycle re-covers the same window -> 8 raw rows, 4 distinct
        _wait_until(lambda: run_sql(
            "SELECT count(*) FROM memory.default.sink"
        ).rows[0][0] >= 8, what="re-covered window")
        n, dn = run_sql(
            "SELECT count(*), count(DISTINCT p * 1000000 + o) "
            "FROM memory.default.sink").rows[0]
        assert n == 8 and dn == 4
        # after the duplicate, the job converges: nothing new appears
        assert run_sql(
            "SELECT count(*) FROM (SELECT DISTINCT p, o "
            "FROM memory.default.sink)").rows == [[4]]
    finally:
        reset()
        mgr.stop()


# --- HTTP + cluster e2e ----------------------------------------------------

def test_coordinator_ingest_and_continuous_http(stream_dir, tmp_path):
    """The single fast e2e in tier-1: HTTP ingest through the
    coordinator, a continuous job created/listed/fetched/canceled at
    /v1/continuous, its row in system.runtime.continuous_queries."""
    from trino_tpu.client import StatementClient
    from trino_tpu.server.coordinator import Coordinator
    co = Coordinator(history_dir=str(tmp_path / "hist")).start()
    try:
        c = StatementClient(co.base_uri)
        c.execute("CREATE TABLE stream.default.events "
                  "(k BIGINT, v DOUBLE, ts DOUBLE)")
        c.execute("CREATE TABLE memory.default.sink "
                  "(p BIGINT, o BIGINT, v DOUBLE)")
        body = b"\n".join(
            json.dumps({"k": i, "v": float(i), "ts": i / 10.0}).encode()
            for i in range(12))
        out = _post(co.base_uri + "/v1/ingest/events", body)
        assert out["count"] == 12
        assert sum(e for e in out["endOffsets"].values()) == 12
        assert c.execute("SELECT count(*) FROM stream.default.events"
                         ).rows == [[12]]
        # unknown-partition ingest is a 400, not a wedged socket
        with pytest.raises(urllib.error.HTTPError):
            _post(co.base_uri + "/v1/ingest/events?partition=99",
                  b"x")
        job = _post(co.base_uri + "/v1/continuous", json.dumps({
            "kind": "insert", "topic": "events",
            "poll_interval_ms": 150,
            "sql": "INSERT INTO memory.default.sink "
                   "SELECT _partition, _offset, v "
                   "FROM stream.default.events"}).encode())
        assert job["state"] == "RUNNING"
        _wait_until(lambda: c.execute(
            "SELECT count(*) FROM memory.default.sink"
        ).rows[0][0] == 12, what="continuous drain")
        # zero dup / zero loss through the HTTP + MPP path
        n, dn = c.execute(
            "SELECT count(*), count(DISTINCT p * 1000000 + o) "
            "FROM memory.default.sink").rows[0]
        assert n == 12 and dn == 12
        # the job is SQL-visible
        rows = c.execute(
            "SELECT job_id, kind, state, rows_total "
            "FROM system.runtime.continuous_queries").rows
        assert rows == [[job["job_id"], "insert", "RUNNING", 12]]
        # REST lifecycle: list, get, cancel, 404s
        assert len(_post(co.base_uri + "/v1/continuous",
                         method="GET")["jobs"]) == 1
        got = _post(co.base_uri + "/v1/continuous/" + job["job_id"],
                    method="GET")
        assert got["kind"] == "insert"
        bad = json.dumps({"kind": "nope", "sql": "x"}).encode()
        with pytest.raises(urllib.error.HTTPError):
            _post(co.base_uri + "/v1/continuous", bad)
        _post(co.base_uri + "/v1/continuous/" + job["job_id"],
              method="DELETE")
        assert _post(co.base_uri + "/v1/continuous/" + job["job_id"],
                     method="GET")["state"] == "CANCELED"
        with pytest.raises(urllib.error.HTTPError):
            _post(co.base_uri + "/v1/continuous/cq_missing",
                  method="DELETE")
    finally:
        co.stop()


@pytest.mark.slow
def test_streaming_acceptance_e2e(stream_dir, tmp_path):
    """The issue's acceptance e2e: messages stream in via /v1/ingest
    (coordinator AND worker endpoints) while a continuous job drains
    them; a worker is killed mid-ingest and the pipeline converges to
    zero duplicated / zero lost rows, proven from the offset ledger;
    the coordinator then fails over and the job restarts, resuming
    from its committed offsets."""
    from trino_tpu.client import StatementClient
    from trino_tpu.fte.spool import default_spool
    from trino_tpu.server.coordinator import Coordinator
    from trino_tpu.server.task_worker import TaskWorkerServer
    hist = str(tmp_path / "hist")
    # one CatalogManager across BOTH coordinators: memory-connector
    # state (the sink) must survive the failover like a real shared
    # warehouse would; the stream + offset state is disk-backed anyway
    cats = LocalQueryRunner(with_tpch=False).catalogs
    workers = [TaskWorkerServer().start() for _ in range(2)]
    co = Coordinator(worker_uris=[w.base_uri for w in workers],
                     catalogs=cats, history_dir=hist).start()
    stop_producing = threading.Event()
    produced = []

    def _produce():
        """20 bursts x 10 rows, alternating coordinator / worker
        ingest endpoints."""
        targets = [co.base_uri] + [w.base_uri for w in workers]
        for burst in range(20):
            if stop_producing.is_set():
                return
            base = burst * 10
            body = b"\n".join(
                json.dumps({"k": (base + i) % 3,
                            "v": float(base + i),
                            "ts": float(base + i)}).encode()
                for i in range(10))
            try:
                _post(targets[burst % len(targets)]
                      + "/v1/ingest/clicks", body)
            except (urllib.error.URLError, OSError):
                # a killed worker's endpoint: the producer retry
                # path re-routes to the coordinator. pre_append is
                # BEFORE the frame lands, so a connection-refused
                # retry cannot duplicate rows.
                _post(co.base_uri + "/v1/ingest/clicks", body)
            produced.append(10)
            time.sleep(0.05)

    try:
        c = StatementClient(co.base_uri)
        c.execute("CREATE TABLE stream.default.clicks "
                  "(k BIGINT, v DOUBLE, ts DOUBLE)")
        c.execute("CREATE TABLE memory.default.sink "
                  "(p BIGINT, o BIGINT, v DOUBLE)")
        job = _post(co.base_uri + "/v1/continuous", json.dumps({
            "kind": "insert", "topic": "clicks",
            "poll_interval_ms": 150,
            "sql": "INSERT INTO memory.default.sink "
                   "SELECT _partition, _offset, v "
                   "FROM stream.default.clicks"}).encode())
        producer = threading.Thread(target=_produce, daemon=True)
        producer.start()

        # the watcher: sink row counts grow MONOTONICALLY while the
        # producer streams
        seen = [0]

        def _count():
            try:
                n = c.execute("SELECT count(*) FROM "
                              "memory.default.sink").rows[0][0]
            except Exception:
                return seen[0]     # transient mid-kill wobble
            assert n >= seen[0], "sink count went backwards"
            seen[0] = n
            return n

        _wait_until(lambda: _count() >= 40, timeout=60,
                    what="first bursts drained")
        # kill one worker MID-INGEST; FTE + cycle retries absorb it
        workers[0].stop()
        producer.join(timeout=60)
        assert not producer.is_alive()
        total = sum(produced)
        assert total == 200
        _wait_until(lambda: _count() >= total, timeout=90,
                    what="all bursts drained after worker kill")

        # zero dup / zero lost, proven from the SQL-visible ledger
        n, dn = c.execute(
            "SELECT count(*), count(DISTINCT p * 1000000 + o) "
            "FROM memory.default.sink").rows[0]
        assert n == total and dn == total, \
            f"dup/loss after worker kill: {n} rows, {dn} distinct"
        src = c.execute(
            "SELECT count(*) FROM stream.default.clicks").rows[0][0]
        assert src == total

        # the offset ledger itself matches the log's end offsets
        offs = OffsetStore(default_spool())
        epoch, committed = offs.load(job["job_id"])
        assert epoch >= 1
        assert sum(committed["clicks"].values()) == total

        # live job in system.runtime.continuous_queries (rows_total
        # updates a beat after the insert lands — wait, don't race)
        _wait_until(lambda: c.execute(
            "SELECT job_id, state, rows_total FROM "
            "system.runtime.continuous_queries").rows
            == [[job["job_id"], "RUNNING", total]],
            what="system table row")

        # coordinator failover: the ledger restarts the job, which
        # resumes from committed offsets (no re-ingest of old rows)
        co.stop()
        co2 = Coordinator(worker_uris=[workers[1].base_uri],
                          catalogs=cats, history_dir=hist).start()
        try:
            c2 = StatementClient(co2.base_uri)
            _wait_until(lambda: _post(
                co2.base_uri + "/v1/continuous",
                method="GET")["jobs"], what="job restarted")
            _post(co2.base_uri + "/v1/ingest/clicks",
                  b"\n".join(
                      json.dumps({"k": 0, "v": -1.0,
                                  "ts": 999.0}).encode()
                      for _ in range(10)))
            _wait_until(lambda: c2.execute(
                "SELECT count(*) FROM memory.default.sink"
            ).rows[0][0] >= total + 10, timeout=60,
                what="post-failover drain")
            n, dn = c2.execute(
                "SELECT count(*), count(DISTINCT p * 1000000 + o) "
                "FROM memory.default.sink").rows[0]
            assert n == total + 10 and dn == total + 10, \
                "failover duplicated or lost rows"
        finally:
            co2.stop()
    finally:
        stop_producing.set()
        for w in workers:
            try:
                w.stop()
            except Exception:
                pass
        try:
            co.stop()
        except Exception:
            pass
