"""Geospatial core (plugin/trino-geospatial GeoFunctions subset):
point lanes, WKT in/out, vectorized polygon containment, haversine."""

import pytest

from trino_tpu.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def test_point_accessors_and_distance(runner):
    assert runner.execute(
        "SELECT ST_X(ST_Point(1.5, 2.5)), ST_Y(ST_Point(1.5, 2.5))"
    ).rows == [[1.5, 2.5]]
    assert runner.execute(
        "SELECT ST_Distance(ST_Point(0.0, 0.0), ST_Point(3.0, 4.0))"
    ).rows == [[5.0]]


def test_wkt_roundtrip(runner):
    assert runner.execute(
        "SELECT ST_AsText(ST_Point(1.0, -2.5))").rows == \
        [["POINT (1 -2.5)"]]
    assert runner.execute(
        "SELECT ST_X(ST_GeometryFromText('POINT (7 8)')), "
        "ST_Y(ST_GeometryFromText('POINT (7 8)'))").rows == [[7.0, 8.0]]


def test_contains_vectorized_over_table(runner):
    rows = runner.execute(
        "SELECT x, ST_Contains(ST_GeometryFromText("
        "'POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))'), "
        "ST_Point(x, y)) FROM (VALUES (5.0, 5.0), (15.0, 5.0), "
        "(-1.0, 2.0), (9.9, 9.9)) t(x, y) ORDER BY x").rows
    assert [[float(x), c] for x, c in rows] == \
        [[-1.0, False], [5.0, True], [9.9, True], [15.0, False]]


def test_contains_multiple_polygons(runner):
    # distinct WKT per row: each dictionary value parses once, masks
    # apply per code
    rows = runner.execute(
        "SELECT ST_Contains(ST_GeometryFromText(p), ST_Point(1.0, 1.0)) "
        "FROM (VALUES ('POLYGON ((0 0, 2 0, 2 2, 0 2, 0 0))'), "
        "('POLYGON ((5 5, 6 5, 6 6, 5 6, 5 5))')) t(p)").rows
    assert rows == [[True], [False]]


def test_great_circle_distance(runner):
    # the reference's documented example: BNA -> LAX ~2886.45 km
    d = runner.execute(
        "SELECT great_circle_distance(36.12, -86.67, 33.94, -118.40)"
    ).rows[0][0]
    assert d == pytest.approx(2886.45, abs=0.5)


def test_point_in_where_clause(runner):
    rows = runner.execute(
        "SELECT count(*) FROM (VALUES (1.0, 1.0), (3.0, 3.0), "
        "(9.0, 9.0)) t(x, y) WHERE ST_Distance(ST_Point(x, y), "
        "ST_Point(0.0, 0.0)) < 5.0").rows
    assert rows == [[2]]


def test_contains_donut_polygon_hole_excluded(runner):
    # interior rings (holes) participate in the even-odd rule: a point
    # inside the hole of a donut polygon is NOT contained (round-5
    # advisor nit: the parser used to drop every ring after the shell)
    donut = ("POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), "
             "(4 4, 6 4, 6 6, 4 6, 4 4))")
    rows = runner.execute(
        f"SELECT x, ST_Contains(ST_GeometryFromText('{donut}'), "
        "ST_Point(x, y)) FROM (VALUES "
        "(5.0, 5.0), "      # dead center of the hole -> outside
        "(2.0, 5.0), "      # in the ring body -> inside
        "(4.5, 4.5), "      # inside the hole near its corner -> outside
        "(11.0, 5.0), "     # beyond the shell -> outside
        "(6.5, 5.0)"        # between hole and shell -> inside
        ") t(x, y) ORDER BY x").rows
    assert [[float(x), c] for x, c in rows] == [
        [2.0, True], [4.5, False], [5.0, False], [6.5, True],
        [11.0, False]]


def test_contains_multiple_holes(runner):
    poly = ("POLYGON ((0 0, 12 0, 12 4, 0 4, 0 0), "
            "(1 1, 3 1, 3 3, 1 3, 1 1), (8 1, 10 1, 10 3, 8 3, 8 1))")
    rows = runner.execute(
        f"SELECT ST_Contains(ST_GeometryFromText('{poly}'), "
        "ST_Point(x, y)) FROM (VALUES (2.0, 2.0), (9.0, 2.0), "
        "(5.0, 2.0)) t(x, y)").rows
    assert rows == [[False], [False], [True]]


def test_contains_single_ring_unchanged(runner):
    # the common no-hole case keeps its exact pre-fix behavior
    rows = runner.execute(
        "SELECT ST_Contains(ST_GeometryFromText("
        "'POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))'), ST_Point(x, x)) "
        "FROM (VALUES 2.0, 5.0) t(x)").rows
    assert rows == [[True], [False]]
