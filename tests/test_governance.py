"""Overload governance: queued admission, cluster memory pool +
low-memory killer, and deadline propagation (PR 10; reference:
InternalResourceGroup + ClusterMemoryManager + LowMemoryKiller +
QueryTracker enforceTimeLimits).

The chaos-style acceptance battery lives here: a burst over
hard_concurrency completes via queueing in fair order (none lost), an
over-memory query is killed naming the pool while a concurrent query
finishes, and a query_max_run_time breach cancels in-flight worker
attempts — with queue depth, pool bytes, and kill counters visible in
/metrics.
"""

import json
import threading
import time
import urllib.request

import pytest

from trino_tpu.client import ClientError, StatementClient
from trino_tpu.errors import error_info, http_status_for
from trino_tpu.obs.metrics import METRICS, parse_exposition
from trino_tpu.runner import QueryResult
from trino_tpu.server.coordinator import Coordinator, QueryTracker
from trino_tpu.server.memory import (ClusterMemoryManager,
                                     ClusterMemoryPool,
                                     MemoryGovernanceError)
from trino_tpu.server.resourcegroups import (ResourceGroup,
                                             ResourceGroupManager)
from trino_tpu.session import Session


def _wait_until(pred, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


class _GatedRunner:
    """Fake runner: execute() optionally reserves pool memory (tagged
    in the SQL), then blocks until its per-query gate opens or the
    query is canceled — admission/governance are runner-agnostic, so
    the tracker-level tests drive them deterministically without
    real query latency."""

    def __init__(self, session, gates, started, reservations):
        self.session = session
        self.gates = gates
        self.started = started
        self.reservations = reservations

    def execute(self, sql):
        self.started.append(sql)
        nbytes = self.reservations.get(sql, 0)
        if nbytes and self.session.memory is not None:
            self.session.memory.reserve(nbytes)
        gate = self.gates.get(sql)
        cancel = self.session.cancel
        while gate is not None and not gate.is_set():
            if cancel is not None and cancel.is_set():
                from trino_tpu.exec.executor import QueryError
                raise QueryError("Query was canceled")
            gate.wait(0.01)
        return QueryResult(["x"], [], [[1]])


# --- admission ------------------------------------------------------------

def test_admission_caps_concurrency_and_drains_fifo():
    """N queries against hard_concurrency=2: two run, the rest queue,
    and completions drain the queue in arrival (FIFO) order — none
    lost. Pure tracker-level (LocalQueryRunner-style in-process
    embedding): admission does not depend on the HTTP layer."""
    mgr = ResourceGroupManager()
    g = mgr.root.add(ResourceGroup("small", hard_concurrency=2,
                                   max_queued=100))
    mgr.add_selector(g)
    gates = {f"q{i}": threading.Event() for i in range(6)}
    started = []
    tracker = QueryTracker(
        lambda s: _GatedRunner(s, gates, started, {}),
        resource_groups=mgr)
    queries = [tracker.submit(f"q{i}", Session(user="alice"))
               for i in range(6)]
    _wait_until(lambda: len(started) == 2, what="2 running")
    time.sleep(0.1)
    # only the admitted pair ran (their two threads race each other,
    # so the first two are order-free)
    assert set(started) == {"q0", "q1"} and len(started) == 2
    assert g.running == 2 and g.queued() == 4
    assert sum(1 for q in queries if q.state == "QUEUED") == 4
    # completions dequeue in arrival order (FIFO within the leaf):
    # each release finishes one query, which admits exactly one
    # queued successor — the next in line
    for i in range(6):
        gates[f"q{i}"].set()
        _wait_until(lambda i=i: queries[i].state == "FINISHED",
                    what=f"q{i} finished")
    assert started[2:] == ["q2", "q3", "q4", "q5"]   # fair order
    assert all(q.state == "FINISHED" for q in queries)     # none lost
    assert g.running == 0 and g.queued() == 0


def test_queue_full_rejected_with_trino_error_identity():
    """Past max_queued the submit FAILS immediately with
    QUERY_QUEUE_FULL — the real StandardErrorCode code and
    INSUFFICIENT_RESOURCES type, counted in the rejection metric."""
    mgr = ResourceGroupManager()
    g = mgr.root.add(ResourceGroup("tiny", hard_concurrency=1,
                                   max_queued=1))
    mgr.add_selector(g)
    gates = {"q0": threading.Event()}
    started = []
    tracker = QueryTracker(
        lambda s: _GatedRunner(s, gates, started, {}),
        resource_groups=mgr)
    rej0 = METRICS.counter("trino_tpu_queue_rejections_total").value()
    q0 = tracker.submit("q0", Session())           # running
    q1 = tracker.submit("q1", Session())           # queued
    q2 = tracker.submit("q2", Session())           # rejected
    _wait_until(lambda: q2.state == "FAILED", what="rejection")
    code, etype = error_info("QUERY_QUEUE_FULL")
    assert q2.error["errorName"] == "QUERY_QUEUE_FULL"
    assert q2.error["errorCode"] == code == 0x0002_0000 + 2
    assert q2.error["errorType"] == etype == "INSUFFICIENT_RESOURCES"
    assert METRICS.counter(
        "trino_tpu_queue_rejections_total").value() == rej0 + 1
    # the rejection did not disturb the admitted pair: q0 completes,
    # then q1 (enqueued BEFORE the rejection) dequeues and completes
    gates["q0"].set()
    _wait_until(lambda: q0.state == "FINISHED", what="q0 finished")
    _wait_until(lambda: q1.state == "FINISHED", what="q1 drained")


def test_http_burst_completes_via_queueing():
    """The protocol-level acceptance leg: a burst of clients over
    hard_concurrency=1 all complete via nextUri polling while QUEUED
    (none lost, no errors), queuedTimeMillis is surfaced in the stats
    payload, and the queued-time histogram moves."""
    mgr = ResourceGroupManager()
    g = mgr.root.add(ResourceGroup("capped", hard_concurrency=1,
                                   max_queued=50))
    mgr.add_selector(g)
    co = Coordinator(resource_groups=mgr).start()
    h = METRICS.histogram("trino_tpu_query_queued_seconds")
    n0 = h.count()
    try:
        results = []
        errors = []

        def run():
            try:
                c = StatementClient(co.base_uri)
                results.append(c.execute(
                    "SELECT count(*) FROM tpch.tiny.region").rows)
            except Exception as e:      # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=run) for _ in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errors
        assert results == [[[5]]] * 5           # all completed, none lost
        assert h.count() >= n0 + 1       # some queries really queued
        assert g.running == 0 and g.queued() == 0
        # queuedTimeMillis rides the protocol stats payload
        c = StatementClient(co.base_uri)
        r = c._request("POST", f"{co.base_uri}/v1/statement",
                       b"SELECT 1")
        assert "queuedTimeMillis" in r["stats"]
    finally:
        co.stop()


# --- memory governance ----------------------------------------------------

def test_low_memory_killer_kills_largest_survivor_completes():
    """Two concurrent queries against a small pool: the LARGEST is
    killed with CLUSTER_OUT_OF_MEMORY naming the victim and the pool
    state; the survivor completes. The memory-kill acceptance e2e at
    the tracker level."""
    memory = ClusterMemoryManager(ClusterMemoryPool(1000))
    gates = {"big": threading.Event(), "small": threading.Event()}
    started = []
    reservations = {"big": 700, "small": 400}
    tracker = QueryTracker(
        lambda s: _GatedRunner(s, gates, started, reservations),
        memory=memory)
    kills0 = METRICS.counter("trino_tpu_memory_kills_total").value()
    qbig = tracker.submit("big", Session())
    _wait_until(lambda: "big" in started, what="big running")
    qsmall = tracker.submit("small", Session())   # 700+400 > 1000
    _wait_until(lambda: qbig.state == "FAILED", what="big killed")
    err = qbig.error
    assert err["errorName"] == "CLUSTER_OUT_OF_MEMORY"
    assert err["errorType"] == "INSUFFICIENT_RESOURCES"
    # actionable: names the victim, its reservation, and the pool state
    assert qbig.query_id in err["message"]
    assert "700" in err["message"] and "low-memory killer" \
        in err["message"]
    assert "reserved" in err["message"]
    gates["small"].set()
    _wait_until(lambda: qsmall.state == "FINISHED", what="survivor")
    assert qsmall.state == "FINISHED"
    gates["big"].set()
    qbig.wait_done(5)
    assert METRICS.counter(
        "trino_tpu_memory_kills_total").value() == kills0 + 1
    # unregistration freed both reservations
    assert memory.pool.reserved_bytes() == 0


def test_group_soft_memory_limit_kills_within_group():
    """A resource group's soft memory limit governs ITS aggregate:
    the offending group's largest query dies, a query in another
    group is untouched."""
    mgr = ResourceGroupManager()
    etl = mgr.root.add(ResourceGroup("etl", hard_concurrency=10,
                                     soft_memory_limit_bytes=500))
    adhoc = mgr.root.add(ResourceGroup("adhoc", hard_concurrency=10))
    mgr.add_selector(etl, user_regex="etl")
    mgr.add_selector(adhoc)
    memory = ClusterMemoryManager(ClusterMemoryPool(10_000))
    gates = {k: threading.Event() for k in ("e1", "e2", "a1")}
    started = []
    reservations = {"e1": 300, "e2": 300, "a1": 5000}
    tracker = QueryTracker(
        lambda s: _GatedRunner(s, gates, started, reservations),
        resource_groups=mgr, memory=memory)
    qa = tracker.submit("a1", Session(user="bob"))   # other group, big
    qe1 = tracker.submit("e1", Session(user="etl"))
    _wait_until(lambda: len(started) >= 2, what="first two running")
    qe2 = tracker.submit("e2", Session(user="etl"))  # 600 > 500 in etl
    _wait_until(lambda: qe1.state == "FAILED"
                or qe2.state == "FAILED", what="etl kill")
    victim = qe1 if qe1.state == "FAILED" else qe2
    assert victim.error["errorName"] == "CLUSTER_OUT_OF_MEMORY"
    assert "global.etl" in victim.error["message"]
    assert qa.state == "RUNNING"        # 5000-byte outsider untouched
    for k in gates:
        gates[k].set()
    for q in (qa, qe1, qe2):
        q.wait_done(5)


def test_real_executor_feeds_pool_and_dies_with_trino_error():
    """The executor wiring, end to end through a REAL query: a join's
    capacity reservation flows into the pool via session.memory, and
    a pool breach fails the query with a CLUSTER_OUT_OF_MEMORY
    QueryError in the reserving thread."""
    from trino_tpu.exec.executor import QueryError
    from trino_tpu.runner import LocalQueryRunner
    # the tiny-schema join's largest capacity reservation is ~940 KiB
    # — a 512 KiB pool guarantees the breach
    memory = ClusterMemoryManager(ClusterMemoryPool(1 << 19))
    s = Session(catalog="tpch", schema="tiny")
    # pin the MATERIALIZED path: with morsel streaming engaged this
    # query now legitimately completes under the pool by reserving
    # its streamed peak (tests/test_stream_exec.py proves that); this
    # test's subject is the un-streamed wiring + killer identity
    s.set("stream_chunk_rows", -1)
    s.memory = memory.register("qx", kill_fn=lambda m, n: None)
    lr = LocalQueryRunner(session=s)
    with pytest.raises(QueryError) as exc:
        lr.execute("SELECT count(*) FROM lineitem JOIN orders "
                   "ON l_orderkey = o_orderkey")
    assert getattr(exc.value, "error_name", None) \
        == "CLUSTER_OUT_OF_MEMORY"
    assert "low-memory killer" in str(exc.value)
    memory.unregister("qx")


def test_query_max_memory_cap_exceeds_global_limit():
    """The per-query cluster cap (query_max_memory) fails ONLY the
    offending query with EXCEEDED_GLOBAL_MEMORY_LIMIT — no other
    query need die for it."""
    memory = ClusterMemoryManager(ClusterMemoryPool(1 << 30))
    ctx = memory.register("qy", kill_fn=lambda m, n: None,
                          query_limit_bytes=100)
    with pytest.raises(MemoryGovernanceError) as exc:
        ctx.reserve(500)
    assert exc.value.error_name == "EXCEEDED_GLOBAL_MEMORY_LIMIT"
    memory.unregister("qy")


def test_memory_kill_error_name_classifies():
    """errors.classify maps governance messages to the Trino names
    (the satellite contract: proper error identity, never a generic
    500 / GENERIC_INTERNAL_ERROR)."""
    from trino_tpu.errors import classify
    from trino_tpu.exec.executor import QueryError
    name, code, etype = classify(QueryError(
        "The cluster is out of memory ..."))
    assert name == "CLUSTER_OUT_OF_MEMORY"
    assert etype == "INSUFFICIENT_RESOURCES"
    name, _, _ = classify(QueryError(
        "Query q exceeded the global memory limit of 5 bytes"))
    assert name == "EXCEEDED_GLOBAL_MEMORY_LIMIT"
    name, _, _ = classify(QueryError(
        "Query exceeded the maximum run time (query_max_run_time)"))
    assert name == "EXCEEDED_TIME_LIMIT"
    # explicit error_name beats message sniffing
    name, _, _ = classify(QueryError("whatever",
                                     error_name="QUERY_QUEUE_FULL"))
    assert name == "QUERY_QUEUE_FULL"
    assert http_status_for("INSUFFICIENT_RESOURCES") == 429
    assert http_status_for("USER_ERROR") == 400
    assert http_status_for("INTERNAL_ERROR") == 500


# --- deadline propagation -------------------------------------------------

def test_deadline_cancels_inflight_worker_attempts():
    """The deadline acceptance chaos: a stage-path distributed query
    blocks in a worker-side scan; the 1s query_max_run_time breach
    fails the query with EXCEEDED_TIME_LIMIT AND aborts the in-flight
    attempts ON the worker (verified via the worker's task registry +
    abort metric) — not merely the next coordinator poll."""
    from trino_tpu.catalog import CatalogManager
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.exec.remote import DistributedHostQueryRunner
    from trino_tpu.server.task_worker import TaskWorkerServer

    gate = threading.Event()

    class BlockingTpch(TpchConnector):
        remote_scan_ok = True

        def read_split(self, split, columns):
            gate.wait(30)
            return super().read_split(split, columns)

    cats = CatalogManager()
    cats.register("tpch", BlockingTpch())
    worker = TaskWorkerServer(catalogs=cats).start()
    aborted = METRICS.counter(
        "trino_tpu_worker_tasks_aborted_total")
    deadline_cancels = METRICS.counter(
        "trino_tpu_deadline_cancels_total")
    a0, d0 = aborted.value(), deadline_cancels.value()
    tracker = QueryTracker(
        lambda s: DistributedHostQueryRunner(
            [worker.base_uri], session=s, catalogs=cats))
    try:
        session = Session(catalog="tpch", schema="tiny")
        session.set("query_max_run_time", 1)
        session.set("multistage_execution", True)
        q = tracker.submit(
            "SELECT count(*) FROM lineitem", session)
        # the worker accepted an attempt (it is blocked in the scan)
        _wait_until(lambda: len(worker._tasks) > 0,
                    what="worker attempt in flight")
        assert q.wait_done(15), "query did not reach a terminal state"
        assert q.state == "FAILED"
        assert q.error["errorName"] == "EXCEEDED_TIME_LIMIT"
        assert "maximum run time" in q.error["message"]
        assert deadline_cancels.value() == d0 + 1
        # the cancel reached the WORKER: its in-flight task was
        # DELETEd (aborted + dropped from the registry) by the
        # scheduler's watch, not left running to completion
        _wait_until(lambda: aborted.value() > a0,
                    what="worker-side abort")
        _wait_until(lambda: len(worker._tasks) == 0,
                    what="worker task registry drained")
    finally:
        gate.set()
        worker.stop()


def test_deadline_fires_while_still_queued():
    """query_max_run_time budgets the WHOLE run including queue time
    (the reference's QUERY_MAX_RUN_TIME): a query that spends its
    budget QUEUED behind a wedged group dies at t=limit with
    EXCEEDED_TIME_LIMIT — it does not wait for admission."""
    mgr = ResourceGroupManager()
    g = mgr.root.add(ResourceGroup("wedged", hard_concurrency=1,
                                   max_queued=10))
    mgr.add_selector(g)
    gates = {"blocker": threading.Event()}
    started = []
    tracker = QueryTracker(
        lambda s: _GatedRunner(s, gates, started, {}),
        resource_groups=mgr)
    blocker = tracker.submit("blocker", Session())    # wedges the slot
    _wait_until(lambda: "blocker" in started, what="blocker running")
    s = Session()
    s.set("query_max_run_time", 1)
    victim = tracker.submit("victim", Session(properties=s.properties))
    assert victim.state == "QUEUED"
    assert victim.wait_done(5), "queued query missed its deadline"
    assert victim.state == "FAILED"
    assert victim.error["errorName"] == "EXCEEDED_TIME_LIMIT"
    assert "victim" not in started        # it never ran
    # the dead entry was withdrawn from the group queue: it no longer
    # holds max_queued capacity and will never burn a concurrency slot
    _wait_until(lambda: g.queued() == 0, what="dead entry withdrawn")
    # a canceled-while-queued query is withdrawn the same way
    q2 = tracker.submit("victim2", Session())
    assert q2.state == "QUEUED" and g.queued() == 1
    tracker.cancel(q2.query_id)
    assert q2.state == "CANCELED" and g.queued() == 0
    gates["blocker"].set()
    blocker.wait_done(5)
    assert g.running == 0


def test_parse_data_size():
    """config.properties query.max-memory accepts the reference's
    DataSize strings, not only raw byte counts."""
    from trino_tpu.server.memory import parse_data_size
    assert parse_data_size("50GB") == 50 << 30
    assert parse_data_size("512MB") == 512 << 20
    assert parse_data_size("1.5GB") == int(1.5 * (1 << 30))
    assert parse_data_size(" 2kB ") == 2048
    assert parse_data_size("12345") == 12345
    assert parse_data_size("100B") == 100


def test_deadline_enforced_by_standalone_runner():
    """A LocalQueryRunner used without a coordinator derives the
    deadline itself: the executor stops between plan nodes with
    EXCEEDED_TIME_LIMIT."""
    from trino_tpu.exec.executor import QueryError
    from trino_tpu.runner import LocalQueryRunner
    s = Session(catalog="tpch", schema="tiny")
    s.set("query_max_run_time", 1)
    s.deadline = time.monotonic() - 0.1      # already spent
    lr = LocalQueryRunner(session=s)
    with pytest.raises(QueryError) as exc:
        lr.execute("SELECT count(*) FROM nation")
    assert getattr(exc.value, "error_name", None) \
        == "EXCEEDED_TIME_LIMIT"


# --- observability of the governance layer --------------------------------

def test_governance_metrics_visible_in_exposition():
    """The acceptance scrape: queue depth, memory-pool bytes, and the
    kill/rejection/deadline counters all render at /metrics on a
    governed coordinator."""
    co = Coordinator(memory_pool_bytes=123456789).start()
    try:
        StatementClient(co.base_uri).execute("SELECT 1")
        raw = urllib.request.urlopen(
            co.base_uri + "/metrics").read().decode()
        fams = parse_exposition(raw)
        assert "trino_tpu_queue_depth" in fams
        assert fams["trino_tpu_memory_pool_bytes"][
            ("kind=total",)] == 123456789
        assert "trino_tpu_memory_kills_total" in fams
        assert "trino_tpu_queue_rejections_total" in fams
        assert "trino_tpu_deadline_cancels_total" in fams
        assert "trino_tpu_query_queued_seconds_count" in raw
        # the cluster overview carries the pool state for the web UI
        cl = json.loads(urllib.request.urlopen(
            co.base_uri + "/v1/cluster").read())
        assert cl["memory"]["maxBytes"] == 123456789
        assert "reservedBytes" in cl["memory"]
        # a default (unconfigured) coordinator still has REAL
        # admission: the root group shows in the group infos
        assert any(i["name"] == "global"
                   for i in co.resource_group_infos())
    finally:
        co.stop()
