"""Regression tests for the round-1 advisor findings (ADVICE.md).

Each test is the advisor's own repro. Reference semantics:
LookupJoinOperator/NestedLoopJoinOperator outer handling,
iterative/rule/ImplementExceptAll.java, operator/window/NTileFunction +
LagFunction/LeadFunction argument handling.
"""

import pytest

from trino_tpu.exec import QueryError
from trino_tpu.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def _sorted(rows):
    return sorted(rows, key=lambda r: tuple(
        (v is None, v) for v in r))


def test_left_join_non_equi_only(runner):
    res = runner.execute(
        "SELECT * FROM (VALUES 1,2,3) t(x) "
        "LEFT JOIN (VALUES 2) u(y) ON t.x < u.y")
    assert _sorted(res.rows) == [[1, 2], [2, None], [3, None]]


def test_right_join_non_equi_only(runner):
    res = runner.execute(
        "SELECT * FROM (VALUES 2) u(y) "
        "RIGHT JOIN (VALUES 1,2,3) t(x) ON t.x < u.y")
    assert _sorted(res.rows) == [[2, 1], [None, 2], [None, 3]]


def test_full_join_non_equi_only(runner):
    res = runner.execute(
        "SELECT * FROM (VALUES 1,2) t(x) "
        "FULL JOIN (VALUES 2,3) u(y) ON t.x > u.y")
    # only match: x=... none? 1>2 F, 1>3 F, 2>2 F... no wait 2>... none
    # matches: x>y pairs: none (2>2 false). All null-extended both ways.
    assert _sorted(res.rows) == [[1, None], [2, None],
                                 [None, 2], [None, 3]]


def test_except_all_multiplicity(runner):
    res = runner.execute(
        "(SELECT * FROM (VALUES 1,1,1,2) t(x)) "
        "EXCEPT ALL (SELECT * FROM (VALUES 1) u(x))")
    assert _sorted(res.rows) == [[1], [1], [2]]


def test_except_distinct_unchanged(runner):
    res = runner.execute(
        "(SELECT * FROM (VALUES 1,1,2) t(x)) "
        "EXCEPT (SELECT * FROM (VALUES 1) u(x))")
    assert res.rows == [[2]]


def test_intersect_all_multiplicity(runner):
    res = runner.execute(
        "(SELECT * FROM (VALUES 1,1,1,2) t(x)) "
        "INTERSECT ALL (SELECT * FROM (VALUES 1,1,3) u(x))")
    assert _sorted(res.rows) == [[1], [1]]


def test_full_join_residual_filter(runner):
    res = runner.execute(
        "SELECT * FROM (VALUES 1,2) t(x) "
        "FULL JOIN (VALUES 1,3) u(y) ON x = y AND x > 5")
    assert _sorted(res.rows) == [[1, None], [2, None],
                                 [None, 1], [None, 3]]


def test_left_join_residual_all_filtered(runner):
    res = runner.execute(
        "SELECT * FROM (VALUES 1,2) t(x) "
        "LEFT JOIN (VALUES 1,3) u(y) ON x = y AND x > 5")
    assert _sorted(res.rows) == [[1, None], [2, None]]


def test_ntile_argument(runner):
    res = runner.execute(
        "SELECT x, ntile(2) OVER (ORDER BY x) FROM "
        "(VALUES 1,2,3,4) t(x) ORDER BY x")
    assert res.rows == [[1, 1], [2, 1], [3, 2], [4, 2]]
    res = runner.execute(
        "SELECT x, ntile(3) OVER (ORDER BY x) FROM "
        "(VALUES 1,2,3,4,5) t(x) ORDER BY x")
    assert res.rows == [[1, 1], [2, 1], [3, 2], [4, 2], [5, 3]]


def test_lag_lead_offset_and_default(runner):
    res = runner.execute(
        "SELECT x, lag(x, 2) OVER (ORDER BY x), "
        "lead(x, 2) OVER (ORDER BY x) FROM "
        "(VALUES 1,2,3,4) t(x) ORDER BY x")
    assert res.rows == [[1, None, 3], [2, None, 4],
                        [3, 1, None], [4, 2, None]]
    res = runner.execute(
        "SELECT x, lag(x, 1, -1) OVER (ORDER BY x) FROM "
        "(VALUES 1,2,3) t(x) ORDER BY x")
    assert res.rows == [[1, -1], [2, 1], [3, 2]]


def test_lag_default_offset_still_one(runner):
    res = runner.execute(
        "SELECT x, lag(x) OVER (ORDER BY x) FROM "
        "(VALUES 10,20,30) t(x) ORDER BY x")
    assert res.rows == [[10, None], [20, 10], [30, 20]]


def test_lag_null_offset_gives_null(runner):
    res = runner.execute(
        "SELECT x, lag(x, y) OVER (ORDER BY x) FROM "
        "(VALUES (1, 1), (2, CAST(NULL AS BIGINT)), (3, 1)) t(x, y) "
        "ORDER BY x")
    assert res.rows == [[1, None], [2, None], [3, 2]]


def test_ntile_more_buckets_than_rows(runner):
    res = runner.execute(
        "SELECT x, ntile(8) OVER (ORDER BY x) FROM "
        "(VALUES 1,2,3,4) t(x) ORDER BY x")
    assert res.rows == [[1, 1], [2, 2], [3, 3], [4, 4]]


def test_lag_string_default(runner):
    res = runner.execute(
        "SELECT x, lag(s, 1, 'none') OVER (ORDER BY x) FROM "
        "(VALUES (1, 'a'), (2, 'b')) t(x, s) ORDER BY x")
    assert res.rows == [[1, "none"], [2, "a"]]
