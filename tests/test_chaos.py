"""Chaos harness: coordinator restarts, worker joins, combine failures.

Reference parity: Trino's fault-tolerant execution spools the root
stage's output through the exchange manager so a client can re-pull
`QueryResults` after a coordinator restart, retries every stage
including the root, and absorbs discovery-service announcements so the
worker set grows mid-query. The scenarios here kill and restart the
processes those guarantees protect — a coordinator serving spooled
results, the combine (root) stage, and the worker fleet — with the
object-store-shaped spool backend active where durability is the point
under test.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from trino_tpu.exec import QueryError
from trino_tpu.exec.remote import DistributedHostQueryRunner
from trino_tpu.fte.objectstore import (InMemoryObjectStore,
                                       ObjectStoreSpool)
from trino_tpu.fte.spool import LocalDirSpool
from trino_tpu.obs.metrics import METRICS
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.server.coordinator import Coordinator
from trino_tpu.server.task_worker import TaskWorkerServer, announce_once
from trino_tpu.session import Session

SQL = ("SELECT n_name, count(*) FROM nation "
       "JOIN region ON n_regionkey = r_regionkey "
       "WHERE r_name = 'ASIA' GROUP BY n_name ORDER BY n_name")


def _counter(name: str) -> float:
    return METRICS.counter(name).value()


def _get_json(uri):
    with urllib.request.urlopen(uri, timeout=10) as r:
        return json.loads(r.read())


def _task_session(**props) -> Session:
    s = Session(catalog="tpch", schema="tiny")
    s.set("retry_policy", "TASK")
    s.set("retry_initial_delay_ms", 10)
    s.set("remote_task_timeout", 30)
    for k, v in props.items():
        s.set(k, v)
    return s


@pytest.fixture(scope="module")
def workers():
    w1, w2 = TaskWorkerServer().start(), TaskWorkerServer().start()
    yield [w1.base_uri, w2.base_uri]
    w1.stop()
    w2.stop()


@pytest.fixture(scope="module")
def expected():
    return LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny")).execute(SQL)


class _HangWorker:
    """A fake worker that accepts task POSTs then answers every result
    pull with 202 forever — a wedged node that can never produce data,
    so any query completing against it PROVES another worker ran the
    retried tasks."""

    def __init__(self):
        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                body = b'{"taskId": "x", "state": "RUNNING"}'
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self.send_response(202)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_DELETE(self):
                self.send_response(204)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.base_uri = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


# --------------------------------------------------------------------------
# coordinator restart: results re-pulled off the spooled manifest
# --------------------------------------------------------------------------

def test_coordinator_restart_mid_pull_recovers_results():
    """The acceptance restart: a client that pulled part of a FINISHED
    query's results from coordinator #1 keeps pulling the SAME rows
    from coordinator #2 — a fresh process that never ran the query —
    because the combine output + manifest live in the shared
    object-store spool, not in coordinator memory."""
    sql = ("SELECT * FROM (VALUES (1, 'a'), (2, 'b'), (3, 'c')) "
           "AS t(x, y) ORDER BY x")
    store = InMemoryObjectStore()          # the durable "bucket"
    co1 = Coordinator(spool=ObjectStoreSpool(store)).start()
    try:
        out = _get_json_post(co1.base_uri + "/v1/statement", sql)
        qid = out["id"]
        # drain coordinator #1's answer (the client's first pull)
        rows1 = list(out.get("data") or [])
        while "nextUri" in out:
            out = _get_json(out["nextUri"])
            rows1.extend(out.get("data") or [])
        assert out["stats"]["state"] == "FINISHED"
        slug = co1.tracker.get(qid).slug
        # the finished query's manifest must hit the bucket before the
        # process dies (persist runs on the query thread post-FINISH)
        deadline = time.time() + 5
        while not store.list(f"{qid}/") and time.time() < deadline:
            time.sleep(0.02)
        assert store.list(f"{qid}/"), "manifest never reached the spool"
    finally:
        co1.stop()                         # the restart

    recovered = _counter("trino_tpu_query_results_recovered_total")
    co2 = Coordinator(spool=ObjectStoreSpool(store)).start()
    try:
        assert co2.tracker.get(qid) is None     # co2 never ran it
        # a wrong slug must NOT recover: the per-query capability
        # token keeps its strength across restarts
        with pytest.raises(urllib.error.HTTPError) as err:
            _get_json(f"{co2.base_uri}/v1/statement/executing/"
                      f"{qid}/forged-slug/0")
        assert err.value.code == 404
        # the real slug resumes the pull from token 0
        out = _get_json(f"{co2.base_uri}/v1/statement/executing/"
                        f"{qid}/{slug}/0")
        rows2 = list(out.get("data") or [])
        while "nextUri" in out:
            out = _get_json(out["nextUri"])
            rows2.extend(out.get("data") or [])
        assert out["stats"]["state"] == "FINISHED"
        assert rows2 == rows1 == [[1, "a"], [2, "b"], [3, "c"]]
        assert [c["name"] for c in out["columns"]] == ["x", "y"]
        assert _counter("trino_tpu_query_results_recovered_total") \
            == recovered + 1
        # the recovered entry also serves the query-detail surface
        detail = _get_json(f"{co2.base_uri}/v1/query/{qid}")
        assert detail["state"] == "FINISHED" and detail["rows"] == 3
    finally:
        co2.stop()


def _get_json_post(uri, data):
    req = urllib.request.Request(uri, data=data.encode())
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _delete(uri):
    req = urllib.request.Request(uri, method="DELETE")
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status


def test_canceled_query_results_never_persisted():
    """A CANCELED query's results must not become recoverable-as-
    FINISHED after a restart: a cancel landing before the persist
    skips it, and a cancel racing INTO the persist window discards
    the just-spooled entry."""
    from trino_tpu.server.coordinator import _Query

    class _Runner:
        def __init__(self, session):
            pass

        def execute(self, sql):
            return LocalQueryRunner(
                session=Session(catalog="tpch", schema="tiny")
            ).execute("SELECT 1 AS x")

    # cancel BEFORE the persist window: on_result never fires
    q1 = _Query("q-early", "s", "SELECT 1",
                Session(catalog="tpch", schema="tiny"))
    calls = []

    def persist_racing_cancel(query, result):
        # the race: the client cancel lands while persist is running
        query.do_cancel()
        calls.append(query.query_id)
        return True

    discarded = []
    q1.state = "QUEUED"
    q1.do_cancel()
    q1.run(_Runner, on_result=lambda q, r: calls.append("early"),
           on_discard=discarded.append)
    assert q1.state == "CANCELED" and "early" not in calls

    # cancel DURING the persist: the entry is released again
    q2 = _Query("q-race", "s", "SELECT 1",
                Session(catalog="tpch", schema="tiny"))
    q2.run(_Runner, on_result=persist_racing_cancel,
           on_discard=lambda q: discarded.append(q.query_id))
    assert q2.state == "CANCELED"
    assert calls == ["q-race"] and discarded == ["q-race"]


def test_delete_requires_slug_to_release_spooled_results():
    """DELETE /v1/statement must present the query's slug to destroy
    its spooled restart-recovery results — the capability token guards
    destruction exactly as it guards recovery, or any client that can
    enumerate query ids could revoke another client's restart
    recoverability."""
    store = InMemoryObjectStore()
    co = Coordinator(spool=ObjectStoreSpool(store)).start()
    try:
        out = _get_json_post(co.base_uri + "/v1/statement",
                             "SELECT 42 AS x")
        qid = out["id"]
        while "nextUri" in out:
            out = _get_json(out["nextUri"])
        assert out["stats"]["state"] == "FINISHED"
        slug = co.tracker.get(qid).slug
        deadline = time.time() + 5
        while not store.list(f"{qid}/") and time.time() < deadline:
            time.sleep(0.02)
        assert store.list(f"{qid}/"), "manifest never reached the spool"
        # a forged slug still cancels idempotently (204) but must NOT
        # reap the durable results
        assert _delete(f"{co.base_uri}/v1/statement/executing/"
                       f"{qid}/forged-slug/0") == 204
        assert store.list(f"{qid}/"), "forged slug destroyed results"
        # the owner's slug releases them immediately
        assert _delete(f"{co.base_uri}/v1/statement/executing/"
                       f"{qid}/{slug}/0") == 204
        assert not store.list(f"{qid}/")
    finally:
        co.stop()


# --------------------------------------------------------------------------
# combine (root) stage retry
# --------------------------------------------------------------------------

class _FlakyCombine:
    """Monkeypatch hook: fail the scheduler's combine Executor N times,
    then delegate — only exec.remote's Executor reference is patched,
    so worker-side execution is untouched."""

    def __init__(self, real, failures):
        self.real = real
        self.left = failures

    def make(self):
        flaky = self

        class FlakyExecutor(flaky.real):
            def execute(ex_self, plan):
                if flaky.left > 0:
                    flaky.left -= 1
                    raise RuntimeError("injected combine failure")
                return super().execute(plan)

        return FlakyExecutor


def test_combine_stage_failure_retried(workers, expected, monkeypatch):
    """The root stage was the one unretried single point of failure:
    under retry_policy=TASK an injected combine crash re-executes on
    the coordinator (its fragment inputs are already gathered), the
    query completes with the right answer, and the retry is visible in
    the counter and the span tree."""
    import trino_tpu.exec.remote as remote
    flaky = _FlakyCombine(remote.Executor, failures=1)
    monkeypatch.setattr(remote, "Executor", flaky.make())
    before = _counter("trino_tpu_combine_retries_total")
    runner = DistributedHostQueryRunner(
        workers, session=_task_session(),
        spool=ObjectStoreSpool(InMemoryObjectStore()),
        collect_node_stats=True)
    res = runner.execute(SQL)
    assert res.rows == expected.rows
    assert flaky.left == 0
    assert _counter("trino_tpu_combine_retries_total") == before + 1
    names = []

    def walk(spans):
        for sp in spans:
            names.append(sp["name"])
            walk(sp.get("children", []))

    walk(res.trace.to_dicts())
    assert "combine_retry" in names, names


def test_combine_failure_none_policy_fails_fast(workers, monkeypatch):
    """retry_policy=NONE keeps the old semantics: a combine crash is
    the query's answer, not a silent re-execution."""
    import trino_tpu.exec.remote as remote
    flaky = _FlakyCombine(remote.Executor, failures=100)
    monkeypatch.setattr(remote, "Executor", flaky.make())
    before = _counter("trino_tpu_combine_retries_total")
    runner = DistributedHostQueryRunner(
        workers, session=Session(catalog="tpch", schema="tiny"))
    with pytest.raises(Exception, match="injected combine failure"):
        runner.execute(SQL)
    assert _counter("trino_tpu_combine_retries_total") == before


# --------------------------------------------------------------------------
# live worker membership
# --------------------------------------------------------------------------

def test_worker_joining_mid_query_receives_retried_task(expected):
    """The acceptance join: the initial worker set is ONE wedged node
    that can never return data, so the only way this query completes
    is the scheduler's membership re-sync handing the retried tasks to
    the worker that joined after dispatch — with the object-store
    spool backend carrying the retried attempts' output."""
    hang = _HangWorker()
    joiner = TaskWorkerServer().start()
    members = [hang.base_uri]
    retries = _counter("trino_tpu_task_retries_total")
    try:
        # warm the joiner (JIT compile of this query's fragments) so
        # the short task timeout below measures the wedged node, not
        # first-run compile on the replacement
        warm = DistributedHostQueryRunner(
            [joiner.base_uri], session=_task_session()).execute(SQL)
        assert warm.rows == expected.rows
        runner = DistributedHostQueryRunner(
            [hang.base_uri],           # dispatch-time fan-out set
            session=_task_session(remote_task_timeout=2),
            spool=ObjectStoreSpool(InMemoryObjectStore()),
            worker_supplier=lambda: members)
        # the join lands after dispatch: the supplier is only
        # consulted when a replacement/speculative attempt is placed
        members.append(joiner.base_uri)
        res = runner.execute(SQL)
    finally:
        hang.stop()
        joiner.stop()
    assert res.rows == expected.rows
    assert _counter("trino_tpu_task_retries_total") > retries


def test_worker_announce_join_and_graceful_leave():
    """The membership endpoints end to end: a worker announces itself
    into an EMPTY coordinator (which also bootstraps detector + spool),
    re-announcement is idempotent, liveness shows in GET, and stop()
    sends the graceful leave."""
    co = Coordinator().start()
    w = TaskWorkerServer().start()
    joins = _counter("trino_tpu_worker_joins_total")
    leaves = _counter("trino_tpu_worker_leaves_total")
    try:
        assert co.live_workers() == []
        assert w.announce(co.base_uri)
        assert w.base_uri in co.live_workers()
        assert co.failure_detector is not None   # bootstrapped on join
        assert co.spool is not None
        assert _counter("trino_tpu_worker_joins_total") == joins + 1
        # idempotent: a re-announcement must not duplicate the entry
        assert announce_once(co.base_uri, w.base_uri, w.node_id)
        assert co.live_workers().count(w.base_uri) == 1
        assert _counter("trino_tpu_worker_joins_total") == joins + 1
        # calling announce() again retires the previous announcer
        # loop (fresh stop event, fresh thread) instead of leaking a
        # second beating loop
        first_loop = w._announce_thread
        first_stop = w._announce_stop
        assert w.announce(co.base_uri)
        assert first_stop.is_set()               # old loop retired
        assert w._announce_thread is not first_loop
        assert not w._announce_stop.is_set()     # new loop live
        listing = _get_json(co.base_uri + "/v1/announcement")
        mine = [e for e in listing["workers"]
                if e["uri"] == w.base_uri]
        # one entry, alive, carrying the PR 11 pre-warm readiness flag
        assert len(mine) == 1 and mine[0]["alive"] is True
        assert "prewarmed" in mine[0]
        # graceful leave rides on worker stop()
        w.stop()
        deadline = time.time() + 5
        while w.base_uri in co.workers and time.time() < deadline:
            time.sleep(0.02)
        assert w.base_uri not in co.workers
        assert _counter("trino_tpu_worker_leaves_total") == leaves + 1
    finally:
        co.stop()


def test_session_spool_backend_override_reaches_runner(workers):
    """`SET SESSION spool_backend` must reach the scheduler: the
    coordinator's runner factory routes the query's fragment spool
    through the requested backend instead of the server default."""
    from trino_tpu.fte.objectstore import ObjectStoreSpool
    co = Coordinator(worker_uris=list(workers)).start()
    try:
        s = Session(catalog="tpch", schema="tiny")
        s.set("spool_backend", "memory")
        runner = co.tracker._make_runner(s)
        assert isinstance(runner.spool, ObjectStoreSpool)
        # and the default stays on the server's spool
        default = co.tracker._make_runner(
            Session(catalog="tpch", schema="tiny"))
        assert default.spool is co.spool
    finally:
        co.stop()


def test_worker_announce_to_authenticated_coordinator():
    """An authenticated coordinator gates /v1/announcement like every
    other resource: a credential-less announce is rejected, one
    carrying the Bearer token joins (the --coordinator-token path)."""
    import time as _time

    from trino_tpu.security import JwtAuthenticator
    auth = JwtAuthenticator(b"cluster-secret")
    co = Coordinator(authenticator=auth).start()
    w = TaskWorkerServer().start()
    try:
        assert not announce_once(co.base_uri, w.base_uri, w.node_id)
        assert co.live_workers() == []
        token = auth.sign({"sub": "worker",
                           "exp": _time.time() + 300})
        assert w.announce(co.base_uri, token=token)
        assert w.base_uri in co.live_workers()
    finally:
        w.stop()
        co.stop()


# --------------------------------------------------------------------------
# single-host double-spool-write coalescing
# --------------------------------------------------------------------------

def test_commit_linked_hard_links_single_write(tmp_path):
    """The coordinator-side coalesced commit hard-links the worker's
    already-committed frames: bytes are written (and metric-counted)
    ONCE, the linked attempt reads back verbatim, and first-commit-wins
    still holds across the linked path."""
    worker = LocalDirSpool(str(tmp_path / "w"))
    coord = LocalDirSpool(str(tmp_path / "c"))
    frames = [b"0123456789" * 100, b"tail"]
    written = _counter("trino_tpu_spool_bytes_written_total")
    coalesced = _counter("trino_tpu_spool_coalesced_commits_total")
    worker.commit("task-1", 0, 0, 0, frames)
    src = worker.attempt_dir("task-1", 0, 0)
    assert coord.commit_linked("q", 3, 1, 0, src) == 0
    assert coord.read("q", 3, 1) == frames
    # byte-counted once: only the worker's physical write moved it
    assert _counter("trino_tpu_spool_bytes_written_total") - written \
        == sum(len(f) for f in frames)
    assert _counter("trino_tpu_spool_coalesced_commits_total") \
        == coalesced + 1
    # same inodes — one physical copy on disk
    for name in os.listdir(src):
        assert os.stat(os.path.join(src, name)).st_nlink >= 2
    # a late duplicate through the linked path reports the winner
    assert coord.commit_linked("q", 3, 1, 7, src) == 0
    # the source dir is worker-supplied (X-TT-Spool-Dir): linked bytes
    # that do not match the pulled frames must be refused, unpublished
    with pytest.raises(ValueError):
        coord.commit_linked("q2", 0, 0, 0, src,
                            expect_frames=[b"forged", b"frames"])
    assert coord.read("q2", 0, 0) is None
    # matching frames pass verification and publish normally
    assert coord.commit_linked("q2", 0, 0, 0, src,
                               expect_frames=frames) == 0
    assert coord.read("q2", 0, 0) == frames


def _kill_server(worker) -> None:
    """shutdown + close on a background thread: connections REFUSE
    immediately (a dead process), not a zombie listening socket."""
    def stop():
        worker._httpd.shutdown()
        worker._httpd.server_close()
    threading.Thread(target=stop, daemon=True).start()


class _DiesOnMidDagTask(TaskWorkerServer):
    """Executes leaf tasks normally, then dies the first time it
    receives an exchange-fed task — mid-flight, while other queries'
    tasks are interleaving on the surviving workers."""

    def create_task(self, tid, payload):
        stage = payload.get("stage") or {}
        if stage.get("sources") and not getattr(self, "_killed",
                                                False):
            self._killed = True
            _kill_server(self)
            raise ConnectionResetError("killed mid-interleave")
        return super().create_task(tid, payload)


def test_worker_kill_during_shared_scheduler_interleaving():
    """ISSUE 14 chaos: a worker dies while the shared split scheduler
    (exec/taskexec.py) is interleaving >= 2 concurrent queries on
    1-runner-slot survivors — both queries complete with exact
    results, and the victim's tasks are rescheduled through the
    normal per-stage retry machinery."""
    sql2 = ("SELECT r_name, count(*) FROM region "
            "GROUP BY r_name ORDER BY r_name")
    exp = {
        "q1": LocalQueryRunner(session=Session(
            catalog="tpch", schema="tiny")).execute(SQL).rows,
        "q2": LocalQueryRunner(session=Session(
            catalog="tpch", schema="tiny")).execute(sql2).rows,
    }
    bad = _DiesOnMidDagTask().start()
    # ONE runner slot each: concurrent queries' tasks genuinely
    # time-slice through the multilevel queue instead of running on
    # parallel threads
    w1 = TaskWorkerServer(task_runners=1).start()
    w2 = TaskWorkerServer(task_runners=1).start()
    retries_before = _counter("trino_tpu_task_retries_total")
    results, errs = {}, []

    def run(name, sql):
        try:
            results[name] = DistributedHostQueryRunner(
                [bad.base_uri, w1.base_uri, w2.base_uri],
                session=_task_session()).execute(sql).rows
        except Exception as e:      # noqa: BLE001
            errs.append(f"{name}: {e!r}")

    threads = [threading.Thread(target=run, args=("q1", SQL)),
               threading.Thread(target=run, args=("q2", sql2))]
    max_open = 0
    try:
        for t in threads:
            t.start()
        while any(t.is_alive() for t in threads):
            max_open = max(max_open,
                           w1.task_executor.open_tasks(),
                           w2.task_executor.open_tasks())
            time.sleep(0.005)
        for t in threads:
            t.join(60)
    finally:
        w1.stop()
        w2.stop()
        try:
            bad.stop()
        except OSError:
            pass
    assert not errs, errs
    assert results["q1"] == exp["q1"]
    assert results["q2"] == exp["q2"]
    # the victim's tasks were rescheduled, not lost
    assert _counter("trino_tpu_task_retries_total") > retries_before
    # and the scheduler really had > 1 task registered at once on a
    # single-slot worker (the interleaving this chaos targets)
    assert max_open >= 2, max_open


def test_single_host_query_spools_bytes_once(tmp_path, expected):
    """End to end on one host: workers commit task output to their
    spool, the coordinator's commit coalesces into hard links — the
    byte-written counter moves by exactly the WORKER-side writes (the
    coordinator's copy costs zero bytes), asserted against the actual
    page files on disk."""
    wdir = tmp_path / "worker-spool"
    w1 = TaskWorkerServer(spool_dir=str(wdir)).start()
    w2 = TaskWorkerServer(spool_dir=str(wdir)).start()
    written = _counter("trino_tpu_spool_bytes_written_total")
    coalesced = _counter("trino_tpu_spool_coalesced_commits_total")
    try:
        # flat-path pin: commit coalescing (X-TT-Spool-Dir hard links)
        # is the leaf-fragment path's coordinator-side double-write
        # optimization; stage tasks never re-commit at the coordinator
        # (their frames stay on the worker spools), so the stage path
        # has nothing to coalesce by construction
        sess = _task_session()
        sess.set("multistage_execution", False)
        runner = DistributedHostQueryRunner(
            [w1.base_uri, w2.base_uri], session=sess,
            spool=LocalDirSpool(str(tmp_path / "coord-spool")))
        res = runner.execute(SQL)
    finally:
        w1.stop()
        w2.stop()
    assert res.rows == expected.rows
    assert _counter("trino_tpu_spool_coalesced_commits_total") \
        > coalesced
    worker_bytes = sum(
        os.path.getsize(os.path.join(root, f))
        for root, _, files in os.walk(wdir)
        for f in files if f.startswith("page_"))
    assert worker_bytes > 0
    assert _counter("trino_tpu_spool_bytes_written_total") - written \
        == worker_bytes
