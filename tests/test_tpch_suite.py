"""All-22 TPC-H correctness suite on tpch.tiny.

Three-way cross-check per query (reference test strategy, SURVEY.md §4:
AbstractTestQueries + H2QueryRunner.java — here sqlite3 plays H2's
independent-oracle role):

  1. local engine result vs sqlite3 over identical data
  2. distributed (8-device mesh) result vs local result

Query texts are the Trino-dialect TPC-H suite; a small dialect
translator rewrites date/interval/extract/substring for sqlite.
"""

import datetime
import math
import re
import sqlite3
from decimal import Decimal

import pytest

from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
from trino_tpu.runner import LocalQueryRunner

TABLES = ["region", "nation", "supplier", "customer", "part", "partsupp",
          "orders", "lineitem"]


# --------------------------------------------------------------------------
# oracle setup
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner()


@pytest.fixture(scope="module")
def dist():
    return LocalQueryRunner(distributed=True, n_devices=8)


@pytest.fixture(scope="module")
def oracle(local):
    con = sqlite3.connect(":memory:")
    for t in TABLES:
        res = local.execute(f"SELECT * FROM {t}")
        cols = ", ".join(res.columns)
        marks = ", ".join("?" * len(res.columns))
        con.execute(f"CREATE TABLE {t} ({cols})")
        rows = [[v.isoformat() if isinstance(v, datetime.date) else
                 float(v) if isinstance(v, Decimal) else v
                 for v in row] for row in res.rows]
        con.executemany(f"INSERT INTO {t} VALUES ({marks})", rows)
    con.commit()
    return con


_MONTH_UNITS = {"day": "day", "month": "month", "year": "year"}


def to_sqlite(q: str) -> str:
    """Trino dialect -> sqlite dialect for the TPC-H query texts."""
    # date 'X' +/- interval 'N' unit  ->  date('X', '+N unit')
    q = re.sub(
        r"date\s+'(\d{4}-\d{2}-\d{2})'\s*([+-])\s*"
        r"interval\s+'(\d+)'\s+(day|month|year)",
        lambda m: f"date('{m.group(1)}', '{m.group(2)}{m.group(3)} "
                  f"{_MONTH_UNITS[m.group(4)]}')",
        q)
    # bare date literal
    q = re.sub(r"date\s+'(\d{4}-\d{2}-\d{2})'", r"'\1'", q)
    # extract(year from X) -> CAST(strftime('%Y', X) AS INTEGER)
    q = re.sub(r"extract\s*\(\s*year\s+from\s+([a-z_.]+)\s*\)",
               r"CAST(strftime('%Y', \1) AS INTEGER)", q)
    # substring(X from A for B) -> substr(X, A, B)
    q = re.sub(r"substring\s*\(\s*([a-z_.]+)\s+from\s+(\d+)\s+"
               r"for\s+(\d+)\s*\)",
               r"substr(\1, \2, \3)", q)
    # fold decimal-literal arithmetic: Trino evaluates 0.06 - 0.01
    # exactly (DECIMAL); sqlite would do float arith and exclude the
    # 0.07 boundary row set of q6
    q = re.sub(
        r"(\d+\.\d+)\s*([+-])\s*(\d+\.\d+)",
        lambda m: str(Decimal(m.group(1)) + Decimal(m.group(3)) *
                      (1 if m.group(2) == "+" else -1)),
        q)
    # q13: sqlite has no derived-table column list — alias inline
    q = q.replace("count(o_orderkey)\n",
                  "count(o_orderkey) as c_count\n")
    q = re.sub(r"\)\s*as\s+c_orders\s*\(\s*c_custkey,\s*c_count\s*\)",
               ") as c_orders", q)
    return q


def norm_row(row):
    out = []
    for v in row:
        if isinstance(v, datetime.date):
            out.append(v.isoformat())
        elif isinstance(v, Decimal):
            out.append(float(v))
        else:
            out.append(v)
    return out


def assert_rows_equal(got, want, qn, ordered):
    assert len(got) == len(want), \
        f"q{qn}: {len(got)} rows vs oracle {len(want)}"
    if not ordered:
        key = lambda r: tuple((x is None, str(type(x)), x) for x in r)
        got = sorted(got, key=key)
        want = sorted(want, key=key)
    for i, (g, w) in enumerate(zip(got, want)):
        assert len(g) == len(w), f"q{qn} row {i}: arity"
        for a, b in zip(g, w):
            if isinstance(a, float) or isinstance(b, float):
                if a is None or b is None:
                    assert a is None and b is None, f"q{qn} row {i}"
                else:
                    assert math.isclose(float(a), float(b),
                                        rel_tol=1e-6, abs_tol=1e-6), \
                        f"q{qn} row {i}: {a} != {b}"
            else:
                assert a == b, f"q{qn} row {i}: {a!r} != {b!r}"


_HAS_ORDER = {qn: "order by" in q for qn, q in TPCH_QUERIES.items()}


# --------------------------------------------------------------------------
# tier 1: local vs sqlite oracle
# --------------------------------------------------------------------------

# q21 (4-way join + two correlated EXISTS probes) dominates the corpus
# wall (~70s on the 1-core CI host) -> slow-swept; the other 21 stay tier-1
@pytest.mark.parametrize(
    "qn", [pytest.param(q, marks=pytest.mark.slow) if q == 21 else q
           for q in sorted(TPCH_QUERIES)])
def test_tpch_local_vs_oracle(local, oracle, qn):
    got = [norm_row(r) for r in local.execute(TPCH_QUERIES[qn]).rows]
    want = [list(r) for r in
            oracle.execute(to_sqlite(TPCH_QUERIES[qn])).fetchall()]
    assert_rows_equal(got, want, qn, ordered=_HAS_ORDER[qn])


# --------------------------------------------------------------------------
# tier 2: distributed == local
# --------------------------------------------------------------------------
# Each distributed query costs ~30-90s of XLA CPU compile on the
# 8-device mesh, so the default run covers a representative subset
# (agg, join+agg+sort, filter-agg, semi-join shapes). Set
# TRINO_TPU_FULL_DIST=1 to sweep all 22 (done per round; see commit log).
import os

_DIST_DEFAULT = (1, 3, 6, 12, 13, 18)
_DIST_QUERIES = (sorted(TPCH_QUERIES)
                 if os.environ.get("TRINO_TPU_FULL_DIST") == "1"
                 else list(_DIST_DEFAULT))


@pytest.mark.slow
@pytest.mark.parametrize("qn", _DIST_QUERIES)
def test_tpch_distributed_matches_local(local, dist, qn):
    lres = [norm_row(r) for r in local.execute(TPCH_QUERIES[qn]).rows]
    dres = [norm_row(r) for r in dist.execute(TPCH_QUERIES[qn]).rows]
    assert_rows_equal(dres, lres, qn, ordered=_HAS_ORDER[qn])


# --------------------------------------------------------------------------
# tier 3: PARTITIONED join distribution == local
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_partitioned_join_matches_local(local):
    """Forced-PARTITIONED joins repartition both sides by key hash and
    join shard-locally (DetermineJoinDistributionType PARTITIONED
    branch; exec/distributed.py _partitioned_join)."""
    dist = LocalQueryRunner(distributed=True, n_devices=8)
    dist.execute("SET SESSION join_distribution_type = 'PARTITIONED'")
    q = ("SELECT n_name, count(*) AS c FROM nation JOIN customer "
         "ON c_nationkey = n_nationkey GROUP BY n_name ORDER BY 1")
    lres = local.execute(q).rows
    dres = dist.execute(q).rows
    assert lres == dres
    # plan records the forced distribution
    p = dist.plan_sql(
        "SELECT count(*) FROM orders JOIN lineitem "
        "ON l_orderkey = o_orderkey")
    from trino_tpu.plan.nodes import JoinNode

    def find(n):
        if isinstance(n, JoinNode):
            return n
        for s in n.sources:
            j = find(s)
            if j is not None:
                return j
        return None

    assert find(p).distribution == "partitioned"
