"""Parser tests (reference model: core/trino-parser tests,
io/trino/sql/parser/TestSqlParser.java — same coverage intent, new cases)."""

import pytest

from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
from trino_tpu.sql import ast as A
from trino_tpu.sql.parser import parse_expression, parse_statement
from trino_tpu.sql.tokenizer import ParseError, tokenize


def test_tokenizer_basics():
    toks = tokenize("SELECT a_b, 'it''s', \"Q\" -- c\n1.5 /*x*/ <> 2e3")
    kinds = [(t.kind, t.value) for t in toks]
    assert ("ident", "select") in kinds
    assert ("string", "it's") in kinds
    assert ("qident", "Q") in kinds
    assert ("decimal", "1.5") in kinds
    assert ("float", "2e3") in kinds
    assert ("op", "<>") in kinds


@pytest.mark.parametrize("qid", sorted(TPCH_QUERIES))
def test_parse_all_tpch(qid):
    stmt = parse_statement(TPCH_QUERIES[qid])
    assert isinstance(stmt, A.QueryStatement)


def test_precedence():
    e = parse_expression("1 + 2 * 3")
    assert isinstance(e, A.BinaryOp) and e.op == "+"
    assert isinstance(e.right, A.BinaryOp) and e.right.op == "*"
    e = parse_expression("a or b and not c = d")
    assert e.op == "or"
    assert e.right.op == "and"
    assert isinstance(e.right.right, A.UnaryOp)


def test_between_and_in():
    e = parse_expression("x between 1 and 2 or y in (3, 4)")
    assert e.op == "or"
    assert isinstance(e.left, A.Between)
    assert isinstance(e.right, A.InList)
    e = parse_expression("x not in (select y from t)")
    assert isinstance(e, A.InSubquery) and e.negated


def test_case_desugar():
    e = parse_expression("case x when 1 then 'a' else 'b' end")
    assert isinstance(e, A.Case)
    cond = e.whens[0][0]
    assert isinstance(cond, A.BinaryOp) and cond.op == "="


def test_join_tree():
    s = parse_statement(
        "select * from a join b on a.x = b.x left join c using (y)")
    spec = s.query.body
    j = spec.from_
    assert isinstance(j, A.Join) and j.join_type == "left"
    assert j.using == ("y",)
    assert isinstance(j.left, A.Join) and j.left.join_type == "inner"


def test_implicit_cross_join():
    s = parse_statement("select * from a, b, c")
    j = s.query.body.from_
    assert isinstance(j, A.Join) and j.join_type == "cross"


def test_window():
    s = parse_statement(
        "select sum(x) over (partition by g order by t "
        "rows between 2 preceding and current row) from t")
    f = s.query.body.select_items[0].expr
    assert f.window is not None
    assert f.window.frame.unit == "rows"
    assert f.window.frame.start_type == "preceding"


def test_set_ops_and_with():
    s = parse_statement(
        "with t as (select 1 x) select x from t union all "
        "select 2 order by 1 limit 5")
    q = s.query
    assert isinstance(q.body, A.SetOperation)
    assert not q.body.distinct
    assert q.limit == 5
    assert q.with_queries[0].name == "t"


def test_grouping_sets():
    s = parse_statement("select a, b, sum(c) from t group by rollup (a, b)")
    g = s.query.body.group_by
    assert len(g.sets) == 3
    s = parse_statement("select a, b from t group by cube (a, b)")
    assert len(s.query.body.group_by.sets) == 4
    s = parse_statement(
        "select a, b from t group by grouping sets ((a), (a, b), ())")
    assert len(s.query.body.group_by.sets) == 3


def test_statements():
    assert isinstance(parse_statement("show catalogs"), A.ShowCatalogs)
    assert isinstance(parse_statement("explain select 1"), A.Explain)
    st = parse_statement("set session a.b = 4")
    assert isinstance(st, A.SetSession) and st.name == "a.b"
    ct = parse_statement(
        "create table t (a bigint not null, b decimal(10,2))")
    assert ct.columns[1].type_name == "decimal(10,2)"
    ins = parse_statement("insert into t select * from u")
    assert isinstance(ins, A.Insert)
    d = parse_statement("delete from t where x = 1")
    assert isinstance(d, A.Delete) and d.where is not None
    u = parse_statement("use tpch.sf1")
    assert u.catalog == "tpch" and u.schema == "sf1"


def test_errors():
    with pytest.raises(ParseError):
        parse_statement("select * frm t")
    with pytest.raises(ParseError):
        parse_statement("select 'unterminated")
    with pytest.raises(ParseError):
        parse_statement("select a from t join u")  # missing ON/USING


def test_quoted_identifiers_preserve_case():
    s = parse_statement('select "MixedCase" from "T"')
    item = s.query.body.select_items[0].expr
    assert item.parts == ("MixedCase",)


def test_literals():
    assert parse_expression("date '2020-01-02'") == A.Literal(
        "2020-01-02", "date")
    iv = parse_expression("interval '3' month")
    assert isinstance(iv, A.IntervalLiteral) and iv.unit == "month"
    assert parse_expression("null") == A.Literal(None)
    assert parse_expression("1.5").type_name == "decimal"


def test_intersect_binds_tighter_than_union():
    s = parse_statement("select 1 union select 2 intersect select 3")
    b = s.query.body
    assert b.op == "union"
    assert b.right.op == "intersect"


def test_is_true_three_valued():
    e = parse_expression("x is not true")
    assert isinstance(e, A.IsDistinctFrom) and not e.negated
    e = parse_expression("x is true")
    assert isinstance(e, A.IsDistinctFrom) and e.negated


def test_nested_type_names():
    s = parse_statement("select cast(x as array(decimal(10,2))) from t")
    assert s.query.body.select_items[0].expr.type_name == \
        "array(decimal(10,2))"
    s = parse_statement("select cast(x as map(varchar, array(bigint))) "
                        "from t")
    assert s.query.body.select_items[0].expr.type_name == \
        "map(varchar, array(bigint))"
