"""Cluster infrastructure: events, resource groups, system.runtime,
kill_query, security, failure detection, web UI, graceful drain.

Reference parity: spi/eventlistener + event/QueryMonitor,
execution/resourcegroups/InternalResourceGroup,
connector/system (QuerySystemTable / KillQueryProcedure),
server/security + security/AccessControlManager,
failuredetector/HeartbeatFailureDetector, server/ui,
server/GracefulShutdownHandler.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from trino_tpu.exec import QueryError
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.security import (AccessDeniedError, AccessRule,
                                InMemoryPasswordAuthenticator,
                                RuleBasedAccessControl,
                                load_password_file)
from trino_tpu.server.coordinator import Coordinator
from trino_tpu.server.events import (EventListener, EventListenerManager,
                                     QueryCompletedEvent,
                                     QueryCreatedEvent)
from trino_tpu.server.failure import HeartbeatFailureDetector
from trino_tpu.server.resourcegroups import (QueryQueueFullError,
                                             ResourceGroup,
                                             ResourceGroupManager)


def _get(uri, headers=None):
    req = urllib.request.Request(uri, headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as r:
        body = r.read()
    return r.status, body


def _post(uri, data, headers=None):
    req = urllib.request.Request(uri, data=data.encode(),
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _run_sql(base, sql, headers=None):
    out = _post(base + "/v1/statement", sql, headers)
    while "nextUri" in out:
        _, body = _get(out["nextUri"], headers)
        out = json.loads(body)
    return out


# --- events ---------------------------------------------------------------

class _Recorder(EventListener):
    def __init__(self):
        self.created = []
        self.completed = []

    def query_created(self, event):
        self.created.append(event)

    def query_completed(self, event):
        self.completed.append(event)


def test_event_listener_lifecycle():
    rec = _Recorder()
    co = Coordinator(event_listeners=[rec]).start()
    try:
        out = _run_sql(co.base_uri, "SELECT 1")
        assert out["stats"]["state"] == "FINISHED"
        deadline = time.time() + 5
        while not rec.completed and time.time() < deadline:
            time.sleep(0.02)
        assert len(rec.created) == 1
        assert isinstance(rec.created[0], QueryCreatedEvent)
        done = rec.completed[0]
        assert isinstance(done, QueryCompletedEvent)
        assert done.state == "FINISHED" and done.rows == 1
    finally:
        co.stop()


def test_event_listener_error_isolated():
    class Bomb(EventListener):
        def query_created(self, event):
            raise RuntimeError("boom")
    mgr = EventListenerManager()
    mgr.add_listener(Bomb())
    mgr.query_created(QueryCreatedEvent("q", "SELECT 1", "u", None,
                                        None))   # must not raise


# --- resource groups ------------------------------------------------------

def test_resource_group_concurrency_and_queueing():
    mgr = ResourceGroupManager()
    g = mgr.root.add(ResourceGroup("small", hard_concurrency=1,
                                   max_queued=1))
    mgr.add_selector(g, user_regex="alice")
    order = []

    def first(group):
        order.append("first")

    def second(group):
        order.append("second")

    grp, started = mgr.submit("alice", "", first)
    assert started and order == ["first"]
    grp2, started2 = mgr.submit("alice", "", second)
    assert not started2 and order == ["first"]     # queued
    with pytest.raises(QueryQueueFullError):
        mgr.submit("alice", "", lambda group: None)    # queue full
    mgr.query_finished(grp)
    assert order == ["first", "second"]
    mgr.query_finished(grp2)
    assert g.running == 0


def test_resource_group_from_config_and_selectors():
    mgr = ResourceGroupManager.from_config({
        "rootGroups": [
            {"name": "adhoc", "hardConcurrencyLimit": 5},
            {"name": "etl", "hardConcurrencyLimit": 2,
             "subGroups": [{"name": "nightly"}]},
        ],
        "selectors": [
            {"user": "etl_.*", "group": "etl.nightly"},
            {"group": "adhoc"},
        ]})
    assert mgr.select("etl_loader").full_name == "global.etl.nightly"
    assert mgr.select("bob").full_name == "global.adhoc"


def test_resource_groups_on_coordinator():
    mgr = ResourceGroupManager()
    g = mgr.root.add(ResourceGroup("all", hard_concurrency=2))
    mgr.add_selector(g)
    co = Coordinator(resource_groups=mgr).start()
    try:
        out = _run_sql(co.base_uri, "SELECT count(*) FROM "
                                    "tpch.tiny.nation")
        assert out["data"] == [[25]]
        rows = _run_sql(co.base_uri,
                        "SELECT name, hard_concurrency_limit FROM "
                        "system.runtime.resource_groups "
                        "WHERE name = 'global.all'")
        assert rows["data"] == [["global.all", 2]]
    finally:
        co.stop()


# --- system.runtime + kill_query ------------------------------------------

def test_system_runtime_queries_and_nodes():
    co = Coordinator().start()
    try:
        _run_sql(co.base_uri, "SELECT 42")
        out = _run_sql(co.base_uri,
                       "SELECT state, query FROM "
                       "system.runtime.queries "
                       "WHERE query LIKE '%42%'")
        states = [r[0] for r in out["data"]]
        assert "FINISHED" in states
        nodes = _run_sql(co.base_uri, "SELECT node_id, coordinator "
                                      "FROM system.runtime.nodes")
        assert nodes["data"][0][1] is True
    finally:
        co.stop()


def test_kill_query_procedure():
    co = Coordinator().start()
    try:
        # a long query: big cross join aggregated
        slow_sql = ("SELECT count(*) FROM tpch.sf1.lineitem a, "
                    "tpch.sf1.lineitem b WHERE a.l_orderkey = "
                    "b.l_orderkey AND a.l_suppkey + b.l_suppkey > 1")
        out = _post(co.base_uri + "/v1/statement", slow_sql)
        qid = out["id"]
        killed = _run_sql(
            co.base_uri,
            f"CALL system.runtime.kill_query('{qid}')")
        assert killed.get("error") is None
        deadline = time.time() + 20
        q = co.tracker.get(qid)
        while q.state not in ("CANCELED", "FINISHED", "FAILED") \
                and time.time() < deadline:
            time.sleep(0.05)
        assert q.state in ("CANCELED", "FINISHED")
    finally:
        co.stop()


# --- security -------------------------------------------------------------

def test_password_authenticator():
    auth = InMemoryPasswordAuthenticator({"alice": "secret"})
    assert auth.authenticate("alice", "secret")
    assert not auth.authenticate("alice", "wrong")
    assert not auth.authenticate("bob", "secret")
    auth2 = load_password_file("bob:pw123\n# comment\n")
    assert auth2.authenticate("bob", "pw123")


def test_http_basic_auth():
    import base64
    auth = InMemoryPasswordAuthenticator({"alice": "secret"})
    co = Coordinator(authenticator=auth).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(co.base_uri + "/v1/info")
        assert e.value.code == 401
        cred = base64.b64encode(b"alice:secret").decode()
        status, _ = _get(co.base_uri + "/v1/info",
                         {"Authorization": f"Basic {cred}"})
        assert status == 200
    finally:
        co.stop()


def test_authenticated_principal_binds_session_user():
    import base64
    auth = InMemoryPasswordAuthenticator({"alice": "pw"})
    co = Coordinator(authenticator=auth).start()
    try:
        cred = base64.b64encode(b"alice:pw").decode()
        req = urllib.request.Request(
            co.base_uri + "/v1/statement", data=b"SELECT 1",
            headers={"Authorization": f"Basic {cred}",
                     "X-Trino-User": "admin"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 403      # no impersonation via header
        req = urllib.request.Request(
            co.base_uri + "/v1/statement", data=b"SELECT 1",
            headers={"Authorization": f"Basic {cred}",
                     "X-Trino-User": "alice"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
    finally:
        co.stop()


def test_access_control_rules():
    ac = RuleBasedAccessControl([
        AccessRule(user="alice", table=r"tpch\..*",
                   privileges=("select",)),
    ])
    ac.check_can_select("alice", "tpch", "tiny", "nation")
    with pytest.raises(AccessDeniedError):
        ac.check_can_select("bob", "tpch", "tiny", "nation")
    with pytest.raises(AccessDeniedError):
        ac.check_can_insert("alice", "tpch", "tiny", "nation")


def test_access_control_enforced_in_engine():
    from trino_tpu.session import Session
    runner = LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny", user="bob"))
    runner.catalogs.access_control = RuleBasedAccessControl([
        AccessRule(user="alice", table=".*"),
    ])
    with pytest.raises(QueryError, match="Access Denied"):
        runner.execute("SELECT * FROM tpch.tiny.nation")
    runner.session.user = "alice"
    assert len(runner.execute(
        "SELECT * FROM tpch.tiny.region").rows) == 5


# --- failure detector -----------------------------------------------------

def test_failure_detector_decay():
    health = {"w1": True, "w2": True}
    det = HeartbeatFailureDetector(
        probe=lambda uri: health[uri], warmup_probes=2)
    det.add_service("w1")
    det.add_service("w2")
    for _ in range(5):
        det.probe_once()
    assert det.is_alive("w1") and det.is_alive("w2")
    health["w2"] = False
    for _ in range(10):
        det.probe_once()
    assert det.is_alive("w1")
    assert not det.is_alive("w2")
    assert det.failed() == ["w2"]


def test_failure_detector_http_probe():
    co = Coordinator().start()
    det = HeartbeatFailureDetector()
    det.add_service(co.base_uri)
    det.add_service("http://127.0.0.1:1")      # nothing listens
    for _ in range(5):
        det.probe_once()
    assert det.is_alive(co.base_uri)
    assert not det.is_alive("http://127.0.0.1:1")
    co.stop()


# --- web UI + cluster stats + drain ---------------------------------------

def test_web_ui_and_cluster_stats():
    co = Coordinator().start()
    try:
        status, body = _get(co.base_uri + "/ui")
        assert status == 200 and b"trino-tpu" in body
        _run_sql(co.base_uri, "SELECT 1")
        status, body = _get(co.base_uri + "/v1/cluster")
        stats = json.loads(body)
        assert stats["totalQueries"] >= 1
    finally:
        co.stop()


def test_graceful_drain():
    co = Coordinator().start()
    _run_sql(co.base_uri, "SELECT 1")
    assert co.drain(timeout=10.0)


def test_leak_report_clean_and_detects():
    """Leak analogs (round-4 verdict §5: 'race detection / leak
    analogs: no'): stuck-query sweep, orphaned query threads, spill
    files, scan-cache residency."""
    import time
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.catalog import CatalogManager
    from trino_tpu.server.coordinator import Coordinator

    class SlowTpch(TpchConnector):
        def read_split(self, split, columns):
            time.sleep(4)
            return super().read_split(split, columns)

    cats = CatalogManager()
    cats.register("tpch", SlowTpch())
    coord = Coordinator(catalogs=cats).start()
    try:
        # a finished query: report is clean (no stuck, no orphans)
        # use the in-process tracker directly to avoid a second server
        from trino_tpu.session import Session
        q = coord.tracker.submit("SELECT 1", Session(catalog="tpch",
                                                     schema="tiny"))
        q.wait_done(60)
        rep = coord.leak_report()
        assert not rep.stuck_queries
        assert not rep.orphaned_threads
        assert rep.retained_results_bytes >= 0

        # a slow query canceled mid-scan: its thread outlives the
        # terminal state -> orphan; and with threshold 0 a RUNNING
        # query counts as stuck
        q2 = coord.tracker.submit(
            "SELECT count(*) FROM nation",
            Session(catalog="tpch", schema="tiny"))
        time.sleep(0.5)
        assert coord.leak_report(stuck_after_s=0.1).stuck_queries
        q2.do_cancel()
        # grace 0: the canceled query's thread is still in the slow
        # scan, which is exactly the orphan shape
        rep = coord.leak_report(orphan_grace_s=0.0)
        assert any("query" in t for t in rep.orphaned_threads)
        q2_thread_done = q2.wait_done(30)
        assert q2_thread_done
    finally:
        coord.stop()


def test_thread_leak_guard():
    import threading
    import time
    from trino_tpu.server.diagnostics import ThreadLeakGuard

    with ThreadLeakGuard(grace_s=1.0) as g:
        t = threading.Thread(target=lambda: time.sleep(0.1))
        t.start()
        t.join()
    assert g.leaked == []

    ev = threading.Event()
    with ThreadLeakGuard(grace_s=0.3) as g:
        t = threading.Thread(target=ev.wait, name="leaky")
        t.start()
    assert any("leaky" in n for n in g.leaked)
    ev.set()
    t.join()
