"""First real coverage for formats/record_decoder.py (PR 20
satellite): the decoders feed the streaming ingest path, where a
malformed producer payload must decode to NULL-lane rows — never an
error that could wedge a continuous query's cycle."""

import pytest

from trino_tpu.formats.record_decoder import (CsvRowDecoder,
                                              DecoderField,
                                              JsonRowDecoder,
                                              RawRowDecoder,
                                              create_decoder)
from trino_tpu.types import BIGINT, BOOLEAN, DOUBLE, VARCHAR


def _rows(batch):
    return batch.to_pylist()


# --- json ------------------------------------------------------------------

def test_json_decodes_fields_and_paths():
    dec = JsonRowDecoder([
        DecoderField("k", BIGINT),
        DecoderField("nested", DOUBLE, "a.b"),
        DecoderField("first", VARCHAR, "tags/0"),
    ])
    rows = _rows(dec.decode([
        b'{"k": 1, "a": {"b": 2.5}, "tags": ["x", "y"]}',
        b'{"k": 2, "a": {}, "tags": []}',
    ]))
    assert rows == [[1, 2.5, "x"], [2, None, None]]


def test_json_malformed_message_is_null_lane_row_not_error():
    """The lenient-mode contract: undecodable messages land as
    all-NULL rows so one bad producer payload cannot fail a scan."""
    dec = JsonRowDecoder([DecoderField("k", BIGINT),
                          DecoderField("v", VARCHAR)])
    rows = _rows(dec.decode([
        b'{"k": 1, "v": "ok"}',
        b'{"k": truncated',          # malformed json
        b"\xff\xfe not even text",   # invalid utf-8
        b"",                         # empty message
        b'{"k": 2, "v": "also ok"}',
    ]))
    assert rows[0] == [1, "ok"]
    assert rows[1] == [None, None]
    assert rows[2] == [None, None]
    assert rows[3] == [None, None]
    assert rows[4] == [2, "also ok"]


def test_json_type_coercion_failures_are_null_not_error():
    dec = JsonRowDecoder([DecoderField("n", BIGINT),
                          DecoderField("b", BOOLEAN),
                          DecoderField("s", VARCHAR)])
    rows = _rows(dec.decode([
        b'{"n": "not-a-number", "b": "true", "s": {"obj": 1}}',
    ]))
    # unparseable bigint -> NULL; "true" -> True; non-string value is
    # re-serialized into the varchar lane rather than dropped
    assert rows == [[None, True, '{"obj": 1}']]


# --- csv -------------------------------------------------------------------

def test_csv_decodes_by_index_mapping():
    dec = CsvRowDecoder([DecoderField("name", VARCHAR, "0"),
                         DecoderField("qty", BIGINT, "1")])
    rows = _rows(dec.decode([b"widget,3", b'"a,b",7']))
    assert rows == [["widget", 3], ["a,b", 7]]


def test_csv_requires_numeric_mapping():
    """A silent default index would decode column 0 into every
    misconfigured field — construction must refuse instead."""
    with pytest.raises(ValueError, match="numeric mapping"):
        CsvRowDecoder([DecoderField("name", VARCHAR)])
    with pytest.raises(ValueError, match="numeric mapping"):
        CsvRowDecoder([DecoderField("name", VARCHAR, "zero")])


def test_csv_nul_invalid_utf8_and_short_rows_are_null_lanes():
    dec = CsvRowDecoder([DecoderField("a", VARCHAR, "0"),
                         DecoderField("n", BIGINT, "1")])
    rows = _rows(dec.decode([
        b"ok,1",
        b"x\x00y,2",          # embedded NUL (csv module rejects)
        b"\xff\xfe,3",        # invalid utf-8 (replacement chars)
        b"only-one-field",    # short row: missing index -> NULL
        b"",                  # empty message -> no fields at all
    ]))
    assert rows[0] == ["ok", 1]
    # NUL and replacement-decoded rows must not raise; every lane that
    # could not be extracted is NULL, extracted lanes keep their value
    assert rows[1][1] in (2, None)
    assert rows[2][1] in (3, None)
    assert rows[3] == ["only-one-field", None]
    assert rows[4] == [None, None]


# --- raw + factory ---------------------------------------------------------

def test_raw_whole_message_single_field():
    dec = RawRowDecoder([DecoderField("_message", VARCHAR)])
    rows = _rows(dec.decode([b"hello", b"\xffworld"]))
    assert rows[0] == ["hello"]
    assert "world" in rows[1][0]    # invalid byte replaced, not fatal


def test_create_decoder_dispatch_and_unknown_kind():
    assert isinstance(
        create_decoder("json", [DecoderField("k", BIGINT)]),
        JsonRowDecoder)
    assert isinstance(
        create_decoder("csv", [DecoderField("k", BIGINT, "0")]),
        CsvRowDecoder)
    assert isinstance(
        create_decoder("raw", [DecoderField("m", VARCHAR)]),
        RawRowDecoder)
    with pytest.raises(ValueError, match="unknown decoder"):
        create_decoder("avro", [DecoderField("k", BIGINT)])
