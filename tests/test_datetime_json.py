"""TIME type, unix-time / MySQL-format datetime functions, JSON
functions, nth_value.

Reference parity: spi/type/TimeType.java,
operator/scalar/DateTimeFunctions.java (from_unixtime/to_unixtime/
date_format/date_parse), operator/scalar/JsonFunctions.java,
operator/window/NthValueFunction.java.
"""

import datetime

import pytest

from trino_tpu.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def q(runner, sql):
    return runner.execute(sql).rows


def test_time_literal_and_fields(runner):
    got = q(runner, "SELECT TIME '10:30:45', hour(TIME '10:30:45'), "
                    "minute(TIME '10:30:45'), second(TIME '10:30:45')")
    assert got == [[datetime.time(10, 30, 45), 10, 30, 45]]


def test_time_compare_cast_minmax(runner):
    got = q(runner, "SELECT TIME '11:00:00' > TIME '10:30:00', "
                    "CAST('09:08:07' AS time)")
    assert got == [[True, datetime.time(9, 8, 7)]]
    got = q(runner, "SELECT min(t), max(t) FROM (VALUES TIME '10:00:00',"
                    " TIME '09:00:00', NULL) x(t)")
    assert got == [[datetime.time(9), datetime.time(10)]]


def test_unixtime_roundtrip(runner):
    got = q(runner, "SELECT to_unixtime(from_unixtime(12345))")
    assert got == [[12345.0]]
    got = q(runner, "SELECT from_unixtime(86400)")
    assert got == [[datetime.datetime(1970, 1, 2)]]


def test_date_format_parse(runner):
    got = q(runner, "SELECT date_format(TIMESTAMP '2020-03-01 10:30:00',"
                    " '%Y-%m-%d %H:%i'), "
                    "date_format(DATE '2021-06-15', '%W'), "
                    "date_parse('2020-03-01 10:30', '%Y-%m-%d %H:%i')")
    assert got == [['2020-03-01 10:30', 'Tuesday',
                    datetime.datetime(2020, 3, 1, 10, 30)]]


def test_date_parse_bad_input_null(runner):
    got = q(runner, "SELECT date_parse(x, '%Y-%m-%d') FROM "
                    "(VALUES 'nope', '2020-01-02') t(x) ORDER BY 1")
    # NULLS LAST is the engine default for ASC (Trino semantics)
    assert got == [[datetime.datetime(2020, 1, 2)], [None]]


def test_json_extract_scalar(runner):
    got = q(runner, """SELECT json_extract_scalar(j, '$.name'),
        json_extract_scalar(j, '$.tags[1]'),
        json_extract_scalar(j, '$.missing'),
        json_array_length(json_extract(j, '$.tags')),
        json_size(j, '$')
        FROM (VALUES '{"name": "ab", "tags": ["x", "y"], "n": 3}') t(j)
    """)
    assert got == [['ab', 'y', None, 2, 3]]


def test_json_invalid_and_types(runner):
    got = q(runner, "SELECT json_extract_scalar('not json', '$.a'), "
                    "json_extract_scalar('[1,2,3]', '$[2]'), "
                    "json_extract_scalar('{\"b\": true}', '$.b')")
    assert got == [[None, '3', 'true']]


def test_nth_value(runner):
    got = q(runner, "SELECT x, nth_value(x, 2) OVER "
                    "(ORDER BY x ROWS BETWEEN UNBOUNDED PRECEDING AND "
                    "UNBOUNDED FOLLOWING) FROM (VALUES 10, 20, 30) t(x)")
    assert got == [[10, 20], [20, 20], [30, 20]]
    # running frame: nth row not yet visible -> NULL
    got = q(runner, "SELECT x, nth_value(x, 3) OVER (ORDER BY x) "
                    "FROM (VALUES 1, 2, 3) t(x) ORDER BY x")
    assert got == [[1, None], [2, None], [3, 3]]


def test_nth_value_partitioned(runner):
    got = q(runner, "SELECT DISTINCT n_regionkey, nth_value(n_name, 2) "
                    "OVER (PARTITION BY n_regionkey ORDER BY n_nationkey"
                    " ROWS BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED "
                    "FOLLOWING) FROM tpch.tiny.nation ORDER BY 1")
    assert len(got) == 5
    assert got[0][1] == 'ETHIOPIA'
