"""Default-on MPP corpus: TPCH + TPCDS through the stage-DAG engine.

Since PR 13 ``multistage_execution`` defaults ON — every distributed
query a DistributedHostQueryRunner executes rides the stage scheduler
(eager pipelining included) unless the fragmenter declines the shape.
This suite proves distributed == local across the whole query corpus
under the DEFAULT session (no knobs): all 22 TPC-H queries in tier 1,
a curated TPC-DS subset covering the shapes PR 13 made fragmentable
(grouping sets / ROLLUP, semi joins, cross joins) in tier 1, and the
full 99-query TPC-DS sweep under the ``slow`` marker.

Comparison discipline follows tests/test_tpch_suite.py: exact for
ordered results, sorted-multiset otherwise, float columns compared
with a relative tolerance (per-task partial aggregation legitimately
reorders float reductions).
"""

import datetime
import math
from decimal import Decimal

import pytest

from trino_tpu.benchmarks.tpcds_queries import TPCDS_QUERIES
from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
from trino_tpu.exec.remote import DistributedHostQueryRunner
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.server.task_worker import TaskWorkerServer
from trino_tpu.session import Session


@pytest.fixture(scope="module")
def workers():
    ws = [TaskWorkerServer().start() for _ in range(2)]
    yield [w.base_uri for w in ws]
    for w in ws:
        w.stop()


@pytest.fixture(scope="module")
def tpch_local():
    return LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny"))


@pytest.fixture(scope="module")
def tpcds_local():
    return LocalQueryRunner(
        session=Session(catalog="tpcds", schema="tiny"))


def norm_row(row):
    out = []
    for v in row:
        if isinstance(v, datetime.date):
            out.append(v.isoformat())
        elif isinstance(v, Decimal):
            out.append(float(v))
        else:
            out.append(v)
    return out


def assert_rows_equal(got, want, label, ordered):
    assert len(got) == len(want), \
        f"{label}: {len(got)} rows vs local {len(want)}"
    if not ordered:
        key = lambda r: tuple((x is None, str(type(x)), x)   # noqa: E731
                              for x in r)
        got = sorted(got, key=key)
        want = sorted(want, key=key)
    for i, (g, w) in enumerate(zip(got, want)):
        assert len(g) == len(w), f"{label} row {i}: arity"
        for a, b in zip(g, w):
            if isinstance(a, float) or isinstance(b, float):
                if a is None or b is None:
                    assert a is None and b is None, f"{label} row {i}"
                else:
                    assert math.isclose(float(a), float(b),
                                        rel_tol=1e-6, abs_tol=1e-6), \
                        f"{label} row {i}: {a} != {b}"
            else:
                assert a == b, f"{label} row {i}: {a!r} != {b!r}"


def _dist_check(workers, local, sql, label, catalog, schema):
    """DEFAULT session — the whole point: no multistage knob set."""
    dist = DistributedHostQueryRunner(
        workers, session=Session(catalog=catalog, schema=schema))
    got = [norm_row(r) for r in dist.execute(sql).rows]
    want = [norm_row(r) for r in local.execute(sql).rows]
    assert_rows_equal(got, want, label,
                      ordered="order by" in sql.lower())


# --------------------------------------------------------------------------
# TPC-H: all 22, tier 1
# --------------------------------------------------------------------------

@pytest.mark.parametrize("qn", sorted(TPCH_QUERIES))
def test_tpch_mpp_default_on_matches_local(workers, tpch_local, qn):
    _dist_check(workers, tpch_local, TPCH_QUERIES[qn], f"tpch q{qn}",
                "tpch", "tiny")


# --------------------------------------------------------------------------
# TPC-DS: newly-fragmentable shapes in tier 1, full sweep slow
# --------------------------------------------------------------------------

# grouping sets / rollup (5, 18, 22, 27, 77, 80), semi joins via
# IN/EXISTS subqueries (10, 16, 33, 69), cross-ish/self joins (1),
# plus plain join+agg sanity (3, 7, 42). The two heaviest rollup
# queries (36, 67 — an order of magnitude slower than the rest of the
# subset) ride the slow sweep instead: tier-1 wall budget.
_TPCDS_TIER1 = (1, 3, 5, 7, 10, 16, 18, 22, 27, 33, 42, 69, 77, 80)


@pytest.mark.parametrize("qn", _TPCDS_TIER1)
def test_tpcds_mpp_default_on_matches_local(workers, tpcds_local, qn):
    _dist_check(workers, tpcds_local, TPCDS_QUERIES[qn],
                f"tpcds q{qn}", "tpcds", "tiny")


@pytest.mark.slow
@pytest.mark.parametrize("qn", [q for q in sorted(TPCDS_QUERIES)
                                if q not in _TPCDS_TIER1])
def test_tpcds_mpp_full_sweep_matches_local(workers, tpcds_local, qn):
    _dist_check(workers, tpcds_local, TPCDS_QUERIES[qn],
                f"tpcds q{qn}", "tpcds", "tiny")
