"""Worker-side multi-query runtime (PR 14): the shared split
scheduler (exec/taskexec.py), live memory feedback into the cluster
pool, cross-query cache governance under pressure, and the BUSY load
shed.

The acceptance battery lives here: K >> runner-threads concurrent
queries all make progress (no starvation), weighted groups drain
proportional split quanta, and a memory-hog query running ON a worker
is killed with CLUSTER_OUT_OF_MEMORY from worker-streamed live
reservations — its worker task actually DELETEd — while a concurrent
small query completes.
"""

import threading
import time

import pytest

from trino_tpu.catalog import CatalogManager
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.exec.remote import DistributedHostQueryRunner
from trino_tpu.exec.taskexec import (LEVEL_THRESHOLDS_S,
                                     TaskCanceledError, TaskExecutor)
from trino_tpu.obs.metrics import METRICS
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.server.coordinator import QueryTracker
from trino_tpu.server.memory import (ClusterMemoryManager,
                                     ClusterMemoryPool)
from trino_tpu.server.task_worker import (RemoteTaskClient,
                                          TaskWorkerServer)
from trino_tpu.session import Session


def _counter(name: str, **labels) -> float:
    return METRICS.counter(name).value(**labels)


def _wait_until(pred, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


# --------------------------------------------------------------------------
# TaskExecutor units: priority, decay, fair share, no starvation
# --------------------------------------------------------------------------

def test_priority_prefers_weighted_fair_share():
    """Among same-level waiters, the group with the smallest WEIGHTED
    virtual time runs next: after equal raw scheduled seconds, a
    weight-3 group's virtual clock advanced 3x slower, so its task
    outranks the weight-1 group's (the WeightedFairQueue contract
    applied at the worker)."""
    ex = TaskExecutor(1)
    a = ex.register("qa", "qa.t", group="ga", weight=1.0)
    b = ex.register("qb", "qb.t", group="gb", weight=3.0)
    with ex._lock:                      # equal RAW seconds charged
        ex._charge_locked(a, 0.9)       # vtime_ga = 0.9
        ex._charge_locked(b, 0.9)       # vtime_gb = 0.3
    with ex._lock:
        assert ex._key_locked(b) < ex._key_locked(a)
    # equal virtual time (same level): the least-served QUERY runs
    # first, then arrival order
    ex.set_group_vtime("ga", 0.5)
    ex.set_group_vtime("gb", 0.5)
    ex.set_query_seconds("qa", 0.2)
    ex.set_query_seconds("qb", 0.4)
    with ex._lock:
        ka, kb = ex._key_locked(a), ex._key_locked(b)
    assert ka[:2] == kb[:2] and ka < kb
    a.close()
    b.close()


def test_group_share_follows_weight_not_query_count():
    """The reviewer scenario: group A (weight 1) runs FOUR concurrent
    queries, group B (weight 3) runs one — B must still drain ~3x
    A's quanta (share follows WEIGHT, not query count; per-query fair
    share would hand A 4/5 of the worker)."""
    state = {"t": 0.0}
    ex = TaskExecutor(1, clock=lambda: state["t"])
    counts = {"ga": 0, "gb": 0}
    total = [0]
    target = 160
    errs = []

    def body(qid, group, weight):
        try:
            h = ex.register(qid, f"{qid}.t", group=group,
                            weight=weight)
            h.acquire()
            deadline = time.monotonic() + 10
            while len(ex._waiting) + len(ex._running) < 5 \
                    and time.monotonic() < deadline:
                time.sleep(0.001)
            try:
                while total[0] < target:
                    state["t"] += 0.001
                    counts[group] += 1
                    total[0] += 1
                    h.checkpoint()
            finally:
                h.close()
        except Exception as e:      # noqa: BLE001
            errs.append(repr(e))

    threads = [threading.Thread(target=body,
                                args=(f"qa{i}", "ga", 1.0))
               for i in range(4)]
    threads.append(threading.Thread(target=body,
                                    args=("qb", "gb", 3.0)))
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errs, errs
    ratio = counts["gb"] / max(counts["ga"], 1)
    assert 2.0 <= ratio <= 4.5, (counts, ratio)


def test_multilevel_decay_outranks_weight():
    """A long-running query decays to a higher level and ANY younger
    query's task outranks it, regardless of weights — short queries
    finish fast even next to a heavyweight hog."""
    ex = TaskExecutor(1)
    hog = ex.register("hog", "hog.t", group="etl", weight=100.0)
    fresh = ex.register("fresh", "fresh.t", group="adhoc", weight=1.0)
    ex.set_query_seconds("hog", LEVEL_THRESHOLDS_S[1] + 5.0)
    ex.set_query_seconds("fresh", 0.0)
    with ex._lock:
        assert ex._key_locked(fresh) < ex._key_locked(hog)
        # and the level dominates: even huge weight cannot pull the
        # hog below a level boundary
        assert ex._key_locked(hog)[0] > ex._key_locked(fresh)[0]
    hog.close()
    fresh.close()


def test_weighted_groups_get_proportional_quanta():
    """Two queries contending for ONE runner slot under a
    deterministic clock: the weight-3 group drains ~3x the split
    quanta of the weight-1 group (fair-share drain weighted by
    resource group)."""
    state = {"t": 0.0}
    ex = TaskExecutor(1, clock=lambda: state["t"])
    counts = {"a": 0, "b": 0}
    total = [0]
    target = 120
    errs = []

    def body(name, weight):
        try:
            h = ex.register(f"q{name}", f"q{name}.t",
                            group=f"g{name}", weight=weight)
            h.acquire()
            # handshake: don't start consuming quanta until BOTH
            # tasks contend for the slot (one registered running +
            # one waiting), or the first thread races through its
            # whole budget before the second even spawns
            deadline = time.monotonic() + 10
            while len(ex._waiting) + len(ex._running) < 2 \
                    and time.monotonic() < deadline:
                time.sleep(0.001)
            try:
                while total[0] < target:
                    state["t"] += 0.001   # one quantum of "work"
                    counts[name] += 1     # (only the slot holder runs)
                    total[0] += 1
                    h.checkpoint()
            finally:
                h.close()
        except Exception as e:      # noqa: BLE001
            errs.append(repr(e))

    ta = threading.Thread(target=body, args=("a", 1.0))
    tb = threading.Thread(target=body, args=("b", 3.0))
    ta.start()
    tb.start()
    ta.join(30)
    tb.join(30)
    assert not errs, errs
    assert counts["a"] + counts["b"] >= target
    ratio = counts["b"] / max(counts["a"], 1)
    assert 2.0 <= ratio <= 4.5, (counts, ratio)
    # the fairness observable: per-group quanta counters moved
    assert _counter("trino_tpu_task_scheduler_quanta_total",
                    group="gb") > 0


def test_no_starvation_k_over_runners():
    """K=8 tasks over 2 runner slots: every task completes its quanta
    (no starvation) and the concurrency bound holds throughout."""
    ex = TaskExecutor(2)
    done = []
    max_seen = [0]
    errs = []

    def body(i):
        try:
            h = ex.register(f"q{i}", f"q{i}.t")
            h.acquire()
            try:
                for _ in range(10):
                    max_seen[0] = max(max_seen[0], len(ex._running))
                    time.sleep(0.001)
                    h.checkpoint()
            finally:
                h.close()
            done.append(i)
        except Exception as e:      # noqa: BLE001
            errs.append(repr(e))

    threads = [threading.Thread(target=body, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errs, errs
    assert sorted(done) == list(range(8))
    assert max_seen[0] <= 2, f"concurrency bound violated: {max_seen}"


def test_blocked_scope_releases_slot():
    """A task blocked off-CPU (the exchange-pull shape) holds no
    runner slot: with ONE runner, a second task executes while the
    first waits — bounded runners cannot deadlock a producer behind
    its blocked consumer."""
    ex = TaskExecutor(1)
    release = threading.Event()
    producer_ran = threading.Event()

    def consumer():
        h = ex.register("qc", "qc.t")
        h.acquire()
        try:
            with h.blocked():
                release.wait(10)    # "waiting for upstream commit"
        finally:
            h.close()

    def producer():
        h = ex.register("qp", "qp.t")
        h.acquire()             # must be grantable while qc blocks
        try:
            producer_ran.set()
        finally:
            h.close()

    tc = threading.Thread(target=consumer)
    tc.start()
    _wait_until(lambda: ex.open_tasks() == 1, what="consumer blocked")
    tp = threading.Thread(target=producer)
    tp.start()
    assert producer_ran.wait(5), \
        "producer starved behind a blocked consumer"
    release.set()
    tc.join(10)
    tp.join(10)
    assert ex.open_tasks() == 0


def test_cancel_while_waiting_for_slot_raises():
    """An aborted task waiting for a runner slot unwinds with
    TaskCanceledError instead of waiting forever on a grant it can
    no longer use."""
    ex = TaskExecutor(1)
    hold = threading.Event()
    holder = ex.register("qh", "qh.t")
    holder.acquire()            # pins the only slot
    cancel = threading.Event()
    waiter = ex.register("qw", "qw.t", cancel=cancel)
    err = []

    def wait_for_slot():
        try:
            waiter.acquire()
        except TaskCanceledError as e:
            err.append(e)

    t = threading.Thread(target=wait_for_slot)
    t.start()
    time.sleep(0.1)
    cancel.set()
    t.join(5)
    assert err, "canceled waiter did not unwind"
    holder.close()
    hold.set()
    assert ex.open_tasks() == 0


# --------------------------------------------------------------------------
# live memory feedback: the e2e governance acceptance
# --------------------------------------------------------------------------

def _gated_tpch_catalogs(gate: threading.Event, block_table: str):
    class BlockingTpch(TpchConnector):
        remote_scan_ok = True

        def read_split(self, split, columns):
            if split.handle.table == block_table:
                gate.wait(30)
            return super().read_split(split, columns)

    cats = CatalogManager()
    cats.register("tpch", BlockingTpch())
    return cats


def test_live_worker_memory_kills_hog_while_small_query_completes():
    """THE acceptance e2e (ISSUE 14): a memory-hog query running ON a
    worker is killed with CLUSTER_OUT_OF_MEMORY from worker-streamed
    live reservations — NOT coordinator-side estimates (the
    coordinator never executes the hog's scan, so every pool byte it
    holds arrived via status beats) — its worker task is actually
    DELETEd, and a concurrent small query completes."""
    gate = threading.Event()
    cats = _gated_tpch_catalogs(gate, "lineitem")
    worker = TaskWorkerServer(catalogs=cats).start()
    pool = ClusterMemoryPool(1 << 20)          # 1 MiB
    memory = ClusterMemoryManager(pool)
    aborted = METRICS.counter("trino_tpu_worker_tasks_aborted_total")
    beats = METRICS.counter("trino_tpu_worker_live_memory_beats_total")
    kills0 = METRICS.counter("trino_tpu_memory_kills_total").value()
    a0, b0 = aborted.value(), beats.value()
    tracker = QueryTracker(
        lambda s: DistributedHostQueryRunner(
            [worker.base_uri], session=s, catalogs=cats),
        memory=memory)
    try:
        hog_sess = Session(catalog="tpch", schema="tiny")
        # a bare 5-lane scan chain: the worker task reserves its full
        # split share (~2.4MB) BEFORE the gated read blocks, so the
        # live figure is on the wire while the task runs
        hog = tracker.submit(
            "SELECT l_orderkey, l_quantity, l_extendedprice, "
            "l_discount, l_tax FROM lineitem", hog_sess)
        # the worker task reserves its ~2.4MB split share (5 lanes x
        # 60K rows) and blocks in the scan; status beats stream the
        # live reservation into the 1MiB pool -> the killer fires
        assert hog.wait_done(30), "hog never reached a terminal state"
        assert hog.state == "FAILED", hog.error
        assert hog.error["errorName"] == "CLUSTER_OUT_OF_MEMORY"
        assert "low-memory killer" in hog.error["message"]
        assert beats.value() > b0, "no live beats reached the pool"
        assert METRICS.counter(
            "trino_tpu_memory_kills_total").value() == kills0 + 1
        # the kill reached the WORKER: its in-flight task was DELETEd
        _wait_until(lambda: aborted.value() > a0,
                    what="worker-side abort")
        _wait_until(lambda: len(worker._tasks) == 0,
                    what="worker task registry drained")
        # a concurrent small query (same tracker, same pool) completes
        small = tracker.submit("SELECT count(*) FROM region",
                               Session(catalog="tpch", schema="tiny"))
        assert small.wait_done(30)
        assert small.state == "FINISHED", small.error
        assert small.result.rows == [[5]]
    finally:
        gate.set()
        worker.stop()


def test_live_memory_feedback_session_property_gates_beats():
    """live_memory_feedback=false pins the pre-PR-14 behavior: the
    pool sees NO worker-streamed reservations during execution."""
    calls = []

    class Recorder:
        def reserve(self, nbytes):
            pass

        def reserve_remote(self, source, nbytes):
            calls.append((source, nbytes))

    worker = TaskWorkerServer().start()
    try:
        for feedback, expect_calls in ((True, True), (False, False)):
            calls.clear()
            s = Session(catalog="tpch", schema="tiny")
            s.set("live_memory_feedback", feedback)
            s.memory = Recorder()
            # a stage-path join: worker tasks reserve join state, and
            # even a fast task's terminal status poll carries the
            # high-water figure (beats are not timing-dependent)
            res = DistributedHostQueryRunner(
                [worker.base_uri], session=s).execute(
                "SELECT n_name, r_name FROM nation JOIN region "
                "ON n_regionkey = r_regionkey")
            assert len(res.rows) == 25
            assert bool(calls) == expect_calls, (feedback, calls)
    finally:
        worker.stop()


def test_pool_releases_terminal_attempt_sources():
    """Retried attempts and sequential stage tasks must not ACCUMULATE
    dead high-water marks: a terminal attempt's source is cleared, so
    a 600-byte task retried once charges 600 bytes, not 1200."""
    pool = ClusterMemoryPool(1 << 30)
    mine, total = pool.set_reservation("qr", 600, "global",
                                       source="qr.f0.p0.a0")
    assert (mine, total) == (600, 600)
    pool.clear_source("qr", "qr.f0.p0.a0")     # attempt died
    mine, total = pool.set_reservation("qr", 600, "global",
                                       source="qr.f0.p0.a1")
    assert (mine, total) == (600, 600)          # NOT 1200
    # the coordinator source coexists and stays monotonic
    mine, total = pool.set_reservation("qr", 100, "global")
    assert (mine, total) == (700, 700)
    pool.free("qr")
    assert pool.reserved_bytes() == 0


# --------------------------------------------------------------------------
# cross-query cache governance under pressure
# --------------------------------------------------------------------------

def test_pool_pressure_evicts_scan_cache_before_killing():
    """A cache full of one query's tables cannot OOM a neighbor: when
    reservations + cache residency exceed the pool, scan-cache
    entries are evicted FIRST and no query is killed (reservations
    alone stay under the pool)."""
    from trino_tpu.exec.executor import cache_memory_bytes
    lr = LocalQueryRunner(session=Session(catalog="tpch",
                                          schema="tiny"))
    lr.execute("SELECT count(*) FROM lineitem")
    cached = cache_memory_bytes()
    assert cached > 0, "scan cache did not populate"
    pool = ClusterMemoryPool(cached + 10_000)
    mgr = ClusterMemoryManager(pool)
    killed = []
    evicted0 = _counter("trino_tpu_cache_pressure_evictions_total",
                        cache="scan")
    ctx = mgr.register("q_cachetest",
                       kill_fn=lambda m, n: killed.append(n))
    ctx.reserve(50_000)     # reservations + cache > pool
    assert cache_memory_bytes() < cached, "no cache relief happened"
    assert not killed and mgr.kills == 0
    assert _counter("trino_tpu_cache_pressure_evictions_total",
                    cache="scan") > evicted0
    mgr.unregister("q_cachetest")


# --------------------------------------------------------------------------
# graceful degradation: the BUSY shed
# --------------------------------------------------------------------------

def test_busy_shed_declines_then_retry_absorbs():
    """A worker past its shed threshold 503s NEW dispatches (known
    tasks are never shed); the scheduler absorbs the decline through
    rotation+backoff without a failure-detector demerit, and the
    query completes."""
    import urllib.error
    import urllib.request
    gate = threading.Event()
    cats = _gated_tpch_catalogs(gate, "lineitem")
    # one runner, shed at 1 open task: the first (blocked) task
    # saturates the worker. ema_s=0 pins the shed signal to the spot
    # open-task count — this test drives an instant saturation, which
    # the default EMA smoothing (deliberately) rides through; the EMA
    # behavior itself is unit-tested with a deterministic clock in
    # test_busy_shed_ema_smooths_bursts
    busy = TaskWorkerServer(catalogs=cats, task_runners=1,
                            busy_shed_factor=1,
                            busy_shed_ema_s=0).start()
    healthy = TaskWorkerServer(catalogs=cats).start()
    rejects = METRICS.counter("trino_tpu_worker_busy_rejections_total")
    r0 = rejects.value()
    try:
        blocker = RemoteTaskClient(busy.base_uri)
        blocker.submit("wedge-task",
                       "SELECT count(*) FROM lineitem")
        _wait_until(lambda: busy.task_executor.open_tasks() >= 1,
                    what="wedge task registered")
        # a NEW dispatch is declined with the retryable 503
        with pytest.raises(urllib.error.HTTPError) as exc:
            RemoteTaskClient(busy.base_uri).submit(
                "shed-me", "SELECT 1 AS x")
        assert exc.value.code == 503
        assert rejects.value() > r0
        # ...but a re-POST of the KNOWN task is idempotent, not shed
        blocker.submit("wedge-task", "SELECT count(*) FROM lineitem")
        # e2e: a query over [busy, healthy] completes — the busy
        # declines rotate to the healthy worker without burning the
        # retry budget or the busy worker's health record
        from trino_tpu.server.failure import HeartbeatFailureDetector
        detector = HeartbeatFailureDetector()
        s = Session(catalog="tpch", schema="tiny")
        s.set("retry_policy", "TASK")
        s.set("retry_initial_delay_ms", 10)
        res = DistributedHostQueryRunner(
            [busy.base_uri, healthy.base_uri], session=s,
            failure_detector=detector,
            catalogs=cats).execute("SELECT count(*) FROM region")
        assert res.rows == [[5]]
        assert busy.base_uri not in detector.failed()
    finally:
        gate.set()
        busy.stop()
        healthy.stop()


# --------------------------------------------------------------------------
# replicate exchange: per-worker fetch-once cache
# --------------------------------------------------------------------------

def test_replicate_fetch_once_cache_unit(tmp_path):
    """Two consumer tasks pulling the same replicate frame: the
    second is served from the per-worker cache (one fetch per worker,
    not one per task); first-commit-wins makes the bytes immutable so
    the cache can never serve stale frames."""
    from trino_tpu.fte.spool import LocalDirSpool
    from trino_tpu.stage.exchange import (ExchangePuller,
                                          evict_replicate_cache,
                                          replicate_cache_bytes)
    evict_replicate_cache(None)
    spool = LocalDirSpool(str(tmp_path))
    from trino_tpu.serde import serialize_batch
    from trino_tpu.columnar import batch_from_pylist
    from trino_tpu.types import BIGINT
    frame = serialize_batch(batch_from_pylist(
        {"x": [1, 2, 3]}, {"x": BIGINT}))
    spool.commit("qr.s0.p0", 0, 0, 0, [frame])
    sources = {"0": {"tasks": ["qr.s0.p0"], "uris": [None],
                     "kind": "replicate", "candidates": [],
                     "eager": False}}
    hits0 = _counter("trino_tpu_exchange_replicate_cache_total",
                     result="hit")
    out1 = ExchangePuller(sources, part=0,
                          spool=spool).read_fragment(0)
    assert replicate_cache_bytes() == len(frame)
    # the second consumer (different part) needs NO spool/HTTP at all
    out2 = ExchangePuller(sources, part=1,
                          spool=None).read_fragment(0)
    assert _counter("trino_tpu_exchange_replicate_cache_total",
                    result="hit") == hits0 + 1
    assert out1[0].to_pylist() == out2[0].to_pylist() \
        == [[1], [2], [3]]
    # pressure governance clears it
    assert evict_replicate_cache(None) == len(frame)
    assert replicate_cache_bytes() == 0


def test_replicate_cache_e2e_semi_join():
    """A semi join's replicated filtering side over THREE consumer
    tasks (one per worker, all in this process sharing the fetch-once
    cache): the cache takes re-pulls off the exchange and the result
    is exact. Barrier mode, so the committed frames are pulled at
    consumer starts staggered by task dispatch."""
    from trino_tpu.stage.exchange import (evict_replicate_cache,
                                          replicate_cache_bytes)
    evict_replicate_cache(None)
    workers = [TaskWorkerServer().start() for _ in range(3)]
    sql = ("SELECT n_name FROM nation WHERE n_regionkey IN "
           "(SELECT r_regionkey FROM region WHERE r_name = 'ASIA') "
           "ORDER BY n_name")
    try:
        expected = LocalQueryRunner(
            session=Session(catalog="tpch", schema="tiny")).execute(sql)
        hits0 = _counter("trino_tpu_exchange_replicate_cache_total",
                         result="hit")
        s = Session(catalog="tpch", schema="tiny")
        s.set("stage_pipelining", False)
        res = DistributedHostQueryRunner(
            [w.base_uri for w in workers], session=s).execute(sql)
        assert res.rows == expected.rows
        # the broadcast frames were cached per worker PROCESS...
        assert replicate_cache_bytes() > 0
        # ...and sibling consumer tasks were served from the cache
        assert _counter("trino_tpu_exchange_replicate_cache_total",
                        result="hit") > hits0
    finally:
        for w in workers:
            w.stop()
