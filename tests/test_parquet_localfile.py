"""Parquet reader (from scratch) + local-file connector.

Reference parity: lib/trino-parquet (reader-only at the snapshot),
plugin/trino-local-file, lib/trino-record-decoder. Test files are
generated with pyarrow — an INDEPENDENT writer — so the reader is
validated against real third-party output, not a round-trip of itself.
"""

import datetime
import json
import os

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402

from trino_tpu.catalog import CatalogManager  # noqa: E402
from trino_tpu.connectors.localfile import LocalFileConnector  # noqa
from trino_tpu.connectors.memory import MemoryConnector  # noqa: E402
from trino_tpu.formats.parquet import (read_metadata, read_parquet,
                                       snappy_decompress)  # noqa: E402
from trino_tpu.runner import LocalQueryRunner  # noqa: E402


@pytest.fixture(scope="module")
def datadir(tmp_path_factory):
    d = tmp_path_factory.mktemp("files")
    n = 1000
    rng = np.random.default_rng(0)
    table = pa.table({
        "id": pa.array(np.arange(n, dtype=np.int64)),
        "qty": pa.array(rng.integers(0, 50, n).astype(np.int32)),
        "price": pa.array(rng.uniform(1.0, 100.0, n)),
        "flag": pa.array((np.arange(n) % 3 == 0)),
        "name": pa.array([f"item_{i % 17}" for i in range(n)]),
        "maybe": pa.array([None if i % 5 == 0 else i
                           for i in range(n)], type=pa.int64()),
        "day": pa.array([datetime.date(1995, 1, 1)
                         + datetime.timedelta(days=int(i % 700))
                         for i in range(n)]),
    })
    pq.write_table(table, d / "plain.parquet", compression="none",
                   use_dictionary=False)
    pq.write_table(table, d / "snappy.parquet", compression="snappy")
    pq.write_table(table, d / "gzipped.parquet", compression="gzip")
    pq.write_table(table, d / "grouped.parquet", compression="snappy",
                   row_group_size=100)
    with open(d / "people.csv", "w") as f:
        f.write("name,age,score\nalice,30,1.5\nbob,25,2.25\n")
    with open(d / "events.json", "w") as f:
        f.write(json.dumps({"kind": "click", "n": 3}) + "\n")
        f.write(json.dumps({"kind": "view", "n": 7}) + "\n")
    return d


def _expected(table_rows=1000):
    rng = np.random.default_rng(0)
    qty = rng.integers(0, 50, table_rows).astype(np.int32)
    price = rng.uniform(1.0, 100.0, table_rows)
    return qty, price


def test_snappy_roundtrip_against_reference_vectors():
    # compress with pyarrow's real snappy, decompress with ours
    import pyarrow as _pa
    raw = b"trino-tpu snappy " * 100 + os.urandom(50)
    comp = _pa.compress(raw, codec="snappy", asbytes=True)
    assert snappy_decompress(comp) == raw


@pytest.mark.parametrize("fname", ["plain.parquet", "snappy.parquet",
                                   "gzipped.parquet",
                                   "grouped.parquet"])
def test_read_parquet_matches_pyarrow(datadir, fname):
    path = str(datadir / fname)
    got = read_parquet(path)
    ref = pq.read_table(path).to_pydict()
    n = got.num_rows_host()
    assert n == 1000
    rows = got.to_pylist()
    names = list(got.columns)
    for i in (0, 1, 499, 999):
        for j, col in enumerate(names):
            want = ref[col][i]
            have = rows[i][j]
            if isinstance(want, float):
                assert have == pytest.approx(want)
            else:
                assert have == want, (col, i, have, want)


def test_metadata_and_row_groups(datadir):
    meta = read_metadata(str(datadir / "grouped.parquet"))
    assert meta.num_rows == 1000
    assert len(meta.row_groups) == 10
    one = read_parquet(str(datadir / "grouped.parquet"), row_group=3)
    assert one.num_rows_host() == 100


def test_column_projection(datadir):
    b = read_parquet(str(datadir / "snappy.parquet"),
                     columns=["id", "name"])
    assert list(b.columns) == ["id", "name"]


def test_localfile_connector_sql(datadir):
    runner = LocalQueryRunner()
    runner.catalogs.register("files",
                             LocalFileConnector(str(datadir)))
    got = runner.execute("SELECT count(*), sum(qty) FROM "
                         "files.default.snappy").rows
    qty, _ = _expected()
    assert got == [[1000, int(qty.sum())]]
    # predicate + projection over parquet, with pushdown
    got = runner.execute("SELECT count(*) FROM files.default.snappy "
                         "WHERE flag AND qty > 25").rows
    flag = np.arange(1000) % 3 == 0
    assert got == [[int((flag & (qty > 25)).sum())]]
    # split-per-row-group parallel scan agrees
    got2 = runner.execute("SELECT count(*) FROM "
                          "files.default.grouped "
                          "WHERE flag AND qty > 25").rows
    assert got2 == got
    # nulls survive
    got = runner.execute("SELECT count(*) FROM files.default.snappy "
                         "WHERE maybe IS NULL").rows
    assert got == [[200]]
    # dates decode
    got = runner.execute("SELECT min(day), max(day) FROM "
                         "files.default.snappy").rows
    assert got[0][0] == datetime.date(1995, 1, 1)


def test_localfile_csv_json(datadir):
    runner = LocalQueryRunner()
    runner.catalogs.register("files",
                             LocalFileConnector(str(datadir)))
    assert runner.execute(
        "SELECT name, age FROM files.default.people "
        "ORDER BY age DESC").rows == [['alice', 30], ['bob', 25]]
    assert runner.execute(
        "SELECT sum(n) FROM files.default.events").rows == [[10]]
    tables = {r[0] for r in runner.execute(
        "SHOW TABLES FROM files.default").rows}
    assert {"people", "events", "snappy"} <= tables


def test_strings_and_varchar_agg(datadir):
    runner = LocalQueryRunner()
    runner.catalogs.register("files",
                             LocalFileConnector(str(datadir)))
    got = runner.execute(
        "SELECT count(DISTINCT name) FROM files.default.plain").rows
    assert got == [[17]]
