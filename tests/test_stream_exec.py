"""Beyond-HBM morsel streaming (exec/streamjoin.py): chunked ==
unchunked bit-exactness across chunk sizes, auto-engagement instead of
the memory error, the one-compiled-program-per-stream contract,
streamed-peak memory governance, hot-shape/AOT pre-warm of chunk
kernels, and the distributed rollup."""

import pytest

from trino_tpu.config import capacity_for
from trino_tpu.obs.metrics import (STREAM_CHUNKS, STREAM_H2D_BYTES,
                                   STREAM_OVERLAPPED)
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session


def _chunk_total() -> float:
    return sum(v for _, v in STREAM_CHUNKS.samples())


def _runner(schema="tiny", **props):
    s = Session(catalog="tpch", schema=schema)
    for k, v in props.items():
        s.set(k, v)
    return LocalQueryRunner(session=s)


@pytest.fixture(scope="module")
def mem_tables():
    """Small memory-catalog tables with NULL join keys and a decimal
    column — tiny enough that chunk size 1 stays fast."""
    r = LocalQueryRunner(session=Session(catalog="tpch",
                                         schema="tiny"))
    r.execute("CREATE TABLE memory.default.sprobe "
              "(k BIGINT, v BIGINT, d DECIMAL(12,2))")
    rows = ",".join(
        f"({'NULL' if i % 5 == 0 else i % 37},{i},"
        f"CAST({i}.{i % 100:02d} AS DECIMAL(12,2)))"
        for i in range(200))
    r.execute(f"INSERT INTO memory.default.sprobe VALUES {rows}")
    r.execute("CREATE TABLE memory.default.sbuild (bk BIGINT, w BIGINT)")
    rows = ",".join(f"({'NULL' if i % 7 == 0 else i},{i * 10})"
                    for i in range(40))
    r.execute(f"INSERT INTO memory.default.sbuild VALUES {rows}")
    return r


# the property suite: joins (incl. NULL keys + outer), a decimal
# aggregation, and an order-sensitive query over a filter chain
_PROPERTY_QUERIES = (
    "SELECT count(*), sum(v), sum(w) FROM memory.default.sprobe "
    "JOIN memory.default.sbuild ON k = bk",
    "SELECT count(*), sum(v), sum(w) FROM memory.default.sprobe "
    "LEFT JOIN memory.default.sbuild ON k = bk",
    "SELECT sum(d), avg(d), count(k), min(v), max(v) "
    "FROM memory.default.sprobe",
    "SELECT k, v, d FROM memory.default.sprobe WHERE v > 20 "
    "ORDER BY v DESC LIMIT 25",
    "SELECT k, sum(d), count(*) FROM memory.default.sprobe "
    "GROUP BY k ORDER BY k",
    # residual (non-equi conjunct) join through the streamed path
    "SELECT count(*), sum(w) FROM memory.default.sprobe "
    "JOIN memory.default.sbuild ON k = bk WHERE v > w / 10",
)


@pytest.mark.parametrize("chunk_rows", [1, 7, 64, 100000])
def test_chunked_equals_unchunked(mem_tables, chunk_rows):
    """Bit-exactness across chunk sizes 1 / prime / pow2 / >nrows:
    forcing every streamable operator to chunk must not change a
    single row — NULL join keys, outer repair, decimal (Int128-exact)
    aggregates, and ORDER BY-sensitive output included."""
    base = [mem_tables.execute(q).rows for q in _PROPERTY_QUERIES]
    s = Session(catalog="tpch", schema="tiny")
    s.set("stream_chunk_rows", chunk_rows)
    r = LocalQueryRunner(session=s, catalogs=mem_tables.catalogs)
    c0 = _chunk_total()
    for q, b in zip(_PROPERTY_QUERIES, base):
        assert r.execute(q).rows == b, f"chunk={chunk_rows}: {q}"
    assert _chunk_total() > c0          # the forced path really ran


def test_over_budget_join_streams_instead_of_raising(monkeypatch):
    """The synthetic over-budget join: a budget below the probe
    scan's materialization estimate used to fail with the memory
    error; now the probe streams and the query completes. The
    monkeypatched control proves the SAME budget still raises when
    streaming is disabled — engagement is what saves it."""
    from trino_tpu.exec.executor import QueryError
    sql = ("SELECT count(*), sum(l_quantity) FROM lineitem "
           "JOIN orders ON l_orderkey = o_orderkey")
    expected = _runner().execute(sql).rows

    # lineitem probe estimate ~960KB (60k rows x 2 lanes); orders
    # build state ~400KB -> budget 600KB engages streaming
    budget = 600_000
    c0 = _chunk_total()
    r = _runner(query_max_memory_per_node=budget)
    assert r.execute(sql).rows == expected
    assert _chunk_total() > c0

    import trino_tpu.exec.streamjoin as sj
    monkeypatch.setattr(sj, "maybe_stream_join",
                        lambda ex, node: (None, None))
    monkeypatch.setattr(sj, "maybe_stream_chain",
                        lambda ex, node: None)
    with pytest.raises(QueryError, match="memory limit"):
        _runner(query_max_memory_per_node=budget).execute(sql)


def test_one_compiled_program_per_streamed_join(mem_tables):
    """Acceptance: every chunk of a streamed operator shares ONE
    compiled program — one jit_trace span total inside the stream
    (the first chunk), device_execute for all the rest."""
    sql = ("SELECT count(*), sum(v), sum(w) "
           "FROM memory.default.sprobe "
           "JOIN memory.default.sbuild ON k = bk")
    s = Session(catalog="tpch", schema="tiny")
    s.set("stream_chunk_rows", 16)
    r = LocalQueryRunner(session=s, catalogs=mem_tables.catalogs,
                         collect_node_stats=True)
    res = r.execute(sql)
    assert res.rows == mem_tables.execute(sql).rows

    def stream_kids(span, inside, out):
        inside = inside or span.name == "stream_chunk"
        if inside and span.name in ("jit_trace", "device_execute"):
            out.append(span.name)
        for c in span.children:
            stream_kids(c, inside, out)

    kinds = []
    for root in res.trace.roots:
        stream_kids(root, False, kinds)
    chunks = [sp for sp in _walk(res.trace) if sp.name == "stream_chunk"]
    assert len(chunks) >= 2             # 200 rows / 16 -> 13 chunks
    traces = [k for k in kinds if k == "jit_trace"]
    # warm-up = the first chunk; every later chunk rides the program.
    # A fully pre-warmed process (cache already holds the program from
    # an earlier test) may even trace zero times.
    assert len(traces) <= 1
    assert kinds.count("device_execute") >= len(chunks) - 1


def _walk(trace):
    out = []

    def rec(sp):
        out.append(sp)
        for c in sp.children:
            rec(c)
    for rootsp in trace.roots:
        rec(rootsp)
    return out


def test_streamed_explain_and_metrics(mem_tables):
    """EXPLAIN ANALYZE shows the chunk count + h2d volume per
    operator and the stream_chunk spans; the Prometheus families
    move."""
    c0, b0, o0 = (_chunk_total(), STREAM_H2D_BYTES.value(),
                  STREAM_OVERLAPPED.value())
    s = Session(catalog="tpch", schema="tiny")
    s.set("stream_chunk_rows", 16)
    r = LocalQueryRunner(session=s, catalogs=mem_tables.catalogs)
    res = r.execute(
        "EXPLAIN ANALYZE SELECT count(*), sum(w) "
        "FROM memory.default.sprobe "
        "JOIN memory.default.sbuild ON k = bk")
    text = "\n".join(row[0] for row in res.rows)
    assert "streamed" in text and "chunks" in text
    assert "stream_chunk" in text
    assert _chunk_total() > c0
    assert STREAM_H2D_BYTES.value() > b0
    # double-buffering: all but the first transfer overlap compute
    assert STREAM_OVERLAPPED.value() > o0


def test_streamed_peak_reported_to_cluster_pool(monkeypatch):
    """Memory-governance fix: a query whose materialized join breaches
    the cluster pool (killed with CLUSTER_OUT_OF_MEMORY) completes
    when streaming engages, because the ledger now carries the
    streamed peak (build + chunk buffers), not the full estimate."""
    from trino_tpu.exec.executor import QueryError
    from trino_tpu.server.memory import (ClusterMemoryManager,
                                         ClusterMemoryPool)
    sql = ("SELECT count(*), sum(l_quantity), sum(o_totalprice) "
           "FROM lineitem JOIN orders ON l_orderkey = o_orderkey")
    expected = _runner().execute(sql).rows
    pool_bytes = 1_200_000      # < the ~3.4MB join-output estimate

    def run_under_pool(disable_streaming: bool):
        mgr = ClusterMemoryManager(ClusterMemoryPool(pool_bytes))
        s = Session(catalog="tpch", schema="tiny")
        s.memory = mgr.register("q-stream")
        r = LocalQueryRunner(session=s)
        if disable_streaming:
            import trino_tpu.exec.streamjoin as sj
            monkeypatch.setattr(sj, "maybe_stream_join",
                                lambda ex, node: (None, None))
            monkeypatch.setattr(sj, "maybe_stream_chain",
                                lambda ex, node: None)
            monkeypatch.setattr(sj, "agg_chunk_capacity",
                                lambda ex, scan: None)
        try:
            return r.execute(sql).rows, mgr
        finally:
            if disable_streaming:
                monkeypatch.undo()

    with pytest.raises(QueryError, match="out of memory"):
        run_under_pool(True)

    rows, mgr = run_under_pool(False)
    assert rows == expected
    assert mgr.kills == 0


def test_streamjoin_hot_shape_recorded_and_aot_compiles(mem_tables):
    """Satellite: streamed chunk shapes land in the hot-shape registry
    under their canonical chunk capacity, and the AOT path rebuilds +
    compiles the probe program into the exact cache slot — a
    pre-warmed worker's first streamed chunk is a cache hit."""
    from trino_tpu.exec import streamjoin as sj
    from trino_tpu.exec.aot import compile_entries
    from trino_tpu.exec.hotshapes import HOT_SHAPES
    HOT_SHAPES.clear()
    sql = ("SELECT count(*), sum(w) FROM memory.default.sprobe "
           "JOIN memory.default.sbuild ON k = bk")
    s = Session(catalog="tpch", schema="tiny")
    s.set("stream_chunk_rows", 16)
    LocalQueryRunner(session=s,
                     catalogs=mem_tables.catalogs).execute(sql)
    entries = [e for e in HOT_SHAPES.top(32)
               if e["kind"] == "streamjoin"]
    assert entries, "streamed join shape was not recorded"
    payload = entries[0]["payload"]
    assert payload["chunk_capacity"] == capacity_for(16, minimum=8)

    # wipe the in-process program cache, AOT-compile from the payload,
    # then prove the live query path lands on the pre-warmed program:
    # zero jit_trace spans inside the stream
    sj._JOIN_JIT_CACHE.clear()
    out = compile_entries(entries)
    assert out["compiled"] == 1 and out["errors"] == 0
    r = LocalQueryRunner(session=s, catalogs=mem_tables.catalogs,
                         collect_node_stats=True)
    res = r.execute(sql)
    names = [sp.name for sp in _walk(res.trace)]
    assert "stream_chunk" in names
    kinds = []
    for root in res.trace.roots:
        _collect_stream_kinds(root, False, kinds)
    assert "jit_trace" not in kinds, \
        "pre-warmed streamed join still traced"


def _collect_stream_kinds(span, inside, out):
    inside = inside or span.name == "stream_chunk"
    if inside and span.name in ("jit_trace", "device_execute"):
        out.append(span.name)
    for c in span.children:
        _collect_stream_kinds(c, inside, out)


def test_chunked_agg_shape_recorded_at_chunk_capacity(monkeypatch,
                                                      mem_tables):
    """The chunked streaming aggregation records its (canonical)
    chunk-capacity program shape so workers pre-warm the chunk kernel
    (ROADMAP item 1's lazily-compiled gap, streamed flavor)."""
    monkeypatch.setenv("TRINO_TPU_FRAGMENT_JIT", "1")
    from trino_tpu.exec.hotshapes import HOT_SHAPES
    HOT_SHAPES.clear()
    s = Session(catalog="tpch", schema="tiny")
    s.set("stream_chunk_rows", 32)
    LocalQueryRunner(session=s, catalogs=mem_tables.catalogs).execute(
        "SELECT k, sum(v), count(*) FROM memory.default.sprobe "
        "GROUP BY k")
    entries = [e for e in HOT_SHAPES.top(32) if e["kind"] == "stream"]
    assert entries, "chunked agg shape was not recorded"
    assert any(e["payload"]["capacity"] == capacity_for(32, minimum=8)
               for e in entries)


def test_distributed_stream_rollup():
    """Worker-side streaming: a stage-task/leaf-fragment executor
    streams its split share, the task status ships
    streamChunks/streamH2dBytes, and the scheduler rolls them up."""
    from trino_tpu.exec.remote import DistributedHostQueryRunner
    from trino_tpu.server.task_worker import TaskWorkerServer
    workers = [TaskWorkerServer().start() for _ in range(2)]
    try:
        s = Session(catalog="tpch", schema="tiny")
        s.set("stream_chunk_rows", 4096)
        r = DistributedHostQueryRunner(
            [w.base_uri for w in workers], session=s,
            collect_node_stats=True)
        base = LocalQueryRunner(
            session=Session(catalog="tpch", schema="tiny")).execute(
            "SELECT l_returnflag, sum(l_quantity) FROM lineitem "
            "GROUP BY l_returnflag ORDER BY l_returnflag").rows
        res = r.execute(
            "SELECT l_returnflag, sum(l_quantity) FROM lineitem "
            "GROUP BY l_returnflag ORDER BY l_returnflag")
        assert res.rows == base
        assert res.stream_chunks > 0
        assert res.stream_h2d_bytes > 0
    finally:
        for w in workers:
            w.stop()


@pytest.mark.slow      # ~102s: the single heaviest tier-1 test; the
# chunked==unchunked matrix + streamed-peak governance tests keep the
# fast lane covered
def test_q18_sf1_streams_under_small_budget_matches_oracle():
    """Acceptance: the full q18 pipeline at sf1 completes under a
    memory budget smaller than its probe working set (the lineitem
    probe estimate is ~96MB; the budget leaves only chunk room after
    the orders build state), streaming the probe join and the
    IN-subquery aggregation — row-for-row against the independent
    numpy oracle."""
    import datetime

    from trino_tpu.benchmarks.q18_oracle import q18_oracle
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    from trino_tpu.connectors.tpch import table_rows

    build_state = capacity_for(table_rows("orders", 1.0)) * 48
    budget = build_state + (64 << 20)
    probe_est = table_rows("orders", 1.0) * 4 * 2 * 8   # ~96MB
    # working set = probe materialization + the capacity-rounded
    # build state the join holds concurrently (~196MB at sf1)
    assert budget < probe_est + build_state, \
        "budget must sit below the q18 join working set"
    s = Session(catalog="tpch", schema="sf1")
    s.set("query_max_memory_per_node", int(budget))
    r = LocalQueryRunner(session=s)
    c0 = _chunk_total()
    res = r.execute(TPCH_QUERIES[18]).rows
    assert _chunk_total() > c0, "q18 did not stream"
    exp = q18_oracle(1.0)
    assert len(res) == len(exp) > 0
    epoch = datetime.date(1970, 1, 1)
    for g, e in zip(res, exp):
        assert [g[0], g[1], g[2], (g[3] - epoch).days, g[4], g[5]] == e
