"""Full-plan AOT coverage (exec/aot.py): the materialized hash join's
count+expand pair, window programs, and the repartition bucketing
kernel each record a hot shape, AOT-compile from the JSON payload
alone, and land in the SAME cache slot the executor hits — a fresh
executor's first run shows ZERO jit_trace spans.

Also the enabler: StringDictionary equality is CONTENT-based
(columnar.py), so an AOT-fabricated dictionary matches the live one in
jax's treedef comparison instead of forcing an identity-mismatch
retrace.

NOTE on the file name: these tests call jax.clear_caches(), which
wipes the process-wide trace caches every OTHER suite module keeps
warm — "warmpath" sorts near the end of tests/ on purpose so the
recompile tax lands after the heavy corpus modules, not under them."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from trino_tpu.exec import aot
from trino_tpu.exec import executor as exmod
from trino_tpu.exec.executor import Executor
from trino_tpu.exec.hotshapes import HOT_SHAPES
from trino_tpu.obs.metrics import METRICS
from trino_tpu.obs.trace import QueryTrace
from trino_tpu.planner import LogicalPlanner
from trino_tpu.planner.optimizer import optimize
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session
from trino_tpu.sql.parser import parse_statement

_JIT_LOOKUPS = METRICS.counter("trino_tpu_jit_cache_total")


@pytest.fixture(autouse=True)
def _fresh_registry():
    """These tests assert on HOT_SHAPES.top(...) contents; hundreds of
    earlier suite tests leave higher-hit entries that would crowd a
    fresh 1-hit recording out of the top-K. Run against an empty
    registry, restore the prior entries afterwards."""
    saved = HOT_SHAPES.top(10 ** 6)
    HOT_SHAPES.clear()
    yield
    HOT_SHAPES.clear()
    HOT_SHAPES.merge(saved)


def _plan(runner, sql):
    stmt = parse_statement(sql)
    return optimize(
        LogicalPlanner(runner.catalogs, runner.session).plan(stmt))


def _span_names(trace):
    names = []

    def walk(sp):
        names.append(sp.name)
        for c in sp.children:
            walk(c)

    for root in trace.roots:
        walk(root)
    return names


def _wipe_program_caches():
    """A fresh worker process: every in-process structural cache AND
    jax's per-callable trace caches are gone — only the AOT path can
    repopulate them."""
    import jax
    from trino_tpu.exec.streamjoin import _JOIN_JIT_CACHE
    from trino_tpu.stage import repartition as rp
    exmod._CHAIN_JIT_CACHE.clear()
    exmod._STREAM_JIT_CACHE.clear()
    exmod._MJOIN_JIT_CACHE.clear()
    exmod._WINDOW_JIT_CACHE.clear()
    _JOIN_JIT_CACHE.clear()
    rp._BUCKET_JIT_CACHE.clear()
    jax.clear_caches()


def _record_wipe_compile_rerun(monkeypatch, sql, needed_kinds):
    """The acceptance loop: run once recording shapes, JSON round-trip
    the registry export, wipe every cache, AOT-compile from payloads
    alone, then run the SAME query through a FRESH executor and return
    its span names (plus the rows, for the correctness check)."""
    monkeypatch.setenv("TRINO_TPU_WHOLE_TABLE", "1")
    r = LocalQueryRunner()
    plan = _plan(r, sql)
    ref = Executor(r.catalogs, r.session,
                   fragment_jit=True).execute(plan).to_pylist()
    entries = json.loads(json.dumps(HOT_SHAPES.top(100)))
    kinds = {e["kind"] for e in entries}
    assert needed_kinds <= kinds, (needed_kinds, kinds)
    _wipe_program_caches()
    summary = aot.compile_entries(entries)
    assert summary["errors"] == 0, summary
    assert summary["compiled"] >= len(needed_kinds)
    session = Session(catalog="tpch", schema="tiny")
    session.trace = QueryTrace("aot-roundtrip")
    ex = Executor(r.catalogs, session, fragment_jit=True)
    with session.trace.span("execute"):
        out = ex.execute(_plan(r, sql)).to_pylist()
    assert out == ref
    return _span_names(session.trace)


def test_stringdictionary_content_equality():
    from trino_tpu.columnar import StringDictionary
    a, _ = StringDictionary.from_strings(["x", "y", "z", "y"])
    b, _ = StringDictionary.from_strings(["x", "y", "z"])
    c, _ = StringDictionary.from_strings(["y", "x", "z"])
    assert a == b and hash(a) == hash(b)    # distinct objects, same pool
    assert a != c                           # order matters: codes index
    assert a != StringDictionary(np.asarray(["x", "y"], dtype=object))
    # merge's identity fast path is untouched by content equality
    m, rs, ro = a.merge(a)
    assert m is a and list(rs) == [0, 1, 2]


def test_stringdictionary_fingerprint_edges():
    """The fingerprint must not collide on byte-stream ambiguities:
    NULL vs the string "None", and entry boundaries (the length prefix
    keeps ["ab","c"] distinct from ["a","bc"])."""
    import numpy as np
    from trino_tpu.columnar import StringDictionary
    null = StringDictionary(np.asarray([None, "x"], dtype=object))
    lit = StringDictionary(np.asarray(["None", "x"], dtype=object))
    assert null != lit and null.fingerprint != lit.fingerprint
    a = StringDictionary(np.asarray(["ab", "c"], dtype=object))
    b = StringDictionary(np.asarray(["a", "bc"], dtype=object))
    assert a != b and a.fingerprint != b.fingerprint
    # cached: the second access returns the same tuple object
    assert a.fingerprint is a.fingerprint


def test_join_aot_zero_retrace(monkeypatch):
    """Materialized hash join (count + expand), with dictionary-carrying
    transported columns: the AOT-fabricated dictionaries must be
    content-equal to the live ones or the first run retraces."""
    names = _record_wipe_compile_rerun(
        monkeypatch,
        "SELECT o_orderstatus, o_orderpriority, c_nationkey FROM orders "
        "JOIN customer ON o_custkey = c_custkey "
        "WHERE o_totalprice < 123000",
        {"join"})
    assert names.count("jit_trace") == 0, names
    assert names.count("device_execute") >= 2


def test_window_aot_zero_retrace(monkeypatch):
    names = _record_wipe_compile_rerun(
        monkeypatch,
        "SELECT o_custkey, row_number() OVER "
        "(PARTITION BY o_custkey ORDER BY o_totalprice) AS rn "
        "FROM orders WHERE o_orderkey < 1777",
        {"window"})
    assert names.count("jit_trace") == 0, names


def test_combined_q3_shaped_plan_zero_retrace(monkeypatch):
    """The combined acceptance corpus: a q3-shaped plan — two hash
    joins, an aggregation, and a window on top — pre-warmed via
    compile_entries alone, executes end-to-end with zero retraces."""
    names = _record_wipe_compile_rerun(
        monkeypatch,
        "SELECT o_orderkey, revenue, "
        "row_number() OVER (ORDER BY revenue DESC) AS rn "
        "FROM (SELECT o_orderkey, "
        "             sum(l_extendedprice * (1 - l_discount)) AS revenue "
        "      FROM customer "
        "      JOIN orders ON c_custkey = o_custkey "
        "      JOIN lineitem ON l_orderkey = o_orderkey "
        "      WHERE c_mktsegment = 'BUILDING' "
        "      GROUP BY o_orderkey) "
        "ORDER BY revenue DESC LIMIT 10",
        {"join", "window"})
    assert names.count("jit_trace") == 0, names


def test_repartition_aot_prewarms_bucket_kernel():
    """The exchange bucketing kernel records a signature-only payload;
    after a wipe, compile_entries alone makes the next partition call
    an in-process cache hit."""
    from trino_tpu.columnar import batch_from_pylist
    from trino_tpu.stage import repartition as rp
    from trino_tpu.types import BIGINT
    b = batch_from_pylist(
        {"k": list(range(90)), "v": list(range(90))},
        {"k": BIGINT, "v": BIGINT})
    sess = Session(catalog="tpch", schema="tiny")
    ref = [p.to_pylist() for p in
           rp.partition_batch(b, ["k"], 4, session=sess)]
    rents = [e for e in HOT_SHAPES.top(100)
             if e["kind"] == "repartition"]
    assert rents
    rents = json.loads(json.dumps(rents))
    _wipe_program_caches()
    summary = aot.compile_entries(rents)
    assert summary["errors"] == 0 and summary["compiled"] >= 1
    h0 = _JIT_LOOKUPS.value(cache="repartition", result="hit")
    out = [p.to_pylist() for p in
           rp.partition_batch(b, ["k"], 4, session=sess)]
    assert out == ref
    assert _JIT_LOOKUPS.value(cache="repartition", result="hit") > h0


def test_xla_cache_dir_env_pins_exact_directory(tmp_path):
    """TRINO_TPU_XLA_CACHE_DIR (the bench's cross-round persistence
    hook) pins jax's persistent compilation cache to the EXACT path —
    no machine-tag suffix."""
    target = str(tmp_path / "xla_rounds")
    code = ("import jax, trino_tpu; "
            "print(jax.config.jax_compilation_cache_dir)")
    env = dict(os.environ)
    env["TRINO_TPU_XLA_CACHE_DIR"] = target
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, timeout=120,
                       env=env, cwd=os.path.dirname(
                           os.path.dirname(os.path.abspath(__file__))))
    assert p.returncode == 0, p.stderr
    assert p.stdout.strip().splitlines()[-1] == target
    assert os.path.isdir(target)


def test_streamed_join_with_string_probe_columns():
    """Satellite: streamed joins no longer decline dictionary-carrying
    probe columns — each chunk's codes are remapped into ONE stable
    per-stream dictionary space (build-side seeded), so every chunk
    shares one compiled program and the output matches the
    materialized path bit-for-bit."""
    from trino_tpu.obs.metrics import STREAM_CHUNKS
    r = LocalQueryRunner(session=Session(catalog="tpch",
                                         schema="tiny"))
    r.execute("CREATE TABLE memory.default.dprobe (k VARCHAR, v BIGINT)")
    rows = ",".join(f"('key{i % 13}', {i})" for i in range(150))
    r.execute(f"INSERT INTO memory.default.dprobe VALUES {rows}")
    r.execute("CREATE TABLE memory.default.dbuild (bk VARCHAR, w BIGINT)")
    rows = ",".join(f"('key{i}', {i * 100})" for i in range(9))
    r.execute(f"INSERT INTO memory.default.dbuild VALUES {rows}")
    sqls = (
        "SELECT count(*), sum(v), sum(w) FROM memory.default.dprobe "
        "JOIN memory.default.dbuild ON k = bk",
        # string payload transported through the streamed join
        "SELECT k, sum(v), sum(w) FROM memory.default.dprobe "
        "JOIN memory.default.dbuild ON k = bk GROUP BY k ORDER BY k",
        "SELECT count(*), sum(v) FROM memory.default.dprobe "
        "LEFT JOIN memory.default.dbuild ON k = bk",
    )
    base = [r.execute(q).rows for q in sqls]
    s = Session(catalog="tpch", schema="tiny")
    s.set("stream_chunk_rows", 16)
    rc = LocalQueryRunner(session=s, catalogs=r.catalogs)
    c0 = sum(v for _, v in STREAM_CHUNKS.samples())
    for q, b in zip(sqls, base):
        assert rc.execute(q).rows == b, q
    assert sum(v for _, v in STREAM_CHUNKS.samples()) > c0
