"""Mid-flight coordinator failover: RUNNING queries survive the death
of the coordinator that dispatched them.

The tentpole contract (PR 17): at dispatch time the coordinator spools
an EXECUTION manifest (identity, session, serde-proven stage payloads,
fan-out, original submit time) under the reserved fragment -2; a
replacement coordinator that receives the client's next poll for a
query it never heard of rebuilds the stage DAG from the manifest,
re-admits the query through resource groups, reads every partition the
exchange spool already holds a COMMITTED marker for, re-dispatches
only the rest, re-runs the combine and serves pages from the client's
token — bit-equal rows through the SAME nextUri chain.

Coordinator death is injected at the named fault sites
(fte/faultpoints.py) with a ``call`` action that severs the HTTP
server and raises SystemExit — a BaseException q.run cannot catch, so
the query thread freezes exactly like the process it stands in for
(no release, no persist, no error served).

The attempt ledger (the ``a<N>`` dirs under each exchange key's task
dir in the worker spool) is snapshotted AT the moment of death: keys
committed by the dead coordinator's dispatch must gain no new attempt
after failover — partitions resume at partition granularity, they are
not re-executed.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from trino_tpu.client import ClientError, StatementClient
from trino_tpu.config import CONFIG
from trino_tpu.fte import faultpoints
from trino_tpu.fte.recovery import ExecutionManifestStore
from trino_tpu.fte.spool import worker_spool_base
from trino_tpu.obs.metrics import FAILOVER_PARTITIONS, METRICS
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.server.coordinator import Coordinator
from trino_tpu.server.task_worker import TaskWorkerServer
from trino_tpu.session import Session

# 3-stage plan (two partitioned sources feeding a partitioned join/agg
# stage) so a death after the FIRST stage commit leaves real committed
# AND real missing partitions
SQL = ("SELECT n_name, count(*) FROM nation "
       "JOIN region ON n_regionkey = r_regionkey "
       "GROUP BY n_name ORDER BY n_name")

TASK_PROPS = {"retry_policy": "TASK", "retry_initial_delay_ms": "10",
              "remote_task_timeout": "30"}


@pytest.fixture(scope="module")
def workers():
    w1, w2 = TaskWorkerServer().start(), TaskWorkerServer().start()
    yield [w1.base_uri, w2.base_uri]
    w1.stop()
    w2.stop()


@pytest.fixture(scope="module")
def expected():
    res = LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny")).execute(SQL)
    return [list(r) for r in res.rows]


@pytest.fixture(autouse=True)
def _clean_faults():
    faultpoints.reset()
    yield
    faultpoints.reset()


def _exchange_ledger(exec_prefix: str):
    """{exchange key: (committed?, frozenset of attempt dirs)} for one
    execution's keys in the shared worker spool — the durable record
    of which attempts ever produced each partition."""
    base = worker_spool_base()
    out = {}
    try:
        names = os.listdir(base)
    except OSError:
        return out
    for name in names:
        if not name.startswith(exec_prefix):
            continue
        tdir = os.path.join(base, name, "f0.p0")
        try:
            entries = os.listdir(tdir)
        except OSError:
            continue
        out[name] = ("COMMITTED" in entries,
                     frozenset(e for e in entries
                               if e.startswith("a")
                               and not e.startswith("a.")))
    return out


class _Failover:
    """One staged coordinator death: co1 dispatches, dies at ``site``;
    co2 binds the SAME port and spool. The kill callback snapshots the
    manifest + attempt ledger at the instant of death."""

    def __init__(self, worker_uris, site, boot_delay_s=0.0,
                 boot_second=True):
        self.uris = list(worker_uris)
        self.site = site
        self.boot_delay_s = boot_delay_s
        self.boot_second = boot_second
        self.co1 = Coordinator(worker_uris=self.uris).start()
        self.co2 = None
        self.died_at = None
        self.manifest = None
        self.ledger_at_death = {}
        self._closed = threading.Event()
        faultpoints.install(site, callback=self._kill)
        if boot_second:
            threading.Thread(target=self._boot_replacement,
                             daemon=True).start()

    def _kill(self, site):
        self.died_at = time.time()
        # observe the durable state the next coordinator will see:
        # the spooled manifest and the committed-attempt ledger
        qids = list(self.co1.tracker._queries)
        if qids:
            self.manifest = ExecutionManifestStore(
                self.co1.spool).load(qids[0])
        if self.manifest is not None:
            self.ledger_at_death = _exchange_ledger(
                str(self.manifest["execId"]) + ".")
        # the "process" dies: HTTP gone, no cleanup may run after —
        # SystemExit is a BaseException q.run cannot catch, so the
        # query thread freezes mid-flight like its process did; the
        # cancel event stops the corpse's scheduler threads from
        # dispatching anything further (in a real death they die too —
        # worker-side tasks already dispatched keep running and
        # committing, exactly like real orphaned tasks)
        for q in self.co1.tracker._queries.values():
            q._cancel.set()
        self.co1.tracker.manifests = None
        self.co1.tracker.results = None
        self.co1._httpd.shutdown()
        self.co1._httpd.server_close()
        self._closed.set()
        raise SystemExit

    def _boot_replacement(self):
        self._closed.wait(60)
        if self.boot_delay_s:
            time.sleep(self.boot_delay_s)
        for _ in range(200):       # the dying server's port may linger
            try:
                self.co2 = Coordinator(port=self.co1.port,
                                       worker_uris=self.uris).start()
                return
            except OSError:
                time.sleep(0.02)

    def stop(self):
        try:
            self.co1.stop()
        except Exception:          # noqa: BLE001 — already half-dead
            pass
        if self.co2 is not None:
            self.co2.stop()


# --------------------------------------------------------------------------
# the chaos matrix: death at each coordinator fault site
# --------------------------------------------------------------------------

@pytest.mark.parametrize("site", ["coordinator.pre_dispatch",
                                  "coordinator.post_stage_commit",
                                  "coordinator.mid_combine"])
def test_failover_matrix_resumes_with_bit_equal_rows(
        workers, expected, site):
    """Kill co1 at each fault site; co2 must finish the query with
    bit-equal rows, replaying ONLY partitions without a COMMITTED
    marker (attempt ledger: committed keys gain no new attempts)."""
    r0 = FAILOVER_PARTITIONS.value(outcome="resumed")
    p0 = FAILOVER_PARTITIONS.value(outcome="replayed")
    fo = _Failover(workers, site)
    try:
        client = StatementClient(fo.co1.base_uri,
                                 session_properties=TASK_PROPS)
        res = client.execute(SQL)
        assert res.state == "FINISHED"
        assert [list(r) for r in res.rows] == expected
        assert fo.died_at is not None, "fault never fired"
        assert fo.manifest is not None, "manifest missing at death"
        # the resumed run was real: co2 touched the failover path
        resumed = FAILOVER_PARTITIONS.value(outcome="resumed") - r0
        replayed = FAILOVER_PARTITIONS.value(outcome="replayed") - p0
        assert resumed + replayed > 0
        committed_at_death = {k for k, (c, _) in
                              fo.ledger_at_death.items() if c}
        if site == "coordinator.pre_dispatch":
            # death BEFORE any dispatch: everything replays
            assert not committed_at_death and resumed == 0
            assert replayed > 0
        elif site == "coordinator.mid_combine":
            # death with every stage committed: nothing replays
            assert committed_at_death and replayed == 0
            assert resumed >= len(committed_at_death)
        else:
            # post_stage_commit: the first stage had committed. How
            # much of the REST was missing at resume time depends on
            # how far the orphaned worker tasks got before dying
            # coordinator's dispatch stopped — "replays only
            # uncommitted" is the ledger invariant below, not a count
            assert committed_at_death
            assert resumed >= len(committed_at_death)
        # attempt ledger: a partition committed by the DEAD
        # coordinator's dispatch was never re-executed — its key kept
        # the marker and gained no new attempt dir
        after = _exchange_ledger(str(fo.manifest["execId"]) + ".")
        for key in committed_at_death:
            assert after[key][0], f"{key} lost its COMMITTED marker"
            assert after[key][1] == fo.ledger_at_death[key][1], \
                f"{key} gained attempts after failover"
    finally:
        fo.stop()


def test_acceptance_post_stage_commit_failover(workers, expected):
    """ISSUE acceptance: a 3-stage query killed at
    coordinator.post_stage_commit after the first stage commits; the
    second coordinator on the same spool resumes, stage-1 partitions
    are read off the spool WITHOUT re-dispatching stage 1 (attempt
    ledger + failover metrics prove zero stage-1 re-executions), and
    the client receives complete bit-exact results through the same
    nextUri chain."""
    resumed0 = METRICS.counter(
        "trino_tpu_exec_manifests_resumed_total").value()
    r0 = FAILOVER_PARTITIONS.value(outcome="resumed")
    fo = _Failover(workers, "coordinator.post_stage_commit")
    try:
        client = StatementClient(fo.co1.base_uri,
                                 session_properties=TASK_PROPS)
        res = client.execute(SQL)        # one POST, one nextUri chain
        assert res.state == "FINISHED"
        assert [list(r) for r in res.rows] == expected
        mf = fo.manifest
        assert mf is not None and len(mf["stages"]) >= 3
        # stage 1 (the first stage the scheduler awaited) had
        # committed when the coordinator died...
        first_sid = min(int(s["sid"]) for s in mf["stages"])
        stage1_keys = {k for k in fo.ledger_at_death
                       if f".s{first_sid}.p" in k}
        committed1 = {k for k in stage1_keys
                      if fo.ledger_at_death[k][0]}
        assert committed1, "no stage-1 partition committed at death"
        # ...and NONE of its partitions were re-executed: same marker,
        # same attempt set, and the resume counter covers them
        after = _exchange_ledger(str(mf["execId"]) + ".")
        for key in committed1:
            assert after[key][0]
            assert after[key][1] == fo.ledger_at_death[key][1]
        assert FAILOVER_PARTITIONS.value(outcome="resumed") - r0 \
            >= len(committed1)
        assert METRICS.counter(
            "trino_tpu_exec_manifests_resumed_total").value() \
            == resumed0 + 1
        # the resumed query is served under its ORIGINAL id + slug
        q2 = fo.co2.tracker.get(res.query_id)
        assert q2 is not None and q2.state == "FINISHED"
    finally:
        fo.stop()


# --------------------------------------------------------------------------
# gating + hygiene + accounting
# --------------------------------------------------------------------------

def test_none_policy_queries_are_not_resumable(workers):
    """retry_policy=NONE writes no execution manifest, so after the
    coordinator dies mid-flight the replacement must 404 the poll —
    resumption is gated exactly like task retries are."""
    fo = _Failover(workers, "coordinator.post_stage_commit")
    try:
        client = StatementClient(
            fo.co1.base_uri,
            session_properties={"remote_task_timeout": "30"})
        with pytest.raises(urllib.error.HTTPError) as err:
            client.execute(SQL)
        assert err.value.code == 404
        assert fo.died_at is not None and fo.manifest is None
    finally:
        fo.stop()


def test_manifest_released_on_normal_completion(workers):
    """Spool hygiene: a query that finishes normally must not leave
    its execution manifest behind (the result fragment -1 stays for
    restart recovery, the manifest fragment -2 goes)."""
    co = Coordinator(worker_uris=workers).start()
    try:
        client = StatementClient(co.base_uri,
                                 session_properties=TASK_PROPS)
        res = client.execute(SQL)
        assert res.state == "FINISHED"
        qdir = os.path.join(CONFIG.spool_dir, res.query_id)
        deadline = time.time() + 5
        while os.path.isdir(os.path.join(qdir, "f-2.p0")) \
                and time.time() < deadline:
            time.sleep(0.02)
        assert not os.path.isdir(os.path.join(qdir, "f-2.p0")), \
            "execution manifest outlived its query"
        assert os.path.isdir(os.path.join(qdir, "f-1.p0")), \
            "result fragment must survive the manifest release"
        assert ExecutionManifestStore(co.spool).load(res.query_id) \
            is None
    finally:
        co.stop()


def test_delete_releases_manifest_and_blocks_resume(workers):
    """A slug-bearing DELETE against the replacement coordinator kills
    the orphaned query's resumability: the manifest is released and a
    later poll 404s instead of resuming."""
    fo = _Failover(workers, "coordinator.post_stage_commit")
    try:
        # drive the protocol by hand: we must NOT let a poll reach co2
        # before the DELETE, or it would legitimately resume
        req = urllib.request.Request(
            fo.co1.base_uri + "/v1/statement", data=SQL.encode(),
            headers={"X-Trino-Catalog": "tpch",
                     "X-Trino-Schema": "tiny",
                     "X-Trino-Session": ",".join(
                         f"{k}={v}" for k, v in TASK_PROPS.items())})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        qid = out["id"]
        slug = out["nextUri"].split("/")[-2]
        assert fo._closed.wait(30), "fault never fired"
        deadline = time.time() + 10
        while fo.co2 is None and time.time() < deadline:
            time.sleep(0.02)
        assert fo.co2 is not None
        # wrong slug: the capability token guards destruction too
        bad = urllib.request.Request(
            f"{fo.co2.base_uri}/v1/statement/executing/"
            f"{qid}/forged",
            method="DELETE")
        with urllib.request.urlopen(bad, timeout=10):
            pass
        assert ExecutionManifestStore(fo.co2.spool).load(qid) \
            is not None
        good = urllib.request.Request(
            f"{fo.co2.base_uri}/v1/statement/executing/"
            f"{qid}/{slug}",
            method="DELETE")
        with urllib.request.urlopen(good, timeout=10):
            pass
        assert ExecutionManifestStore(fo.co2.spool).load(qid) is None
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"{fo.co2.base_uri}/v1/statement/executing/"
                f"{qid}/{slug}/0", timeout=10)
        assert err.value.code == 404
    finally:
        fo.stop()


def test_resume_honors_original_time_budget(workers):
    """EXCEEDED_TIME_LIMIT must span the restart: the resumed query's
    deadline anchors at the ORIGINAL submit epoch from the manifest,
    so a query whose query_max_run_time budget was spent while its
    coordinator lay dead fails on arrival at the replacement — it
    does not get a fresh budget."""
    limit = 4
    fo = _Failover(workers, "coordinator.pre_dispatch",
                   boot_second=False)
    try:
        props = dict(TASK_PROPS, query_max_run_time=str(limit))
        req = urllib.request.Request(
            fo.co1.base_uri + "/v1/statement", data=SQL.encode(),
            headers={"X-Trino-Catalog": "tpch",
                     "X-Trino-Schema": "tiny",
                     "X-Trino-Session": ",".join(
                         f"{k}={v}" for k, v in props.items())})
        submit_t = time.time()
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        qid, next_uri = out["id"], out["nextUri"]
        assert fo._closed.wait(30), "fault never fired"
        # let the ORIGINAL budget run out while no coordinator lives
        time.sleep(max(0.0, submit_t + limit + 0.5 - time.time()))
        fo.co2 = Coordinator(port=fo.co1.port,
                             worker_uris=workers).start()
        deadline = time.time() + 20
        payload = None
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(next_uri, timeout=10) as r:
                    payload = json.loads(r.read())
            except urllib.error.URLError:
                time.sleep(0.05)
                continue
            if payload["stats"]["state"] in ("FAILED", "FINISHED",
                                             "CANCELED"):
                break
            next_uri = payload.get("nextUri") or next_uri
            time.sleep(0.05)
        assert payload is not None
        assert payload["stats"]["state"] == "FAILED", payload
        assert payload["error"]["errorName"] == "EXCEEDED_TIME_LIMIT"
        # accounting spans coordinators: elapsed includes the dead time
        assert payload["stats"]["elapsedTimeMillis"] >= limit * 1000
        q2 = fo.co2.tracker.get(qid)
        assert q2 is not None and q2.created <= submit_t + 1.0
    finally:
        fo.stop()
