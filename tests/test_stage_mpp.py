"""Multi-stage MPP: stage-DAG fragmenter, hash-repartition kernel,
worker-to-worker partitioned exchange, and per-stage fault tolerance.

Reference parity: SqlQueryScheduler -> SqlStageExecution -> RemoteTask
with PartitionedOutputOperator hash repartition (SURVEY L5/L6) — the
acceptance shape is a distributed hash-join + final-aggregation query
whose join and FINAL aggregation execute ON WORKERS (per-stage rollup
proves it), the coordinator executing only the root-stage stream, and
a worker killed mid-DAG recovering via per-stage retry off the spool.
"""

import threading

import numpy as np
import pytest

from trino_tpu.columnar import batch_from_pylist
from trino_tpu.exec.remote import DistributedHostQueryRunner
from trino_tpu.obs.metrics import METRICS
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.serde import deserialize_batch
from trino_tpu.server.task_worker import TaskWorkerServer
from trino_tpu.session import Session
from trino_tpu.stage.fragmenter import StageFragmenter
from trino_tpu.stage.repartition import (partition_batch,
                                         partition_buckets,
                                         partition_frames)
from trino_tpu.types import BIGINT, DOUBLE, VARCHAR

JOIN_AGG_SQL = ("SELECT n_name, count(*) FROM nation "
                "JOIN region ON n_regionkey = r_regionkey "
                "WHERE r_name = 'ASIA' GROUP BY n_name "
                "ORDER BY n_name")


def _counter(name: str) -> float:
    return sum(v for _, v in METRICS.counter(name).samples())


def _mpp_session(**props) -> Session:
    s = Session(catalog="tpch", schema="tiny")
    s.set("multistage_execution", True)
    for k, v in props.items():
        s.set(k, v)
    return s


# --------------------------------------------------------------------------
# repartition kernel: determinism, completeness, disjointness
# --------------------------------------------------------------------------

def test_bucket_determinism_golden():
    """Buckets are a pure function of key VALUES — pinned against
    golden constants so any process-local or algorithmic drift (a
    seed, a different mix) fails loudly: two workers disagreeing on a
    bucket silently drops join matches."""
    b = batch_from_pylist({"k": list(range(8))}, {"k": BIGINT})
    got = [int(x) for x in partition_buckets(b, ["k"], 4)]
    assert got == [int(x) for x in partition_buckets(b, ["k"], 4)]
    # golden: mix64(v) % 4 for v in 0..7 (pinned — see GOLDEN below)
    assert got == _GOLDEN_BUCKETS, got


# computed once from an independent pure-python splitmix64 (x ^= x>>30;
# x *= BF58476D1CE4E5B9; x ^= x>>27; x *= 94D049BB133111EB; x ^= x>>31;
# mod 4) — a change here is a WIRE-FORMAT change (workers of different
# versions would disagree on buckets mid-query) and must be deliberate
_GOLDEN_BUCKETS = [0, 1, 2, 0, 0, 0, 0, 0]


def test_bucket_ignores_dictionary_code_assignment():
    """The same string VALUES under different dictionary code layouts
    (two workers build dictionaries in different scan orders) must
    bucket identically — codes are process-local, values are not."""
    rows = ["pear", "apple", "plum", "apple", "fig", "pear"]
    a = batch_from_pylist({"s": rows}, {"s": VARCHAR})
    b = batch_from_pylist({"s": list(reversed(rows))}, {"s": VARCHAR})
    ba = [int(x) for x in partition_buckets(a, ["s"], 5)]
    bb = [int(x) for x in partition_buckets(b, ["s"], 5)]
    assert ba == list(reversed(bb))
    # and same-value rows always share a bucket
    assert ba[0] == ba[5] and ba[1] == ba[3]


def test_null_keys_colocate_on_partition_zero():
    b = batch_from_pylist({"k": [None, 7, None, 123]}, {"k": BIGINT})
    bk = partition_buckets(b, ["k"], 4)
    assert bk[0] == bk[2] == 0      # NULL hashes to 0 (Trino convention)


def test_partitions_complete_and_disjoint():
    """Property test: partitioning a mixed-type batch (ints, strings,
    floats, NULLs) is a permutation — every row lands in exactly one
    partition, and its frame index equals its key bucket."""
    rng = np.random.default_rng(7)
    n = 500
    ks = [int(rng.integers(0, 40)) for _ in range(n)]
    ss = [f"s{int(rng.integers(0, 17))}" for _ in range(n)]
    xs = [float(rng.standard_normal()) if i % 11 else None
          for i in range(n)]
    b = batch_from_pylist({"k": ks, "s": ss, "x": xs},
                          {"k": BIGINT, "s": VARCHAR, "x": DOUBLE})
    nparts = 7
    parts = partition_batch(b, ["k", "s"], nparts)
    assert len(parts) == nparts
    got = [r for p in parts for r in p.to_pylist()]
    assert len(got) == n
    key = lambda r: (r[0], r[1])                         # noqa: E731
    assert sorted(map(repr, got)) == sorted(
        map(repr, b.to_pylist()))                        # multiset-equal
    # same key -> same partition, and bucket == frame index
    bk = partition_buckets(b, ["k", "s"], nparts)
    by_key = {}
    for r, p in zip(b.to_pylist(), bk):
        assert by_key.setdefault(key(r), int(p)) == int(p)
    for i, p in enumerate(parts):
        for r in p.to_pylist():
            assert by_key[key(r)] == i


def test_partition_frames_layout():
    """frame i IS partition i; empty partitions are real zero-row
    frames; gather emits exactly one frame with every row."""
    b = batch_from_pylist({"k": [1, 1, 1]}, {"k": BIGINT})
    frames = partition_frames(b, ["k"], "hash", 5)
    assert len(frames) == 5
    decoded = [deserialize_batch(f) for f in frames]
    counts = [d.num_rows_host() for d in decoded]
    assert sum(counts) == 3 and counts.count(0) == 4    # one hot bucket
    gather = partition_frames(b, (), "gather", 5)
    assert len(gather) == 1
    assert deserialize_batch(gather[0]).num_rows_host() == 3
    # replicate spools ONE frame (not one per consumer task): the
    # broadcast fan-out lives on the consumer side (every task reads
    # frame 0 — stage/exchange.py), so the bytes are written once
    rep = partition_frames(b, (), "replicate", 5)
    assert len(rep) == 1
    assert deserialize_batch(rep[0]).num_rows_host() == 3


# --------------------------------------------------------------------------
# fragmenter: the DAG shape
# --------------------------------------------------------------------------

def _optimized(sql, cat="tpch", schema="tiny"):
    from trino_tpu.planner.logical import LogicalPlanner
    from trino_tpu.planner.optimizer import optimize
    from trino_tpu.sql.parser import parse_statement
    r = LocalQueryRunner(session=Session(catalog=cat, schema=schema))
    return r, optimize(LogicalPlanner(r.catalogs, r.session).plan(
        parse_statement(sql)), r.catalogs, r.session)


def test_fragmenter_cuts_join_agg_dag():
    """The acceptance DAG: two leaf scan stages, a join stage with the
    PARTIAL aggregation fused above it, a FINAL aggregation stage —
    the coordinator root carries only gather-side nodes."""
    from trino_tpu.plan.nodes import (AggregationNode, JoinNode,
                                      RemoteSourceNode, TableScanNode)
    from trino_tpu.analysis.sanity import (validate_stage_dag,
                                           walk_plan)
    r, plan = _optimized(JOIN_AGG_SQL)
    dag = StageFragmenter(r.catalogs, r.session).fragment(plan)
    assert dag is not None and len(dag.stages) >= 3
    kinds = [{type(n).__name__ for n in walk_plan(st.plan)}
             for st in dag.stages]
    assert any("JoinNode" in k for k in kinds)           # join on workers
    assert sum("AggregationNode" in k for k in kinds) >= 2  # partial+final
    # leaves scan, intermediates exchange
    leaf = dag.stages[0]
    assert not leaf.inputs and any(
        isinstance(n, TableScanNode) for n in walk_plan(leaf.plan))
    # the root is exchange-fed only: no scan, join, or aggregation
    root_kinds = {type(n).__name__ for n in walk_plan(dag.root_plan)}
    assert "RemoteSourceNode" in root_kinds
    assert not root_kinds & {"TableScanNode", "JoinNode",
                             "AggregationNode"}
    # the boundary battery accepts what the fragmenter produced and
    # returns one wire payload per stage
    payloads = validate_stage_dag(dag)
    assert sorted(payloads) == [st.sid for st in dag.stages]


def test_fragmenter_semi_join_replicates_filtering_source():
    """Semi joins fragment now: the filtering source becomes a
    REPLICATE stage (every task sees the whole relation, so NULL-IN
    semantics hold per task) and the probe scan stays INLINE in the
    consuming stage — no probe-side exchange hop."""
    from trino_tpu.analysis.sanity import walk_plan
    from trino_tpu.plan.nodes import SemiJoinNode, TableScanNode
    r, plan = _optimized(
        "SELECT count(*) FROM orders WHERE o_custkey IN "
        "(SELECT c_custkey FROM customer)")
    dag = StageFragmenter(r.catalogs, r.session).fragment(plan)
    assert dag is not None
    kinds = {st.sid: st.output_node.kind for st in dag.stages}
    assert "replicate" in kinds.values()
    # the semi-join stage carries BOTH the probe scan and the semi join
    # (colocated — the probe never crossed an exchange)
    for st in dag.stages:
        names = {type(n).__name__ for n in walk_plan(st.plan)}
        if "SemiJoinNode" in names:
            assert "TableScanNode" in names
            break
    else:
        raise AssertionError("no stage carries the semi join")


def test_fragmenter_declines_unsupported_shapes():
    """Non-remotable (coordinator-state-backed) scans stay on the flat
    path."""
    r2, plan2 = _optimized(
        "SELECT node_id, count(*) FROM system.runtime.nodes "
        "GROUP BY node_id")
    assert StageFragmenter(r2.catalogs,
                           r2.session).fragment(plan2) is None


def test_stage_boundary_checker_rejects_broken_edges():
    from dataclasses import replace as dc_replace
    from trino_tpu.analysis.sanity import (PlanValidationError,
                                           validate_stage_dag)
    from trino_tpu.plan.nodes import RemoteSourceNode
    from trino_tpu.stage.fragmenter import StageDAG
    r, plan = _optimized(JOIN_AGG_SQL)
    dag = StageFragmenter(r.catalogs, r.session).fragment(plan)
    final_sid = dag.stages[-1].sid
    final_schema = dag.stages[-1].plan.output_schema()

    # partition key the body does not produce
    broken = [dc_replace(st) for st in dag.stages]
    broken[0].plan = dc_replace(broken[0].plan,
                                partition_keys=("nonexistent$",))
    with pytest.raises(PlanValidationError) as e:
        validate_stage_dag(StageDAG(broken, dag.root_plan))
    assert "partition keys" in str(e.value)

    # RemoteSource naming a stage that does not exist
    with pytest.raises(PlanValidationError,
                       match="StageBoundaryChecker"):
        validate_stage_dag(StageDAG(
            list(dag.stages),
            RemoteSourceNode((99,), final_schema, "gather")))

    # consumer schema type drift across the edge
    drifted = {s: (VARCHAR if str(t) != "varchar" else BIGINT)
               for s, t in final_schema.items()}
    with pytest.raises(PlanValidationError,
                       match="StageBoundaryChecker"):
        validate_stage_dag(StageDAG(
            list(dag.stages),
            RemoteSourceNode((final_sid,), drifted, "gather")))


def test_partitioned_output_key_closure_in_plan_battery():
    """The per-plan half of the satellite: ValidateDependenciesChecker
    rejects a PartitionedOutputNode whose keys the body lacks."""
    from trino_tpu.analysis.sanity import (PlanValidationError,
                                           validate_plan)
    from trino_tpu.plan.nodes import PartitionedOutputNode
    r, plan = _optimized("SELECT n_regionkey FROM nation")
    body = plan.source if hasattr(plan, "source") else plan
    good_key = next(iter(body.output_schema()))
    validate_plan(PartitionedOutputNode(body, (good_key,), "hash"))
    with pytest.raises(PlanValidationError,
                       match="ValidateDependenciesChecker"):
        validate_plan(PartitionedOutputNode(body, ("missing$",),
                                            "hash"))


# --------------------------------------------------------------------------
# e2e: distributed == local through REAL worker servers
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def workers():
    ws = [TaskWorkerServer().start() for _ in range(2)]
    yield [w.base_uri for w in ws]
    for w in ws:
        w.stop()


def _check(workers, sql, approx=(), **props):
    dist = DistributedHostQueryRunner(
        workers, session=_mpp_session(**props))
    local = LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny"))
    got = dist.execute(sql)
    exp = local.execute(sql)
    assert got.columns == exp.columns
    assert len(got.rows) == len(exp.rows)
    for g, e in zip(got.rows, exp.rows):
        for i, (gv, ev) in enumerate(zip(g, e)):
            if i in approx:
                assert gv == pytest.approx(ev, rel=1e-9)
            else:
                assert gv == ev
    return dist


def test_mpp_join_aggregation_matches_local(workers):
    before = _counter("trino_tpu_exchange_partitions_total")
    _check(workers, JOIN_AGG_SQL)
    # the partitioned exchange actually moved frames
    assert _counter("trino_tpu_exchange_partitions_total") > before


def test_mpp_three_table_join_matches_local(workers):
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    _check(workers, TPCH_QUERIES[3], approx=(1,))


def test_mpp_distinct_aggregation_repartitions_rows(workers):
    """Holistic kinds (count DISTINCT) cannot split PARTIAL/FINAL —
    the rows themselves repartition by group key."""
    _check(workers,
           "SELECT n_name, count(DISTINCT s_suppkey) FROM supplier "
           "JOIN nation ON s_nationkey = n_nationkey "
           "GROUP BY n_name ORDER BY n_name")


def test_mpp_global_aggregation_finalizes_on_worker(workers):
    _check(workers,
           "SELECT count(*), sum(l_quantity), avg(l_discount) "
           "FROM lineitem", approx=(2,))


def test_mpp_window_partitions_by_keys(workers):
    _check(workers,
           "SELECT c_custkey, o_orderkey, row_number() OVER "
           "(PARTITION BY c_custkey ORDER BY o_orderdate) rn "
           "FROM customer JOIN orders ON c_custkey = o_custkey "
           "WHERE c_custkey < 20 ORDER BY c_custkey, rn")


def test_mpp_decimal_avg_exact(workers):
    """Decimal avg through the exchange stays bit-exact (Int128 sums,
    decimal division in the FINAL stage's reconstruction)."""
    dist = DistributedHostQueryRunner(
        workers, session=Session(catalog="tpcds", schema="tiny",
                                 properties={
                                     "multistage_execution": True}))
    local = LocalQueryRunner(
        session=Session(catalog="tpcds", schema="tiny"))
    sql = ("SELECT ss_store_sk, sum(ss_ext_sales_price), "
           "avg(ss_sales_price) FROM store_sales "
           "GROUP BY ss_store_sk ORDER BY ss_store_sk")
    assert dist.execute(sql).rows == local.execute(sql).rows


def test_explain_analyze_proves_worker_side_execution(workers):
    """THE acceptance criterion: >= 3 stages, the join and the final
    aggregation tagged with worker stages in the per-stage rollup, the
    coordinator executing only the root-stage stream."""
    dist = DistributedHostQueryRunner(
        workers, session=_mpp_session())
    res = dist.execute("EXPLAIN ANALYZE " + JOIN_AGG_SQL)
    text = "\n".join(r[0] for r in res.rows)
    stage_heads = [l for l in text.splitlines()
                   if l.startswith("Stage ")]
    assert len(stage_heads) >= 4        # >=3 worker stages + root
    stats = {}
    for line in text.splitlines():
        if "stage " not in line or ":" not in line:
            continue
        name = line.split(":")[0].strip()
        where = line[line.index("stage "):]
        stats.setdefault(name, []).append(where)
    # the join and SOME aggregation ran on a worker stage...
    assert any(w.startswith("stage ") and "coordinator" not in w
               for w in stats.get("Join", [])), stats
    assert any(w.startswith("stage ") and "coordinator" not in w
               for w in stats.get("Aggregation", [])), stats
    # ...every aggregation did (none fell to the coordinator)...
    assert all("coordinator" not in w
               for w in stats.get("Aggregation", [])), stats
    # ...and the coordinator ran ONLY root-stage gather-side nodes
    coord = [n for n, ws in stats.items()
             if any("coordinator" in w for w in ws)]
    assert set(coord) <= {"RemoteSource", "Sort", "Output",
                          "Project", "Limit"}, coord


def test_exchange_partition_count_caps_intermediate_fanout(workers):
    """Session-property plumbing, end to end: the intermediate stages
    run exactly exchange_partition_count tasks while leaves keep the
    per-worker fan-out. PARTITIONED distribution pinned — under the
    default AUTOMATIC the tiny build side makes the join REPLICATED,
    which colocates it with the probe scan (leaf fan-out by design)."""
    dist = DistributedHostQueryRunner(
        workers, session=_mpp_session(
            exchange_partition_count=1,
            join_distribution_type="PARTITIONED"))
    res = dist.execute("EXPLAIN ANALYZE " + JOIN_AGG_SQL)
    text = "\n".join(r[0] for r in res.rows)
    joins = [l for l in text.splitlines() if l.startswith("Join:")]
    assert joins and all("x1 tasks" in l for l in joins), joins
    scans = [l for l in text.splitlines()
             if l.startswith("TableScan:")]
    assert scans and all("x2 tasks" in l for l in scans), scans


# --------------------------------------------------------------------------
# per-stage fault tolerance: mid-DAG kill + straggler speculation
# --------------------------------------------------------------------------

def _kill_server(worker) -> None:
    """shutdown + close: connections REFUSE immediately (a dead
    process), instead of a zombie listening socket absorbing
    30s-timeout polls — the half-open-socket shape is covered by the
    eager-pull candidate sweep's short probe timeout."""
    def stop():
        worker._httpd.shutdown()
        worker._httpd.server_close()
    threading.Thread(target=stop, daemon=True).start()


class _SabotagedWorker(TaskWorkerServer):
    """Executes leaf-stage tasks normally (committing their output to
    the spool), then DIES the first time it receives a mid-DAG
    (exchange-fed) task — the acceptance kill: the upstream partitions
    it already committed must survive it."""

    def create_task(self, tid, payload):
        stage = payload.get("stage") or {}
        if stage.get("sources") and not getattr(self, "_killed",
                                                False):
            self._killed = True
            _kill_server(self)
            raise ConnectionResetError("killed mid-DAG")
        return super().create_task(tid, payload)


def test_mid_dag_worker_kill_recovers_off_spool():
    bad = _SabotagedWorker().start()
    good = TaskWorkerServer().start()
    retries_before = _counter("trino_tpu_task_retries_total")
    try:
        runner = DistributedHostQueryRunner(
            [bad.base_uri, good.base_uri],
            session=_mpp_session(retry_policy="TASK",
                                 retry_initial_delay_ms=10,
                                 remote_task_timeout=30),
            collect_node_stats=True)
        res = runner.execute(JOIN_AGG_SQL)
    finally:
        good.stop()
    exp = LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny")).execute(
            JOIN_AGG_SQL)
    assert res.rows == exp.rows
    assert _counter("trino_tpu_task_retries_total") > retries_before
    # the retry is visible in the trace as a stage-tagged span
    names = []

    def walk(spans):
        for sp in spans:
            names.append(sp["name"])
            walk(sp.get("children", []))

    walk(res.trace.to_dicts())
    assert any(n.startswith("stage_") and n.endswith("_retry")
               for n in names), names


class _RecordingWorker(TaskWorkerServer):
    """Records every task id it is asked to execute (attempt
    bookkeeping for the replay-scope assertion below)."""

    def __init__(self, *a, **k):
        super().__init__(*a, **k)
        self.seen = []

    def create_task(self, tid, payload):
        self.seen.append(tid)
        return super().create_task(tid, payload)


class _RecordingSabotagedWorker(_RecordingWorker):
    """Executes leaf tasks normally, records everything, then DIES on
    its first mid-DAG (exchange-fed) task — the mid-pipeline kill."""

    def create_task(self, tid, payload):
        stage = payload.get("stage") or {}
        if stage.get("sources") and not getattr(self, "_killed",
                                                False):
            self._killed = True
            self.seen.append(tid)
            _kill_server(self)
            raise ConnectionResetError("killed mid-pipeline")
        return super().create_task(tid, payload)


def test_mid_pipeline_kill_replays_only_uncommitted():
    """THE pipelining chaos contract: a worker killed while the DAG is
    eagerly pipelined costs only the partitions it had NOT yet
    committed. Every (stage, part) task that ran more than once must
    have lost its FIRST attempt to the killed worker — a task whose
    first attempt committed on a surviving worker is never
    re-executed (consumers re-pull its committed frames off the spool
    instead)."""
    bad = _RecordingSabotagedWorker().start()
    good = [_RecordingWorker().start() for _ in range(2)]
    retries_before = _counter("trino_tpu_task_retries_total")
    try:
        runner = DistributedHostQueryRunner(
            [bad.base_uri] + [g.base_uri for g in good],
            session=_mpp_session(retry_policy="TASK",
                                 retry_initial_delay_ms=10,
                                 remote_task_timeout=60))
        res = runner.execute(JOIN_AGG_SQL)
    finally:
        for g in good:
            g.stop()
    exp = LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny")).execute(
            JOIN_AGG_SQL)
    assert res.rows == exp.rows
    assert _counter("trino_tpu_task_retries_total") > retries_before
    # attempt ledger: tid == <qid>.s<sid>.<part>.a<attempt>
    execs = {}
    for who, w in [("bad", bad)] + [("good", g) for g in good]:
        for tid in w.seen:
            _, s, p, a = tid.rsplit(".", 3)
            execs.setdefault((s, p), []).append((int(a[1:]), who))
    replayed = {k: sorted(v) for k, v in execs.items() if len(v) > 1}
    assert replayed, "the kill must have forced at least one replay"
    for key, attempts in replayed.items():
        assert attempts[0][1] == "bad", (
            f"task {key} was re-executed although its first attempt "
            f"ran on a surviving worker: {attempts} — a committed "
            "partition was replayed")


class _StuckWorker:
    """Accepts every task and reports RUNNING forever — the straggler
    shape (a wedged, not dead, worker)."""

    def __init__(self):
        import json
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, payload):
                body = json.dumps(payload).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                self._json({"taskId": "x", "state": "RUNNING"})

            def do_GET(self):
                self._json({"state": "RUNNING"})

            def do_DELETE(self):
                self.send_response(204)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.base_uri = \
            f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_stage_speculation_rescues_straggler(workers):
    """First-completion-wins per stage: tasks stuck on the wedged
    worker are speculatively duplicated once siblings establish the
    stage's runtime median; the spool's first-commit-wins arbitrates."""
    stuck = _StuckWorker()
    wins_before = _counter("trino_tpu_speculative_wins_total")
    try:
        # stuck worker LAST: single-task stages home on worker 0
        runner = DistributedHostQueryRunner(
            workers + [stuck.base_uri],
            session=_mpp_session(speculation_enabled=True,
                                 speculation_multiplier=1.5,
                                 speculation_min_runtime_ms=100,
                                 remote_task_timeout=60))
        res = runner.execute(JOIN_AGG_SQL)
    finally:
        stuck.stop()
    exp = LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny")).execute(
            JOIN_AGG_SQL)
    assert res.rows == exp.rows
    assert _counter("trino_tpu_speculative_wins_total") > wins_before


def test_mpp_semi_join_matches_local(workers):
    """NULL-IN semantics through the replicate exchange: the filtering
    side (with NULL-capable keys) replicates whole, so the per-task
    verdicts equal the local engine's."""
    _check(workers,
           "SELECT count(*) FROM orders WHERE o_custkey IN "
           "(SELECT c_custkey FROM customer WHERE c_acctbal > 0)")
    _check(workers,
           "SELECT count(*) FROM customer WHERE c_custkey NOT IN "
           "(SELECT o_custkey FROM orders WHERE o_totalprice > 100000)")


def test_mpp_cross_join_matches_local(workers):
    _check(workers,
           "SELECT count(*) FROM nation CROSS JOIN region")
    # non-equi join filter (no equi-criteria): replicate-right shape
    _check(workers,
           "SELECT n1.n_name, n2.n_name FROM nation n1 "
           "JOIN nation n2 ON n1.n_nationkey < n2.n_nationkey "
           "WHERE n1.n_regionkey = 0 ORDER BY 1, 2")


def test_mpp_grouping_sets_matches_local(workers):
    """Grouping sets repartition rows on (keys..., grouping-set id):
    GroupIdNode expands split-locally in the producer stage, subtotal
    copies' NULLed key lanes hash identically everywhere."""
    _check(workers,
           "SELECT n_regionkey, n_name, count(*) FROM nation "
           "GROUP BY ROLLUP(n_regionkey, n_name) ORDER BY 1, 2")
    _check(workers,
           "SELECT o_orderstatus, o_orderpriority, count(*), "
           "sum(o_totalprice) FROM orders GROUP BY GROUPING SETS "
           "((o_orderstatus), (o_orderpriority), ()) ORDER BY 1, 2",
           approx=(3,))


def test_mpp_grouping_sets_fragment_shape():
    """The DAG proof behind the e2e: a ROLLUP aggregation fragments
    with the GroupIdNode INSIDE the producer stage and the hash
    exchange keyed on the full key tuple incl. the set id."""
    from trino_tpu.analysis.sanity import walk_plan
    from trino_tpu.plan.nodes import AggregationNode, GroupIdNode
    r, plan = _optimized(
        "SELECT n_regionkey, n_name, count(*) FROM nation "
        "GROUP BY ROLLUP(n_regionkey, n_name)")
    dag = StageFragmenter(r.catalogs, r.session).fragment(plan)
    assert dag is not None
    producer = next(st for st in dag.stages
                    if any(isinstance(n, GroupIdNode)
                           for n in walk_plan(st.plan)))
    agg = next(n for st in dag.stages
               for n in walk_plan(st.plan)
               if isinstance(n, AggregationNode))
    assert agg.group_id_symbol is not None
    assert agg.group_id_symbol in producer.output_node.partition_keys


# --------------------------------------------------------------------------
# eager pipelining: consumer pulls while producers run
# --------------------------------------------------------------------------

def test_pipelining_matches_barrier_and_overlaps(workers):
    """The tentpole A/B: identical results with stage_pipelining on
    and off; the pipelined run shows cross-stage overlap (tasks of
    >= 2 stages in flight concurrently), the barrier run none.
    PARTITIONED distribution keeps >= 4 stages in the DAG so there is
    something to overlap."""
    from trino_tpu.benchmarks.tpch_queries import TPCH_QUERIES
    gauge = METRICS.gauge("trino_tpu_mpp_pipeline_overlap_ratio")
    _check(workers, TPCH_QUERIES[3], approx=(1,),
           join_distribution_type="PARTITIONED",
           stage_pipelining=False)
    assert gauge.value() == 0.0
    _check(workers, TPCH_QUERIES[3], approx=(1,),
           join_distribution_type="PARTITIONED",
           stage_pipelining=True)
    assert gauge.value() > 0.0


# --------------------------------------------------------------------------
# ICI-native exchange: the stage DAG on the device mesh
# --------------------------------------------------------------------------

@pytest.mark.slow      # heaviest tier-1 test (~90s); the ici_exchange
# escape-hatch test below keeps the ICI plumbing tier-1
def test_ici_stage_execution_matches_local():
    """The in-slice unification: LocalQueryRunner(distributed=True)
    routes fragmentable plans through the SAME stage DAG with the hash
    repartition lowered to jax.lax.all_to_all (stage/ici.py) — results
    equal the local engine and the ICI byte counter moves while the
    spool counter does not."""
    sql = ("SELECT o_orderpriority, count(*), sum(l_extendedprice) "
           "FROM lineitem JOIN orders ON l_orderkey = o_orderkey "
           "GROUP BY o_orderpriority ORDER BY o_orderpriority")
    loc = LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny")).execute(sql)
    ici_b = _counter("trino_tpu_exchange_ici_bytes_total")
    spool_b = _counter("trino_tpu_exchange_partition_bytes_total")
    dist = LocalQueryRunner(distributed=True, n_devices=8,
                            session=Session(catalog="tpch",
                                            schema="tiny")).execute(sql)
    assert len(dist.rows) == len(loc.rows)
    for d, l in zip(dist.rows, loc.rows):
        assert d[0] == l[0] and d[1] == l[1]
        assert d[2] == pytest.approx(l[2], rel=1e-9)
    assert _counter("trino_tpu_exchange_ici_bytes_total") > ici_b
    assert _counter(
        "trino_tpu_exchange_partition_bytes_total") == spool_b


def test_ici_exchange_off_keeps_node_path():
    """The escape hatch: ici_exchange=false keeps the node-at-a-time
    distributed executor — same answers, no ICI edge counted."""
    sql = ("SELECT n_name, count(*) FROM nation "
           "JOIN customer ON c_nationkey = n_nationkey "
           "GROUP BY n_name ORDER BY 1")
    loc = LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny")).execute(sql)
    edges = _counter("trino_tpu_exchange_ici_edges_total")
    s = Session(catalog="tpch", schema="tiny")
    s.set("ici_exchange", False)
    dist = LocalQueryRunner(distributed=True, n_devices=8,
                            session=s).execute(sql)
    assert dist.rows == loc.rows
    assert _counter("trino_tpu_exchange_ici_edges_total") == edges


def test_partition_endpoint_serves_committed_frames():
    """The serve half of the exchange: a committed attempt's frames
    are addressable over HTTP by (exchange key, partition index);
    unknown keys / indices 404."""
    import urllib.error
    import urllib.request
    srv = TaskWorkerServer().start()
    try:
        srv.spool.commit("qx.s0.p0", 0, 0, 0, [b"frame-a", b"frame-b"])
        for i, want in enumerate((b"frame-a", b"frame-b")):
            with urllib.request.urlopen(
                    f"{srv.base_uri}/v1/partition/qx.s0.p0/{i}",
                    timeout=5) as r:
                assert r.read() == want
        for bad in ("/v1/partition/qx.s0.p0/9",
                    "/v1/partition/no-such-key/0"):
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(srv.base_uri + bad, timeout=5)
            assert e.value.code == 404
    finally:
        srv.stop()
