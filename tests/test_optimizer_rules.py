"""Plan assertions for the round-5 optimizer rules.

Reference: sql/planner/iterative/rule/{UnwrapCastInComparison,
SingleDistinctAggregationToGroupBy, CreatePartialTopN,
PushdownFilterIntoWindow}.java.
"""

import pytest

from trino_tpu.plan.nodes import (AggregationNode, FilterNode,
                                  LimitNode, TableScanNode, TopNNode,
                                  UnionNode, WindowNode)
from trino_tpu.planner.logical import LogicalPlanner
from trino_tpu.planner.optimizer import optimize
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session
from trino_tpu.sql.parser import parse_statement


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner(session=Session(catalog="tpch",
                                            schema="tiny"))


def _plan(runner, sql):
    stmt = parse_statement(sql)
    return optimize(LogicalPlanner(runner.catalogs, runner.session)
                    .plan(stmt), runner.catalogs, runner.session)


def _find(node, cls):
    out = []
    if isinstance(node, cls):
        out.append(node)
    for s in node.sources:
        out.extend(_find(s, cls))
    return out


def test_unwrap_cast_enables_scan_pushdown(runner):
    # cast(integer-ish col to DOUBLE) compared to a double literal:
    # unwrapping lets the domain reach the connector handle
    plan = _plan(runner,
                 "SELECT count(*) FROM orders "
                 "WHERE CAST(o_shippriority AS DOUBLE) = 0.0")
    scans = _find(plan, TableScanNode)
    assert len(scans) == 1
    assert scans[0].handle.constraint is not None
    assert not _find(plan, FilterNode)   # fully absorbed by the scan

    rows = runner.execute(
        "SELECT count(*) FROM orders "
        "WHERE CAST(o_shippriority AS DOUBLE) = 0.0").rows
    assert rows[0][0] == 15000


def test_unwrap_cast_nonintegral_bound(runner):
    got = runner.execute(
        "SELECT count(*) FROM orders "
        "WHERE CAST(o_shippriority AS DOUBLE) < 0.5").rows
    assert got[0][0] == 15000
    got = runner.execute(
        "SELECT count(*) FROM orders "
        "WHERE CAST(o_shippriority AS DOUBLE) > 0.5").rows
    assert got[0][0] == 0


def test_single_distinct_becomes_groupby(runner):
    plan = _plan(runner,
                 "SELECT o_orderpriority, count(DISTINCT o_custkey) "
                 "FROM orders GROUP BY o_orderpriority")
    aggs = _find(plan, AggregationNode)
    assert len(aggs) == 2     # outer plain agg over inner dedup
    outer, inner = aggs
    assert all(not a.distinct for a in outer.aggregates.values())
    assert not inner.aggregates      # pure GROUP BY dedup
    assert set(inner.group_keys) >= set(outer.group_keys)

    got = runner.execute(
        "SELECT o_orderpriority, count(DISTINCT o_custkey) c "
        "FROM orders GROUP BY o_orderpriority ORDER BY 1").rows
    exp = runner.execute(
        "SELECT o_orderpriority, count(*) FROM ("
        "  SELECT DISTINCT o_orderpriority, o_custkey FROM orders) "
        "GROUP BY o_orderpriority ORDER BY 1").rows
    assert got == exp


def test_mixed_distinct_not_rewritten(runner):
    # a non-distinct aggregate alongside: rewrite must NOT fire
    plan = _plan(runner,
                 "SELECT count(DISTINCT o_custkey), count(*) "
                 "FROM orders")
    aggs = _find(plan, AggregationNode)
    assert len(aggs) == 1


def test_partial_topn_through_union(runner):
    plan = _plan(runner,
                 "SELECT * FROM ("
                 "  SELECT o_orderkey AS k FROM orders"
                 "  UNION ALL SELECT c_custkey FROM customer) "
                 "ORDER BY k DESC LIMIT 7")
    tops = _find(plan, TopNNode)
    finals = [t for t in tops if t.step == "FINAL"]
    partials = [t for t in tops if t.step == "PARTIAL"]
    assert len(finals) == 1 and len(partials) == 2
    u = _find(plan, UnionNode)[0]
    assert all(isinstance(c, TopNNode) for c in u.children)

    got = runner.execute(
        "SELECT * FROM (SELECT o_orderkey AS k FROM orders "
        "UNION ALL SELECT c_custkey FROM customer) "
        "ORDER BY k DESC LIMIT 7").rows
    assert len(got) == 7
    assert got == sorted(got, reverse=True)


def test_partial_limit_through_union(runner):
    plan = _plan(runner,
                 "SELECT * FROM (SELECT o_orderkey AS k FROM orders "
                 "UNION ALL SELECT c_custkey FROM customer) LIMIT 9")
    u = _find(plan, UnionNode)[0]
    assert all(isinstance(c, LimitNode) and c.partial
               for c in u.children)
    got = runner.execute(
        "SELECT * FROM (SELECT o_orderkey AS k FROM orders "
        "UNION ALL SELECT c_custkey FROM customer) LIMIT 9").rows
    assert len(got) == 9


def test_filter_pushes_into_window_partition(runner):
    sql = ("SELECT * FROM ("
           "  SELECT o_custkey, o_orderkey, "
           "  rank() OVER (PARTITION BY o_custkey "
           "               ORDER BY o_totalprice) r"
           "  FROM orders) WHERE o_custkey = 370")
    plan = _plan(runner, sql)
    win = _find(plan, WindowNode)[0]
    # the partition-key conjunct moved below the window (ideally all
    # the way into the scan handle)
    below = _find(win, (FilterNode, TableScanNode))
    pushed = any(
        isinstance(n, FilterNode) or
        (isinstance(n, TableScanNode) and n.handle.constraint is not None)
        for n in below)
    assert pushed
    assert not _find(plan, FilterNode) or _find(win, FilterNode)

    got = runner.execute(sql + " ORDER BY r").rows
    exp = [r for r in runner.execute(
        "SELECT o_custkey, o_orderkey, rank() OVER ("
        "PARTITION BY o_custkey ORDER BY o_totalprice) r FROM orders "
        "ORDER BY r").rows if r[0] == 370]
    assert got == exp


def test_matching_engine():
    """lib/trino-matching analog: typed patterns with property checks,
    source sub-patterns, and captures."""
    from trino_tpu.matching import Capture, Pattern
    from trino_tpu.plan.nodes import LimitNode, TopNNode, UnionNode

    union_cap = Capture("union")
    pat = (Pattern.type_of(TopNNode)
           .with_prop("step", "SINGLE")
           .with_source(Pattern.type_of(UnionNode)
                        .capture_as(union_cap)))
    u = UnionNode((), {}, ())
    topn = TopNNode(u, 5, (), "SINGLE")
    m = pat.match(topn)
    assert m and m[union_cap] is u
    assert pat.match(TopNNode(u, 5, (), "FINAL")) is None
    assert pat.match(LimitNode(u, 5)) is None
    # predicate checks + shared-pattern immutability
    base = Pattern.type_of(LimitNode)
    small = base.matching("count", lambda c: c is not None and c < 10)
    assert small.match(LimitNode(u, 5))
    assert small.match(LimitNode(u, 50)) is None
    assert base.match(LimitNode(u, 50))   # base unaffected
