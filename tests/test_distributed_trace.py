"""Distributed tracing (ISSUE 15): span identity, W3C traceparent
propagation, id-preserving graft, OTLP export, device/CPU attribution,
and the EMA busy-shed signal.

The acceptance spine: a distributed (default MPP) query through real
worker HTTP servers produces ONE trace — every worker span born with
the query's 128-bit trace id and its true parent span id — served as
OTLP/JSON at GET /v1/trace/{query_id}, while EXPLAIN ANALYZE shows
per-stage device_ms and CPU-seconds distinct from wall time.
"""

import json
import re
import threading
import time
import urllib.request

import pytest

from trino_tpu.obs.otlp import (FileSink, HttpSink, spans_from_otlp,
                                trace_to_resource_spans,
                                validate_resource_spans)
from trino_tpu.obs.trace import (QueryTrace, format_traceparent,
                                 new_span_id, new_trace_id,
                                 parse_traceparent)
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session


# ---------------------------------------------------------------------------
# span identity + W3C context units
# ---------------------------------------------------------------------------

def test_span_and_trace_id_shapes():
    tid, sid = new_trace_id(), new_span_id()
    assert len(tid) == 32 and int(tid, 16) >= 0
    assert len(sid) == 16 and int(sid, 16) >= 0
    assert new_span_id() != sid          # 64-bit mints don't collide
    tp = format_traceparent(tid, sid)
    assert tp == f"00-{tid}-{sid}-01"
    assert parse_traceparent(tp) == (tid, sid)


@pytest.mark.parametrize("bad", [
    None, 42, "", "00-zz-yy-01", "00-" + "a" * 32,
    "00-" + "a" * 31 + "-" + "b" * 16 + "-01",
    "00-" + "g" * 32 + "-" + "b" * 16 + "-01"])
def test_parse_traceparent_rejects_malformed(bad):
    assert parse_traceparent(bad) is None


def test_every_span_carries_identity():
    tr = QueryTrace("q")
    with tr.span("a"):
        with tr.span("b"):
            pass
    a, b = tr.roots[0], tr.roots[0].children[0]
    assert len(a.span_id) == 16 and len(b.span_id) == 16
    assert a.span_id != b.span_id
    d = tr.to_dicts()[0]
    assert d["spanId"] == a.span_id
    assert d["children"][0]["spanId"] == b.span_id
    assert d["startUnixNanos"] > 0 and d["endUnixNanos"] >= \
        d["startUnixNanos"]


# ---------------------------------------------------------------------------
# the span-stack race regression: per-thread open stacks
# ---------------------------------------------------------------------------

def test_two_thread_span_stack_isolation():
    """A span opened on a second thread must NOT nest under whatever
    the first thread has open — the pre-identity implementation shared
    one stack and produced exactly that mis-nesting."""
    tr = QueryTrace("q")
    entered = threading.Event()
    release = threading.Event()
    errors = []

    def dispatcher():
        try:
            with tr.span("dispatch_side") as sp:
                with tr.span("dispatch_child"):
                    pass
                assert tr.current() is sp   # own stack, own top
            entered.set()
            release.wait(5)
        except Exception as e:     # noqa: BLE001
            errors.append(e)
            entered.set()

    with tr.span("executor_side") as main_sp:
        t = threading.Thread(target=dispatcher)
        t.start()
        assert entered.wait(5)
        # the executor thread's stack is untouched by the other thread
        assert tr.current() is main_sp
        release.set()
        t.join()
    assert not errors
    names = {r.name for r in tr.roots}
    # dispatch_side is a ROOT (not a child of executor_side), and its
    # own child nested correctly under it
    assert names == {"executor_side", "dispatch_side"}
    disp = next(r for r in tr.roots if r.name == "dispatch_side")
    assert [c.name for c in disp.children] == ["dispatch_child"]
    assert not next(r for r in tr.roots
                    if r.name == "executor_side").children


def test_explicit_parent_escape_hatch():
    """Cross-thread attachment is explicit: parent= places the span
    under a span owned by another thread."""
    tr = QueryTrace("q")
    with tr.span("root") as root:
        done = threading.Event()

        def worker():
            with tr.span("attached", parent=root, part=1):
                pass
            done.set()

        threading.Thread(target=worker).start()
        assert done.wait(5)
    assert [c.name for c in root.children] == ["attached"]


# ---------------------------------------------------------------------------
# id-preserving graft (the merge that replaced clock rebasing)
# ---------------------------------------------------------------------------

def test_graft_preserves_ids_and_realigns_clock():
    co = QueryTrace("query_9")
    minted = co.new_span_id()
    tp = co.traceparent(minted)
    # the worker side: born with the query's trace id + parent span id
    tid, psid = parse_traceparent(tp)
    wk = QueryTrace("task_9.0", trace_id=tid, parent_span_id=psid)
    assert wk.trace_id == co.trace_id
    with wk.span("task_execute", task="t0"):
        with wk.span("device_execute", cache="chain"):
            time.sleep(0.002)
    wire = wk.to_dicts()                 # what task status ships
    frag = co.record("stage_0_execute", co.origin_s,
                     co.origin_s + 0.05, span_id=minted)
    co.graft(frag, wire)
    merged = frag.children[0]
    # identity survived the wire
    assert merged.span_id == wk.roots[0].span_id
    assert merged.parent_id == minted
    assert merged.children[0].span_id == \
        wk.roots[0].children[0].span_id
    # the clock was REALIGNED via unix-nanos anchors, not rebased to
    # the parent's start: duration is preserved. Tolerance covers
    # time_ns-vs-perf_counter slew over the 2ms span (NTP can drift
    # them a few µs); a rebase bug would be off by the parent's ~50ms.
    assert merged.children[0].wall_s == pytest.approx(
        wk.roots[0].children[0].wall_s, abs=1e-4)


def test_graft_legacy_dicts_without_ids_still_merge():
    co = QueryTrace("q")
    parent = co.record("fragment_0_execute", co.origin_s,
                       co.origin_s + 0.01)
    co.graft(parent, [{"name": "task_execute", "startMillis": 0.0,
                       "wallMillis": 5.0}])
    child = parent.children[0]
    assert len(child.span_id) == 16      # minted on decode
    assert child.parent_id == parent.span_id


# ---------------------------------------------------------------------------
# OTLP: ResourceSpans shape, sinks, round-trip
# ---------------------------------------------------------------------------

def _demo_trace() -> QueryTrace:
    tr = QueryTrace("query_42")
    with tr.span("plan"):
        pass
    with tr.span("execute", rows=10):
        with tr.span("jit_trace", cache="chain", device_ms=1.5):
            pass
    return tr


def test_otlp_document_shape_and_roundtrip():
    tr = _demo_trace()
    doc = trace_to_resource_spans(tr, {"extra": "x"})
    validate_resource_spans(doc)
    # JSON round-trip stays valid (what the file sink persists)
    doc2 = json.loads(json.dumps(doc))
    validate_resource_spans(doc2)
    spans = spans_from_otlp(doc2)
    by_name = {s["name"]: s for s in spans}
    assert set(by_name) == {"plan", "execute", "jit_trace"}
    assert all(s["traceId"] == tr.trace_id for s in spans)
    assert by_name["jit_trace"]["parentSpanId"] == \
        by_name["execute"]["spanId"]
    assert "parentSpanId" not in by_name["plan"]
    res = doc2["resourceSpans"][0]["resource"]["attributes"]
    keys = {a["key"] for a in res}
    assert {"service.name", "trino_tpu.query_id", "extra"} <= keys
    # typed attribute values
    attrs = {a["key"]: a["value"]
             for a in by_name["execute"]["attributes"]}
    assert attrs["rows"] == {"intValue": "10"}


def test_otlp_validation_catches_bad_ids():
    doc = trace_to_resource_spans(_demo_trace())
    doc["resourceSpans"][0]["scopeSpans"][0]["spans"][0]["spanId"] = \
        "short"
    with pytest.raises(ValueError, match="spanId"):
        validate_resource_spans(doc)


def test_otlp_file_sink_appends_jsonl(tmp_path):
    path = str(tmp_path / "otlp.jsonl")
    sink = FileSink(path)
    sink.export(trace_to_resource_spans(_demo_trace()))
    sink.export(trace_to_resource_spans(_demo_trace()))
    lines = open(path).read().splitlines()
    assert len(lines) == 2
    for line in lines:
        validate_resource_spans(json.loads(line))


def test_otlp_http_sink_posts_to_collector():
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    got = []

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            got.append((self.path, json.loads(self.rfile.read(n))))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        sink = HttpSink(f"http://127.0.0.1:{srv.server_address[1]}")
        sink.export(trace_to_resource_spans(_demo_trace()))
        assert got and got[0][0] == "/v1/traces"
        validate_resource_spans(got[0][1])
    finally:
        srv.shutdown()
        srv.server_close()


def test_maybe_export_respects_config_and_session(tmp_path):
    from trino_tpu.config import CONFIG
    from trino_tpu.obs.otlp import maybe_export
    path = str(tmp_path / "sink.jsonl")
    old = CONFIG.otlp_file
    CONFIG.otlp_file = path
    try:
        tr = _demo_trace()
        s = Session(catalog="tpch", schema="tiny")
        s.set("otlp_export", False)
        assert maybe_export(tr, session=s) == 0    # opted out
        s.set("otlp_export", True)
        assert maybe_export(tr, session=s) == 1
        validate_resource_spans(json.loads(open(path).read()))
    finally:
        CONFIG.otlp_file = old


# ---------------------------------------------------------------------------
# device-time attribution
# ---------------------------------------------------------------------------

def test_device_time_attribution_on_jitted_dispatch(monkeypatch):
    """device_ms rides the device_execute/jit_trace spans and the
    per-node stats, distinct from wall — forced through the fragment
    jit path (the CPU default would run eagerly and dispatch
    nothing)."""
    monkeypatch.setenv("TRINO_TPU_FRAGMENT_JIT", "1")
    r = LocalQueryRunner(
        session=Session(catalog="tpch", schema="tiny"),
        collect_node_stats=True)
    sql = ("SELECT l_orderkey + 1 AS k FROM lineitem "
           "WHERE l_quantity > 30")
    r.execute(sql)                       # cold: trace + compile
    res = r.execute(sql)                 # warm: pure device dispatches
    spans = []

    def walk(ds):
        for d in ds:
            spans.append(d)
            walk(d.get("children") or [])

    walk(res.trace.to_dicts())
    dev = [d for d in spans if d["name"] == "device_execute"]
    assert dev, "no device_execute span on the warm run"
    assert all("device_ms" in (d.get("attrs") or {}) for d in dev)
    assert any((d["attrs"]["device_ms"] or 0) > 0 for d in dev)
    # per-node rollup: some node carries device_s > 0 and cpu_s >= 0
    assert any(s.device_s > 0 for s in res.stats)
    assert all(s.cpu_s >= 0 for s in res.stats)
    text = "\n".join(
        row[0] for row in r.execute("EXPLAIN ANALYZE " + sql).rows)
    assert "device " in text


# ---------------------------------------------------------------------------
# worker-side: traceparent in, cpu/device/traceId out
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def workers():
    from trino_tpu.server.task_worker import TaskWorkerServer
    ws = [TaskWorkerServer().start() for _ in range(2)]
    yield ws
    for w in ws:
        w.stop()


def test_worker_status_carries_attribution_and_trace_id(workers):
    from trino_tpu.plan.serde import to_jsonable
    from trino_tpu.server.task_worker import RemoteTaskClient
    r = LocalQueryRunner(session=Session(catalog="tpch",
                                         schema="tiny"))
    plan = r.plan_sql("SELECT o_orderkey FROM orders "
                      "WHERE o_orderkey < 500")
    tid, psid = new_trace_id(), new_span_id()
    client = RemoteTaskClient(workers[0].base_uri)
    client.submit_fragment(
        "trace-task-1", to_jsonable(plan), catalog="tpch",
        schema="tiny", part=0, nparts=1, collect_stats=True,
        traceparent=format_traceparent(tid, psid))
    status = client.wait_done("trace-task-1")
    assert status["state"] == "FINISHED"
    # born with the QUERY's trace id, parented on the pre-minted span
    assert status["traceId"] == tid
    roots = status["spans"]
    assert roots and roots[0]["name"] == "task_execute"
    assert roots[0]["parentSpanId"] == psid
    assert len(roots[0]["spanId"]) == 16
    # scheduler CPU + device attribution in the status beat
    assert status["cpuSeconds"] > 0
    assert status["deviceSeconds"] >= 0


def test_traceparent_header_fallback(workers):
    """A payload without the field still propagates via the HTTP
    header (clients that predate the payload field)."""
    from trino_tpu.plan.serde import to_jsonable
    from trino_tpu.server.task_worker import RemoteTaskClient
    r = LocalQueryRunner(session=Session(catalog="tpch",
                                         schema="tiny"))
    plan = r.plan_sql("SELECT r_name FROM region")
    tid, psid = new_trace_id(), new_span_id()
    body = {"fragment": to_jsonable(plan), "catalog": "tpch",
            "schema": "tiny", "part": 0, "nparts": 1,
            "collect_stats": True, "properties": {}}
    req = urllib.request.Request(
        f"{workers[0].base_uri}/v1/task/trace-task-hdr",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 "traceparent": format_traceparent(tid, psid)},
        method="POST")
    with urllib.request.urlopen(req, timeout=30):
        pass
    status = RemoteTaskClient(workers[0].base_uri).wait_done(
        "trace-task-hdr")
    assert status["traceId"] == tid
    assert status["spans"][0]["parentSpanId"] == psid


# ---------------------------------------------------------------------------
# the distributed e2e: one trace id end to end on the default MPP path
# ---------------------------------------------------------------------------

JOIN_AGG_SQL = (
    "SELECT o_orderpriority, count(*) AS n FROM orders "
    "JOIN lineitem ON o_orderkey = l_orderkey "
    "WHERE l_quantity > 30 GROUP BY o_orderpriority")


def _walk_dicts(ds, out):
    for d in ds:
        out.append(d)
        _walk_dicts(d.get("children") or [], out)


def test_distributed_trace_single_identity_default_mpp(workers):
    from trino_tpu.exec.remote import DistributedHostQueryRunner
    before = {tid for w in workers for tid in w._tasks}
    d = DistributedHostQueryRunner(
        [w.base_uri for w in workers],
        session=Session(catalog="tpch", schema="tiny"),
        collect_node_stats=True)
    res = d.execute(JOIN_AGG_SQL)
    trace = res.trace
    assert len(trace.trace_id) == 32
    flat = []
    _walk_dicts(trace.to_dicts(), flat)
    stage_spans = {d["spanId"]: d for d in flat
                   if re.match(r"stage_\d+_execute", d["name"])}
    assert stage_spans, "no stage spans — did the MPP path run?"
    task_spans = [d for d in flat if d["name"] == "task_execute"]
    assert task_spans, "no worker subtrees grafted"
    # every worker task_execute is parented on the stage span the
    # coordinator pre-minted for its dispatch
    for t in task_spans:
        assert t.get("parentSpanId") in stage_spans
    # the stage spans carry the attribution rollup
    for sp in stage_spans.values():
        attrs = sp.get("attrs") or {}
        assert "cpu_s" in attrs and "device_ms" in attrs
    # the workers were BORN with the query's trace id (not merely
    # relabeled at graft time) — only THIS query's tasks, the module
    # fixture's registry still holds earlier tests' tasks
    born = [t.trace_id for w in workers
            for tid, t in w._tasks.items()
            if tid not in before and t.trace_id is not None]
    assert born and all(tid == trace.trace_id for tid in born)


def test_distributed_explain_analyze_shows_cpu_and_device(workers):
    from trino_tpu.exec.remote import DistributedHostQueryRunner
    d = DistributedHostQueryRunner(
        [w.base_uri for w in workers],
        session=Session(catalog="tpch", schema="tiny"),
        collect_node_stats=True)
    res = d.execute("EXPLAIN ANALYZE " + JOIN_AGG_SQL)
    text = "\n".join(r[0] for r in res.rows)
    # per-stage rollup: cpu seconds + device ms, distinct from wall
    tags = re.findall(r"stage \d+ x\d+ tasks \[cpu ([0-9.]+)s, "
                      r"device ([0-9.]+)ms\]", text)
    assert tags, text
    assert any(float(cpu) > 0 for cpu, _ in tags), tags


def test_coordinator_v1_trace_endpoint_e2e(workers):
    """The acceptance e2e: a distributed query through a real
    coordinator + real worker HTTP servers, then GET /v1/trace/{id}
    serves OTLP/JSON where every span shares one trace id and worker
    spans hang off their dispatching stage spans."""
    from trino_tpu.client import StatementClient
    from trino_tpu.server import Coordinator
    co = Coordinator().start()
    try:
        for w in workers:
            co.add_worker(w.base_uri)
        res = StatementClient(co.base_uri, catalog="tpch",
                              schema="tiny").execute(JOIN_AGG_SQL)
        assert res.rows
        with urllib.request.urlopen(
                f"{co.base_uri}/v1/trace/{res.query_id}") as r:
            doc = json.loads(r.read())
        validate_resource_spans(doc)
        spans = spans_from_otlp(doc)
        trace_ids = {s["traceId"] for s in spans}
        assert len(trace_ids) == 1
        by_id = {s["spanId"]: s for s in spans}
        tasks = [s for s in spans if s["name"] == "task_execute"]
        assert tasks, "no worker spans in the exported trace"
        for t in tasks:
            parent = by_id.get(t.get("parentSpanId"))
            assert parent is not None, "worker span parent missing"
            assert re.match(r"(stage|fragment)_\d+_execute",
                            parent["name"])
        # resource attrs name the query
        attrs = {a["key"]: a["value"]
                 for a in doc["resourceSpans"][0]["resource"]
                 ["attributes"]}
        assert attrs["trino_tpu.query_id"]["stringValue"] == \
            res.query_id
        # unknown id → 404
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"{co.base_uri}/v1/trace/nope_404")
        assert exc.value.code == 404
    finally:
        co.stop()


# ---------------------------------------------------------------------------
# scheduler observables: EMA shed, quantum/level/queue-depth families
# ---------------------------------------------------------------------------

def test_busy_shed_ema_smooths_bursts():
    """Deterministic clock: an instant registration burst does NOT
    move the EMA (no shed), sustained load saturates it, and idling
    decays it back."""
    from trino_tpu.exec.taskexec import TaskExecutor
    now = [0.0]
    ex = TaskExecutor(1, clock=lambda: now[0], ema_tau_s=10.0)
    handles = [ex.register("q", f"t{i}") for i in range(8)]
    assert ex.open_tasks() == 8
    assert ex.open_tasks_ema() < 1.0     # the burst rides through
    now[0] = 30.0                        # sustained: ~3 time constants
    assert ex.open_tasks_ema() > 7.0
    for h in handles:
        h.close()
    now[0] = 60.0
    assert ex.open_tasks_ema() < 1.0     # quiet worker recovers
    # tau=0 pins the spot value (the pre-EMA behavior)
    ex0 = TaskExecutor(1, clock=lambda: now[0], ema_tau_s=0)
    ex0.register("q", "t")
    assert ex0.open_tasks_ema() == 1.0


def test_shed_reason_uses_ema_with_factor_floor():
    from trino_tpu.server.task_worker import TaskWorkerServer
    w = TaskWorkerServer(task_runners=1, busy_shed_factor=2,
                         busy_shed_ema_s=120.0).start()
    try:
        # cap = 2: spot past the floor but inside the burst window
        # ([cap, 2*cap)) and the EMA (tau=120s) has seen none of it —
        # no shed
        hs = [w.task_executor.register("q", f"t{i}") for i in range(3)]
        assert w._shed_reason() is None
        # ...but the hard ceiling (2 x cap) sheds REGARDLESS of the
        # EMA: smoothing tolerates a burst, never an unbounded pile-up
        hs.append(w.task_executor.register("q", "t3"))
        reason = w._shed_reason()
        assert reason is not None and "hard ceiling" in reason
        for h in hs:
            h.close()
    finally:
        w.stop()


def test_quantum_level_and_queue_depth_metrics():
    from trino_tpu.exec.taskexec import TaskExecutor
    from trino_tpu.obs.metrics import (TASK_QUANTUM_SECONDS,
                                       TASK_SCHED_LEVEL_SECONDS,
                                       TASK_SCHED_QUEUE_DEPTH)
    q0 = TASK_QUANTUM_SECONDS.count()
    l0 = TASK_SCHED_LEVEL_SECONDS.value(level="0")
    ex = TaskExecutor(1)
    h = ex.register("qm", "t0")
    h.acquire()
    h.checkpoint()                       # one accounted quantum
    # a second task waits → queue depth published
    h2 = ex.register("qm", "t1")
    waiter = threading.Thread(target=h2.acquire)
    waiter.start()
    deadline = time.time() + 5
    while ex.queue_depth() < 1 and time.time() < deadline:
        time.sleep(0.01)
    assert TASK_SCHED_QUEUE_DEPTH.value() >= 1
    h.close()
    waiter.join(5)
    h2.close()
    assert TASK_QUANTUM_SECONDS.count() > q0
    assert TASK_SCHED_LEVEL_SECONDS.value(level="0") >= l0


def test_exchange_wait_histogram_observes_blocked_scope():
    from trino_tpu.exec.taskexec import TaskExecutor
    from trino_tpu.obs.metrics import EXCHANGE_WAIT_SECONDS
    c0 = EXCHANGE_WAIT_SECONDS.count()
    ex = TaskExecutor(1)
    h = ex.register("qw", "t0")
    h.acquire()
    with h.blocked():
        time.sleep(0.005)
    h.close()
    assert EXCHANGE_WAIT_SECONDS.count() == c0 + 1


def test_scheduler_cpu_accounting_per_query():
    from trino_tpu.exec.taskexec import TaskExecutor
    ex = TaskExecutor(2)
    h = ex.register("qcpu", "t0")
    h.acquire()
    x = 0
    for _ in range(200_000):             # real CPU inside the quantum
        x += 1
    h.checkpoint()
    assert ex.query_cpu_seconds("qcpu") > 0
    h.close()
    assert h.cpu_s > 0                   # survives close for status
