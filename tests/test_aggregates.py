"""Aggregate-function breadth tests.

Reference parity: operator/aggregation/ (98 builtins) —
MinMaxByAggregationFunction, ApproximateCountDistinctAggregation,
CovarianceAggregation, CentralMomentsAggregation, ChecksumAggregation,
ApproximateDoublePercentileAggregations. Oracles are sqlite (stdlib) for
count-distinct shapes and numpy closed forms for the statistical family.
"""

import math
import sqlite3

import numpy as np
import pytest

from trino_tpu.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def q(runner, sql):
    return runner.execute(sql).rows


@pytest.fixture(scope="module")
def li(runner):
    """(partkey, quantity, extendedprice) of tiny lineitem + a sqlite
    mirror for oracle queries."""
    rows = q(runner, "SELECT l_partkey, l_quantity, l_extendedprice "
                     "FROM tpch.tiny.lineitem")
    con = sqlite3.connect(":memory:")
    con.execute("CREATE TABLE t(pk INT, qty REAL, price REAL)")
    con.executemany("INSERT INTO t VALUES (?,?,?)", rows)
    return np.asarray(rows, dtype=float), con


# -- count(DISTINCT) / approx_distinct --------------------------------------

def test_count_distinct_global(runner, li):
    _, con = li
    exp = con.execute("SELECT count(DISTINCT qty) FROM t").fetchone()[0]
    got = q(runner, "SELECT count(DISTINCT l_quantity), "
                    "approx_distinct(l_quantity) FROM tpch.tiny.lineitem")
    assert got[0] == [exp, exp]


def test_count_distinct_grouped(runner, li):
    _, con = li
    exp = [list(r) for r in con.execute(
        "SELECT pk % 11, count(DISTINCT qty), count(*) FROM t "
        "GROUP BY pk % 11 ORDER BY 1")]
    got = q(runner, "SELECT l_partkey % 11, count(DISTINCT l_quantity), "
                    "count(*) FROM tpch.tiny.lineitem "
                    "GROUP BY l_partkey % 11 ORDER BY 1")
    assert got == exp


def test_count_distinct_strings_and_nulls(runner):
    got = q(runner, "SELECT count(DISTINCT x) FROM (VALUES 'a', 'b', "
                    "'a', NULL, 'c', NULL) t(x)")
    assert got == [[3]]


def test_count_distinct_with_filter(runner):
    got = q(runner, "SELECT count(DISTINCT x) FILTER (WHERE x > 1) "
                    "FROM (VALUES 1, 2, 2, 3, NULL) t(x)")
    assert got == [[2]]


# -- min_by / max_by --------------------------------------------------------

def test_min_max_by_global(runner, li):
    arr, _ = li
    pk, qty, price = arr[:, 0], arr[:, 1], arr[:, 2]
    exp_min = pk[np.argmin(price)]
    exp_max = pk[np.argmax(price)]
    got = q(runner, "SELECT min_by(l_partkey, l_extendedprice), "
                    "max_by(l_partkey, l_extendedprice) "
                    "FROM tpch.tiny.lineitem")
    assert got == [[int(exp_min), int(exp_max)]]


def test_min_by_grouped_strings(runner):
    got = q(runner, "SELECT n_regionkey, min_by(n_name, n_nationkey), "
                    "max_by(n_name, n_nationkey) FROM tpch.tiny.nation "
                    "GROUP BY n_regionkey ORDER BY n_regionkey")
    # first/last nation name per region by nationkey
    names = q(runner, "SELECT n_regionkey, n_nationkey, n_name "
                      "FROM tpch.tiny.nation ORDER BY n_nationkey")
    by_region = {}
    for rk, nk, nm in names:
        lo, hi = by_region.get(rk, (None, None))
        if lo is None:
            by_region[rk] = (nm, nm)
        else:
            by_region[rk] = (lo, nm)
    exp = [[rk, *by_region[rk]] for rk in sorted(by_region)]
    assert got == exp


def test_min_by_null_comparators_ignored(runner):
    got = q(runner, "SELECT min_by(a, b) FROM (VALUES "
                    "(1, NULL), (2, 10), (3, 5)) t(a, b)")
    assert got == [[3]]
    got = q(runner, "SELECT min_by(a, b) FROM (VALUES "
                    "(CAST(NULL AS bigint), NULL)) t(a, b)")
    assert got == [[None]]


# -- approx_percentile ------------------------------------------------------

def test_percentile_global(runner, li):
    arr, _ = li
    qty = np.sort(arr[:, 1])
    for frac in (0.0, 0.25, 0.5, 0.9, 1.0):
        got = q(runner, f"SELECT approx_percentile(l_quantity, {frac}) "
                        "FROM tpch.tiny.lineitem")[0][0]
        k = int(np.clip(math.floor(frac * (len(qty) - 1) + 0.5),
                        0, len(qty) - 1))
        assert got == qty[k], frac


def test_percentile_grouped(runner):
    got = q(runner, "SELECT x % 2, approx_percentile(x, 0.5) FROM "
                    "(VALUES 1, 2, 3, 4, 5, 6, 7) t(x) "
                    "GROUP BY x % 2 ORDER BY 1")
    # odd: 1 3 5 7 -> median ~ 5 (nearest-rank of 0.5*(4-1)+0.5 = 2);
    # even: 2 4 6 -> 4
    assert got == [[0, 4], [1, 5]]


# -- statistical family -----------------------------------------------------

def test_corr_covar_regr(runner, li):
    arr, _ = li
    qty, price = arr[:, 1], arr[:, 2]
    got = q(runner, "SELECT corr(l_extendedprice, l_quantity), "
                    "covar_pop(l_extendedprice, l_quantity), "
                    "covar_samp(l_extendedprice, l_quantity), "
                    "regr_slope(l_extendedprice, l_quantity), "
                    "regr_intercept(l_extendedprice, l_quantity) "
                    "FROM tpch.tiny.lineitem")[0]
    n = len(qty)
    exp_corr = np.corrcoef(price, qty)[0, 1]
    exp_cpop = np.cov(price, qty, bias=True)[0, 1]
    exp_csamp = np.cov(price, qty, bias=False)[0, 1]
    slope, intercept = np.polyfit(qty, price, 1)
    for g, e in zip(got, (exp_corr, exp_cpop, exp_csamp, slope,
                          intercept)):
        assert g == pytest.approx(e, rel=1e-9)


def test_corr_pairwise_nulls(runner):
    # rows with a NULL on either side are excluded pairwise
    got = q(runner, "SELECT covar_pop(y, x), count(*) FROM (VALUES "
                    "(1.0, 2.0), (2.0, 4.0), (NULL, 9.0), (3.0, NULL)) "
                    "t(y, x)")[0]
    assert got[0] == pytest.approx(np.cov([1, 2], [2, 4],
                                          bias=True)[0, 1])
    assert got[1] == 4


def test_skewness_kurtosis(runner, li):
    arr, _ = li
    x = arr[:, 2]
    n = len(x)
    m = x.mean()
    m2 = ((x - m) ** 2).sum()
    m3 = ((x - m) ** 3).sum()
    m4 = ((x - m) ** 4).sum()
    exp_skew = math.sqrt(n) * m3 / m2 ** 1.5
    exp_kurt = (n * (n + 1.0) / ((n - 1.0) * (n - 2.0) * (n - 3.0))
                * (n * m4 / (m2 * m2))
                - 3.0 * (n - 1.0) ** 2 / ((n - 2.0) * (n - 3.0)))
    got = q(runner, "SELECT skewness(l_extendedprice), "
                    "kurtosis(l_extendedprice) FROM tpch.tiny.lineitem")
    assert got[0][0] == pytest.approx(exp_skew, rel=1e-9)
    assert got[0][1] == pytest.approx(exp_kurt, rel=1e-6)


def test_skewness_small_n_null(runner):
    got = q(runner, "SELECT skewness(x), kurtosis(x) FROM "
                    "(VALUES 1.0, 2.0) t(x)")
    assert got == [[None, None]]


# -- checksum ---------------------------------------------------------------

def test_checksum_order_independent(runner):
    a = q(runner, "SELECT checksum(x) FROM (VALUES 1, 2, 3) t(x)")
    b = q(runner, "SELECT checksum(x) FROM (VALUES 3, 1, 2) t(x)")
    c = q(runner, "SELECT checksum(x) FROM (VALUES 3, 1, 4) t(x)")
    assert a == b
    assert a != c
    # NULLs participate (multiset semantics)
    d = q(runner, "SELECT checksum(x) FROM (VALUES 1, NULL, 2) t(x)")
    e = q(runner, "SELECT checksum(x) FROM (VALUES 1, 2) t(x)")
    assert d != e


def test_checksum_grouped_strings(runner):
    got = q(runner, "SELECT n_regionkey, checksum(n_name) "
                    "FROM tpch.tiny.nation GROUP BY n_regionkey")
    assert len(got) == 5
    assert all(r[1] is not None for r in got)


# -- distributed equivalence for non-decomposable kinds ---------------------

@pytest.fixture(scope="module")
def dist_runner():
    return LocalQueryRunner(distributed=True, n_devices=8)


def test_distributed_nondecomposable_grouped(runner, dist_runner):
    sql = ("SELECT l_partkey % 5, count(DISTINCT l_quantity), "
           "min_by(l_orderkey, l_extendedprice), "
           "approx_percentile(l_quantity, 0.5) "
           "FROM tpch.tiny.lineitem GROUP BY l_partkey % 5 ORDER BY 1")
    assert q(dist_runner, sql) == q(runner, sql)


def test_distributed_nondecomposable_global(runner, dist_runner):
    sql = ("SELECT count(DISTINCT l_suppkey), "
           "max_by(l_orderkey, l_extendedprice) "
           "FROM tpch.tiny.lineitem")
    assert q(dist_runner, sql) == q(runner, sql)


def test_mixed_same_arg_distinct(runner):
    # sum(DISTINCT x) + count(DISTINCT x) share the inner-group-by
    # rewrite; count(DISTINCT)-only mixes run natively
    got = q(runner, "SELECT sum(DISTINCT x), count(DISTINCT x), "
                    "avg(DISTINCT x) FROM (VALUES 1, 2, 2, 3) t(x)")
    assert got == [[6, 3, 2.0]]
