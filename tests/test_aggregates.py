"""Aggregate-function breadth tests.

Reference parity: operator/aggregation/ (98 builtins) —
MinMaxByAggregationFunction, ApproximateCountDistinctAggregation,
CovarianceAggregation, CentralMomentsAggregation, ChecksumAggregation,
ApproximateDoublePercentileAggregations. Oracles are sqlite (stdlib) for
count-distinct shapes and numpy closed forms for the statistical family.
"""

import math
import sqlite3

import numpy as np
import pytest

from trino_tpu.runner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner()


def q(runner, sql):
    return runner.execute(sql).rows


@pytest.fixture(scope="module")
def li(runner):
    """(partkey, quantity, extendedprice) of tiny lineitem + a sqlite
    mirror for oracle queries."""
    rows = q(runner, "SELECT l_partkey, l_quantity, l_extendedprice "
                     "FROM tpch.tiny.lineitem")
    con = sqlite3.connect(":memory:")
    con.execute("CREATE TABLE t(pk INT, qty REAL, price REAL)")
    con.executemany("INSERT INTO t VALUES (?,?,?)", rows)
    return np.asarray(rows, dtype=float), con


# -- count(DISTINCT) / approx_distinct --------------------------------------

def test_count_distinct_global(runner, li):
    _, con = li
    exp = con.execute("SELECT count(DISTINCT qty) FROM t").fetchone()[0]
    got = q(runner, "SELECT count(DISTINCT l_quantity), "
                    "approx_distinct(l_quantity) FROM tpch.tiny.lineitem")
    assert got[0] == [exp, exp]


def test_count_distinct_grouped(runner, li):
    _, con = li
    exp = [list(r) for r in con.execute(
        "SELECT pk % 11, count(DISTINCT qty), count(*) FROM t "
        "GROUP BY pk % 11 ORDER BY 1")]
    got = q(runner, "SELECT l_partkey % 11, count(DISTINCT l_quantity), "
                    "count(*) FROM tpch.tiny.lineitem "
                    "GROUP BY l_partkey % 11 ORDER BY 1")
    assert got == exp


def test_count_distinct_strings_and_nulls(runner):
    got = q(runner, "SELECT count(DISTINCT x) FROM (VALUES 'a', 'b', "
                    "'a', NULL, 'c', NULL) t(x)")
    assert got == [[3]]


def test_count_distinct_with_filter(runner):
    got = q(runner, "SELECT count(DISTINCT x) FILTER (WHERE x > 1) "
                    "FROM (VALUES 1, 2, 2, 3, NULL) t(x)")
    assert got == [[2]]


# -- min_by / max_by --------------------------------------------------------

def test_min_max_by_global(runner, li):
    arr, _ = li
    pk, qty, price = arr[:, 0], arr[:, 1], arr[:, 2]
    exp_min = pk[np.argmin(price)]
    exp_max = pk[np.argmax(price)]
    got = q(runner, "SELECT min_by(l_partkey, l_extendedprice), "
                    "max_by(l_partkey, l_extendedprice) "
                    "FROM tpch.tiny.lineitem")
    assert got == [[int(exp_min), int(exp_max)]]


def test_min_by_grouped_strings(runner):
    got = q(runner, "SELECT n_regionkey, min_by(n_name, n_nationkey), "
                    "max_by(n_name, n_nationkey) FROM tpch.tiny.nation "
                    "GROUP BY n_regionkey ORDER BY n_regionkey")
    # first/last nation name per region by nationkey
    names = q(runner, "SELECT n_regionkey, n_nationkey, n_name "
                      "FROM tpch.tiny.nation ORDER BY n_nationkey")
    by_region = {}
    for rk, nk, nm in names:
        lo, hi = by_region.get(rk, (None, None))
        if lo is None:
            by_region[rk] = (nm, nm)
        else:
            by_region[rk] = (lo, nm)
    exp = [[rk, *by_region[rk]] for rk in sorted(by_region)]
    assert got == exp


def test_min_by_null_comparators_ignored(runner):
    got = q(runner, "SELECT min_by(a, b) FROM (VALUES "
                    "(1, NULL), (2, 10), (3, 5)) t(a, b)")
    assert got == [[3]]
    got = q(runner, "SELECT min_by(a, b) FROM (VALUES "
                    "(CAST(NULL AS bigint), NULL)) t(a, b)")
    assert got == [[None]]


# -- approx_percentile ------------------------------------------------------

def test_percentile_global(runner, li):
    arr, _ = li
    qty = np.sort(arr[:, 1])
    for frac in (0.0, 0.25, 0.5, 0.9, 1.0):
        got = q(runner, f"SELECT approx_percentile(l_quantity, {frac}) "
                        "FROM tpch.tiny.lineitem")[0][0]
        k = int(np.clip(math.floor(frac * (len(qty) - 1) + 0.5),
                        0, len(qty) - 1))
        assert got == qty[k], frac


def test_percentile_grouped(runner):
    got = q(runner, "SELECT x % 2, approx_percentile(x, 0.5) FROM "
                    "(VALUES 1, 2, 3, 4, 5, 6, 7) t(x) "
                    "GROUP BY x % 2 ORDER BY 1")
    # odd: 1 3 5 7 -> median ~ 5 (nearest-rank of 0.5*(4-1)+0.5 = 2);
    # even: 2 4 6 -> 4
    assert got == [[0, 4], [1, 5]]


# -- statistical family -----------------------------------------------------

def test_corr_covar_regr(runner, li):
    arr, _ = li
    qty, price = arr[:, 1], arr[:, 2]
    got = q(runner, "SELECT corr(l_extendedprice, l_quantity), "
                    "covar_pop(l_extendedprice, l_quantity), "
                    "covar_samp(l_extendedprice, l_quantity), "
                    "regr_slope(l_extendedprice, l_quantity), "
                    "regr_intercept(l_extendedprice, l_quantity) "
                    "FROM tpch.tiny.lineitem")[0]
    n = len(qty)
    exp_corr = np.corrcoef(price, qty)[0, 1]
    exp_cpop = np.cov(price, qty, bias=True)[0, 1]
    exp_csamp = np.cov(price, qty, bias=False)[0, 1]
    slope, intercept = np.polyfit(qty, price, 1)
    for g, e in zip(got, (exp_corr, exp_cpop, exp_csamp, slope,
                          intercept)):
        assert g == pytest.approx(e, rel=1e-9)


def test_corr_pairwise_nulls(runner):
    # rows with a NULL on either side are excluded pairwise
    got = q(runner, "SELECT covar_pop(y, x), count(*) FROM (VALUES "
                    "(1.0, 2.0), (2.0, 4.0), (NULL, 9.0), (3.0, NULL)) "
                    "t(y, x)")[0]
    assert got[0] == pytest.approx(np.cov([1, 2], [2, 4],
                                          bias=True)[0, 1])
    assert got[1] == 4


def test_skewness_kurtosis(runner, li):
    arr, _ = li
    x = arr[:, 2]
    n = len(x)
    m = x.mean()
    m2 = ((x - m) ** 2).sum()
    m3 = ((x - m) ** 3).sum()
    m4 = ((x - m) ** 4).sum()
    exp_skew = math.sqrt(n) * m3 / m2 ** 1.5
    exp_kurt = (n * (n + 1.0) / ((n - 1.0) * (n - 2.0) * (n - 3.0))
                * (n * m4 / (m2 * m2))
                - 3.0 * (n - 1.0) ** 2 / ((n - 2.0) * (n - 3.0)))
    got = q(runner, "SELECT skewness(l_extendedprice), "
                    "kurtosis(l_extendedprice) FROM tpch.tiny.lineitem")
    assert got[0][0] == pytest.approx(exp_skew, rel=1e-9)
    assert got[0][1] == pytest.approx(exp_kurt, rel=1e-6)


def test_skewness_small_n_null(runner):
    got = q(runner, "SELECT skewness(x), kurtosis(x) FROM "
                    "(VALUES 1.0, 2.0) t(x)")
    assert got == [[None, None]]


# -- checksum ---------------------------------------------------------------

def test_checksum_order_independent(runner):
    a = q(runner, "SELECT checksum(x) FROM (VALUES 1, 2, 3) t(x)")
    b = q(runner, "SELECT checksum(x) FROM (VALUES 3, 1, 2) t(x)")
    c = q(runner, "SELECT checksum(x) FROM (VALUES 3, 1, 4) t(x)")
    assert a == b
    assert a != c
    # NULLs participate (multiset semantics)
    d = q(runner, "SELECT checksum(x) FROM (VALUES 1, NULL, 2) t(x)")
    e = q(runner, "SELECT checksum(x) FROM (VALUES 1, 2) t(x)")
    assert d != e


def test_checksum_grouped_strings(runner):
    got = q(runner, "SELECT n_regionkey, checksum(n_name) "
                    "FROM tpch.tiny.nation GROUP BY n_regionkey")
    assert len(got) == 5
    assert all(r[1] is not None for r in got)


# -- distributed equivalence for non-decomposable kinds ---------------------

@pytest.fixture(scope="module")
def dist_runner():
    return LocalQueryRunner(distributed=True, n_devices=8)


@pytest.mark.slow
def test_distributed_nondecomposable_grouped(runner, dist_runner):
    sql = ("SELECT l_partkey % 5, count(DISTINCT l_quantity), "
           "min_by(l_orderkey, l_extendedprice), "
           "approx_percentile(l_quantity, 0.5) "
           "FROM tpch.tiny.lineitem GROUP BY l_partkey % 5 ORDER BY 1")
    assert q(dist_runner, sql) == q(runner, sql)


@pytest.mark.slow
def test_distributed_nondecomposable_global(runner, dist_runner):
    sql = ("SELECT count(DISTINCT l_suppkey), "
           "max_by(l_orderkey, l_extendedprice) "
           "FROM tpch.tiny.lineitem")
    assert q(dist_runner, sql) == q(runner, sql)


def test_mixed_same_arg_distinct(runner):
    # sum(DISTINCT x) + count(DISTINCT x) share the inner-group-by
    # rewrite; count(DISTINCT)-only mixes run natively
    got = q(runner, "SELECT sum(DISTINCT x), count(DISTINCT x), "
                    "avg(DISTINCT x) FROM (VALUES 1, 2, 2, 3) t(x)")
    assert got == [[6, 3, 2.0]]


# -- bitwise / collection aggregates (round 4) ------------------------------

def test_bitwise_aggs_global(runner):
    got = q(runner, "SELECT bitwise_and_agg(x), bitwise_or_agg(x) "
                    "FROM (VALUES 12, 10, NULL, 14) t(x)")
    assert got == [[12 & 10 & 14, 12 | 10 | 14]]


def test_bitwise_aggs_empty_and_null(runner):
    got = q(runner, "SELECT bitwise_and_agg(x), bitwise_or_agg(x) "
                    "FROM (VALUES CAST(NULL AS BIGINT)) t(x)")
    assert got == [[None, None]]


def test_bitwise_aggs_grouped(runner):
    got = q(runner, "SELECT g, bitwise_and_agg(x), bitwise_or_agg(x) "
                    "FROM (VALUES (1, 7), (1, 5), (2, 8), (2, 2), "
                    "(2, NULL)) t(g, x) GROUP BY g ORDER BY g")
    assert got == [[1, 7 & 5, 7 | 5], [2, 8 & 2, 8 | 2]]


def test_bitwise_aggs_grouped_general_path(runner, li):
    # keys with a large domain force the lexsort+segmented-scan kernel
    _, con = li
    rows = con.execute("SELECT pk, CAST(qty AS INT) FROM t").fetchall()
    import collections
    a = collections.defaultdict(lambda: -1)
    o = collections.defaultdict(int)
    for pk, x in rows:
        a[pk] &= x
        o[pk] |= x
    exp = sorted([k, a[k], o[k]] for k in a)[:20]
    got = q(runner, "SELECT l_partkey, "
                    "bitwise_and_agg(CAST(l_quantity AS INTEGER)), "
                    "bitwise_or_agg(CAST(l_quantity AS INTEGER)) "
                    "FROM tpch.tiny.lineitem GROUP BY l_partkey "
                    "ORDER BY l_partkey LIMIT 20")
    assert got == exp


def test_map_union_global(runner):
    got = q(runner, "SELECT map_union(m) FROM (VALUES "
                    "map(ARRAY[1, 2], ARRAY[10, 20]), "
                    "map(ARRAY[2, 3], ARRAY[99, 30])) t(m)")
    assert got == [[{1: 10, 2: 20, 3: 30}]]


def test_map_union_grouped(runner):
    got = q(runner, "SELECT g, map_union(m) FROM (VALUES "
                    "(1, map(ARRAY['a'], ARRAY[1])), "
                    "(1, map(ARRAY['b'], ARRAY[2])), "
                    "(2, map(ARRAY['c'], ARRAY[3])), "
                    "(2, CAST(NULL AS map(varchar, integer)))"
                    ") t(g, m) GROUP BY g ORDER BY g")
    assert got == [[1, {"a": 1, "b": 2}], [2, {"c": 3}]]


def test_multimap_agg(runner):
    got = q(runner, "SELECT multimap_agg(k, v) FROM (VALUES "
                    "('a', 1), ('b', 2), ('a', 3)) t(k, v)")
    assert got == [[{"a": [1, 3], "b": [2]}]]


def test_multimap_agg_grouped(runner):
    got = q(runner, "SELECT g, multimap_agg(k, v) FROM (VALUES "
                    "(1, 'x', 1), (1, 'x', 2), (2, 'y', 3)) t(g, k, v) "
                    "GROUP BY g ORDER BY g")
    assert got == [[1, {"x": [1, 2]}], [2, {"y": [3]}]]


def test_numeric_histogram(runner):
    got = q(runner, "SELECT numeric_histogram(4, x) FROM (VALUES "
                    "1.0, 1.0, 2.0, 50.0, 51.0, 100.0) t(x)")
    (m,), = got
    assert sum(m.values()) == 6.0
    assert len(m) == 4
    assert min(m) >= 1.0 and max(m) <= 100.0


def test_numeric_histogram_merges_closest(runner):
    got = q(runner, "SELECT numeric_histogram(2, x) FROM (VALUES "
                    "1.0, 2.0, 100.0) t(x)")
    (m,), = got
    assert m == {1.5: 2.0, 100.0: 1.0}


def test_tdigest_agg(runner):
    got = q(runner, "SELECT value_at_quantile(tdigest_agg(x), 0.5e0), "
                    "value_at_quantile(tdigest_agg(x), 0.0e0), "
                    "value_at_quantile(tdigest_agg(x), 1.0e0) "
                    "FROM (VALUES 1.0e0, 2.0e0, 3.0e0, 4.0e0, 5.0e0) "
                    "t(x)")
    assert got == [[3.0, 1.0, 5.0]]


def test_qdigest_agg_and_merge(runner):
    got = q(runner, "SELECT value_at_quantile(merge(d), 0.5e0) FROM ("
                    "SELECT qdigest_agg(x) AS d FROM (VALUES 1, 2, 3) "
                    "t(x) UNION ALL SELECT qdigest_agg(x) "
                    "FROM (VALUES 4, 5) t(x)) u")
    assert got == [[3]]


def test_tdigest_quantile_accuracy_large(runner, li):
    vals, _ = li
    import numpy as np
    prices = np.sort(vals[:, 2])
    got = q(runner, "SELECT value_at_quantile(tdigest_agg("
                    "l_extendedprice), 0.5e0) FROM tpch.tiny.lineitem")
    exact = float(np.quantile(prices, 0.5))
    assert abs(got[0][0] - exact) / exact < 0.05


def test_values_at_quantiles(runner):
    got = q(runner, "SELECT values_at_quantiles(tdigest_agg(x), "
                    "ARRAY[0.0e0, 0.5e0, 1.0e0]) "
                    "FROM (VALUES 10.0e0, 20.0e0, 30.0e0) t(x)")
    assert got == [[[10.0, 20.0, 30.0]]]


def test_quantile_at_value(runner):
    got = q(runner, "SELECT quantile_at_value(tdigest_agg(x), 15.0e0) "
                    "FROM (VALUES 10.0e0, 20.0e0) t(x)")
    assert abs(got[0][0] - 0.5) < 0.26


def test_grouped_tdigest(runner):
    got = q(runner, "SELECT g, value_at_quantile(tdigest_agg(x), 0.5e0) "
                    "FROM (VALUES (1, 1.0e0), (1, 3.0e0), (1, 5.0e0), "
                    "(2, 10.0e0)) t(g, x) GROUP BY g ORDER BY g")
    assert got == [[1, 3.0], [2, 10.0]]


def test_numeric_histogram_weighted(runner):
    got = q(runner, "SELECT numeric_histogram(2, x, w) FROM (VALUES "
                    "(1.0e0, 5.0e0), (2.0e0, 1.0e0), (100.0e0, 2.0e0))"
                    " t(x, w)")
    (m,), = got
    assert m == {(1.0 * 5 + 2.0 * 1) / 6: 6.0, 100.0: 2.0}


def test_empty_approx_set_merges_with_approx_set(runner):
    got = q(runner, "SELECT cardinality(merge(d)) FROM ("
                    "SELECT approx_set(x) AS d FROM (VALUES 1, 2, 3) "
                    "t(x) UNION ALL SELECT empty_approx_set()) u")
    assert got == [[3]]


def test_values_fallback_many_rows(runner):
    rows = ", ".join(f"map(ARRAY[{i}], ARRAY[{i}])" for i in range(60))
    got = q(runner, f"SELECT cardinality(map_union(m)) "
                    f"FROM (VALUES {rows}) t(m)")
    assert got == [[60]]
