"""TPC-DS correctness suite on tpcds.tiny.

Same three-way cross-check as test_tpch_suite.py (reference strategy
SURVEY.md §4): local engine vs sqlite3 oracle over identical data, plus
a distributed==local check for the flagship q64 star-join
(BASELINE.json configs[4]).
"""

import datetime
import math
import sqlite3
from decimal import Decimal

import pytest

from trino_tpu.benchmarks.tpcds_queries import TPCDS_QUERIES
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session

# per-table column subsets the suite queries touch (loading every
# column would mostly exercise to_pylist, not the engine)
_ORACLE_TABLES = {
    "date_dim": ["d_date_sk", "d_date", "d_year", "d_moy", "d_dom",
                 "d_qoy", "d_dow", "d_month_seq", "d_week_seq",
                 "d_day_name", "d_quarter_name"],
    "item": ["i_item_sk", "i_item_id", "i_product_name",
             "i_item_desc", "i_color", "i_current_price",
             "i_wholesale_cost", "i_brand_id", "i_brand",
             "i_manufact_id", "i_manufact", "i_category_id",
             "i_category", "i_class_id", "i_class", "i_manager_id",
             "i_units", "i_size"],
    "store_sales": ["ss_sold_date_sk", "ss_sold_time_sk",
                    "ss_item_sk", "ss_customer_sk",
                    "ss_cdemo_sk", "ss_hdemo_sk", "ss_addr_sk",
                    "ss_store_sk", "ss_promo_sk", "ss_ticket_number",
                    "ss_quantity", "ss_wholesale_cost", "ss_list_price",
                    "ss_sales_price", "ss_ext_sales_price",
                    "ss_ext_wholesale_cost", "ss_ext_list_price",
                    "ss_ext_tax", "ss_ext_discount_amt", "ss_net_paid",
                    "ss_coupon_amt", "ss_net_profit"],
    "store_returns": ["sr_item_sk", "sr_ticket_number",
                      "sr_returned_date_sk", "sr_customer_sk",
                      "sr_store_sk", "sr_reason_sk", "sr_cdemo_sk",
                      "sr_return_quantity",
                      "sr_return_amt", "sr_net_loss"],
    "catalog_sales": ["cs_item_sk", "cs_order_number",
                      "cs_ext_list_price", "cs_sold_date_sk",
                      "cs_ship_date_sk", "cs_sold_time_sk",
                      "cs_bill_customer_sk",
                      "cs_ship_customer_sk",
                      "cs_bill_cdemo_sk", "cs_bill_hdemo_sk",
                      "cs_promo_sk",
                      "cs_warehouse_sk", "cs_ship_mode_sk",
                      "cs_call_center_sk", "cs_quantity",
                      "cs_list_price", "cs_coupon_amt",
                      "cs_ext_discount_amt", "cs_ext_sales_price",
                      "cs_ship_addr_sk", "cs_ext_ship_cost",
                      "cs_bill_addr_sk", "cs_ext_wholesale_cost",
                      "cs_net_paid", "cs_wholesale_cost",
                      "cs_catalog_page_sk",
                      "cs_sales_price", "cs_net_profit"],
    "catalog_returns": ["cr_item_sk", "cr_order_number",
                        "cr_refunded_cash", "cr_reversed_charge",
                        "cr_store_credit", "cr_net_loss",
                        "cr_returned_date_sk",
                        "cr_returning_customer_sk",
                        "cr_call_center_sk", "cr_return_quantity",
                        "cr_return_amount", "cr_return_amt_inc_tax",
                        "cr_returning_addr_sk",
                        "cr_catalog_page_sk"],
    "store": ["s_store_sk", "s_store_id", "s_store_name", "s_zip",
              "s_state", "s_city", "s_number_employees", "s_county",
              "s_company_name", "s_company_id", "s_market_id",
              "s_street_number",
              "s_street_name", "s_street_type", "s_suite_number"],
    "customer": ["c_customer_sk", "c_customer_id",
                 "c_first_name", "c_last_name", "c_current_cdemo_sk",
                 "c_current_hdemo_sk", "c_current_addr_sk",
                 "c_first_sales_date_sk", "c_first_shipto_date_sk",
                 "c_birth_year", "c_birth_month", "c_birth_day",
                 "c_salutation",
                 "c_preferred_cust_flag", "c_birth_country"],
    "customer_demographics": ["cd_demo_sk", "cd_gender",
                              "cd_marital_status",
                              "cd_education_status", "cd_dep_count",
                              "cd_purchase_estimate",
                              "cd_credit_rating",
                              "cd_dep_employed_count",
                              "cd_dep_college_count"],
    "household_demographics": ["hd_demo_sk", "hd_income_band_sk",
                               "hd_buy_potential", "hd_dep_count",
                               "hd_vehicle_count"],
    "customer_address": ["ca_address_sk", "ca_street_number",
                         "ca_street_name", "ca_city", "ca_zip",
                         "ca_state", "ca_country", "ca_county",
                         "ca_gmt_offset", "ca_street_type",
                         "ca_suite_number", "ca_location_type"],
    "income_band": ["ib_income_band_sk", "ib_lower_bound",
                    "ib_upper_bound"],
    "promotion": ["p_promo_sk", "p_channel_email", "p_channel_event",
                  "p_channel_dmail", "p_channel_tv"],
    "web_sales": ["ws_sold_date_sk", "ws_sold_time_sk",
                  "ws_ship_date_sk", "ws_item_sk",
                  "ws_order_number", "ws_warehouse_sk",
                  "ws_web_site_sk", "ws_ship_mode_sk",
                  "ws_web_page_sk", "ws_ship_hdemo_sk",
                  "ws_bill_customer_sk", "ws_bill_addr_sk",
                  "ws_ship_addr_sk",
                  "ws_ext_sales_price", "ws_ext_discount_amt",
                  "ws_ext_ship_cost", "ws_net_paid",
                  "ws_sales_price", "ws_ship_customer_sk",
                  "ws_ext_list_price", "ws_ext_wholesale_cost",
                  "ws_quantity", "ws_list_price",
                  "ws_wholesale_cost", "ws_promo_sk",
                  "ws_net_profit"],
    "warehouse": ["w_warehouse_sk", "w_warehouse_name", "w_state",
                  "w_warehouse_sq_ft", "w_city", "w_county",
                  "w_country"],
    "ship_mode": ["sm_ship_mode_sk", "sm_type", "sm_carrier"],
    "web_site": ["web_site_sk", "web_site_id", "web_name",
                 "web_company_name"],
    "web_page": ["wp_web_page_sk", "wp_char_count"],
    "catalog_page": ["cp_catalog_page_sk", "cp_catalog_page_id"],
    "web_returns": ["wr_item_sk", "wr_order_number",
                    "wr_returned_date_sk",
                    "wr_returning_customer_sk", "wr_return_amt",
                    "wr_return_quantity", "wr_refunded_cash",
                    "wr_fee", "wr_returning_addr_sk",
                    "wr_refunded_addr_sk", "wr_refunded_cdemo_sk",
                    "wr_returning_cdemo_sk", "wr_reason_sk",
                    "wr_net_loss", "wr_web_page_sk"],
    "call_center": ["cc_call_center_sk", "cc_call_center_id",
                    "cc_name", "cc_manager", "cc_county"],
    "time_dim": ["t_time_sk", "t_time", "t_hour", "t_minute",
                 "t_meal_time"],
    "reason": ["r_reason_sk", "r_reason_desc"],
    "inventory": ["inv_date_sk", "inv_item_sk", "inv_warehouse_sk",
                  "inv_quantity_on_hand"],
}


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner(
        session=Session(catalog="tpcds", schema="tiny"))


class _StddevSamp:
    def __init__(self):
        self.vals = []

    def step(self, v):
        if v is not None:
            self.vals.append(float(v))

    def finalize(self):
        n = len(self.vals)
        if n < 2:
            return None
        m = sum(self.vals) / n
        return math.sqrt(sum((x - m) ** 2 for x in self.vals)
                         / (n - 1))


@pytest.fixture(scope="module")
def oracle(local):
    con = sqlite3.connect(":memory:")
    con.create_aggregate("stddev_samp", 1, _StddevSamp)
    for t, cols in _ORACLE_TABLES.items():
        res = local.execute(f"SELECT {', '.join(cols)} FROM {t}")
        marks = ", ".join("?" * len(cols))
        con.execute(f"CREATE TABLE {t} ({', '.join(cols)})")
        rows = [[v.isoformat() if isinstance(v, datetime.date) else
                 float(v) if isinstance(v, Decimal) else v
                 for v in row] for row in res.rows]
        con.executemany(f"INSERT INTO {t} VALUES ({marks})", rows)
    con.commit()
    return con


def norm_row(row):
    # sqlite yields NULL for division by zero where the engine follows
    # IEEE double semantics (0.0/0.0 = NaN): normalize NaN to None
    out = []
    for v in row:
        if isinstance(v, datetime.date):
            v = v.isoformat()
        elif isinstance(v, Decimal):
            v = float(v)
        if isinstance(v, float) and math.isnan(v):
            v = None
        out.append(v)
    return out


def assert_rows_equal(got, want, tag, ordered):
    assert len(got) == len(want), \
        f"{tag}: {len(got)} rows vs oracle {len(want)}"
    if not ordered:
        key = lambda r: tuple((x is None, str(type(x)), x) for x in r)
        got = sorted(got, key=key)
        want = sorted(want, key=key)
    for i, (g, w) in enumerate(zip(got, want)):
        assert len(g) == len(w), f"{tag} row {i}: arity"
        for a, b in zip(g, w):
            if isinstance(a, float) or isinstance(b, float):
                assert (a is None) == (b is None), f"{tag} row {i}"
                if a is not None:
                    assert math.isclose(float(a), float(b),
                                        rel_tol=1e-6, abs_tol=1e-6), \
                        f"{tag} row {i}: {a} != {b}"
            else:
                assert a == b, f"{tag} row {i}: {a!r} != {b!r}"


def to_sqlite(q: str) -> str:
    """Trino dialect -> sqlite for the TPC-DS texts (DATE literals;
    integer division is // semantics in sqlite already)."""
    import re
    return re.sub(r"DATE\s+'(\d{4}-\d{2}-\d{2})'", r"'\1'", q)


# sqlite has no ROLLUP: expand q27 as the UNION ALL of its grouping
# levels (same semantics per the SQL standard)
_Q27_BODY = """
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND cd_gender = 'M' AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND d_year = 2000
  AND s_state IN ('TN', 'OH', 'TX', 'GA', 'IL')
"""
# q48's official text repeats the cd/ca join conjunct inside each OR
# arm; sqlite's planner cannot extract it and nested-loops for hours.
# Hoisting the common conjuncts (identical semantics) keeps the oracle
# tractable; the ENGINE still runs the official OR-embedded form.
_Q48_ORACLE = """
SELECT sum(ss_quantity) total
FROM store_sales, store, customer_demographics, customer_address,
     date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2000
  AND cd_demo_sk = ss_cdemo_sk
  AND ((cd_marital_status = 'M'
        AND cd_education_status = '4 yr Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00)
       OR (cd_marital_status = 'D'
           AND cd_education_status = '2 yr Degree'
           AND ss_sales_price BETWEEN 50.00 AND 100.00)
       OR (cd_marital_status = 'S'
           AND cd_education_status = 'College'
           AND ss_sales_price BETWEEN 150.00 AND 200.00))
  AND ss_addr_sk = ca_address_sk AND ca_country = 'United States'
  AND ((ca_state IN ('CA', 'OH', 'TX')
        AND ss_net_profit BETWEEN 0 AND 2000)
       OR (ca_state IN ('OR', 'MN', 'KY')
           AND ss_net_profit BETWEEN 150 AND 3000)
       OR (ca_state IN ('VA', 'CA', 'MS')
           AND ss_net_profit BETWEEN 50 AND 25000))
"""

_Q86_BODY = """
FROM web_sales, date_dim d1, item
WHERE d1.d_month_seq BETWEEN 1200 AND 1211
  AND d1.d_date_sk = ws_sold_date_sk
  AND i_item_sk = ws_item_sk
"""

_Q22_BODY = """
FROM inventory, date_dim, item
WHERE inv_date_sk = d_date_sk
  AND inv_item_sk = i_item_sk
  AND d_month_seq BETWEEN 1200 AND 1211
"""

# q13: same sqlite nested-loop hazard as q48 — hoist the join
# conjuncts that the official text repeats inside each OR arm
_Q13_ORACLE = """
SELECT avg(ss_quantity) q, avg(ss_ext_sales_price) esp,
       avg(ss_ext_wholesale_cost) ewc, sum(ss_ext_wholesale_cost) swc
FROM store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2001
  AND ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
  AND ss_addr_sk = ca_address_sk AND ca_country = 'United States'
  AND ((cd_marital_status = 'M'
        AND cd_education_status = 'Advanced Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00
        AND hd_dep_count = 3)
       OR (cd_marital_status = 'S'
           AND cd_education_status = 'College'
           AND ss_sales_price BETWEEN 50.00 AND 100.00
           AND hd_dep_count = 1)
       OR (cd_marital_status = 'W'
           AND cd_education_status = '2 yr Degree'
           AND ss_sales_price BETWEEN 150.00 AND 200.00
           AND hd_dep_count = 1))
  AND ((ca_state IN ('TX', 'OH', 'TX')
        AND ss_net_profit BETWEEN 100 AND 200)
       OR (ca_state IN ('OR', 'NM', 'KY')
           AND ss_net_profit BETWEEN 150 AND 300)
       OR (ca_state IN ('VA', 'TX', 'MS')
           AND ss_net_profit BETWEEN 50 AND 250))
"""

_Q18_BODY = """
FROM catalog_sales, customer_demographics cd1,
     customer_demographics cd2, customer, customer_address,
     date_dim, item
WHERE cs_sold_date_sk = d_date_sk
  AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd1.cd_demo_sk
  AND cs_bill_customer_sk = c_customer_sk
  AND cd1.cd_gender = 'F'
  AND cd1.cd_education_status = 'Unknown'
  AND c_current_cdemo_sk = cd2.cd_demo_sk
  AND c_current_addr_sk = ca_address_sk
  AND c_birth_month IN (1, 6, 8, 9, 12, 2)
  AND d_year = 1998
  AND ca_state IN ('MS', 'IN', 'ND', 'OK', 'NM', 'VA', 'MS')
"""
_Q18_AGGS = """avg(cs_quantity), avg(cs_list_price),
       avg(cs_coupon_amt), avg(cs_sales_price), avg(cs_net_profit),
       avg(c_birth_year), avg(cd1.cd_dep_count)"""

_Q36_BODY = """
FROM store_sales, date_dim d1, item, store
WHERE d1.d_year = 2001
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk
  AND s_store_sk = ss_store_sk
  AND s_state IN ('TN', 'OH', 'TX', 'GA', 'IL')
"""

_Q70_BODY = """
FROM store_sales, date_dim d1, store
WHERE d1.d_month_seq BETWEEN 1200 AND 1211
  AND d1.d_date_sk = ss_sold_date_sk
  AND s_store_sk = ss_store_sk
  AND s_state IN
      (SELECT s_state
       FROM (SELECT s_state s_state,
                    rank() OVER (PARTITION BY s_state
                                 ORDER BY sum(ss_net_profit)
                                     DESC) ranking
             FROM store_sales, store, date_dim
             WHERE d_month_seq BETWEEN 1200 AND 1211
               AND d_date_sk = ss_sold_date_sk
               AND s_store_sk = ss_store_sk
             GROUP BY s_state) tmp1
       WHERE ranking <= 5)
"""

def _expand_rollup(sql: str, keys) -> str:
    """sqlite has no ROLLUP: rewrite the outer
    `SELECT k1..kn, <aggs> FROM <src> GROUP BY ROLLUP (k1..kn)
     ORDER BY .. LIMIT ..` shape into the UNION ALL of its grouping
    levels (prefixes of the key list, missing keys as NULL)."""
    marker = f"GROUP BY ROLLUP ({', '.join(keys)})"
    pre, post = sql.split(marker)
    # the OUTER select is the last `SELECT <k1>` before the rollup;
    # everything before it (WITH clauses) is kept verbatim
    hs = pre.rindex(f"SELECT {keys[0]}")
    prefix, outer = pre[:hs], pre[hs:]
    fi = outer.index("\nFROM")
    head, from_part = outer[:fi], outer[fi:]
    aggs = head[head.index("SELECT") + 6:]
    for k in keys:
        aggs = aggs.replace(f"{k},", "", 1)
    levels = []
    for n in range(len(keys), -1, -1):
        cols = ", ".join(list(keys[:n]) + ["NULL"] * (len(keys) - n))
        grp = (f" GROUP BY {', '.join(keys[:n])}" if n else "")
        levels.append(f"SELECT {cols}, {aggs} {from_part}{grp}")
    return (prefix + "SELECT * FROM ("
            + " UNION ALL ".join(levels) + ") zz" + post)


def _qualify_order_item_id(sql: str, tbl: str) -> str:
    """sqlite calls the bare `ORDER BY item_id` ambiguous when several
    FROM items expose item_id; the engine resolves it to the output
    column per the standard. Qualify only on the oracle side."""
    return sql.replace("ORDER BY item_id,", f"ORDER BY {tbl}.item_id,")


_Q67_KEYS = ("i_category", "i_class", "i_brand", "i_product_name",
             "d_year", "d_qoy", "d_moy", "s_store_id")
_Q67_BODY = """
FROM store_sales, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk
  AND d_month_seq BETWEEN 1200 AND 1211
"""


def _q67_oracle() -> str:
    levels = []
    for n in range(len(_Q67_KEYS), -1, -1):
        cols = ", ".join(list(_Q67_KEYS[:n])
                         + [f"NULL {k}" for k in _Q67_KEYS[n:]])
        grp = (f" GROUP BY {', '.join(_Q67_KEYS[:n])}" if n else "")
        levels.append(
            f"SELECT {cols}, "
            "sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales"
            f" {_Q67_BODY}{grp}")
    inner = " UNION ALL ".join(levels)
    return f"""
SELECT * FROM (
  SELECT i_category, i_class, i_brand, i_product_name, d_year,
         d_qoy, d_moy, s_store_id, sumsales,
         rank() OVER (PARTITION BY i_category
                      ORDER BY sumsales DESC) rk
  FROM ({inner}) dw1) dw2
WHERE rk <= 100
ORDER BY i_category NULLS LAST, i_class NULLS LAST,
         i_brand NULLS LAST, i_product_name NULLS LAST,
         d_year NULLS LAST, d_qoy NULLS LAST, d_moy NULLS LAST,
         s_store_id NULLS LAST, sumsales, rk
LIMIT 100
"""


_ORACLE_OVERRIDE = {
    67: _q67_oracle(),
    # sqlite has no INTERVAL arithmetic: date() modifier instead
    72: TPCDS_QUERIES[72].replace(
        "d3.d_date > d1.d_date + interval '5' day",
        "d3.d_date > date(d1.d_date, '+5 days')"),
    48: _Q48_ORACLE,
    13: _Q13_ORACLE,
    58: _qualify_order_item_id(TPCDS_QUERIES[58], "ss_items"),
    5: _expand_rollup(TPCDS_QUERIES[5], ("channel", "id")),
    77: _expand_rollup(TPCDS_QUERIES[77], ("channel", "id")),
    80: _expand_rollup(TPCDS_QUERIES[80], ("channel", "id")),
    14: _expand_rollup(TPCDS_QUERIES[14],
                       ("channel", "i_brand_id", "i_class_id",
                        "i_category_id")),
    # sqlite has no ROLLUP: q70 expands to its 3 grouping levels
    70: f"""
SELECT total_sum, s_state, s_county, lochierarchy,
       rank() OVER (PARTITION BY lochierarchy,
                        CASE WHEN county_grouping = 0
                             THEN s_state END
                    ORDER BY total_sum DESC) rank_within_parent
FROM (SELECT sum(ss_net_profit) total_sum, s_state, s_county,
             0 lochierarchy, 0 county_grouping
      {_Q70_BODY} GROUP BY s_state, s_county
      UNION ALL
      SELECT sum(ss_net_profit), s_state, NULL, 1, 1
      {_Q70_BODY} GROUP BY s_state
      UNION ALL
      SELECT sum(ss_net_profit), NULL, NULL, 2, 1
      {_Q70_BODY}) t
ORDER BY lochierarchy DESC,
         CASE WHEN lochierarchy = 0 THEN s_state END,
         rank_within_parent
LIMIT 100
""",
    # sqlite rejects parenthesized compound-select members: restate
    # q8/q87 with bare INTERSECT/EXCEPT (left-assoc, same semantics)
    8: """
SELECT s_store_name, sum(ss_net_profit) profit
FROM store_sales, date_dim, store,
     (SELECT substr(ca_zip, 1, 5) ca_zip
      FROM customer_address
      WHERE substr(ca_zip, 1, 5) IN
            ('24250', '38800', '50440', '59170', '75369',
             '77697', '86136', '87494', '92635', '97000')
      INTERSECT
      SELECT ca_zip
      FROM (SELECT substr(ca_zip, 1, 5) ca_zip, count(*) cnt
            FROM customer_address, customer
            WHERE ca_address_sk = c_current_addr_sk
              AND c_preferred_cust_flag = 'Y'
            GROUP BY substr(ca_zip, 1, 5)
            HAVING count(*) > 1) a1) v1
WHERE ss_store_sk = s_store_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 1998
  AND substr(s_zip, 1, 2) = substr(v1.ca_zip, 1, 2)
GROUP BY s_store_name
ORDER BY s_store_name
LIMIT 100
""",
    87: """
SELECT count(*) cnt
FROM (SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM store_sales, date_dim, customer
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_customer_sk = customer.c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1211
      EXCEPT
      SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM catalog_sales, date_dim, customer
      WHERE catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
        AND catalog_sales.cs_bill_customer_sk
            = customer.c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1211
      EXCEPT
      SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM web_sales, date_dim, customer
      WHERE web_sales.ws_sold_date_sk = date_dim.d_date_sk
        AND web_sales.ws_bill_customer_sk = customer.c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1211) cool_cust
""",
    18: f"""
SELECT * FROM (
  SELECT i_item_id, ca_country, ca_state, ca_county, {_Q18_AGGS}
  {_Q18_BODY} GROUP BY i_item_id, ca_country, ca_state, ca_county
  UNION ALL
  SELECT i_item_id, ca_country, ca_state, NULL, {_Q18_AGGS}
  {_Q18_BODY} GROUP BY i_item_id, ca_country, ca_state
  UNION ALL
  SELECT i_item_id, ca_country, NULL, NULL, {_Q18_AGGS}
  {_Q18_BODY} GROUP BY i_item_id, ca_country
  UNION ALL
  SELECT i_item_id, NULL, NULL, NULL, {_Q18_AGGS}
  {_Q18_BODY} GROUP BY i_item_id
  UNION ALL
  SELECT NULL, NULL, NULL, NULL, {_Q18_AGGS} {_Q18_BODY})
ORDER BY ca_country NULLS LAST, ca_state NULLS LAST,
         ca_county NULLS LAST, i_item_id NULLS LAST
LIMIT 100
""",
    36: f"""
SELECT gross_margin, i_category, i_class, lochierarchy,
       rank() OVER (PARTITION BY lochierarchy,
                        CASE WHEN cls_grouping = 0
                             THEN i_category END
                    ORDER BY gross_margin) rank_within_parent
FROM (SELECT sum(ss_net_profit) * 1.0 / sum(ss_ext_sales_price)
                 gross_margin,
             i_category, i_class, 0 lochierarchy, 0 cls_grouping
      {_Q36_BODY} GROUP BY i_category, i_class
      UNION ALL
      SELECT sum(ss_net_profit) * 1.0 / sum(ss_ext_sales_price),
             i_category, NULL, 1, 1
      {_Q36_BODY} GROUP BY i_category
      UNION ALL
      SELECT sum(ss_net_profit) * 1.0 / sum(ss_ext_sales_price),
             NULL, NULL, 2, 1
      {_Q36_BODY}) t
ORDER BY lochierarchy DESC,
         CASE WHEN lochierarchy = 0 THEN i_category END,
         rank_within_parent
LIMIT 100
""",
    # sqlite has no ROLLUP: q22 expands to the 5 grouping levels
    22: f"""
SELECT * FROM (
  SELECT i_product_name, i_brand, i_class, i_category,
         avg(inv_quantity_on_hand) qoh {_Q22_BODY}
  GROUP BY i_product_name, i_brand, i_class, i_category
  UNION ALL
  SELECT i_product_name, i_brand, i_class, NULL,
         avg(inv_quantity_on_hand) {_Q22_BODY}
  GROUP BY i_product_name, i_brand, i_class
  UNION ALL
  SELECT i_product_name, i_brand, NULL, NULL,
         avg(inv_quantity_on_hand) {_Q22_BODY}
  GROUP BY i_product_name, i_brand
  UNION ALL
  SELECT i_product_name, NULL, NULL, NULL,
         avg(inv_quantity_on_hand) {_Q22_BODY}
  GROUP BY i_product_name
  UNION ALL
  SELECT NULL, NULL, NULL, NULL,
         avg(inv_quantity_on_hand) {_Q22_BODY})
ORDER BY qoh, i_product_name NULLS LAST, i_brand NULLS LAST,
         i_class NULLS LAST, i_category NULLS LAST
LIMIT 100
""",
    # sqlite has no ROLLUP: expand q86's grouping levels as UNION ALL
    86: f"""
SELECT total_sum, i_category, i_class, lochierarchy,
       rank() OVER (PARTITION BY lochierarchy,
                        CASE WHEN cls_grouping = 0
                             THEN i_category END
                    ORDER BY total_sum DESC) rank_within_parent
FROM (SELECT sum(ws_net_paid) total_sum, i_category, i_class,
             0 lochierarchy, 0 cls_grouping {_Q86_BODY}
      GROUP BY i_category, i_class
      UNION ALL
      SELECT sum(ws_net_paid), i_category, NULL, 1, 1 {_Q86_BODY}
      GROUP BY i_category
      UNION ALL
      SELECT sum(ws_net_paid), NULL, NULL, 2, 1 {_Q86_BODY}) t
ORDER BY lochierarchy DESC,
         CASE WHEN lochierarchy = 0 THEN i_category END,
         rank_within_parent
LIMIT 100
""",
    27: f"""
SELECT * FROM (
  SELECT i_item_id, s_state, avg(ss_quantity) agg1,
         avg(ss_list_price) agg2, avg(ss_coupon_amt) agg3,
         avg(ss_sales_price) agg4 {_Q27_BODY}
  GROUP BY i_item_id, s_state
  UNION ALL
  SELECT i_item_id, NULL, avg(ss_quantity), avg(ss_list_price),
         avg(ss_coupon_amt), avg(ss_sales_price) {_Q27_BODY}
  GROUP BY i_item_id
  UNION ALL
  SELECT NULL, NULL, avg(ss_quantity), avg(ss_list_price),
         avg(ss_coupon_amt), avg(ss_sales_price) {_Q27_BODY})
ORDER BY i_item_id NULLS LAST, s_state NULLS LAST
LIMIT 100
""",
}


# q14 (double INTERSECT cross-channel) and q16 (catalog_sales anti-join
# chain) are the two corpus heavyweights (~35s combined) -> slow-swept;
# q51/q97 need RIGHT/FULL OUTER JOIN on the sqlite oracle side, which
# this host's sqlite build lacks -> slow-swept as env-unsupported
@pytest.mark.parametrize(
    "qn", [pytest.param(q, marks=pytest.mark.slow) if q in (14, 16) else
           pytest.param(q, marks=pytest.mark.slow) if q in (51, 97)
           else q
           for q in sorted(TPCDS_QUERIES)])
def test_tpcds_local_vs_oracle(local, oracle, qn):
    sql = TPCDS_QUERIES[qn]
    got = [norm_row(r) for r in local.execute(sql).rows]
    osql = to_sqlite(_ORACLE_OVERRIDE.get(qn, sql))
    want = [list(r) for r in oracle.execute(osql).fetchall()]
    assert_rows_equal(got, want, f"q{qn}", ordered="ORDER BY" in sql)


def test_q24_relaxed_nonempty(local, oracle):
    """q24's spec parameters (s_market_id = 8, i_color = 'pale') match
    nothing at tiny scale — the official text runs empty-vs-empty. A
    relaxed variant (all markets, all colors) must be nonempty so the
    6-table ssales CTE + HAVING-scalar path is genuinely exercised."""
    sql = TPCDS_QUERIES[24]
    sql = sql.replace("AND s_market_id = 8", "")
    sql = sql.replace("WHERE i_color = 'pale'", "WHERE i_color >= ''")
    # the 2 tiny-scale stores' exact zips happen to miss every
    # customer zip: widen to the zip prefix so the join correlation
    # stays exercised without being vacuously empty
    sql = sql.replace("AND s_zip = ca_zip",
                      "AND substr(s_zip, 1, 2) = substr(ca_zip, 1, 2)")
    got = [norm_row(r) for r in local.execute(sql).rows]
    want = [list(r) for r in oracle.execute(to_sqlite(sql)).fetchall()]
    assert len(got) > 0, "relaxed q24 returned no rows"
    assert_rows_equal(got, want, "q24-relaxed", ordered=True)


def test_q64_relaxed_nonempty(local, oracle):
    """The spec q64 can legitimately be empty at tiny scale; a relaxed
    variant (all colors, full price range, no year pin on cs2) must be
    nonempty so the 18-way join path is genuinely exercised."""
    sql = TPCDS_QUERIES[64]
    sql = sql.replace("AND i_current_price BETWEEN 64 AND 74", "")
    sql = sql.replace("AND i_current_price BETWEEN 65 AND 79", "")
    sql = sql.replace(
        "AND i_color IN ('purple', 'burlywood', 'indian', 'spring',\n"
        "                    'floral', 'medium')", "")
    sql = sql.replace("AND cs1.syear = 1999", "")
    sql = sql.replace("AND cs2.syear = 2000", "")
    got = [norm_row(r) for r in local.execute(sql).rows]
    want = [list(r) for r in oracle.execute(sql).fetchall()]
    assert len(got) > 0, "relaxed q64 returned no rows"
    assert_rows_equal(got, want, "q64-relaxed", ordered=True)


@pytest.mark.slow
def test_q64_distributed_matches_local(local):
    dist = LocalQueryRunner(
        session=Session(catalog="tpcds", schema="tiny"),
        distributed=True, n_devices=8)
    sql = TPCDS_QUERIES[64]
    lres = [norm_row(r) for r in local.execute(sql).rows]
    dres = [norm_row(r) for r in dist.execute(sql).rows]
    assert_rows_equal(dres, lres, "q64-dist", ordered=True)
