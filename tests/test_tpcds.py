"""TPC-DS correctness suite on tpcds.tiny.

Same three-way cross-check as test_tpch_suite.py (reference strategy
SURVEY.md §4): local engine vs sqlite3 oracle over identical data, plus
a distributed==local check for the flagship q64 star-join
(BASELINE.json configs[4]).
"""

import datetime
import math
import sqlite3
from decimal import Decimal

import pytest

from trino_tpu.benchmarks.tpcds_queries import TPCDS_QUERIES
from trino_tpu.runner import LocalQueryRunner
from trino_tpu.session import Session

# per-table column subsets the suite queries touch (loading every
# column would mostly exercise to_pylist, not the engine)
_ORACLE_TABLES = {
    "date_dim": ["d_date_sk", "d_date", "d_year", "d_moy", "d_dom",
                 "d_qoy", "d_dow", "d_month_seq", "d_week_seq",
                 "d_day_name", "d_quarter_name"],
    "item": ["i_item_sk", "i_item_id", "i_product_name",
             "i_item_desc", "i_color", "i_current_price",
             "i_wholesale_cost", "i_brand_id", "i_brand",
             "i_manufact_id", "i_category_id", "i_category",
             "i_class_id", "i_class", "i_manager_id"],
    "store_sales": ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
                    "ss_cdemo_sk", "ss_hdemo_sk", "ss_addr_sk",
                    "ss_store_sk", "ss_promo_sk", "ss_ticket_number",
                    "ss_quantity", "ss_wholesale_cost", "ss_list_price",
                    "ss_sales_price", "ss_ext_sales_price",
                    "ss_coupon_amt", "ss_net_profit"],
    "store_returns": ["sr_item_sk", "sr_ticket_number",
                      "sr_returned_date_sk", "sr_customer_sk",
                      "sr_store_sk", "sr_return_quantity",
                      "sr_return_amt", "sr_net_loss"],
    "catalog_sales": ["cs_item_sk", "cs_order_number",
                      "cs_ext_list_price", "cs_sold_date_sk",
                      "cs_bill_customer_sk", "cs_quantity",
                      "cs_sales_price", "cs_net_profit"],
    "catalog_returns": ["cr_item_sk", "cr_order_number",
                        "cr_refunded_cash", "cr_reversed_charge",
                        "cr_store_credit"],
    "store": ["s_store_sk", "s_store_id", "s_store_name", "s_zip",
              "s_state", "s_city", "s_number_employees", "s_county",
              "s_company_name"],
    "customer": ["c_customer_sk", "c_customer_id",
                 "c_first_name", "c_last_name", "c_current_cdemo_sk",
                 "c_current_hdemo_sk", "c_current_addr_sk",
                 "c_first_sales_date_sk", "c_first_shipto_date_sk"],
    "customer_demographics": ["cd_demo_sk", "cd_gender",
                              "cd_marital_status",
                              "cd_education_status"],
    "household_demographics": ["hd_demo_sk", "hd_income_band_sk",
                               "hd_buy_potential", "hd_dep_count",
                               "hd_vehicle_count"],
    "customer_address": ["ca_address_sk", "ca_street_number",
                         "ca_street_name", "ca_city", "ca_zip",
                         "ca_state", "ca_country"],
    "income_band": ["ib_income_band_sk"],
    "promotion": ["p_promo_sk", "p_channel_email", "p_channel_event"],
}


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner(
        session=Session(catalog="tpcds", schema="tiny"))


@pytest.fixture(scope="module")
def oracle(local):
    con = sqlite3.connect(":memory:")
    for t, cols in _ORACLE_TABLES.items():
        res = local.execute(f"SELECT {', '.join(cols)} FROM {t}")
        marks = ", ".join("?" * len(cols))
        con.execute(f"CREATE TABLE {t} ({', '.join(cols)})")
        rows = [[v.isoformat() if isinstance(v, datetime.date) else
                 float(v) if isinstance(v, Decimal) else v
                 for v in row] for row in res.rows]
        con.executemany(f"INSERT INTO {t} VALUES ({marks})", rows)
    con.commit()
    return con


def norm_row(row):
    return [v.isoformat() if isinstance(v, datetime.date)
            else float(v) if isinstance(v, Decimal) else v for v in row]


def assert_rows_equal(got, want, tag, ordered):
    assert len(got) == len(want), \
        f"{tag}: {len(got)} rows vs oracle {len(want)}"
    if not ordered:
        key = lambda r: tuple((x is None, str(type(x)), x) for x in r)
        got = sorted(got, key=key)
        want = sorted(want, key=key)
    for i, (g, w) in enumerate(zip(got, want)):
        assert len(g) == len(w), f"{tag} row {i}: arity"
        for a, b in zip(g, w):
            if isinstance(a, float) or isinstance(b, float):
                assert (a is None) == (b is None), f"{tag} row {i}"
                if a is not None:
                    assert math.isclose(float(a), float(b),
                                        rel_tol=1e-6, abs_tol=1e-6), \
                        f"{tag} row {i}: {a} != {b}"
            else:
                assert a == b, f"{tag} row {i}: {a!r} != {b!r}"


def to_sqlite(q: str) -> str:
    """Trino dialect -> sqlite for the TPC-DS texts (DATE literals;
    integer division is // semantics in sqlite already)."""
    import re
    return re.sub(r"DATE\s+'(\d{4}-\d{2}-\d{2})'", r"'\1'", q)


# sqlite has no ROLLUP: expand q27 as the UNION ALL of its grouping
# levels (same semantics per the SQL standard)
_Q27_BODY = """
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND cd_gender = 'M' AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND d_year = 2000
  AND s_state IN ('TN', 'OH', 'TX', 'GA', 'IL')
"""
# q48's official text repeats the cd/ca join conjunct inside each OR
# arm; sqlite's planner cannot extract it and nested-loops for hours.
# Hoisting the common conjuncts (identical semantics) keeps the oracle
# tractable; the ENGINE still runs the official OR-embedded form.
_Q48_ORACLE = """
SELECT sum(ss_quantity) total
FROM store_sales, store, customer_demographics, customer_address,
     date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2000
  AND cd_demo_sk = ss_cdemo_sk
  AND ((cd_marital_status = 'M'
        AND cd_education_status = '4 yr Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00)
       OR (cd_marital_status = 'D'
           AND cd_education_status = '2 yr Degree'
           AND ss_sales_price BETWEEN 50.00 AND 100.00)
       OR (cd_marital_status = 'S'
           AND cd_education_status = 'College'
           AND ss_sales_price BETWEEN 150.00 AND 200.00))
  AND ss_addr_sk = ca_address_sk AND ca_country = 'United States'
  AND ((ca_state IN ('CA', 'OH', 'TX')
        AND ss_net_profit BETWEEN 0 AND 2000)
       OR (ca_state IN ('OR', 'MN', 'KY')
           AND ss_net_profit BETWEEN 150 AND 3000)
       OR (ca_state IN ('VA', 'CA', 'MS')
           AND ss_net_profit BETWEEN 50 AND 25000))
"""

_ORACLE_OVERRIDE = {
    48: _Q48_ORACLE,
    27: f"""
SELECT * FROM (
  SELECT i_item_id, s_state, avg(ss_quantity) agg1,
         avg(ss_list_price) agg2, avg(ss_coupon_amt) agg3,
         avg(ss_sales_price) agg4 {_Q27_BODY}
  GROUP BY i_item_id, s_state
  UNION ALL
  SELECT i_item_id, NULL, avg(ss_quantity), avg(ss_list_price),
         avg(ss_coupon_amt), avg(ss_sales_price) {_Q27_BODY}
  GROUP BY i_item_id
  UNION ALL
  SELECT NULL, NULL, avg(ss_quantity), avg(ss_list_price),
         avg(ss_coupon_amt), avg(ss_sales_price) {_Q27_BODY})
ORDER BY i_item_id NULLS LAST, s_state NULLS LAST
LIMIT 100
""",
}


@pytest.mark.parametrize("qn", sorted(TPCDS_QUERIES))
def test_tpcds_local_vs_oracle(local, oracle, qn):
    sql = TPCDS_QUERIES[qn]
    got = [norm_row(r) for r in local.execute(sql).rows]
    osql = to_sqlite(_ORACLE_OVERRIDE.get(qn, sql))
    want = [list(r) for r in oracle.execute(osql).fetchall()]
    assert_rows_equal(got, want, f"q{qn}", ordered="ORDER BY" in sql)


def test_q64_relaxed_nonempty(local, oracle):
    """The spec q64 can legitimately be empty at tiny scale; a relaxed
    variant (all colors, full price range, no year pin on cs2) must be
    nonempty so the 18-way join path is genuinely exercised."""
    sql = TPCDS_QUERIES[64]
    sql = sql.replace("AND i_current_price BETWEEN 64 AND 74", "")
    sql = sql.replace("AND i_current_price BETWEEN 65 AND 79", "")
    sql = sql.replace(
        "AND i_color IN ('purple', 'burlywood', 'indian', 'spring',\n"
        "                    'floral', 'medium')", "")
    sql = sql.replace("AND cs1.syear = 1999", "")
    sql = sql.replace("AND cs2.syear = 2000", "")
    got = [norm_row(r) for r in local.execute(sql).rows]
    want = [list(r) for r in oracle.execute(sql).fetchall()]
    assert len(got) > 0, "relaxed q64 returned no rows"
    assert_rows_equal(got, want, "q64-relaxed", ordered=True)


def test_q64_distributed_matches_local(local):
    dist = LocalQueryRunner(
        session=Session(catalog="tpcds", schema="tiny"),
        distributed=True, n_devices=8)
    sql = TPCDS_QUERIES[64]
    lres = [norm_row(r) for r in local.execute(sql).rows]
    dres = [norm_row(r) for r in dist.execute(sql).rows]
    assert_rows_equal(dres, lres, "q64-dist", ordered=True)
